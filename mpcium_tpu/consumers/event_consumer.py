"""Event consumers — the node's application brain (reference
pkg/eventconsumer/event_consumer.go).

Subscribes to the three command topics, verifies initiator signatures,
spawns sessions, publishes results:

- keygen: one wallet-creation event drives BOTH curves' DKG concurrently;
  a single KeygenSuccessEvent carries both pubkeys (event_consumer.go:
  103-204).
- signing: dup-session check on walletID-txID (event_consumer.go:234-238),
  NotEnoughParticipants ⇒ raise for queue redelivery (276-280), success ⇒
  idempotent result enqueue + reply-inbox publish (327-337), failure ⇒
  error result event.
- resharing: one dual-role resharing session per node, result aggregated
  (375-518).
- stale-session GC (default 30 min timeout / 5 min sweep,
  event_consumer.go:71-72).
"""
from __future__ import annotations

import json
import threading
import time
from types import SimpleNamespace
from typing import Dict, Optional

from .. import wire
from ..node.node import Node, NotEnoughParticipants
from ..node.session import RetryableSessionError
from ..transport.api import Transport
from ..utils import log

SESSION_TIMEOUT_S = 30 * 60  # event_consumer.go:71
GC_INTERVAL_S = 5 * 60  # event_consumer.go:72


class EventConsumer:
    def __init__(
        self,
        node: Node,
        transport: Transport,
        session_timeout_s: float = SESSION_TIMEOUT_S,
        gc_interval_s: float = GC_INTERVAL_S,
        batch_signing: bool = False,
        batch_window_s: float = 0.05,
        metrics=None,
    ):
        from ..utils.metrics import MetricsRegistry

        self.node = node
        self.transport = transport
        self.session_timeout_s = session_timeout_s
        self.gc_interval_s = gc_interval_s
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._sessions: Dict[str, list] = {}  # dedup key -> [Session]
        self._claim_ts: Dict[str, float] = {}  # dedup key -> claim time
        self._claim_meta: Dict[str, tuple] = {}  # ("sign", msg) for GC
        self._lock = threading.RLock()
        self._subs = []
        self._gc_stop = threading.Event()
        self._gc_thread: Optional[threading.Thread] = None
        self.scheduler = None
        if batch_signing:
            from .batch_scheduler import BatchSigningScheduler

            self.scheduler = BatchSigningScheduler(
                node, transport, window_s=batch_window_s,
                metrics=self.metrics,
                on_fallback=self._batch_fallback,
                on_tx_done=lambda w, t: self._finish(f"{w}-{t}"),
                on_tx_released=lambda w, t: self._release(f"{w}-{t}"),
                claim_tx=lambda w, t: self._claim(f"{w}-{t}"),
                on_fallback_keygen=self._keygen_fallback,
                on_kg_done=lambda w: self._finish(f"keygen-{w}"),
                on_kg_released=lambda w: self._release(f"keygen-{w}"),
                claim_kg=lambda w: self._claim(f"keygen-{w}"),
                on_fallback_reshare=self._reshare_fallback,
                on_rs_done=lambda kt, w: self._finish(f"reshare-{kt}-{w}"),
                on_rs_released=lambda kt, w: self._release(f"reshare-{kt}-{w}"),
                claim_rs=lambda kt, w: self._claim(f"reshare-{kt}-{w}"),
            )

    # -- lifecycle ----------------------------------------------------------

    def run(self) -> None:
        ps = self.transport.pubsub
        self._subs.append(ps.subscribe(wire.TOPIC_GENERATE, self._on_generate))
        self._subs.append(ps.subscribe(wire.TOPIC_SIGN, self._on_sign))
        self._subs.append(ps.subscribe(wire.TOPIC_RESHARE, self._on_reshare))
        self._gc_thread = threading.Thread(
            target=self._gc_loop, name=f"session-gc-{self.node.node_id}", daemon=True
        )
        self._gc_thread.start()

    def close(self) -> None:
        self._gc_stop.set()
        if self.scheduler is not None:
            self.scheduler.close()
        for s in self._subs:
            s.unsubscribe()
        with self._lock:
            doomed = [s for ss in self._sessions.values() for s in ss]
            self._sessions.clear()
            self._claim_ts.clear()
            self._claim_meta.clear()
        # close OUTSIDE the lock: closing an unfinished session fires its
        # on_error callback, which may re-enter our bookkeeping
        for s in doomed:
            s.close()

    # -- health surface ------------------------------------------------------

    def health(self) -> dict:
        """JSON-ready operational snapshot: live session/claim counts plus
        every metric in the registry (the scheduler's lane depths, shed
        counters, latency histograms). The daemon publishes this to the
        control plane; LocalCluster aggregates it for tests and soaks."""
        with self._lock:
            live_sessions = sum(len(ss) for ss in self._sessions.values())
            claims = len(self._claim_ts)
        # refresh the observability gauges the snapshot should carry:
        # flight-recorder ring drops, the settled-map size, and the
        # compile ledger (all cheap; health is called at human cadence)
        from ..perf import compile_watch
        from ..trace import recorder

        self.metrics.gauge("trace.dropped_spans").set(
            float(recorder.recorder_for(self.node.node_id).dropped)
        )
        if self.scheduler is not None:
            self.metrics.gauge("scheduler.settled_size").set(
                float(self.scheduler.settled_size())
            )
        compile_watch.export_gauges(self.metrics)
        # measurement debt next to warming state: owed/claimed/stale
        # counts from the claims ledger (TTL-cached file reads; the
        # helper never raises — health must not die on a corrupt corpus)
        from ..perf import claims as claims_ledger

        claim_counts = claims_ledger.export_gauges(self.metrics)
        out = {
            "node": self.node.node_id,
            "live_sessions": live_sessions,
            "dedup_claims": claims,
            "batch_signing": self.scheduler is not None,
            "compile": compile_watch.health_summary(),
            "claims": claim_counts,
            "metrics": self.metrics.snapshot(),
        }
        if self.scheduler is not None:
            out["batches_run"] = self.scheduler.batches_run
        return out

    # -- crash recovery (boot-time WAL resume) ------------------------------

    def resume_incomplete(self) -> int:
        """Rebuild every incomplete WAL session at daemon boot: restore the
        party at its last checkpoint, re-attach it to its dedup claim (so
        queue redeliveries of the originating event get a WIP answer instead
        of spawning a conflicting duplicate run), and re-join the wire via
        the session's resume replay. Returns the number of resumed sessions."""
        wal = self.node.session_wal
        if wal is None:
            return 0
        keygen_reps: Dict[str, list] = {}
        others = []
        for rep in wal.incomplete():
            if rep.meta.get("kind") == "keygen":
                # the two curves of one wallet share a dedup claim and a
                # single success event — resume them as a unit
                keygen_reps.setdefault(rep.meta["wallet_id"], []).append(rep)
            else:
                others.append(rep)
        n = 0
        for wallet_id, reps in keygen_reps.items():
            n += self._try_resume(
                reps, lambda: self._resume_keygen(wallet_id, reps)
            )
        for rep in others:
            # the kind tag is routing metadata, not key material — but it
            # rides inside the decrypted WAL record, so declassify the one
            # field we log instead of formatting the record itself
            kind = rep.meta.get("kind")  # mpcflow: declassified — WAL routing tag
            if kind == "sign":
                n += self._try_resume([rep], lambda r=rep: self._resume_sign(r))
            elif kind == "reshare":
                n += self._try_resume(
                    [rep], lambda r=rep: self._resume_reshare(r)
                )
            else:
                log.warn("unknown WAL kind — dropping",
                         session=rep.session_id, kind=kind)
                wal.drop(rep.session_id)
        if n:
            log.info("crash recovery: sessions resumed", node=self.node.node_id,
                     count=n)
        return n

    def _try_resume(self, reps, fn) -> int:
        try:
            return int(bool(fn()))
        except Exception as e:  # noqa: BLE001
            # unresumable (share/keyinfo missing, snapshot mismatch, ...):
            # drop the journal so boot never loops on it; the originating
            # event's redelivery path still provides the retry
            log.warn("session resume failed — dropping WAL",
                     sessions=[r.session_id for r in reps], error=repr(e))
            for r in reps:
                self.node.session_wal.drop(r.session_id)
            return 0

    def _resume_keygen(self, wallet_id: str, reps) -> bool:
        dedup = f"keygen-{wallet_id}"
        if not self._claim(dedup):
            return False
        state = {"left": len(reps)}
        slock = threading.Lock()

        def finalize():
            try:
                infos = {
                    kt: self.node.keyinfo.get(kt, wallet_id)
                    for kt in (wire.KEY_TYPE_SECP256K1, wire.KEY_TYPE_ED25519)
                }
                if all(i is not None and i.public_key for i in infos.values()):
                    ev = wire.KeygenSuccessEvent(
                        wallet_id=wallet_id,
                        ecdsa_pub_key=infos[wire.KEY_TYPE_SECP256K1].public_key,
                        eddsa_pub_key=infos[wire.KEY_TYPE_ED25519].public_key,
                    )
                    self.transport.queues.enqueue(
                        f"{wire.TOPIC_KEYGEN_RESULT}.{wallet_id}",
                        wire.canonical_json(ev.to_json()),
                        idempotency_key=wallet_id,
                    )
                    log.info("wallet created (resumed)", wallet=wallet_id,
                             node=self.node.node_id)
            finally:
                self._finish(dedup)

        def step():
            with slock:
                state["left"] -= 1
                last = state["left"] <= 0
            if last:
                finalize()

        def on_done(_share):
            step()

        def on_error(e):
            log.warn("resumed keygen failed", wallet=wallet_id, error=str(e))
            step()

        sessions = [
            self.node.resume_session(rep, on_done=on_done, on_error=on_error)
            for rep in reps
        ]
        self._track(dedup, sessions)
        for s in sessions:
            s.listen()
        return True

    def _resume_sign(self, rep) -> bool:
        meta = rep.meta
        wallet_id, tx_id = meta["wallet_id"], meta["tx_id"]
        key_type = meta["key_type"]
        nic = meta.get("network_internal_code", "")
        dedup = f"{wallet_id}-{tx_id}"
        fake_msg = SimpleNamespace(
            wallet_id=wallet_id, tx_id=tx_id, network_internal_code=nic
        )
        if not self._claim(dedup, meta=("sign", fake_msg)):
            return False

        def on_done(result):
            try:
                if key_type == wire.KEY_TYPE_SECP256K1:
                    ev = wire.SigningResultEvent(
                        result_type=wire.RESULT_SUCCESS,
                        wallet_id=wallet_id,
                        tx_id=tx_id,
                        network_internal_code=nic,
                        r=format(result["r"], "x"),
                        s=format(result["s"], "x"),
                        signature_recovery=format(result["recovery"], "02x"),
                    )
                else:
                    ev = wire.SigningResultEvent(
                        result_type=wire.RESULT_SUCCESS,
                        wallet_id=wallet_id,
                        tx_id=tx_id,
                        network_internal_code=nic,
                        signature=result.hex(),
                    )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_SIGNING_RESULT}.{tx_id}",
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=tx_id,
                )
                log.info("tx signed (resumed)", wallet=wallet_id, tx=tx_id,
                         node=self.node.node_id)
            finally:
                self._finish(dedup)

        def on_error(e):
            if not isinstance(e, RetryableSessionError):
                ev = wire.SigningResultEvent(
                    result_type=wire.RESULT_ERROR,
                    wallet_id=wallet_id,
                    tx_id=tx_id,
                    network_internal_code=nic,
                    error_reason=str(e),
                )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_SIGNING_RESULT}.{tx_id}",
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=tx_id,
                )
            else:
                log.warn("resumed signing retryable failure",
                         wallet=wallet_id, tx=tx_id, reason=str(e))
            self._finish(dedup)

        session = self.node.resume_session(rep, on_done=on_done,
                                           on_error=on_error)
        self._track(dedup, [session])
        session.listen()
        return True

    def _resume_reshare(self, rep) -> bool:
        meta = rep.meta
        wallet_id, key_type = meta["wallet_id"], meta["key_type"]
        new_threshold = meta["new_threshold"]
        dedup = f"reshare-{key_type}-{wallet_id}"
        if not self._claim(dedup):
            return False

        def on_done(share):
            try:
                if share is None:
                    return  # old-only member
                ev = wire.ResharingSuccessEvent(
                    wallet_id=wallet_id,
                    new_threshold=new_threshold,
                    key_type=key_type,
                    pub_key=share.public_key.hex(),
                )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_RESHARING_RESULT}.{wallet_id}",
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=f"{wallet_id}-{key_type}",
                )
                log.info("wallet reshared (resumed)", wallet=wallet_id,
                         key_type=key_type, node=self.node.node_id)
            finally:
                self._finish(dedup)

        def on_error(e):
            log.error("resumed resharing failed", wallet=wallet_id,
                      error=str(e))
            self._finish(dedup)

        session = self.node.resume_session(rep, on_done=on_done,
                                           on_error=on_error)
        self._track(dedup, [session])
        session.listen()
        return True

    # -- keygen -------------------------------------------------------------

    def _on_generate(self, raw: bytes) -> None:
        try:
            msg = wire.GenerateKeyMessage.from_json(json.loads(raw))
        except Exception as e:  # noqa: BLE001
            log.warn("bad generate event", error=repr(e))
            return
        if not self.node.identity.verify_initiator(msg.raw(), msg.signature):
            log.warn("generate event with BAD initiator signature dropped",
                     wallet=msg.wallet_id)
            return
        wallet_id = msg.wallet_id
        dedup = f"keygen-{wallet_id}"
        if not self._claim(dedup):
            log.info("duplicate keygen event ignored", wallet=wallet_id)
            return
        # TPU batch path: coalesce concurrent wallet creations into one
        # batched-DKG dispatch pair (consumers.batch_scheduler kind="kg")
        if self.scheduler is not None and self.scheduler.submit_keygen(msg):
            return
        self._start_keygen_single(msg, dedup)

    def _keygen_fallback(self, msg) -> None:
        """Scheduler liveness fallback (keygen manifest never arrived):
        per-wallet dual-curve sessions. The dedup claim is still held."""
        self._start_keygen_single(msg, f"keygen-{msg.wallet_id}")

    def _start_keygen_single(self, msg, dedup: str) -> None:
        wallet_id = msg.wallet_id
        threshold = self._threshold()
        results: Dict[str, bytes] = {}
        errors: list = []
        done = threading.Event()

        def mk_done(kt):
            def _done(share):
                results[kt] = share.public_key
                if len(results) == 2:
                    done.set()
            return _done

        def mk_err(kt):
            def _err(e):
                errors.append((kt, e))
                done.set()  # real error propagation, not a hung WaitGroup
                             # (reference wart §7.5: error goroutines never
                             # abort the WaitGroup)
            return _err

        def emit_keygen_error(reason: str):
            ev = wire.KeygenSuccessEvent(
                wallet_id=wallet_id, ecdsa_pub_key="", eddsa_pub_key="",
                result_type=wire.RESULT_ERROR, error_reason=reason,
            )
            self.transport.queues.enqueue(
                f"{wire.TOPIC_KEYGEN_RESULT}.{wallet_id}",
                wire.canonical_json(ev.to_json()),
                idempotency_key=f"{wallet_id}-err",
            )

        try:
            sessions = []
            for kt in (wire.KEY_TYPE_SECP256K1, wire.KEY_TYPE_ED25519):
                s = self.node.create_keygen_session(
                    kt, wallet_id, threshold,
                    on_done=mk_done(kt), on_error=mk_err(kt),
                )
                sessions.append(s)
        except NotEnoughParticipants as e:
            log.warn("keygen: cluster not ready", wallet=wallet_id, error=str(e))
            emit_keygen_error(f"cluster not ready: {e}")
            self._release(dedup)
            return
        self._track(dedup, sessions)
        for s in sessions:
            s.listen()

        def waiter():
            finished = done.wait(self.session_timeout_s)
            try:
                if errors or len(results) != 2:
                    log.error("keygen failed", wallet=wallet_id,
                              errors=repr(errors))
                    reason = (
                        "; ".join(f"{kt}: {e}" for kt, e in errors)
                        if errors
                        else ("timed out" if not finished else "incomplete")
                    )
                    emit_keygen_error(reason)
                    return
                event = wire.KeygenSuccessEvent(
                    wallet_id=wallet_id,
                    ecdsa_pub_key=results[wire.KEY_TYPE_SECP256K1].hex(),
                    eddsa_pub_key=results[wire.KEY_TYPE_ED25519].hex(),
                )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_KEYGEN_RESULT}.{wallet_id}",
                    wire.canonical_json(event.to_json()),
                    idempotency_key=wallet_id,
                )
                log.info("wallet created", wallet=wallet_id,
                         node=self.node.node_id)
            finally:
                self._finish(dedup)

        threading.Thread(target=waiter, daemon=True).start()

    # -- signing ------------------------------------------------------------

    def _on_sign(self, raw: bytes) -> None:
        """Handles mpc:sign — wrapped by publish_with_reply, so the payload
        carries the reply inbox."""
        try:
            outer = json.loads(raw)
            reply_topic = outer.get("reply", "")
            msg = wire.SignTxMessage.from_json(
                json.loads(bytes.fromhex(outer["data"]))
            )
        except Exception:
            # tolerate un-wrapped direct publishes too
            try:
                msg = wire.SignTxMessage.from_json(json.loads(raw))
                reply_topic = ""
            except Exception as e:  # noqa: BLE001
                log.warn("bad sign event", error=repr(e))
                return
        if not self.node.identity.verify_initiator(msg.raw(), msg.signature):
            log.warn("sign event with BAD initiator signature dropped",
                     wallet=msg.wallet_id, tx=msg.tx_id)
            return
        dedup = f"{msg.wallet_id}-{msg.tx_id}"
        if not self._claim(dedup, meta=("sign", msg)):
            log.info("duplicate signing session ignored", key=dedup)
            # Answer the (fresh) reply inbox anyway: a batched dispatch
            # can legitimately outlive the durable bridge's reply window
            # (full-size GG18 compiles take minutes), and an unanswered
            # redelivery would march to dead-letter and emit a timeout
            # ERROR for work that is still in flight. A reply means
            # "accepted, in progress" — completion reaches the client
            # through the idempotent result queues, and in-node liveness
            # is the scheduler's/session-GC's job, not redelivery's.
            if reply_topic:
                self.transport.pubsub.publish(reply_topic, b"WIP")
            return
        # TPU batch path: coalesce concurrent requests into one engine
        # dispatch per round (consumers.batch_scheduler); falls back to the
        # per-session path when batching does not apply
        if self.scheduler is not None and self.scheduler.submit(
            msg, reply_topic
        ):
            return
        self._start_single(msg, reply_topic, dedup)

    def _batch_fallback(self, msg, reply_topic) -> None:
        """Scheduler liveness fallback (manifest never arrived): run the
        request through the normal per-session path. The dedup claim from
        _on_sign is still held."""
        self._start_single(msg, reply_topic, f"{msg.wallet_id}-{msg.tx_id}")

    def _start_single(self, msg, reply_topic: str, dedup: str) -> None:
        def emit_error(reason: str, timeout: bool = False):
            ev = wire.SigningResultEvent(
                result_type=wire.RESULT_ERROR,
                wallet_id=msg.wallet_id,
                tx_id=msg.tx_id,
                network_internal_code=msg.network_internal_code,
                error_reason=reason,
                is_timeout=timeout,
            )
            self.transport.queues.enqueue(
                f"{wire.TOPIC_SIGNING_RESULT}.{msg.tx_id}",
                wire.canonical_json(ev.to_json()),
                idempotency_key=msg.tx_id,
            )
            # terminal error: ack the reply inbox so the durable bridge
            # doesn't burn its full timeout before acking (the reference
            # error path Acks the stream message, event_consumer.go:349-373)
            if reply_topic:
                self.transport.pubsub.publish(reply_topic, b"ERR")

        def on_done(result):
            try:
                if msg.key_type == wire.KEY_TYPE_SECP256K1:
                    ev = wire.SigningResultEvent(
                        result_type=wire.RESULT_SUCCESS,
                        wallet_id=msg.wallet_id,
                        tx_id=msg.tx_id,
                        network_internal_code=msg.network_internal_code,
                        r=format(result["r"], "x"),
                        s=format(result["s"], "x"),
                        signature_recovery=format(result["recovery"], "02x"),
                    )
                else:
                    ev = wire.SigningResultEvent(
                        result_type=wire.RESULT_SUCCESS,
                        wallet_id=msg.wallet_id,
                        tx_id=msg.tx_id,
                        network_internal_code=msg.network_internal_code,
                        signature=result.hex(),
                    )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_SIGNING_RESULT}.{msg.tx_id}",
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=msg.tx_id,
                )
                if reply_topic:
                    self.transport.pubsub.publish(reply_topic, b"OK")
                log.info("tx signed", wallet=msg.wallet_id, tx=msg.tx_id,
                         node=self.node.node_id)
            finally:
                self._finish(dedup)

        def on_error(e):
            if isinstance(e, RetryableSessionError):
                # e.g. hello-barrier deadline: leave the durable request
                # un-acked (no reply, no result event) so the queue
                # redelivers and a later attempt can gather the quorum
                log.warn("signing retryable failure", wallet=msg.wallet_id,
                         tx=msg.tx_id, reason=str(e))
                self._finish(dedup)
                return
            emit_error(str(e))
            self._finish(dedup)

        try:
            session = self.node.create_signing_session(
                msg.key_type, msg.wallet_id, msg.tx_id, msg.tx,
                on_done=on_done, on_error=on_error,
                network_internal_code=msg.network_internal_code,
            )
        except NotEnoughParticipants as e:
            # no reply ⇒ the durable bridge times out, naks, and the queue
            # redelivers (event_consumer.go:276-280 leaves the event
            # un-acked for exactly this retry)
            log.warn("signing retryable", wallet=msg.wallet_id,
                     tx=msg.tx_id, reason=str(e))
            self._release(dedup)
            return
        except Exception as e:  # noqa: BLE001
            log.error("signing session init failed", error=str(e))
            emit_error(str(e))
            self._release(dedup)
            return
        if session is None:
            # not in quorum — other nodes will sign. Do NOT reply: an early
            # OK would ack the durable request before any quorum node has
            # committed, killing the redelivery path when quorum nodes bail
            # retryably.
            self._release(dedup)
            return
        self._track(dedup, [session])
        session.listen()

    # -- resharing ----------------------------------------------------------

    def _on_reshare(self, raw: bytes) -> None:
        try:
            msg = wire.ResharingMessage.from_json(json.loads(raw))
        except Exception as e:  # noqa: BLE001
            log.warn("bad reshare event", error=repr(e))
            return
        if not self.node.identity.verify_initiator(msg.raw(), msg.signature):
            log.warn("reshare event with BAD initiator signature dropped",
                     wallet=msg.wallet_id)
            return
        dedup = f"reshare-{msg.key_type}-{msg.wallet_id}"
        if not self._claim(dedup):
            return
        # TPU batch path: coalesce concurrent rotations of one topology
        # into a single batched re-deal (consumers.batch_scheduler "rs")
        if self.scheduler is not None and self.scheduler.submit_reshare(msg):
            return
        self._start_reshare_single(msg, dedup)

    def _reshare_fallback(self, msg) -> None:
        """Scheduler liveness fallback (reshare manifest never arrived)."""
        self._start_reshare_single(
            msg, f"reshare-{msg.key_type}-{msg.wallet_id}"
        )

    def _start_reshare_single(self, msg, dedup: str) -> None:
        def on_done(share):
            try:
                if share is None:
                    return  # old-only member
                ev = wire.ResharingSuccessEvent(
                    wallet_id=msg.wallet_id,
                    new_threshold=msg.new_threshold,
                    key_type=msg.key_type,
                    pub_key=share.public_key.hex(),
                )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_RESHARING_RESULT}.{msg.wallet_id}",
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=f"{msg.wallet_id}-{msg.key_type}",
                )
                log.info("wallet reshared", wallet=msg.wallet_id,
                         key_type=msg.key_type, node=self.node.node_id)
            finally:
                self._finish(dedup)

        def emit_reshare_error(reason: str):
            ev = wire.ResharingSuccessEvent(
                wallet_id=msg.wallet_id, new_threshold=msg.new_threshold,
                key_type=msg.key_type, pub_key="",
                result_type=wire.RESULT_ERROR, error_reason=reason,
            )
            self.transport.queues.enqueue(
                f"{wire.TOPIC_RESHARING_RESULT}.{msg.wallet_id}",
                wire.canonical_json(ev.to_json()),
                idempotency_key=f"{msg.wallet_id}-{msg.key_type}-err",
            )

        def on_error(e):
            log.error("resharing failed", wallet=msg.wallet_id, error=str(e))
            emit_reshare_error(str(e))
            self._finish(dedup)

        try:
            session = self.node.create_resharing_session(
                msg.key_type, msg.wallet_id, msg.new_threshold,
                on_done=on_done, on_error=on_error,
            )
        except NotEnoughParticipants as e:
            # mpc:reshare is an ephemeral command (no durable retry path,
            # matching the reference) — surface a terminal error event so
            # the initiator is not left waiting
            log.warn("resharing: not enough participants", error=str(e))
            emit_reshare_error(str(e))
            self._release(dedup)
            return
        except Exception as e:  # noqa: BLE001
            log.error("resharing session init failed", error=str(e))
            emit_reshare_error(str(e))
            self._release(dedup)
            return
        self._track(dedup, [session])
        session.listen()

    # -- session bookkeeping (event_consumer.go:49-53, 550-573) -------------

    def _claim(self, key: str, meta=None) -> bool:
        with self._lock:
            if key in self._sessions:
                return False
            self._sessions[key] = []
            self._claim_ts[key] = time.monotonic()
            if meta is not None:
                self._claim_meta[key] = meta
            return True

    def _track(self, key: str, sessions) -> None:
        with self._lock:
            self._sessions[key] = list(sessions)

    def _release(self, key: str) -> None:
        with self._lock:
            self._sessions.pop(key, None)
            self._claim_ts.pop(key, None)
            self._claim_meta.pop(key, None)

    def _finish(self, key: str) -> None:
        with self._lock:
            sessions = self._sessions.pop(key, [])
            self._claim_ts.pop(key, None)
            self._claim_meta.pop(key, None)
        for s in sessions:
            s.close()

    def _threshold(self) -> int:
        from ..config import get_config

        return get_config().mpc_threshold

    # -- GC (event_consumer.go:520-547) -------------------------------------

    def _gc_loop(self) -> None:
        while not self._gc_stop.wait(self.gc_interval_s):
            now = time.monotonic()
            stale = []
            # session-less claims (scheduler-owned or the _claim→_track
            # window) reap only when aged out AND the scheduler disowns
            # them — an unreaped empty claim would answer WIP to every
            # redelivery forever, but a live full-size batch
            # legitimately outlives session_timeout_s. The scheduler
            # query happens OUTSIDE our lock: scheduler paths call our
            # release callbacks while holding THEIR lock, so querying
            # owns_dedup under ours would be an ABBA deadlock.
            with self._lock:
                aged_empty = [
                    key for key, sessions in self._sessions.items()
                    if not sessions
                    and now - self._claim_ts.get(key, now)
                    > self.session_timeout_s
                ]
            disowned = {
                key for key in aged_empty
                if not (self.scheduler is not None
                        and self.scheduler.owns_dedup(key))
            }
            with self._lock:
                for key, sessions in list(self._sessions.items()):
                    if sessions:
                        reap = any(
                            now - s.last_activity > self.session_timeout_s
                            for s in sessions
                        )
                    else:
                        # re-check under the lock: the claim must still
                        # be present, session-less, disowned, AND still
                        # aged — during the out-of-lock owns_dedup query
                        # the claim may have been released and freshly
                        # re-claimed by a redelivery; its new _claim_ts
                        # fails the age test and spares it
                        reap = (
                            key in disowned
                            and now - self._claim_ts.get(key, now)
                            > self.session_timeout_s
                        )
                    if reap:
                        stale.append((key, self._claim_meta.get(key), sessions))
                        self._sessions.pop(key, None)
                        self._claim_ts.pop(key, None)
                        self._claim_meta.pop(key, None)
            for key, meta, sessions in stale:
                # close OUTSIDE the lock: an unfinished session's close
                # fires on_error, which re-enters our bookkeeping
                for s in sessions:
                    s.close()
                log.warn("stale session reaped", key=key,
                         node=self.node.node_id)
                # a reaped SIGNING claim must surface to the client: WIP
                # replies have been acking its redeliveries, so without
                # this terminal event the dead-letter path never fires
                # and the client hangs forever
                if meta is not None and meta[0] == "sign":
                    msg = meta[1]
                    ev = wire.SigningResultEvent(
                        result_type=wire.RESULT_ERROR,
                        wallet_id=msg.wallet_id,
                        tx_id=msg.tx_id,
                        network_internal_code=msg.network_internal_code,
                        error_reason="signing session reaped after "
                        "inactivity timeout",
                        is_timeout=True,
                    )
                    try:
                        self.transport.queues.enqueue(
                            f"{wire.TOPIC_SIGNING_RESULT}.{msg.tx_id}",
                            wire.canonical_json(ev.to_json()),
                            idempotency_key=msg.tx_id,
                        )
                    except Exception as e:  # noqa: BLE001
                        log.warn("reap result emit failed", error=repr(e))
