"""Durable signing ingestion bridge (reference sign_consumer.go).

Consumes the durable signing-request queue, re-publishes each event on the
ephemeral ``mpc:sign`` topic with a fresh reply inbox, and waits for a
reply: reply ⇒ ack; timeout ⇒ raise (nak → queue redelivery, up to
max_deliver, then dead-letter → timeout consumer).

A reply means "accepted and in progress", not "complete": consumers
answer OK/ERR on terminal outcomes and WIP when a redelivered request is
already claimed by a live session or batch (batched full-size GG18 runs
far outlive the reply window; an unanswered redelivery would dead-letter
work still in flight). Results always travel the idempotent result
queues, never the inbox."""
from __future__ import annotations

import threading
import uuid

from .. import wire
from ..transport.api import Transport
from ..utils import log

REPLY_TIMEOUT_S = 30.0  # sign_consumer.go:16-20


class SigningConsumer:
    def __init__(self, transport: Transport, reply_timeout_s: float = REPLY_TIMEOUT_S):
        self.transport = transport
        self.reply_timeout_s = reply_timeout_s
        self._sub = None

    def run(self) -> None:
        self._sub = self.transport.queues.dequeue(
            wire.TOPIC_SIGNING_REQUEST, self._handle
        )

    def close(self) -> None:
        if self._sub:
            self._sub.unsubscribe()

    def _handle(self, data: bytes) -> None:
        """One delivery: publish on mpc:sign with a fresh inbox, wait one
        reply window. Any reply acks the durable message — including WIP
        from a claim holder still batching (terminal results travel the
        idempotent result queues, and an in-process failure later is
        surfaced by the consumer GC's reap-with-error). Known tradeoff:
        if the claim-holding PROCESS dies after a WIP ack, the request is
        gone from the queue and the client learns via its own timeout
        rather than an explicit event — the bound is the client timeout,
        same as the reference's initiator-side budget."""
        reply_topic = f"_inbox.{uuid.uuid4().hex}"
        got_reply = threading.Event()
        sub = self.transport.pubsub.subscribe(
            reply_topic, lambda _d: got_reply.set()
        )
        try:
            self.transport.pubsub.publish_with_reply(
                wire.TOPIC_SIGN, reply_topic, data
            )
            if not got_reply.wait(self.reply_timeout_s):
                log.warn("signing request timed out waiting for reply")
                raise TimeoutError("no signing reply")  # nak ⇒ redelivery
        finally:
            sub.unsubscribe()


class TimeoutConsumer:
    """Dead-letter → client error event (reference timeout_consumer.go):
    when a signing request exhausts its deliveries, synthesize
    SigningResultEvent{error, is_timeout} so the client learns of the
    failure instead of waiting forever."""

    def __init__(self, transport: Transport):
        self.transport = transport

    def run(self) -> None:
        self.transport.set_dead_letter_handler(self._on_dead_letter)

    def _on_dead_letter(self, topic: str, data: bytes, deliveries: int) -> None:
        if not topic.startswith(wire.TOPIC_SIGNING_REQUEST):
            return
        import json

        try:
            msg = wire.SignTxMessage.from_json(json.loads(data))
        except Exception as e:  # noqa: BLE001
            log.warn("dead-letter with undecodable payload", error=repr(e))
            return
        ev = wire.SigningResultEvent(
            result_type=wire.RESULT_ERROR,
            wallet_id=msg.wallet_id,
            tx_id=msg.tx_id,
            network_internal_code=msg.network_internal_code,
            error_reason=f"signing request exhausted {deliveries} deliveries",
            is_timeout=True,
        )
        self.transport.queues.enqueue(
            f"{wire.TOPIC_SIGNING_RESULT}.{msg.tx_id}",
            wire.canonical_json(ev.to_json()),
            idempotency_key=msg.tx_id,
        )
        log.warn("signing request dead-lettered", wallet=msg.wallet_id,
                 tx=msg.tx_id, deliveries=deliveries)
