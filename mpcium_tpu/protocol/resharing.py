"""Committee rotation (resharing) for both curves.

Semantics match the reference (§3.4): an old-committee quorum (≥ t_old+1
holders) re-deals the SAME secret to a new committee under a new threshold;
the wallet public key is unchanged; old shares become useless once the new
committee takes over (`is_reshared` bookkeeping — reference node.go:149-159,
keyinfo.IsReshared).

Construction (Desmedt–Jajodia style, the standard VSS redeal):

  each old quorum member i computes its Lagrange-weighted additive share
  w_i = λ_i·x_i  (Σ w_i = secret), then deals a fresh degree-t_new Feldman
  VSS of w_i to the new committee:

  R1  (old, broadcast)  hash commitment to Feldman points of w_i
  R2a (old, broadcast)  decommitment; C_i0 MUST equal λ_i·X_i, publicly
                        recomputable from the OLD aggregated VSS commitments
                        — binds the redeal to the original wallet key
  R2b (old, unicast)    sub-share f_i(x'_j) for each new member j
  R3  (new, broadcast)  confirm: hash of (new pubkey ‖ new agg commitments)
  finalize              new share x'_j = Σ_i f_i(x'_j); pub unchanged

For secp256k1 the new committee also needs each other's Paillier/ring-
Pedersen material for future GG18 signing; it rides R3 along with DLN +
Paillier-validity proofs (this is why the reference's ECDSA resharing has 7
message types to EdDSA's 5 — pkg/mpc/ecdsa_rounds.go:26-32 vs
eddsa_rounds.go:26-30).

A party may be old-only (deals, then observes confirms), new-only (receives),
or both. Old-only parties finish with ``result = None``.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from ..core import hostmath as hm
from ..core.paillier import PaillierPublicKey, PreParams
from . import commitments as cm
from .base import KeygenShare, PartyBase, ProtocolError, RoundMsg, party_xs

R1 = "reshare/1/commit"
R2_DECOMMIT = "reshare/2/decommit"
R2_SHARE = "reshare/2/share"
R3_CONFIRM = "reshare/3/confirm"


@dataclass(frozen=True)
class CurveOps:
    name: str
    order: int
    mul: Callable  # (k, point) -> point
    add: Callable
    compress: Callable
    decompress: Callable
    generator: object
    identity: object

    def is_identity(self, p) -> bool:
        if self.name == "secp256k1":
            return p.is_infinity
        return p.equals(self.identity)


ED_OPS = CurveOps(
    name="ed25519",
    order=hm.ED_L,
    mul=hm.ed_mul,
    add=hm.ed_add,
    compress=hm.ed_compress,
    decompress=hm.ed_decompress,
    generator=hm.ED_B,
    identity=hm.ED_IDENT,
)

SECP_OPS = CurveOps(
    name="secp256k1",
    order=hm.SECP_N,
    mul=hm.secp_mul,
    add=hm.secp_add,
    compress=hm.secp_compress,
    decompress=hm.secp_decompress,
    generator=hm.SECP_G,
    identity=hm.SECP_INF,
)


def curve_ops(key_type: str) -> CurveOps:
    return {"ed25519": ED_OPS, "secp256k1": SECP_OPS}[key_type]


class ResharingParty(PartyBase):
    """One participant of a resharing session.

    ``old_quorum``: the ≥ t_old+1 old holders driving the redeal (must all
    participate). ``new_committee``: the receivers. ``old_share`` required
    iff self is in the old quorum. ``preparams`` required iff secp256k1 and
    self is in the new committee.
    """

    _SNAP_EXTRA = (
        "_sent_r2", "_sent_r3", "_w_i", "_coeffs", "_shares_out",
        "_points", "_commitment", "_blind", "_x_new", "_new_agg",
        "new_agg", "pre",
    )

    def __init__(
        self,
        session_id: str,
        self_id: str,
        key_type: str,
        old_quorum: Sequence[str],
        new_committee: Sequence[str],
        new_threshold: int,
        old_share: Optional[KeygenShare] = None,
        old_public_key: Optional[bytes] = None,
        old_vss_commitments: Optional[Sequence[bytes]] = None,
        preparams: Optional[PreParams] = None,
        rng=None,
        min_paillier_bits: int = 2046,
        old_epoch: int = 0,
    ):
        import secrets as _secrets

        all_ids = sorted(set(old_quorum) | set(new_committee))
        super().__init__(session_id, self_id, all_ids, rng or _secrets)
        self.old_epoch = old_epoch
        self.new_epoch = old_epoch + 1
        # populated at finalize for ALL roles (old-only members recompute it
        # from the R1/R2 broadcasts) so every participant can move its
        # keyinfo to the new topology
        self.new_agg: Optional[List[bytes]] = None
        self.ops = curve_ops(key_type)
        self.key_type = key_type
        self.old_quorum = sorted(old_quorum)
        self.new_committee = sorted(new_committee)
        self.is_old = self_id in set(old_quorum)
        self.is_new = self_id in set(new_committee)
        self.min_paillier_bits = min_paillier_bits
        if not 0 < new_threshold < len(new_committee):
            raise ValueError("need 0 < t_new < |new committee|")
        self.new_threshold = new_threshold
        self.pre = preparams
        if self.is_old:
            if old_share is None:
                raise ValueError("old-quorum member needs its share")
            if old_share.key_type != key_type:
                raise ValueError("share key-type mismatch")
            self.old_share = old_share
            old_public_key = old_share.public_key
            old_vss_commitments = old_share.vss_commitments
        if old_public_key is None or old_vss_commitments is None:
            raise ValueError(
                "new-only members need old_public_key + old_vss_commitments "
                "(from keyinfo metadata)"
            )
        self.old_public_key = old_public_key
        self.old_agg = [self.ops.decompress(c) for c in old_vss_commitments]
        if key_type == "secp256k1" and self.is_new and preparams is None:
            raise ValueError("secp256k1 new-committee member needs preparams")

        # x-coordinate universes
        if self.is_old:
            self.old_xs = party_xs(self.old_share.participants)
            for pid in self.old_quorum:
                if pid not in self.old_xs:
                    raise ProtocolError("old member outside keygen universe", pid)
        else:
            # any consistent assignment works for verification: old parties'
            # x-coords derive from the OLD keygen universe which new-only
            # members learn from keyinfo participants
            self.old_xs = None  # set lazily from commitments check
        self.new_xs = party_xs(self.new_committee)
        self._sent_r2 = False
        self._sent_r3 = False

    # ------------------------------------------------------------------
    # NOTE: new-only members must know the old universe to check
    # C_i0 == λ_i·X_i; it travels in the R1 payload (signed by each old
    # member, cross-checked for consistency).
    # ------------------------------------------------------------------

    def start(self) -> List[RoundMsg]:
        if not self.is_old:
            return []
        ops = self.ops
        q = ops.order
        quorum_xs = [self.old_xs[p] for p in self.old_quorum]
        lam = hm.lagrange_coeff(quorum_xs, self.old_xs[self.self_id], q)
        self._w_i = lam * self.old_share.share % q
        self._coeffs, self._shares_out = hm.shamir_share(
            self._w_i,
            self.new_threshold,
            [self.new_xs[p] for p in self.new_committee],
            q,
            rng=self.rng,
        )
        self._points = [
            ops.compress(ops.mul(c, ops.generator)) for c in self._coeffs
        ]
        data = cm.encode_points(self._points)
        self._commitment, self._blind = cm.commit(data, rng=self.rng)
        return [
            self.broadcast(
                R1,
                {
                    "commitment": self._commitment.hex(),
                    "old_participants": list(self.old_share.participants),
                },
            )
        ]

    def receive(self, msg: RoundMsg) -> List[RoundMsg]:
        if self.done:
            return []
        expect_old = [p for p in self.old_quorum if p != self.self_id]
        expect_new = [p for p in self.new_committee if p != self.self_id]
        self._store(msg)
        out: List[RoundMsg] = []

        if (
            self.is_old
            and not self._sent_r2
            and self._round_full(R1, expect_old)
        ):
            self._sent_r2 = True
            out.append(
                self.broadcast(
                    R2_DECOMMIT,
                    {
                        "points": [p.hex() for p in self._points],
                        "blind": self._blind.hex(),
                    },
                )
            )
            for pid in self.new_committee:
                if pid == self.self_id:
                    continue
                out.append(
                    self.unicast(
                        pid,
                        R2_SHARE,
                        {"share": str(self._shares_out[self.new_xs[pid]])},
                    )
                )

        if (
            self.is_new
            and not self._sent_r3
            and self._round_full(R1, [p for p in self.old_quorum if p != self.self_id])
            and self._round_full(R2_DECOMMIT, [p for p in self.old_quorum if p != self.self_id])
            and self._round_full(R2_SHARE, [p for p in self.old_quorum if p != self.self_id])
        ):
            self._sent_r3 = True
            out.append(self._build_confirm())

        if self._round_full(R3_CONFIRM, expect_new) and (
            not self.is_new or self._sent_r3
        ):
            # old-only members also need the full R1/R2 broadcast set to
            # recompute the new commitments in _finalize
            if self.is_new or (
                self._round_full(R1, expect_old)
                and self._round_full(R2_DECOMMIT, expect_old)
            ):
                self._finalize()
        return out

    # -- new-member verification + confirm ----------------------------------

    def _redeal_points(self) -> Dict[str, list]:
        """Verify decommitments + C_i0 binding; returns per-old-member
        Feldman points. Requires R1/R2 full (new members only)."""
        ops = self.ops
        commits = self._round_payloads(R1)
        decommits = self._round_payloads(R2_DECOMMIT)

        # establish the old keygen universe consistently
        old_parts = None
        for pid in self.old_quorum:
            if pid == self.self_id:
                parts = list(self.old_share.participants)
            else:
                parts = list(commits[pid]["old_participants"])
            if old_parts is None:
                old_parts = parts
            elif old_parts != parts:
                raise ProtocolError("inconsistent old-universe claims", pid)
        old_xs = party_xs(old_parts)
        for pid in self.old_quorum:
            if pid not in old_xs:
                raise ProtocolError("old member outside claimed universe", pid)
        quorum_xs = [old_xs[p] for p in self.old_quorum]

        all_points: Dict[str, list] = {}
        for pid in self.old_quorum:
            if pid == self.self_id:
                pts = [ops.decompress(p) for p in self._points]
            else:
                pts_hex = decommits[pid]["points"]
                if len(pts_hex) != self.new_threshold + 1:
                    raise ProtocolError("wrong redeal commitment count", pid)
                pts_bytes = [bytes.fromhex(p) for p in pts_hex]
                if not cm.verify(
                    bytes.fromhex(commits[pid]["commitment"]),
                    bytes.fromhex(decommits[pid]["blind"]),
                    cm.encode_points(pts_bytes),
                ):
                    raise ProtocolError("redeal decommitment mismatch", pid)
                try:
                    pts = [ops.decompress(p) for p in pts_bytes]
                except ValueError as e:
                    raise ProtocolError(f"bad redeal point: {e}", pid)
            # C_i0 must equal λ_i·X_i — the public binding to the old key
            lam = hm.lagrange_coeff(quorum_xs, old_xs[pid], ops.order)
            X_i = _eval_commitments_generic(ops, self.old_agg, old_xs[pid])
            expect = ops.mul(lam, X_i)
            if ops.compress(pts[0]) != ops.compress(expect):
                raise ProtocolError("redeal does not match old key share", pid)
            all_points[pid] = pts
        return all_points

    def _build_confirm(self) -> RoundMsg:
        ops = self.ops
        all_points = self._redeal_points()
        shares = self._round_payloads(R2_SHARE)
        my_x = self.new_xs[self.self_id]
        x_new = 0
        for pid in self.old_quorum:
            if pid == self.self_id:
                s = self._shares_out[my_x]
            else:
                s = int(shares[pid]["share"])
                if not 0 <= s < ops.order:
                    raise ProtocolError("sub-share out of range", pid)
                expect = _eval_commitments_generic(ops, all_points[pid], my_x)
                if ops.compress(ops.mul(s, ops.generator)) != ops.compress(expect):
                    raise ProtocolError("sub-share VSS verification failed", pid)
            x_new = (x_new + s) % ops.order
        # aggregate new VSS commitments
        agg = []
        for k in range(self.new_threshold + 1):
            acc = self.ops.identity
            for pid in self.old_quorum:
                acc = ops.add(acc, all_points[pid][k])
            agg.append(acc)
        new_pub = ops.compress(agg[0])
        if new_pub != ops.compress(self.ops.decompress(self.old_public_key)):
            raise ProtocolError("resharing changed the public key")
        self._x_new = x_new
        self._new_agg = [ops.compress(p) for p in agg]
        digest = hashlib.sha256(
            b"reshare-confirm" + new_pub + b"".join(self._new_agg)
        ).hexdigest()
        payload = {"digest": digest}
        if self.key_type == "secp256k1":
            payload.update(self._paillier_payload())
        return self.broadcast(R3_CONFIRM, payload)

    # -- secp256k1: fresh Paillier material for the new committee -----------

    def _paillier_payload(self) -> dict:
        from .ecdsa.zk import DLNProof, PaillierProof

        pre = self.pre
        pq = (pre.P - 1) // 2 * ((pre.Q - 1) // 2)
        bind = f"{self.session_id}:{self.self_id}".encode()
        return {
            "paillier_n": str(pre.paillier.N),
            "ntilde": str(pre.NTilde),
            "h1": str(pre.h1),
            "h2": str(pre.h2),
            "dln1": DLNProof.prove(
                pre.h1, pre.h2, pre.alpha, pq, pre.NTilde, self.rng, bind=bind
            ).to_json(),
            "dln2": DLNProof.prove(
                pre.h2, pre.h1, pre.beta, pq, pre.NTilde, self.rng, bind=bind
            ).to_json(),
            "paillier_proof": PaillierProof.prove(pre.paillier, bind=bind).to_json(),
        }

    def _verify_paillier_payload(self, pid: str, p: dict) -> dict:
        from .ecdsa.zk import DLNProof, PaillierProof

        N = int(p["paillier_n"])
        ntilde, h1, h2 = int(p["ntilde"]), int(p["h1"]), int(p["h2"])
        if N.bit_length() < self.min_paillier_bits:
            raise ProtocolError("Paillier modulus too small", pid)
        if ntilde.bit_length() < self.min_paillier_bits:
            raise ProtocolError("NTilde too small", pid)
        if h1 in (0, 1) or h2 in (0, 1) or h1 == h2:
            raise ProtocolError("degenerate ring-Pedersen bases", pid)
        bind = f"{self.session_id}:{pid}".encode()
        if not DLNProof.from_json(p["dln1"]).verify(h1, h2, ntilde, bind=bind):
            raise ProtocolError("DLN proof failed", pid)
        if not DLNProof.from_json(p["dln2"]).verify(h2, h1, ntilde, bind=bind):
            raise ProtocolError("DLN proof failed", pid)
        proof = PaillierProof.from_json(p["paillier_proof"])
        if N.bit_length() >= 2046:
            if not proof.verify(PaillierPublicKey(N), bind=bind):
                raise ProtocolError("Paillier validity proof failed", pid)
        return {"N": N, "ntilde": ntilde, "h1": h1, "h2": h2}

    # -- finalize ------------------------------------------------------------

    def _finalize(self) -> None:
        confirms = self._round_payloads(R3_CONFIRM)
        digests = set()
        peer_material: Dict[str, dict] = {}
        for pid in self.new_committee:
            if pid == self.self_id:
                continue
            digests.add(confirms[pid]["digest"])
            if self.key_type == "secp256k1" and self.is_new:
                peer_material[pid] = self._verify_paillier_payload(
                    pid, confirms[pid]
                )
        if len(digests) > 1:
            raise ProtocolError("new committee disagrees on reshared key")

        if not self.is_new:
            # old-only member: recompute the new aggregated commitments from
            # the R1/R2 broadcasts (it saw them as a dealer) and check them
            # against the new committee's confirm digest, so its keyinfo can
            # follow the rotation even though it holds no new share
            all_points = self._redeal_points()
            agg = []
            for k in range(self.new_threshold + 1):
                acc = self.ops.identity
                for pid in self.old_quorum:
                    acc = self.ops.add(acc, all_points[pid][k])
                agg.append(acc)
            new_agg = [self.ops.compress(p) for p in agg]
            digest = hashlib.sha256(
                b"reshare-confirm"
                + self.ops.compress(self.ops.decompress(self.old_public_key))
                + b"".join(new_agg)
            ).hexdigest()
            if digests and digests != {digest}:
                raise ProtocolError("confirm digest mismatch (old-only view)")
            self.new_agg = new_agg
            self.result = None
            self.done = True
            return

        digest = hashlib.sha256(
            b"reshare-confirm"
            + self.ops.compress(self.ops.decompress(self.old_public_key))
            + b"".join(self._new_agg)
        ).hexdigest()
        if digests and digests != {digest}:
            raise ProtocolError("confirm digest mismatch")

        aux = {"is_reshared": True}
        if self.key_type == "secp256k1":
            aux.update(
                {
                    "paillier_sk": self.pre.paillier.to_json(),
                    "preparams": {
                        "ntilde": str(self.pre.NTilde),
                        "h1": str(self.pre.h1),
                        "h2": str(self.pre.h2),
                    },
                    "peer_paillier": {
                        pid: str(m["N"]) for pid, m in peer_material.items()
                    },
                    "peer_ring_pedersen": {
                        pid: {
                            "ntilde": str(m["ntilde"]),
                            "h1": str(m["h1"]),
                            "h2": str(m["h2"]),
                        }
                        for pid, m in peer_material.items()
                    },
                }
            )
        self.new_agg = list(self._new_agg)
        self.result = KeygenShare(
            key_type=self.key_type,
            share=self._x_new,
            self_x=self.new_xs[self.self_id],
            public_key=self.old_public_key
            if isinstance(self.old_public_key, bytes)
            else bytes(self.old_public_key),
            vss_commitments=self._new_agg,
            participants=list(self.new_committee),
            threshold=self.new_threshold,
            epoch=self.new_epoch,
            aux=aux,
        )
        self.done = True


def _eval_commitments_generic(ops: CurveOps, points, x: int):
    acc = ops.identity
    for pt in reversed(points):
        acc = ops.add(ops.mul(x, acc), pt)
    return acc
