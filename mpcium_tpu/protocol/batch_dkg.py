"""Distributed batched DKG + resharing: ONE protocol instance creates (or
rotates) B wallets concurrently.

The node-side face of :mod:`engine.dkg_batch` (BASELINE configs 4–5): where
:mod:`.ecdsa.keygen` / :mod:`.eddsa.keygen` / :mod:`.resharing` run one
party per wallet (the reference spawns one tss-lib party per request,
event_consumer.go:103-204, 375-518), these parties exchange fixed-shape
byte blocks — (B·32)-byte coefficient/sub-share blocks, (B·(t+1)·w)-byte
Feldman commitment blocks — and compute every round with the batched
device kernels. The scheduler (consumers.batch_scheduler) buckets
concurrent wallet-creation / resharing requests into these batches.

Curve-generic (ed25519 + secp256k1). For secp256k1 the per-NODE
Paillier/ring-Pedersen material is batch-independent: it is exchanged and
proven ONCE per batch (DLN proofs in round 1, the Paillier validity proof
in round 2) instead of once per wallet — B wallets' GG18 aux material for
the price of one proof exchange.

DKG wire schedule (3 rounds, the reference's 4-round GG18 DKG with the
paillier proof folded into the reveal round):

  R1  broadcast   hash-commitment block to the Feldman commitments
                  [+ secp: paillier N, NTilde/h1/h2, two DLN proofs]
  R2  broadcast   decommit: commitment-point block + blind block
                  [+ secp: Paillier validity proof]
      unicast→j   sub-share block f_i(x_j) (B·32)
  finalize        binding + Feldman VSS + proof checks, aggregate

Resharing wire schedule (old quorum re-deals to the new committee; public
keys must be preserved; epoch increments):

  R1  broadcast (old)   commitment block (coeff0 = λ_i·x_i)
  R2  broadcast (old)   decommit; unicast→new: sub-share block
  R3  broadcast (new)   confirm [+ secp: NEW paillier material + proofs]
  finalize              new members aggregate + rebuild aux; old-only
                        members complete on confirms

Failures raise :class:`ProtocolError` with the culprit attributed (batch
abort): a DKG/reshare batch is an all-or-nothing artifact — unlike
signing, a partially-created wallet set must not be persisted, and the
durable request path retries the batch.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import bignum as bn
from ..core import hostmath as hm
from ..core.bignum import P256
from ..core.paillier import PaillierPublicKey, PreParams
from ..engine import pipeline as pl
from ..engine.dkg_batch import (
    _blk_vss_check, _curve, _rand_scalars, _subshare_phase, _xj_bits,
)
from ..ops.sha256 import sha256 as dev_sha256
from ..perf import compile_watch
from .base import (BatchBlockMixin, KeygenShare, PartyBase, ProtocolError,
                   RoundMsg, party_xs)
from .ecdsa.keygen import MIN_PAILLIER_BITS
from .ecdsa.zk import DLNProof, PaillierProof

SCALAR_BITS = 256

DKG_R1 = "dkg/b/1/commit"
DKG_R2B = "dkg/b/2/reveal"
DKG_R2S = "dkg/b/2/share"

RS_R1 = "reshare/b/1/commit"
RS_R2B = "reshare/b/2/reveal"
RS_R2S = "reshare/b/2/share"
RS_R3 = "reshare/b/3/confirm"


def _comp_width(key_type: str) -> int:
    return 33 if key_type == "secp256k1" else 32


@functools.partial(jax.jit, static_argnames=("key_type",))
def _blk_deal_commit(coeffs, blind, bind_row, key_type: str):
    """Own dealing: coeffs (t+1, B, 22) → (points list, compressed block
    (B, (t+1)·w), hash-commitment block (B, 32))."""
    mod, _ = _curve(key_type)
    pts, comps = [], []
    for k in range(coeffs.shape[0]):
        pt = mod.base_mul(bn.limbs_to_bits(coeffs[k], P256, SCALAR_BITS))
        pts.append(pt)
        comps.append(mod.compress(pt))
    block = jnp.concatenate(comps, axis=-1)
    commit = dev_sha256(jnp.concatenate([bind_row, blind, block], axis=-1))
    return pts, block, commit


@jax.jit
def _blk_commit_check(bind_row, blind, block, commit):
    got = dev_sha256(jnp.concatenate([bind_row, blind, block], axis=-1))
    return jnp.all(got == commit, axis=-1)


def _concat_pts(parts):
    """Per-cohort point batches (NamedTuple pytrees of (width, …) leaves)
    concatenated back to batch order along the lane axis."""
    if len(parts) == 1:
        return parts[0]
    return type(parts[0])(*(
        jnp.concatenate(leaves, axis=0) for leaves in zip(*parts)
    ))


class _DealingMixin(BatchBlockMixin):
    """Shared block (de)serialization + Feldman machinery (binding row and
    block parsing come from protocol.base.BatchBlockMixin — one definition
    shared with the batched signing party)."""

    key_type: str
    B: int

    def _ser_scalars(self, x: jnp.ndarray) -> str:
        # mpcflow: host-ok — wire serialization of a scalar block
        host = np.asarray(bn.limbs_to_bytes_le(x, P256, 32))
        return host.tobytes().hex()

    def _parse_scalars(self, hexstr: str, order: int, pid: str) -> jnp.ndarray:
        arr = self._parse_block(hexstr, 32, pid)
        mod, _ = _curve(self.key_type)
        ring = mod.scalar_ring()
        return ring.reduce(bn.bytes_to_limbs_le(jnp.asarray(arr), P256, 22))

    def _decompress_dealer_points(
        self, block: np.ndarray, tp1: int, pid: str
    ):
        """(B, (t+1)·w) compressed block → list of t+1 point batches."""
        mod, _ = _curve(self.key_type)
        w = _comp_width(self.key_type)
        pts = []
        ok_all = None
        for k in range(tp1):
            pt, ok = mod.decompress(jnp.asarray(block[:, k * w:(k + 1) * w]))
            ok_all = ok if ok_all is None else ok_all & ok
            pts.append(pt)
        # one device→host sync for the whole block, not one per coefficient
        if not bool(np.asarray(ok_all).all()):  # mpcflow: host-ok — verification verdict gates the protocol on host
            raise ProtocolError("bad commitment point in batch", pid)
        return pts

    def _verify_dealer(
        self,
        pid: str,
        commit_hex: str,
        reveal: Dict,
        subshare: jnp.ndarray,
        self_x: int,
    ):
        """Binding + Feldman VSS for one dealer → their commitment points."""
        w = _comp_width(self.key_type)
        tp1 = self.tp1
        block_np = self._parse_block(reveal["points"], tp1 * w, pid)
        blind = jnp.asarray(self._parse_block(reveal["blind"], 32, pid))
        commit = jnp.asarray(self._parse_block(commit_hex, 32, pid))
        ok = _blk_commit_check(
            self._bind_row(pid), blind, jnp.asarray(block_np), commit
        )
        if not bool(np.asarray(ok).all()):  # mpcflow: host-ok — per-dealer verification verdict must gate the protocol on host
            raise ProtocolError("dealing decommitment mismatch", pid)
        pts = self._decompress_dealer_points(block_np, tp1, pid)
        pts_desc = tuple(pts[::-1])
        okv = _blk_vss_check(
            subshare, pts_desc, _xj_bits(self_x, self.B), self.key_type
        )
        if not bool(np.asarray(okv).all()):  # mpcflow: host-ok — per-dealer verification verdict must gate the protocol on host
            raise ProtocolError("Feldman VSS share verification failed", pid)
        return pts


class BatchedDKGParty(_DealingMixin, PartyBase):
    """One node's side of a B-wallet batched DKG (one curve; the consumer
    runs one party per curve and joins results, mirroring the reference's
    concurrent dual-curve keygen, event_consumer.go:121-178)."""

    def __init__(
        self,
        session_id: str,
        self_id: str,
        party_ids: Sequence[str],
        threshold: int,
        key_type: str,
        n_wallets: int,
        preparams: Optional[PreParams] = None,
        min_paillier_bits: int = MIN_PAILLIER_BITS,
        rng=None,
        cohorts: Optional[int] = None,
    ):
        import secrets as _secrets

        super().__init__(session_id, self_id, party_ids, rng or _secrets)
        if not 0 < threshold < len(party_ids):
            raise ValueError("need 0 < t < n")
        if n_wallets < 1:
            raise ValueError("need at least one wallet")
        if key_type == "secp256k1" and preparams is None:
            raise ValueError("secp256k1 batched DKG requires preparams")
        self.threshold = threshold
        self.tp1 = threshold + 1
        self.key_type = key_type
        self.B = n_wallets
        self.pre = preparams
        self.min_paillier_bits = min_paillier_bits
        self._plan = pl.CohortPlan.for_batch(self.B, cohorts)
        self._stage = 0

    def _proof_bind(self, sender: str) -> bytes:
        return f"{self.session_id}:{sender}".encode()

    def start(self) -> List[RoundMsg]:
        B, q = self.B, len(self.party_ids)
        # mpcshape: unbounded-ok — B is pow-2 snapped upstream (scheduler chunks via engine/buckets.floor_bucket; bench via bucket_b)
        self._cw = compile_watch.begin("party.dkg", f"B{B}|q{q}|{self.key_type}")
        mod, order = _curve(self.key_type)
        self._coeffs = jnp.asarray(
            _rand_scalars((self.tp1, self.B), order, self.rng)
        )
        self._blind = jnp.asarray(
            np.frombuffer(
                self.rng.token_bytes(self.B * 32), dtype=np.uint8
            ).reshape(self.B, 32)
        )
        # counter-phase dealing (engine/pipeline): coeffs/blinds were
        # drawn full-batch above in K=1 serial order, so the commitment
        # block is bit-identical for every cohort count
        bind = self._bind_row(self.self_id)

        def make_job(ci: int, sl: slice):
            def job():
                pts, block, commit = _blk_deal_commit(
                    self._coeffs[:, sl], self._blind[sl], bind[sl],
                    self.key_type,
                )
                commit_host = yield (
                    "commit_egress",
                    lambda: np.asarray(commit),  # mpcflow: host-ok — commitment block leaves device for wire serialization
                )
                return pts, block, commit_host

            return job

        outs = pl.run_counter_phase(
            [make_job(ci, sl) for ci, sl in enumerate(self._plan.slices())]
        )
        self._pts = [
            _concat_pts([o[0][k] for o in outs]) for k in range(self.tp1)
        ]
        self._block = (
            outs[0][1]
            if self._plan.serial
            else jnp.concatenate([o[1] for o in outs], axis=0)
        )
        commit_host = np.concatenate([o[2] for o in outs], axis=0)
        payload = {"commit": commit_host.tobytes().hex()}
        if self.key_type == "secp256k1":
            pre = self.pre
            pq = (pre.P - 1) // 2 * ((pre.Q - 1) // 2)
            bind = self._proof_bind(self.self_id)
            payload.update(
                {
                    "paillier_n": str(pre.paillier.N),
                    "ntilde": str(pre.NTilde),
                    "h1": str(pre.h1),
                    "h2": str(pre.h2),
                    "dln1": DLNProof.prove(
                        pre.h1, pre.h2, pre.alpha, pq, pre.NTilde, self.rng,
                        bind=bind,
                    ).to_json(),
                    "dln2": DLNProof.prove(
                        pre.h2, pre.h1, pre.beta, pq, pre.NTilde, self.rng,
                        bind=bind,
                    ).to_json(),
                }
            )
        self._stage = 1
        return [self.broadcast(DKG_R1, payload)]

    def receive(self, msg: RoundMsg) -> List[RoundMsg]:
        if self.done:
            return []
        self._store(msg)
        others = self.others()
        out: List[RoundMsg] = []
        if self._stage == 1 and self._round_full(DKG_R1, others):
            self._verify_r1()
            payload = {
                "points": np.asarray(self._block).tobytes().hex(),
                "blind": np.asarray(self._blind).tobytes().hex(),
            }
            if self.key_type == "secp256k1":
                payload["paillier_proof"] = PaillierProof.prove(
                    self.pre.paillier, bind=self._proof_bind(self.self_id)
                ).to_json()
            out.append(self.broadcast(DKG_R2B, payload))
            xs_tuple = tuple(self.xs[p] for p in self.party_ids)
            subs = _subshare_phase(
                self._coeffs[None], self.key_type, xs_tuple
            )[0]
            self._own_sub = {
                pid: subs[i] for i, pid in enumerate(self.party_ids)
            }
            for pid in others:
                out.append(
                    self.unicast(
                        pid, DKG_R2S,
                        {"share": self._ser_scalars(self._own_sub[pid])},
                    )
                )
            self._stage = 2
        if (
            self._stage == 2
            and self._round_full(DKG_R2B, others)
            and self._round_full(DKG_R2S, others)
        ):
            self._finalize()
        return out

    def _verify_r1(self) -> None:
        if self.key_type != "secp256k1":
            return
        r1 = self._round_payloads(DKG_R1)
        self._peer_pk: Dict[str, PaillierPublicKey] = {}
        self._peer_rp: Dict[str, Dict[str, int]] = {}
        for pid in self.others():
            p = r1[pid]
            N = int(p["paillier_n"])
            ntilde, h1, h2 = int(p["ntilde"]), int(p["h1"]), int(p["h2"])
            if N.bit_length() < self.min_paillier_bits:
                raise ProtocolError("Paillier modulus too small", pid)
            if ntilde.bit_length() < self.min_paillier_bits:
                raise ProtocolError("NTilde too small", pid)
            if h1 in (0, 1) or h2 in (0, 1) or h1 == h2:
                raise ProtocolError("degenerate ring-Pedersen bases", pid)
            bind = self._proof_bind(pid)
            if not DLNProof.from_json(p["dln1"]).verify(h1, h2, ntilde, bind=bind):
                raise ProtocolError("DLN proof (h2 = h1^a) failed", pid)
            if not DLNProof.from_json(p["dln2"]).verify(h2, h1, ntilde, bind=bind):
                raise ProtocolError("DLN proof (h1 = h2^b) failed", pid)
            self._peer_pk[pid] = PaillierPublicKey(N)
            self._peer_rp[pid] = {"ntilde": ntilde, "h1": h1, "h2": h2}

    def _finalize(self) -> None:
        mod, order = _curve(self.key_type)
        ring = mod.scalar_ring()
        r1 = self._round_payloads(DKG_R1)
        r2b = self._round_payloads(DKG_R2B)
        r2s = self._round_payloads(DKG_R2S)

        if self.key_type == "secp256k1":
            for pid in self.others():
                proof = PaillierProof.from_json(r2b[pid]["paillier_proof"])
                pk = self._peer_pk[pid]
                if pk.N.bit_length() >= 2046:
                    if not proof.verify(pk, bind=self._proof_bind(pid)):
                        raise ProtocolError("Paillier validity proof failed", pid)
                elif not proof.ys:
                    raise ProtocolError("missing Paillier proof", pid)

        agg_share = self._own_sub[self.self_id]
        agg_pts = list(self._pts)
        for pid in self.others():
            sub = self._parse_scalars(r2s[pid]["share"], order, pid)
            pts = self._verify_dealer(
                pid, r1[pid]["commit"], r2b[pid], sub, self.self_x
            )
            agg_share = ring.addmod(agg_share, sub)
            for k in range(self.tp1):
                agg_pts[k] = mod.add(agg_pts[k], pts[k])

        agg_comp = [
            np.asarray(mod.compress(pt))  # mpcflow: host-ok — public VSS commitments, egress into the share objects
            for pt in agg_pts
        ]  # (t+1) arrays of (B, w)
        share_ints = bn.batch_from_limbs(np.asarray(agg_share), P256)  # mpcflow: host-ok — aggregated shares leave device once, for the returned share objects
        aux: Dict = {}
        if self.key_type == "secp256k1":
            pre = self.pre
            aux = {
                "paillier_sk": pre.paillier.to_json(),
                "preparams": {
                    "ntilde": str(pre.NTilde),
                    "h1": str(pre.h1),
                    "h2": str(pre.h2),
                },
                "peer_paillier": {
                    pid: str(pk.N) for pid, pk in self._peer_pk.items()
                },
                "peer_ring_pedersen": {
                    pid: {k: str(v) for k, v in rp.items()}
                    for pid, rp in self._peer_rp.items()
                },
            }
        shares: List[KeygenShare] = []
        for w in range(self.B):
            pub = bytes(agg_comp[0][w].tobytes())
            if share_ints[w] % order == 0:
                raise ProtocolError("degenerate share in batch")
            shares.append(
                KeygenShare(
                    key_type=self.key_type,
                    share=share_ints[w],
                    self_x=self.self_x,
                    public_key=pub,
                    vss_commitments=[
                        bytes(agg_comp[k][w].tobytes())
                        for k in range(self.tp1)
                    ],
                    participants=list(self.party_ids),
                    threshold=self.threshold,
                    aux=dict(aux),
                )
            )
        self.result = shares
        self.done = True
        compile_watch.finish(self._cw)


class BatchedReshareParty(_DealingMixin, PartyBase):
    """One node's side of a B-wallet batched committee rotation.

    ``old_shares``: this node's current shares (old-quorum members only;
    wallet order = manifest order). New members receive fresh shares with
    epoch+1; public keys are verified unchanged. ``result`` is the list of
    new shares for new-committee members, None for old-only members."""

    def __init__(
        self,
        session_id: str,
        self_id: str,
        key_type: str,
        old_quorum: Sequence[str],
        new_committee: Sequence[str],
        new_threshold: int,
        n_wallets: int,
        old_shares: Optional[Sequence[KeygenShare]] = None,
        old_public_keys: Optional[Sequence[bytes]] = None,
        preparams: Optional[PreParams] = None,
        min_paillier_bits: int = MIN_PAILLIER_BITS,
        old_epoch: int = 0,
        rng=None,
        cohorts: Optional[int] = None,
    ):
        import secrets as _secrets

        all_ids = sorted(set(old_quorum) | set(new_committee))
        super().__init__(session_id, self_id, all_ids, rng or _secrets)
        self.key_type = key_type
        self.old_quorum = sorted(old_quorum)
        self.new_committee = sorted(new_committee)
        self.is_old = self_id in self.old_quorum
        self.is_new = self_id in self.new_committee
        self.t_new = new_threshold
        self.tp1 = new_threshold + 1
        self.B = n_wallets
        self.pre = preparams
        self.min_paillier_bits = min_paillier_bits
        self.old_epoch = old_epoch
        self.new_epoch = old_epoch + 1
        if not 0 < new_threshold < len(self.new_committee):
            raise ValueError("need 0 < t_new < |new committee|")
        if self.is_old:
            if old_shares is None or len(old_shares) != n_wallets:
                raise ProtocolError("old member requires one share per wallet")
            for s in old_shares:
                if s.key_type != key_type or s.epoch != old_epoch:
                    raise ProtocolError("stale/mismatched share for reshare")
            self.old_shares = list(old_shares)
            old_public_keys = [s.public_key for s in old_shares]
        if old_public_keys is None or len(old_public_keys) != n_wallets:
            raise ProtocolError("old public keys required for binding check")
        self.old_pubs = [bytes(p) for p in old_public_keys]
        if key_type == "secp256k1" and self.is_new and preparams is None:
            raise ValueError("secp256k1 reshare requires preparams (new member)")
        self._plan = pl.CohortPlan.for_batch(self.B, cohorts)
        self._stage = 0
        self._confirm_sent = False

    def _proof_bind(self, sender: str) -> bytes:
        return f"{self.session_id}:{sender}".encode()

    def start(self) -> List[RoundMsg]:
        B, q, t_new = self.B, len(self.party_ids), self.t_new
        # mpcshape: unbounded-ok — B is pow-2 snapped upstream (scheduler chunks via engine/buckets.floor_bucket; bench via bucket_b)
        self._cw = compile_watch.begin(
            "party.reshare", f"B{B}|q{q}|{self.key_type}|t{t_new}"
        )
        self._stage = 1
        if not self.is_old:
            return []
        mod, order = _curve(self.key_type)
        first = self.old_shares[0]
        old_xs = party_xs(first.participants)
        quorum_xs = [old_xs[p] for p in self.old_quorum]
        lam = hm.lagrange_coeff(quorum_xs, old_xs[self.self_id], order)
        w_ints = [lam * s.share % order for s in self.old_shares]
        coeffs_np = _rand_scalars((self.tp1, self.B), order, self.rng)
        coeffs_np[0] = bn.batch_to_limbs(w_ints, P256)
        self._coeffs = jnp.asarray(coeffs_np)
        self._blind = jnp.asarray(
            np.frombuffer(
                self.rng.token_bytes(self.B * 32), dtype=np.uint8
            ).reshape(self.B, 32)
        )
        # counter-phase dealing, same transcript discipline as the DKG
        # party: secrets drawn full-batch above, cohorts only slice
        bind = self._bind_row(self.self_id)

        def make_job(ci: int, sl: slice):
            def job():
                pts, block, commit = _blk_deal_commit(
                    self._coeffs[:, sl], self._blind[sl], bind[sl],
                    self.key_type,
                )
                commit_host = yield (
                    "commit_egress",
                    lambda: np.asarray(commit),  # mpcflow: host-ok — commitment block leaves device for wire serialization
                )
                return pts, block, commit_host

            return job

        outs = pl.run_counter_phase(
            [make_job(ci, sl) for ci, sl in enumerate(self._plan.slices())]
        )
        self._pts = [
            _concat_pts([o[0][k] for o in outs]) for k in range(self.tp1)
        ]
        self._block = (
            outs[0][1]
            if self._plan.serial
            else jnp.concatenate([o[1] for o in outs], axis=0)
        )
        commit_host = np.concatenate([o[2] for o in outs], axis=0)
        commit_hex = commit_host.tobytes().hex()  # mpcflow: declassified — hash commitment, protocol-public
        return [
            self.broadcast(RS_R1, {"commit": commit_hex})
        ]

    def receive(self, msg: RoundMsg) -> List[RoundMsg]:
        if self.done:
            return []
        self._store(msg)
        out: List[RoundMsg] = []
        old_others = [p for p in self.old_quorum if p != self.self_id]
        new_others = [p for p in self.new_committee if p != self.self_id]
        if (
            self._stage == 1
            and self.is_old
            and self._round_full(RS_R1, old_others)
        ):
            payload = {
                "points": np.asarray(self._block).tobytes().hex(),
                "blind": np.asarray(self._blind).tobytes().hex(),
            }
            out.append(self.broadcast(RS_R2B, payload))
            new_xs = party_xs(self.new_committee)
            xs_tuple = tuple(new_xs[p] for p in self.new_committee)
            subs = _subshare_phase(
                self._coeffs[None], self.key_type, xs_tuple
            )[0]
            for i, pid in enumerate(self.new_committee):
                if pid == self.self_id:
                    self._own_sub = subs[i]
                else:
                    out.append(
                        self.unicast(
                            pid, RS_R2S,
                            {"share": self._ser_scalars(subs[i])},
                        )
                    )
            self._stage = 2
        deal_from = [p for p in self.old_quorum if p != self.self_id]
        if (
            self.is_new
            and not self._confirm_sent
            and self._round_full(RS_R1, deal_from)
            and self._round_full(RS_R2B, deal_from)
            and self._round_full(RS_R2S, deal_from)
            and (not self.is_old or self._stage >= 2)
        ):
            self._aggregate_new()
            self._confirm_sent = True
            payload: Dict = {"ok": True}
            if self.key_type == "secp256k1":
                pre = self.pre
                pq = (pre.P - 1) // 2 * ((pre.Q - 1) // 2)
                bind = self._proof_bind(self.self_id)
                payload.update(
                    {
                        "paillier_n": str(pre.paillier.N),
                        "ntilde": str(pre.NTilde),
                        "h1": str(pre.h1),
                        "h2": str(pre.h2),
                        "dln1": DLNProof.prove(
                            pre.h1, pre.h2, pre.alpha, pq, pre.NTilde,
                            self.rng, bind=bind,
                        ).to_json(),
                        "dln2": DLNProof.prove(
                            pre.h2, pre.h1, pre.beta, pq, pre.NTilde,
                            self.rng, bind=bind,
                        ).to_json(),
                        "paillier_proof": PaillierProof.prove(
                            pre.paillier, bind=bind
                        ).to_json(),
                    }
                )
            out.append(self.broadcast(RS_R3, payload))
        if not self.done and self._round_full(RS_R3, new_others) and (
            self._confirm_sent or not self.is_new
        ):
            if self.is_old and not self.is_new and self._stage < 2:
                return out  # haven't dealt yet — wait
            self._finalize()
        return out

    def _aggregate_new(self) -> None:
        mod, order = _curve(self.key_type)
        ring = mod.scalar_ring()
        r1 = self._round_payloads(RS_R1)
        r2b = self._round_payloads(RS_R2B)
        r2s = self._round_payloads(RS_R2S)
        new_xs = party_xs(self.new_committee)
        self_x_new = new_xs[self.self_id]

        agg_share = None
        agg_pts = None
        for pid in self.old_quorum:
            if pid == self.self_id:
                sub = self._own_sub
                pts = self._pts
            else:
                sub = self._parse_scalars(r2s[pid]["share"], order, pid)
                pts = self._verify_dealer(
                    pid, r1[pid]["commit"], r2b[pid], sub, self_x_new
                )
            if agg_share is None:
                agg_share = sub
                agg_pts = list(pts)
            else:
                agg_share = ring.addmod(agg_share, sub)
                for k in range(self.tp1):
                    agg_pts[k] = mod.add(agg_pts[k], pts[k])
        # binding: Σ_i C_i0 must equal the old public keys (batch)
        pub_comp = np.asarray(mod.compress(agg_pts[0]))  # mpcflow: host-ok — public-key binding check against host-held old pubs
        for w in range(self.B):
            if bytes(pub_comp[w].tobytes()) != self.old_pubs[w]:
                raise ProtocolError(
                    f"resharing changed the public key for wallet {w}"
                )
        self._agg_share = agg_share
        # mpcflow: host-ok — public VSS commitments, egress into the share objects
        self._agg_comp = [np.asarray(mod.compress(pt)) for pt in agg_pts]

    def _finalize(self) -> None:
        if not self.is_new:
            self.result = None
            self.done = True
            compile_watch.finish(self._cw)
            return
        r3 = self._round_payloads(RS_R3)
        aux: Dict = {"is_reshared": True}
        if self.key_type == "secp256k1":
            peer_pk: Dict[str, str] = {}
            peer_rp: Dict[str, Dict[str, str]] = {}
            for pid in self.new_committee:
                if pid == self.self_id:
                    continue
                p = r3[pid]
                N = int(p["paillier_n"])
                ntilde, h1, h2 = int(p["ntilde"]), int(p["h1"]), int(p["h2"])
                if N.bit_length() < self.min_paillier_bits:
                    raise ProtocolError("Paillier modulus too small", pid)
                if ntilde.bit_length() < self.min_paillier_bits:
                    raise ProtocolError("NTilde too small", pid)
                if h1 in (0, 1) or h2 in (0, 1) or h1 == h2:
                    raise ProtocolError("degenerate ring-Pedersen bases", pid)
                bind = self._proof_bind(pid)
                if not DLNProof.from_json(p["dln1"]).verify(
                    h1, h2, ntilde, bind=bind
                ):
                    raise ProtocolError("DLN proof failed", pid)
                if not DLNProof.from_json(p["dln2"]).verify(
                    h2, h1, ntilde, bind=bind
                ):
                    raise ProtocolError("DLN proof failed", pid)
                proof = PaillierProof.from_json(p["paillier_proof"])
                if N.bit_length() >= 2046:
                    if not proof.verify(PaillierPublicKey(N), bind=bind):
                        raise ProtocolError("Paillier validity proof failed", pid)
                elif not proof.ys:
                    raise ProtocolError("missing Paillier proof", pid)
                peer_pk[pid] = str(N)
                peer_rp[pid] = {
                    "ntilde": str(ntilde), "h1": str(h1), "h2": str(h2)
                }
            pre = self.pre
            aux.update(
                {
                    "paillier_sk": pre.paillier.to_json(),
                    "preparams": {
                        "ntilde": str(pre.NTilde),
                        "h1": str(pre.h1),
                        "h2": str(pre.h2),
                    },
                    "peer_paillier": peer_pk,
                    "peer_ring_pedersen": peer_rp,
                }
            )
        new_xs = party_xs(self.new_committee)
        share_ints = bn.batch_from_limbs(np.asarray(self._agg_share), P256)
        shares: List[KeygenShare] = []
        for w in range(self.B):
            shares.append(
                KeygenShare(
                    key_type=self.key_type,
                    share=share_ints[w],
                    self_x=new_xs[self.self_id],
                    public_key=self.old_pubs[w],
                    vss_commitments=[
                        bytes(self._agg_comp[k][w].tobytes())
                        for k in range(self.tp1)
                    ],
                    participants=list(self.new_committee),
                    threshold=self.t_new,
                    epoch=self.new_epoch,
                    aux=aux,
                )
            )
        self.result = shares
        self.done = True
        compile_watch.finish(self._cw)
