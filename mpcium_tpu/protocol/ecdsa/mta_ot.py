"""OT-based MtA: Gilboa multiplication over the secp256k1 scalar ring.

The GG18 cost center is the Paillier MtA — encryptions, range proofs and
CRT decryptions at 2048/4096-bit are ~100% of the audited mulmod budget
(PERFORMANCE.md). This module replaces the two MtA legs with
oblivious-transfer multiplication (Gilboa 1999, the approach of
Doerner–Kondi–Lee–shelat threshold ECDSA): Alice holds ``a``, Bob holds
``b``, and they derive additive shares of ``a·b mod q`` from 256
1-of-2 OTs per product — all symmetric crypto (PRG expansion, bit-matrix
transpose, bulk hashing) plus 256-bit scalar sums, with NO big-modulus
exponentiation anywhere.

Construction:

* **Base OTs** (once per ordered quorum pair): Chou–Orlandi simplest OT
  on secp256k1. Bob — the MtA *sender* — is the base-OT *receiver* with
  choice bits Δ (the IKNP role reversal).
* **Extension** (per signing batch): IKNP. Alice's choice bits are the
  bits of her multiplicands; matrices expand from the base seeds with a
  per-(leg, invocation) counter, so one base-OT setup serves every batch
  (stateful IKNP: each extension consumes a disjoint PRF range).
* **Payloads**: for OT index (s, i) — signature lane s, bit i — Bob
  offers ``z_{s,i}`` and ``z_{s,i} + 2^i·b_s mod q``; Alice picks by bit
  i of ``a_s``. Alice's share is ``Σ_i received``, Bob's is ``-Σ_i z``;
  they sum to ``a_s·b_s mod q``. The mod-q sums and the ``2^i·b``
  doubling ladder run batched on device (existing scalar-ring kernels);
  masking/hashing runs through the native batched SHA-256.
* **Pipelining** (the 45%-host-wall fix — PERFORMANCE.md): ``run_multi``
  splits the batch into MPCIUM_OT_CHUNKS sub-batches and double-buffers
  them — all device payload math is dispatched asynchronously up front
  and a background worker runs each chunk's host extension work (PRG
  expansion, packed transpose, pad hashing — natively threaded, knob
  MPCIUM_NATIVE_THREADS) while the main thread drains the previous
  chunk's device arrays. Chunk boundaries align with the 32-byte PRG
  blocks and the global OT index, so chunking/threading change
  SCHEDULING ONLY — transcripts and shares are bit-identical to the
  serial three-round composition (tests/test_mta_ot_pipeline.py).

SECURITY (be explicit — this is why the flag defaults off): as
implemented this provides passive (semi-honest) security. The IKNP
extension lacks the KOS15 consistency check and the Gilboa payloads lack
the DKLs18/19 encoding-and-check layer, so an ACTIVELY deviating party
can cause incorrect outputs; incorrectness is caught by the engine's
in-protocol ECDSA verification (no bad signature is ever released), but
REPEATED induced aborts can leak bits of the honest party's nonce share
(selective-failure), which the default Paillier+range-proof path
prevents. See SECURITY.md "OT-MtA (experimental)". Enable with
MPCIUM_MTA=ot.

Reference correspondence: replaces the tss-lib MtA
(SURVEY.md §2.3; reference pkg/mpc/ecdsa_signing_session.go drives
Paillier MtA per session) with the OT-based alternative the DKLs line of
work uses; the leading axis is the concurrent-session batch.
"""
from __future__ import annotations

import hashlib
import os
import secrets as _secrets
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...core import bignum as bn
from ...core import hostmath as hm
from ...core import secp256k1_jax as sp
from ...core.bignum import P256
from ...ops import hash_suite as hs
from ...utils import tracing

KAPPA = 128  # IKNP width / computational security parameter
NBITS = 256  # multiplicand bits (secp256k1 scalars)
Q = hm.SECP_N

# Wire/domain version of the extension layer. v2: the pad hash domain
# carries the per-payload-set suffix (`…|s0`, `…|s1` — the run_multi
# amortization) AND the version byte itself rides every PRF/pad tag, so
# mixed-version parties derive unrelated pads instead of silently
# unmasking garbage; the explicit `v` field in the round messages turns
# that into a LOUD contract failure (see bob_round2_multi /
# alice_round3_multi). SECURITY.md "OT-MtA" documents the break.
OT_WIRE_VERSION = 2

# One background worker is the whole double-buffer: run_multi enqueues
# every chunk's host-side extension work (PRG expansion, bit-matrix
# transpose, pad hashing) on it IN ORDER, then the main thread drains
# chunks — while it blocks on chunk i's device arrays, the worker is
# already expanding chunk i+1. The native kernels release the GIL (and
# thread internally per MPCIUM_NATIVE_THREADS), so worker and main
# thread genuinely overlap.
_HOST_POOL: Optional[ThreadPoolExecutor] = None
_HOST_POOL_LOCK = threading.Lock()


def _host_pool() -> ThreadPoolExecutor:
    global _HOST_POOL
    with _HOST_POOL_LOCK:
        if _HOST_POOL is None:
            _HOST_POOL = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ot-host"
            )
        return _HOST_POOL


def resolve_chunks(B: int, chunks: Optional[int] = None) -> int:
    """Pipeline chunk count: explicit argument wins, then
    MPCIUM_OT_CHUNKS, then auto from the batch (enough chunks to hide
    host extension work behind device compute without shrinking device
    dispatches below ~256 lanes). Clamped to the largest divisor of B
    so every chunk keeps the same static shape (one XLA executable)."""
    if chunks is None or chunks <= 0:
        chunks = int(os.environ.get("MPCIUM_OT_CHUNKS", "0") or 0)
    if chunks <= 0:
        chunks = max(1, min(8, B // 256))
    chunks = max(1, min(chunks, B))
    while B % chunks:
        chunks -= 1
    return chunks


def device_path_enabled() -> bool:
    """MPCIUM_OT_DEVICE gates ``run_multi``'s fused on-device extension
    (default ON): PRG expansion, bit-matrix transpose, pad hashing and
    payload masking all run as one jitted dispatch per chunk
    (ops.hash_suite), and the host touches nothing but wire bytes. The
    host/native path remains the wire-round implementation
    (alice_round1 / bob_round2_multi / alice_round3_multi) and the
    transcript oracle; set MPCIUM_OT_DEVICE=0 to force it in-process."""
    return os.environ.get("MPCIUM_OT_DEVICE", "1") != "0"


def _hash_rows(prefix: bytes, rows: np.ndarray) -> np.ndarray:
    """sha256(prefix || row) per row → (N, 32); native batched C++ when
    built, hashlib otherwise (tests / cold environments)."""
    from ... import native

    if native.available():
        return native.batch_sha256(prefix, np.ascontiguousarray(rows))
    out = np.empty((rows.shape[0], 32), np.uint8)
    for i, r in enumerate(rows):
        out[i] = np.frombuffer(
            hashlib.sha256(prefix + r.tobytes()).digest(), np.uint8
        )
    return out


def _prg(
    seeds: np.ndarray, n_bytes: int, tag: bytes, blk_off: int = 0
) -> np.ndarray:
    """Expand each 32-byte seed row to ``n_bytes`` pseudorandom bytes:
    sha256(tag || seed || j || blk) blocks. → (n_seeds, n_bytes).

    ``blk_off`` starts the per-seed block counter mid-stream, so a
    chunked caller expanding ``[blk_off, blk_off + n/32)`` gets exactly
    the matching slice of the full expansion (chunking never changes
    the transcript). Fused native path when built; the numpy fallback
    assembles the (n_seeds·nblk, 38) message matrix explicitly."""
    from ... import native

    n_seeds = seeds.shape[0]
    nblk = -(-n_bytes // 32)
    prefix = b"mpcium-ot-prg|" + tag
    out = native.prg_expand(prefix, seeds, nblk, blk_off)
    if out is not None:
        return out[:, :n_bytes] if nblk * 32 != n_bytes else out
    rows = np.empty((n_seeds * nblk, 32 + 2 + 4), np.uint8)
    rows[:, :32] = np.repeat(seeds, nblk, axis=0)
    j_ids = np.repeat(np.arange(n_seeds, dtype=np.uint16), nblk)
    rows[:, 32:34] = j_ids.view(np.uint8).reshape(-1, 2)
    blk = np.tile(
        np.arange(blk_off, blk_off + nblk, dtype=np.uint32), n_seeds
    )
    rows[:, 34:38] = blk.view(np.uint8).reshape(-1, 4)
    out = _hash_rows(prefix, rows)
    return out.reshape(n_seeds, nblk * 32)[:, :n_bytes]


# ---------------------------------------------------------------------------
# base OTs (Chou–Orlandi on secp256k1; host curve math, once per pair)
# ---------------------------------------------------------------------------


def _pt_hash(point) -> bytes:
    return hashlib.sha256(b"mpcium-ot-base|" + hm.secp_compress(point)).digest()


def _secp_neg(pt: "hm.SecpPoint") -> "hm.SecpPoint":
    if pt.is_infinity:
        return pt
    return hm.SecpPoint(pt.x, (-pt.y) % hm.SECP_P)


def _bcast_pt(pt_bytes: bytes, n: int):
    """Compressed point → device SecpPointJ broadcast to batch n."""
    p = sp.from_host([hm.secp_decompress(pt_bytes)])
    return type(p)(
        *(jnp.broadcast_to(c, (n,) + c.shape[1:]) for c in p)
    )


@jax.jit
def _k_base_receive(bits, delta, S_pt):
    """Receiver's batched curve work: (compress(R), compress(X·S)).
    Jitted once per process — the 256-step ladders would otherwise
    re-trace per call (~minutes per quorum pair on a 1-core host)."""
    XG = sp.base_mul(bits)
    XS = sp.scalar_mul(bits, S_pt)
    R = sp.select(delta, sp.add(XG, S_pt), XG)
    return sp.compress(R), sp.compress(XS)


@jax.jit
def _k_base_sender(y_bits, R_pt, yS_neg_pt):
    """Sender's batched curve work: (compress(y·R), compress(y·R−y·S))."""
    yR = sp.scalar_mul(y_bits, R_pt)
    return sp.compress(yR), sp.compress(sp.add(yR, yS_neg_pt))


def _pt_hash_rows(comp_rows: np.ndarray) -> np.ndarray:
    """(n, 33) compressed points → (n, 32) H(point) key rows (same
    domain tag as _pt_hash)."""
    return _hash_rows(b"mpcium-ot-base|", comp_rows)


def base_ot_sender_init(rng=_secrets) -> Tuple[int, bytes]:
    """Alice (MtA receiver = base-OT sender): y, S = y·G."""
    y = rng.randbelow(Q - 1) + 1
    return y, hm.secp_compress(hm.secp_mul(y, hm.SECP_G))


def base_ot_receive(
    S_bytes: bytes, rng=_secrets
) -> Tuple[np.ndarray, np.ndarray, List[bytes]]:
    """Bob: picks Δ ∈ {0,1}^κ; per base OT j sends R_j = x_j·G + Δ_j·S
    and keeps k^{Δ_j}_j = H(x_j·S). Returns (delta_bits, keys, R_msgs).
    All κ curve ops ride ONE batched device dispatch each (host
    double-and-add at ~70 ms/mul would cost ~30 s per quorum pair)."""
    delta = np.frombuffer(rng.token_bytes(KAPPA), np.uint8) & 1
    xs = [rng.randbelow(Q - 1) + 1 for _ in range(KAPPA)]
    bits = jnp.asarray(sp.scalars_to_bits(xs))
    R_comp, XS_comp = _k_base_receive(
        bits, jnp.asarray(delta), _bcast_pt(S_bytes, KAPPA)
    )
    msgs = [bytes(r) for r in np.asarray(R_comp)]  # mpcflow: host-ok — base-OT wire messages (κ=128 rows, once per pair)
    keys = _pt_hash_rows(np.asarray(XS_comp))  # mpcflow: host-ok — ROT key derivation hashes on host (κ=128 rows, once per pair)
    return delta, keys, msgs


def base_ot_sender_keys(
    y: int, R_msgs: List[bytes]
) -> Tuple[np.ndarray, np.ndarray]:
    """Alice: k0_j = H(y·R_j), k1_j = H(y·(R_j − S)) — batched device
    scalar-mults (y broadcast across the κ rows)."""
    S = hm.secp_mul(y, hm.SECP_G)
    # y·(R − S) = y·R − y·S — subtract the SCALED point, not S itself
    yS_neg = _secp_neg(hm.secp_mul(y, S))
    R = sp.from_host([hm.secp_decompress(rb) for rb in R_msgs])
    y_bits = jnp.broadcast_to(
        jnp.asarray(sp.scalars_to_bits([y])), (KAPPA, 256)
    )
    yR_comp, yRmS_comp = _k_base_sender(
        y_bits, R, _bcast_pt(hm.secp_compress(yS_neg), KAPPA)
    )
    k0 = _pt_hash_rows(np.asarray(yR_comp))  # mpcflow: host-ok — ROT key derivation hashes on host (κ=128 rows, once per pair)
    k1 = _pt_hash_rows(np.asarray(yRmS_comp))  # mpcflow: host-ok — ROT key derivation hashes on host (κ=128 rows, once per pair)
    return k0, k1


# ---------------------------------------------------------------------------
# device helpers (batched mod-q arithmetic on the scalar-ring kernels)
# ---------------------------------------------------------------------------


@jax.jit
def _pow2_ladder(b: jnp.ndarray) -> jnp.ndarray:
    """(B, n) scalars mod q → (NBITS, B, n) with ladder[i] = 2^i·b."""
    ring = sp.scalar_ring()

    def step(c, _):
        return ring.addmod(c, c), c

    _, ys = lax.scan(step, b, None, length=NBITS)
    return ys


@jax.jit
def _m1_payloads(z_red: jnp.ndarray, pow2b: jnp.ndarray) -> jnp.ndarray:
    """(B, NBITS, n) reduced z + (NBITS, B, n) ladder → m1 bytes
    (B, NBITS, 32)."""
    ring = sp.scalar_ring()
    m1 = ring.addmod(z_red, jnp.moveaxis(pow2b, 0, 1))
    return bn.limbs_to_bytes_le(m1, P256, 32)


@jax.jit
def _reduce_bytes(raw: jnp.ndarray) -> jnp.ndarray:
    """(..., 32) LE bytes → reduced (..., n) scalars mod q."""
    ring = sp.scalar_ring()
    return ring.reduce(bn.bytes_to_limbs_le(raw, P256, 22))


@jax.jit
def _sum_mod_q(vals: jnp.ndarray) -> jnp.ndarray:
    """(B, NBITS, n) reduced scalars → (B, n) sum mod q. Limb sums stay
    < NBITS·2^12 < 2^21 (int32-safe redundancy), normalized by carry
    before the Barrett reduce."""
    ring = sp.scalar_ring()
    s = jnp.sum(vals, axis=-2)
    return ring.reduce(bn.carry(s, P256))


@jax.jit
def _neg_sum_mod_q(vals: jnp.ndarray) -> jnp.ndarray:
    ring = sp.scalar_ring()
    return ring.negmod(_sum_mod_q(vals))


@jax.jit
def _bits_256(a: jnp.ndarray) -> jnp.ndarray:
    """(B, n) scalars → (B, NBITS) int32 bits LSB-first."""
    return bn.limbs_to_bits(a, P256, NBITS)


@jax.jit
def _ot_chunk_device(
    k0, k1, kD, delta_mask, delta_packed, prg_prefix, pad_prefixes,
    r_bits_c, r_packed_c, m0s, m1s, blk_off, m_off,
):
    """One pipeline chunk of the extension, fused on device: PRG-expand
    all three seed matrices, assemble U and Q, transpose both packed
    matrices, derive every payload set's pads, mask the payloads and
    recover Alice's selections — byte-for-byte the host three-round
    composition, with only wire bytes ever leaving the device.

    Shapes (Bc lanes per chunk, Mc = Bc·NBITS OTs, S payload sets):
    seeds (κ, 32); delta_mask (κ, 1) uint8 0x00/0xFF; delta_packed
    (κ/8,); prg_prefix / pad_prefixes traced uint8 ((P,), (S, P2) — the
    tags embed the extension counter, so static args would recompile
    every invocation); r_bits_c (Mc,); r_packed_c (Mc/8,); m0s/m1s
    (S, Bc, NBITS, 32); blk_off/m_off traced uint32 (the chunk's PRG
    block / global OT index origin). → (alphas (S, Bc, n), U (κ, Bc·32),
    y0s, y1s (S, Mc, 32))."""
    Bc = r_packed_c.shape[0] // 32
    Mc = r_bits_c.shape[0]
    t0 = hs.prg_expand_core(k0, prg_prefix, Bc, blk_off)
    t1 = hs.prg_expand_core(k1, prg_prefix, Bc, blk_off)
    tD = hs.prg_expand_core(kD, prg_prefix, Bc, blk_off)
    U = t0 ^ t1 ^ r_packed_c[None, :]
    Q = tD ^ (U & delta_mask)  # fold U into the Δ=1 rows only
    rows_a = hs.ot_transpose_core(t0)  # (Mc, κ/8)
    rows_b = hs.ot_transpose_core(Q)
    idx_le = hs.le32_bytes(
        jnp.asarray(m_off, jnp.uint32) + jnp.arange(Mc, dtype=jnp.uint32)
    )
    sel_bits = r_bits_c.astype(bool)[:, None]
    alphas, y0s, y1s = [], [], []
    for s in range(pad_prefixes.shape[0]):
        pref = pad_prefixes[s]
        pad_a = hs.pad_hash_core(pref, rows_a, idx_le)
        pad0 = hs.pad_hash_core(pref, rows_b, idx_le)
        pad1 = hs.pad_hash_core(pref, rows_b ^ delta_packed[None, :], idx_le)
        y0 = pad0 ^ m0s[s].reshape(Mc, 32)
        y1 = pad1 ^ m1s[s].reshape(Mc, 32)
        sel = jnp.where(sel_bits, y1, y0) ^ pad_a
        alphas.append(
            _sum_mod_q(_reduce_bytes(sel.reshape(Bc, NBITS, 32)))
        )
        y0s.append(y0)
        y1s.append(y1)
    return jnp.stack(alphas), U, jnp.stack(y0s), jnp.stack(y1s)


# ---------------------------------------------------------------------------
# the per-ordered-pair MtA instance
# ---------------------------------------------------------------------------


def _pack(bits: np.ndarray) -> np.ndarray:
    """(..., n) 0/1 → packed little-endian-bit bytes (..., n/8)."""
    return np.packbits(bits.astype(np.uint8), axis=-1, bitorder="little")


def _unpack(b: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(b, axis=-1, count=n, bitorder="little")


def _derive_pads_multi(
    prefixes, packed: np.ndarray, M: int, delta=None, m_off: int = 0
):
    """Per-OT hash pads from the packed (κ, M/8) extension matrix, for
    SEVERAL payload-set hash domains at once:
    pad_s[j] = H(prefix_s ‖ column j re-packed ‖ le32(j)), plus the
    delta-offset variant per set when ``delta`` (packed κ/8) is given.
    The transpose depends only on ``packed``, so it runs ONCE however
    many sets are derived — natively (batch_hash.cpp walks the packed
    matrix directly) when available; the numpy fallback materializes
    the unpacked bit matrix and a strided transpose copy (~130 MB per
    leg at M = 2^20), also once. ``m_off`` offsets the le32 OT index
    for a chunked caller (columns [m_off, m_off+M) of the full
    matrix), so per-chunk pads equal the matching slice of the
    full-width derivation. Returns [pad0_s] or [(pad0_s, pad1_s)] in
    prefix order."""
    from ... import native

    rows = native.ot_transpose(packed) if native.available() else None
    if rows is None:
        rows = _pack(_unpack(packed, M).T)  # (M, κ/8)
    idx = (
        np.arange(m_off, m_off + M, dtype=np.uint32)
        .view(np.uint8).reshape(M, 4)
    )
    buf = np.concatenate([rows, idx], axis=1)
    bufd = (
        None if delta is None
        else np.concatenate([rows ^ delta[None, :], idx], axis=1)
    )
    out = []
    for prefix in prefixes:
        if delta is None:
            out.append(_hash_rows(prefix, buf))
        else:
            out.append((_hash_rows(prefix, buf), _hash_rows(prefix, bufd)))
    return out


class OTMtALeg:
    """One ordered quorum pair (Alice = receiver with ``a``; Bob = sender
    with ``b``). In-process engine form: both roles live on this object,
    but every inter-party value flows through explicit ``*_msg`` returns
    so the distributed wiring is mechanical. One instance serves every
    batch invocation (extension counter in all PRF/hash domains)."""

    def __init__(self, tag: str, rng=_secrets):
        self.tag = tag.encode()
        self.rng = rng
        self.ctr = 0
        y, S = base_ot_sender_init(rng)
        self.delta, self.keysD, R_msgs = base_ot_receive(S, rng)
        self.k0, self.k1 = base_ot_sender_keys(y, R_msgs)
        self.delta_packed = _pack(self.delta)  # (16,)
        self._delta_rows = np.nonzero(self.delta)[0]

    def _ext_tag(self, ctr: int) -> bytes:
        """Per-invocation PRF/pad domain tag, version-stamped (see
        OT_WIRE_VERSION)."""
        return self.tag + b"|v%d|%d" % (OT_WIRE_VERSION, ctr)

    @staticmethod
    def _pad_prefixes(tag: bytes, n_sets: int) -> List[bytes]:
        return [
            b"mpcium-ot-pad|" + tag + b"|s%d" % s for s in range(n_sets)
        ]

    def _device_state(self) -> Dict[str, jnp.ndarray]:
        """Base-OT key material as device arrays, uploaded once per leg
        and reused by every device-path extension."""
        st = getattr(self, "_dev_state", None)
        if st is None:
            st = {
                "k0": jnp.asarray(self.k0),
                "k1": jnp.asarray(self.k1),
                "kD": jnp.asarray(self.keysD),
                "delta_mask": jnp.asarray(
                    (self.delta.astype(np.uint8) * np.uint8(0xFF))[:, None]
                ),
                "delta_packed": jnp.asarray(self.delta_packed),
            }
            self._dev_state = st
        return st

    # -- chunk-granular extension stages (host side) -------------------------
    #
    # Each stage covers lanes [blk_off, blk_off + Bc) of the batch — a
    # contiguous 32-byte-block range of every PRG stream and a
    # contiguous column range of the extension matrix — so running them
    # chunk-by-chunk produces byte-identical transcripts to the
    # full-width call: chunking (and the threading underneath) changes
    # scheduling only, never values.

    def _ext_alice_chunk(self, tag: bytes, r_packed_c, blk_off: int, Bc: int):
        """PRG-expand the Alice half for one chunk → (t0_c, U_c), each
        (κ, Bc·32). U is assembled in place in the t1 buffer (native
        threaded xor when built) — no fresh temporaries."""
        from ... import native

        t0 = _prg(self.k0, Bc * 32, tag, blk_off)
        t1 = _prg(self.k1, Bc * 32, tag, blk_off)
        native.xor_rows(t1, t0)          # t1 ← t0 ^ t1
        native.xor_rows(t1, r_packed_c)  # ... ^ r (row broadcast)
        return t0, t1

    def _ext_bob_chunk(self, tag: bytes, U_c, blk_off: int, Bc: int):
        """PRG-expand Bob's half for one chunk and fold in Alice's U on
        the Δ=1 rows → Q_c (κ, Bc·32), built in place in the tD
        buffer (the old path materialized a full (κ, M/8) mask and two
        temporaries)."""
        tD = _prg(self.keysD, Bc * 32, tag, blk_off)
        for r in self._delta_rows:
            tD[r] ^= U_c[r]  # in-place row view, no temp
        return tD

    def _pads_chunk(self, tag, n_sets, t0_c, Qm_c, m_off, m_count):
        """Transpose + pad hashing for one chunk, both roles, every
        payload set. → (padsA: [pad_s], padsB: [(pad0_s, pad1_s)])."""
        prefixes = self._pad_prefixes(tag, n_sets)
        padsA = _derive_pads_multi(prefixes, t0_c, m_count, m_off=m_off)
        padsB = _derive_pads_multi(
            prefixes, Qm_c, m_count, delta=self.delta_packed, m_off=m_off
        )
        return padsA, padsB

    # -- Alice ---------------------------------------------------------------

    def alice_round1(self, a: jnp.ndarray, ctr: int) -> Dict:
        """``a``: (B, n) scalars mod q. → {"U": (κ, M/8), "v"} to Bob;
        local state kept for round 3."""
        B = a.shape[0]
        M = B * NBITS
        r_bits = np.asarray(_bits_256(a)).astype(np.uint8).reshape(M)  # mpcflow: host-ok — choice bits feed the host-side OT extension (ROADMAP: IKNP on device)
        tag = self._ext_tag(ctr)
        t0, U = self._ext_alice_chunk(tag, _pack(r_bits), 0, B)
        self._alice_state = (t0, r_bits, B, tag)
        return {"U": U, "v": OT_WIRE_VERSION}

    def alice_round3(self, bob_msg: Dict) -> jnp.ndarray:
        """Recover the selected payloads → Alice's additive share
        (B, n) mod q."""
        return self.alice_round3_multi((bob_msg,))[0]

    def alice_round3_multi(self, bob_msgs) -> List[jnp.ndarray]:
        """One extension, several payload sets (see bob_round2_multi):
        per-set pads come from the SAME transposed rows under
        set-separated hash domains, so each set's pads are independent
        random-oracle outputs."""
        from ... import native

        for i, m in enumerate(bob_msgs):
            if m.get("v") != OT_WIRE_VERSION:
                raise ValueError(
                    f"OT-MtA wire version mismatch in bob msg {i}: got "
                    f"{m.get('v')!r}, this party speaks v{OT_WIRE_VERSION}"
                )
        t0, r_bits, B, tag = self._alice_state
        M = B * NBITS
        pad_sets = _derive_pads_multi(
            self._pad_prefixes(tag, len(bob_msgs)), t0, M
        )
        alphas = []
        sel_bits = r_bits[:, None].astype(bool)
        for bob_msg, pads in zip(bob_msgs, pad_sets):
            sel = np.where(sel_bits, bob_msg["y1"], bob_msg["y0"])
            native.xor_rows(sel, pads)  # m_sel, in place
            alphas.append(
                _sum_mod_q(
                    _reduce_bytes(jnp.asarray(sel.reshape(B, NBITS, 32)))
                )
            )
        return alphas

    # -- Bob -----------------------------------------------------------------

    def bob_round2(
        self, b_scalars: jnp.ndarray, alice_msg: Dict, ctr: int
    ) -> Tuple[Dict, jnp.ndarray]:
        """``b_scalars``: (B, n) mod q. → ({"y0", "y1", "v"} to Alice,
        Bob's additive share (B, n) mod q)."""
        msgs, betas = self.bob_round2_multi((b_scalars,), alice_msg, ctr)
        return msgs[0], betas[0]

    def bob_round2_multi(
        self, b_list, alice_msg: Dict, ctr: int
    ) -> Tuple[List[Dict], List[jnp.ndarray]]:
        """Several payload sets against ONE extension. Alice's choice
        bits (bits of ``a``) are shared across sets by construction —
        GG18 multiplies the same k_a against both γ_b and w_b — so the
        expensive extension half (t/U PRG expansion, the Q matrix) runs
        once and only the per-set payload masking repeats, under
        set-separated pad domains (`…|s0`, `…|s1`: independent RO
        outputs from the same rows)."""
        from ... import native

        b_list = tuple(b_list)
        if any(b.shape != b_list[0].shape for b in b_list):
            raise ValueError(
                "bob_round2_multi: payload sets disagree on batch shape: "
                f"{[tuple(b.shape) for b in b_list]}"
            )
        if alice_msg.get("v") != OT_WIRE_VERSION:
            raise ValueError(
                f"OT-MtA wire version mismatch: alice msg carries "
                f"{alice_msg.get('v')!r}, this party speaks "
                f"v{OT_WIRE_VERSION} (mixed-version quorum?)"
            )
        B = b_list[0].shape[0]
        M = B * NBITS
        tag = self._ext_tag(ctr)
        Qm = self._ext_bob_chunk(tag, alice_msg["U"], 0, B)
        pad_sets = _derive_pads_multi(
            self._pad_prefixes(tag, len(b_list)), Qm, M,
            delta=self.delta_packed,
        )
        msgs, betas = [], []
        for (b_scalars, (pad0, pad1)) in zip(b_list, pad_sets):
            # payloads: z and z + 2^i·b (mod q), z freshly random per OT
            z_raw = np.frombuffer(
                self.rng.token_bytes(M * 32), np.uint8
            ).reshape(B, NBITS, 32)
            z_red = _reduce_bytes(jnp.asarray(z_raw))  # (B, NBITS, n)
            m1 = np.asarray(_m1_payloads(z_red, _pow2_ladder(b_scalars)))  # mpcflow: host-ok — OT payloads are pad-masked on host before the wire (ROADMAP: IKNP on device)
            m0 = np.asarray(bn.limbs_to_bytes_le(z_red, P256, 32))  # mpcflow: host-ok — OT payloads are pad-masked on host before the wire (ROADMAP: IKNP on device)
            # mask INTO the pad buffers (ours, writable, dead after)
            y0 = native.xor_rows(pad0, m0.reshape(M, 32))
            y1 = native.xor_rows(pad1, m1.reshape(M, 32))
            msgs.append({"y0": y0, "y1": y1, "v": OT_WIRE_VERSION})
            betas.append(_neg_sum_mod_q(z_red))
        return msgs, betas

    # -- in-process convenience (the engine path) ----------------------------

    def run(
        self, a: jnp.ndarray, b: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Both roles locally: → (alice_share, bob_share), (B, n) each,
        with alice_share + bob_share ≡ a·b (mod q) per lane."""
        (pair,) = self.run_multi(a, (b,))
        return pair

    def run_multi(
        self,
        a: jnp.ndarray,
        b_list,
        chunks: Optional[int] = None,
        timings: Optional[Dict[str, float]] = None,
        transcript: Optional[list] = None,
    ):
        """Both roles locally, several Bob scalars against one ``a``
        (ONE extension): → [(alpha_s, beta_s)] with
        alpha_s + beta_s ≡ a·b_s (mod q) per lane.

        Two implementations, bit-identical transcripts (the z draw
        order, PRG block schedule and pad domains are shared, so the
        wire bytes cannot differ — tests/test_mta_ot_device.py):

        * **Device** (default; ``device_path_enabled``): the whole
          extension — PRG, transpose, pads, masking, selection — fuses
          into one jitted dispatch per chunk (``_ot_chunk_device``).
          The host stage degenerates to wire-byte packing; nothing is
          pulled off device in the hot loop.
        * **Host/native** (MPCIUM_OT_DEVICE=0, or > 10 payload sets):
          pipelined double-buffer. The batch is split into ``chunks``
          sub-batches (resolve_chunks — MPCIUM_OT_CHUNKS / auto), all
          device-side payload math is dispatched asynchronously up
          front, and every chunk's host extension work (PRG expansion,
          transpose, pad hashing) is enqueued on the background worker
          BEFORE any device array is blocked on. Chunking changes
          scheduling only: results and transcripts are bit-identical
          to the serial three-round composition for every chunk count.

        ``timings`` (optional dict) accumulates host_s (worker busy
        time), device_wait_s / host_wait_s (main-thread blocking) and
        total_s — the bench's overlap instrumentation; the device path
        reports total_s only (there is no host stage to time).
        ``transcript`` (optional list; device path only) receives one
        {"U", "y0", "y1"} dict of host arrays per chunk — the wire
        bytes, for oracle comparison in tests."""
        from ... import native

        b_list = tuple(b_list)
        B = a.shape[0]
        if any(b.shape != b_list[0].shape for b in b_list):
            raise ValueError(
                "run_multi: payload sets disagree on batch shape: "
                f"{[tuple(b.shape) for b in b_list]}"
            )
        K = resolve_chunks(B, chunks)
        ctr = self.ctr
        self.ctr += 1
        tag = self._ext_tag(ctr)
        M = B * NBITS
        t_total0 = time.perf_counter()
        t_span0 = tracing.now_ns()

        # z randomness: one serial-order draw per payload set — the
        # exact stream positions of the unchunked path (bit-exactness
        # under a deterministic rng) and the only rng use, so neither
        # the worker thread nor the device path perturbs the stream.
        z_raw = [
            np.frombuffer(self.rng.token_bytes(M * 32), np.uint8)
            .reshape(B, NBITS, 32)
            for _ in b_list
        ]

        # > 10 sets would ragged-stack the pad prefixes (`|s10` is one
        # byte wider); no engine path comes close, but fall back loudly
        # rather than mis-shape.
        if device_path_enabled() and len(b_list) <= 10:
            return self._run_multi_device(
                a, b_list, K, tag, z_raw, timings, transcript,
                t_total0, t_span0,
            )

        r_bits = np.asarray(_bits_256(a)).astype(np.uint8).reshape(M)  # mpcflow: host-ok — host/native fallback path (MPCIUM_OT_DEVICE=0): choice bits drive the host IKNP stage; the default device path never pulls them
        r_packed = _pack(r_bits)

        Bc = B // K
        Mc = Bc * NBITS

        # device stage 1 (async dispatch; nothing is blocked on yet):
        # per (chunk, set) payload material + Bob's share
        dev = []
        for c in range(K):
            sl = slice(c * Bc, (c + 1) * Bc)
            per_set = []
            for s, b_s in enumerate(b_list):
                z_red = _reduce_bytes(jnp.asarray(z_raw[s][sl]))
                m1 = _m1_payloads(z_red, _pow2_ladder(b_s[sl]))
                m0 = bn.limbs_to_bytes_le(z_red, P256, 32)
                per_set.append((m0, m1, _neg_sum_mod_q(z_red)))
            dev.append(per_set)

        def host_stage(c: int):
            t0_ = time.perf_counter()
            blk_off = c * Bc
            r_pc = r_packed[blk_off * 32:(blk_off + Bc) * 32]
            t0_c, U_c = self._ext_alice_chunk(tag, r_pc, blk_off, Bc)
            Qm_c = self._ext_bob_chunk(tag, U_c, blk_off, Bc)
            pads = self._pads_chunk(
                tag, len(b_list), t0_c, Qm_c, c * Mc, Mc
            )
            if timings is not None:
                timings["host_s"] = (
                    timings.get("host_s", 0.0)
                    + time.perf_counter() - t0_
                )
            return pads

        # the double-buffer: EVERY chunk's host work is enqueued before
        # the first device array is blocked on
        futs = [_host_pool().submit(host_stage, c) for c in range(K)]

        host_wait = 0.0
        device_wait = 0.0
        alpha_pieces: List[List[jnp.ndarray]] = [[] for _ in b_list]
        beta_pieces: List[List[jnp.ndarray]] = [[] for _ in b_list]
        for c in range(K):
            t_w = time.perf_counter()
            padsA, padsB = futs[c].result()
            host_wait += time.perf_counter() - t_w
            sel_bits = r_bits[c * Mc:(c + 1) * Mc, None].astype(bool)
            for s in range(len(b_list)):
                m0_d, m1_d, beta_d = dev[c][s]
                t_w = time.perf_counter()
                m0 = np.asarray(m0_d).reshape(Mc, 32)  # mpcflow: host-ok — host/native fallback path (MPCIUM_OT_DEVICE=0): payloads meet the host-derived pads here; the default device path masks on device
                m1 = np.asarray(m1_d).reshape(Mc, 32)  # mpcflow: host-ok — host/native fallback path (MPCIUM_OT_DEVICE=0): payloads meet the host-derived pads here; the default device path masks on device
                device_wait += time.perf_counter() - t_w
                pad0, pad1 = padsB[s]
                y0 = native.xor_rows(pad0, m0)
                y1 = native.xor_rows(pad1, m1)
                sel = np.where(sel_bits, y1, y0)
                native.xor_rows(sel, padsA[s])
                alpha_pieces[s].append(
                    _sum_mod_q(
                        _reduce_bytes(
                            jnp.asarray(sel.reshape(Bc, NBITS, 32))
                        )
                    )
                )
                beta_pieces[s].append(beta_d)

        alphas = [
            p[0] if K == 1 else jnp.concatenate(p, axis=0)
            for p in alpha_pieces
        ]
        betas = [
            p[0] if K == 1 else jnp.concatenate(p, axis=0)
            for p in beta_pieces
        ]
        if timings is not None:
            timings["host_wait_s"] = (
                timings.get("host_wait_s", 0.0) + host_wait
            )
            timings["device_wait_s"] = (
                timings.get("device_wait_s", 0.0) + device_wait
            )
            timings["total_s"] = (
                timings.get("total_s", 0.0)
                + time.perf_counter() - t_total0
            )
        # mpctrace: one span per extension with the overlap split as
        # public attrs (no-op unless tracing is armed)
        tracing.emit(
            "phase:ot_extension", t_span0, tracing.now_ns(),
            node="engine", tid=f"ot:B{B}",
            host_wait_s=round(host_wait, 6),
            device_wait_s=round(device_wait, 6),
            chunks=K, sets=len(b_list),
        )
        return list(zip(alphas, betas))

    def _run_multi_device(
        self, a, b_list, K, tag, z_raw, timings, transcript,
        t_total0, t_span0,
    ):
        """Device extension driver (see run_multi): per chunk, dispatch
        the payload math then the fused `_ot_chunk_device` kernel. The
        host never sees the extension matrices, pads or choice bits —
        only the optional ``transcript`` capture (tests) and the final
        shares cross the wire boundary. Chunk boundaries are the same
        PRG-block / OT-index origins as the host path, so the K=1/2/4
        transcripts are all identical to the serial composition."""
        B = a.shape[0]
        M = B * NBITS
        Bc = B // K
        Mc = Bc * NBITS
        n_sets = len(b_list)
        dev = self._device_state()
        prg_prefix = jnp.asarray(
            np.frombuffer(b"mpcium-ot-prg|" + tag, np.uint8)
        )
        pad_prefixes = jnp.asarray(
            np.frombuffer(
                b"".join(self._pad_prefixes(tag, n_sets)), np.uint8
            ).reshape(n_sets, -1)
        )
        r_bits_d = _bits_256(a).astype(jnp.uint8).reshape(M)
        r_packed_d = hs.pack_bits_core(r_bits_d)

        alpha_pieces: List[List[jnp.ndarray]] = [[] for _ in b_list]
        beta_pieces: List[List[jnp.ndarray]] = [[] for _ in b_list]
        for c in range(K):
            sl = slice(c * Bc, (c + 1) * Bc)
            m0s, m1s = [], []
            for s, b_s in enumerate(b_list):
                z_red = _reduce_bytes(jnp.asarray(z_raw[s][sl]))
                m1s.append(_m1_payloads(z_red, _pow2_ladder(b_s[sl])))
                m0s.append(bn.limbs_to_bytes_le(z_red, P256, 32))
                beta_pieces[s].append(_neg_sum_mod_q(z_red))
            alphas_c, U_c, y0s_c, y1s_c = _ot_chunk_device(
                dev["k0"], dev["k1"], dev["kD"], dev["delta_mask"],
                dev["delta_packed"], prg_prefix, pad_prefixes,
                r_bits_d[c * Mc:(c + 1) * Mc],
                r_packed_d[c * Bc * 32:(c + 1) * Bc * 32],
                jnp.stack(m0s), jnp.stack(m1s),
                jnp.uint32(c * Bc), jnp.uint32(c * Mc),
            )
            for s in range(n_sets):
                alpha_pieces[s].append(alphas_c[s])
            if transcript is not None:
                transcript.append({
                    "U": np.asarray(U_c),  # mpcflow: host-ok — transcript-oracle capture (tests only; None in production)
                    "y0": [np.asarray(y0s_c[s]) for s in range(n_sets)],  # mpcflow: host-ok — transcript-oracle capture (tests only; None in production)
                    "y1": [np.asarray(y1s_c[s]) for s in range(n_sets)],  # mpcflow: host-ok — transcript-oracle capture (tests only; None in production)
                })

        alphas = [
            p[0] if K == 1 else jnp.concatenate(p, axis=0)
            for p in alpha_pieces
        ]
        betas = [
            p[0] if K == 1 else jnp.concatenate(p, axis=0)
            for p in beta_pieces
        ]
        if timings is not None:
            timings["total_s"] = (
                timings.get("total_s", 0.0)
                + time.perf_counter() - t_total0
            )
        tracing.emit(
            "phase:ot_extension", t_span0, tracing.now_ns(),
            node="engine", tid=f"ot:B{B}",
            host_wait_s=0.0, device_wait_s=0.0,
            chunks=K, sets=n_sets, device=True,
        )
        return list(zip(alphas, betas))
