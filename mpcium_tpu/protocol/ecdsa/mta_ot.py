"""OT-based MtA: Gilboa multiplication over the secp256k1 scalar ring.

The GG18 cost center is the Paillier MtA — encryptions, range proofs and
CRT decryptions at 2048/4096-bit are ~100% of the audited mulmod budget
(PERFORMANCE.md). This module replaces the two MtA legs with
oblivious-transfer multiplication (Gilboa 1999, the approach of
Doerner–Kondi–Lee–shelat threshold ECDSA): Alice holds ``a``, Bob holds
``b``, and they derive additive shares of ``a·b mod q`` from 256
1-of-2 OTs per product — all symmetric crypto (PRG expansion, bit-matrix
transpose, bulk hashing) plus 256-bit scalar sums, with NO big-modulus
exponentiation anywhere.

Construction:

* **Base OTs** (once per ordered quorum pair): Chou–Orlandi simplest OT
  on secp256k1. Bob — the MtA *sender* — is the base-OT *receiver* with
  choice bits Δ (the IKNP role reversal).
* **Extension** (per signing batch): IKNP. Alice's choice bits are the
  bits of her multiplicands; matrices expand from the base seeds with a
  per-(leg, invocation) counter, so one base-OT setup serves every batch
  (stateful IKNP: each extension consumes a disjoint PRF range).
* **Payloads**: for OT index (s, i) — signature lane s, bit i — Bob
  offers ``z_{s,i}`` and ``z_{s,i} + 2^i·b_s mod q``; Alice picks by bit
  i of ``a_s``. Alice's share is ``Σ_i received``, Bob's is ``-Σ_i z``;
  they sum to ``a_s·b_s mod q``. The mod-q sums and the ``2^i·b``
  doubling ladder run batched on device (existing scalar-ring kernels);
  masking/hashing runs through the native batched SHA-256.

SECURITY (be explicit — this is why the flag defaults off): as
implemented this provides passive (semi-honest) security. The IKNP
extension lacks the KOS15 consistency check and the Gilboa payloads lack
the DKLs18/19 encoding-and-check layer, so an ACTIVELY deviating party
can cause incorrect outputs; incorrectness is caught by the engine's
in-protocol ECDSA verification (no bad signature is ever released), but
REPEATED induced aborts can leak bits of the honest party's nonce share
(selective-failure), which the default Paillier+range-proof path
prevents. See SECURITY.md "OT-MtA (experimental)". Enable with
MPCIUM_MTA=ot.

Reference correspondence: replaces the tss-lib MtA
(SURVEY.md §2.3; reference pkg/mpc/ecdsa_signing_session.go drives
Paillier MtA per session) with the OT-based alternative the DKLs line of
work uses; the leading axis is the concurrent-session batch.
"""
from __future__ import annotations

import functools
import hashlib
import secrets as _secrets
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...core import bignum as bn
from ...core import hostmath as hm
from ...core import secp256k1_jax as sp
from ...core.bignum import P256

KAPPA = 128  # IKNP width / computational security parameter
NBITS = 256  # multiplicand bits (secp256k1 scalars)
Q = hm.SECP_N


def _hash_rows(prefix: bytes, rows: np.ndarray) -> np.ndarray:
    """sha256(prefix || row) per row → (N, 32); native batched C++ when
    built, hashlib otherwise (tests / cold environments)."""
    from ... import native

    if native.available():
        return native.batch_sha256(prefix, np.ascontiguousarray(rows))
    out = np.empty((rows.shape[0], 32), np.uint8)
    for i, r in enumerate(rows):
        out[i] = np.frombuffer(
            hashlib.sha256(prefix + r.tobytes()).digest(), np.uint8
        )
    return out


def _prg(seeds: np.ndarray, n_bytes: int, tag: bytes) -> np.ndarray:
    """Expand each 32-byte seed row to ``n_bytes`` pseudorandom bytes:
    sha256(tag || seed || j || blk) blocks. → (n_seeds, n_bytes)."""
    n_seeds = seeds.shape[0]
    nblk = -(-n_bytes // 32)
    rows = np.empty((n_seeds * nblk, 32 + 2 + 4), np.uint8)
    rows[:, :32] = np.repeat(seeds, nblk, axis=0)
    j_ids = np.repeat(np.arange(n_seeds, dtype=np.uint16), nblk)
    rows[:, 32:34] = j_ids.view(np.uint8).reshape(-1, 2)
    blk = np.tile(np.arange(nblk, dtype=np.uint32), n_seeds)
    rows[:, 34:38] = blk.view(np.uint8).reshape(-1, 4)
    out = _hash_rows(b"mpcium-ot-prg|" + tag, rows)
    return out.reshape(n_seeds, nblk * 32)[:, :n_bytes]


# ---------------------------------------------------------------------------
# base OTs (Chou–Orlandi on secp256k1; host curve math, once per pair)
# ---------------------------------------------------------------------------


def _pt_hash(point) -> bytes:
    return hashlib.sha256(b"mpcium-ot-base|" + hm.secp_compress(point)).digest()


def _secp_neg(pt: "hm.SecpPoint") -> "hm.SecpPoint":
    if pt.is_infinity:
        return pt
    return hm.SecpPoint(pt.x, (-pt.y) % hm.SECP_P)


def _bcast_pt(pt_bytes: bytes, n: int):
    """Compressed point → device SecpPointJ broadcast to batch n."""
    p = sp.from_host([hm.secp_decompress(pt_bytes)])
    return type(p)(
        *(jnp.broadcast_to(c, (n,) + c.shape[1:]) for c in p)
    )


@jax.jit
def _k_base_receive(bits, delta, S_pt):
    """Receiver's batched curve work: (compress(R), compress(X·S)).
    Jitted once per process — the 256-step ladders would otherwise
    re-trace per call (~minutes per quorum pair on a 1-core host)."""
    XG = sp.base_mul(bits)
    XS = sp.scalar_mul(bits, S_pt)
    R = sp.select(delta, sp.add(XG, S_pt), XG)
    return sp.compress(R), sp.compress(XS)


@jax.jit
def _k_base_sender(y_bits, R_pt, yS_neg_pt):
    """Sender's batched curve work: (compress(y·R), compress(y·R−y·S))."""
    yR = sp.scalar_mul(y_bits, R_pt)
    return sp.compress(yR), sp.compress(sp.add(yR, yS_neg_pt))


def _pt_hash_rows(comp_rows: np.ndarray) -> np.ndarray:
    """(n, 33) compressed points → (n, 32) H(point) key rows (same
    domain tag as _pt_hash)."""
    return _hash_rows(b"mpcium-ot-base|", comp_rows)


def base_ot_sender_init(rng=_secrets) -> Tuple[int, bytes]:
    """Alice (MtA receiver = base-OT sender): y, S = y·G."""
    y = rng.randbelow(Q - 1) + 1
    return y, hm.secp_compress(hm.secp_mul(y, hm.SECP_G))


def base_ot_receive(
    S_bytes: bytes, rng=_secrets
) -> Tuple[np.ndarray, np.ndarray, List[bytes]]:
    """Bob: picks Δ ∈ {0,1}^κ; per base OT j sends R_j = x_j·G + Δ_j·S
    and keeps k^{Δ_j}_j = H(x_j·S). Returns (delta_bits, keys, R_msgs).
    All κ curve ops ride ONE batched device dispatch each (host
    double-and-add at ~70 ms/mul would cost ~30 s per quorum pair)."""
    delta = np.frombuffer(rng.token_bytes(KAPPA), np.uint8) & 1
    xs = [rng.randbelow(Q - 1) + 1 for _ in range(KAPPA)]
    bits = jnp.asarray(sp.scalars_to_bits(xs))
    R_comp, XS_comp = _k_base_receive(
        bits, jnp.asarray(delta), _bcast_pt(S_bytes, KAPPA)
    )
    msgs = [bytes(r) for r in np.asarray(R_comp)]
    keys = _pt_hash_rows(np.asarray(XS_comp))
    return delta, keys, msgs


def base_ot_sender_keys(
    y: int, R_msgs: List[bytes]
) -> Tuple[np.ndarray, np.ndarray]:
    """Alice: k0_j = H(y·R_j), k1_j = H(y·(R_j − S)) — batched device
    scalar-mults (y broadcast across the κ rows)."""
    S = hm.secp_mul(y, hm.SECP_G)
    # y·(R − S) = y·R − y·S — subtract the SCALED point, not S itself
    yS_neg = _secp_neg(hm.secp_mul(y, S))
    R = sp.from_host([hm.secp_decompress(rb) for rb in R_msgs])
    y_bits = jnp.broadcast_to(
        jnp.asarray(sp.scalars_to_bits([y])), (KAPPA, 256)
    )
    yR_comp, yRmS_comp = _k_base_sender(
        y_bits, R, _bcast_pt(hm.secp_compress(yS_neg), KAPPA)
    )
    k0 = _pt_hash_rows(np.asarray(yR_comp))
    k1 = _pt_hash_rows(np.asarray(yRmS_comp))
    return k0, k1


# ---------------------------------------------------------------------------
# device helpers (batched mod-q arithmetic on the scalar-ring kernels)
# ---------------------------------------------------------------------------


@jax.jit
def _pow2_ladder(b: jnp.ndarray) -> jnp.ndarray:
    """(B, n) scalars mod q → (NBITS, B, n) with ladder[i] = 2^i·b."""
    ring = sp.scalar_ring()

    def step(c, _):
        return ring.addmod(c, c), c

    _, ys = lax.scan(step, b, None, length=NBITS)
    return ys


@jax.jit
def _m1_payloads(z_red: jnp.ndarray, pow2b: jnp.ndarray) -> jnp.ndarray:
    """(B, NBITS, n) reduced z + (NBITS, B, n) ladder → m1 bytes
    (B, NBITS, 32)."""
    ring = sp.scalar_ring()
    m1 = ring.addmod(z_red, jnp.moveaxis(pow2b, 0, 1))
    return bn.limbs_to_bytes_le(m1, P256, 32)


@jax.jit
def _reduce_bytes(raw: jnp.ndarray) -> jnp.ndarray:
    """(..., 32) LE bytes → reduced (..., n) scalars mod q."""
    ring = sp.scalar_ring()
    return ring.reduce(bn.bytes_to_limbs_le(raw, P256, 22))


@jax.jit
def _sum_mod_q(vals: jnp.ndarray) -> jnp.ndarray:
    """(B, NBITS, n) reduced scalars → (B, n) sum mod q. Limb sums stay
    < NBITS·2^12 < 2^21 (int32-safe redundancy), normalized by carry
    before the Barrett reduce."""
    ring = sp.scalar_ring()
    s = jnp.sum(vals, axis=-2)
    return ring.reduce(bn.carry(s, P256))


@jax.jit
def _neg_sum_mod_q(vals: jnp.ndarray) -> jnp.ndarray:
    ring = sp.scalar_ring()
    return ring.negmod(_sum_mod_q(vals))


@jax.jit
def _bits_256(a: jnp.ndarray) -> jnp.ndarray:
    """(B, n) scalars → (B, NBITS) int32 bits LSB-first."""
    return bn.limbs_to_bits(a, P256, NBITS)


# ---------------------------------------------------------------------------
# the per-ordered-pair MtA instance
# ---------------------------------------------------------------------------


def _pack(bits: np.ndarray) -> np.ndarray:
    """(..., n) 0/1 → packed little-endian-bit bytes (..., n/8)."""
    return np.packbits(bits.astype(np.uint8), axis=-1, bitorder="little")


def _unpack(b: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(b, axis=-1, count=n, bitorder="little")


def _derive_pads_multi(prefixes, packed: np.ndarray, M: int, delta=None):
    """Per-OT hash pads from the packed (κ, M/8) extension matrix, for
    SEVERAL payload-set hash domains at once:
    pad_s[j] = H(prefix_s ‖ column j re-packed ‖ le32(j)), plus the
    delta-offset variant per set when ``delta`` (packed κ/8) is given.
    The transpose depends only on ``packed``, so it runs ONCE however
    many sets are derived — natively (batch_hash.cpp walks the packed
    matrix directly) when available; the numpy fallback materializes
    the unpacked bit matrix and a strided transpose copy (~130 MB per
    leg at M = 2^20), also once. Returns [pad0_s] or [(pad0_s, pad1_s)]
    in prefix order."""
    from ... import native

    rows = native.ot_transpose(packed) if native.available() else None
    if rows is None:
        rows = _pack(_unpack(packed, M).T)  # (M, κ/8)
    idx = np.arange(M, dtype=np.uint32).view(np.uint8).reshape(M, 4)
    buf = np.concatenate([rows, idx], axis=1)
    bufd = (
        None if delta is None
        else np.concatenate([rows ^ delta[None, :], idx], axis=1)
    )
    out = []
    for prefix in prefixes:
        if delta is None:
            out.append(_hash_rows(prefix, buf))
        else:
            out.append((_hash_rows(prefix, buf), _hash_rows(prefix, bufd)))
    return out


class OTMtALeg:
    """One ordered quorum pair (Alice = receiver with ``a``; Bob = sender
    with ``b``). In-process engine form: both roles live on this object,
    but every inter-party value flows through explicit ``*_msg`` returns
    so the distributed wiring is mechanical. One instance serves every
    batch invocation (extension counter in all PRF/hash domains)."""

    def __init__(self, tag: str, rng=_secrets):
        self.tag = tag.encode()
        self.rng = rng
        self.ctr = 0
        y, S = base_ot_sender_init(rng)
        self.delta, self.keysD, R_msgs = base_ot_receive(S, rng)
        self.k0, self.k1 = base_ot_sender_keys(y, R_msgs)
        self.delta_packed = _pack(self.delta)  # (16,)

    # -- Alice ---------------------------------------------------------------

    def alice_round1(self, a: jnp.ndarray, ctr: int) -> Dict:
        """``a``: (B, n) scalars mod q. → {"U": (κ, M/8)} to Bob; local
        state kept for round 3."""
        B = a.shape[0]
        M = B * NBITS
        r_bits = np.asarray(_bits_256(a)).astype(np.uint8).reshape(M)
        tag = self.tag + b"|%d" % ctr
        t0 = _prg(self.k0, M // 8, tag)  # (κ, M/8) packed
        t1 = _prg(self.k1, M // 8, tag)
        r_packed = _pack(r_bits)
        U = t0 ^ t1 ^ r_packed[None, :]
        self._alice_state = (t0, r_bits, B, tag)
        return {"U": U}

    def alice_round3(self, bob_msg: Dict) -> jnp.ndarray:
        """Recover the selected payloads → Alice's additive share
        (B, n) mod q."""
        return self.alice_round3_multi((bob_msg,))[0]

    def alice_round3_multi(self, bob_msgs) -> List[jnp.ndarray]:
        """One extension, several payload sets (see bob_round2_multi):
        per-set pads come from the SAME transposed rows under
        set-separated hash domains, so each set's pads are independent
        random-oracle outputs."""
        t0, r_bits, B, tag = self._alice_state
        M = B * NBITS
        pad_sets = _derive_pads_multi(
            [b"mpcium-ot-pad|" + tag + b"|s%d" % s
             for s in range(len(bob_msgs))],
            t0, M,
        )
        alphas = []
        for bob_msg, pads in zip(bob_msgs, pad_sets):
            sel = np.where(
                r_bits[:, None].astype(bool), bob_msg["y1"], bob_msg["y0"]
            )
            m_sel = (sel ^ pads).reshape(B, NBITS, 32)
            alphas.append(_sum_mod_q(_reduce_bytes(jnp.asarray(m_sel))))
        return alphas

    # -- Bob -----------------------------------------------------------------

    def bob_round2(
        self, b_scalars: jnp.ndarray, alice_msg: Dict, ctr: int
    ) -> Tuple[Dict, jnp.ndarray]:
        """``b_scalars``: (B, n) mod q. → ({"y0", "y1"} to Alice, Bob's
        additive share (B, n) mod q)."""
        msgs, betas = self.bob_round2_multi((b_scalars,), alice_msg, ctr)
        return msgs[0], betas[0]

    def bob_round2_multi(
        self, b_list, alice_msg: Dict, ctr: int
    ) -> Tuple[List[Dict], List[jnp.ndarray]]:
        """Several payload sets against ONE extension. Alice's choice
        bits (bits of ``a``) are shared across sets by construction —
        GG18 multiplies the same k_a against both γ_b and w_b — so the
        expensive extension half (t/U PRG expansion, the Q matrix) runs
        once and only the per-set payload masking repeats, under
        set-separated pad domains (`…|s0`, `…|s1`: independent RO
        outputs from the same rows)."""
        B = b_list[0].shape[0]
        M = B * NBITS
        tag = self.tag + b"|%d" % ctr
        tD = _prg(self.keysD, M // 8, tag)  # (κ, M/8)
        U = alice_msg["U"]
        Qm = tD ^ (U & (self.delta[:, None].astype(np.uint8) * 0xFF))
        pad_sets = _derive_pads_multi(
            [b"mpcium-ot-pad|" + tag + b"|s%d" % s
             for s in range(len(b_list))],
            Qm, M, delta=self.delta_packed,
        )
        msgs, betas = [], []
        for (b_scalars, (pad0, pad1)) in zip(b_list, pad_sets):
            # payloads: z and z + 2^i·b (mod q), z freshly random per OT
            z_raw = np.frombuffer(
                self.rng.token_bytes(M * 32), np.uint8
            ).reshape(B, NBITS, 32)
            z_red = _reduce_bytes(jnp.asarray(z_raw))  # (B, NBITS, n)
            m1 = np.asarray(_m1_payloads(z_red, _pow2_ladder(b_scalars)))
            m0 = np.asarray(bn.limbs_to_bytes_le(z_red, P256, 32))
            y0 = m0.reshape(M, 32) ^ pad0
            y1 = m1.reshape(M, 32) ^ pad1
            msgs.append({"y0": y0, "y1": y1})
            betas.append(_neg_sum_mod_q(z_red))
        return msgs, betas

    # -- in-process convenience (the engine path) ----------------------------

    def run(
        self, a: jnp.ndarray, b: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Both roles locally: → (alice_share, bob_share), (B, n) each,
        with alice_share + bob_share ≡ a·b (mod q) per lane."""
        (pair,) = self.run_multi(a, (b,))
        return pair

    def run_multi(self, a: jnp.ndarray, b_list):
        """Both roles locally, several Bob scalars against one ``a``
        (ONE extension): → [(alpha_s, beta_s)] with
        alpha_s + beta_s ≡ a·b_s (mod q) per lane."""
        ctr = self.ctr
        self.ctr += 1
        msg_a = self.alice_round1(a, ctr)
        msgs_b, betas = self.bob_round2_multi(b_list, msg_a, ctr)
        alphas = self.alice_round3_multi(msgs_b)
        return list(zip(alphas, betas))
