"""OT-based MtA: Gilboa multiplication over the secp256k1 scalar ring.

The GG18 cost center is the Paillier MtA — encryptions, range proofs and
CRT decryptions at 2048/4096-bit are ~100% of the audited mulmod budget
(PERFORMANCE.md). This module replaces the two MtA legs with
oblivious-transfer multiplication (Gilboa 1999, the approach of
Doerner–Kondi–Lee–shelat threshold ECDSA): Alice holds ``a``, Bob holds
``b``, and they derive additive shares of ``a·b mod q`` from 256
1-of-2 OTs per product — all symmetric crypto (PRG expansion, bit-matrix
transpose, bulk hashing) plus 256-bit scalar sums, with NO big-modulus
exponentiation anywhere.

Construction:

* **Base OTs** (once per ordered quorum pair): Chou–Orlandi simplest OT
  on secp256k1. Bob — the MtA *sender* — is the base-OT *receiver* with
  choice bits Δ (the IKNP role reversal).
* **Extension** (per signing batch): IKNP. Alice's choice bits are the
  bits of her multiplicands; matrices expand from the base seeds with a
  per-(leg, invocation) counter, so one base-OT setup serves every batch
  (stateful IKNP: each extension consumes a disjoint PRF range).
* **Payloads**: for OT index (s, i) — signature lane s, bit i — Bob
  offers ``z_{s,i}`` and ``z_{s,i} + 2^i·b_s mod q``; Alice picks by bit
  i of ``a_s``. Alice's share is ``Σ_i received``, Bob's is ``-Σ_i z``;
  they sum to ``a_s·b_s mod q``. The mod-q sums and the ``2^i·b``
  doubling ladder run batched on device (existing scalar-ring kernels);
  masking/hashing runs through the native batched SHA-256.
* **Pipelining** (the 45%-host-wall fix — PERFORMANCE.md): ``run_multi``
  splits the batch into MPCIUM_OT_CHUNKS sub-batches and double-buffers
  them — all device payload math is dispatched asynchronously up front
  and a background worker runs each chunk's host extension work (PRG
  expansion, packed transpose, pad hashing — natively threaded, knob
  MPCIUM_NATIVE_THREADS) while the main thread drains the previous
  chunk's device arrays. Chunk boundaries align with the 32-byte PRG
  blocks and the global OT index, so chunking/threading change
  SCHEDULING ONLY — transcripts and shares are bit-identical to the
  serial three-round composition (tests/test_mta_ot_pipeline.py).

SECURITY (active checks, ON by default — MPCIUM_OT_CHECKS=0 is the A/B
escape hatch): every extension carries three statistically-sound check
layers, all vmapped device math on the ops.hash_suite primitives:

* **KOS-style correlation check** (verifier: Bob) — a Fiat–Shamir
  challenge χ ∈ GF(2)^{κ×256} per lane, derived from a Merkle digest of
  the lane's U columns, binds Alice's extension matrix to ONE consistent
  choice-bit vector: Alice ships x̄ = χ·x and t̄ = χ·T with round 1, Bob
  checks χ·Q = t̄ ⊕ x̄⊗Δ. Soundness 2^-κ; failure blames Alice.
* **Gilboa ψ-encoding check** (verifier: Alice) — DKLs18-style: weights
  ψ_i ∈ Z_q are FS-derived from a Merkle digest of Bob's masked payload
  rows, fixed AFTER the payloads; Bob ships D = Σψ_i·z_i and B = b·G,
  Alice checks (Σψ_i·m_sel,i)·G == D·G + (Σ_{x_i=1}ψ_i·2^i)·B, so any
  payload pair inconsistent with SOME (z, b) encoding on a selected
  branch is caught. Failure blames Bob.
* **MtA output consistency** (verifier: Alice) — Bob ships β·G; Alice
  checks α·G + β·G == a·(b·G), pinning the advertised output shares to
  the checked encoding. Failure blames Bob.

Verdicts land per lane in ``check_verdicts`` (see ``check_blame``), so
the batch engine can attribute an identifiable abort to the offending
(session, party) instead of killing the cohort. Residual gaps — a
lying verifier can still FRAME the other party (no publicly verifiable
transcript), each aborted attempt leaks ≤ 1 chosen predicate bit of the
honest input (selective failure), and output substitution AFTER a clean
MtA is caught by GG18 phase 5, not here — are scoped in SECURITY.md
"OT-MtA". Enable the path with MPCIUM_MTA=ot.

Reference correspondence: replaces the tss-lib MtA
(SURVEY.md §2.3; reference pkg/mpc/ecdsa_signing_session.go drives
Paillier MtA per session) with the OT-based alternative the DKLs line of
work uses; the leading axis is the concurrent-session batch.
"""
from __future__ import annotations

import hashlib
import os
import secrets as _secrets
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...core import bignum as bn
from ...core import hostmath as hm
from ...core import secp256k1_jax as sp
from ...core.bignum import P256
from ...ops import hash_suite as hs
from ...utils import tracing

KAPPA = 128  # IKNP width / computational security parameter
NBITS = 256  # multiplicand bits (secp256k1 scalars)
Q = hm.SECP_N

# Wire/domain version of the extension layer. v2: the pad hash domain
# carries the per-payload-set suffix (`…|s0`, `…|s1` — the run_multi
# amortization) AND the version byte itself rides every PRF/pad tag, so
# mixed-version parties derive unrelated pads instead of silently
# unmasking garbage; the explicit `v` field in the round messages turns
# that into a LOUD contract failure (see bob_round2_multi /
# alice_round3_multi). v3: active-security check messages ride the
# rounds — alice_round1 gains the KOS tags (`kos_xbar`, `kos_tbar`),
# each bob_round2 payload set gains the Gilboa/consistency openings
# (`D`, `B_pt`, `Beta_pt`) — and the version-stamped tag again firewalls
# the PRF domains of mixed-version quorums. SECURITY.md "OT-MtA".
OT_WIRE_VERSION = 3

# One background worker is the whole double-buffer: run_multi enqueues
# every chunk's host-side extension work (PRG expansion, bit-matrix
# transpose, pad hashing) on it IN ORDER, then the main thread drains
# chunks — while it blocks on chunk i's device arrays, the worker is
# already expanding chunk i+1. The native kernels release the GIL (and
# thread internally per MPCIUM_NATIVE_THREADS), so worker and main
# thread genuinely overlap.
_HOST_POOL: Optional[ThreadPoolExecutor] = None
_HOST_POOL_LOCK = threading.Lock()


def _host_pool() -> ThreadPoolExecutor:
    global _HOST_POOL
    with _HOST_POOL_LOCK:
        if _HOST_POOL is None:
            _HOST_POOL = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ot-host"
            )
        return _HOST_POOL


def resolve_chunks(B: int, chunks: Optional[int] = None) -> int:
    """Pipeline chunk count: explicit argument wins, then
    MPCIUM_OT_CHUNKS, then auto from the batch (enough chunks to hide
    host extension work behind device compute without shrinking device
    dispatches below ~256 lanes). Clamped to the largest divisor of B
    so every chunk keeps the same static shape (one XLA executable)."""
    if chunks is None or chunks <= 0:
        chunks = int(os.environ.get("MPCIUM_OT_CHUNKS", "0") or 0)
    if chunks <= 0:
        chunks = max(1, min(8, B // 256))
    chunks = max(1, min(chunks, B))
    while B % chunks:
        chunks -= 1
    return chunks


def device_path_enabled() -> bool:
    """MPCIUM_OT_DEVICE gates ``run_multi``'s fused on-device extension
    (default ON): PRG expansion, bit-matrix transpose, pad hashing and
    payload masking all run as one jitted dispatch per chunk
    (ops.hash_suite), and the host touches nothing but wire bytes. The
    host/native path remains the wire-round implementation
    (alice_round1 / bob_round2_multi / alice_round3_multi) and the
    transcript oracle; set MPCIUM_OT_DEVICE=0 to force it in-process."""
    return os.environ.get("MPCIUM_OT_DEVICE", "1") != "0"


def ot_checks_enabled() -> bool:
    """MPCIUM_OT_CHECKS gates the active-security check layers (KOS
    correlation / Gilboa ψ-encoding / output consistency — module
    docstring). Default ON; =0 is the A/B escape hatch for measuring
    the cost of active security (bench.py gg18_ot_checks_s) and MUST be
    set identically quorum-wide: a checks-on party rejects a checks-off
    peer's round messages loudly (missing check fields)."""
    return os.environ.get("MPCIUM_OT_CHECKS", "1") != "0"


def _hash_rows(prefix: bytes, rows: np.ndarray) -> np.ndarray:
    """sha256(prefix || row) per row → (N, 32); native batched C++ when
    built, hashlib otherwise (tests / cold environments)."""
    from ... import native

    if native.available():
        return native.batch_sha256(prefix, np.ascontiguousarray(rows))
    out = np.empty((rows.shape[0], 32), np.uint8)
    for i, r in enumerate(rows):
        out[i] = np.frombuffer(
            hashlib.sha256(prefix + r.tobytes()).digest(), np.uint8
        )
    return out


def _prg(
    seeds: np.ndarray, n_bytes: int, tag: bytes, blk_off: int = 0
) -> np.ndarray:
    """Expand each 32-byte seed row to ``n_bytes`` pseudorandom bytes:
    sha256(tag || seed || j || blk) blocks. → (n_seeds, n_bytes).

    ``blk_off`` starts the per-seed block counter mid-stream, so a
    chunked caller expanding ``[blk_off, blk_off + n/32)`` gets exactly
    the matching slice of the full expansion (chunking never changes
    the transcript). Fused native path when built; the numpy fallback
    assembles the (n_seeds·nblk, 38) message matrix explicitly."""
    from ... import native

    n_seeds = seeds.shape[0]
    nblk = -(-n_bytes // 32)
    prefix = b"mpcium-ot-prg|" + tag
    out = native.prg_expand(prefix, seeds, nblk, blk_off)
    if out is not None:
        return out[:, :n_bytes] if nblk * 32 != n_bytes else out
    rows = np.empty((n_seeds * nblk, 32 + 2 + 4), np.uint8)
    rows[:, :32] = np.repeat(seeds, nblk, axis=0)
    j_ids = np.repeat(np.arange(n_seeds, dtype=np.uint16), nblk)
    rows[:, 32:34] = j_ids.view(np.uint8).reshape(-1, 2)
    blk = np.tile(
        np.arange(blk_off, blk_off + nblk, dtype=np.uint32), n_seeds
    )
    rows[:, 34:38] = blk.view(np.uint8).reshape(-1, 4)
    out = _hash_rows(prefix, rows)
    return out.reshape(n_seeds, nblk * 32)[:, :n_bytes]


# ---------------------------------------------------------------------------
# base OTs (Chou–Orlandi on secp256k1; host curve math, once per pair)
# ---------------------------------------------------------------------------


def _pt_hash(point) -> bytes:
    return hashlib.sha256(b"mpcium-ot-base|" + hm.secp_compress(point)).digest()


def _secp_neg(pt: "hm.SecpPoint") -> "hm.SecpPoint":
    if pt.is_infinity:
        return pt
    return hm.SecpPoint(pt.x, (-pt.y) % hm.SECP_P)


def _bcast_pt(pt_bytes: bytes, n: int):
    """Compressed point → device SecpPointJ broadcast to batch n."""
    p = sp.from_host([hm.secp_decompress(pt_bytes)])
    return type(p)(
        *(jnp.broadcast_to(c, (n,) + c.shape[1:]) for c in p)
    )


@jax.jit
def _k_base_receive(bits, delta, S_pt):
    """Receiver's batched curve work: (compress(R), compress(X·S)).
    Jitted once per process — the 256-step ladders would otherwise
    re-trace per call (~minutes per quorum pair on a 1-core host)."""
    XG = sp.base_mul(bits)
    XS = sp.scalar_mul(bits, S_pt)
    R = sp.select(delta, sp.add(XG, S_pt), XG)
    return sp.compress(R), sp.compress(XS)


@jax.jit
def _k_base_sender(y_bits, R_pt, yS_neg_pt):
    """Sender's batched curve work: (compress(y·R), compress(y·R−y·S))."""
    yR = sp.scalar_mul(y_bits, R_pt)
    return sp.compress(yR), sp.compress(sp.add(yR, yS_neg_pt))


def _pt_hash_rows(comp_rows: np.ndarray) -> np.ndarray:
    """(n, 33) compressed points → (n, 32) H(point) key rows (same
    domain tag as _pt_hash)."""
    return _hash_rows(b"mpcium-ot-base|", comp_rows)


def base_ot_sender_init(rng=_secrets) -> Tuple[int, bytes]:
    """Alice (MtA receiver = base-OT sender): y, S = y·G."""
    y = rng.randbelow(Q - 1) + 1
    return y, hm.secp_compress(hm.secp_mul(y, hm.SECP_G))


def base_ot_receive(
    S_bytes: bytes, rng=_secrets
) -> Tuple[np.ndarray, np.ndarray, List[bytes]]:
    """Bob: picks Δ ∈ {0,1}^κ; per base OT j sends R_j = x_j·G + Δ_j·S
    and keeps k^{Δ_j}_j = H(x_j·S). Returns (delta_bits, keys, R_msgs).
    All κ curve ops ride ONE batched device dispatch each (host
    double-and-add at ~70 ms/mul would cost ~30 s per quorum pair)."""
    delta = np.frombuffer(rng.token_bytes(KAPPA), np.uint8) & 1
    xs = [rng.randbelow(Q - 1) + 1 for _ in range(KAPPA)]
    bits = jnp.asarray(sp.scalars_to_bits(xs))
    R_comp, XS_comp = _k_base_receive(
        bits, jnp.asarray(delta), _bcast_pt(S_bytes, KAPPA)
    )
    msgs = [bytes(r) for r in np.asarray(R_comp)]  # mpcflow: host-ok — base-OT wire messages (κ=128 rows, once per pair)
    keys = _pt_hash_rows(np.asarray(XS_comp))  # mpcflow: host-ok — ROT key derivation hashes on host (κ=128 rows, once per pair)
    return delta, keys, msgs


def base_ot_sender_keys(
    y: int, R_msgs: List[bytes]
) -> Tuple[np.ndarray, np.ndarray]:
    """Alice: k0_j = H(y·R_j), k1_j = H(y·(R_j − S)) — batched device
    scalar-mults (y broadcast across the κ rows)."""
    S = hm.secp_mul(y, hm.SECP_G)
    # y·(R − S) = y·R − y·S — subtract the SCALED point, not S itself
    yS_neg = _secp_neg(hm.secp_mul(y, S))
    R = sp.from_host([hm.secp_decompress(rb) for rb in R_msgs])
    y_bits = jnp.broadcast_to(
        jnp.asarray(sp.scalars_to_bits([y])), (KAPPA, 256)
    )
    yR_comp, yRmS_comp = _k_base_sender(
        y_bits, R, _bcast_pt(hm.secp_compress(yS_neg), KAPPA)
    )
    k0 = _pt_hash_rows(np.asarray(yR_comp))  # mpcflow: host-ok — ROT key derivation hashes on host (κ=128 rows, once per pair)
    k1 = _pt_hash_rows(np.asarray(yRmS_comp))  # mpcflow: host-ok — ROT key derivation hashes on host (κ=128 rows, once per pair)
    return k0, k1


# ---------------------------------------------------------------------------
# device helpers (batched mod-q arithmetic on the scalar-ring kernels)
# ---------------------------------------------------------------------------


@jax.jit
def _pow2_ladder(b: jnp.ndarray) -> jnp.ndarray:
    """(B, n) scalars mod q → (NBITS, B, n) with ladder[i] = 2^i·b."""
    ring = sp.scalar_ring()

    def step(c, _):
        return ring.addmod(c, c), c

    _, ys = lax.scan(step, b, None, length=NBITS)
    return ys


@jax.jit
def _m1_payloads(z_red: jnp.ndarray, pow2b: jnp.ndarray) -> jnp.ndarray:
    """(B, NBITS, n) reduced z + (NBITS, B, n) ladder → m1 bytes
    (B, NBITS, 32)."""
    ring = sp.scalar_ring()
    m1 = ring.addmod(z_red, jnp.moveaxis(pow2b, 0, 1))
    return bn.limbs_to_bytes_le(m1, P256, 32)


@jax.jit
def _reduce_bytes(raw: jnp.ndarray) -> jnp.ndarray:
    """(..., 32) LE bytes → reduced (..., n) scalars mod q."""
    ring = sp.scalar_ring()
    return ring.reduce(bn.bytes_to_limbs_le(raw, P256, 22))


@jax.jit
def _sum_mod_q(vals: jnp.ndarray) -> jnp.ndarray:
    """(B, NBITS, n) reduced scalars → (B, n) sum mod q. Limb sums stay
    < NBITS·2^12 < 2^21 (int32-safe redundancy), normalized by carry
    before the Barrett reduce."""
    ring = sp.scalar_ring()
    s = jnp.sum(vals, axis=-2)
    return ring.reduce(bn.carry(s, P256))


@jax.jit
def _neg_sum_mod_q(vals: jnp.ndarray) -> jnp.ndarray:
    ring = sp.scalar_ring()
    return ring.negmod(_sum_mod_q(vals))


@jax.jit
def _bits_256(a: jnp.ndarray) -> jnp.ndarray:
    """(B, n) scalars → (B, NBITS) int32 bits LSB-first."""
    return bn.limbs_to_bits(a, P256, NBITS)


@jax.jit
def _ot_chunk_device(
    k0, k1, kD, delta_mask, delta_packed, prg_prefix, pad_prefixes,
    r_bits_c, r_packed_c, m0s, m1s, blk_off, m_off,
):
    """One pipeline chunk of the extension, fused on device: PRG-expand
    all three seed matrices, assemble U and Q, transpose both packed
    matrices, derive every payload set's pads, mask the payloads and
    recover Alice's selections — byte-for-byte the host three-round
    composition, with only wire bytes ever leaving the device.

    Shapes (Bc lanes per chunk, Mc = Bc·NBITS OTs, S payload sets):
    seeds (κ, 32); delta_mask (κ, 1) uint8 0x00/0xFF; delta_packed
    (κ/8,); prg_prefix / pad_prefixes traced uint8 ((P,), (S, P2) — the
    tags embed the extension counter, so static args would recompile
    every invocation); r_bits_c (Mc,); r_packed_c (Mc/8,); m0s/m1s
    (S, Bc, NBITS, 32); blk_off/m_off traced uint32 (the chunk's PRG
    block / global OT index origin). → (alphas (S, Bc, n), U (κ, Bc·32),
    y0s, y1s (S, Mc, 32), rows_a, rows_b (Mc, κ/8), sels (S, Mc, 32) —
    the row matrices and unmasked selections feed the active-security
    check pass (`_verify_inprocess`); they already exist inside the
    fused kernel, so emitting them costs copies, not compute)."""
    Bc = r_packed_c.shape[0] // 32
    Mc = r_bits_c.shape[0]
    t0 = hs.prg_expand_core(k0, prg_prefix, Bc, blk_off)
    t1 = hs.prg_expand_core(k1, prg_prefix, Bc, blk_off)
    tD = hs.prg_expand_core(kD, prg_prefix, Bc, blk_off)
    U = t0 ^ t1 ^ r_packed_c[None, :]
    Q = tD ^ (U & delta_mask)  # fold U into the Δ=1 rows only
    rows_a = hs.ot_transpose_core(t0)  # (Mc, κ/8)
    rows_b = hs.ot_transpose_core(Q)
    idx_le = hs.le32_bytes(
        jnp.asarray(m_off, jnp.uint32) + jnp.arange(Mc, dtype=jnp.uint32)
    )
    sel_bits = r_bits_c.astype(bool)[:, None]
    alphas, y0s, y1s, sels = [], [], [], []
    for s in range(pad_prefixes.shape[0]):
        pref = pad_prefixes[s]
        pad_a = hs.pad_hash_core(pref, rows_a, idx_le)
        pad0 = hs.pad_hash_core(pref, rows_b, idx_le)
        pad1 = hs.pad_hash_core(pref, rows_b ^ delta_packed[None, :], idx_le)
        y0 = pad0 ^ m0s[s].reshape(Mc, 32)
        y1 = pad1 ^ m1s[s].reshape(Mc, 32)
        sel = jnp.where(sel_bits, y1, y0) ^ pad_a
        alphas.append(
            _sum_mod_q(_reduce_bytes(sel.reshape(Bc, NBITS, 32)))
        )
        y0s.append(y0)
        y1s.append(y1)
        sels.append(sel)
    return (
        jnp.stack(alphas), U, jnp.stack(y0s), jnp.stack(y1s),
        rows_a, rows_b, jnp.stack(sels),
    )


# ---------------------------------------------------------------------------
# active-security checks (module docstring "SECURITY"): KOS correlation,
# Gilboa ψ-encoding, MtA output consistency — all pure device math
# (batched SHA-256, GF(2) algebra as integer matmuls, scalar-ring sums,
# curve ladders), so a 4096-lane cohort is checked in a handful of
# dispatches.
# ---------------------------------------------------------------------------

CHECK_KOS = "kos"                  # verifier Bob; failure blames Alice
CHECK_GILBOA = "gilboa"            # verifier Alice; failure blames Bob
CHECK_CONSISTENCY = "consistency"  # verifier Alice; failure blames Bob


def _fs_prefixes(tag: bytes, kind: bytes, set_idx: Optional[int] = None):
    """Fiat–Shamir hash-domain prefixes (leaf / merkle-node / prg) for
    one check family, as traced uint8 arrays — tags embed the extension
    counter, so static operands would recompile every invocation."""
    base = b"mpcium-ot-" + kind + b"|" + tag
    if set_idx is not None:
        base += b"|s%d" % set_idx
    return tuple(
        jnp.asarray(np.frombuffer(base + sfx, np.uint8))
        for sfx in (b"|leaf", b"|node", b"|prg")
    )


def _pt_encode(p) -> jnp.ndarray:
    """Batch points → SEC1 *uncompressed* bytes (..., 65). Uncompressed
    on purpose: the verifier's decode then needs only the curve
    equation, not the Tonelli square-root ladder a compressed decode
    would drag into every check kernel's one-time compile."""
    F = sp.secp256k1_field()
    zi = F.inv(p.Z)
    x = F.canonical(F.mul(p.X, zi))
    y = F.canonical(F.mul(p.Y, zi))
    tag = jnp.full(x.shape[:-1] + (1,), 4, jnp.uint8)
    return jnp.concatenate(
        [tag, sp.pack_be_32(x), sp.pack_be_32(y)], axis=-1
    )


def _pt_decode(b: jnp.ndarray):
    """SEC1 uncompressed (..., 65) → (SecpPointJ, ok mask). Bad
    encodings (wrong tag, coords ≥ p, off-curve — anything a cheater
    could substitute) yield ok=False with a valid-shape point; callers
    fold the mask into the check verdict."""
    F = sp.secp256k1_field()
    tag = b[..., 0].astype(jnp.int32)
    x = bn.bytes_to_limbs_le(
        jnp.flip(b[..., 1:33], axis=-1), sp.PROF, sp.PROF.n_limbs
    )
    y = bn.bytes_to_limbs_le(
        jnp.flip(b[..., 33:65], axis=-1), sp.PROF, sp.PROF.n_limbs
    )
    p_l = jnp.broadcast_to(
        jnp.asarray(bn.to_limbs(hm.SECP_P, sp.PROF)), x.shape
    )
    on_curve = F.eq(
        F.square(y),
        F.add(F.mul(F.square(x), x), F.const(7, x.shape[:-1])),
    )
    ok = (
        (tag == 4)
        & (bn.compare(x, p_l) < 0)
        & (bn.compare(y, p_l) < 0)
        & on_curve
    )
    one = jnp.broadcast_to(jnp.asarray(bn.to_limbs(1, sp.PROF)), x.shape)
    return sp.SecpPointJ(x, y, one), ok


def _merkle_root(leaves: jnp.ndarray, node_prefix: jnp.ndarray) -> jnp.ndarray:
    """(..., L, 32) digests, L a power of two → (..., 32) Merkle root
    via log2(L) batched pair-hash levels. A sequential chain would
    unroll one SHA compression per leaf into the trace; the tree keeps
    the trace logarithmic and every level a single batched dispatch."""
    P = node_prefix.shape[0]
    while leaves.shape[-2] > 1:
        half = leaves.shape[-2] // 2
        pairs = leaves.reshape(leaves.shape[:-2] + (half, 64))
        msg = jnp.concatenate(
            [jnp.broadcast_to(node_prefix, pairs.shape[:-1] + (P,)), pairs],
            axis=-1,
        )
        leaves = hs.sha256_core(msg, P + 64)
    return leaves[..., 0, :]


def _chi_bits(U: jnp.ndarray, leaf_p, node_p, prg_p) -> jnp.ndarray:
    """Per-lane KOS challenge χ ∈ GF(2)^{κ×256}, FS-derived from the
    lane's own U columns: per-row leaf digests → Merkle root → PRG
    expansion. Both parties compute this from the U that crossed the
    wire, so a tampered U yields a DIFFERENT challenge on Bob's side
    and the tag equation fails with overwhelming probability.
    U (κ, B·32) packed → (B, κ, 256) int32 0/1."""
    Bn = U.shape[1] // 32
    lanes = jnp.moveaxis(U.reshape(KAPPA, Bn, 32), 1, 0)  # (B, κ, 32)
    r_le = hs.le16_bytes(jnp.arange(KAPPA, dtype=jnp.uint32))
    P = leaf_p.shape[0]
    msg = jnp.concatenate(
        [
            jnp.broadcast_to(leaf_p, (Bn, KAPPA, P)),
            lanes,
            jnp.broadcast_to(r_le[None], (Bn, KAPPA, 2)),
        ],
        axis=-1,
    )
    root = _merkle_root(hs.sha256_core(msg, P + 34), node_p)  # (B, 32)
    raw = hs.prg_expand_core(root, prg_p, KAPPA, jnp.uint32(0))
    return hs.unpack_bits_core(raw.reshape(Bn, KAPPA, 32)).astype(jnp.int32)


@jax.jit
def _k_kos_tags(rows_a, x_bits, U, leaf_p, node_p, prg_p):
    """Alice's KOS opening: x̄ = χ·x, t̄ = χ·T over GF(2), computed as
    integer matmuls masked to the low bit (MXU-friendly; values stay
    ≤ 256). rows_a (M, κ/8) packed, x_bits (M,) 0/1, U (κ, B·32) →
    (x̄ packed (B, κ/8), t̄ packed (B, κ, κ/8))."""
    Bn = x_bits.shape[0] // NBITS
    chi = _chi_bits(U, leaf_p, node_p, prg_p)  # (B, κ, 256)
    xb = x_bits.reshape(Bn, NBITS).astype(jnp.int32)
    xbar = jnp.einsum("brj,bj->br", chi, xb) & 1
    bits_a = (
        hs.unpack_bits_core(rows_a)
        .reshape(Bn, NBITS, KAPPA)
        .astype(jnp.int32)
    )
    tbar = jnp.einsum("brj,bjc->brc", chi, bits_a) & 1
    return (
        hs.pack_bits_core(xbar.astype(jnp.uint8)),
        hs.pack_bits_core(tbar.astype(jnp.uint8)),
    )


@jax.jit
def _k_kos_verify(rows_b, delta_bits, U, xbar_p, tbar_p, leaf_p, node_p, prg_p):
    """Bob's side of the correlation check: χ·Q == t̄ ⊕ x̄ ⊗ Δ per lane
    (Q rows satisfy q_j = t_j ⊕ x_j·Δ exactly when Alice used one
    consistent choice vector). → (B,) bool, soundness 2^-κ."""
    Bn = rows_b.shape[0] // NBITS
    chi = _chi_bits(U, leaf_p, node_p, prg_p)
    bits_b = (
        hs.unpack_bits_core(rows_b)
        .reshape(Bn, NBITS, KAPPA)
        .astype(jnp.int32)
    )
    qbar = jnp.einsum("brj,bjc->brc", chi, bits_b) & 1
    xbar = hs.unpack_bits_core(xbar_p).astype(jnp.int32)  # (B, κ)
    tbar = (
        hs.unpack_bits_core(tbar_p).astype(jnp.int32)  # (B, κ, κ)
    )
    want = tbar ^ (xbar[..., None] * delta_bits.astype(jnp.int32)[None, None, :])
    return jnp.all(qbar == want, axis=(-2, -1))


def _psi_weights(y0, y1, leaf_p, node_p, prg_p) -> jnp.ndarray:
    """Per-lane Gilboa check weights ψ_i ∈ Z_q, FS-derived from the
    MASKED payload rows (so they are fixed only after Bob commits to
    his payloads): leaf digests of (y0_i ‖ y1_i ‖ index) → Merkle root
    → PRG → mod-q reduction. (M, 32) ×2 → (B, NBITS, n)."""
    M = y0.shape[0]
    Bn = M // NBITS
    P = leaf_p.shape[0]
    idx_le = hs.le32_bytes(jnp.arange(M, dtype=jnp.uint32))
    msg = jnp.concatenate(
        [jnp.broadcast_to(leaf_p, (M, P)), y0, y1, idx_le], axis=-1
    )
    leaves = hs.sha256_core(msg, P + 68).reshape(Bn, NBITS, 32)
    root = _merkle_root(leaves, node_p)  # (B, 32)
    raw = hs.prg_expand_core(root, prg_p, NBITS, jnp.uint32(0))
    return _reduce_bytes(raw.reshape(Bn, NBITS, 32))


# The EC legs of the Gilboa/consistency checks go through SHARED jit
# units below (one compiled ladder per primitive, points crossing the
# boundaries as SecpPointJ pytrees) instead of inlining sp.base_mul /
# sp.scalar_mul into each check kernel: inlined, the three kernels
# re-compile the same 256-step scan ladders nine times over (~143 s
# cold on the 1-core CPU host); shared, each ladder compiles once.
# All-integer math, so the split is bit-exact — wire bytes and
# verdicts are unchanged.


@jax.jit
def _k_ec_base_mul(bits):
    """Shared fixed-base ladder: (B, NBITS) bits → b·G (Jacobian)."""
    return sp.base_mul(bits)


@jax.jit
def _k_ec_scalar_mul(bits, p):
    """Shared variable-base ladder: (B, NBITS) bits × point (Jacobian)."""
    return sp.scalar_mul(bits, p)


@jax.jit
def _k_ec_add_eq(a, b, c):
    """Shared check tail: a + b == c over Jacobian points → (B,) bool."""
    return sp.equal(sp.add(a, b), c)


@jax.jit
def _k_ec_encode(p):
    """Shared SEC1 encode (the one field-inversion ladder)."""
    return _pt_encode(p)


@jax.jit
def _k_ec_decode(b):
    """Shared SEC1 decode → (SecpPointJ, ok mask); no ladder."""
    return _pt_decode(b)


@jax.jit
def _k_gilboa_bob_scalars(y0, y1, z_red, b_scalars, leaf_p, node_p, prg_p):
    """Scalar half of Bob's opening: ψ-weighted sum D = Σψ_i·z_i mod q
    plus the b and −Σz exponent bit vectors for the shared ladders."""
    psi = _psi_weights(y0, y1, leaf_p, node_p, prg_p)
    ring = sp.scalar_ring()
    D = _sum_mod_q(ring.mulmod(psi, z_red))
    return (
        bn.limbs_to_bytes_le(D, P256, 32),
        bn.limbs_to_bits(b_scalars, P256, NBITS),
        bn.limbs_to_bits(_neg_sum_mod_q(z_red), P256, NBITS),
    )


def _k_gilboa_bob(y0, y1, z_red, b_scalars, leaf_p, node_p, prg_p):
    """Bob's Gilboa/consistency opening for one payload set:
    D = Σψ_i·z_i mod q plus the curve commitments B = b·G and β·G.
    → (D LE bytes (B, 32), uncompressed B_pt (B, 65), Beta_pt (B, 65))."""
    D_bytes, b_bits, nz_bits = _k_gilboa_bob_scalars(
        y0, y1, z_red, b_scalars, leaf_p, node_p, prg_p
    )
    return (
        D_bytes,
        _k_ec_encode(_k_ec_base_mul(b_bits)),
        _k_ec_encode(_k_ec_base_mul(nz_bits)),
    )


@jax.jit
def _k_gilboa_alice_scalars(y0, y1, msel, x_bits, D_bytes, leaf_p, node_p, prg_p):
    """Scalar half of Alice's encoding check: the ψ-weighted selected
    sum A_ψ, the re-reduced D and the masked power sum c_x, each as the
    exponent bit vectors the shared ladders consume."""
    psi = _psi_weights(y0, y1, leaf_p, node_p, prg_p)
    ring = sp.scalar_ring()
    Bn = msel.shape[0]
    A_psi = _sum_mod_q(ring.mulmod(psi, _reduce_bytes(msel)))
    one = jnp.asarray(bn.batch_to_limbs([1], P256))
    pow2 = jnp.moveaxis(_pow2_ladder(one), 0, 1)  # (1, NBITS, n): 2^i
    xb = x_bits.reshape(Bn, NBITS)
    psi_x = jnp.where((xb != 0)[..., None], psi, jnp.zeros_like(psi))
    c_x = _sum_mod_q(
        ring.mulmod(psi_x, jnp.broadcast_to(pow2, psi.shape))
    )
    D = ring.reduce(bn.bytes_to_limbs_le(D_bytes, P256, 22))
    return (
        bn.limbs_to_bits(A_psi, P256, NBITS),
        bn.limbs_to_bits(D, P256, NBITS),
        bn.limbs_to_bits(c_x, P256, NBITS),
    )


def _k_gilboa_alice(y0, y1, msel, x_bits, D_bytes, B_comp, leaf_p, node_p, prg_p):
    """Alice's Gilboa encoding check for one payload set:
    (Σψ_i·m_sel,i)·G == D·G + (Σ_{x_i=1} ψ_i·2^i)·B — any selected
    payload inconsistent with the (z, b) encoding Bob opened shifts the
    left side by a ψ-weighted offset, caught except with probability
    ~2^-256 over χ-independent ψ. msel is the UNMASKED selection bytes
    (B·NBITS → (B, NBITS, 32)); a non-decodable B_pt folds into a
    False verdict. → (B,) bool."""
    a_bits, d_bits, cx_bits = _k_gilboa_alice_scalars(
        y0, y1, msel, x_bits, D_bytes, leaf_p, node_p, prg_p
    )
    B_pt, okB = _k_ec_decode(B_comp)
    lhs = _k_ec_base_mul(a_bits)
    return _k_ec_add_eq(
        _k_ec_base_mul(d_bits),
        _k_ec_scalar_mul(cx_bits, B_pt),
        lhs,
    ) & okB


@jax.jit
def _k_alpha_bits(alpha):
    """Limbs → exponent bit vector for the consistency check's α·G."""
    return bn.limbs_to_bits(alpha, P256, NBITS)


def _k_consistency(alpha, x_bits, B_comp, Beta_comp):
    """MtA output consistency for one payload set: α·G + β·G == a·B —
    the advertised output shares must land on the checked product.
    x_bits are Alice's choice bits (= bits of a, LSB-first). → (B,)."""
    Bn = alpha.shape[0]
    B_pt, okB = _k_ec_decode(B_comp)
    beta_pt, okE = _k_ec_decode(Beta_comp)
    lhs = _k_ec_base_mul(_k_alpha_bits(alpha))
    rhs = _k_ec_scalar_mul(
        x_bits.reshape(Bn, NBITS).astype(jnp.int32), B_pt
    )
    return _k_ec_add_eq(lhs, beta_pt, rhs) & okB & okE


def _tamper_lane_view(field: str, arr: np.ndarray, lane: int) -> np.ndarray:
    """The slice of a wire tensor one batch lane owns — the corruption
    surface an active cheater controls for that session."""
    if field == "U":
        return arr[:, lane * 32:(lane + 1) * 32]
    if field in ("kos_xbar", "kos_tbar", "D", "B_pt", "Beta_pt"):
        return arr[lane]
    if field in ("y0", "y1"):
        return arr[lane * NBITS:(lane + 1) * NBITS]
    raise ValueError(f"unknown tamper field {field!r}")


def _apply_tamper(spec: Dict, msg: Dict) -> bool:
    """Mutate one byte of one lane's slice of ``spec["field"]`` inside a
    round message (no-op, returning False, when the field is absent —
    the caller then targets the other round). Writes through a fresh
    copy so device-backed arrays stay untouched."""
    field = spec["field"]
    if field not in msg:
        return False
    arr = np.array(msg[field])
    view = _tamper_lane_view(field, arr, int(spec.get("lane", 0)))
    idx = np.unravel_index(int(spec.get("byte", 0)) % view.size, view.shape)
    view[idx] = view[idx] ^ np.uint8(int(spec.get("xor", 1)) or 1)
    msg[field] = arr
    return True


# ---------------------------------------------------------------------------
# the per-ordered-pair MtA instance
# ---------------------------------------------------------------------------


def _pack(bits: np.ndarray) -> np.ndarray:
    """(..., n) 0/1 → packed little-endian-bit bytes (..., n/8)."""
    return np.packbits(bits.astype(np.uint8), axis=-1, bitorder="little")


def _unpack(b: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(b, axis=-1, count=n, bitorder="little")


def _derive_pads_multi(
    prefixes, packed: np.ndarray, M: int, delta=None, m_off: int = 0
):
    """Per-OT hash pads from the packed (κ, M/8) extension matrix, for
    SEVERAL payload-set hash domains at once:
    pad_s[j] = H(prefix_s ‖ column j re-packed ‖ le32(j)), plus the
    delta-offset variant per set when ``delta`` (packed κ/8) is given.
    The transpose depends only on ``packed``, so it runs ONCE however
    many sets are derived — natively (batch_hash.cpp walks the packed
    matrix directly) when available; the numpy fallback materializes
    the unpacked bit matrix and a strided transpose copy (~130 MB per
    leg at M = 2^20), also once. ``m_off`` offsets the le32 OT index
    for a chunked caller (columns [m_off, m_off+M) of the full
    matrix), so per-chunk pads equal the matching slice of the
    full-width derivation. Returns [pad0_s] or [(pad0_s, pad1_s)] in
    prefix order."""
    from ... import native

    rows = native.ot_transpose(packed) if native.available() else None
    if rows is None:
        rows = _pack(_unpack(packed, M).T)  # (M, κ/8)
    idx = (
        np.arange(m_off, m_off + M, dtype=np.uint32)
        .view(np.uint8).reshape(M, 4)
    )
    buf = np.concatenate([rows, idx], axis=1)
    bufd = (
        None if delta is None
        else np.concatenate([rows ^ delta[None, :], idx], axis=1)
    )
    out = []
    for prefix in prefixes:
        if delta is None:
            out.append(_hash_rows(prefix, buf))
        else:
            out.append((_hash_rows(prefix, buf), _hash_rows(prefix, bufd)))
    return out


class OTMtALeg:
    """One ordered quorum pair (Alice = receiver with ``a``; Bob = sender
    with ``b``). In-process engine form: both roles live on this object,
    but every inter-party value flows through explicit ``*_msg`` returns
    so the distributed wiring is mechanical. One instance serves every
    batch invocation (extension counter in all PRF/hash domains)."""

    def __init__(self, tag: str, rng=_secrets):
        self.tag = tag.encode()
        self.rng = rng
        self.ctr = 0
        y, S = base_ot_sender_init(rng)
        self.delta, self.keysD, R_msgs = base_ot_receive(S, rng)
        self.k0, self.k1 = base_ot_sender_keys(y, R_msgs)
        self.delta_packed = _pack(self.delta)  # (16,)
        self._delta_rows = np.nonzero(self.delta)[0]

    def _ext_tag(self, ctr: int) -> bytes:
        """Per-invocation PRF/pad domain tag, version-stamped (see
        OT_WIRE_VERSION)."""
        return self.tag + b"|v%d|%d" % (OT_WIRE_VERSION, ctr)

    @staticmethod
    def _pad_prefixes(tag: bytes, n_sets: int) -> List[bytes]:
        return [
            b"mpcium-ot-pad|" + tag + b"|s%d" % s for s in range(n_sets)
        ]

    def _device_state(self) -> Dict[str, jnp.ndarray]:
        """Base-OT key material as device arrays, uploaded once per leg
        and reused by every device-path extension."""
        st = getattr(self, "_dev_state", None)
        if st is None:
            st = {
                "k0": jnp.asarray(self.k0),
                "k1": jnp.asarray(self.k1),
                "kD": jnp.asarray(self.keysD),
                "delta_mask": jnp.asarray(
                    (self.delta.astype(np.uint8) * np.uint8(0xFF))[:, None]
                ),
                "delta_packed": jnp.asarray(self.delta_packed),
            }
            self._dev_state = st
        return st

    # -- check verdicts / blame / tamper hook --------------------------------

    def _store_verdicts(self, **named: np.ndarray) -> None:
        """Merge per-check verdict arrays from the last invocation into
        ``check_verdicts``: {"kos": (B,), "gilboa": (S, B),
        "consistency": (S, B)} bool. Wire rounds fill the dict from
        both verifier roles; the in-process paths fill it in one pass."""
        v = getattr(self, "check_verdicts", None)
        if v is None:
            v = {}
        v.update(named)
        self.check_verdicts = v

    def check_blame(self) -> Optional[List[Optional[Tuple[str, str]]]]:
        """Per-lane blame from the last invocation's verdicts: None for
        a clean lane, else ("alice"|"bob", check name). KOS failure
        DOMINATES for a lane: a corrupted extension matrix garbles the
        pads, so the downstream payload checks fail as a side effect of
        Alice's deviation — attributing them to Bob would misblame.
        Returns None when checks were off (no verdicts collected)."""
        v = getattr(self, "check_verdicts", None)
        if not v:
            return None
        kos = v.get("kos")
        gil = v.get("gilboa")
        con = v.get("consistency")
        Bn = next(iter(v.values())).shape[-1]
        out: List[Optional[Tuple[str, str]]] = []
        for i in range(Bn):
            if kos is not None and not kos[i]:
                out.append(("alice", CHECK_KOS))
            elif gil is not None and not gil[:, i].all():
                out.append(("bob", CHECK_GILBOA))
            elif con is not None and not con[:, i].all():
                out.append(("bob", CHECK_CONSISTENCY))
            else:
                out.append(None)
        return out

    def set_tamper(self, spec: Optional[Dict]) -> None:
        """Install a deterministic wire corruption for the NEXT
        run_multi calls (tests / chaos drills): the leg executes the
        serial three-round composition and mutates one wire field
        between rounds — exactly what an active cheater controls.
        spec keys: field ("U" | "kos_xbar" | "kos_tbar" | "y0" | "y1" |
        "D" | "B_pt" | "Beta_pt"), lane (batch index), set (payload-set
        index, payload fields), byte (offset into the lane's slice),
        xor (mask, default 0x01). None clears."""
        self._tamper = spec

    def _verify_inprocess(
        self, tag, rows_a, rows_b, U, r_bits, b_list, z_raw, y0s, y1s,
        sels, alphas,
    ):
        """Full-width check pass for the in-process run paths: the same
        kernels the wire rounds run, fed the same wire tensors, so the
        verdicts are bit-identical to the three-round composition
        (host or device arrays accepted — jnp.asarray is a no-op on
        device residents)."""
        r_bits_d = jnp.asarray(r_bits)
        U_d = jnp.asarray(U)
        kos_pref = _fs_prefixes(tag, b"kos")
        xbar, tbar = _k_kos_tags(
            jnp.asarray(rows_a), r_bits_d, U_d, *kos_pref
        )
        kos_ok = _k_kos_verify(
            jnp.asarray(rows_b), jnp.asarray(self.delta), U_d,
            xbar, tbar, *kos_pref,
        )
        g_oks, c_oks = [], []
        for s, b_s in enumerate(b_list):
            pref = _fs_prefixes(tag, b"gilboa", s)
            y0_d, y1_d = jnp.asarray(y0s[s]), jnp.asarray(y1s[s])
            z_red = _reduce_bytes(jnp.asarray(z_raw[s]))
            D_b, B_comp, Beta_comp = _k_gilboa_bob(
                y0_d, y1_d, z_red, b_s, *pref
            )
            Bn = b_s.shape[0]
            msel = jnp.asarray(sels[s]).reshape(Bn, NBITS, 32)
            g_oks.append(_k_gilboa_alice(
                y0_d, y1_d, msel, r_bits_d, D_b, B_comp, *pref
            ))
            c_oks.append(_k_consistency(
                alphas[s], r_bits_d, B_comp, Beta_comp
            ))
        self.check_verdicts = {
            "kos": np.asarray(kos_ok),  # mpcflow: host-ok — check verdicts are the abort decision (B bools per extension)
            "gilboa": np.stack([np.asarray(g) for g in g_oks]),  # mpcflow: host-ok — check verdicts are the abort decision (S·B bools per extension)
            "consistency": np.stack([np.asarray(c) for c in c_oks]),  # mpcflow: host-ok — check verdicts are the abort decision (S·B bools per extension)
        }

    # -- chunk-granular extension stages (host side) -------------------------
    #
    # Each stage covers lanes [blk_off, blk_off + Bc) of the batch — a
    # contiguous 32-byte-block range of every PRG stream and a
    # contiguous column range of the extension matrix — so running them
    # chunk-by-chunk produces byte-identical transcripts to the
    # full-width call: chunking (and the threading underneath) changes
    # scheduling only, never values.

    def _ext_alice_chunk(self, tag: bytes, r_packed_c, blk_off: int, Bc: int):
        """PRG-expand the Alice half for one chunk → (t0_c, U_c), each
        (κ, Bc·32). U is assembled in place in the t1 buffer (native
        threaded xor when built) — no fresh temporaries."""
        from ... import native

        t0 = _prg(self.k0, Bc * 32, tag, blk_off)
        t1 = _prg(self.k1, Bc * 32, tag, blk_off)
        native.xor_rows(t1, t0)          # t1 ← t0 ^ t1
        native.xor_rows(t1, r_packed_c)  # ... ^ r (row broadcast)
        return t0, t1

    def _ext_bob_chunk(self, tag: bytes, U_c, blk_off: int, Bc: int):
        """PRG-expand Bob's half for one chunk and fold in Alice's U on
        the Δ=1 rows → Q_c (κ, Bc·32), built in place in the tD
        buffer (the old path materialized a full (κ, M/8) mask and two
        temporaries)."""
        tD = _prg(self.keysD, Bc * 32, tag, blk_off)
        for r in self._delta_rows:
            tD[r] ^= U_c[r]  # in-place row view, no temp
        return tD

    def _pads_chunk(self, tag, n_sets, t0_c, Qm_c, m_off, m_count):
        """Transpose + pad hashing for one chunk, both roles, every
        payload set. → (padsA: [pad_s], padsB: [(pad0_s, pad1_s)])."""
        prefixes = self._pad_prefixes(tag, n_sets)
        padsA = _derive_pads_multi(prefixes, t0_c, m_count, m_off=m_off)
        padsB = _derive_pads_multi(
            prefixes, Qm_c, m_count, delta=self.delta_packed, m_off=m_off
        )
        return padsA, padsB

    # -- Alice ---------------------------------------------------------------

    def alice_round1(self, a: jnp.ndarray, ctr: int) -> Dict:
        """``a``: (B, n) scalars mod q. → {"U": (κ, M/8), "v"} to Bob —
        plus the KOS correlation tags {"kos_xbar", "kos_tbar"} when
        checks are on (χ is FS-derived from U, so no extra round);
        local state kept for round 3."""
        B = a.shape[0]
        M = B * NBITS
        r_bits = np.asarray(_bits_256(a)).astype(np.uint8).reshape(M)  # mpcflow: host-ok — choice bits feed the host-side OT extension (ROADMAP: IKNP on device)
        tag = self._ext_tag(ctr)
        t0, U = self._ext_alice_chunk(tag, _pack(r_bits), 0, B)
        self._alice_state = (t0, r_bits, B, tag)
        self.check_verdicts = None
        msg = {"U": U, "v": OT_WIRE_VERSION}
        if ot_checks_enabled():
            xbar, tbar = _k_kos_tags(
                hs.ot_transpose_device(jnp.asarray(t0)),
                jnp.asarray(r_bits), jnp.asarray(U),
                *_fs_prefixes(tag, b"kos"),
            )
            msg["kos_xbar"] = np.asarray(xbar)  # mpcflow: host-ok — KOS tags are wire bytes (B·(κ/8+κ²/8) per extension)
            msg["kos_tbar"] = np.asarray(tbar)  # mpcflow: host-ok — KOS tags are wire bytes (B·(κ/8+κ²/8) per extension)
        return msg

    def alice_round3(self, bob_msg: Dict) -> jnp.ndarray:
        """Recover the selected payloads → Alice's additive share
        (B, n) mod q."""
        return self.alice_round3_multi((bob_msg,))[0]

    def alice_round3_multi(self, bob_msgs) -> List[jnp.ndarray]:
        """One extension, several payload sets (see bob_round2_multi):
        per-set pads come from the SAME transposed rows under
        set-separated hash domains, so each set's pads are independent
        random-oracle outputs. With checks on, verifies each set's
        Gilboa ψ-encoding and output-consistency openings against the
        RECEIVED payload bytes (verdicts → ``check_verdicts``; Alice is
        the verifier, failures blame Bob)."""
        from ... import native

        checks = ot_checks_enabled()
        for i, m in enumerate(bob_msgs):
            if m.get("v") != OT_WIRE_VERSION:
                raise ValueError(
                    f"OT-MtA wire version mismatch in bob msg {i}: got "
                    f"{m.get('v')!r}, this party speaks v{OT_WIRE_VERSION}"
                )
            if checks and "D" not in m:
                raise ValueError(
                    f"OT-MtA checks enabled but bob msg {i} carries no "
                    "Gilboa opening (peer running MPCIUM_OT_CHECKS=0?)"
                )
        t0, r_bits, B, tag = self._alice_state
        M = B * NBITS
        pad_sets = _derive_pads_multi(
            self._pad_prefixes(tag, len(bob_msgs)), t0, M
        )
        alphas = []
        g_oks, c_oks = [], []
        sel_bits = r_bits[:, None].astype(bool)
        for s, (bob_msg, pads) in enumerate(zip(bob_msgs, pad_sets)):
            sel = np.where(sel_bits, bob_msg["y1"], bob_msg["y0"])
            native.xor_rows(sel, pads)  # m_sel, in place
            alpha = _sum_mod_q(
                _reduce_bytes(jnp.asarray(sel.reshape(B, NBITS, 32)))
            )
            alphas.append(alpha)
            if checks:
                pref = _fs_prefixes(tag, b"gilboa", s)
                g_oks.append(_k_gilboa_alice(
                    jnp.asarray(bob_msg["y0"]), jnp.asarray(bob_msg["y1"]),
                    jnp.asarray(sel.reshape(B, NBITS, 32)),
                    jnp.asarray(r_bits), jnp.asarray(bob_msg["D"]),
                    jnp.asarray(bob_msg["B_pt"]), *pref,
                ))
                c_oks.append(_k_consistency(
                    alpha, jnp.asarray(r_bits),
                    jnp.asarray(bob_msg["B_pt"]),
                    jnp.asarray(bob_msg["Beta_pt"]),
                ))
        if checks:
            self._store_verdicts(
                gilboa=np.stack([np.asarray(g) for g in g_oks]),  # mpcflow: host-ok — check verdicts are the abort decision (S·B bools per extension)
                consistency=np.stack([np.asarray(c) for c in c_oks]),  # mpcflow: host-ok — check verdicts are the abort decision (S·B bools per extension)
            )
        return alphas

    # -- Bob -----------------------------------------------------------------

    def bob_round2(
        self, b_scalars: jnp.ndarray, alice_msg: Dict, ctr: int
    ) -> Tuple[Dict, jnp.ndarray]:
        """``b_scalars``: (B, n) mod q. → ({"y0", "y1", "v"} to Alice,
        Bob's additive share (B, n) mod q)."""
        msgs, betas = self.bob_round2_multi((b_scalars,), alice_msg, ctr)
        return msgs[0], betas[0]

    def bob_round2_multi(
        self, b_list, alice_msg: Dict, ctr: int
    ) -> Tuple[List[Dict], List[jnp.ndarray]]:
        """Several payload sets against ONE extension. Alice's choice
        bits (bits of ``a``) are shared across sets by construction —
        GG18 multiplies the same k_a against both γ_b and w_b — so the
        expensive extension half (t/U PRG expansion, the Q matrix) runs
        once and only the per-set payload masking repeats, under
        set-separated pad domains (`…|s0`, `…|s1`: independent RO
        outputs from the same rows). With checks on, verifies Alice's
        KOS correlation tags against the received U (verdict →
        ``check_verdicts``; Bob is the verifier, failure blames Alice)
        and attaches each set's Gilboa opening {"D", "B_pt",
        "Beta_pt"}."""
        from ... import native

        checks = ot_checks_enabled()
        b_list = tuple(b_list)
        if any(b.shape != b_list[0].shape for b in b_list):
            raise ValueError(
                "bob_round2_multi: payload sets disagree on batch shape: "
                f"{[tuple(b.shape) for b in b_list]}"
            )
        if alice_msg.get("v") != OT_WIRE_VERSION:
            # mpclint: disable=MPF702 — the formatted value is the public wire-version field (a small int every peer sees), not the PRG-derived tensors that taint the message dict
            raise ValueError(
                f"OT-MtA wire version mismatch: alice msg carries "
                f"{alice_msg.get('v')!r}, this party speaks "
                f"v{OT_WIRE_VERSION} (mixed-version quorum?)"
            )
        if checks and "kos_xbar" not in alice_msg:
            raise ValueError(
                "OT-MtA checks enabled but alice msg carries no KOS "
                "tags (peer running MPCIUM_OT_CHECKS=0?)"
            )
        B = b_list[0].shape[0]
        M = B * NBITS
        tag = self._ext_tag(ctr)
        Qm = self._ext_bob_chunk(tag, alice_msg["U"], 0, B)
        if checks:
            kos_ok = _k_kos_verify(
                hs.ot_transpose_device(jnp.asarray(Qm)),
                jnp.asarray(self.delta), jnp.asarray(alice_msg["U"]),
                jnp.asarray(alice_msg["kos_xbar"]),
                jnp.asarray(alice_msg["kos_tbar"]),
                *_fs_prefixes(tag, b"kos"),
            )
            self._store_verdicts(kos=np.asarray(kos_ok))  # mpcflow: host-ok — check verdicts are the abort decision (B bools per extension)
        pad_sets = _derive_pads_multi(
            self._pad_prefixes(tag, len(b_list)), Qm, M,
            delta=self.delta_packed,
        )
        msgs, betas = [], []
        for s, (b_scalars, (pad0, pad1)) in enumerate(
            zip(b_list, pad_sets)
        ):
            # payloads: z and z + 2^i·b (mod q), z freshly random per OT
            z_raw = np.frombuffer(
                self.rng.token_bytes(M * 32), np.uint8
            ).reshape(B, NBITS, 32)
            z_red = _reduce_bytes(jnp.asarray(z_raw))  # (B, NBITS, n)
            m1 = np.asarray(_m1_payloads(z_red, _pow2_ladder(b_scalars)))  # mpcflow: host-ok — OT payloads are pad-masked on host before the wire (ROADMAP: IKNP on device)
            m0 = np.asarray(bn.limbs_to_bytes_le(z_red, P256, 32))  # mpcflow: host-ok — OT payloads are pad-masked on host before the wire (ROADMAP: IKNP on device)
            # mask INTO the pad buffers (ours, writable, dead after)
            y0 = native.xor_rows(pad0, m0.reshape(M, 32))
            y1 = native.xor_rows(pad1, m1.reshape(M, 32))
            msg = {"y0": y0, "y1": y1, "v": OT_WIRE_VERSION}
            if checks:
                D_b, B_comp, Beta_comp = _k_gilboa_bob(
                    jnp.asarray(y0), jnp.asarray(y1), z_red, b_scalars,
                    *_fs_prefixes(tag, b"gilboa", s),
                )
                msg["D"] = np.asarray(D_b)  # mpcflow: host-ok — Gilboa openings are wire bytes (B·98 per set)
                msg["B_pt"] = np.asarray(B_comp)  # mpcflow: host-ok — Gilboa openings are wire bytes (B·98 per set)
                msg["Beta_pt"] = np.asarray(Beta_comp)  # mpcflow: host-ok — Gilboa openings are wire bytes (B·98 per set)
            msgs.append(msg)
            betas.append(_neg_sum_mod_q(z_red))
        return msgs, betas

    # -- in-process convenience (the engine path) ----------------------------

    def run(
        self, a: jnp.ndarray, b: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Both roles locally: → (alice_share, bob_share), (B, n) each,
        with alice_share + bob_share ≡ a·b (mod q) per lane."""
        (pair,) = self.run_multi(a, (b,))
        return pair

    def run_multi(
        self,
        a: jnp.ndarray,
        b_list,
        chunks: Optional[int] = None,
        timings: Optional[Dict[str, float]] = None,
        transcript: Optional[list] = None,
    ):
        """Both roles locally, several Bob scalars against one ``a``
        (ONE extension): → [(alpha_s, beta_s)] with
        alpha_s + beta_s ≡ a·b_s (mod q) per lane.

        Two implementations, bit-identical transcripts (the z draw
        order, PRG block schedule and pad domains are shared, so the
        wire bytes cannot differ — tests/test_mta_ot_device.py):

        * **Device** (default; ``device_path_enabled``): the whole
          extension — PRG, transpose, pads, masking, selection — fuses
          into one jitted dispatch per chunk (``_ot_chunk_device``).
          The host stage degenerates to wire-byte packing; nothing is
          pulled off device in the hot loop.
        * **Host/native** (MPCIUM_OT_DEVICE=0, or > 10 payload sets):
          pipelined double-buffer. The batch is split into ``chunks``
          sub-batches (resolve_chunks — MPCIUM_OT_CHUNKS / auto), all
          device-side payload math is dispatched asynchronously up
          front, and every chunk's host extension work (PRG expansion,
          transpose, pad hashing) is enqueued on the background worker
          BEFORE any device array is blocked on. Chunking changes
          scheduling only: results and transcripts are bit-identical
          to the serial three-round composition for every chunk count.

        ``timings`` (optional dict) accumulates host_s (worker busy
        time), device_wait_s / host_wait_s (main-thread blocking) and
        total_s — the bench's overlap instrumentation; the device path
        reports total_s only (there is no host stage to time).
        ``transcript`` (optional list; device path only) receives one
        {"U", "y0", "y1"} dict of host arrays per chunk — the wire
        bytes, for oracle comparison in tests."""
        from ... import native

        b_list = tuple(b_list)
        B = a.shape[0]
        if any(b.shape != b_list[0].shape for b in b_list):
            raise ValueError(
                "run_multi: payload sets disagree on batch shape: "
                f"{[tuple(b.shape) for b in b_list]}"
            )
        self.check_verdicts = None  # per-invocation; the check pass refills
        if getattr(self, "_tamper", None) is not None:
            return self._run_multi_tampered(a, b_list)
        K = resolve_chunks(B, chunks)
        ctr = self.ctr
        self.ctr += 1
        tag = self._ext_tag(ctr)
        M = B * NBITS
        t_total0 = time.perf_counter()
        t_span0 = tracing.now_ns()

        # z randomness: one serial-order draw per payload set — the
        # exact stream positions of the unchunked path (bit-exactness
        # under a deterministic rng) and the only rng use, so neither
        # the worker thread nor the device path perturbs the stream.
        z_raw = [
            np.frombuffer(self.rng.token_bytes(M * 32), np.uint8)
            .reshape(B, NBITS, 32)
            for _ in b_list
        ]

        # > 10 sets would ragged-stack the pad prefixes (`|s10` is one
        # byte wider); no engine path comes close, but fall back loudly
        # rather than mis-shape.
        if device_path_enabled() and len(b_list) <= 10:
            return self._run_multi_device(
                a, b_list, K, tag, z_raw, timings, transcript,
                t_total0, t_span0,
            )

        r_bits = np.asarray(_bits_256(a)).astype(np.uint8).reshape(M)  # mpcflow: host-ok — host/native fallback path (MPCIUM_OT_DEVICE=0): choice bits drive the host IKNP stage; the default device path never pulls them
        r_packed = _pack(r_bits)

        Bc = B // K
        Mc = Bc * NBITS

        # device stage 1 (async dispatch; nothing is blocked on yet):
        # per (chunk, set) payload material + Bob's share
        dev = []
        for c in range(K):
            sl = slice(c * Bc, (c + 1) * Bc)
            per_set = []
            for s, b_s in enumerate(b_list):
                z_red = _reduce_bytes(jnp.asarray(z_raw[s][sl]))
                m1 = _m1_payloads(z_red, _pow2_ladder(b_s[sl]))
                m0 = bn.limbs_to_bytes_le(z_red, P256, 32)
                per_set.append((m0, m1, _neg_sum_mod_q(z_red)))
            dev.append(per_set)

        checks = ot_checks_enabled()

        def host_stage(c: int):
            t0_ = time.perf_counter()
            blk_off = c * Bc
            r_pc = r_packed[blk_off * 32:(blk_off + Bc) * 32]
            t0_c, U_c = self._ext_alice_chunk(tag, r_pc, blk_off, Bc)
            Qm_c = self._ext_bob_chunk(tag, U_c, blk_off, Bc)
            pads = self._pads_chunk(
                tag, len(b_list), t0_c, Qm_c, c * Mc, Mc
            )
            if timings is not None:
                timings["host_s"] = (
                    timings.get("host_s", 0.0)
                    + time.perf_counter() - t0_
                )
            return pads, t0_c, U_c, Qm_c

        # the double-buffer: EVERY chunk's host work is enqueued before
        # the first device array is blocked on
        futs = [_host_pool().submit(host_stage, c) for c in range(K)]

        host_wait = 0.0
        device_wait = 0.0
        alpha_pieces: List[List[jnp.ndarray]] = [[] for _ in b_list]
        beta_pieces: List[List[jnp.ndarray]] = [[] for _ in b_list]
        # per-chunk wire tensors, kept only for the check pass
        t0_cs, Qm_cs = [], []
        U_cs = []
        y_cs = [([], [], []) for _ in b_list]  # (y0, y1, sel) per set
        for c in range(K):
            t_w = time.perf_counter()
            (padsA, padsB), t0_c, U_c, Qm_c = futs[c].result()
            host_wait += time.perf_counter() - t_w
            if checks:
                t0_cs.append(t0_c)
                U_cs.append(U_c)
                Qm_cs.append(Qm_c)
            sel_bits = r_bits[c * Mc:(c + 1) * Mc, None].astype(bool)
            for s in range(len(b_list)):
                m0_d, m1_d, beta_d = dev[c][s]
                t_w = time.perf_counter()
                m0 = np.asarray(m0_d).reshape(Mc, 32)  # mpcflow: host-ok — host/native fallback path (MPCIUM_OT_DEVICE=0): payloads meet the host-derived pads here; the default device path masks on device
                m1 = np.asarray(m1_d).reshape(Mc, 32)  # mpcflow: host-ok — host/native fallback path (MPCIUM_OT_DEVICE=0): payloads meet the host-derived pads here; the default device path masks on device
                device_wait += time.perf_counter() - t_w
                pad0, pad1 = padsB[s]
                y0 = native.xor_rows(pad0, m0)
                y1 = native.xor_rows(pad1, m1)
                sel = np.where(sel_bits, y1, y0)
                native.xor_rows(sel, padsA[s])
                alpha_pieces[s].append(
                    _sum_mod_q(
                        _reduce_bytes(
                            jnp.asarray(sel.reshape(Bc, NBITS, 32))
                        )
                    )
                )
                beta_pieces[s].append(beta_d)
                if checks:
                    y_cs[s][0].append(y0)
                    y_cs[s][1].append(y1)
                    y_cs[s][2].append(sel)

        alphas = [
            p[0] if K == 1 else jnp.concatenate(p, axis=0)
            for p in alpha_pieces
        ]
        betas = [
            p[0] if K == 1 else jnp.concatenate(p, axis=0)
            for p in beta_pieces
        ]
        checks_s = 0.0
        if checks:
            t_chk = time.perf_counter()
            t0_full = np.concatenate(t0_cs, axis=1)
            Qm_full = np.concatenate(Qm_cs, axis=1)
            self._verify_inprocess(
                tag,
                hs.ot_transpose_device(jnp.asarray(t0_full)),
                hs.ot_transpose_device(jnp.asarray(Qm_full)),
                np.concatenate(U_cs, axis=1), r_bits, b_list, z_raw,
                [np.concatenate(ys[0], axis=0) for ys in y_cs],
                [np.concatenate(ys[1], axis=0) for ys in y_cs],
                [np.concatenate(ys[2], axis=0) for ys in y_cs],
                alphas,
            )
            checks_s = time.perf_counter() - t_chk
        if timings is not None:
            timings["checks_s"] = (
                timings.get("checks_s", 0.0) + checks_s
            )
        if timings is not None:
            timings["host_wait_s"] = (
                timings.get("host_wait_s", 0.0) + host_wait
            )
            timings["device_wait_s"] = (
                timings.get("device_wait_s", 0.0) + device_wait
            )
            timings["total_s"] = (
                timings.get("total_s", 0.0)
                + time.perf_counter() - t_total0
            )
        # mpctrace: one span per extension with the overlap split as
        # public attrs (no-op unless tracing is armed)
        tracing.emit(
            "phase:ot_extension", t_span0, tracing.now_ns(),
            node="engine", tid=f"ot:B{B}",
            host_wait_s=round(host_wait, 6),
            device_wait_s=round(device_wait, 6),
            chunks=K, sets=len(b_list), checks=checks,
        )
        return list(zip(alphas, betas))

    def _run_multi_tampered(self, a, b_list):
        """Chaos/test path (``set_tamper``): the serial three-round wire
        composition with one deterministic corruption applied to the
        cheating party's outbound message — alice fields (U, KOS tags)
        before Bob's round 2, bob fields (payloads, openings) before
        Alice's round 3 — so the verdicts exercised are exactly the
        receiving verifier's, on real wire bytes."""
        spec = self._tamper
        ctr = self.ctr
        self.ctr += 1
        msg_a = self.alice_round1(a, ctr)
        applied = _apply_tamper(spec, msg_a)
        msgs_b, betas = self.bob_round2_multi(b_list, msg_a, ctr)
        if not applied:
            target = msgs_b[int(spec.get("set", 0))]
            if not _apply_tamper(spec, target):
                raise ValueError(
                    f"tamper field {spec['field']!r} absent from both "
                    "rounds (checks disabled?)"
                )
        alphas = self.alice_round3_multi(msgs_b)
        return list(zip(alphas, betas))

    def _run_multi_device(
        self, a, b_list, K, tag, z_raw, timings, transcript,
        t_total0, t_span0,
    ):
        """Device extension driver (see run_multi): per chunk, dispatch
        the payload math then the fused `_ot_chunk_device` kernel. The
        host never sees the extension matrices, pads or choice bits —
        only the optional ``transcript`` capture (tests) and the final
        shares cross the wire boundary. Chunk boundaries are the same
        PRG-block / OT-index origins as the host path, so the K=1/2/4
        transcripts are all identical to the serial composition."""
        B = a.shape[0]
        M = B * NBITS
        Bc = B // K
        Mc = Bc * NBITS
        n_sets = len(b_list)
        dev = self._device_state()
        prg_prefix = jnp.asarray(
            np.frombuffer(b"mpcium-ot-prg|" + tag, np.uint8)
        )
        pad_prefixes = jnp.asarray(
            np.frombuffer(
                b"".join(self._pad_prefixes(tag, n_sets)), np.uint8
            ).reshape(n_sets, -1)
        )
        r_bits_d = _bits_256(a).astype(jnp.uint8).reshape(M)
        r_packed_d = hs.pack_bits_core(r_bits_d)

        checks = ot_checks_enabled()
        alpha_pieces: List[List[jnp.ndarray]] = [[] for _ in b_list]
        beta_pieces: List[List[jnp.ndarray]] = [[] for _ in b_list]
        rows_a_cs, rows_b_cs, U_cs = [], [], []
        sel_cs: List[List[jnp.ndarray]] = [[] for _ in b_list]
        y_cs: List[Tuple[List, List]] = [([], []) for _ in b_list]
        for c in range(K):
            sl = slice(c * Bc, (c + 1) * Bc)
            m0s, m1s = [], []
            for s, b_s in enumerate(b_list):
                z_red = _reduce_bytes(jnp.asarray(z_raw[s][sl]))
                m1s.append(_m1_payloads(z_red, _pow2_ladder(b_s[sl])))
                m0s.append(bn.limbs_to_bytes_le(z_red, P256, 32))
                beta_pieces[s].append(_neg_sum_mod_q(z_red))
            alphas_c, U_c, y0s_c, y1s_c, rows_a_c, rows_b_c, sels_c = (
                _ot_chunk_device(
                    dev["k0"], dev["k1"], dev["kD"], dev["delta_mask"],
                    dev["delta_packed"], prg_prefix, pad_prefixes,
                    r_bits_d[c * Mc:(c + 1) * Mc],
                    r_packed_d[c * Bc * 32:(c + 1) * Bc * 32],
                    jnp.stack(m0s), jnp.stack(m1s),
                    jnp.uint32(c * Bc), jnp.uint32(c * Mc),
                )
            )
            for s in range(n_sets):
                alpha_pieces[s].append(alphas_c[s])
            if checks:
                rows_a_cs.append(rows_a_c)
                rows_b_cs.append(rows_b_c)
                U_cs.append(U_c)
                for s in range(n_sets):
                    sel_cs[s].append(sels_c[s])
                    y_cs[s][0].append(y0s_c[s])
                    y_cs[s][1].append(y1s_c[s])
            if transcript is not None:
                transcript.append({
                    "U": np.asarray(U_c),  # mpcflow: host-ok — transcript-oracle capture (tests only; None in production)
                    "y0": [np.asarray(y0s_c[s]) for s in range(n_sets)],  # mpcflow: host-ok — transcript-oracle capture (tests only; None in production)
                    "y1": [np.asarray(y1s_c[s]) for s in range(n_sets)],  # mpcflow: host-ok — transcript-oracle capture (tests only; None in production)
                })

        alphas = [
            p[0] if K == 1 else jnp.concatenate(p, axis=0)
            for p in alpha_pieces
        ]
        betas = [
            p[0] if K == 1 else jnp.concatenate(p, axis=0)
            for p in beta_pieces
        ]
        if checks:
            t_chk = time.perf_counter()
            self._verify_inprocess(
                tag,
                jnp.concatenate(rows_a_cs, axis=0),
                jnp.concatenate(rows_b_cs, axis=0),
                jnp.concatenate(U_cs, axis=1),
                r_bits_d, b_list, z_raw,
                [jnp.concatenate(ys[0], axis=0) for ys in y_cs],
                [jnp.concatenate(ys[1], axis=0) for ys in y_cs],
                [jnp.concatenate(p, axis=0) for p in sel_cs],
                alphas,
            )
            if timings is not None:
                timings["checks_s"] = (
                    timings.get("checks_s", 0.0)
                    + time.perf_counter() - t_chk
                )
        if timings is not None:
            timings["total_s"] = (
                timings.get("total_s", 0.0)
                + time.perf_counter() - t_total0
            )
        tracing.emit(
            "phase:ot_extension", t_span0, tracing.now_ns(),
            node="engine", tid=f"ot:B{B}",
            host_wait_s=0.0, device_wait_s=0.0,
            chunks=K, sets=n_sets, device=True, checks=checks,
        )
        return list(zip(alphas, betas))
