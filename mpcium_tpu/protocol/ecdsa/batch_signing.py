"""Distributed batched GG18 threshold-ECDSA signing: ONE protocol instance
signs B wallets' digests concurrently.

This is the secp256k1 face of the TPU batch engine (SURVEY.md §7.2 step 5)
— the distributed counterpart of the in-process measurement fabric
:class:`engine.gg18_batch.GG18BatchCoSigners`, and the batch analogue of
the per-session :class:`.signing.ECDSASigningParty` (reference
ecdsa_signing_session.go drives one tss-lib party per tx). Each quorum
member exchanges fixed-shape BYTE BLOCKS (B-row limb serializations) and
computes every round with the engine's jitted device kernels; the
scheduler (consumers.batch_scheduler) buckets concurrent requests into
these batches.

Wire schedule (9 network rounds — the same round structure as GG18,
reference ecdsa_rounds.go:16-25):

  R1  broadcast  Γ-commitment block + Enc_i(k_i) ciphertext block
      unicast→j  MtA range proof of Enc_i(k_i) in j's ring
  R2  unicast→j  MtA responses (γ and w legs): c_b + range proofs
  R3  broadcast  δ_i block (after verifying responses + CRT decrypting)
  R4  broadcast  Γ_i decommit + Schnorr PoK of γ_i
  R5  broadcast  phase-5A (V_i, A_i) commitment block
  R6  broadcast  5B decommit + Pedersen PoK of (s_i, l_i)
  R7  broadcast  5C (U_i, T_i) commitment block
  R8  broadcast  5D decommit
  R9  broadcast  partial-signature block s_i
  finalize       combine, low-s normalize, batched ECDSA verify → ok mask

Per-lane semantics: proof/commitment failures mark only their wallet's
lane false (the result carries a per-session ok mask); structural
violations (bad block sizes, equivocation) abort the batch with the
culprit attributed, like the per-session protocol.

All wallets in a batch must share (participants, threshold, epoch) AND
the quorum's Paillier/ring-Pedersen material (see
:func:`quorum_material_digest` — the scheduler buckets on it): the engine
builds one modulus context per party.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ... import wire
from ...core import bignum as bn
from ...core import hostmath as hm
from ...core import secp256k1_jax as sp
from ...core.bignum import P256
from ...core.paillier import PaillierPrivateKey, PreParams
from ...engine import gg18_batch as gb
from ...engine import pipeline as pl
from ...ops.paillier_mxu import RAND_BITS
from ...perf import compile_watch
from ..base import (BatchBlockMixin, KeygenShare, PartyBase, ProtocolError,
                    RoundMsg, party_xs)

Q = hm.SECP_N

R1B = "gg18/b/1/commit"
R1A = "gg18/b/1/rangeproof"
R2 = "gg18/b/2/respond"
R3 = "gg18/b/3/delta"
R4 = "gg18/b/4/decommit"
R5 = "gg18/b/5/va-commit"
R6 = "gg18/b/6/va-reveal"
R7 = "gg18/b/7/ut-commit"
R8 = "gg18/b/8/ut-reveal"
R9 = "gg18/b/9/partial"


def quorum_material_digest(share: KeygenShare) -> str:
    """Digest of the committee's shared Paillier/ring-Pedersen material.
    Equal across the quorum's nodes for wallets created by the same
    committee generation — the scheduler's batch-homogeneity key (one
    modulus context set per batch)."""
    aux = share.aux
    if not aux or "paillier_sk" not in aux:
        return ""
    sk = aux["paillier_sk"]
    own_n = int(sk["p"]) * int(sk["q"])
    mat = {
        "paillier": dict(aux.get("peer_paillier", {})),
        "ring": {
            pid: dict(rp)
            for pid, rp in aux.get("peer_ring_pedersen", {}).items()
        },
    }
    mat["paillier"][share_owner_key(share)] = str(own_n)
    mat["ring"][share_owner_key(share)] = dict(aux["preparams"])
    return hashlib.sha256(wire.canonical_json(mat)).hexdigest()


def share_owner_key(share: KeygenShare) -> str:
    """The owning party's ID, recovered from self_x within the sorted
    participant universe."""
    xs = party_xs(share.participants)
    for pid, x in xs.items():
        if x == share.self_x:
            return pid
    raise ProtocolError("share self_x not in participant universe")


def _nb(prof: bn.LimbProfile) -> int:
    return -(-prof.n_limbs * prof.bits // 8)


def _ser(x: jnp.ndarray, prof: bn.LimbProfile) -> str:
    return np.asarray(bn.limbs_to_bytes_le(x, prof, _nb(prof))).tobytes().hex()  # mpcflow: host-ok — wire serialization


def _ser_bytes(arr) -> str:
    return np.asarray(arr).tobytes().hex()


class BatchedECDSASigningParty(BatchBlockMixin, PartyBase):
    """One signer's side of a B-session GG18 batch.

    ``shares``: this node's per-wallet key shares (manifest order —
    identical on every quorum member). ``digests``: the B 32-byte
    transaction digests. All shares must come from one committee
    generation (same participants/threshold/epoch/aux material)."""

    def __init__(
        self,
        session_id: str,
        self_id: str,
        party_ids: Sequence[str],
        shares: Sequence[KeygenShare],
        digests: Sequence[bytes],
        dom: gb.Domains = gb.Domains(),
        rng=None,
        cohorts: Optional[int] = None,
    ):
        import secrets as _secrets

        super().__init__(session_id, self_id, party_ids, rng or _secrets)
        if len(shares) != len(digests) or not shares:
            raise ValueError("one share per digest required")
        self.B = len(shares)
        self.dom = dom
        first = shares[0]
        digest0 = quorum_material_digest(first)
        if not digest0:
            raise ProtocolError("shares carry no GG18 aux material")
        universe = list(first.participants)
        u_xs = party_xs(universe)
        for s in shares:
            if s.key_type != "secp256k1":
                raise ProtocolError("wrong key type for GG18 batch signing")
            if s.participants != first.participants:
                raise ProtocolError("mixed keygen universes in one batch")
            if s.threshold != first.threshold or s.epoch != first.epoch:
                raise ProtocolError("mixed threshold/epoch in one batch")
            if s.self_x != u_xs[self_id]:
                raise ProtocolError("share does not belong to this node")
            if len(s.vss_commitments) != s.threshold + 1:
                raise ProtocolError("missing VSS commitments on share")
            if quorum_material_digest(s) != digest0:
                raise ProtocolError("mixed Paillier material in one batch")
        if len(self.party_ids) < first.threshold + 1:
            raise ProtocolError("not enough participants for threshold")
        for pid in self.party_ids:
            if pid not in u_xs:
                raise ProtocolError("signer not in keygen universe", pid)

        aux = first.aux
        sk = PaillierPrivateKey.from_json(aux["paillier_sk"])
        rp = {k: int(v) for k, v in aux["preparams"].items()}
        own_pre = PreParams(
            paillier=sk, NTilde=rp["ntilde"], h1=rp["h1"], h2=rp["h2"],
            alpha=0, beta=0, P=0, Q=0,
        )
        self.own = gb.PartyCtx(self_id, own_pre, rng=self.rng)
        self.peers: Dict[str, gb.PartyCtx] = {}
        peer_pk = aux.get("peer_paillier", {})
        peer_rp = aux.get("peer_ring_pedersen", {})
        for pid in self.others():
            if pid not in peer_pk or pid not in peer_rp:
                raise ProtocolError("missing peer Paillier material", pid)
            prp = {k: int(v) for k, v in peer_rp[pid].items()}
            self.peers[pid] = gb.PartyCtx.public(
                pid, int(peer_pk[pid]), prp["ntilde"], prp["h1"], prp["h2"],
                rng=self.rng,
            )
        self._ctx = {self_id: self.own, **self.peers}
        # ordered-pair MtA contexts: out = self as Alice, in = self as Bob
        self.mta_out = {
            j: gb.MtaBatch(self.own, self.peers[j], dom)
            for j in self.others()
        }
        self.mta_in = {
            j: gb.MtaBatch(self.peers[j], self.own, dom)
            for j in self.others()
        }

        # quorum Shamir data (shared across the batch: one universe)
        quorum_xs = [u_xs[p] for p in self.party_ids]
        self._lam = {
            pid: hm.lagrange_coeff(quorum_xs, u_xs[pid], Q)
            for pid in self.party_ids
        }
        self._uxs = u_xs
        w_ints = [self._lam[self_id] * s.share % Q for s in shares]
        self._w = jnp.asarray(bn.batch_to_limbs(w_ints, P256))

        # public per-wallet data on device: Y and every member's W_j
        pub_comp = jnp.asarray(
            np.stack([
                np.frombuffer(s.public_key, dtype=np.uint8) for s in shares
            ])
        )
        self.Y, okY = sp.decompress(pub_comp)
        C_comp = jnp.asarray(
            np.stack([
                np.stack([
                    np.frombuffer(c, dtype=np.uint8)
                    for c in s.vss_commitments
                ])
                for s in shares
            ]).transpose(1, 0, 2)  # (t+1, B, 33)
        )
        self.W_pts: Dict[str, sp.SecpPointJ] = {}
        self._ok = okY
        for pid in self.party_ids:
            lam_bits = jnp.asarray(
                sp.scalars_to_bits([self._lam[pid]])[0]
            )
            # mpclint: disable=MPS902 — intentional: q executables total (one per quorum member's Shamir x, config-bounded); lam_bits stays traced so the batch dim shares one compile
            W, okW = gb._blk_W_from_vss(C_comp, u_xs[pid], lam_bits)
            self.W_pts[pid] = W
            self._ok = self._ok & okW

        self.ring = sp.scalar_ring()
        digs = np.stack([
            np.frombuffer(bytes(d), dtype=np.uint8) for d in digests
        ])
        if digs.shape[-1] != 32:
            raise ProtocolError("digests must be 32 bytes")
        self.m = self.ring.reduce(
            bn.bytes_to_limbs_le(jnp.asarray(digs[:, ::-1].copy()), P256, 22)
        )
        # counter-phase cohort geometry for the finalize round (the nine
        # wire rounds stay full-batch: their proofs/rng draws are ordered
        # per peer, and the wire transcript must not depend on K)
        self._plan = pl.CohortPlan.for_batch(self.B, cohorts)
        self._stage = 0

    # -- serialization helpers ----------------------------------------------

    # binding row + block parsing come from protocol.base.BatchBlockMixin
    # (shared with batch_dkg: one definition of the security-relevant
    # session+sender binding, so the two cannot drift)
    _parse_bytes = BatchBlockMixin._parse_block

    def _parse_limbs(
        self, hexstr: str, prof: bn.LimbProfile, pid: str
    ) -> jnp.ndarray:
        arr = self._parse_bytes(hexstr, _nb(prof), pid)
        return bn.bytes_to_limbs_le(jnp.asarray(arr), prof, prof.n_limbs)

    def _ser_scalar(self, x: jnp.ndarray) -> str:
        return _ser_bytes(sp.pack_be_32(x))

    def _parse_scalar(self, hexstr: str, pid: str) -> jnp.ndarray:
        arr = self._parse_bytes(hexstr, 32, pid)
        return self.ring.reduce(
            bn.bytes_to_limbs_le(jnp.asarray(arr[:, ::-1].copy()), P256, 22)
        )

    def _parse_point_block(
        self, hexstr: str, pid: str
    ) -> Tuple[jnp.ndarray, sp.SecpPointJ]:
        comp = self._parse_bytes(hexstr, 33, pid)
        pts, ok = sp.decompress(jnp.asarray(comp))
        self._ok = self._ok & ok
        return jnp.asarray(comp), pts

    # -- round 1 ------------------------------------------------------------

    def start(self) -> List[RoundMsg]:
        B, q = self.B, len(self.party_ids)
        # mpcshape: unbounded-ok — B is pow-2 snapped upstream (scheduler chunks via engine/buckets.floor_bucket; bench via bucket_b)
        self._cw = compile_watch.begin("party.ecdsa", f"B{B}|q{q}")
        rb = gb.rand_bits
        self._k = gb._scalar_from_wide_bytes(jnp.asarray(rb(B, 320, self.rng)))
        self._gamma = gb._scalar_from_wide_bytes(
            jnp.asarray(rb(B, 320, self.rng))
        )
        self._gblind = jnp.asarray(rb(B, 256, self.rng))
        Gam, Gam_comp, commit = gb._blk_gamma(
            self._gamma, self._gblind, self._bind_row(self.self_id)
        )
        self._Gamma_own = Gam
        self._Gamma_comp = Gam_comp
        u_bits = gb.rand_bit_tensor(B, RAND_BITS, self.rng)
        kp = gb._scalar_to_plain(self.own.pmx, self._k)
        c_k, _r = self.own.pmx.encrypt(kp, u_bits)
        self._c_k = c_k
        self._kp = kp
        out = [
            self.broadcast(
                R1B,
                {
                    "gc": _ser_bytes(commit),
                    "ck": _ser(c_k, self.own.pmx.prof_n2),
                },
            )
        ]
        self._alice_beta: Dict[Tuple[str, str], jnp.ndarray] = {}
        for j in self.others():
            mta = self.mta_out[j]
            Ra = mta.alice_randoms(B, self.rng)
            T = mta.alice_init(kp, Ra)
            e = mta.e_limbs(mta.alice_challenge(c_k, T))
            P = mta.alice_finish(e, kp, Ra, u_bits)
            nt_j = self.peers[j].ctx_nt.prof
            out.append(
                self.unicast(
                    j,
                    R1A,
                    {
                        "z": _ser(T["z"], nt_j),
                        "u": _ser(T["u"], self.own.pmx.prof_n2),
                        "w": _ser(T["w"], nt_j),
                        "s": _ser(P["s"], self.own.pmx.prof_n),
                        "s1": _ser(P["s1"], mta.p_s1),
                        "s2": _ser(P["s2"], mta.p_s2),
                    },
                )
            )
        self._stage = 1
        return out

    # -- driver --------------------------------------------------------------

    def receive(self, msg: RoundMsg) -> List[RoundMsg]:
        if self.done:
            return []
        self._store(msg)
        others = self.others()
        out: List[RoundMsg] = []
        if (
            self._stage == 1
            and self._round_full(R1B, others)
            and self._round_full(R1A, others)
        ):
            out.extend(self._respond())
            self._stage = 2
        if self._stage == 2 and self._round_full(R2, others):
            out.append(self._delta())
            self._stage = 3
        if self._stage == 3 and self._round_full(R3, others):
            out.append(self._decommit_gamma())
            self._stage = 4
        if self._stage == 4 and self._round_full(R4, others):
            out.append(self._phase5a())
            self._stage = 5
        if self._stage == 5 and self._round_full(R5, others):
            out.append(self._phase5b())
            self._stage = 6
        if self._stage == 6 and self._round_full(R6, others):
            out.append(self._phase5c())
            self._stage = 7
        if self._stage == 7 and self._round_full(R7, others):
            out.append(self._phase5d())
            self._stage = 8
        if self._stage == 8 and self._round_full(R8, others):
            out.append(self._partial())
            self._stage = 9
        if self._stage == 9 and self._round_full(R9, others):
            self._finalize()
        return out

    # -- round 2: Bob side ---------------------------------------------------

    def _peer_ck(self, j: str) -> jnp.ndarray:
        return self._parse_limbs(
            self._round_payloads(R1B)[j]["ck"], self.peers[j].pmx.prof_n2, j
        )

    def _respond(self) -> List[RoundMsg]:
        B = self.B
        out = []
        self._peer_c_k: Dict[str, jnp.ndarray] = {}
        for j in self.others():
            mta = self.mta_in[j]  # alice = j, bob = self
            c_a = self._peer_ck(j)
            self._peer_c_k[j] = c_a
            p = self._round_payloads(R1A)[j]
            nt_own = self.own.ctx_nt.prof
            T = {
                "z": self._parse_limbs(p["z"], nt_own, j),
                "u": self._parse_limbs(p["u"], self.peers[j].pmx.prof_n2, j),
                "w": self._parse_limbs(p["w"], nt_own, j),
            }
            P = {
                "s": self._parse_limbs(p["s"], self.peers[j].pmx.prof_n, j),
                "s1": self._parse_limbs(p["s1"], mta.p_s1, j),
                "s2": self._parse_limbs(p["s2"], mta.p_s2, j),
            }
            e = mta.e_limbs(mta.alice_challenge(c_a, T))
            self._ok = self._ok & mta.bob_check_alice(c_a, T, P, e, self.rng)
            payload = {}
            for name, secret in (("gamma", self._gamma), ("w", self._w)):
                Rb = mta.bob_randoms(B, self.rng)
                b_e = gb._scalar_to_prof(secret, mta.p_e)
                Tb = mta.bob_respond(c_a, b_e, Rb)
                extra = ()
                if name == "w":
                    alpha_q = gb._mod_q_from_limbs(Rb["alpha"], mta.p_alpha)
                    _U_pt, U_comp = gb._base_mul_compressed(alpha_q)
                    X_comp = sp.compress(self.W_pts[self.self_id])
                    extra = (U_comp, X_comp)
                    payload["w_U"] = _ser_bytes(U_comp)
                e_b = mta.e_limbs(mta.bob_challenge(c_a, Tb, extra))
                Pb = mta.bob_finish(e_b, b_e, Rb)
                self._alice_beta[(j, name)] = self.ring.negmod(
                    gb._mod_q_from_limbs(Rb["beta_prime"], mta.p_bp)
                )
                nt_j = self.peers[j].ctx_nt.prof
                n2_j = self.peers[j].pmx.prof_n2
                payload.update(
                    {
                        f"{name}_cb": _ser(Tb["c_b"], n2_j),
                        f"{name}_z": _ser(Tb["z"], nt_j),
                        f"{name}_zp": _ser(Tb["z_p"], nt_j),
                        f"{name}_t": _ser(Tb["t"], nt_j),
                        f"{name}_v": _ser(Tb["v"], n2_j),
                        f"{name}_w": _ser(Tb["w"], nt_j),
                        f"{name}_s": _ser(Pb["s"], self.peers[j].pmx.prof_n),
                        f"{name}_s1": _ser(Pb["s1"], mta.p_s1),
                        f"{name}_s2": _ser(Pb["s2"], mta.p_s2),
                        f"{name}_t1": _ser(Pb["t1"], mta.p_t1),
                        f"{name}_t2": _ser(Pb["t2"], mta.p_s2),
                    }
                )
            out.append(self.unicast(j, R2, payload))
        return out

    # -- round 3: Alice verifies + decrypts, broadcasts δ_i ------------------

    def _delta(self) -> RoundMsg:
        ring = self.ring
        alpha: Dict[Tuple[str, str], jnp.ndarray] = {}
        for j in self.others():
            mta = self.mta_out[j]
            p = self._round_payloads(R2)[j]
            nt_own = self.own.ctx_nt.prof
            n2_own = self.own.pmx.prof_n2
            for name in ("gamma", "w"):
                Tb = {
                    "c_b": self._parse_limbs(p[f"{name}_cb"], n2_own, j),
                    "z": self._parse_limbs(p[f"{name}_z"], nt_own, j),
                    "z_p": self._parse_limbs(p[f"{name}_zp"], nt_own, j),
                    "t": self._parse_limbs(p[f"{name}_t"], nt_own, j),
                    "v": self._parse_limbs(p[f"{name}_v"], n2_own, j),
                    "w": self._parse_limbs(p[f"{name}_w"], nt_own, j),
                }
                Pb = {
                    "s": self._parse_limbs(p[f"{name}_s"], self.own.pmx.prof_n, j),
                    "s1": self._parse_limbs(p[f"{name}_s1"], mta.p_s1, j),
                    "s2": self._parse_limbs(p[f"{name}_s2"], mta.p_s2, j),
                    "t1": self._parse_limbs(p[f"{name}_t1"], mta.p_t1, j),
                    "t2": self._parse_limbs(p[f"{name}_t2"], mta.p_s2, j),
                }
                extra = ()
                if name == "w":
                    U_comp, U_pt = self._parse_point_block(p["w_U"], j)
                    X_comp = sp.compress(self.W_pts[j])
                    extra = (U_comp, X_comp)
                e_b = mta.e_limbs(mta.bob_challenge(self._c_k, Tb, extra))
                self._ok = self._ok & mta.alice_check_bob(
                    self._c_k, Tb, Pb, e_b, self.rng
                )
                if name == "w":
                    self._ok = self._ok & gb._withcheck_curve(
                        gb._mod_q_from_limbs(Pb["s1"], mta.p_s1),
                        gb._mod_q_from_limbs(e_b, mta.p_e),
                        U_pt,
                        self.W_pts[j],
                    )
                alpha[(j, name)] = mta.alice_decrypt_share(Tb["c_b"])

        d = ring.mulmod(self._k, self._gamma)
        s_ = ring.mulmod(self._k, self._w)
        for j in self.others():
            d = ring.addmod(
                d, ring.addmod(alpha[(j, "gamma")], self._alice_beta[(j, "gamma")])
            )
            s_ = ring.addmod(
                s_, ring.addmod(alpha[(j, "w")], self._alice_beta[(j, "w")])
            )
        self._delta_own = d
        self._sigma_own = s_
        return self.broadcast(R3, {"d": self._ser_scalar(d)})

    # -- round 4: Γ decommit + Schnorr PoK -----------------------------------

    def _decommit_gamma(self) -> RoundMsg:
        kpok = gb._scalar_from_wide_bytes(
            jnp.asarray(gb.rand_bits(self.B, 320, self.rng))
        )
        A_comp, s_pok = gb._blk_schnorr_prove(
            kpok, self._gamma, self._Gamma_comp, self._bind_row(self.self_id)
        )
        return self.broadcast(
            R4,
            {
                "G": _ser_bytes(self._Gamma_comp),
                "blind": _ser_bytes(self._gblind),
                "A": _ser_bytes(A_comp),
                "spok": self._ser_scalar(s_pok),
            },
        )

    # -- round 5A ------------------------------------------------------------

    def _phase5a(self) -> RoundMsg:
        ring = self.ring
        delta = self._delta_own
        Gamma_sum = self._Gamma_own
        commits = self._round_payloads(R1B)
        for j in self.others():
            p = self._round_payloads(R4)[j]
            G_comp, G_pt = self._parse_point_block(p["G"], j)
            blind = jnp.asarray(self._parse_bytes(p["blind"], 32, j))
            commit = jnp.asarray(self._parse_bytes(commits[j]["gc"], 32, j))
            self._ok = self._ok & gb._blk_gamma_check(
                blind, G_comp, self._bind_row(j), commit
            )
            A_comp = jnp.asarray(self._parse_bytes(p["A"], 33, j))
            s_pok = self._parse_scalar(p["spok"], j)
            self._ok = self._ok & gb._blk_schnorr_verify(
                A_comp, s_pok, G_pt, G_comp, self._bind_row(j)
            )
            delta = ring.addmod(
                delta, self._parse_scalar(self._round_payloads(R3)[j]["d"], j)
            )
            Gamma_sum = gb._blk_point_add(Gamma_sum, G_pt)
        ok_R, R_pt, r, rec = gb._blk_R(delta, Gamma_sum)
        self._ok = self._ok & ok_R
        self._R_pt, self._r, self._rec = R_pt, r, rec

        rb = gb.rand_bits
        B = self.B
        self._li = gb._scalar_from_wide_bytes(jnp.asarray(rb(B, 320, self.rng)))
        self._rho = gb._scalar_from_wide_bytes(jnp.asarray(rb(B, 320, self.rng)))
        self._ka = gb._scalar_from_wide_bytes(jnp.asarray(rb(B, 320, self.rng)))
        self._kb = gb._scalar_from_wide_bytes(jnp.asarray(rb(B, 320, self.rng)))
        self._va_blind = jnp.asarray(rb(B, 256, self.rng))
        si, Vi, Ai, vc, ac, cmt = gb._blk_va(
            self.m, r, self._k, self._sigma_own, self._li, self._rho,
            R_pt, self._va_blind, self._bind_row(self.self_id),
        )
        self._s_own, self._V_own, self._A_own = si, Vi, Ai
        self._vc, self._ac = vc, ac
        return self.broadcast(R5, {"c": _ser_bytes(cmt)})

    # -- round 5B ------------------------------------------------------------

    def _phase5b(self) -> RoundMsg:
        Apok, sa, sb = gb._blk_pedersen_prove(
            self._ka, self._kb, self._s_own, self._li, self._R_pt,
            self._vc, self._ac, self._bind_row(self.self_id),
        )
        return self.broadcast(
            R6,
            {
                "vc": _ser_bytes(self._vc),
                "ac": _ser_bytes(self._ac),
                "blind": _ser_bytes(self._va_blind),
                "apok": _ser_bytes(Apok),
                "sa": self._ser_scalar(sa),
                "sb": self._ser_scalar(sb),
            },
        )

    # -- round 5C ------------------------------------------------------------

    def _phase5c(self) -> RoundMsg:
        V_sum, A_sum = self._V_own, self._A_own
        for j in self.others():
            p = self._round_payloads(R6)[j]
            vc, V_pt = self._parse_point_block(p["vc"], j)
            ac, A_pt = self._parse_point_block(p["ac"], j)
            blind = jnp.asarray(self._parse_bytes(p["blind"], 32, j))
            commit = jnp.asarray(
                self._parse_bytes(self._round_payloads(R5)[j]["c"], 32, j)
            )
            self._ok = self._ok & gb._blk_va_check(
                blind, vc, ac, self._bind_row(j), commit
            )
            apok = jnp.asarray(self._parse_bytes(p["apok"], 33, j))
            self._ok = self._ok & gb._blk_pedersen_verify(
                apok, self._parse_scalar(p["sa"], j),
                self._parse_scalar(p["sb"], j),
                V_pt, self._R_pt, vc, ac, self._bind_row(j),
            )
            V_sum = gb._blk_point_add(V_sum, V_pt)
            A_sum = gb._blk_point_add(A_sum, A_pt)
        V = gb._blk_V(V_sum, self.m, self._r, self.Y)
        self._A_sum = A_sum
        self._ut_blind = jnp.asarray(gb.rand_bits(self.B, 256, self.rng))
        Ui, Ti, uc, tc, cmt = gb._blk_ut(
            self._rho, self._li, V, A_sum, self._ut_blind,
            self._bind_row(self.self_id),
        )
        self._U_own, self._T_own = Ui, Ti
        self._uc, self._tc = uc, tc
        return self.broadcast(R7, {"c": _ser_bytes(cmt)})

    # -- round 5D ------------------------------------------------------------

    def _phase5d(self) -> RoundMsg:
        return self.broadcast(
            R8,
            {
                "uc": _ser_bytes(self._uc),
                "tc": _ser_bytes(self._tc),
                "blind": _ser_bytes(self._ut_blind),
            },
        )

    # -- round 5E ------------------------------------------------------------

    def _partial(self) -> RoundMsg:
        U_s, T_s = self._U_own, self._T_own
        for j in self.others():
            p = self._round_payloads(R8)[j]
            uc, U_pt = self._parse_point_block(p["uc"], j)
            tc, T_pt = self._parse_point_block(p["tc"], j)
            blind = jnp.asarray(self._parse_bytes(p["blind"], 32, j))
            commit = jnp.asarray(
                self._parse_bytes(self._round_payloads(R7)[j]["c"], 32, j)
            )
            self._ok = self._ok & gb._blk_ut_check(
                blind, uc, tc, self._bind_row(j), commit
            )
            U_s = gb._blk_point_add(U_s, U_pt)
            T_s = gb._blk_point_add(T_s, T_pt)
        self._ok = self._ok & gb._blk_point_eq(U_s, T_s)
        return self.broadcast(R9, {"s": self._ser_scalar(self._s_own)})

    def _finalize(self) -> None:
        s = self._s_own
        for j in self.others():
            s = self.ring.addmod(
                s, self._parse_scalar(self._round_payloads(R9)[j]["s"], j)
            )

        # combine + verify as the engine's DONATED round step, cohorted:
        # cohort A's signature egress (host byte packing) overlaps cohort
        # B's _step_final dispatch (engine/pipeline counter-phase model)
        def make_job(ci: int, sl: slice):
            def job():
                st = {
                    "s": s[sl], "m": self.m[sl], "r": self._r[sl],
                    "rec": self._rec[sl], "ok": self._ok[sl],
                }
                st = gb._step_final(st, gb._slice_pt(self.Y, sl))
                egress = yield (
                    "sig_egress",
                    lambda: gb._sig_egress(
                        st["r"], st["s"], st["rec"], st["ok"]
                    ),
                )
                return egress

            return job

        outs = pl.run_counter_phase(
            [make_job(ci, sl) for ci, sl in enumerate(self._plan.slices())]
        )
        self.result = {
            key: pl.merge_rows([o[key] for o in outs])
            for key in ("r", "s", "recovery", "ok")
        }
        self.done = True
        compile_watch.finish(self._cw)
