"""GG18 ECDSA distributed key generation (secp256k1).

4 rounds matching the reference inventory (pkg/mpc/ecdsa_rounds.go:12-15:
KGRound1Message, KGRound2Message1 unicast, KGRound2Message2, KGRound3Message):

  R1 (broadcast)  hash commitment to Feldman VSS points + Paillier pubkey
                  + ring-Pedersen params (NTilde, h1, h2) + two DLN proofs
  R2a (unicast)   Shamir share f_i(x_j)
  R2b (broadcast) VSS decommitment
  R3 (broadcast)  Paillier modulus validity proof
  finalize        verify everything; x_i = Σ f_j(x_i), pub = Σ C_j0

The expensive Paillier/NTilde material comes from per-node :class:`PreParams`
generated once at startup (reference node.go:69) — passed in, not generated
per wallet.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

from ...core import hostmath as hm
from ...core.paillier import PaillierPublicKey, PreParams
from .. import commitments as cm
from ..base import KeygenShare, PartyBase, ProtocolError, RoundMsg
from .zk import DLNProof, PaillierProof, Q

R1 = "ecdsa/kg/1"
R2_SHARE = "ecdsa/kg/2/share"
R2_DECOMMIT = "ecdsa/kg/2/decommit"
R3 = "ecdsa/kg/3"

# minimum Paillier modulus size accepted from peers (tss-lib enforces 2048)
MIN_PAILLIER_BITS = 2046


class ECDSAKeygenParty(PartyBase):
    """One party of the GG18 DKG. ``preparams`` is this node's startup
    artifact; ``min_paillier_bits`` is lowered only in tests (small keys)."""

    # "pre" rides along because a restarted node draws FRESH preparams from
    # the pool — but round 1 already committed the old ones to the peers
    _SNAP_EXTRA = (
        "_sent_r2", "_sent_r3", "_coeffs", "_shares_out", "_points",
        "_commitment", "_blind", "_peer_pk", "_peer_rp", "pre",
    )

    def __init__(
        self,
        session_id: str,
        self_id: str,
        party_ids: Sequence[str],
        threshold: int,
        preparams: PreParams,
        rng=None,
        min_paillier_bits: int = MIN_PAILLIER_BITS,
    ):
        import secrets as _secrets

        super().__init__(session_id, self_id, party_ids, rng or _secrets)
        if not 0 < threshold < len(party_ids):
            raise ValueError("need 0 < t < n")
        self.threshold = threshold
        self.pre = preparams
        self.min_paillier_bits = min_paillier_bits
        self._sent_r2 = False
        self._sent_r3 = False

    # -- round 1 ------------------------------------------------------------

    def start(self) -> List[RoundMsg]:
        t = self.threshold
        u = self.rng.randbelow(Q - 1) + 1
        self._coeffs, self._shares_out = hm.shamir_share(
            u, t, [self.xs[p] for p in self.party_ids], Q, rng=self.rng
        )
        self._points = [
            hm.secp_compress(hm.secp_mul(c, hm.SECP_G)) for c in self._coeffs
        ]
        data = cm.encode_points(self._points)
        self._commitment, self._blind = cm.commit(data, rng=self.rng)
        pre = self.pre
        pq = (pre.P - 1) // 2 * ((pre.Q - 1) // 2)
        bind = self._proof_bind(self.self_id)
        dln1 = DLNProof.prove(
            pre.h1, pre.h2, pre.alpha, pq, pre.NTilde, self.rng, bind=bind
        )
        dln2 = DLNProof.prove(
            pre.h2, pre.h1, pre.beta, pq, pre.NTilde, self.rng, bind=bind
        )
        return [
            self.broadcast(
                R1,
                {
                    "commitment": self._commitment.hex(),
                    "paillier_n": str(pre.paillier.N),
                    "ntilde": str(pre.NTilde),
                    "h1": str(pre.h1),
                    "h2": str(pre.h2),
                    "dln1": dln1.to_json(),
                    "dln2": dln2.to_json(),
                },
            )
        ]

    # -- message handling ---------------------------------------------------

    def receive(self, msg: RoundMsg) -> List[RoundMsg]:
        if self.done:
            return []
        self._store(msg)
        out: List[RoundMsg] = []
        others = self.others()
        if not self._sent_r2 and self._round_full(R1, others):
            self._verify_round1()
            self._sent_r2 = True
            out.append(
                self.broadcast(
                    R2_DECOMMIT,
                    {
                        "points": [p.hex() for p in self._points],
                        "blind": self._blind.hex(),
                    },
                )
            )
            for pid in others:
                out.append(
                    self.unicast(
                        pid,
                        R2_SHARE,
                        {"share": str(self._shares_out[self.xs[pid]])},
                    )
                )
        if (
            self._sent_r2
            and not self._sent_r3
            and self._round_full(R2_DECOMMIT, others)
            and self._round_full(R2_SHARE, others)
        ):
            self._sent_r3 = True
            proof = PaillierProof.prove(
                self.pre.paillier, bind=self._proof_bind(self.self_id)
            )
            out.append(self.broadcast(R3, {"paillier_proof": proof.to_json()}))
        if self._sent_r3 and not self.done and self._round_full(R3, others):
            self._finalize()
        return out

    def _proof_bind(self, sender: str) -> bytes:
        """Session+sender binding for the keygen ZK proofs — prevents a peer
        from replaying another node's (long-lived) DLN/Paillier proofs as
        its own in a different wallet's keygen."""
        return f"{self.session_id}:{sender}".encode()

    # -- verification -------------------------------------------------------

    def _verify_round1(self) -> None:
        """DLN proofs + parameter sanity for every peer (run once, before
        revealing anything in round 2)."""
        r1 = self._round_payloads(R1)
        self._peer_pk: Dict[str, PaillierPublicKey] = {}
        self._peer_rp: Dict[str, Dict[str, int]] = {}
        for pid in self.others():
            p = r1[pid]
            N = int(p["paillier_n"])
            ntilde, h1, h2 = int(p["ntilde"]), int(p["h1"]), int(p["h2"])
            if N.bit_length() < self.min_paillier_bits:
                raise ProtocolError("Paillier modulus too small", pid)
            if ntilde.bit_length() < self.min_paillier_bits:
                raise ProtocolError("NTilde too small", pid)
            if h1 in (0, 1) or h2 in (0, 1) or h1 == h2:
                raise ProtocolError("degenerate ring-Pedersen bases", pid)
            bind = self._proof_bind(pid)
            if not DLNProof.from_json(p["dln1"]).verify(h1, h2, ntilde, bind=bind):
                raise ProtocolError("DLN proof (h2 = h1^a) failed", pid)
            if not DLNProof.from_json(p["dln2"]).verify(h2, h1, ntilde, bind=bind):
                raise ProtocolError("DLN proof (h1 = h2^b) failed", pid)
            self._peer_pk[pid] = PaillierPublicKey(N)
            self._peer_rp[pid] = {"ntilde": ntilde, "h1": h1, "h2": h2}

    # -- finalize -----------------------------------------------------------

    def _finalize(self) -> None:
        t = self.threshold
        commits = self._round_payloads(R1)
        decommits = self._round_payloads(R2_DECOMMIT)
        shares = self._round_payloads(R2_SHARE)
        r3 = self._round_payloads(R3)

        all_points: Dict[str, List[hm.SecpPoint]] = {
            self.self_id: [hm.secp_decompress(p) for p in self._points]
        }
        for pid in self.others():
            pts_hex = decommits[pid]["points"]
            if len(pts_hex) != t + 1:
                raise ProtocolError("wrong VSS commitment count", pid)
            blind = bytes.fromhex(decommits[pid]["blind"])
            pts_bytes = [bytes.fromhex(p) for p in pts_hex]
            if not cm.verify(
                bytes.fromhex(commits[pid]["commitment"]),
                blind,
                cm.encode_points(pts_bytes),
            ):
                raise ProtocolError("decommitment mismatch", pid)
            try:
                all_points[pid] = [hm.secp_decompress(p) for p in pts_bytes]
            except ValueError as e:
                raise ProtocolError(f"bad commitment point: {e}", pid)

        # Paillier validity proofs
        for pid in self.others():
            proof = PaillierProof.from_json(r3[pid]["paillier_proof"])
            pk = self._peer_pk[pid]
            if pk.N.bit_length() >= 2046:
                if not proof.verify(pk, bind=self._proof_bind(pid)):
                    raise ProtocolError("Paillier validity proof failed", pid)
            else:  # test-sized keys: structural check only
                if not proof.ys:
                    raise ProtocolError("missing Paillier proof", pid)

        # Feldman share verification: s_ji·G == Σ x_i^k · C_jk
        x_i = self._shares_out[self.self_x]
        for pid in self.others():
            s = int(shares[pid]["share"])
            if not 0 <= s < Q:
                raise ProtocolError("share out of range", pid)
            expect = _eval_commitments(all_points[pid], self.self_x)
            if hm.secp_mul(s, hm.SECP_G) != expect:
                raise ProtocolError("VSS share verification failed", pid)
            x_i = (x_i + s) % Q

        # aggregate public data
        agg: List[hm.SecpPoint] = []
        for k in range(t + 1):
            acc = hm.SECP_INF
            for pid in self.party_ids:
                acc = hm.secp_add(acc, all_points[pid][k])
            agg.append(acc)
        pub = agg[0]
        if pub.is_infinity:
            raise ProtocolError("degenerate public key")

        self.result = KeygenShare(
            key_type="secp256k1",
            share=x_i,
            self_x=self.self_x,
            public_key=hm.secp_compress(pub),
            vss_commitments=[hm.secp_compress(p) for p in agg],
            participants=list(self.party_ids),
            threshold=t,
            aux={
                # own secret material
                "paillier_sk": self.pre.paillier.to_json(),
                "preparams": {
                    "ntilde": str(self.pre.NTilde),
                    "h1": str(self.pre.h1),
                    "h2": str(self.pre.h2),
                },
                # peers' public material, needed by every signing session
                "peer_paillier": {
                    pid: str(pk.N) for pid, pk in self._peer_pk.items()
                },
                "peer_ring_pedersen": {
                    pid: {k: str(v) for k, v in rp.items()}
                    for pid, rp in self._peer_rp.items()
                },
            },
        )
        self.done = True


def _eval_commitments(points: Sequence[hm.SecpPoint], x: int) -> hm.SecpPoint:
    """Σ_k x^k · C_k (Horner over the group)."""
    acc = hm.SECP_INF
    for pt in reversed(points):
        acc = hm.secp_add(hm.secp_mul(x, acc), pt)
    return acc
