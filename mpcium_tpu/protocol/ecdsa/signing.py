"""GG18 threshold ECDSA signing (secp256k1).

9 rounds / 10 message types, matching the reference inventory
(pkg/mpc/ecdsa_rounds.go:16-25: SignRound1Message1 unicast +
SignRound1Message2 … SignRound9Message):

  R1a (unicast)   MtA init: c_i = Enc_i(k_i) + range proof per verifier
  R1b (broadcast) hash commitment to Γ_i = γ_i·G
  R2  (unicast)   MtA responses: k_j·γ_i and k_j·w_i (with-check)
  R3  (broadcast) δ_i = k_i·γ_i + Σ(α+β)
  R4  (broadcast) Γ decommit + Schnorr PoK of γ_i → R = δ⁻¹·ΣΓ, r = R_x
  R5  (broadcast) commit to V_i = s_i·R + l_i·G, A_i = ρ_i·G     (5A)
  R6  (broadcast) decommit + PoK of (s_i, l_i)                    (5B)
  R7  (broadcast) commit to U_i = ρ_i·V, T_i = l_i·A              (5C)
  R8  (broadcast) decommit U_i, T_i; check ΣT == ΣU               (5D)
  R9  (broadcast) s_i; s = Σs_i, low-s normalize, verify          (5E)

Phase-5 structure follows the GG18 paper (§4.3): the commit/reveal dance
ensures no party learns whether the signature verifies before every party
is committed to its s_i — aborting early reveals nothing about shares.

The additive key share is w_i = λ_i·x_i (λ from the keygen-universe
x-coords over the signing quorum); W_i = λ_i·X_i is publicly computable
from the aggregated VSS commitments, which is what the MtAwc check pins.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

from ...core import hostmath as hm
from ...core.paillier import PaillierPrivateKey, PaillierPublicKey
from .. import commitments as cm
from ..base import KeygenShare, PartyBase, ProtocolError, RoundMsg, party_xs
from . import mta
from .keygen import _eval_commitments
from .zk import PedersenPoK, Q, SchnorrProof

R1_MTA = "ecdsa/sign/1/mta"
R1_COMMIT = "ecdsa/sign/1/commit"
R2 = "ecdsa/sign/2"
R3 = "ecdsa/sign/3"
R4 = "ecdsa/sign/4"
R5 = "ecdsa/sign/5"
R6 = "ecdsa/sign/6"
R7 = "ecdsa/sign/7"
R8 = "ecdsa/sign/8"
R9 = "ecdsa/sign/9"


class ECDSASigningParty(PartyBase):
    """One signer among the quorum (≥ t+1 keygen participants)."""

    # k_i/γ_i and every phase-5 secret are committed to peers; a resumed
    # signer must replay the identical values (crash-recovery WAL)
    _SNAP_EXTRA = (
        "_stage", "k_i", "gamma_i", "Gamma_i", "_gamma_commit",
        "_gamma_blind", "_mta_inits", "_beta", "_nu", "_delta_i",
        "_sigma_i", "_R", "_r", "_s_i", "_l_i", "_rho_i", "_V_i", "_A_i",
        "_va_commit", "_va_blind", "_peer_VA", "_U_i", "_T_i",
        "_ut_commit", "_ut_blind",
    )

    def __init__(
        self,
        session_id: str,
        self_id: str,
        party_ids: Sequence[str],
        share: KeygenShare,
        digest: int,
        rng=None,
    ):
        import secrets as _secrets

        super().__init__(session_id, self_id, party_ids, rng or _secrets)
        if len(party_ids) < share.threshold + 1:
            raise ProtocolError("not enough participants for threshold")
        if share.key_type != "secp256k1":
            raise ValueError("wrong key type for ECDSA signing")
        self.share = share
        self.digest = digest % Q
        keygen_xs = party_xs(share.participants)
        for pid in party_ids:
            if pid not in keygen_xs:
                raise ProtocolError("signer not in keygen participant set", pid)
        self.xs = {pid: keygen_xs[pid] for pid in self.party_ids}
        self.self_x = self.xs[self_id]
        assert self.self_x == share.self_x

        # additive share w_i = λ_i·x_i and public W_j for every signer
        quorum_xs = [self.xs[p] for p in self.party_ids]
        self.lam = {
            pid: hm.lagrange_coeff(quorum_xs, self.xs[pid], Q)
            for pid in self.party_ids
        }
        self.w_i = self.lam[self_id] * share.share % Q
        agg_points = [hm.secp_decompress(c) for c in share.vss_commitments]
        self.W = {
            pid: hm.secp_mul(
                self.lam[pid], _eval_commitments(agg_points, self.xs[pid])
            )
            for pid in self.party_ids
        }
        self.pub = hm.secp_decompress(share.public_key)

        aux = share.aux
        self.paillier_sk = PaillierPrivateKey.from_json(aux["paillier_sk"])
        self.own_rp = {k: int(v) for k, v in aux["preparams"].items()}
        self.peer_pk = {
            pid: PaillierPublicKey(int(n))
            for pid, n in aux["peer_paillier"].items()
        }
        self.peer_rp = {
            pid: {k: int(v) for k, v in rp.items()}
            for pid, rp in aux["peer_ring_pedersen"].items()
        }
        for pid in self.others():
            if pid not in self.peer_pk or pid not in self.peer_rp:
                raise ProtocolError("missing peer Paillier material", pid)

        self._stage = 0  # last completed send stage (1..9)

    def _bind(self, sender: str) -> bytes:
        """Session+sender binding for signing commitments and PoKs — a
        malicious signer cannot replay another party's R1 Γ-commitment or
        R4/R6 decommit+PoK as its own (that would only cause an abort with
        the wrong culprit, but culprit attribution must be right; keygen
        already binds via _proof_bind)."""
        return f"{self.session_id}:{sender}".encode()

    # -- round 1 ------------------------------------------------------------

    def start(self) -> List[RoundMsg]:
        self.k_i = self.rng.randbelow(Q - 1) + 1
        self.gamma_i = self.rng.randbelow(Q - 1) + 1
        self.Gamma_i = hm.secp_mul(self.gamma_i, hm.SECP_G)
        data = self._bind(self.self_id) + hm.secp_compress(self.Gamma_i)
        self._gamma_commit, self._gamma_blind = cm.commit(data, rng=self.rng)

        out = [self.broadcast(R1_COMMIT, {"commitment": self._gamma_commit.hex()})]
        # one Enc(k_i) per verifier: the range proof is bound to the
        # verifier's ring-Pedersen params
        self._mta_inits: Dict[str, mta.MtaInit] = {}
        pk_own = self.paillier_sk.public
        for pid in self.others():
            rp = self.peer_rp[pid]
            init, _r = mta.mta_init(
                pk_own, rp["ntilde"], rp["h1"], rp["h2"], self.k_i, rng=self.rng
            )
            self._mta_inits[pid] = init
            out.append(self.unicast(pid, R1_MTA, {"init": init.to_json()}))
        self._stage = 1
        return out

    # -- dispatch -----------------------------------------------------------

    def receive(self, msg: RoundMsg) -> List[RoundMsg]:
        if self.done:
            return []
        self._store(msg)
        out: List[RoundMsg] = []
        others = self.others()

        if (
            self._stage == 1
            and self._round_full(R1_MTA, others)
            and self._round_full(R1_COMMIT, others)
        ):
            out.extend(self._round2())
            self._stage = 2
        if self._stage == 2 and self._round_full(R2, others):
            out.append(self._round3())
            self._stage = 3
        if self._stage == 3 and self._round_full(R3, others):
            out.append(self._round4())
            self._stage = 4
        if self._stage == 4 and self._round_full(R4, others):
            out.append(self._round5())
            self._stage = 5
        if self._stage == 5 and self._round_full(R5, others):
            out.append(self._round6())
            self._stage = 6
        if self._stage == 6 and self._round_full(R6, others):
            out.append(self._round7())
            self._stage = 7
        if self._stage == 7 and self._round_full(R7, others):
            out.append(self._round8())
            self._stage = 8
        if self._stage == 8 and self._round_full(R8, others):
            out.append(self._round9())
            self._stage = 9
        if self._stage == 9 and self._round_full(R9, others):
            self._finalize()
        return out

    # -- round 2: MtA responses --------------------------------------------

    def _round2(self) -> List[RoundMsg]:
        inits = self._round_payloads(R1_MTA)
        out: List[RoundMsg] = []
        self._beta: Dict[str, int] = {}  # from k_j·γ_i
        self._nu: Dict[str, int] = {}  # from k_j·w_i
        rp_own = self.own_rp
        for pid in self.others():
            init = mta.MtaInit.from_json(inits[pid]["init"])
            pk_j = self.peer_pk[pid]
            rp_j = self.peer_rp[pid]
            try:
                resp_g, beta = mta.mta_respond(
                    pk_j,
                    rp_j["ntilde"], rp_j["h1"], rp_j["h2"],
                    rp_own["ntilde"], rp_own["h1"], rp_own["h2"],
                    init, self.gamma_i, with_check=False, rng=self.rng,
                )
                resp_w, nu = mta.mta_respond(
                    pk_j,
                    rp_j["ntilde"], rp_j["h1"], rp_j["h2"],
                    rp_own["ntilde"], rp_own["h1"], rp_own["h2"],
                    init, self.w_i, with_check=True, rng=self.rng,
                    init_verified=True,  # the γ response above verified it
                )
            except ValueError as e:
                raise ProtocolError(f"MtA: {e}", pid)
            self._beta[pid] = beta
            self._nu[pid] = nu
            out.append(
                self.unicast(
                    pid,
                    R2,
                    {"gamma": resp_g.to_json(), "w": resp_w.to_json()},
                )
            )
        return out

    # -- round 3: δ_i -------------------------------------------------------

    def _round3(self) -> RoundMsg:
        resps = self._round_payloads(R2)
        rp_own = self.own_rp
        delta_i = self.k_i * self.gamma_i % Q
        sigma_i = self.k_i * self.w_i % Q
        for pid in self.others():
            init = self._mta_inits[pid]
            resp_g = mta.MtaResp.from_json(resps[pid]["gamma"])
            resp_w = mta.MtaResp.from_json(resps[pid]["w"])
            try:
                alpha = mta.mta_finalize(
                    self.paillier_sk,
                    rp_own["ntilde"], rp_own["h1"], rp_own["h2"],
                    init, resp_g,
                )
                mu = mta.mta_finalize(
                    self.paillier_sk,
                    rp_own["ntilde"], rp_own["h1"], rp_own["h2"],
                    init, resp_w, X=self.W[pid],
                )
            except ValueError as e:
                raise ProtocolError(f"MtA finalize: {e}", pid)
            delta_i = (delta_i + alpha + self._beta[pid]) % Q  # mpcflow: declassified — δᵢ is the GG18 R3 public reveal
            sigma_i = (sigma_i + mu + self._nu[pid]) % Q
        self._delta_i = delta_i
        self._sigma_i = sigma_i
        return self.broadcast(R3, {"delta": str(delta_i)})

    # -- round 4: Γ decommit → R -------------------------------------------

    def _round4(self) -> RoundMsg:
        pok = SchnorrProof.prove(
            self.gamma_i, self.Gamma_i, rng=self.rng,
            bind=self._bind(self.self_id),
        )
        return self.broadcast(
            R4,
            {
                "Gamma": hm.secp_compress(self.Gamma_i).hex(),
                "blind": self._gamma_blind.hex(),
                "pok": pok.to_json(),
            },
        )

    # -- round 5 (5A): commit V_i, A_i -------------------------------------

    def _round5(self) -> RoundMsg:
        # assemble R from decommitments
        commits = self._round_payloads(R1_COMMIT)
        deltas = self._round_payloads(R3)
        decommits = self._round_payloads(R4)
        delta = self._delta_i
        for pid in self.others():
            d = int(deltas[pid]["delta"])
            if not 0 <= d < Q:
                raise ProtocolError("delta out of range", pid)
            delta = (delta + d) % Q
        if delta == 0:
            raise ProtocolError("degenerate delta (k·γ = 0)")
        Gamma = self.Gamma_i
        for pid in self.others():
            gb = bytes.fromhex(decommits[pid]["Gamma"])
            if not cm.verify(
                bytes.fromhex(commits[pid]["commitment"]),
                bytes.fromhex(decommits[pid]["blind"]),
                self._bind(pid) + gb,
            ):
                raise ProtocolError("Γ decommitment mismatch", pid)
            try:
                Gamma_j = hm.secp_decompress(gb)
            except ValueError as e:
                raise ProtocolError(f"bad Γ point: {e}", pid)
            if not SchnorrProof.from_json(decommits[pid]["pok"]).verify(
                Gamma_j, bind=self._bind(pid)
            ):
                raise ProtocolError("Γ PoK failed", pid)
            Gamma = hm.secp_add(Gamma, Gamma_j)
        R = hm.secp_mul(pow(delta, -1, Q), Gamma)
        if R.is_infinity:
            raise ProtocolError("degenerate R")
        self._R = R
        self._r = R.x % Q
        if self._r == 0:
            raise ProtocolError("degenerate r = 0")
        # s_i and the 5A commitment
        self._s_i = (self.digest * self.k_i + self._r * self._sigma_i) % Q
        self._l_i = self.rng.randbelow(Q - 1) + 1
        self._rho_i = self.rng.randbelow(Q - 1) + 1
        self._V_i = hm.secp_add(
            hm.secp_mul(self._s_i, R), hm.secp_mul(self._l_i, hm.SECP_G)
        )
        self._A_i = hm.secp_mul(self._rho_i, hm.SECP_G)
        data = (
            self._bind(self.self_id)
            + hm.secp_compress(self._V_i)
            + hm.secp_compress(self._A_i)
        )
        self._va_commit, self._va_blind = cm.commit(data, rng=self.rng)
        return self.broadcast(R5, {"commitment": self._va_commit.hex()})

    # -- round 6 (5B): decommit V_i, A_i + PoK ------------------------------

    def _round6(self) -> RoundMsg:
        pok = PedersenPoK.prove(
            self._s_i, self._l_i, self._R, self._V_i, rng=self.rng,
            bind=self._bind(self.self_id),
        )
        return self.broadcast(
            R6,
            {
                "V": hm.secp_compress(self._V_i).hex(),
                "A": hm.secp_compress(self._A_i).hex(),
                "blind": self._va_blind.hex(),
                "pok": pok.to_json(),
            },
        )

    # -- round 7 (5C): commit U_i, T_i --------------------------------------

    def _round7(self) -> RoundMsg:
        commits = self._round_payloads(R5)
        decommits = self._round_payloads(R6)
        V_sum = self._V_i
        A_sum = self._A_i
        self._peer_VA: Dict[str, tuple] = {}
        for pid in self.others():
            Vb = bytes.fromhex(decommits[pid]["V"])
            Ab = bytes.fromhex(decommits[pid]["A"])
            if not cm.verify(
                bytes.fromhex(commits[pid]["commitment"]),
                bytes.fromhex(decommits[pid]["blind"]),
                self._bind(pid) + Vb + Ab,
            ):
                raise ProtocolError("V/A decommitment mismatch", pid)
            try:
                V_j = hm.secp_decompress(Vb)
                A_j = hm.secp_decompress(Ab)
            except ValueError as e:
                raise ProtocolError(f"bad V/A point: {e}", pid)
            if not PedersenPoK.from_json(decommits[pid]["pok"]).verify(
                self._R, V_j, bind=self._bind(pid)
            ):
                raise ProtocolError("V_i PoK failed", pid)
            self._peer_VA[pid] = (V_j, A_j)
            V_sum = hm.secp_add(V_sum, V_j)
            A_sum = hm.secp_add(A_sum, A_j)
        # V = -m·G - r·y + ΣV_i ;  honest ⇒ V = (Σl_i)·G
        neg = lambda P: hm.SecpPoint(P.x, (-P.y) % hm.SECP_P) if not P.is_infinity else P
        V = hm.secp_add(
            V_sum,
            hm.secp_add(
                neg(hm.secp_mul(self.digest, hm.SECP_G)),
                neg(hm.secp_mul(self._r, self.pub)),
            ),
        )
        self._U_i = hm.secp_mul(self._rho_i, V)
        self._T_i = hm.secp_mul(self._l_i, A_sum)
        data = (
            self._bind(self.self_id)
            + hm.secp_compress(self._U_i)
            + hm.secp_compress(self._T_i)
        )
        self._ut_commit, self._ut_blind = cm.commit(data, rng=self.rng)
        return self.broadcast(R7, {"commitment": self._ut_commit.hex()})

    # -- round 8 (5D): decommit U_i, T_i ------------------------------------

    def _round8(self) -> RoundMsg:
        return self.broadcast(
            R8,
            {
                "U": hm.secp_compress(self._U_i).hex(),
                "T": hm.secp_compress(self._T_i).hex(),
                "blind": self._ut_blind.hex(),
            },
        )

    # -- round 9 (5E): reveal s_i -------------------------------------------

    def _round9(self) -> RoundMsg:
        commits = self._round_payloads(R7)
        decommits = self._round_payloads(R8)
        U_sum = self._U_i
        T_sum = self._T_i
        for pid in self.others():
            Ub = bytes.fromhex(decommits[pid]["U"])
            Tb = bytes.fromhex(decommits[pid]["T"])
            if not cm.verify(
                bytes.fromhex(commits[pid]["commitment"]),
                bytes.fromhex(decommits[pid]["blind"]),
                self._bind(pid) + Ub + Tb,
            ):
                raise ProtocolError("U/T decommitment mismatch", pid)
            try:
                U_sum = hm.secp_add(U_sum, hm.secp_decompress(Ub))
                T_sum = hm.secp_add(T_sum, hm.secp_decompress(Tb))
            except ValueError as e:
                raise ProtocolError(f"bad U/T point: {e}", pid)
        # honest: ΣU_i = ρ·(Σl)G and ΣT_i = l·(Σρ)G — equal iff s consistent
        if U_sum != T_sum:
            raise ProtocolError(
                "phase-5 consistency check failed (ΣU ≠ ΣT): some party's "
                "s_i is inconsistent; aborting before any s_i is revealed"
            )
        return self.broadcast(R9, {"s": str(self._s_i)})

    # -- finalize ------------------------------------------------------------

    def _finalize(self) -> None:
        partials = self._round_payloads(R9)
        s = self._s_i
        for pid in self.others():
            v = int(partials[pid]["s"])
            if not 0 <= v < Q:
                raise ProtocolError("partial s out of range", pid)
            s = (s + v) % Q
        if s == 0:
            raise ProtocolError("degenerate s = 0")
        r = self._r
        rec = (self._R.y & 1) | (2 if self._R.x >= Q else 0)
        if s > Q // 2:  # low-s normalization (reference emits canonical sigs)
            s = Q - s
            rec ^= 1
        if not hm.ecdsa_verify(self.pub, self.digest, r, s):
            raise ProtocolError("aggregate ECDSA signature failed verification")
        self.result = {"r": r, "s": s, "recovery": rec}
        self.done = True
