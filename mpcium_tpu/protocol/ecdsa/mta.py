"""MtA (Multiplicative-to-Additive) share conversion — the GG18 signing
workhorse (SURVEY.md §3.3: "MtA … is the dominant per-signature cost and the
main TPU batching target").

Two parties holding a and b end with α + β ≡ a·b (mod q) without revealing
their inputs:

  Alice:  cA = Enc_A(a)            + RangeProofAlice (a < q³)
  Bob:    cB = cA^b · Enc_A(β′)    + RespProofBob (b < q³, β′ committed)
          β  = −β′ mod q
  Alice:  α  = Dec_A(cB) mod q     (integer value a·b + β′ < N, no wrap)

The "with check" variant (MtAwc) additionally binds b to a public point
B = b·G — used when Bob's input is his secret-share summand w_j (GG18 §5).

Host-side reference implementation (python ints). The batched device path
(engine/ecdsa_batch) evaluates the same equations over limb tensors using
core.paillier.PaillierBatch.
"""
from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Optional, Tuple

from ...core import hostmath as hm
from ...core.paillier import PaillierPrivateKey, PaillierPublicKey
from .zk import Q, RangeProofAlice, RespProofBob, _rand_unit


@dataclass(frozen=True)
class MtaInit:
    """Alice → Bob."""

    c_a: int
    proof: RangeProofAlice

    def to_json(self) -> dict:
        return {"c_a": str(self.c_a), "proof": self.proof.to_json()}

    @classmethod
    def from_json(cls, d: dict) -> "MtaInit":
        return cls(c_a=int(d["c_a"]), proof=RangeProofAlice.from_json(d["proof"]))


@dataclass(frozen=True)
class MtaResp:
    """Bob → Alice."""

    c_b: int
    proof: RespProofBob

    def to_json(self) -> dict:
        return {"c_b": str(self.c_b), "proof": self.proof.to_json()}

    @classmethod
    def from_json(cls, d: dict) -> "MtaResp":
        return cls(c_b=int(d["c_b"]), proof=RespProofBob.from_json(d["proof"]))


def mta_init(
    pk_a: PaillierPublicKey,
    ntilde_b: int,
    h1_b: int,
    h2_b: int,
    a: int,
    rng=secrets,
) -> Tuple[MtaInit, int]:
    """Alice's first flow. Returns (message, r_a) — r_a is the Paillier
    randomness, retained for nothing further (kept for tests)."""
    assert 0 <= a < Q
    r = _rand_unit(pk_a.N, rng)
    c_a = pk_a.encrypt(a, r=r)
    proof = RangeProofAlice.prove(pk_a, ntilde_b, h1_b, h2_b, c_a, a, r, rng=rng)
    return MtaInit(c_a=c_a, proof=proof), r


def mta_respond(
    pk_a: PaillierPublicKey,
    ntilde_a: int,
    h1_a: int,
    h2_a: int,
    ntilde_b: int,
    h1_b: int,
    h2_b: int,
    init: MtaInit,
    b: int,
    with_check: bool = False,
    rng=secrets,
    init_verified: bool = False,
) -> Tuple[MtaResp, int]:
    """Bob's flow: verify Alice's proof (under Bob's own ring-Pedersen
    params), homomorphically evaluate, prove (under Alice's params).
    Returns (message, β) — Bob's additive share.
    Raises ValueError if Alice's proof fails.

    ``init_verified=True`` skips re-verifying Alice's proof — for callers
    that respond to the SAME init twice (γ and w MtAs share one Enc(k));
    the first call must have verified it."""
    assert 0 <= b < Q
    if not init_verified:
        if not init.proof.verify(pk_a, ntilde_b, h1_b, h2_b, init.c_a):
            raise ValueError("MtA: Alice's range proof failed")
        if not 0 < init.c_a < pk_a.N2:
            raise ValueError("MtA: ciphertext out of range")
    # β′ ← Z_{q⁵} (GG18 §A.2): large enough to statistically mask a·b mod q,
    # small enough that a·b + β′ < q⁶ + q⁵ ≪ N never wraps the plaintext ring
    beta_prime = rng.randbelow(Q**5)
    r = _rand_unit(pk_a.N, rng)
    c_beta = pk_a.encrypt(beta_prime, r=r)
    c_b = pow(init.c_a, b, pk_a.N2) * c_beta % pk_a.N2
    X = hm.secp_mul(b, hm.SECP_G) if with_check else None
    proof = RespProofBob.prove(
        pk_a, ntilde_a, h1_a, h2_a, init.c_a, c_b, b, beta_prime, r, X=X, rng=rng
    )
    beta = (-beta_prime) % Q
    return MtaResp(c_b=c_b, proof=proof), beta


def mta_finalize(
    sk_a: PaillierPrivateKey,
    ntilde_a: int,
    h1_a: int,
    h2_a: int,
    init: MtaInit,
    resp: MtaResp,
    X: Optional[hm.SecpPoint] = None,
) -> int:
    """Alice's final flow: verify Bob's proof (under Alice's ring-Pedersen
    params), decrypt → α. ``X`` enables the with-check binding b·G == X.
    Raises ValueError on a failing proof."""
    pk_a = sk_a.public
    if not resp.proof.verify(
        pk_a, ntilde_a, h1_a, h2_a, init.c_a, resp.c_b, X=X
    ):
        raise ValueError("MtA: Bob's response proof failed")
    if not 0 < resp.c_b < pk_a.N2:
        raise ValueError("MtA: response ciphertext out of range")
    return sk_a.decrypt(resp.c_b) % Q
