"""Zero-knowledge proofs for GG18 (keygen + MtA).

The reference delegates all of these to tss-lib (SURVEY.md §2.3: "commitments,
ZK range proofs, VSS" are the crypto engine to rebuild). Clean-room
implementations from the GG18 paper (Gennaro–Goldfeder 2018, eprint 2019/114)
and the original FO97/MtA range-proof constructions:

- :class:`DLNProof` — Girault-style proof of knowledge of x with
  h2 = h1^x (mod NTilde), 128 binary-challenge iterations. Exchanged in
  keygen round 1 to certify ring-Pedersen parameters.
- :class:`PaillierProof` — proof that N is a valid Paillier modulus
  (gcd(N, φ(N)) = 1): y_i = x_i^{N⁻¹ mod φ} for hash-derived x_i.
  Keygen round 3.
- :class:`SchnorrProof` — PoK of discrete log on secp256k1 (used for the
  keygen share PoK and the signing phase-4 Γ decommit proof).
- :class:`RangeProofAlice` — MtA initiator proof: the Paillier ciphertext
  c = Enc(m) has m ∈ (-q³, q³)  (GG18 appendix A.1).
- :class:`RespProofBob` — MtA responder proof (A.2, the "with check" variant
  adds the X = x·G link — :class:`RespProofBobWC`).

Fiat–Shamir: SHA-256 over domain-tagged canonical encodings. All integers
are python ints (host control-plane); the batched device verification paths
live in engine/ (the modexps are fixed-shape and batchable per SURVEY §7.2).
"""
from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ...core import hostmath as hm
from ...core.paillier import PaillierPublicKey

Q = hm.SECP_N  # curve order

DLN_ITERS = 128
PAILLIER_ITERS = 13


def _hash_ints(tag: bytes, *vals: int, n_bytes: int = 32) -> bytes:
    h = hashlib.sha256()
    h.update(b"mpcium-tpu/zk/" + tag)
    for v in vals:
        b = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
        h.update(len(b).to_bytes(4, "big"))
        h.update(b)
    return h.digest()


def _hash_to_int(tag: bytes, *vals: int) -> int:
    return int.from_bytes(_hash_ints(tag, *vals), "big")


# ---------------------------------------------------------------------------
# DLN (Girault) proof: h2 = h1^x mod NTilde
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DLNProof:
    alphas: Tuple[int, ...]  # 128 commitments h1^{a_i}
    ts: Tuple[int, ...]  # 128 responses a_i + c_i·x mod pq

    @classmethod
    def prove(
        cls, h1: int, h2: int, x: int, pq: int, NTilde: int, rng=secrets,
        bind: bytes = b"",
    ) -> "DLNProof":
        a = [rng.randbelow(pq) for _ in range(DLN_ITERS)]
        alphas = [pow(h1, ai, NTilde) for ai in a]
        cbits = _challenge_bits(h1, h2, NTilde, alphas, bind)
        ts = [
            (ai + (x if c else 0)) % pq for ai, c in zip(a, cbits)
        ]
        return cls(alphas=tuple(alphas), ts=tuple(ts))

    def verify(self, h1: int, h2: int, NTilde: int, bind: bytes = b"") -> bool:
        if len(self.alphas) != DLN_ITERS or len(self.ts) != DLN_ITERS:
            return False
        if not (1 < h1 < NTilde and 1 < h2 < NTilde and h1 != h2):
            return False
        cbits = _challenge_bits(h1, h2, NTilde, list(self.alphas), bind)
        for ai, ti, c in zip(self.alphas, self.ts, cbits):
            if not 0 < ai < NTilde or ti < 0:
                return False
            rhs = ai * (h2 if c else 1) % NTilde
            if pow(h1, ti, NTilde) != rhs:
                return False
        return True

    def to_json(self) -> dict:
        return {
            "alphas": [str(a) for a in self.alphas],
            "ts": [str(t) for t in self.ts],
        }

    @classmethod
    def from_json(cls, d: dict) -> "DLNProof":
        return cls(
            alphas=tuple(int(a) for a in d["alphas"]),
            ts=tuple(int(t) for t in d["ts"]),
        )


def _challenge_bits(
    h1: int, h2: int, NTilde: int, alphas: Sequence[int], bind: bytes = b""
) -> List[int]:
    digest = hashlib.sha256(
        _hash_ints(b"dln", h1, h2, NTilde, *alphas) + bind
    ).digest()
    # expand to 128 bits
    out = []
    counter = 0
    while len(out) < DLN_ITERS:
        blk = hashlib.sha256(digest + counter.to_bytes(4, "big")).digest()
        for byte in blk:
            for i in range(8):
                out.append((byte >> i) & 1)
                if len(out) == DLN_ITERS:
                    break
            if len(out) == DLN_ITERS:
                break
        counter += 1
    return out


# ---------------------------------------------------------------------------
# Paillier modulus validity proof
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PaillierProof:
    ys: Tuple[int, ...]

    @classmethod
    def prove(cls, sk, bind: bytes = b"") -> "PaillierProof":
        """sk: PaillierPrivateKey. Proves gcd(N, φ(N)) = 1 by exhibiting
        N-th roots of hash-derived challenge values. ``bind`` ties the
        proof to a session/party (replay resistance)."""
        N = sk.N
        phi = (sk.p - 1) * (sk.q - 1)
        inv = pow(N, -1, phi)
        xs = _paillier_challenges(N, bind)
        return cls(ys=tuple(pow(x, inv, N) for x in xs))

    def verify(self, pk: PaillierPublicKey, bind: bytes = b"") -> bool:
        if len(self.ys) != PAILLIER_ITERS:
            return False
        N = pk.N
        if N <= 0 or N.bit_length() < 2046:
            return False
        # reject even N / tiny factors cheaply
        for p in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
            if N % p == 0:
                return False
        xs = _paillier_challenges(N, bind)
        for x, y in zip(xs, self.ys):
            if not 0 < y < N:
                return False
            if pow(y, N, N) != x % N:
                return False
        return True

    def to_json(self) -> dict:
        return {"ys": [str(y) for y in self.ys]}

    @classmethod
    def from_json(cls, d: dict) -> "PaillierProof":
        return cls(ys=tuple(int(y) for y in d["ys"]))


def _paillier_challenges(N: int, bind: bytes) -> List[int]:
    """Derive PAILLIER_ITERS values in Z_N from H(N, bind, i), rejecting
    non-units (gcd > 1 would itself reveal a factor)."""
    import math

    out = []
    i = 0
    while len(out) < PAILLIER_ITERS:
        v = (
            _hash_to_int(b"paillier", N, int.from_bytes(bind, "big") if bind else 0, i)
            % N
        )
        i += 1
        if v > 1 and math.gcd(v, N) == 1:
            out.append(v)
    return out


# ---------------------------------------------------------------------------
# Schnorr PoK of EC discrete log (secp256k1)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SchnorrProof:
    e: int  # challenge
    s: int  # response

    @classmethod
    def prove(
        cls, x: int, X: hm.SecpPoint, rng=secrets, bind: bytes = b""
    ) -> "SchnorrProof":
        k = rng.randbelow(Q - 1) + 1
        R = hm.secp_mul(k, hm.SECP_G)
        e = _schnorr_challenge(R, X, bind)
        return cls(e=e, s=(k - e * x) % Q)

    def verify(self, X: hm.SecpPoint, bind: bytes = b"") -> bool:
        if X.is_infinity or not (0 <= self.e < Q and 0 <= self.s < Q):
            return False
        R = hm.secp_add(hm.secp_mul(self.s, hm.SECP_G), hm.secp_mul(self.e, X))
        if R.is_infinity:
            return False
        return _schnorr_challenge(R, X, bind) == self.e

    def to_json(self) -> dict:
        return {"e": str(self.e), "s": str(self.s)}

    @classmethod
    def from_json(cls, d: dict) -> "SchnorrProof":
        return cls(e=int(d["e"]), s=int(d["s"]))


def _schnorr_challenge(R: hm.SecpPoint, X: hm.SecpPoint, bind: bytes) -> int:
    h = hashlib.sha256()
    h.update(b"mpcium-tpu/zk/schnorr")
    h.update(hm.secp_compress(R))
    h.update(hm.secp_compress(X))
    h.update(bind)
    return int.from_bytes(h.digest(), "big") % Q


@dataclass(frozen=True)
class PedersenPoK:
    """PoK of (a, b) with V = a·R + b·G (two-generator Schnorr) — the GG18
    phase-5B consistency proof for V_i = s_i·R + l_i·G."""

    e: int
    s_a: int
    s_b: int

    @classmethod
    def prove(
        cls,
        a: int,
        b: int,
        R: hm.SecpPoint,
        V: hm.SecpPoint,
        rng=secrets,
        bind: bytes = b"",
    ) -> "PedersenPoK":
        ka = rng.randbelow(Q - 1) + 1
        kb = rng.randbelow(Q - 1) + 1
        A = hm.secp_add(hm.secp_mul(ka, R), hm.secp_mul(kb, hm.SECP_G))
        e = _pedersen_challenge(A, R, V, bind)
        return cls(e=e, s_a=(ka - e * a) % Q, s_b=(kb - e * b) % Q)

    def verify(self, R: hm.SecpPoint, V: hm.SecpPoint, bind: bytes = b"") -> bool:
        if R.is_infinity or V.is_infinity:
            return False
        if not (0 <= self.e < Q and 0 <= self.s_a < Q and 0 <= self.s_b < Q):
            return False
        A = hm.secp_add(
            hm.secp_add(
                hm.secp_mul(self.s_a, R), hm.secp_mul(self.s_b, hm.SECP_G)
            ),
            hm.secp_mul(self.e, V),
        )
        if A.is_infinity:
            return False
        return _pedersen_challenge(A, R, V, bind) == self.e

    def to_json(self) -> dict:
        return {"e": str(self.e), "s_a": str(self.s_a), "s_b": str(self.s_b)}

    @classmethod
    def from_json(cls, d: dict) -> "PedersenPoK":
        return cls(e=int(d["e"]), s_a=int(d["s_a"]), s_b=int(d["s_b"]))


def _pedersen_challenge(
    A: hm.SecpPoint, R: hm.SecpPoint, V: hm.SecpPoint, bind: bytes
) -> int:
    h = hashlib.sha256()
    h.update(b"mpcium-tpu/zk/pedersen-pok")
    for pt in (A, R, V):
        h.update(hm.secp_compress(pt))
    h.update(bind)
    return int.from_bytes(h.digest(), "big") % Q


# ---------------------------------------------------------------------------
# MtA range proofs (GG18 appendix A)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RangeProofAlice:
    """Proof that c = Enc_N(m, r) with m ∈ (-q³, q³) (GG18 A.1).

    Statement: Paillier pk N, ciphertext c; verifier ring-Pedersen params
    (NTilde, h1, h2) belong to BOB (the verifier).
    """

    z: int
    u: int
    w: int
    s: int
    s1: int
    s2: int

    @classmethod
    def prove(
        cls,
        pk: PaillierPublicKey,
        ntilde: int,
        h1: int,
        h2: int,
        c: int,
        m: int,
        r: int,
        rng=secrets,
    ) -> "RangeProofAlice":
        q3 = Q**3
        N = pk.N
        alpha = rng.randbelow(q3)
        beta = _rand_unit(N, rng)
        gamma = rng.randbelow(q3 * ntilde)
        rho = rng.randbelow(Q * ntilde)

        z = pow(h1, m, ntilde) * pow(h2, rho, ntilde) % ntilde
        u = (1 + alpha * N) % pk.N2 * pow(beta, N, pk.N2) % pk.N2
        w = pow(h1, alpha, ntilde) * pow(h2, gamma, ntilde) % ntilde
        e = _range_challenge(b"alice", N, ntilde, h1, h2, c, z, u, w)
        s = pow(r, e, N) * beta % N
        s1 = e * m + alpha
        s2 = e * rho + gamma
        return cls(z=z, u=u, w=w, s=s, s1=s1, s2=s2)

    def verify(
        self,
        pk: PaillierPublicKey,
        ntilde: int,
        h1: int,
        h2: int,
        c: int,
    ) -> bool:
        q3 = Q**3
        N = pk.N
        # the range guarantee — BOTH bounds: a negative s1 would make pow()
        # take modular inverses and the equations verify for out-of-range
        # plaintexts (e.g. m ≡ -q⁶)
        if not 0 <= self.s1 <= q3:
            return False
        if self.s2 < 0:
            return False
        if not (0 < self.z < ntilde and 0 < self.u < pk.N2 and 0 < self.w < ntilde):
            return False
        if not (0 < self.s < N):
            return False
        e = _range_challenge(
            b"alice", N, ntilde, h1, h2, c, self.z, self.u, self.w
        )
        # u ?= (1+N)^{s1} s^N c^{-e} mod N²
        lhs = (1 + self.s1 * N) % pk.N2 * pow(self.s, N, pk.N2) % pk.N2
        rhs = self.u * pow(c, e, pk.N2) % pk.N2
        if lhs != rhs:
            return False
        # h1^{s1} h2^{s2} ?= w · z^e mod NTilde
        lhs2 = pow(h1, self.s1, ntilde) * pow(h2, self.s2, ntilde) % ntilde
        rhs2 = self.w * pow(self.z, e, ntilde) % ntilde
        return lhs2 == rhs2

    def to_json(self) -> dict:
        return {
            k: str(getattr(self, k)) for k in ("z", "u", "w", "s", "s1", "s2")
        }

    @classmethod
    def from_json(cls, d: dict) -> "RangeProofAlice":
        return cls(**{k: int(d[k]) for k in ("z", "u", "w", "s", "s1", "s2")})


@dataclass(frozen=True)
class RespProofBob:
    """Bob's MtA response proof (GG18 A.2): c2 = c1^b · Enc(β') with
    b ∈ (-q³, q³), β' ∈ Z_N. Optional "with check" (A.3) binds X = b·G.
    """

    z: int
    z_prime: int
    t: int
    v: int
    w: int
    s: int
    s1: int
    s2: int
    t1: int
    t2: int
    # with-check extension (None for plain MtA)
    u_point: Optional[hm.SecpPoint] = None

    @classmethod
    def prove(
        cls,
        pk: PaillierPublicKey,
        ntilde: int,
        h1: int,
        h2: int,
        c1: int,
        c2: int,
        b: int,
        beta_prime: int,
        r: int,
        X: Optional[hm.SecpPoint] = None,
        rng=secrets,
    ) -> "RespProofBob":
        q3 = Q**3
        q7 = Q**7
        N = pk.N
        alpha = rng.randbelow(q3)
        rho = rng.randbelow(Q * ntilde)
        rho_prime = rng.randbelow(q3 * ntilde)
        sigma = rng.randbelow(Q * ntilde)
        tau = rng.randbelow(q3 * ntilde)
        beta = _rand_unit(N, rng)
        gamma = rng.randbelow(q7)

        z = pow(h1, b, ntilde) * pow(h2, rho, ntilde) % ntilde
        z_prime = pow(h1, alpha, ntilde) * pow(h2, rho_prime, ntilde) % ntilde
        t = pow(h1, beta_prime, ntilde) * pow(h2, sigma, ntilde) % ntilde
        v = (
            pow(c1, alpha, pk.N2)
            * ((1 + gamma * N) % pk.N2)
            * pow(beta, N, pk.N2)
            % pk.N2
        )
        w = pow(h1, gamma, ntilde) * pow(h2, tau, ntilde) % ntilde
        u_point = None
        extra: Tuple[int, ...] = ()
        if X is not None:
            u_point = hm.secp_mul(alpha, hm.SECP_G)
            extra = (u_point.x, u_point.y, X.x, X.y)
        e = _range_challenge(
            b"bob", N, ntilde, h1, h2, c1, c2, z, z_prime, t, v, w, *extra
        )
        s = pow(r, e, N) * beta % N
        s1 = e * b + alpha
        s2 = e * rho + rho_prime
        t1 = e * beta_prime + gamma
        t2 = e * sigma + tau
        return cls(
            z=z, z_prime=z_prime, t=t, v=v, w=w, s=s, s1=s1, s2=s2, t1=t1,
            t2=t2, u_point=u_point,
        )

    def verify(
        self,
        pk: PaillierPublicKey,
        ntilde: int,
        h1: int,
        h2: int,
        c1: int,
        c2: int,
        X: Optional[hm.SecpPoint] = None,
    ) -> bool:
        q3 = Q**3
        q7 = Q**7
        N = pk.N
        # range guarantees with BOTH bounds (negative values flip pow() into
        # modular inverses); t1 ≤ q⁷ bounds Bob's β′ — without it a malicious
        # β′ ≈ N turns Alice's decrypt-wrap behavior into an oracle on k_i
        if not 0 <= self.s1 <= q3:
            return False
        if not 0 <= self.t1 <= q7:
            return False
        if self.s2 < 0 or self.t2 < 0:
            return False
        vals = (self.z, self.z_prime, self.t, self.w)
        if not all(0 < v_ < ntilde for v_ in vals):
            return False
        if not (0 < self.v < pk.N2 and 0 < self.s < N):
            return False
        extra: Tuple[int, ...] = ()
        if X is not None:
            if self.u_point is None or self.u_point.is_infinity or X.is_infinity:
                return False
            extra = (self.u_point.x, self.u_point.y, X.x, X.y)
        elif self.u_point is not None:
            return False
        e = _range_challenge(
            b"bob", N, ntilde, h1, h2, c1, c2, self.z, self.z_prime, self.t,
            self.v, self.w, *extra,
        )
        if X is not None:
            # s1·G ?= U + e·X  (binds b to the public point)
            lhs_pt = hm.secp_mul(self.s1, hm.SECP_G)
            rhs_pt = hm.secp_add(self.u_point, hm.secp_mul(e, X))
            if lhs_pt != rhs_pt:  # frozen dataclass: affine equality
                return False
        # h1^{s1} h2^{s2} ?= z'· z^e
        if (
            pow(h1, self.s1, ntilde) * pow(h2, self.s2, ntilde) % ntilde
            != self.z_prime * pow(self.z, e, ntilde) % ntilde
        ):
            return False
        # h1^{t1} h2^{t2} ?= w · t^e
        if (
            pow(h1, self.t1, ntilde) * pow(h2, self.t2, ntilde) % ntilde
            != self.w * pow(self.t, e, ntilde) % ntilde
        ):
            return False
        # c1^{s1} (1+N)^{t1} s^N ?= v · c2^e mod N²
        lhs = (
            pow(c1, self.s1, pk.N2)
            * ((1 + self.t1 * N) % pk.N2)
            * pow(self.s, N, pk.N2)
            % pk.N2
        )
        rhs = self.v * pow(c2, e, pk.N2) % pk.N2
        return lhs == rhs

    def to_json(self) -> dict:
        d = {
            k: str(getattr(self, k))
            for k in ("z", "z_prime", "t", "v", "w", "s", "s1", "s2", "t1", "t2")
        }
        if self.u_point is not None:
            d["u_point"] = hm.secp_compress(self.u_point).hex()
        return d

    @classmethod
    def from_json(cls, d: dict) -> "RespProofBob":
        u = d.get("u_point")
        return cls(
            **{
                k: int(d[k])
                for k in ("z", "z_prime", "t", "v", "w", "s", "s1", "s2", "t1", "t2")
            },
            u_point=hm.secp_decompress(bytes.fromhex(u)) if u else None,
        )


def _range_challenge(tag: bytes, *vals: int) -> int:
    return _hash_to_int(b"range/" + tag, *vals) % Q


def _rand_unit(N: int, rng=secrets) -> int:
    import math

    while True:
        v = rng.randbelow(N)
        if v > 1 and math.gcd(v, N) == 1:
            return v
