"""Protocol plumbing shared by all six session types.

The reference drives tss-lib `LocalParty` state machines and routes their
wire messages over NATS (pkg/mpc/session.go:97-205). Here the protocol layer
is *transport-free and deterministic*: a party object consumes/produces
:class:`RoundMsg` values; routing, signing and persistence live in higher
layers (node/, transport/). That inversion is what makes the protocol unit-
testable in-process (SURVEY.md §4 "implication for the new framework") and
batchable by the engine.

Round messages carry JSON-safe payloads (ints as decimal strings, bytes as
hex) so the wire envelope layer can serialize canonically for Ed25519
signing — mirroring types.TssMessage.MarshalForSigning (reference
pkg/types/tss.go:149-163).
"""
from __future__ import annotations

import secrets
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence


class ProtocolError(Exception):
    """Protocol violation attributable to a peer (culprit recorded)."""

    def __init__(self, message: str, culprit: Optional[str] = None):
        super().__init__(message + (f" (culprit: {culprit})" if culprit else ""))
        self.culprit = culprit


@dataclass(frozen=True)
class RoundMsg:
    """One protocol message.

    ``to`` is None for broadcast, else the recipient party ID — matching the
    reference's broadcast/unicast split (session.go:116-133).
    """

    session_id: str
    round: str
    from_id: str
    payload: Dict[str, Any]
    to: Optional[str] = None

    @property
    def is_broadcast(self) -> bool:
        return self.to is None


# ---------------------------------------------------------------------------
# snapshot codec (crash-recoverable sessions)
#
# Party state is a mix of JSON-safe payload dicts (the inbox) and protocol
# secrets: python ints, bytes, curve points, Paillier/MtA objects. The WAL
# (store/session_wal.py) needs all of it round-trippable through JSON, so
# values are encoded with explicit tags. Every *plain* dict is encoded as a
# ``{"__d": [[k, v], ...]}`` pair list, which makes the tag space
# collision-free (a real payload dict can never be mistaken for a tag) and
# preserves non-string keys (Shamir share maps are keyed by int x-coords).
# ---------------------------------------------------------------------------

_SNAP_TYPES: Dict[str, tuple] = {}  # name -> (cls, encode_fn, decode_fn)


def register_snap_type(name: str, cls, enc, dec) -> None:
    """Register a custom type for party snapshots. ``enc`` maps an instance
    to a JSON-safe value, ``dec`` inverts it."""
    _SNAP_TYPES[name] = (cls, enc, dec)


def _ensure_snap_types() -> None:
    """Lazy registration of the crypto object types every protocol party
    stores (deferred so importing protocol.base stays cheap and cycle-free)."""
    if "edpoint" in _SNAP_TYPES:
        return
    from ..core import hostmath as hm
    from ..core.paillier import PaillierPublicKey

    register_snap_type(
        "edpoint", hm.EdPoint,
        lambda p: hm.ed_compress(p).hex(),
        lambda v: hm.ed_decompress(bytes.fromhex(v)),
    )
    register_snap_type(
        "secppoint", hm.SecpPoint,
        lambda p: "" if p.is_infinity else hm.secp_compress(p).hex(),
        lambda v: hm.SECP_INF if v == "" else hm.secp_decompress(bytes.fromhex(v)),
    )
    register_snap_type(
        "paillier_pk", PaillierPublicKey,
        lambda pk: str(pk.N),
        lambda v: PaillierPublicKey(int(v)),
    )
    # a node's PreParams are drawn from the safe-prime pool at boot, so a
    # restarted process holds DIFFERENT ones — mid-keygen parties must
    # resume with the exact material their round-1 broadcast committed to
    from ..core.paillier import PreParams

    register_snap_type(
        "preparams", PreParams,
        lambda p: p.to_json(), lambda v: PreParams.from_json(v),
    )
    register_snap_type(
        "keygen_share", KeygenShare,
        lambda s: s.to_json(), lambda v: KeygenShare.from_json(v),
    )
    from .ecdsa.mta import MtaInit, MtaResp

    register_snap_type(
        "mta_init", MtaInit,
        lambda m: m.to_json(), lambda v: MtaInit.from_json(v),
    )
    register_snap_type(
        "mta_resp", MtaResp,
        lambda m: m.to_json(), lambda v: MtaResp.from_json(v),
    )


def snap_encode(v: Any) -> Any:
    """Party state → JSON-safe tagged value (see module comment above)."""
    if v is None or isinstance(v, (bool, str, float)):
        return v
    if isinstance(v, int):
        return {"__i": str(v)}
    if isinstance(v, (bytes, bytearray)):
        return {"__b": bytes(v).hex()}
    if isinstance(v, list):
        return [snap_encode(x) for x in v]
    if isinstance(v, tuple):
        return {"__t": [snap_encode(x) for x in v]}
    if isinstance(v, dict):
        return {"__d": [[snap_encode(k), snap_encode(x)] for k, x in v.items()]}
    _ensure_snap_types()
    for name, (cls, enc, _dec) in _SNAP_TYPES.items():
        if isinstance(v, cls):
            return {"__o": [name, enc(v)]}
    raise TypeError(f"snapshot cannot encode {type(v).__name__}")


def snap_decode(v: Any) -> Any:
    if v is None or isinstance(v, (bool, str, float)):
        return v
    if isinstance(v, list):
        return [snap_decode(x) for x in v]
    if isinstance(v, dict):
        if "__i" in v:
            return int(v["__i"])
        if "__b" in v:
            return bytes.fromhex(v["__b"])
        if "__t" in v:
            return tuple(snap_decode(x) for x in v["__t"])
        if "__d" in v:
            return {snap_decode(k): snap_decode(x) for k, x in v["__d"]}
        if "__o" in v:
            name, payload = v["__o"]
            _ensure_snap_types()
            if name not in _SNAP_TYPES:
                raise TypeError(f"snapshot references unknown type {name!r}")
            return _SNAP_TYPES[name][2](payload)
    # report structure only: snapshot values are decrypted WAL state and
    # may hold share material — repr() of the value must never reach an
    # exception message (handlers log str(e))
    tags = sorted(v) if isinstance(v, dict) else ()
    raise TypeError(
        f"snapshot cannot decode value of type {type(v).__name__}"
        f" (tags: {list(tags)})"
    )


def party_xs(party_ids: Sequence[str]) -> Dict[str, int]:
    """Deterministic Shamir x-coordinates: 1-based rank in the sorted ID
    list. Every party derives the same mapping from the same participant set
    (the analogue of the reference's sorted PartyID universe,
    node.go:288-301)."""
    return {pid: i + 1 for i, pid in enumerate(sorted(party_ids))}


class PartyBase:
    """Common state for a protocol party.

    Subclasses implement ``start() -> [RoundMsg]`` and
    ``receive(RoundMsg) -> [RoundMsg]``; when ``done`` flips True the
    ``result`` is available. Errors raise :class:`ProtocolError`.
    """

    def __init__(
        self,
        session_id: str,
        self_id: str,
        party_ids: Sequence[str],
        rng=secrets,
    ):
        assert self_id in party_ids
        self.session_id = session_id
        self.self_id = self_id
        self.party_ids = sorted(party_ids)
        self.xs = party_xs(self.party_ids)
        self.self_x = self.xs[self_id]
        self.rng = rng
        self.done = False
        self.result: Any = None
        # per-round inbox: round name -> {from_id: payload}
        self._inbox: Dict[str, Dict[str, Dict[str, Any]]] = {}

    # -- inbox machinery ----------------------------------------------------

    def _store(self, msg: RoundMsg) -> None:
        if msg.session_id != self.session_id:
            raise ProtocolError(
                f"message for session {msg.session_id!r} delivered to "
                f"{self.session_id!r}"
            )
        if msg.from_id not in self.xs:
            raise ProtocolError("message from non-participant", msg.from_id)
        if msg.to is not None and msg.to != self.self_id:
            # unicast not for us — transport error, drop loudly
            raise ProtocolError(f"unicast for {msg.to!r} delivered to {self.self_id!r}")
        box = self._inbox.setdefault(msg.round, {})
        if msg.from_id in box:
            # duplicate delivery is legal (at-least-once transport); ignore
            # only if identical, else a peer equivocated
            if box[msg.from_id] != msg.payload:
                raise ProtocolError(
                    f"equivocation in round {msg.round}", msg.from_id
                )
            return
        box[msg.from_id] = msg.payload

    def _round_full(self, round_name: str, expect_from: Sequence[str]) -> bool:
        box = self._inbox.get(round_name, {})
        return all(pid in box for pid in expect_from)

    def _round_payloads(self, round_name: str) -> Dict[str, Dict[str, Any]]:
        return self._inbox.get(round_name, {})

    # -- crash-recovery snapshots -------------------------------------------
    #
    # ``snapshot()`` captures the party's complete message-driven state: the
    # per-round inbox plus every attribute named in ``_SNAP_EXTRA`` (the
    # per-protocol secrets — nonces, Shamir coefficients, commitments —
    # whose loss would change the transcript on resume). ``restore()``
    # inverts it onto a freshly constructed party with the same
    # constructor arguments. Attributes that do not exist yet (rounds not
    # reached) are simply absent from the snapshot and stay absent.

    _SNAP_EXTRA: Sequence[str] = ()

    def snapshot(self) -> Dict[str, Any]:
        extra = {}
        for name in self._SNAP_EXTRA:
            if hasattr(self, name):
                extra[name] = snap_encode(getattr(self, name))
        return {
            "v": 1,
            "protocol": type(self).__name__,
            "session_id": self.session_id,
            "done": self.done,
            "result": snap_encode(self.result),
            "inbox": snap_encode(self._inbox),
            "extra": extra,
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        if snap.get("protocol") != type(self).__name__:
            raise ProtocolError(
                f"snapshot for {snap.get('protocol')!r} restored into "
                f"{type(self).__name__}"
            )
        if snap.get("session_id") != self.session_id:
            raise ProtocolError(
                f"snapshot for session {snap.get('session_id')!r} restored "
                f"into {self.session_id!r}"
            )
        self._inbox = snap_decode(snap["inbox"])
        for name, v in snap.get("extra", {}).items():
            setattr(self, name, snap_decode(v))
        self.done = bool(snap.get("done", False))
        self.result = snap_decode(snap.get("result"))
        self._post_restore()

    def _post_restore(self) -> None:
        """Recompute derived (non-serialized) state; per-protocol hook."""

    # -- helpers ------------------------------------------------------------

    def others(self) -> List[str]:
        return [p for p in self.party_ids if p != self.self_id]

    def broadcast(self, round_name: str, payload: Dict[str, Any]) -> RoundMsg:
        return RoundMsg(self.session_id, round_name, self.self_id, payload)

    def unicast(self, to: str, round_name: str, payload: Dict[str, Any]) -> RoundMsg:
        return RoundMsg(self.session_id, round_name, self.self_id, payload, to=to)


@dataclass
class KeygenShare:
    """Durable per-wallet share record (the analogue of tss-lib
    LocalPartySaveData persisted at ecdsa_keygen_session.go:102-113)."""

    key_type: str  # "ed25519" | "secp256k1"
    share: int  # Shamir share of the secret key, f(self_x)
    self_x: int
    public_key: bytes  # compressed group encoding
    vss_commitments: List[bytes] = field(default_factory=list)  # aggregated
    participants: List[str] = field(default_factory=list)
    threshold: int = 0
    # resharing generation: 0 at keygen, +1 per committee rotation. Signing
    # sessions are fenced on (epoch in keyinfo) == (epoch in share) so a
    # quorum can never mix shares from different polynomials (the reference
    # gates on IsReshared, node.go:149-159; an epoch counter subsumes it)
    epoch: int = 0
    aux: Dict[str, Any] = field(default_factory=dict)  # scheme-specific

    def to_json(self) -> Dict[str, Any]:
        return {
            "key_type": self.key_type,
            "share": str(self.share),
            "self_x": self.self_x,
            "public_key": self.public_key.hex(),
            "vss_commitments": [c.hex() for c in self.vss_commitments],
            "participants": self.participants,
            "threshold": self.threshold,
            "epoch": self.epoch,
            "aux": self.aux,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "KeygenShare":
        return cls(
            key_type=d["key_type"],
            share=int(d["share"]),
            self_x=d["self_x"],
            public_key=bytes.fromhex(d["public_key"]),
            vss_commitments=[bytes.fromhex(c) for c in d["vss_commitments"]],
            participants=list(d["participants"]),
            threshold=d["threshold"],
            epoch=int(d.get("epoch", 0)),
            aux=dict(d.get("aux", {})),
        )


class BatchBlockMixin:
    """Fixed-shape byte-block helpers shared by the batched parties
    (batch_dkg dealing rounds, ecdsa.batch_signing). Requires
    ``session_id: str`` and ``B: int`` on the host class.

    ``_bind_row`` is security-relevant: the (B, 32) session+sender row is
    hashed into every commitment/PoK so a transcript replayed from
    another session or attributed to another party mis-verifies. One
    definition, used by every batched protocol, so it cannot drift.
    """

    session_id: str
    B: int

    def _bind_row(self, pid: str):
        import hashlib

        import jax.numpy as jnp
        import numpy as np

        h = hashlib.sha256(f"{self.session_id}:{pid}".encode()).digest()
        return jnp.broadcast_to(
            jnp.asarray(np.frombuffer(h, dtype=np.uint8)), (self.B, 32)
        )

    def _parse_block(self, hexstr: str, nbytes: int, pid: str):
        import numpy as np

        try:
            raw = bytes.fromhex(hexstr)
        except ValueError:
            raise ProtocolError("non-hex block", pid)
        if len(raw) != self.B * nbytes:
            raise ProtocolError(
                f"bad block size {len(raw)} != {self.B}x{nbytes}", pid
            )
        return np.frombuffer(raw, dtype=np.uint8).reshape(self.B, nbytes)
