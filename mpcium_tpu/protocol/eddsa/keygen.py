"""Threshold Ed25519 distributed key generation.

3-round Feldman-VSS DKG matching the reference's EdDSA keygen round count
(pkg/mpc/eddsa_rounds.go:20-22 — KGRound1 commit, KGRound2Message1 unicast
share, KGRound2Message2 decommit):

  R1 (broadcast)  hash commitment to this party's Feldman commitment points
  R2a (broadcast) decommitment: C_ik = a_ik·B for the degree-t polynomial
  R2b (unicast)   Shamir share f_i(x_j) for each peer j
  finalize        verify commitments + shares, x_i = Σ_j f_j(x_i),
                  A = Σ_j C_j0, aggregate VSS commitments Σ_j C_jk

Threshold semantics follow tss-lib: ``threshold`` = t means t+1 parties are
required to sign (reference node.go passes mpc_threshold straight through).
"""
from __future__ import annotations

from typing import Dict, List, Sequence

from ...core import hostmath as hm
from .. import commitments as cm
from ..base import KeygenShare, PartyBase, ProtocolError, RoundMsg

R1 = "eddsa/kg/1"
R2_DECOMMIT = "eddsa/kg/2/decommit"
R2_SHARE = "eddsa/kg/2/share"


class EDDSAKeygenParty(PartyBase):
    # everything rng-derived before/at the last send (crash-recovery WAL)
    _SNAP_EXTRA = (
        "_sent_r2", "_coeffs", "_shares_out", "_points", "_commitment",
        "_blind",
    )

    def __init__(self, session_id, self_id, party_ids, threshold: int, rng=None):
        import secrets as _secrets

        super().__init__(session_id, self_id, party_ids, rng or _secrets)
        if not 0 < threshold < len(party_ids):
            raise ValueError("need 0 < t < n")
        self.threshold = threshold
        self._sent_r2 = False

    # -- round 1 ------------------------------------------------------------

    def start(self) -> List[RoundMsg]:
        t = self.threshold
        secret = self.rng.randbelow(hm.ED_L - 1) + 1
        self._coeffs, shares = hm.shamir_share(
            secret, t, [self.xs[p] for p in self.party_ids], hm.ED_L, rng=self.rng
        )
        self._shares_out = shares
        self._points = [
            hm.ed_compress(hm.ed_mul(c, hm.ED_B)) for c in self._coeffs
        ]
        data = cm.encode_points(self._points)
        self._commitment, self._blind = cm.commit(data, rng=self.rng)
        return [self.broadcast(R1, {"commitment": self._commitment.hex()})]

    # -- message handling ---------------------------------------------------

    def receive(self, msg: RoundMsg) -> List[RoundMsg]:
        if self.done:
            return []
        self._store(msg)
        out: List[RoundMsg] = []
        others = self.others()
        if not self._sent_r2 and self._round_full(R1, others):
            # everyone committed — safe to reveal
            self._sent_r2 = True
            out.append(
                self.broadcast(
                    R2_DECOMMIT,
                    {
                        "points": [p.hex() for p in self._points],
                        "blind": self._blind.hex(),
                    },
                )
            )
            for pid in others:
                out.append(
                    self.unicast(
                        pid,
                        R2_SHARE,
                        {"share": str(self._shares_out[self.xs[pid]])},
                    )
                )
        if (
            self._sent_r2
            and not self.done
            and self._round_full(R2_DECOMMIT, others)
            and self._round_full(R2_SHARE, others)
        ):
            self._finalize()
        return out

    # -- finalize -----------------------------------------------------------

    def _finalize(self) -> None:
        t = self.threshold
        decommits = self._round_payloads(R2_DECOMMIT)
        shares = self._round_payloads(R2_SHARE)
        commits = self._round_payloads(R1)

        all_points: Dict[str, List[hm.EdPoint]] = {
            self.self_id: [hm.ed_decompress(p) for p in self._points]
        }
        for pid in self.others():
            pts_hex = decommits[pid]["points"]
            if len(pts_hex) != t + 1:
                raise ProtocolError("wrong VSS commitment count", pid)
            blind = bytes.fromhex(decommits[pid]["blind"])
            pts_bytes = [bytes.fromhex(p) for p in pts_hex]
            if not cm.verify(
                bytes.fromhex(commits[pid]["commitment"]),
                blind,
                cm.encode_points(pts_bytes),
            ):
                raise ProtocolError("decommitment mismatch", pid)
            try:
                all_points[pid] = [hm.ed_decompress(p) for p in pts_bytes]
            except ValueError as e:
                raise ProtocolError(f"bad commitment point: {e}", pid)

        # verify Feldman shares: s_ji·B == Σ_k x_i^k · C_jk
        x_i = self._shares_out[self.self_x]
        for pid in self.others():
            s = int(shares[pid]["share"])
            if not 0 <= s < hm.ED_L:
                raise ProtocolError("share out of range", pid)
            expect = _eval_commitments(all_points[pid], self.self_x)
            if not hm.ed_mul(s, hm.ED_B).equals(expect):
                raise ProtocolError("VSS share verification failed", pid)
            x_i = (x_i + s) % hm.ED_L

        # aggregate public data
        agg: List[hm.EdPoint] = []
        for k in range(t + 1):
            acc = hm.ED_IDENT
            for pid in self.party_ids:
                acc = hm.ed_add(acc, all_points[pid][k])
            agg.append(acc)
        pub = agg[0]
        if pub.equals(hm.ED_IDENT):
            raise ProtocolError("degenerate public key")

        self.result = KeygenShare(
            key_type="ed25519",
            share=x_i,
            self_x=self.self_x,
            public_key=hm.ed_compress(pub),
            vss_commitments=[hm.ed_compress(p) for p in agg],
            participants=list(self.party_ids),
            threshold=t,
        )
        self.done = True


def _eval_commitments(points: Sequence[hm.EdPoint], x: int) -> hm.EdPoint:
    """Σ_k x^k · C_k (Horner over the group)."""
    acc = hm.ED_IDENT
    for pt in reversed(points):
        acc = hm.ed_add(hm.ed_mul(x, acc), pt)
    return acc
