"""Distributed batched threshold-Ed25519 signing: ONE protocol instance
signs B wallets' digests concurrently.

This is the node-side face of the TPU batch engine (SURVEY.md §7.2 step 5):
where :mod:`.signing` runs one session per wallet (per-session goroutine
concurrency in the reference, event_consumer.go:295-338), this party
exchanges fixed-shape BYTE BLOCKS — (B·32)-byte commitment/nonce/partial
blocks — and computes each round with one :mod:`engine.eddsa_batch`
dispatch. The scheduler (consumers.batch_scheduler) buckets concurrent
signing requests into these batches.

Protocol (same 3-round commit–reveal threshold Schnorr as .signing, over
the batch):

  R1 (broadcast) hash commitment to this party's (B, 32) nonce block
  R2 (broadcast) decommit: nonce block + blind
  R3 (broadcast) partial-signature block (B, 32)
  finalize       combine + batched RFC 8032 verification → per-session ok

A failed session (bad point, verification miss) fails ONLY its lane: the
result carries a per-session ok mask so the scheduler can emit per-tx
success/error events. Commitment fraud aborts the whole batch with the
culprit attributed (same abort semantics as the per-session protocol).
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core import bignum as bn
from ...core import hostmath as hm
from ...engine import eddsa_batch as eb
from ...engine import pipeline as pl
from ...perf import compile_watch
from ...utils import tracing
from ..base import KeygenShare, PartyBase, ProtocolError, RoundMsg, party_xs

R1_COMMIT = "eddsa/bsign/1/commit"
R2_REVEAL = "eddsa/bsign/2/reveal"
R3_PARTIAL = "eddsa/bsign/3/partial"


def _block_commit(blind: bytes, block: bytes, bind: bytes) -> str:
    return hashlib.sha256(
        b"mpcium-tpu/bsign/" + bind + blind + block
    ).hexdigest()


def _span_sync(tensors) -> None:
    """Materialize a cohort's device-phase result before its span closes
    so the interval is honest device time — only when tracing is armed
    (untraced runs never sync here; engine PhaseTimer discipline)."""
    if tracing.enabled():
        jax.block_until_ready(tensors)  # mpcflow: host-ok — trace instrumentation, only when tracing is armed


class BatchedEDDSASigningParty(PartyBase):
    """One signer's side of a B-session batch.

    ``shares``: this node's key shares, one per wallet (batch order is the
    manifest order, identical on every quorum member). ``messages``: the
    B digests/transactions to sign. All wallets must share the signing
    quorum (``party_ids``); universes may differ per wallet (λ is computed
    per wallet from its own keygen universe).
    """

    def __init__(
        self,
        session_id: str,
        self_id: str,
        party_ids: Sequence[str],
        shares: Sequence[KeygenShare],
        messages: Sequence[bytes],
        rng=None,
        cohorts: Optional[int] = None,
    ):
        import secrets as _secrets

        super().__init__(session_id, self_id, party_ids, rng or _secrets)
        self._cohorts = cohorts
        if len(shares) != len(messages) or not shares:
            raise ValueError("one share per message required")
        self.B = len(shares)
        self.messages = [bytes(m) for m in messages]
        lamx = []
        for s in shares:
            if s.key_type != "ed25519":
                raise ProtocolError("wrong key type for EdDSA batch signing")
            if len(party_ids) < s.threshold + 1:
                raise ProtocolError("not enough participants for threshold")
            xs = party_xs(s.participants)
            for pid in party_ids:
                if pid not in xs:
                    raise ProtocolError("signer not in keygen universe", pid)
            if xs[self_id] != s.self_x:
                raise ProtocolError("share does not belong to this node")
            quorum_xs = [xs[p] for p in self.party_ids]
            lam = hm.lagrange_coeff(quorum_xs, xs[self_id], hm.ED_L)
            lamx.append(lam * s.share % hm.ED_L)
        self.lamx = eb.scalars_to_limb_batch(lamx)
        self.A_comp = np.stack(
            [np.frombuffer(s.public_key, dtype=np.uint8) for s in shares]
        )
        self._stage = 0

    # -- rounds --------------------------------------------------------------

    def _bind(self) -> bytes:
        return f"{self.session_id}:{self.self_id}".encode()

    def start(self) -> List[RoundMsg]:
        # party-level compile signature: the whole 3-round session is one
        # shape bucket — first session per (B, q) pays the warmup, later
        # ones cost a set lookup (engine-level begin sites nest inside)
        B, q = self.B, len(self.party_ids)
        # mpcshape: unbounded-ok — B is pow-2 snapped upstream (scheduler chunks via engine/buckets.floor_bucket; bench via bucket_b)
        self._cw = compile_watch.begin("party.eddsa", f"B{B}|q{q}")
        # counter-phase cohort schedule (engine/pipeline): nonces for the
        # FULL batch are drawn first in K=1 serial order, then row-sliced
        # per cohort, so wire blocks are bit-identical for every K
        self._plan = pl.CohortPlan.for_batch(B, self._cohorts)
        r64 = eb.fresh_nonce_bytes(self.B, self.rng)

        # device-phase spans: each cohort's round syncs its result before
        # the span closes (only when traced), so the interval is honest
        # device time; byte packing runs as a host:* pipeline stage
        def make_job(ci: int, sl: slice):
            def job():
                with tracing.span(
                    "phase:bsign_nonce_commit",
                    batch=sl.stop - sl.start, cohort=ci,
                ):
                    r_limbs, R_comp = eb.nonce_commitments(eb.to_dev(r64[sl]))
                    _span_sync(R_comp)
                block = yield (
                    "nonce_egress",
                    lambda: np.asarray(R_comp).tobytes(),
                )
                return r_limbs, block

            return job

        outs = pl.run_counter_phase(
            [make_job(ci, sl) for ci, sl in enumerate(self._plan.slices())]
        )
        self._r_limbs_c = [r for r, _ in outs]
        self._R_block = b"".join(blk for _, blk in outs)  # B·32 bytes
        self._blind = self.rng.token_bytes(32)
        commit = _block_commit(self._blind, self._R_block, self._bind())
        self._stage = 1
        return [self.broadcast(R1_COMMIT, {"commit": commit})]

    def receive(self, msg: RoundMsg) -> List[RoundMsg]:
        if self.done:
            return []
        self._store(msg)
        others = self.others()
        out: List[RoundMsg] = []
        if self._stage == 1 and self._round_full(R1_COMMIT, others):
            out.append(
                self.broadcast(
                    R2_REVEAL,
                    {"R": self._R_block.hex(), "blind": self._blind.hex()},
                )
            )
            self._stage = 2
        if self._stage == 2 and self._round_full(R2_REVEAL, others):
            out.append(self._round3())
            self._stage = 3
        if self._stage == 3 and self._round_full(R3_PARTIAL, others):
            self._finalize()
        return out

    def _peer_blocks(self, round_name: str, field: str, nbytes: int) -> Dict[str, bytes]:
        payloads = self._round_payloads(round_name)
        out = {}
        for pid, p in payloads.items():
            b = bytes.fromhex(p[field])
            if len(b) != nbytes:
                raise ProtocolError(f"bad {field} block size", pid)
            out[pid] = b
        return out

    def _round3(self) -> RoundMsg:
        commits = self._round_payloads(R1_COMMIT)
        reveals = self._round_payloads(R2_REVEAL)
        R_blocks: List[bytes] = []
        for pid in self.party_ids:
            if pid == self.self_id:
                R_blocks.append(self._R_block)
                continue
            blk = bytes.fromhex(reveals[pid]["R"])
            if len(blk) != self.B * 32:
                raise ProtocolError("bad nonce block size", pid)
            bind = f"{self.session_id}:{pid}".encode()
            if (
                _block_commit(bytes.fromhex(reveals[pid]["blind"]), blk, bind)
                != commits[pid]["commit"]
            ):
                raise ProtocolError("nonce commitment fraud", pid)
            R_blocks.append(blk)
        R_all = np.stack(
            [np.frombuffer(b, dtype=np.uint8).reshape(self.B, 32) for b in R_blocks]
        )

        def make_job(ci: int, sl: slice):
            def job():
                with tracing.span(
                    "phase:bsign_aggregate_partial",
                    batch=sl.stop - sl.start, cohort=ci,
                ):
                    R_sum, ok_R = eb.aggregate_nonce(
                        eb.to_dev(R_all[:, sl], axis=1)
                    )
                    R_sum_h = np.asarray(R_sum)  # mpcflow: host-ok — R enters the host challenge hash
                    c64 = eb.challenge_hashes(
                        R_sum_h, self.A_comp[sl], self.messages[sl]
                    )
                    parts = eb.partial_signature(
                        self._r_limbs_c[ci], eb.to_dev(c64),
                        eb.to_dev(self.lamx[sl]),
                    )
                    _span_sync(parts)
                egress = yield (
                    "partial_egress",
                    lambda: (
                        np.asarray(bn.limbs_to_bytes_le(parts, bn.P256, 32)),
                        np.asarray(ok_R),
                    ),
                )
                return R_sum_h, np.asarray(c64), parts, egress

            return job

        outs = pl.run_counter_phase(
            [make_job(ci, sl) for ci, sl in enumerate(self._plan.slices())]
        )
        self._R_sum = pl.merge_rows([o[0] for o in outs])
        self._c64 = pl.merge_rows([o[1] for o in outs])
        self._parts_c = [o[2] for o in outs]
        self._ok_R = pl.merge_rows([o[3][1] for o in outs])
        s_block = pl.merge_rows([o[3][0] for o in outs])
        return self.broadcast(R3_PARTIAL, {"s": s_block.tobytes().hex()})

    def _finalize(self) -> None:
        blocks = self._peer_blocks(R3_PARTIAL, "s", self.B * 32)
        peer_rows = {
            pid: np.frombuffer(blocks[pid], dtype=np.uint8).reshape(self.B, 32)
            for pid in self.party_ids
            if pid != self.self_id
        }

        def make_job(ci: int, sl: slice):
            def job():
                with tracing.span(
                    "phase:bsign_combine_verify",
                    batch=sl.stop - sl.start, cohort=ci,
                ):
                    stacked = [self._parts_c[ci]]
                    for pid in self.party_ids:
                        if pid == self.self_id:
                            continue
                        stacked.append(
                            bn.bytes_to_limbs_le(
                                jnp.asarray(peer_rows[pid][sl]),
                                bn.P256, bn.P256.n_limbs,
                            )
                        )
                    parts = jnp.stack(stacked)
                    sigs, _s = eb.combine_signatures(
                        parts, eb.to_dev(self._R_sum[sl])
                    )
                    ok = eb.verify_signatures(
                        sigs, eb.to_dev(self.A_comp[sl]),
                        eb.to_dev(self._c64[sl]),
                    )
                    _span_sync(ok)
                egress = yield (
                    "sig_egress",
                    lambda: (np.asarray(sigs), np.asarray(ok)),
                )
                return egress

            return job

        outs = pl.run_counter_phase(
            [make_job(ci, sl) for ci, sl in enumerate(self._plan.slices())]
        )
        self.result = {
            "signatures": pl.merge_rows([o[0] for o in outs]),
            "ok": pl.merge_rows([o[1] for o in outs]) & self._ok_R,
        }
        self.done = True
        compile_watch.finish(self._cw)
