"""Threshold Ed25519 signing.

3-round commit–reveal threshold Schnorr matching the reference's EdDSA
signing round count (pkg/mpc/eddsa_rounds.go:23-25):

  R1 (broadcast)  hash commitment to the nonce share point R_i = r_i·B
  R2 (broadcast)  decommitment: R_i
  R3 (broadcast)  partial signature s_i = r_i + H(R‖A‖M)·λ_i·x_i mod l
  finalize        s = Σ s_i; (R, s) must verify under RFC 8032

The commitment round makes concurrent signing safe against ROS/Drijvers
style nonce-bias attacks (each R_i is fixed before any is revealed). The
final signature is a standard RFC 8032 Ed25519 signature over the wallet
public key A — byte-compatible with the reference's output
(eddsa_signing_session.go:147 verifies with edwards.Verify).

Note: threshold signatures cannot use RFC 8032's *deterministic* nonce
derivation (no party knows the full private key); nonces are random, as in
the reference (tss-lib eddsa/signing).
"""
from __future__ import annotations

import os
from typing import List, Sequence

from ...core import hostmath as hm
from .. import commitments as cm
from ..base import KeygenShare, PartyBase, ProtocolError, RoundMsg

R1 = "eddsa/sign/1"
R2 = "eddsa/sign/2"
R3 = "eddsa/sign/3"


def _challenge_int(R_bytes: bytes, A_bytes: bytes, message: bytes) -> int:
    """RFC 8032 challenge H(R ‖ A ‖ M) as a little-endian integer.

    Default: host hashlib (hm.sha512_int_le) — one digest per session is
    control-plane. MPCIUM_EDDSA_DEVICE_HASH_SESSION=1 routes it through
    the device SHA-512 kernel instead (ops.hash_suite.sha512_bytes;
    byte-identical — useful for validating the kernel against the
    per-session oracle on a new platform; the batch engine's fused path
    is engine/eddsa_batch.challenge_device)."""
    if os.environ.get("MPCIUM_EDDSA_DEVICE_HASH_SESSION", "0") == "1":
        from ...ops.hash_suite import sha512_bytes

        return int.from_bytes(
            sha512_bytes(R_bytes + A_bytes + message), "little"
        )
    return hm.sha512_int_le(R_bytes, A_bytes, message)


class EDDSASigningParty(PartyBase):
    """One signer among the chosen quorum (|party_ids| ≥ t+1 participants,
    all of whom hold keygen shares for this wallet)."""

    # nonce + commitments: a resumed signer MUST reuse the exact r_i it
    # committed to, or peers see a decommitment mismatch (crash-recovery WAL)
    _SNAP_EXTRA = (
        "_sent_r2", "_sent_r3", "_r", "_R_i", "_R_i_bytes", "_commitment",
        "_blind", "_R_bytes", "_s_i", "_c",
    )

    def __init__(
        self,
        session_id: str,
        self_id: str,
        party_ids: Sequence[str],
        share: KeygenShare,
        message: bytes,
        rng=None,
    ):
        import secrets as _secrets

        super().__init__(session_id, self_id, party_ids, rng or _secrets)
        if len(party_ids) < share.threshold + 1:
            raise ProtocolError("not enough participants for threshold")
        if share.key_type != "ed25519":
            raise ValueError("wrong key type for EdDSA signing")
        self.share = share
        self.message = message
        # Shamir x-coords come from the keygen participant universe, NOT the
        # signing quorum — the reference reconstructs the same party universe
        # from keyinfo (node.go:149-159)
        from ..base import party_xs

        keygen_xs = party_xs(share.participants)
        for pid in party_ids:
            if pid not in keygen_xs:
                raise ProtocolError("signer not in keygen participant set", pid)
        self.sign_xs = {pid: keygen_xs[pid] for pid in self.party_ids}
        # PartyBase assigned quorum-local x-coords; Shamir evaluation points
        # MUST come from the keygen universe or Lagrange interpolation is
        # silently wrong for any quorum that isn't a sorted prefix.
        self.xs = self.sign_xs
        self.self_x = self.sign_xs[self_id]
        assert self.self_x == share.self_x
        self._sent_r2 = False
        self._sent_r3 = False

    # -- round 1 ------------------------------------------------------------

    def start(self) -> List[RoundMsg]:
        self._r = self.rng.randbelow(hm.ED_L - 1) + 1
        self._R_i = hm.ed_mul(self._r, hm.ED_B)
        self._R_i_bytes = hm.ed_compress(self._R_i)
        self._commitment, self._blind = cm.commit(self._R_i_bytes, rng=self.rng)
        return [self.broadcast(R1, {"commitment": self._commitment.hex()})]

    # -- message handling ---------------------------------------------------

    def receive(self, msg: RoundMsg) -> List[RoundMsg]:
        if self.done:
            return []
        self._store(msg)
        out: List[RoundMsg] = []
        others = self.others()
        if not self._sent_r2 and self._round_full(R1, others):
            self._sent_r2 = True
            out.append(
                self.broadcast(
                    R2,
                    {"R": self._R_i_bytes.hex(), "blind": self._blind.hex()},
                )
            )
        if (
            self._sent_r2
            and not self._sent_r3
            and self._round_full(R2, others)
        ):
            out.append(self._round3())
        if self._sent_r3 and not self.done and self._round_full(R3, others):
            self._finalize()
        return out

    # -- round 3: partial signature -----------------------------------------

    def _round3(self) -> RoundMsg:
        self._sent_r3 = True
        commits = self._round_payloads(R1)
        decommits = self._round_payloads(R2)
        R_points = {self.self_id: self._R_i}
        for pid in self.others():
            Rb = bytes.fromhex(decommits[pid]["R"])
            if not cm.verify(
                bytes.fromhex(commits[pid]["commitment"]),
                bytes.fromhex(decommits[pid]["blind"]),
                Rb,
            ):
                raise ProtocolError("nonce decommitment mismatch", pid)
            try:
                R_points[pid] = hm.ed_decompress(Rb)
            except ValueError as e:
                raise ProtocolError(f"bad nonce point: {e}", pid)

        R = hm.ED_IDENT
        for pid in self.party_ids:
            R = hm.ed_add(R, R_points[pid])
        self._R_bytes = hm.ed_compress(R)

        c = _challenge_int(
            self._R_bytes, self.share.public_key, self.message
        ) % hm.ED_L
        lam = hm.lagrange_coeff(
            list(self.sign_xs.values()), self.self_x, hm.ED_L
        )
        self._s_i = (self._r + c * lam * self.share.share) % hm.ED_L  # mpcflow: declassified — partial response sᵢ is the R3 broadcast
        self._c = c
        return self.broadcast(R3, {"s": str(self._s_i)})


    # -- finalize -----------------------------------------------------------

    def _finalize(self) -> None:
        partials = self._round_payloads(R3)
        s = 0
        for pid in self.party_ids:
            if pid == self.self_id:
                continue
            v = int(partials[pid]["s"])
            if not 0 <= v < hm.ED_L:
                raise ProtocolError("partial signature out of range", pid)
            s = (s + v) % hm.ED_L
        # add own partial (the exact value broadcast in round 3)
        s = (s + self._s_i) % hm.ED_L
        sig = self._R_bytes + s.to_bytes(32, "little")
        # local verification before publishing, as the reference does
        # (eddsa_signing_session.go:147)
        if not hm.ed25519_verify(self.share.public_key, self.message, sig):
            raise ProtocolError("aggregate signature failed verification")
        self.result = sig
        self.done = True
