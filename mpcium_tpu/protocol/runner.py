"""In-process protocol driver: runs a set of parties to completion.

The deterministic test fabric (SURVEY.md §4): messages route synchronously,
broadcast fan-out + unicast, until every party reports done. Production
routing happens over the transport layer instead; this runner pins protocol
correctness independent of transport.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List

from .base import PartyBase


def run_protocol(parties: Dict[str, PartyBase], max_msgs: int = 100_000) -> None:
    """Drive all parties until done. Raises on protocol errors/stalls."""
    queue: deque = deque()
    # sorted: every member must walk the peer set identically (dict order
    # is insertion order, which differs per node) — MPL202
    for _pid, party in sorted(parties.items()):
        for m in party.start():
            queue.append(m)
    delivered = 0
    while queue:
        msg = queue.popleft()
        delivered += 1
        if delivered > max_msgs:
            raise RuntimeError("protocol did not converge (message storm)")
        targets: List[PartyBase] = (
            [p for pid, p in sorted(parties.items()) if pid != msg.from_id]
            if msg.is_broadcast
            else [parties[msg.to]]
        )
        for t in targets:
            for out in t.receive(msg):
                queue.append(out)
    stalled = [pid for pid, p in sorted(parties.items()) if not p.done]
    if stalled:
        raise RuntimeError(f"protocol stalled; undone parties: {stalled}")
