"""In-process protocol driver: runs a set of parties to completion.

The deterministic test fabric (SURVEY.md §4): messages route synchronously,
broadcast fan-out + unicast, until every party reports done. Production
routing happens over the transport layer instead; this runner pins protocol
correctness independent of transport.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List

from ..utils import tracing
from .base import PartyBase


def run_protocol(parties: Dict[str, PartyBase], max_msgs: int = 100_000) -> None:
    """Drive all parties until done. Raises on protocol errors/stalls."""
    queue: deque = deque()
    # mpctrace: the in-process fabric plays "every node" — pid is the
    # party id, one shared trace id for the whole run. Spans only exist
    # when tracing is armed; message flow is identical either way.
    run_tid = tracing.trace_id_for("runner:" + "|".join(sorted(parties)))
    # sorted: every member must walk the peer set identically (dict order
    # is insertion order, which differs per node) — MPL202
    for _pid, party in sorted(parties.items()):
        with tracing.span("round:start", trace_id=run_tid, node=_pid,
                          tid="runner"):
            for m in party.start():
                queue.append(m)
    delivered = 0
    while queue:
        msg = queue.popleft()
        delivered += 1
        if delivered > max_msgs:
            raise RuntimeError("protocol did not converge (message storm)")
        targets: List[PartyBase] = (
            [p for pid, p in sorted(parties.items()) if pid != msg.from_id]
            if msg.is_broadcast
            else [parties[msg.to]]
        )
        for t in targets:
            with tracing.span(f"round:{msg.round}", trace_id=run_tid,
                              node=t.self_id, tid="runner",
                              sender=msg.from_id):
                for out in t.receive(msg):
                    queue.append(out)
    stalled = [pid for pid, p in sorted(parties.items()) if not p.done]
    if stalled:
        raise RuntimeError(f"protocol stalled; undone parties: {stalled}")
