"""Hash commitments (commit–reveal) used by every round-1 protocol step.

The reference inherits tss-lib's HashCommitment scheme; functionally this is
commit = H(blind ‖ data) with a fresh 256-bit blinding factor, revealed in
the decommit round. Domain-separated SHA-256.
"""
from __future__ import annotations

import hashlib
import hmac
import secrets
from typing import Sequence, Tuple

_DOMAIN = b"mpcium-tpu/commit/v1"


def commit(data: bytes, rng=secrets) -> Tuple[bytes, bytes]:
    """→ (commitment, blinding)."""
    blind = rng.token_bytes(32) if hasattr(rng, "token_bytes") else bytes(
        rng.randbelow(256) for _ in range(32)
    )
    return hashlib.sha256(_DOMAIN + blind + data).digest(), blind


def verify(commitment: bytes, blind: bytes, data: bytes) -> bool:
    expect = hashlib.sha256(_DOMAIN + blind + data).digest()
    return hmac.compare_digest(expect, commitment)


def encode_points(points: Sequence[bytes]) -> bytes:
    """Length-prefixed canonical concatenation of point encodings."""
    out = [len(points).to_bytes(4, "big")]
    for p in points:
        out.append(len(p).to_bytes(2, "big"))
        out.append(p)
    return b"".join(out)
