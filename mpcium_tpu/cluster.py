"""In-process development cluster — the docker-compose-equivalent dev stack.

Assembles n nodes over the loopback fabric with real identities, encrypted
share stores, registries and consumers, plus a client. This is what the
reference achieves with NATS + Consul + 3 daemon processes +
setup_identities.sh (SURVEY.md §2.1 #32); here it is one object for tests,
examples and local development. Production deployments wire the same
pieces against the TCP transport and a shared control-plane KV instead.
"""
from __future__ import annotations

import json
import tempfile
from pathlib import Path
from typing import Dict, List, Optional

from . import wire
from .client.client import MPCClient
from .consumers.event_consumer import EventConsumer
from .consumers.signing_consumer import SigningConsumer, TimeoutConsumer
from .core.paillier import PreParams
from .identity.identity import IdentityStore, InitiatorKey, generate_identity
from .node.node import Node
from .registry.registry import PeerRegistry
from .store.keyinfo import KeyinfoStore
from .store.kvstore import EncryptedFileKV, MemoryKV
from .trace import arm as _trace_arm
from .trace import snapshot_chrome as _trace_snapshot_chrome
from .transport.loopback import LoopbackFabric
from .utils import log


class _NotMine(Exception):
    """Result event for a different operation: raising naks it back to
    the work queue (transport/api.py:20 contract) so a concurrent waiter
    can dequeue it, instead of silently ack-and-discarding another
    client's result. (Like the reference, result queues remain work
    queues — one dequeuer wins per event; unclaimed mismatches
    eventually dead-letter after max redeliveries.)"""


class SyncOps:
    """Blocking convenience wrappers over an :class:`MPCClient` at
    ``self.client`` — shared by :class:`LocalCluster` (in-process) and
    :class:`RemoteCluster` (networked broker)."""

    @staticmethod
    def _await_result(subscribe, fire, matches, timeout_s, what: str):
        import threading

        done = threading.Event()
        box: list = []

        def on_ev(ev):
            if not matches(ev):
                raise _NotMine(what)
            box.append(ev)
            done.set()

        sub = subscribe(on_ev)
        try:
            fire()
            if not done.wait(timeout_s):
                raise TimeoutError(f"{what} produced no result in time")
            return box[0]
        finally:
            sub.unsubscribe()

    def create_wallet_sync(
        self, wallet_id: str, timeout_s: float = 600.0
    ) -> wire.KeygenSuccessEvent:
        # keygen results land on per-wallet topics — subscribe to OUR
        # wallet's topic so concurrent clients on one broker never
        # round-robin-steal (and after max_deliver naks, dead-letter)
        # each other's results. The matches() predicate stays as a
        # belt-and-braces check.
        ev = self._await_result(
            lambda h: self.client.on_wallet_creation_result(
                h, wallet_id=wallet_id
            ),
            lambda: self.client.create_wallet(wallet_id),
            lambda ev: ev.wallet_id == wallet_id,
            timeout_s,
            f"wallet {wallet_id!r} creation",
        )
        if ev.result_type != wire.RESULT_SUCCESS:
            raise RuntimeError(f"keygen failed: {ev.error_reason}")
        return ev

    def sign_sync(
        self, msg: wire.SignTxMessage, timeout_s: float = 600.0
    ) -> wire.SigningResultEvent:
        return self._await_result(
            lambda h: self.client.on_sign_result(h, tx_id=msg.tx_id),
            lambda: self.client.sign_transaction(msg),
            lambda ev: ev.tx_id == msg.tx_id,
            timeout_s,
            f"tx {msg.tx_id!r}",
        )

    def reshare_sync(
        self, wallet_id: str, new_threshold: int, key_type: str,
        timeout_s: float = 600.0,
    ) -> wire.ResharingSuccessEvent:
        ev = self._await_result(
            lambda h: self.client.on_resharing_result(h, wallet_id=wallet_id),
            lambda: self.client.resharing(wallet_id, new_threshold, key_type),
            lambda ev: ev.wallet_id == wallet_id and ev.key_type == key_type,
            timeout_s,
            f"wallet {wallet_id!r} resharing",
        )
        if ev.result_type != wire.RESULT_SUCCESS:
            raise RuntimeError(f"resharing failed: {ev.error_reason}")
        return ev


class LocalCluster(SyncOps):
    """n identical in-process MPC nodes + a client over loopback."""

    def __init__(
        self,
        n_nodes: int = 3,
        threshold: int = 2,
        root_dir: Optional[str] = None,
        preparams: Optional[Dict[str, PreParams]] = None,
        store_password: str = "dev-password",
        min_paillier_bits: int = 2046,
        reply_timeout_s: float = 30.0,
        transport: str = "loopback",  # "loopback" | "tcp"
        batch_signing: bool = False,
        batch_window_s: float = 0.05,
        fault_plans: Optional[Dict] = None,  # node_id|"*"|"client" → FaultPlan
        broker_standby: bool = False,  # tcp only: hot-standby broker pair
        hello_timeout_s: Optional[float] = 20.0,
        session_timeout_s: Optional[float] = None,  # EventConsumer GC knobs
        gc_interval_s: Optional[float] = None,  # (chaos drills shrink both)
        session_wal: bool = False,  # encrypted per-round WAL + crash resume
        batch_max_batch: Optional[int] = None,  # SLO batching knobs (None =
        batch_deadline_ms: Optional[int] = None,  # config defaults; see
        batch_max_queue_depth: Optional[int] = None,  # config.py batch_*)
        batch_manifest_timeout_s: Optional[float] = None,
    ):
        from .config import init_config

        self.root = Path(root_dir or tempfile.mkdtemp(prefix="mpcium-tpu-"))
        self.node_ids = [f"node{i}" for i in range(n_nodes)]
        # flight recorder is always on for clusters: bounded per-node ring
        # buffers, merged on demand by trace_snapshot(); incident dumps land
        # under the cluster root so drills can attach them to reports
        _trace_arm(node_ids=self.node_ids,
                   dump_dir=str(self.root / "trace_incidents"))
        # None overrides are skipped by init_config → config defaults apply
        init_config(path=str(self.root / "nonexistent.yaml"),
                    mpc_threshold=threshold,
                    batch_max_batch=batch_max_batch,
                    batch_deadline_ms=batch_deadline_ms,
                    batch_max_queue_depth=batch_max_queue_depth,
                    batch_manifest_timeout_s=batch_manifest_timeout_s)
        self.broker = None
        self.standby_broker = None
        if transport == "tcp":
            from .transport.tcp import BrokerServer, tcp_transport

            self.broker = BrokerServer(port=0)
            standbys = None
            if broker_standby:
                self.standby_broker = BrokerServer(
                    port=0, follow=(self.broker.host, self.broker.port)
                )
                assert self.standby_broker._rep_synced.wait(10), (
                    "standby broker never synced to primary"
                )
                standbys = [(self.standby_broker.host,
                             self.standby_broker.port)]
            self._mk_transport = lambda: tcp_transport(
                self.broker.host, self.broker.port, standbys=standbys
            )
            self.fabric = None
        else:
            self.fabric = LoopbackFabric()
            self._mk_transport = self.fabric.transport
        # fault-injection seam (mpcium_tpu/faults): nodes with a plan get
        # their transport wrapped; with no plan nothing is constructed and
        # behavior is byte-identical to a bare cluster
        self._fault_plans = fault_plans or {}
        self.fault_transports: Dict[str, object] = {}
        self._retired_fault_transports: List[object] = []
        self._hello_timeout_s = hello_timeout_s
        self.control_kv = MemoryKV()  # the Consul analogue

        # identities (setup_identities.sh equivalent)
        ident_dir = self.root / "identity"
        for nid in self.node_ids:
            generate_identity(nid, ident_dir)
        self.initiator = InitiatorKey.generate()

        # per-node ctor state, retained so respawn_node() can rebuild a
        # killed node's runtime stack over its surviving on-disk state
        self._ident_dir = ident_dir
        self._peers = {nid: nid for nid in self.node_ids}
        self._store_password = store_password
        self._min_paillier_bits = min_paillier_bits
        self._preparams = preparams or {}
        self._session_wal = session_wal
        self._batch_signing = batch_signing
        self._batch_window_s = batch_window_s
        self._reply_timeout_s = reply_timeout_s
        self._ec_kw: Dict[str, float] = {}
        if session_timeout_s is not None:
            self._ec_kw["session_timeout_s"] = session_timeout_s
        if gc_interval_s is not None:
            self._ec_kw["gc_interval_s"] = gc_interval_s

        self.nodes: Dict[str, Node] = {}
        self.consumers: List[EventConsumer] = []
        self.signing_consumers: List[SigningConsumer] = []
        self.node_consumers: Dict[str, EventConsumer] = {}
        for nid in self.node_ids:
            self._spawn_node(nid)
        for node in self.nodes.values():
            assert node.registry.wait_all_ready(10), "cluster failed to form"
        log.info("local cluster ready", nodes=n_nodes, threshold=threshold)
        self.client = MPCClient(
            self._wrap_faults("client", self._mk_transport()), self.initiator
        )

    def _spawn_node(self, nid: str) -> EventConsumer:
        """Build one node's full runtime stack — identity, encrypted share
        store (at its canonical on-disk path), optional session-WAL store,
        registry, transport, Node, consumers — exactly the daemon boot
        sequence. Used at cluster construction and by :meth:`respawn_node`."""
        identity = IdentityStore(
            self._ident_dir, nid, self._peers,
            initiator_pubkey=self.initiator.public_bytes,
        )
        kv = EncryptedFileKV(self.root / "db" / nid, self._store_password)
        wal = None
        if self._session_wal:
            from .store.session_wal import SessionWALStore

            wal = SessionWALStore(kv)
        registry = PeerRegistry(
            nid, self.node_ids, self.control_kv, poll_interval_s=0.05
        )
        transport = self._wrap_faults(nid, self._mk_transport())
        node = Node(
            node_id=nid,
            peer_ids=self.node_ids,
            transport=transport,
            identity=identity,
            kvstore=kv,
            keyinfo=KeyinfoStore(self.control_kv),
            registry=registry,
            preparams=self._preparams.get(nid),
            min_paillier_bits=self._min_paillier_bits,
            hello_timeout_s=self._hello_timeout_s,
            session_wal=wal,
        )
        self.nodes[nid] = node
        ec = EventConsumer(
            node, transport,
            batch_signing=self._batch_signing,
            batch_window_s=self._batch_window_s,
            **self._ec_kw,
        )
        ec.run()
        self.consumers.append(ec)
        self.node_consumers[nid] = ec
        sc = SigningConsumer(transport, reply_timeout_s=self._reply_timeout_s)
        sc.run()
        self.signing_consumers.append(sc)
        TimeoutConsumer(transport).run()
        registry.ready()
        return ec

    def respawn_node(self, node_id: str) -> EventConsumer:
        """In-process 'restart after SIGKILL': rebuild ``node_id``'s entire
        runtime over its surviving on-disk state (identity keys, encrypted
        share store, session WALs) the way a fresh daemon boot would, then
        replay incomplete WAL sessions. The dead incarnation's objects are
        deliberately left in place — a killed process never cleans up; its
        crashed transport keeps black-holing whatever still reaches it."""
        old_ft = self.fault_transports.pop(node_id, None)
        if old_ft is not None:
            self._retired_fault_transports.append(old_ft)
        ec = self._spawn_node(node_id)
        # boot-time crash recovery, after ready() — mirrors daemon.run_node
        ec.resume_incomplete()
        return ec

    def health(self) -> Dict[str, dict]:
        """Per-node operational snapshots (EventConsumer.health): live
        sessions, dedup claims, and every scheduler metric — lane queue
        depths, shed counters, fill ratios, latency percentiles."""
        return {nid: ec.health() for nid, ec in self.node_consumers.items()}

    def metrics_snapshot(self) -> Dict[str, dict]:
        """Just the metric registries, keyed by node id (the soak harness
        and smoke tests consume this)."""
        return {
            nid: ec.metrics.snapshot()
            for nid, ec in self.node_consumers.items()
        }

    def trace_snapshot(self, clear: bool = False,
                       meta: Optional[dict] = None) -> dict:
        """Merge every node's flight-recorder ring buffer (plus the shared
        engine/client tracks) into one Chrome-trace-event JSON document —
        pid = node, tid = session/lane — loadable in Perfetto / chrome://
        tracing. Buffers survive :meth:`close`, so drills can snapshot
        after teardown."""
        return _trace_snapshot_chrome(clear=clear, meta=meta)

    def prometheus_text(self) -> str:
        """Prometheus text exposition for the whole cluster: each node's
        registry rendered with a ``node`` label, concatenated."""
        return "".join(
            ec.metrics.to_prometheus(labels={"node": nid})
            for nid, ec in self.node_consumers.items()
        )

    def _wrap_faults(self, owner: str, transport):
        """Wrap ``transport`` in a FaultyTransport when a fault plan is
        installed for ``owner`` (or under the "*" wildcard). No plan ⇒
        the bare transport passes through untouched."""
        plan = self._fault_plans.get(owner) or (
            self._fault_plans.get("*") if owner != "client" else None
        )
        if plan is None:
            return transport
        from .faults.transport import FaultyTransport

        ft = FaultyTransport(transport, owner, plan)
        self.fault_transports[owner] = ft
        return ft

    def close(self) -> None:
        for ec in self.consumers:
            try:
                ec.close()
            except Exception as e:  # noqa: BLE001 — dead incarnations may
                log.warn("consumer close failed", error=repr(e))  # throw
        for sc in self.signing_consumers:
            sc.close()
        for node in self.nodes.values():
            node.registry.resign()
        for ft in list(self.fault_transports.values()) + \
                self._retired_fault_transports:
            ft.close()
        if self.fabric is not None:
            self.fabric.close()
        if self.broker is not None:
            self.broker.close()
        if self.standby_broker is not None:
            self.standby_broker.close()


class RemoteCluster(SyncOps):
    """Client-side handle to an ALREADY RUNNING networked deployment
    (broker + daemons — the docker-compose topology): the analogue of the
    reference examples connecting to a live NATS+Consul stack
    (INSTALLATION.md "Start Mpcium Nodes"; examples/generate/main.go).

    Reads broker endpoint/auth/encryption from the same config file the
    daemons use and loads the initiator's PRIVATE key (default:
    ``event_initiator.key`` next to the config, the client.go:64-146
    layout)."""

    def __init__(
        self,
        config_path: str,
        initiator_key_path: Optional[str] = None,
        passphrase: Optional[str] = None,
    ):
        from .config import init_config
        from .transport.tcp import parse_addrs, tcp_transport

        cfg = init_config(path=str(config_path))
        key_path = Path(
            initiator_key_path
            or Path(config_path).resolve().parent / "event_initiator.key"
        )
        # load the key BEFORE connecting: a missing/locked key must not
        # leak a live authenticated broker connection + reader thread
        initiator = InitiatorKey.load(key_path, passphrase)
        self.transport = tcp_transport(
            cfg.broker_host,
            cfg.broker_port,
            auth_token=cfg.broker_token or None,
            encrypt=cfg.broker_encrypt,
            standbys=parse_addrs(cfg.broker_standbys) or None,
        )
        self.client = MPCClient(self.transport, initiator)

    def close(self) -> None:
        self.transport.client.close()


def load_test_preparams(bits: int = 2048) -> Dict[str, PreParams]:
    """The committed fixtures (TEST/BENCH ONLY — production nodes generate
    fresh pre-params, reference node.go:69). ``bits=1024`` selects the
    shrunk-key fixture used by fast unit tests: FIXED keys also keep the
    persistent XLA compile cache valid across runs (fresh random moduli
    would embed different constants into every kernel)."""
    name = "test_preparams.json" if bits == 2048 else f"test_preparams_{bits}.json"
    data_path = Path(__file__).resolve().parent / "data" / name
    d = json.load(open(data_path))["preparams"]
    return {k: PreParams.from_json(v) for k, v in d.items()}
