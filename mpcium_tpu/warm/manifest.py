"""The warm work-list: a pure function of the committed compile surface.

HACCLE's observation (PAPERS.md) is that an MPC protocol's compile
surface is *data* — so ahead-of-time specialization is a table walk,
not a heuristic. ``COMPILE_SURFACE.json`` (mpcshape, drift-gated) is
that table: per engine, the ``compile_watch.begin`` template with every
signature dimension classified constant/knob/bucketed/unbounded. This
module instantiates it into the concrete list of (engine, shape)
signatures a node will ever request in serving:

- serving-reachable templates only (``serving: false`` records — bench
  fabrics with no node path — are excluded);
- the batch dimension ranges over ``engine/buckets.BUCKETS`` (the
  scheduler drains pow-2 chunks, so these are the ONLY B values the
  engines are ever handed);
- knob dimensions (quorum size, key type, MtA backend, new threshold)
  come from :class:`WarmKnobs` — derived from config, finite by
  construction. A knob dim with no configured values is a **gap**,
  reported loudly (``coverage_check`` / ``make warmcheck``), never
  silently skipped;
- entries are ordered hot-first by observed traffic
  (``COMPILE_LEDGER.json`` + ``PERF_history.jsonl``), then cheap-first
  (small B) so a budget-cut pre-warm covers the most value.

The manifest is keyed by the ``perf/envfp.py`` host fingerprint plus
jax/jaxlib versions: compiled artifacts are machine-feature- and
toolchain-stamped, and a key mismatch means every cached executable is
stale — skipped and recompiled, never trusted (``key_matches``).

Pure stdlib on purpose (like ``engine/buckets``): building or checking
a manifest must never pay a jax import or a backend bring-up.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis.shape.surface import (
    SURFACE_BASENAME,
    _DIM_RE,
    load_surface,
    shape_predicted,
)
from ..engine.buckets import BUCKETS
from ..perf import envfp

REPORT_BASENAME = "WARM_MANIFEST.json"  # the prewarm report, beside the cache

# engine → scheme family: ``warm_schemes`` selects families, and the
# party-level protocol engines ride along with their scheme
ENGINE_SCHEME = {
    "eddsa.sign": "eddsa",
    "gg18.sign": "ecdsa",
    "party.ecdsa": "ecdsa",
    "party.eddsa": "eddsa",
    "dkg.run": "dkg",
    "party.dkg": "dkg",
    "reshare.run": "reshare",
    "party.reshare": "reshare",
}

ALL_SCHEMES = ("eddsa", "ecdsa", "dkg", "reshare")


@dataclass(frozen=True)
class WarmKnobs:
    """Concrete values for every knob-classed surface dimension. Finite
    by construction: these are configuration, not traffic."""

    q: Tuple[int, ...] = (2,)
    key_type: Tuple[str, ...] = ("ed25519", "secp256k1")
    mta_impl: Tuple[str, ...] = ("paillier", "ot")
    t_new: Tuple[int, ...] = (1,)

    def values_for(self, name: str) -> Tuple[str, ...]:
        vals = getattr(self, name, ())
        return tuple(str(v) for v in vals)

    def to_json(self) -> Dict[str, list]:
        return {
            "q": list(self.q),
            "key_type": list(self.key_type),
            "mta_impl": list(self.mta_impl),
            "t_new": list(self.t_new),
        }


def default_knobs(threshold: Optional[int] = None) -> WarmKnobs:
    """Knob values for a t-of-n deployment: the serving quorum is t+1
    and reshares rotate to the same threshold. The MtA backend axis is
    whatever this process would actually serve (``MPCIUM_MTA``) plus
    ``ot`` — the OT backend's active-security check kernels (ISSUE 16)
    ride the gg18.sign signature, and a node must be able to flip to
    the checked backend without hitting a cold compile."""
    t = 1 if threshold is None else int(threshold)
    if t < 1:
        raise ValueError(f"need threshold >= 1, got {t}")
    mta = os.environ.get("MPCIUM_MTA", "paillier")
    return WarmKnobs(
        q=(t + 1,),
        mta_impl=(mta,) if mta == "ot" else (mta, "ot"),
        t_new=(t,),
    )


def knobs_from_config(cfg) -> WarmKnobs:
    return default_knobs(threshold=cfg.mpc_threshold)


# -- the environment key -----------------------------------------------------


def jaxlib_version() -> Optional[str]:
    """Like envfp.jax_version: read the already-imported module first,
    fall back to package metadata — never import jaxlib here."""
    mod = sys.modules.get("jaxlib")
    if mod is not None:
        v = getattr(mod, "__version__", None)
        if v:
            return v
    try:
        from importlib.metadata import version

        return version("jaxlib")
    except Exception:  # noqa: BLE001 — fingerprinting must never raise
        return None


def manifest_key() -> Dict[str, Optional[str]]:
    """What a compiled executable's validity depends on: the host CPU
    feature set (AOT artifacts are machine-feature-stamped; containers
    live-migrate) and the jax/jaxlib pair that traced and lowered it."""
    return {
        "host": envfp.host_fingerprint(),
        "jax": envfp.jax_version(),
        "jaxlib": jaxlib_version(),
    }


def key_matches(stored: Optional[Dict[str, object]],
                current: Optional[Dict[str, object]] = None
                ) -> Tuple[bool, str]:
    """(ok, reason). A stale key means every artifact under it is
    untrusted — the caller skips and recompiles, loudly."""
    if current is None:
        current = manifest_key()
    if not isinstance(stored, dict):
        return False, "no environment key stored"
    for k in ("host", "jax", "jaxlib"):
        if stored.get(k) != current.get(k):
            return False, (
                f"{k} changed: {stored.get(k)!r} -> {current.get(k)!r}"
            )
    return True, "ok"


# -- traffic priority --------------------------------------------------------


def traffic_weights(ledger_entries: Sequence[dict] = (),
                    history_records: Sequence[dict] = ()
                    ) -> Dict[Tuple[str, str], float]:
    """Observed-traffic weight per (engine, shape). Ledger entries are
    exact signatures (weight 1 each); perf-history bench records vote
    for their scheme's engines at the recorded batch bucket."""
    w: Dict[Tuple[str, str], float] = {}
    for e in ledger_entries:
        eng, shape = e.get("engine"), e.get("shape")
        if isinstance(eng, str) and isinstance(shape, str):
            k = (eng, shape)
            w[k] = w.get(k, 0.0) + 1.0
    hot_b: Dict[int, float] = {}
    for r in history_records:
        ctx = r.get("context") or {}
        for key in ("batch", "ed25519_batch", "gg18_ot_mta_batch",
                    "dkg_batch", "reshare_batch"):
            b = ctx.get(key)
            if isinstance(b, int) and b > 0:
                hot_b[b] = hot_b.get(b, 0.0) + 0.5
    for b, v in hot_b.items():
        w[("__B__", str(b))] = v
    return w


def load_traffic(ledger_path: Optional[str] = None,
                 history_path: Optional[str] = None
                 ) -> Dict[Tuple[str, str], float]:
    """Best-effort read of the committed/on-host traffic artifacts.
    Missing or malformed files simply contribute no weight."""
    entries: List[dict] = []
    records: List[dict] = []
    if ledger_path:
        try:
            with open(ledger_path) as f:
                doc = json.load(f)
            entries = list(doc.get("entries") or [])
        except (OSError, ValueError):
            pass
    if history_path:
        try:
            with open(history_path) as f:
                lines = f.readlines()
        except OSError:
            lines = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # one bad JSONL line must not erase the rest
    return traffic_weights(entries, records)


# -- enumeration -------------------------------------------------------------


@dataclass
class WarmEntry:
    engine: str
    shape: str
    B: int
    scheme: str
    dims: Dict[str, str] = field(default_factory=dict)
    priority: float = 0.0

    def to_json(self) -> dict:
        return {
            "engine": self.engine,
            "shape": self.shape,
            "B": self.B,
            "scheme": self.scheme,
            "dims": dict(self.dims),
            "priority": round(self.priority, 3),
        }


def default_surface_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, SURFACE_BASENAME)


def load_default_surface() -> Dict[str, object]:
    path = default_surface_path()
    doc = load_surface(path)
    if doc is None:
        raise FileNotFoundError(
            f"committed compile surface unreadable: {path} "
            f"(regenerate with scripts/mpcshape_surface.py)"
        )
    return doc


def _dim_axis(engine: str, name: str, row: Dict[str, object],
              knobs: WarmKnobs, buckets: Sequence[int],
              gaps: List[str]) -> List[str]:
    cls = row.get("class")
    if cls in ("bucketed", "unbounded"):
        # the batch axis: finite because the scheduler pow-2-snaps it
        return [str(b) for b in buckets]
    if cls == "constant":
        v = row.get("value")
        return [str(v)] if v is not None else [""]
    if cls == "knob":
        vals = knobs.values_for(name)
        if not vals:
            gaps.append(
                f"{engine}: knob dim {name!r} has no warm values "
                f"configured (WarmKnobs gap — the pre-warmer would "
                f"silently never compile this signature)"
            )
        return list(vals)
    gaps.append(f"{engine}: dim {name!r} has unknown class {cls!r}")
    return []


def build_manifest(surface: Dict[str, object],
                   knobs: WarmKnobs,
                   buckets: Sequence[int] = BUCKETS,
                   schemes: Optional[Sequence[str]] = None,
                   max_b: Optional[int] = None,
                   traffic: Optional[Dict[Tuple[str, str], float]] = None,
                   ) -> Dict[str, object]:
    """Instantiate the surface into the concrete warm work-list.

    ``schemes`` filters to scheme families (None = all serving);
    ``max_b`` caps the bucket axis (budget control — the cut is recorded
    in counts, never silent); ``traffic`` orders hot shapes first.
    Returns a JSON-able manifest dict with ``entries`` sorted by
    descending priority then ascending B (cheap compiles early maximize
    coverage inside a deadline).
    """
    if max_b is not None:
        buckets = [b for b in buckets if b <= max_b]
    traffic = traffic or {}
    gaps: List[str] = []
    entries: List[WarmEntry] = []
    n_serving = 0
    engines = surface.get("engines", {})
    for engine in sorted(engines):
        for rec in engines[engine]:
            if not rec.get("serving"):
                continue
            n_serving += 1
            scheme = ENGINE_SCHEME.get(engine, engine.split(".", 1)[0])
            if schemes is not None and scheme not in schemes:
                continue
            template = str(rec.get("template", ""))
            names = _DIM_RE.findall(template)
            dims = rec.get("dims", {})
            axes = [
                _dim_axis(engine, nm, dims.get(nm, {}), knobs, buckets, gaps)
                for nm in names
            ]
            for combo in itertools.product(*axes):
                shape = template
                for nm, val in zip(names, combo):
                    shape = shape.replace("{" + nm + "}", val, 1)
                d = dict(zip(names, combo))
                b = int(d.get("B", "1"))
                prio = traffic.get((engine, shape), 0.0)
                prio += traffic.get(("__B__", str(b)), 0.0)
                entries.append(WarmEntry(
                    engine=engine, shape=shape, B=b, scheme=scheme,
                    dims=d, priority=prio,
                ))
    entries.sort(key=lambda e: (-e.priority, e.B, e.engine, e.shape))
    return {
        "comment": (
            "Warm work-list derived from COMPILE_SURFACE.json (serving "
            "templates x WarmKnobs x engine/buckets.BUCKETS), hot shapes "
            "first. Valid only under the environment key; a key mismatch "
            "invalidates every cached executable."
        ),
        "key": manifest_key(),
        "knobs": knobs.to_json(),
        "buckets": list(buckets),
        "schemes": list(schemes) if schemes is not None else list(ALL_SCHEMES),
        "gaps": gaps,
        "entries": [e.to_json() for e in entries],
        "counts": {
            "entries": len(entries),
            "serving_templates": n_serving,
            "buckets": len(buckets),
        },
    }


def manifest_entries(manifest: Dict[str, object]) -> List[WarmEntry]:
    out = []
    for e in manifest.get("entries", []):  # type: ignore[union-attr]
        out.append(WarmEntry(
            engine=str(e["engine"]), shape=str(e["shape"]),
            B=int(e["B"]), scheme=str(e.get("scheme", "")),
            dims=dict(e.get("dims", {})),
            priority=float(e.get("priority", 0.0)),
        ))
    return out


# -- the enumeration gate (make warmcheck / check_all / tier-1) --------------


def coverage_check(surface: Dict[str, object],
                   knobs: Optional[WarmKnobs] = None,
                   buckets: Sequence[int] = BUCKETS) -> List[str]:
    """Verify manifest enumeration == serving templates x knob values x
    buckets, with no silent gaps. Returns problem strings (empty =
    clean). This is the ``make warmcheck`` gate, folded into
    scripts/check_all.py off the shared parse and drift-gated in tier-1:
    a new serving engine or knob dim that the warm layer cannot
    enumerate fails the build instead of silently never pre-warming."""
    knobs = knobs or default_knobs()
    manifest = build_manifest(surface, knobs, buckets=buckets)
    problems: List[str] = list(manifest["gaps"])  # type: ignore[arg-type]
    per_engine: Dict[str, int] = {}
    for e in manifest_entries(manifest):
        per_engine[e.engine] = per_engine.get(e.engine, 0) + 1
        if not shape_predicted(surface, e.engine, e.shape):
            problems.append(
                f"{e.engine}: manifest shape {e.shape!r} is not predicted "
                f"by the surface it was derived from (template/matcher "
                f"disagreement)"
            )
    engines = surface.get("engines", {})
    for engine in sorted(engines):  # type: ignore[union-attr]
        serving_recs = [r for r in engines[engine] if r.get("serving")]
        if serving_recs and engine not in ENGINE_SCHEME:
            problems.append(
                f"{engine}: no scheme mapping in "
                f"warm.manifest.ENGINE_SCHEME — warm_schemes cannot "
                f"select it"
            )
        expect = 0
        for rec in serving_recs:
            template = str(rec.get("template", ""))
            names = _DIM_RE.findall(template)
            dims = rec.get("dims", {})
            n = 1
            for nm in names:
                cls = dims.get(nm, {}).get("class")
                if cls in ("bucketed", "unbounded"):
                    n *= len(buckets)
                elif cls == "knob":
                    n *= len(knobs.values_for(nm))
                elif cls != "constant":
                    n = 0
            expect += n
        got = per_engine.get(engine, 0)
        if serving_recs and got != expect:
            problems.append(
                f"{engine}: enumerated {got} signatures, expected "
                f"{expect} (|buckets| x knob values per serving "
                f"template) — the warm work-list has a gap"
            )
    return problems
