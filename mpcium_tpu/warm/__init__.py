"""mpcwarm — shape-bucketed AOT compile cache and warm-start pass.

The compile surface is *data* (``COMPILE_SURFACE.json``), so erasing
the compile wall is a table walk, not a heuristic: :mod:`.manifest`
enumerates knobs × buckets into a prioritized work-list, :mod:`.aot`
persists ``jax.export`` artifacts with loud environment-key
invalidation, and :mod:`.prewarm` walks the list at daemon boot between
``compile_watch.mark_warming()`` and ``mark_ready()``. See
PERFORMANCE.md "Warm start" and ROADMAP item 4.

This package never imports jax at module scope — manifest enumeration
and ``make warmcheck`` stay sub-second and host-only.
"""
from .manifest import (  # noqa: F401
    ALL_SCHEMES,
    REPORT_BASENAME,
    WarmEntry,
    WarmKnobs,
    build_manifest,
    coverage_check,
    default_knobs,
    key_matches,
    knobs_from_config,
    load_default_surface,
    manifest_entries,
    manifest_key,
)
