"""AOT executable serialization: ``jax.export`` artifacts beside the cache.

Two warm mechanisms complement each other (ROADMAP item 4):

- the **XLA persistent cache** (``jax_compilation_cache_dir``) caches
  every compiled executable keyed by lowered HLO — the pre-warmer's
  trace-and-compile runners populate it for whole engine paths, and a
  restarted process deserializes instead of recompiling. This is the
  universal fallback: it covers callables ``jax.export`` cannot
  (host-callback-bearing, multi-dispatch protocol drivers).
- **``jax.export`` artifacts** (this module) serialize individual
  flagship kernels to versioned ``.bin`` files that a booting process
  can deserialize and call directly — no Python retrace, no jit-cache
  population, bit-identical outputs (tests/test_warm_aot.py).

Every artifact is stamped with the :func:`~.manifest.manifest_key`
(host CPU fingerprint + jax/jaxlib versions). A stale stamp is loud:
the artifact is **skipped and recompiled, never trusted** — jax.export
payloads are toolchain-versioned and the XLA:CPU deserializer has
segfaulted on machine-feature mismatches before (tests/conftest.py).
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import log
from . import manifest as wm


class AOTUnsupported(RuntimeError):
    """``jax.export`` cannot serialize this callable — callers fall back
    to trace-and-compile into the persistent cache."""


def export_jit(fn: Callable, *example_args: Any):
    """Trace + lower ``jit(fn)`` at the example arguments' shapes and
    return the ``jax.export.Exported`` (raises :class:`AOTUnsupported`
    when the callable or backend cannot be exported)."""
    import jax
    from jax import export as jax_export

    try:
        return jax_export.export(jax.jit(fn))(*example_args)
    except Exception as e:  # noqa: BLE001 — any export failure means fallback
        raise AOTUnsupported(f"jax.export failed: {e!r}") from e


def serialize(exported) -> bytes:
    return bytes(exported.serialize())


def deserialize(data: bytes):
    from jax import export as jax_export

    return jax_export.deserialize(bytearray(data))


def _slug(name: str) -> str:
    digest = hashlib.sha256(name.encode()).hexdigest()[:10]
    safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in name)
    return f"{safe[:80]}__{digest}"


class ArtifactStore:
    """A directory of serialized executables with loud invalidation.

    Layout: ``<root>/<slug>.bin`` (the jax.export payload) +
    ``<slug>.json`` (the environment key + name). ``load`` returns None
    — after a warn log — for missing, stale-keyed, or undeserializable
    artifacts; the caller recompiles. Never raises on bad disk state.
    """

    def __init__(self, root: str,
                 key: Optional[Dict[str, object]] = None) -> None:
        self.root = root
        self.key = dict(key) if key is not None else wm.manifest_key()

    def _paths(self, name: str) -> Tuple[str, str]:
        s = _slug(name)
        return (os.path.join(self.root, s + ".bin"),
                os.path.join(self.root, s + ".json"))

    def save(self, name: str, exported) -> str:
        os.makedirs(self.root, exist_ok=True)
        bin_path, meta_path = self._paths(name)
        data = serialize(exported)
        with open(bin_path, "wb") as f:
            f.write(data)
        with open(meta_path, "w") as f:
            json.dump({"name": name, "key": self.key,
                       "bytes": len(data)}, f, indent=1, sort_keys=True)
            f.write("\n")
        return bin_path

    def load(self, name: str):
        """The deserialized ``Exported`` (call via ``.call(*args)``), or
        None. Version/fingerprint mismatches are the expected stale path
        and log loudly — a silent wrong-machine deserialize is how AOT
        segfaults happen."""
        bin_path, meta_path = self._paths(name)
        if not (os.path.exists(bin_path) and os.path.exists(meta_path)):
            return None
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            log.warn("warm: unreadable AOT artifact meta — recompiling",
                     artifact=name, error=repr(e))
            return None
        ok, reason = wm.key_matches(meta.get("key"), self.key)
        if not ok:
            log.warn("warm: STALE AOT artifact skipped — recompiling",
                     artifact=name, reason=reason)
            return None
        try:
            with open(bin_path, "rb") as f:
                return deserialize(f.read())
        except Exception as e:  # noqa: BLE001 — a corrupt payload must not kill boot
            log.warn("warm: undeserializable AOT artifact — recompiling",
                     artifact=name, error=repr(e))
            return None

    def names(self) -> List[str]:
        out = []
        try:
            metas = [n for n in os.listdir(self.root) if n.endswith(".json")]
        except OSError:
            return []
        for n in sorted(metas):
            try:
                with open(os.path.join(self.root, n)) as f:
                    out.append(str(json.load(f)["name"]))
            except (OSError, ValueError, KeyError):
                continue
        return out


# -- the exportable-kernel registry ------------------------------------------
#
# Flagship jit entry points that are pure array→array (no host
# callbacks, no Python protocol driving) and worth a direct AOT
# artifact. Builders return (name, fn, example_args) for a given
# manifest entry's dims; shapes matter, values do not.


def _eddsa_kernels(B: int, q: int) -> List[Tuple[str, Callable, tuple]]:
    import jax.numpy as jnp

    from ..engine import eddsa_batch as eb

    r64 = jnp.zeros((q, B, 64), jnp.uint8)
    c64 = jnp.zeros((B, 64), jnp.uint8)
    lamx = jnp.zeros((q,) + eb.scalars_to_limb_batch([0] * B).shape,
                     jnp.int32)
    return [
        (f"eddsa.fused_sign_step__B{B}q{q}",
         eb.fused_sign_step, (r64, c64, lamx)),
        (f"eddsa.nonce_commitments__B{B}q{q}",
         eb.nonce_commitments, (r64,)),
    ]


def kernels_for_entry(entry: "wm.WarmEntry") -> List[Tuple[str, Callable, tuple]]:
    """The jax.export-able kernels behind a manifest entry (may be
    empty — the trace-and-compile runner still covers the engine)."""
    if entry.engine == "eddsa.sign":
        return _eddsa_kernels(entry.B, int(entry.dims.get("q", "2")))
    return []


def warm_entry_artifacts(store: ArtifactStore, entry: "wm.WarmEntry"
                         ) -> Dict[str, int]:
    """Load-or-export every AOT kernel behind one manifest entry.
    Returns {"loaded": n, "exported": n, "unsupported": n}."""
    stats = {"loaded": 0, "exported": 0, "unsupported": 0}
    for name, fn, args in kernels_for_entry(entry):
        if store.load(name) is not None:
            stats["loaded"] += 1
            continue
        try:
            store.save(name, export_jit(fn, *args))
            stats["exported"] += 1
        except AOTUnsupported as e:
            # expected fallback: the persistent cache still covers it
            # mpclint: disable=MPF701 — `name` is the kernel's registry label (a shape-derived string), not nonce material
            log.warn("warm: kernel not exportable — persistent cache "
                     "fallback", kernel=name, error=str(e))
            stats["unsupported"] += 1
    return stats
