"""The pre-warmer: walk the warm manifest before advertising ready.

Each manifest entry names one (engine, shape) compile bucket. The runner
for an entry drives the *real* engine entry point at that exact shape —
the same ``compile_watch.begin`` site live traffic hits — so warming
produces genuine ledger entries and ``compile:*`` spans, and the XLA
persistent cache (``configure_cache``) fills with exactly the
executables the serving set needs. A later fresh-process boot then
classifies its first real request ``cache: hit``: the compile wall is
paid once per host+toolchain, not once per restart
(tests/test_warm_boot.py proves the zero-miss boot on CPU).

The walk is budget-aware and failure-isolated: a deadline miss marks the
remaining entries ``skipped`` (the daemon goes ready anyway — cold, but
alive), and a runner exception marks that entry ``failed`` without
taking boot down. The report lands as ``WARM_MANIFEST.json`` next to
the cache, one verdict per signature.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..perf import compile_watch
from ..utils import log
from . import aot
from . import manifest as wm

def _ids(n: int) -> List[str]:
    return [f"warm{i}" for i in range(n)]


def _digests(B: int):
    import numpy as np

    return np.stack(
        [np.frombuffer(bytes([i % 256]) * 32, dtype=np.uint8)
         for i in range(B)]
    )


def _messages(B: int) -> List[bytes]:
    return [bytes([i % 256]) * 32 for i in range(B)]


def _test_preparams(ids: Sequence[str]) -> Dict[str, object]:
    """The committed FIXED Paillier fixtures mapped onto warm party ids —
    fixed keys keep the persistent cache valid across runs (fresh moduli
    would embed new constants into every kernel)."""
    from ..cluster import load_test_preparams

    tp = load_test_preparams(bits=1024)
    pool = [tp[k] for k in sorted(tp)]
    return {pid: pool[i % len(pool)] for i, pid in enumerate(ids)}


# -- per-engine runners ------------------------------------------------------
#
# Each runner compiles the bucket for ONE manifest entry by running the
# engine at that shape with throwaway dealer-keygen material. Dims come
# from the entry (strings, straight from the surface template).


def _run_eddsa_sign(e: wm.WarmEntry) -> None:
    import secrets

    from ..engine import eddsa_batch as eb

    q = int(e.dims["q"])
    ids = _ids(q + 1)
    shares = eb.dealer_keygen_batch(e.B, ids, q - 1, rng=secrets)
    eb.BatchedCoSigners(ids[:q], shares[:q], rng=secrets).sign(
        _messages(e.B)
    )


def _run_dkg_run(e: wm.WarmEntry) -> None:
    import secrets

    from ..engine import dkg_batch as db

    q = int(e.dims["q"])
    db.BatchedDKG(_ids(q), q - 1, e.dims["key_type"], rng=secrets).run(e.B)


def _run_reshare_run(e: wm.WarmEntry) -> None:
    import secrets

    t_new = int(e.dims["t_new"])
    committee = _ids(max(t_new + 1, 2))
    key_type = e.dims["key_type"]
    if key_type == "secp256k1":
        from ..engine import gg18_batch as gb

        old = gb.dealer_keygen_secp_batch(e.B, committee, 1, rng=secrets)
    else:
        from ..engine import eddsa_batch as eb

        old = eb.dealer_keygen_batch(e.B, committee, 1, rng=secrets)
    from ..engine import dkg_batch as db

    db.BatchedReshare(committee[:2], old[:2], committee, t_new,
                      rng=secrets).run()


def _run_gg18_sign(e: wm.WarmEntry) -> None:
    import secrets

    from ..engine import gg18_batch as gb

    q = int(e.dims["q"])
    mta = e.dims["mta_impl"]
    ids = _ids(q + 1)
    shares = gb.dealer_keygen_secp_batch(e.B, ids, q - 1, rng=secrets)
    pre = _test_preparams(ids[:q]) if mta == "paillier" else None
    signer = gb.GG18BatchCoSigners(
        ids[:q], shares[:q], pre, rng=secrets, mta_impl=mta
    )
    signer.sign(_digests(e.B))


def _run_party_dkg(e: wm.WarmEntry) -> None:
    import secrets

    from ..protocol.batch_dkg import BatchedDKGParty
    from ..protocol.runner import run_protocol

    q = int(e.dims["q"])
    key_type = e.dims["key_type"]
    ids = _ids(q)
    pre = _test_preparams(ids) if key_type == "secp256k1" else {}
    parties = {
        pid: BatchedDKGParty(
            "warm-dkg", pid, ids, q - 1, key_type, e.B,
            preparams=pre.get(pid), min_paillier_bits=512, rng=secrets,
        )
        for pid in ids
    }
    run_protocol(parties)


def _run_party_ecdsa(e: wm.WarmEntry) -> None:
    import secrets

    from ..engine import gg18_batch as gb
    from ..protocol.ecdsa.batch_signing import BatchedECDSASigningParty
    from ..protocol.runner import run_protocol

    q = int(e.dims["q"])
    ids = _ids(q)
    pre = _test_preparams(ids)
    shares = gb.dealer_keygen_secp_batch(
        e.B, ids, q - 1, rng=secrets, preparams=pre
    )
    digests = [bytes([i % 256]) * 32 for i in range(e.B)]
    parties = {
        pid: BatchedECDSASigningParty(
            "warm-ecdsa", pid, ids, shares[i], digests, rng=secrets
        )
        for i, pid in enumerate(ids)
    }
    run_protocol(parties)


def _run_party_reshare(e: wm.WarmEntry) -> None:
    import secrets

    from ..protocol.batch_dkg import BatchedReshareParty
    from ..protocol.runner import run_protocol

    # q in the shape is |old ∪ new|: same committee re-deals to itself
    q = int(e.dims["q"])
    t_new = int(e.dims["t_new"])
    key_type = e.dims["key_type"]
    ids = _ids(q)
    if key_type == "secp256k1":
        from ..engine import gg18_batch as gb

        pre = _test_preparams(ids)
        old = gb.dealer_keygen_secp_batch(e.B, ids, t_new, rng=secrets)
    else:
        from ..engine import eddsa_batch as eb

        pre = {pid: None for pid in ids}
        old = eb.dealer_keygen_batch(e.B, ids, t_new, rng=secrets)
    parties = {
        pid: BatchedReshareParty(
            "warm-reshare", pid, key_type, ids, ids, t_new, e.B,
            old_shares=old[i], preparams=pre.get(pid),
            min_paillier_bits=512, rng=secrets,
        )
        for i, pid in enumerate(ids)
    }
    run_protocol(parties)


RUNNERS: Dict[str, Callable[[wm.WarmEntry], None]] = {
    "eddsa.sign": _run_eddsa_sign,
    "dkg.run": _run_dkg_run,
    "reshare.run": _run_reshare_run,
    "gg18.sign": _run_gg18_sign,
    "party.dkg": _run_party_dkg,
    "party.ecdsa": _run_party_ecdsa,
    "party.reshare": _run_party_reshare,
}


# -- cache configuration -----------------------------------------------------


def configure_cache(cache_dir: str, min_compile_s: float = 0.0) -> None:
    """Point the XLA persistent cache at ``cache_dir`` and drop the
    min-compile-time floor so every warmed executable persists (the
    default floor silently skips sub-second compiles — a warm pass wants
    all of them on disk)."""
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    try:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_s
        )
    except Exception:  # noqa: BLE001 — knob renamed across jax versions
        pass


# -- the walk ----------------------------------------------------------------


def prewarm(
    manifest: dict,
    budget_s: float = 300.0,
    *,
    report_dir: Optional[str] = None,
    aot_store: Optional[aot.ArtifactStore] = None,
    now: Callable[[], float] = time.monotonic,
) -> dict:
    """Walk the manifest (hot shapes first) until covered or out of
    budget. Returns — and writes, when ``report_dir`` is given — the
    ``WARM_MANIFEST.json`` report: one verdict per signature plus
    totals. Never raises: a failed entry is a report line, not a boot
    failure."""
    deadline = now() + budget_s
    results: List[dict] = []
    totals = {
        "entries": 0, "warmed": 0, "already": 0, "skipped": 0,
        "failed": 0, "hits": 0, "misses": 0, "unpredicted": 0,
    }
    for e in wm.manifest_entries(manifest):
        totals["entries"] += 1
        row = {"engine": e.engine, "shape": e.shape, "B": e.B,
               "scheme": e.scheme, "priority": e.priority}
        if now() >= deadline:
            row["status"] = "skipped"
            row["reason"] = "budget exhausted"
            totals["skipped"] += 1
            results.append(row)
            continue
        if compile_watch.seen(e.engine, e.shape):
            row["status"] = "already"
            totals["already"] += 1
            results.append(row)
            continue
        runner = RUNNERS.get(e.engine)
        if runner is None:
            row["status"] = "failed"
            row["reason"] = f"no warm runner for engine {e.engine!r}"
            totals["failed"] += 1
            results.append(row)
            continue
        t0 = now()
        try:
            runner(e)
        except Exception as exc:  # noqa: BLE001 — warming must not kill boot
            row["status"] = "failed"
            row["reason"] = repr(exc)
            totals["failed"] += 1
            log.warn("warm: entry failed", engine=e.engine, shape=e.shape,
                     error=repr(exc))
            results.append(row)
            continue
        row["status"] = "warmed"
        row["warm_s"] = round(now() - t0, 3)
        totals["warmed"] += 1
        ledger = next(
            (le for le in reversed(compile_watch.entries())
             if le["engine"] == e.engine and le["shape"] == e.shape),
            None,
        )
        if ledger is not None:
            row["cache"] = ledger["cache"]
            row["compile_s"] = ledger["compile_s"]
            if ledger["cache"] == "hit":
                totals["hits"] += 1
            elif ledger["cache"] == "miss":
                totals["misses"] += 1
            if ledger.get("predicted") is False:
                # a warmed shape the static surface missed — drift that
                # escaped the mpcshape gate; make it impossible to miss
                row["predicted"] = False
                totals["unpredicted"] += 1
                log.warn(
                    "warm: UNPREDICTED compile — shape missing from "
                    "COMPILE_SURFACE.json, regenerate via make shapecheck",
                    engine=e.engine, shape=e.shape,
                )
        if aot_store is not None:
            try:
                row["aot"] = aot.warm_entry_artifacts(aot_store, e)
            except Exception as exc:  # noqa: BLE001
                row["aot_error"] = repr(exc)
        results.append(row)
    report = {
        "comment": "pre-warm report: one verdict per warm-manifest "
                   "signature (mpcium_tpu.warm.prewarm)",
        "key": manifest.get("key", wm.manifest_key()),
        "budget_s": budget_s,
        "totals": totals,
        "results": results,
    }
    if report_dir:
        try:
            os.makedirs(report_dir, exist_ok=True)
            path = os.path.join(report_dir, wm.REPORT_BASENAME)
            with open(path, "w") as f:
                json.dump(report, f, indent=1, sort_keys=True)
                f.write("\n")
            report["path"] = path
        except OSError as exc:
            log.warn("warm: could not write report", error=repr(exc))
    return report


# -- daemon / drill entry points ---------------------------------------------


def default_cache_dir(base_dir: str) -> str:
    """Per-host cache location: a cache compiled on one CPU generation
    must not be trusted on another, so the host fingerprint is in the
    path (coarser than the manifest key — jax version changes invalidate
    *artifacts* via the key check, not the whole directory)."""
    return os.path.join(
        base_dir, f"warm_cache_{wm.envfp.host_fingerprint()}"
    )


def prewarm_for_daemon(cfg, node_name: str) -> Optional[dict]:
    """The boot-time warm pass (node/daemon.py, between ``mark_warming``
    and ``mark_ready``). Never raises — a broken warm config degrades to
    a cold-but-serving node, loudly."""
    try:
        db_dir = os.path.join(cfg.db_dir, node_name)
        cache_dir = cfg.warm_cache_dir or default_cache_dir(db_dir)
        configure_cache(cache_dir)
        surface = wm.load_default_surface()
        knobs = wm.knobs_from_config(cfg)
        schemes = tuple(
            s.strip() for s in cfg.warm_schemes.split(",") if s.strip()
        ) or None
        traffic = wm.load_traffic(
            os.path.join(db_dir, compile_watch.LEDGER_BASENAME), None
        )
        manifest = wm.build_manifest(
            surface, knobs, schemes=schemes, max_b=cfg.warm_max_b,
            traffic=traffic,
        )
        log.info(
            "warm: pre-warming serving set", node=node_name,
            entries=len(manifest["entries"]), budget_s=cfg.warm_budget_s,
            cache=cache_dir,
        )
        report = prewarm(
            manifest, cfg.warm_budget_s, report_dir=cache_dir,
            aot_store=aot.ArtifactStore(os.path.join(cache_dir, "aot")),
        )
        t = report["totals"]
        log.info(
            "warm: pre-warm complete", node=node_name, warmed=t["warmed"],
            already=t["already"], skipped=t["skipped"], failed=t["failed"],
            cache_hits=t["hits"], cache_misses=t["misses"],
        )
        return report
    except Exception as exc:  # noqa: BLE001 — boot must survive a bad warm pass
        log.warn("warm: pre-warm pass failed — serving cold",
                 node=node_name, error=repr(exc))
        return None


def warm_for_drill(budget_s: float = 60.0) -> Dict[str, object]:
    """A tiny eddsa-only warm pass for the kill-resume chaos drill: warm
    the drill's own signing bucket so resume latency reflects a warm
    cache, and report ``{warmed, hits, budget_s}`` for the drill report.
    Never raises."""
    try:
        surface = wm.load_default_surface()
        knobs = wm.WarmKnobs(q=(2,), key_type=("ed25519",),
                             mta_impl=("paillier",), t_new=(1,))
        manifest = wm.build_manifest(
            surface, knobs, buckets=(2,), schemes=("eddsa",)
        )
        report = prewarm(manifest, budget_s)
        t = report["totals"]
        return {
            "warmed": t["warmed"] + t["already"],
            "hits": t["hits"],
            "budget_s": budget_s,
        }
    except Exception as exc:  # noqa: BLE001 — a drill must not die warming
        log.warn("warm: drill warm pass failed", error=repr(exc))
        return {"warmed": 0, "hits": 0, "budget_s": budget_s}
