"""Environment fingerprints: which machine/toolchain produced a number.

The r05 stale-fallback confusion — a CPU-degraded bench record sitting
in the official round slot with the chip number only under
``last_tpu_measurement`` — happened because records carried no durable
statement of WHERE they were measured. Every bench/soak record now
stamps ``env_fingerprint()`` and the perf ledger groups trends by
``fingerprint_key``, so a degraded run is structurally incapable of
averaging into a chip trend.

Deliberately import-light: no jax import at module scope, and device
facts are read only from an already-initialized jax (``sys.modules``),
never by importing it — stamping a record must not cost a backend
bring-up or hang on a wedged accelerator relay.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import sys
from typing import Dict, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))

# the env knobs that change what a perf number means; anything else
# (paths, passwords) is noise the fingerprint must not leak
_KNOB_PREFIXES = (
    "MPCIUM_MTA", "MPCIUM_OT_CHUNKS", "MPCIUM_NATIVE_THREADS",
    "MPCIUM_BENCH_B", "MPCIUM_BENCH_RUNS", "MPCIUM_PROFILE",
    "JAX_PLATFORMS",
)


def host_fingerprint() -> str:
    """Short stable id for THIS host's CPU feature set (the same scheme
    bench.py keys its per-host XLA:CPU cache dirs by: AOT artifacts are
    machine-feature-stamped and containers live-migrate)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha256(
                        " ".join(sorted(line.split()[2:])).encode()
                    ).hexdigest()[:12]
    except OSError:
        pass
    import platform as _p

    return hashlib.sha256(_p.processor().encode() or b"?").hexdigest()[:12]


def git_sha() -> Optional[str]:
    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=_REPO, capture_output=True, timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if r.returncode != 0:
        return None
    return r.stdout.decode().strip() or None


def jax_version() -> Optional[str]:
    jax = sys.modules.get("jax")
    if jax is not None:
        return getattr(jax, "__version__", None)
    try:
        from importlib.metadata import version

        return version("jax")
    except Exception:  # noqa: BLE001 — fingerprinting must never raise
        return None


def device_facts() -> Dict[str, object]:
    """platform/kind/count of the ALREADY-initialized jax backend, or
    ``{"platform": "uninitialized"}``. Never imports or initializes jax:
    a fingerprint read must not pay (or hang on) a backend bring-up."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {"platform": "uninitialized"}
    try:
        devs = jax.devices()
    except Exception:  # noqa: BLE001 — a wedged backend is a fact too
        return {"platform": "unavailable"}
    return {
        "platform": devs[0].platform if devs else "none",
        "device_kind": getattr(devs[0], "device_kind", "?") if devs else "?",
        "device_count": len(devs),
    }


def knob_snapshot() -> Dict[str, str]:
    return {
        k: v for k, v in sorted(os.environ.items())
        if k.startswith(_KNOB_PREFIXES)
    }


def env_fingerprint() -> Dict[str, object]:
    """The full stamp bench/soak records carry. Values are public build/
    machine facts only (SECURITY.md: no secret-taxonomy values)."""
    fp: Dict[str, object] = {
        "git_sha": git_sha(),
        "jax": jax_version(),
        "python": ".".join(map(str, sys.version_info[:3])),
        "host": host_fingerprint(),
        "knobs": knob_snapshot(),
    }
    fp.update(device_facts())
    return fp


def fingerprint_key(env: Optional[Dict[str, object]],
                    platform_hint: Optional[str] = None) -> str:
    """The ledger's grouping key: ``<platform>/<host>[/<n>x<kind>]``.
    Records without a stamp (pre-observatory artifacts) group under
    ``<platform-hint>/unstamped`` so they can never blend into a stamped
    trend."""
    if not env:
        return f"{platform_hint or 'unknown'}/unstamped"
    platform = str(env.get("platform") or platform_hint or "unknown")
    host = str(env.get("host") or "unknown")
    key = f"{platform}/{host}"
    if env.get("device_count"):
        key += f"/{env['device_count']}x{env.get('device_kind', '?')}"
    return key
