"""Optional deep profiling: jax device timelines folded into mpctrace.

``MPCIUM_PROFILE=1`` arms ``device_profile`` — a context manager around
``jax.profiler.start_trace``/``stop_trace`` that captures the XLA
device timeline for the wrapped region. ``fold_device_ops`` then walks
the resulting ``*.trace.json.gz`` files, attributes device-op time to
the mpctrace ``phase:`` spans whose window each op midpoint lands in,
and returns ``{"<phase>_device_op_s": seconds}`` for the bench record —
the host-side phase share and the on-chip op time in one table.

Everything here is best-effort and fails to a no-op: profiling is a
diagnostic lane, never a dependency of the measurement. Without the
env knob (or without jax importable) ``device_profile`` yields without
touching anything and ``fold_device_ops`` returns ``{}``.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional

PROFILE_ENV = "MPCIUM_PROFILE"


def profiling_enabled() -> bool:
    return os.environ.get(PROFILE_ENV, "") == "1"


@contextmanager
def device_profile(logdir: str) -> Iterator[bool]:
    """Capture a jax profiler trace into ``logdir`` for the enclosed
    region. Yields True when a capture is actually running. No-op (and
    yields False) when profiling is disabled or jax is unavailable."""
    if not profiling_enabled():
        yield False
        return
    try:
        import jax.profiler as _profiler
    except Exception:  # noqa: BLE001 — no jax, no profile; the run proceeds
        yield False
        return
    try:
        _profiler.start_trace(logdir)
    except Exception:  # noqa: BLE001 — e.g. a second concurrent capture
        yield False
        return
    try:
        yield True
    finally:
        try:
            _profiler.stop_trace()
        except Exception:  # noqa: BLE001 — a failed stop must not mask the run
            pass


def _load_trace_events(logdir: str) -> List[dict]:
    events: List[dict] = []
    for path in sorted(glob.glob(
            os.path.join(logdir, "**", "*.trace.json.gz"), recursive=True)):
        try:
            with gzip.open(path, "rt") as f:
                doc = json.load(f)
            events.extend(doc.get("traceEvents") or [])
        except Exception:  # noqa: BLE001 — a torn capture file yields nothing
            continue
    return events


def _device_pids(events: List[dict]) -> set:
    """Pids whose process_name metadata names a device timeline (TPU/GPU
    core lanes in the XLA trace; host threads stay excluded)."""
    pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = str((e.get("args") or {}).get("name", "")).lower()
            if any(t in pname for t in ("tpu", "gpu", "device", "/device:",
                                        "xla")):
                if "host" not in pname and "cpu" not in pname:
                    pids.add(e.get("pid"))
    return pids


def fold_device_ops(spans: List[dict], logdir: str) -> Dict[str, float]:
    """Attribute device-op time from a captured profile to the mpctrace
    phase windows.

    The profiler's clock and ``time.monotonic_ns`` share no epoch, so
    the two timelines are aligned at their starts: min device-op ts ↔
    min phase-span t0. Each complete ("X") device event whose midpoint
    falls inside a phase window adds its duration to that phase's
    ``<phase>_device_op_s``. Returns {} when there is nothing to fold
    (no capture, no device pids, no phase spans) or on any parse error.
    """
    phases = [(s["name"][len("phase:"):], s["t0_ns"], s["t1_ns"])
              for s in spans if s.get("name", "").startswith("phase:")]
    if not phases:
        return {}
    events = _load_trace_events(logdir)
    if not events:
        return {}
    dev_pids = _device_pids(events)
    ops = [e for e in events
           if e.get("ph") == "X" and e.get("pid") in dev_pids
           and isinstance(e.get("ts"), (int, float))
           and isinstance(e.get("dur"), (int, float))]
    if not ops:
        return {}
    trace_t0_us = min(e["ts"] for e in ops)
    span_t0_ns = min(t0 for _n, t0, _t1 in phases)
    out: Dict[str, float] = {}
    for e in ops:
        mid_ns = span_t0_ns + int((e["ts"] - trace_t0_us + e["dur"] / 2.0)
                                  * 1e3)
        for name, t0, t1 in phases:
            if t0 <= mid_ns < t1:
                out[f"{name}_device_op_s"] = (
                    out.get(f"{name}_device_op_s", 0.0) + e["dur"] / 1e6
                )
                break
    return {k: round(v, 6) for k, v in out.items()}


def default_logdir(root: Optional[str] = None) -> str:
    return os.path.join(root or os.getcwd(), ".mpcium_profile")
