"""Render the perf ledger: trend dashboard + Perfetto counter track.

``render_dashboard`` turns the normalized history into the committed
``PERFORMANCE_dashboard.md`` — per-metric trend tables with on-chip and
degraded fingerprint groups in SEPARATE tables (a CPU fallback number
physically cannot sit in a chip trend row), plus delta-vs-previous
within each group. ``counter_track`` turns the same records into
Chrome-trace ``C`` (counter) events that merge into the PR 8 trace
export via ``chrome_trace(extra_events=...)`` — the bench trajectory as
a Perfetto counter lane under the session timeline.

Everything here is a pure function of the records: the committed
dashboard is drift-gated against a regeneration in CI.
"""
from __future__ import annotations

from typing import Dict, List, Optional

# the trend columns of the flagship table, in narrative order
_BENCH_COLUMNS = (
    ("secp256k1_2of3_gg18_sigs_per_sec", "gg18 sigs/s"),
    ("gg18_ot_mta_sigs_per_sec", "OT-MtA sigs/s"),
    ("ed25519_2of3_sigs_per_sec", "ed25519 sigs/s"),
    ("ed25519_2of3_threshold_sigs_per_sec", "ed25519 sigs/s (r1 metric)"),
    ("secp256k1_dkg_wallets_per_sec", "DKG wallets/s"),
    ("reshare_2of3_to_3of5_wallets_per_sec", "reshare wallets/s"),
)

COUNTER_PID = "perf-ledger"


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "—"
    if abs(v) >= 1000:
        return f"{v:,.0f}"
    if abs(v) >= 10:
        return f"{v:.1f}"
    return f"{v:.3f}"


def _delta(cur: Optional[float], prev: Optional[float]) -> str:
    if cur is None or prev is None or prev == 0:
        return ""
    pct = (cur / prev - 1.0) * 100.0
    return f" ({pct:+.1f}%)"


def _bench_table(records: List[dict]) -> List[str]:
    cols = [c for c in _BENCH_COLUMNS
            if any(c[0] in r["metrics"] for r in records)]
    head = ("| source | round | fingerprint | mta | "
            + " | ".join(label for _k, label in cols)
            + " | compile_s | notes |")
    sep = "|" + "---|" * (len(cols) + 6)
    lines = [head, sep]
    # deltas compare like with like: same fingerprint group, same MtA
    # implementation (a paillier→ot jump is a config change, not a trend)
    prev: Dict[tuple, float] = {}
    for r in records:
        mta = str(r["context"].get("mta", "—"))
        cells = [r["source"], str(r["round"] if r["round"] is not None else "—"),
                 f"`{r['fingerprint']}`", mta]
        for key, _label in cols:
            v = r["metrics"].get(key)
            pk = (r["fingerprint"], mta, key)
            cells.append(_fmt(v) + _delta(v, prev.get(pk)))
            if v is not None:
                prev[pk] = v
        cells.append(_fmt(r["context"].get("compile_s")))
        cells.append("; ".join(r["notes"]) if r["notes"] else "")
        lines.append("| " + " | ".join(cells) + " |")
    return lines


def _soak_table(records: List[dict]) -> List[str]:
    lines = [
        "| source | fingerprint | sigs/s | sigs/s under SLO | SLO hit | "
        "p50 overall (ms) | p99 overall (ms) | accounting | notes |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in records:
        m = r["metrics"]
        lines.append(
            "| " + " | ".join([
                r["source"], f"`{r['fingerprint']}`",
                _fmt(m.get("sigs_per_s")),
                _fmt(m.get("sigs_per_s_under_slo")),
                _fmt(m.get("slo_hit_rate")),
                _fmt(m.get("latency_overall_p50_ms")),
                _fmt(m.get("latency_overall_p99_ms")),
                "closed" if r["context"].get("accounting_ok") else "OPEN",
                "; ".join(r["notes"]) if r["notes"] else "",
            ]) + " |"
        )
    return lines


def _multichip_table(records: List[dict]) -> List[str]:
    lines = ["| source | round | devices | dryrun | notes |",
             "|---|---|---|---|---|"]
    for r in records:
        ok = r["metrics"].get("dryrun_ok")
        lines.append("| " + " | ".join([
            r["source"], str(r["round"] if r["round"] is not None else "—"),
            str(r["context"].get("n_devices", "—")),
            "ok" if ok else "FAILED",
            "; ".join(r["notes"]) if r["notes"] else "",
        ]) + " |")
    return lines


def _pipeline_table(records: List[dict]) -> List[str]:
    lines = ["| source | batch | idle K=1 | idle K=2 | idle K=4 | "
             "bit-identical | platform |",
             "|---|---|---|---|---|---|---|"]
    for r in records:
        m = r["metrics"]
        lines.append("| " + " | ".join([
            r["source"], str(r["context"].get("batch", "—")),
            _fmt(m.get("idle_fraction_k1")), _fmt(m.get("idle_fraction_k2")),
            _fmt(m.get("idle_fraction_k4")),
            "yes" if r["context"].get("signatures_bit_identical") else "NO",
            r["platform"],
        ]) + " |")
    return lines


def _campaign_table(records: List[dict]) -> List[str]:
    lines = ["| source | mode | steps | DNF | flagship sigs/s | "
             "warm boot (s) | notes |",
             "|---|---|---|---|---|---|---|"]
    for r in records:
        m = r["metrics"]
        lines.append("| " + " | ".join([
            r["source"],
            "rehearsal" if r["context"].get("rehearse") else "live",
            f"{int(m.get('campaign_steps_done', 0))}/"
            f"{int(m.get('campaign_steps_total', 0))}",
            str(int(m.get("campaign_steps_dnf", 0))),
            _fmt(m.get("gg18_ot_mta_sigs_per_sec")
                 or m.get("secp256k1_2of3_gg18_sigs_per_sec")),
            _fmt(m.get("warmboot_first_sign_s")),
            "; ".join(r["notes"]) if r["notes"] else "",
        ]) + " |")
    return lines


def _claims_section(records: List[dict]) -> List[str]:
    from . import claims

    evaluated = claims.evaluate(records)
    s = claims.summary(evaluated)
    lines = [
        f"Every ROADMAP-owed headline as a machine-evaluated claim "
        f"(`mpcium_tpu/perf/claims.py`; full ledger in `CLAIMS.md`): "
        f"**{s['claimed']} claimed · {s['owed']} owed · "
        f"{s['stale']} stale.**",
        "",
        "| claim | class | status | evidence |",
        "|---|---|---|---|",
    ]
    for c in evaluated:
        ev = ""
        if c["evidence"]:
            ev = f"`{c['evidence']['source']}` → {c['evidence']['value']}"
        lines.append(
            f"| {c['id']} | {c['envfp_class']} | {c['status']} | {ev} |"
        )
    return lines


def render_dashboard(records: List[dict],
                     micro_baseline: Optional[dict] = None) -> str:
    """The committed dashboard, deterministic from its inputs."""
    by_kind: Dict[str, List[dict]] = {"bench": [], "soak": [], "multichip": []}
    for r in records:
        by_kind.setdefault(r["kind"], []).append(r)

    out: List[str] = [
        "# Performance dashboard",
        "",
        "Generated by `scripts/perfcheck.py --regen-history` from the",
        "committed `BENCH_*` / `SOAK_*` / `MULTICHIP_*` artifacts — do not",
        "edit by hand; CI gates this file against a regeneration. Records",
        "are grouped by env fingerprint (`mpcium_tpu/perf/envfp.py`):",
        "**degraded runs (CPU fallback, watchdog zero-records, DNFs) are",
        "tabled separately and never enter a chip trend.** Deltas compare",
        "against the previous row of the same table.",
        "",
    ]

    bench = by_kind["bench"]
    chip = [r for r in bench if not r["degraded"]]
    degraded = [r for r in bench if r["degraded"]]
    out += ["## Flagship trajectory — on-chip", ""]
    if chip:
        out += _bench_table(chip)
    else:
        out.append("(no on-chip records yet)")
    out += ["", "## Bench rounds — degraded / DNF (not comparable to chip)",
            ""]
    if degraded:
        out += _bench_table(degraded)
    else:
        out.append("(none)")

    out += ["", "## Soak (serving under SLO)", ""]
    out += _soak_table(by_kind["soak"]) if by_kind["soak"] else ["(none)"]

    out += ["", "## Multichip dryruns", ""]
    out += (_multichip_table(by_kind["multichip"])
            if by_kind["multichip"] else ["(none)"])

    pipeline = by_kind.get("pipeline") or []
    out += ["", "## Pipeline idle A/B (counter-phase cohorts)", ""]
    out += _pipeline_table(pipeline) if pipeline else ["(none)"]

    campaigns = by_kind.get("campaign") or []
    out += ["", "## Campaigns (scripts/tpu_round.py)", ""]
    out += _campaign_table(campaigns) if campaigns else ["(none)"]

    out += ["", "## Claims ledger", ""]
    out += _claims_section(records)

    if micro_baseline:
        out += ["", "## Micro-baselines (perfcheck gate)", "",
                f"Committed for host `{micro_baseline.get('host', '?')}`, "
                f"python {micro_baseline.get('python', '?')}; the gate "
                "re-anchors informationally on foreign hosts.", "",
                "| bench | baseline median (ms) | samples |",
                "|---|---|---|"]
        from .statcheck import median

        for name, b in sorted((micro_baseline.get("benches") or {}).items()):
            samples = b.get("samples") or []
            med = median(samples) * 1e3 if samples else None
            out.append(f"| {name} | {_fmt(med)} | {len(samples)} |")
    out.append("")
    return "\n".join(out)


def counter_track(records: List[dict]) -> List[dict]:
    """Chrome-trace counter events for the bench trajectory: one ``C``
    event per (record, metric), ts = round index in seconds, on a
    dedicated ``perf-ledger`` pid with its own process_name metadata.
    Merge with ``trace.export.chrome_trace(..., extra_events=...)``."""
    events: List[dict] = [{
        "ph": "M", "name": "process_name", "pid": COUNTER_PID, "tid": 0,
        "args": {"name": "perf ledger (bench trajectory)"},
    }]
    bench = [r for r in records if r["kind"] == "bench" and not r["degraded"]]
    for i, rec in enumerate(bench):
        ts_us = float(i) * 1e6  # one "second" per record: a trend axis,
        for key, _label in _BENCH_COLUMNS:  # not a wall-clock claim
            v = rec["metrics"].get(key)
            if v is None:
                continue
            events.append({
                "ph": "C", "name": f"bench:{key}", "pid": COUNTER_PID,
                "tid": "trend", "ts": ts_us, "args": {"value": v},
            })
    return events
