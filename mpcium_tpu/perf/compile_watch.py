"""The compile-wall ledger: every jit warmup, written down where an
operator can see it.

The 802–1,401 s XLA compile wall (ROADMAP item 4) was measured nowhere
but bench stdout. Engines now report every first-call-per-shape warmup
here via the two-line ``begin``/``finish`` token protocol; each finish
records a ledger entry::

    {"engine": "gg18.sign", "shape": "B4096|q2|mta=ot", "platform":
     "tpu", "compile_s": 802.1, "cache": "miss", "at": "..."}

- persisted as ``COMPILE_LEDGER.json`` beside the XLA persistent cache
  (or under an explicit ``set_ledger_dir`` — the daemon points it at
  its db dir), append-on-every-finish so a crash mid-warmup still
  leaves the completed entries on disk;
- emitted as an mpctrace ``compile:<engine>`` span (node ``engine``,
  tid ``compile``) so compile time lands on the same Perfetto timeline
  as the device phases;
- surfaced through ``health_summary()`` — the ``compile`` section of
  daemon health — with a **warming/ready** state so a restarted node
  (alive, paying the compile wall) is distinguishable from a dead one.
  The ROADMAP-item-4 warm-start daemon will pre-warm shapes between
  ``mark_warming()`` and ``mark_ready()``; today the daemon flips to
  ready once boot completes and entries accrue as traffic compiles.

Each entry is also stamped ``predicted: true|false`` against the
committed ``COMPILE_SURFACE.json`` (the mpcshape static analysis,
STATIC_ANALYSIS.md "Compile surface"; ``set_surface_path`` overrides,
no key when no surface is readable) — the runtime check that every
compile the fleet actually pays was statically enumerable, i.e. a
shape the item-4 AOT pre-warmer could have compiled ahead of time.

Persistent-cache hit/miss: the XLA cache dir (when configured) is
snapshotted at ``begin`` — new files at ``finish`` mean a real compile
wrote artifacts (``miss``); none mean the executable deserialized from
the persistent cache (``hit``); ``none`` means no cache dir was
configured. Shape-bucket dedup is process-global: only the FIRST call
per (engine, shape) pays the snapshot, every later call is one set
lookup returning None.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils import tracing

LEDGER_BASENAME = "COMPILE_LEDGER.json"

_lock = threading.Lock()
_seen: set = set()  # (engine, shape) shape-buckets already ledgered
_entries: List[dict] = []
_state = "ready"  # non-daemon default; run_node marks warming at boot
_ledger_dir: Optional[str] = None  # explicit override (daemon db dir)
_surface_path: Optional[str] = None  # explicit override (tests)
_surface: Any = False  # False = not loaded yet; None = load failed


class _Token:
    __slots__ = ("engine", "shape", "t0", "t0_ns", "cache_dir",
                 "files_before", "meta")

    def __init__(self, engine: str, shape: str,
                 meta: Dict[str, Any]) -> None:
        self.engine = engine
        self.shape = shape
        self.meta = meta
        self.cache_dir = _jax_cache_dir()
        self.files_before = _count_files(self.cache_dir)
        self.t0 = time.perf_counter()
        self.t0_ns = tracing.now_ns()


def _jax_cache_dir() -> Optional[str]:
    """The configured XLA persistent-cache dir, read from an ALREADY
    imported jax only — ledgering must never trigger a backend import."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.config.jax_compilation_cache_dir
    except Exception:  # noqa: BLE001 — config shape varies across jax versions
        return None


def _platform() -> str:
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return "unknown"
    try:
        devs = jax.devices()
        return devs[0].platform if devs else "none"
    except Exception:  # noqa: BLE001 — a wedged backend still gets a ledger entry
        return "unknown"


def _count_files(path: Optional[str]) -> Optional[int]:
    if not path:
        return None
    try:
        return sum(1 for n in os.listdir(path) if n != LEDGER_BASENAME)
    except OSError:
        return None


def set_ledger_dir(path: Optional[str]) -> None:
    """Explicit ledger location (the daemon points this at its db dir so
    daemon-side compiles are ledgered even without a jax cache config)."""
    global _ledger_dir
    with _lock:
        _ledger_dir = path


def ledger_path() -> Optional[str]:
    with _lock:
        d = _ledger_dir
    d = d or _jax_cache_dir()
    return os.path.join(d, LEDGER_BASENAME) if d else None


def set_surface_path(path: Optional[str]) -> None:
    """Explicit COMPILE_SURFACE.json location (test hook); also drops
    the cached surface so the next finish() reloads."""
    global _surface_path, _surface
    with _lock:
        _surface_path = path
        _surface = False


def _load_surface():
    """The committed static compile surface, loaded once per process.
    None when missing/unreadable — entries then carry no ``predicted``
    key rather than guessing."""
    global _surface
    with _lock:
        cached = _surface
        path = _surface_path
    if cached is not False:
        return cached
    # repo-root sibling of HOST_TRANSFER_BUDGET.json; analysis.shape is
    # pure stdlib (no jax) so this lazy import never warms a backend
    from ..analysis.shape.surface import SURFACE_BASENAME, load_surface

    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), SURFACE_BASENAME,
        )
    doc = load_surface(path)
    with _lock:
        _surface = doc
    return doc


def begin(engine: str, shape: str, **meta: Any) -> Optional[_Token]:
    """Open a warmup observation for (engine, shape). Returns None — one
    set lookup, no timing — when this shape bucket was already ledgered
    in this process, so steady-state calls cost nothing."""
    key = (engine, shape)
    with _lock:
        if key in _seen:
            return None
        _seen.add(key)
    return _Token(engine, shape, meta)


def finish(token: Optional[_Token]) -> Optional[dict]:
    """Close an observation: classify the persistent-cache outcome,
    append the entry to the ledger (memory + JSON file), emit the
    ``compile:<engine>`` span. Returns the entry (tests assert on it)."""
    if token is None:
        return None
    elapsed = time.perf_counter() - token.t0
    t1_ns = tracing.now_ns()
    files_after = _count_files(token.cache_dir)
    if token.files_before is None or files_after is None:
        cache = "none"
    elif files_after > token.files_before:
        cache = "miss"
    else:
        cache = "hit"
    entry = {
        "engine": token.engine,
        "shape": token.shape,
        "platform": _platform(),
        "compile_s": round(elapsed, 3),
        "cache": cache,
        "at": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()),
    }
    surface = _load_surface()
    if surface is not None:
        from ..analysis.shape.surface import shape_predicted

        # an unpredicted compile is an mpcshape analysis gap — the
        # tier-1 gate over committed artifacts fails loudly on one
        entry["predicted"] = shape_predicted(
            surface, token.engine, token.shape
        )
    for k, v in token.meta.items():
        if isinstance(v, (str, int, float, bool)):
            entry.setdefault(k, v)
    with _lock:
        _entries.append(entry)
        snapshot = list(_entries)
    _write_ledger(snapshot)
    tracing.emit(
        f"compile:{token.engine}", token.t0_ns, t1_ns,
        node="engine", tid="compile",
        shape=token.shape, cache=cache,
        compile_s=entry["compile_s"], platform=entry["platform"],
    )
    return entry


def _write_ledger(snapshot: List[dict]) -> None:
    path = ledger_path()
    if path is None:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"entries": snapshot}, f, indent=1, sort_keys=True)
            f.write("\n")
    except OSError:
        pass  # ledgering must never take the engine down


def entries() -> List[dict]:
    with _lock:
        return list(_entries)


def seen(engine: str, shape: str) -> bool:
    """True when this (engine, shape) bucket was already ledgered in this
    process — the pre-warmer skips work an earlier pass (or live traffic)
    has already paid for."""
    with _lock:
        return (engine, shape) in _seen


def mark_warming() -> None:
    """Daemon boot: kernels for this node's shapes are not compiled yet.
    A node publishing ``warming`` is alive-but-cold — the health state
    that makes a restart distinguishable from a death."""
    global _state
    with _lock:
        _state = "warming"


def mark_ready() -> None:
    global _state
    with _lock:
        _state = "ready"


def health_summary() -> Dict[str, object]:
    """The ``compile`` section of the health payload: warming/ready
    state plus hit/miss/seconds accounting and the most recent entry."""
    with _lock:
        ents = list(_entries)
        state = _state
    hits = sum(1 for e in ents if e["cache"] == "hit")
    misses = sum(1 for e in ents if e["cache"] == "miss")
    # predicted: False = a compile the static surface did not enumerate —
    # drift that escaped the mpcshape gate, visible at runtime
    unpredicted = sum(1 for e in ents if e.get("predicted") is False)
    return {
        "state": state,
        "compiles": len(ents),
        "cache_hits": hits,
        "cache_misses": misses,
        "unpredicted": unpredicted,
        "total_compile_s": round(sum(e["compile_s"] for e in ents), 3),
        "last": ents[-1] if ents else None,
        "ledger": ledger_path(),
    }


def export_gauges(metrics, ready_states=("ready",)) -> None:
    """Mirror the summary into a ``MetricsRegistry`` as gauges so the
    daemon's Prometheus text carries the compile surface."""
    s = health_summary()
    metrics.gauge("compile.ready").set(
        1.0 if s["state"] in ready_states else 0.0
    )
    metrics.gauge("compile.count").set(float(s["compiles"]))
    metrics.gauge("compile.cache_hits").set(float(s["cache_hits"]))
    metrics.gauge("compile.cache_misses").set(float(s["cache_misses"]))
    metrics.gauge("compile.unpredicted").set(float(s["unpredicted"]))
    metrics.gauge("compile.seconds_total").set(float(s["total_compile_s"]))


def reset() -> None:
    """Test hook: forget every shape bucket, entry, and state override."""
    global _state, _ledger_dir, _surface_path, _surface
    with _lock:
        _seen.clear()
        _entries.clear()
        _state = "ready"
        _ledger_dir = None
        _surface_path = None
        _surface = False
