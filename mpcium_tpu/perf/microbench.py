"""The perfcheck micro-benches: fast, CPU-safe, hot-path-shaped.

Each bench returns a list of per-sample wall seconds for statcheck to
compare against the committed baseline. They are chosen to cover the
layers a PR can silently slow down without touching a kernel:

- ``field_mulmod``: host-side field arithmetic (the Python bignum path
  every host verdict and reshare coefficient rides).
- ``sha256_block``: host hashing throughput (commitments, OT pads —
  the host half of ROADMAP item 2).
- ``wheel_latency``: scheduler intake→dispatch timer latency through
  the real ``_TimingWheel`` (PR 5's one-thread timer core).
- ``span_overhead``: mpctrace span open/close cost with tracing armed
  (PR 8's promise that observability stays cheap).
- ``sha512_block``: host SHA-512 throughput (the hashlib fallback the
  Ed25519 challenge hashing keeps for ragged batches).
- ``prg_expand_device`` / ``ot_transpose_device``: warm-dispatch cost
  of the ops.hash_suite device kernels the OT-MtA extension rides
  (ISSUE 11) — compile happens once in the warmup call, so the samples
  measure dispatch + execute, which is what a regression would slow.
- ``ot_kos_check_device``: warm-dispatch cost of the KOS correlation
  check pair (tags + verify) the active-security OT-MtA runs per
  extension (ISSUE 16). One lane: the per-extension fixed cost every
  checked signing batch pays. The Gilboa/consistency kernels are
  deliberately NOT micro-benched — their shared secp-ladder jit units
  cost ~70 s of cold compile on a bare CPU host, blowing the <30 s
  budget; bench.py's ``gg18_ot_checks_s`` A/B covers them end to end.
- ``pipeline_handoff``: counter-phase cohort machinery cost (ISSUE 17)
  — a K=1 and a K=2 pass over no-op stub rounds, timing generator
  round-robin + executor handoff with zero device work in the way.
- ``donated_round_step``: warm re-dispatch of a ``donate_argnums``
  round step with the ``st = step(st)`` rebind the zero-idle pipeline
  carries through every round.

No TOP-LEVEL jax import: perfcheck must run in <30 s on a bare CPU
host, so the device rows import jax lazily inside the bench body and
use deliberately small shapes. Samples use best-of-k inner reps to
shave scheduler noise off the floor; the statistics in statcheck
absorb what remains.
"""
from __future__ import annotations

import hashlib
import random
import threading
import time
from typing import Callable, Dict, List

# secp256k1 field prime — the modulus the host math actually uses
_P = 2**256 - 2**32 - 977

DEFAULT_SAMPLES = 30


def _timed_samples(fn: Callable[[], None], samples: int,
                   best_of: int = 3) -> List[float]:
    """Per sample: best wall time of ``best_of`` runs of ``fn`` — the
    minimum estimates the noise-free cost; sample-to-sample spread is
    what statcheck's rank test consumes."""
    fn()  # warm caches/allocators outside the measurement
    out = []
    for _ in range(samples):
        best = float("inf")
        for _ in range(best_of):
            t0 = time.perf_counter()
            fn()
            dt = time.perf_counter() - t0
            if dt < best:
                best = dt
        out.append(best)
    return out


def field_mulmod(samples: int = DEFAULT_SAMPLES, inner: int = 400) -> List[float]:
    rng = random.Random(0xF1E1D)
    xs = [rng.getrandbits(256) | 1 for _ in range(64)]

    def body() -> None:
        acc = 1
        for i in range(inner):
            acc = acc * xs[i & 63] % _P
        if acc == 0:  # keep the loop un-eliminable
            raise AssertionError("mulmod degenerated")

    return _timed_samples(body, samples)


def sha256_block(samples: int = DEFAULT_SAMPLES, kib: int = 96) -> List[float]:
    block = bytes(range(256)) * (kib * 4)  # kib KiB of fixed bytes

    def body() -> None:
        hashlib.sha256(block).digest()

    return _timed_samples(body, samples)


def wheel_latency(samples: int = DEFAULT_SAMPLES) -> List[float]:
    """Schedule→fire latency of the scheduler's timing wheel: the intake
    →dispatch path's timer hop, measured on the real class. Imported
    lazily — batch_scheduler pulls wire/session modules that a bare
    statcheck import must not pay for."""
    from ..consumers.batch_scheduler import _TimingWheel

    wheel = _TimingWheel(name="perfcheck-wheel")
    try:
        out = []
        fired = threading.Event()
        wheel.schedule("warm", 0.0, fired.set)
        fired.wait(2.0)
        for i in range(samples):
            fired = threading.Event()
            t0 = time.perf_counter()
            wheel.schedule(("s", i), 0.0, fired.set)
            if not fired.wait(2.0):
                raise RuntimeError("timing wheel never fired (perfcheck)")
            out.append(time.perf_counter() - t0)
        return out
    finally:
        wheel.close()


def span_overhead(samples: int = DEFAULT_SAMPLES, inner: int = 400) -> List[float]:
    """Cost of ``inner`` armed span open/closes into a null sink.
    Tracing state is saved and restored — the bench must not leave the
    process armed (or disarm a caller's recorder)."""
    from ..utils import tracing

    was_enabled = tracing.enabled()
    prev_sink = tracing._sink

    def body() -> None:
        for _ in range(inner):
            with tracing.span("perfcheck", kind="X"):
                pass

    tracing.enable(sink=lambda _s: None)
    try:
        return _timed_samples(body, samples)
    finally:
        if was_enabled:
            tracing.enable(sink=prev_sink)
        else:
            tracing.disable()


def sha512_block(samples: int = DEFAULT_SAMPLES, kib: int = 96) -> List[float]:
    """Host SHA-512 throughput — the hashlib fallback lane of the
    Ed25519 challenge hashing (ragged message batches)."""
    block = bytes(range(256)) * (kib * 4)  # kib KiB of fixed bytes

    def body() -> None:
        hashlib.sha512(block).digest()

    return _timed_samples(body, samples)


def prg_expand_device(samples: int = DEFAULT_SAMPLES) -> List[float]:
    """Warm dispatch of the device IKNP PRG expansion (hash_suite):
    KAPPA=128 seeds × 8 blocks. The warmup call inside _timed_samples
    pays the one-time compile; samples measure dispatch + execute."""
    import numpy as np

    from ..ops import hash_suite as hs

    seeds = np.frombuffer(
        hashlib.sha256(b"perfcheck-prg-seeds").digest() * (128 * 32 // 32),
        np.uint8,
    ).reshape(128, 32)
    prefix = b"perfcheck-prg|v1"

    def body() -> None:
        hs.prg_expand_device(prefix, seeds, 8).block_until_ready()

    return _timed_samples(body, samples)


def ot_transpose_device(samples: int = DEFAULT_SAMPLES) -> List[float]:
    """Warm dispatch of the device packed bit-transpose (hash_suite):
    (128, 512) packed bytes → (4096, 16), the per-chunk OT shape at
    B=16 lanes."""
    import jax.numpy as jnp
    import numpy as np

    from ..ops import hash_suite as hs

    rng = random.Random(0x0707)
    packed = jnp.asarray(np.frombuffer(
        bytes(rng.getrandbits(8) for _ in range(128 * 512)), np.uint8
    ).reshape(128, 512))

    def body() -> None:
        hs.ot_transpose_device(packed).block_until_ready()

    return _timed_samples(body, samples)


def ot_kos_check_device(samples: int = DEFAULT_SAMPLES) -> List[float]:
    """Warm dispatch of the KOS correlation-check kernels (mta_ot):
    Alice's χ-tag opening plus Bob's χ·Q == t̄ ⊕ x̄⊗Δ verify at one
    batch lane (M = 256 OTs, κ = 128). The warmup call pays the
    one-time compile; samples measure dispatch + execute."""
    import numpy as np

    from ..protocol.ecdsa import mta_ot

    def blob(tag: bytes, n: int) -> bytes:
        out = bytearray()
        ctr = 0
        while len(out) < n:
            out += hashlib.sha256(b"perfkos|%s|%d" % (tag, ctr)).digest()
            ctr += 1
        return bytes(out[:n])

    kappa, m = mta_ot.KAPPA, mta_ot.NBITS  # one lane
    rows_a = np.frombuffer(
        blob(b"ra", m * kappa // 8), np.uint8).reshape(m, kappa // 8)
    rows_b = np.frombuffer(
        blob(b"rb", m * kappa // 8), np.uint8).reshape(m, kappa // 8)
    x_bits = np.frombuffer(blob(b"xb", m), np.uint8) & 1
    delta = np.frombuffer(blob(b"dl", kappa), np.uint8) & 1
    U = np.frombuffer(blob(b"uu", kappa * 32), np.uint8).reshape(kappa, 32)
    pref = mta_ot._fs_prefixes(b"perfkos|", b"kos")

    def body() -> None:
        xbar, tbar = mta_ot._k_kos_tags(rows_a, x_bits, U, *pref)
        mta_ot._k_kos_verify(
            rows_b, delta, U, xbar, tbar, *pref
        ).block_until_ready()

    return _timed_samples(body, samples)


def pipeline_handoff(samples: int = DEFAULT_SAMPLES, rounds: int = 32) -> List[float]:
    """Handoff cost of the counter-phase cohort pipeline (engine/
    pipeline): one K=1 inline pass and one K=2 overlapped pass over
    ``rounds`` stub rounds whose device and host stages are no-ops, so
    the sample times ONLY the machinery — generator round-robin,
    executor submit, future wait — and a regression in either path
    (serial oracle or overlap schedule) moves the row."""
    from ..engine import pipeline as pl

    def make_jobs(k: int):
        def make_job(ci: int):
            def job():
                acc = 0
                for r in range(rounds):
                    acc += yield ("stub", lambda r=r: r)
                return acc

            return job

        return [make_job(ci) for ci in range(k)]

    want = rounds * (rounds - 1) // 2

    def body() -> None:
        for k in (1, 2):
            outs = pl.run_counter_phase(make_jobs(k))
            if outs != [want] * k:  # keep the schedule un-eliminable
                raise AssertionError("stub pipeline produced wrong sums")

    return _timed_samples(body, samples)


def donated_round_step(samples: int = DEFAULT_SAMPLES) -> List[float]:
    """Warm re-dispatch of a ``donate_argnums`` round step over a
    signing-shaped state pytree — dict of (16, 8) uint32 planes donated
    and rebound ``st = step(st)``, the carried-round-state discipline of
    the zero-idle pipeline (ISSUE 17). CPU usually declines the donation
    (buffers not usable — warning suppressed here); the row still times
    the donation-annotated dispatch path the TPU rides."""
    import functools
    import warnings

    import jax
    import jax.numpy as jnp

    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable"
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(st):
        return {k: v + jnp.uint32(1) for k, v in st.items()}

    def body() -> None:
        st = {k: jnp.zeros((16, 8), jnp.uint32) for k in ("s", "m", "r")}
        for _ in range(8):
            st = step(st)
        jax.block_until_ready(st)

    return _timed_samples(body, samples)


ALL_BENCHES: Dict[str, Callable[[int], List[float]]] = {
    "field_mulmod": field_mulmod,
    "sha256_block": sha256_block,
    "sha512_block": sha512_block,
    "wheel_latency": wheel_latency,
    "span_overhead": span_overhead,
    "prg_expand_device": prg_expand_device,
    "ot_transpose_device": ot_transpose_device,
    "ot_kos_check_device": ot_kos_check_device,
    "pipeline_handoff": pipeline_handoff,
    "donated_round_step": donated_round_step,
}


def run_all(samples: int = DEFAULT_SAMPLES) -> Dict[str, List[float]]:
    return {name: fn(samples) for name, fn in sorted(ALL_BENCHES.items())}
