"""The statistical perf-regression gate: honest about noise.

A micro-bench sample is a noisy draw; a gate that compares two means
fails on a busy CI box and passes a real 20% regression on a quiet one.
This module gates the way the accelerator-crypto literature reports
numbers: a one-sided Mann-Whitney U test (does the current distribution
stochastically dominate — run slower than — the baseline?) combined
with a practical-effect floor (the median ratio must exceed
``min_ratio``) and a seeded bootstrap confidence interval on that ratio
(its lower bound must clear 1.0). All three must agree before the gate
fails, which is what keeps the false-positive rate on identical
distributions under alpha while an injected 1.5× slowdown at n=30 fails
with p ≈ 1e-11.

Zero dependencies: the normal approximation with tie correction covers
n ≥ ~8 per side, which is the regime perfcheck runs in. Bootstrap
resampling uses ``random.Random(seed)`` — deterministic, replayable
verdicts.
"""
from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple

DEFAULT_ALPHA = 0.01
DEFAULT_MIN_RATIO = 1.25  # practical-effect floor: <25% slower never fails
DEFAULT_BOOT_ITERS = 800


def _phi(z: float) -> float:
    """Standard normal CDF."""
    return 0.5 * (1.0 + math.erf(z / math.sqrt(2.0)))


def median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        raise ValueError("median of empty sample")
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def mann_whitney_p(baseline: Sequence[float],
                   current: Sequence[float]) -> float:
    """One-sided p-value for H1 "current is stochastically greater
    (slower) than baseline", normal approximation with tie correction
    and continuity correction. Degenerate spreads (all values tied)
    return 1.0 — indistinguishable is not a regression."""
    n1, n2 = len(baseline), len(current)
    if n1 == 0 or n2 == 0:
        raise ValueError("mann_whitney_p needs non-empty samples")
    pooled = [(v, 0) for v in baseline] + [(v, 1) for v in current]
    pooled.sort(key=lambda t: t[0])
    # midranks with tie groups
    ranks = [0.0] * len(pooled)
    tie_term = 0.0
    i = 0
    while i < len(pooled):
        j = i
        while j + 1 < len(pooled) and pooled[j + 1][0] == pooled[i][0]:
            j += 1
        rank = (i + j + 2) / 2.0  # ranks are 1-based
        for k in range(i, j + 1):
            ranks[k] = rank
        t = j - i + 1
        tie_term += t * t * t - t
        i = j + 1
    r2 = sum(r for r, (_v, side) in zip(ranks, pooled) if side == 1)
    u2 = r2 - n2 * (n2 + 1) / 2.0  # U statistic for "current greater"
    mean = n1 * n2 / 2.0
    n = n1 + n2
    var = (n1 * n2 / 12.0) * ((n + 1) - tie_term / (n * (n - 1)))
    if var <= 0.0:
        return 1.0
    z = (u2 - mean - 0.5) / math.sqrt(var)
    return 1.0 - _phi(z)


def bootstrap_ratio_ci(
    baseline: Sequence[float],
    current: Sequence[float],
    iters: int = DEFAULT_BOOT_ITERS,
    seed: int = 0,
    lo_q: float = 0.025,
    hi_q: float = 0.975,
) -> Tuple[float, float]:
    """Seeded bootstrap CI of median(current)/median(baseline)."""
    rng = random.Random(seed)
    b, c = list(baseline), list(current)
    ratios = []
    for _ in range(iters):
        rb = [b[rng.randrange(len(b))] for _ in b]
        rc = [c[rng.randrange(len(c))] for _ in c]
        mb = median(rb)
        ratios.append(median(rc) / mb if mb > 0 else float("inf"))
    ratios.sort()
    lo = ratios[min(len(ratios) - 1, int(lo_q * len(ratios)))]
    hi = ratios[min(len(ratios) - 1, int(hi_q * len(ratios)))]
    return (lo, hi)


@dataclass
class Verdict:
    bench: str
    regressed: bool
    p_value: float
    ratio: float  # median(current)/median(baseline); >1 = slower
    ci: Tuple[float, float]
    baseline_median: float
    current_median: float
    note: str = ""

    def render(self) -> str:
        mark = "REGRESSION" if self.regressed else "ok"
        line = (
            f"{self.bench}: {mark} — median "
            f"{self.baseline_median * 1e3:.3f}ms → "
            f"{self.current_median * 1e3:.3f}ms "
            f"(ratio {self.ratio:.3f}, p={self.p_value:.2e}, "
            f"95% CI [{self.ci[0]:.3f}, {self.ci[1]:.3f}])"
        )
        return line + (f" [{self.note}]" if self.note else "")


def compare(
    bench: str,
    baseline: Sequence[float],
    current: Sequence[float],
    alpha: float = DEFAULT_ALPHA,
    min_ratio: float = DEFAULT_MIN_RATIO,
    boot_iters: int = DEFAULT_BOOT_ITERS,
    seed: int = 0,
) -> Verdict:
    """The gate for one bench: regression iff the rank test, the effect
    floor, AND the bootstrap CI all say slower."""
    bm, cm = median(baseline), median(current)
    ratio = cm / bm if bm > 0 else float("inf")
    p = mann_whitney_p(baseline, current)
    ci = bootstrap_ratio_ci(baseline, current, iters=boot_iters, seed=seed)
    regressed = p < alpha and ratio >= min_ratio and ci[0] > 1.0
    return Verdict(
        bench=bench, regressed=regressed, p_value=p, ratio=ratio, ci=ci,
        baseline_median=bm, current_median=cm,
    )


@dataclass
class GateResult:
    verdicts: List[Verdict] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    @property
    def regressions(self) -> List[Verdict]:
        return [v for v in self.verdicts if v.regressed]

    @property
    def ok(self) -> bool:
        return not self.regressions


def gate(
    baselines: Dict[str, Sequence[float]],
    currents: Dict[str, Sequence[float]],
    alpha: float = DEFAULT_ALPHA,
    min_ratio: float = DEFAULT_MIN_RATIO,
    seed: int = 0,
) -> GateResult:
    """Compare every bench present in BOTH dicts; benches only on one
    side are reported as notes, never silently skipped (no silent caps)."""
    result = GateResult()
    for name in sorted(set(baselines) | set(currents)):
        if name not in baselines:
            result.notes.append(f"{name}: no committed baseline — skipped")
            continue
        if name not in currents:
            result.notes.append(f"{name}: not measured this run — skipped")
            continue
        result.verdicts.append(compare(
            name, baselines[name], currents[name],
            alpha=alpha, min_ratio=min_ratio, seed=seed,
        ))
    return result
