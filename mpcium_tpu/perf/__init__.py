"""mpcperf: the performance observatory (PERFORMANCE.md "perf observatory").

Four coupled parts, each importable on its own so nothing here rides the
hot path unless asked:

- ``compile_watch``: the compile-wall ledger. Engines report every
  first-call-per-shape warmup (the XLA compile) as a ledger entry
  {engine, shape, platform, compile_s, persistent-cache hit/miss},
  persisted as ``COMPILE_LEDGER.json`` beside the XLA cache, emitted as
  mpctrace ``compile:*`` spans, and surfaced through daemon health with
  a warming/ready state — the data surface the ROADMAP-item-4
  warm-start daemon builds on.
- ``ledger`` + ``report``: the bench trajectory. Every committed
  ``BENCH_*`` / ``SOAK_*`` / ``MULTICHIP_*`` artifact normalizes into
  ``PERF_history.jsonl`` grouped by platform/env fingerprint (CPU-
  degraded runs can never average into chip trends), rendered as
  ``PERFORMANCE_dashboard.md`` and a Perfetto counter track.
- ``statcheck`` + ``microbench``: the statistical regression gate.
  Fast CPU-safe micro-benches compared against committed baselines with
  a Mann-Whitney + bootstrap noise band (``scripts/perfcheck.py``,
  ``make perfcheck``, wired into ``make check`` and tier-1).
- ``profile``: optional deep profiling (``MPCIUM_PROFILE=1``) capturing
  ``jax.profiler`` device timelines and folding device-op time into the
  PhaseTimer span tables.

``envfp`` stamps bench/soak records with the environment fingerprint
(git sha, jax version, device kind/count, MPCIUM_* knobs) the ledger
groups by. Nothing in this package imports jax at module scope.
"""
