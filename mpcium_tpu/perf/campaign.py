"""mpccampaign: the resumable step-DAG runner for a TPU measurement round.

ROADMAP item 1's round kept not happening because it was a manual,
multi-hour checklist run inside a preemptible TPU window: it died twice
to hung steps (BENCH_r02/r04 watchdog DNFs) and once to a tunnel outage
that left a CPU-degraded record in the round's official slot (r05).
This module turns the checklist into a **campaign**: an ordered list of
``Step``\\ s, each subprocess-isolated under its own timeout (one hung
step can never kill the window), checkpointed to a JSONL state file
after every step (a preempted window resumes exactly where it died),
streamed as campaign spans plus a ``.prom`` heartbeat, and assembled
into one ``CAMPAIGN_*.json`` artifact the perf ledger and the claims
engine ingest.

The state file is append-only JSONL — one header line, then one line
per finished step, each ``flush``+``fsync``'d before the next step
starts. A SIGKILL mid-step therefore loses at most the in-flight step;
a SIGKILL mid-*write* leaves a torn tail, which ``load_state`` detects
(unparseable last line), truncates, and re-runs — the same torn-tail
contract the broker journal uses.

Step drivers live in ``scripts/tpu_round.py``; this module is the
engine and is deliberately jax-free (the runner process must never
claim the chip its step subprocesses are measuring).
"""
from __future__ import annotations

import hashlib
import json
import os
import subprocess
import time
from typing import Callable, Dict, Optional, Sequence

from ..utils.metrics import MetricsRegistry
from .envfp import env_fingerprint

STATE_BASENAME = "CAMPAIGN_state.json"
HEARTBEAT_BASENAME = "campaign_heartbeat.prom"

# step state gauge values for the heartbeat
_PENDING, _RUNNING, _DONE, _DNF = 0.0, 1.0, 2.0, 3.0


class Step:
    """One subprocess-isolated campaign step.

    ``parse`` maps captured stdout to the step's result dict; the
    default takes the LAST line that parses as a JSON object (every
    bench/driver in this repo prints its record as a single JSON line,
    possibly after warm-up noise). ``needs`` lists step ids that must
    have finished OK first — a failed dependency skips the dependent
    with a structured DNF instead of burning window time on it.
    """

    def __init__(
        self,
        step_id: str,
        argv: Sequence[str],
        *,
        env: Optional[Dict[str, str]] = None,
        timeout_s: float = 600.0,
        needs: Sequence[str] = (),
        parse: Optional[Callable[[str], dict]] = None,
        cwd: Optional[str] = None,
    ):
        self.id = step_id
        self.argv = list(argv)
        self.env = dict(env or {})
        self.timeout_s = float(timeout_s)
        self.needs = list(needs)
        self.parse = parse or last_json_line
        self.cwd = cwd

    def plan_entry(self) -> dict:
        return {"id": self.id, "argv": self.argv, "env": self.env,
                "timeout_s": self.timeout_s, "needs": self.needs}


def last_json_line(stdout: str) -> dict:
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return doc
    raise ValueError("no JSON object line in step stdout")


def plan_fingerprint(steps: Sequence[Step]) -> str:
    """Identity of the step DAG: resuming a state file recorded under a
    DIFFERENT plan must be an error, not a silent skip-mismatch."""
    doc = json.dumps([s.plan_entry() for s in steps], sort_keys=True)
    return hashlib.sha256(doc.encode()).hexdigest()[:16]


# -- state file (append-only JSONL, torn-tail tolerant) ----------------------


class StateMismatch(RuntimeError):
    """State file belongs to a different plan/campaign."""


def load_state(path: str) -> dict:
    """Replay the checkpoint file. Returns ``{"header": dict|None,
    "results": {step_id: line}, "torn": bool}``. An unparseable LAST
    line is a torn tail (killed mid-write): it is dropped and the file
    truncated to the surviving prefix. An unparseable line anywhere
    else is corruption and raises — resuming over it would silently
    skip real work."""
    header = None
    results: Dict[str, dict] = {}
    torn = False
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return {"header": None, "results": {}, "torn": False}
    lines = raw.split(b"\n")
    good_bytes = 0
    for i, line in enumerate(lines):
        if not line.strip():
            good_bytes += len(line) + 1
            continue
        try:
            doc = json.loads(line)
            if not isinstance(doc, dict):
                raise ValueError("state line is not an object")
        except ValueError:
            rest = b"".join(lines[i + 1:]).strip()
            if rest:
                raise StateMismatch(
                    f"{path}: corrupt line {i + 1} with data after it — "
                    f"not a torn tail; refusing to resume over it"
                )
            torn = True
            break
        good_bytes += len(line) + 1
        if "campaign" in doc and "step" not in doc:
            header = doc
        elif "step" in doc:
            results[doc["step"]] = doc
    if torn:
        with open(path, "r+b") as f:
            f.truncate(max(good_bytes - 1, 0) if good_bytes else 0)
            f.flush()
            os.fsync(f.fileno())
    return {"header": header, "results": results, "torn": torn}


def _append_state(path: str, doc: dict) -> None:
    with open(path, "a") as f:
        f.write(json.dumps(doc, sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())


# -- the runner --------------------------------------------------------------


class Campaign:
    def __init__(
        self,
        name: str,
        steps: Sequence[Step],
        *,
        state_path: str,
        rehearse: bool = False,
        heartbeat_path: Optional[str] = None,
        log: Callable[[str], None] = print,
    ):
        self.name = name
        self.steps = list(steps)
        self.state_path = state_path
        self.rehearse = rehearse
        self.heartbeat_path = heartbeat_path
        self.log = log
        self.metrics = MetricsRegistry()
        self._t0 = time.monotonic()
        self._fp = plan_fingerprint(self.steps)
        ids = [s.id for s in self.steps]
        if len(ids) != len(set(ids)):
            raise ValueError(f"duplicate step ids in plan: {ids}")

    # -- heartbeat ----------------------------------------------------------

    def _beat(self, current: Optional[str], results: Dict[str, dict],
              last_rc: Optional[int] = None) -> None:
        m = self.metrics
        done = sum(1 for r in results.values()
                   if not (r.get("result") or {}).get("dnf"))
        dnf = len(results) - done
        m.gauge("campaign.steps_total").set(float(len(self.steps)))
        m.gauge("campaign.steps_done").set(float(done))
        m.gauge("campaign.steps_dnf").set(float(dnf))
        m.gauge("campaign.elapsed_s").set(
            round(time.monotonic() - self._t0, 3))
        if last_rc is not None:
            m.gauge("campaign.last_step_rc").set(float(last_rc))
        for s in self.steps:
            if s.id in results:
                state = (_DNF if (results[s.id].get("result") or {}).get("dnf")
                         else _DONE)
            elif s.id == current:
                state = _RUNNING
            else:
                state = _PENDING
            m.gauge(f"campaign.step.{s.id}.state").set(state)
        if self.heartbeat_path:
            tmp = self.heartbeat_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(m.to_prometheus(labels={"campaign": self.name}))
            os.replace(tmp, self.heartbeat_path)

    # -- one step -----------------------------------------------------------

    def _run_step(self, step: Step) -> dict:
        env = dict(os.environ)
        env.update(step.env)
        t0 = time.monotonic()
        t0_ns = time.time_ns()
        try:
            proc = subprocess.run(
                step.argv, env=env, cwd=step.cwd,
                capture_output=True, text=True, timeout=step.timeout_s,
            )
            rc, stdout, stderr = proc.returncode, proc.stdout, proc.stderr
        except subprocess.TimeoutExpired as e:
            elapsed = round(time.monotonic() - t0, 3)
            result = {
                "dnf": True,
                "reason": f"watchdog: step exceeded {step.timeout_s:.0f}s",
                "elapsed_s": elapsed,
                "env": env_fingerprint(),
            }
            tail = (e.stdout or b"")
            if isinstance(tail, bytes):
                tail = tail.decode(errors="replace")
            return {"step": step.id, "rc": None, "result": result,
                    "elapsed_s": elapsed, "tail": tail[-500:],
                    "t0_ns": t0_ns, "t1_ns": time.time_ns()}
        elapsed = round(time.monotonic() - t0, 3)
        if rc != 0:
            result = {
                "dnf": True,
                "reason": f"rc={rc}: {stderr.strip()[-300:] or 'no stderr'}",
                "elapsed_s": elapsed,
                "env": env_fingerprint(),
            }
        else:
            try:
                result = step.parse(stdout)
            except Exception as e:  # noqa: BLE001 — unparseable = DNF
                result = {
                    "dnf": True,
                    "reason": f"unparseable step output: {e}",
                    "elapsed_s": elapsed,
                    "env": env_fingerprint(),
                }
        return {"step": step.id, "rc": rc, "result": result,
                "elapsed_s": elapsed, "tail": stdout[-500:],
                "t0_ns": t0_ns, "t1_ns": time.time_ns()}


    def _emit_span(self, line: dict) -> None:
        try:
            from ..utils import tracing

            if not tracing.enabled():
                return
            result = line.get("result") or {}
            tracing.emit(
                f"campaign:{line['step']}",
                line.get("t0_ns") or 0,
                line.get("t1_ns") or 0,
                node="campaign", tid=self.name,
                rc=line.get("rc") if line.get("rc") is not None else -1,
                dnf=1 if result.get("dnf") else 0,
            )
        except Exception:  # noqa: BLE001 — spans must never kill a step
            pass

    # -- the loop -----------------------------------------------------------

    def run(self) -> dict:
        """Execute the plan, resuming from the state file. Returns the
        assembled campaign report (also see ``report()``)."""
        state = load_state(self.state_path)
        if state["torn"]:
            self.log(f"campaign: torn tail truncated in {self.state_path}; "
                     f"the interrupted step will re-run")
        header = state["header"]
        if header is not None:
            if header.get("plan_fp") != self._fp:
                raise StateMismatch(
                    f"{self.state_path} was recorded under a different "
                    f"plan (fp {header.get('plan_fp')} != {self._fp}); "
                    f"delete it or pass a fresh --state path"
                )
        else:
            _append_state(self.state_path, {
                "campaign": self.name, "plan_fp": self._fp,
                "rehearse": self.rehearse,
                "steps": [s.id for s in self.steps],
            })
        results = state["results"]
        for step in self.steps:
            if step.id in results:
                self.log(f"campaign: [{step.id}] already finished — "
                         f"skipping (resume)")
                continue
            bad_needs = [
                n for n in step.needs
                if (results.get(n) or {}).get("result", {}).get("dnf")
                or n not in results
            ]
            if bad_needs:
                line = {
                    "step": step.id, "rc": None,
                    "result": {
                        "dnf": True,
                        "reason": f"dependency not satisfied: {bad_needs}",
                        "elapsed_s": 0.0,
                        "env": env_fingerprint(),
                    },
                    "elapsed_s": 0.0, "tail": "",
                }
                results[step.id] = line
                _append_state(self.state_path, line)
                self._beat(None, results)
                self.log(f"campaign: [{step.id}] DNF (deps: {bad_needs})")
                continue
            self._beat(step.id, results)
            self.log(f"campaign: [{step.id}] running "
                     f"(timeout {step.timeout_s:.0f}s): "
                     f"{' '.join(step.argv[:6])}…")
            line = self._run_step(step)
            results[step.id] = line
            _append_state(self.state_path, line)
            self._emit_span(line)
            self._beat(None, results, last_rc=line.get("rc"))
            verdict = ("DNF: " + line["result"].get("reason", "?")
                       if line["result"].get("dnf")
                       else f"ok in {line['elapsed_s']:.1f}s")
            self.log(f"campaign: [{step.id}] {verdict}")
        return self.report(results)

    # -- report assembly ----------------------------------------------------

    def report(self, results: Dict[str, dict]) -> dict:
        steps_doc = {}
        dnf = 0
        for s in self.steps:
            line = results.get(s.id)
            if line is None:
                dnf += 1
                steps_doc[s.id] = {"dnf": True, "reason": "never ran"}
                continue
            res = dict(line.get("result") or {})
            if res.get("dnf"):
                dnf += 1
            res["_elapsed_s"] = line.get("elapsed_s")
            res["_rc"] = line.get("rc")
            steps_doc[s.id] = res
        done = len(self.steps) - dnf
        complete = dnf == 0
        # the runner itself is jax-free, so its own fingerprint says
        # "uninitialized"; the record's platform must be the one the
        # step subprocesses actually measured on, or a live TPU round
        # would self-report as degraded and satisfy no chip claim
        env = env_fingerprint()
        if env.get("platform") in (None, "uninitialized"):
            for res in steps_doc.values():
                senv = res.get("env") if isinstance(res, dict) else None
                if isinstance(senv, dict) and senv.get("platform") not in (
                        None, "uninitialized", "unavailable", "none"):
                    for k in ("platform", "device_kind", "device_count"):
                        if senv.get(k) is not None:
                            env[k] = senv[k]
                    break
        metrics = lift_metrics(steps_doc)
        metrics.update({
            "campaign_complete": 1.0 if complete else 0.0,
            "campaign_steps_total": float(len(self.steps)),
            "campaign_steps_done": float(done),
            "campaign_steps_dnf": float(dnf),
        })
        return {
            "comment": (
                f"Campaign report '{self.name}' — generated by "
                f"scripts/tpu_round.py; one record per step, metrics "
                f"lifted for the perf ledger and the claims engine."
            ),
            "campaign": self.name,
            "rehearse": self.rehearse,
            "plan_fp": self._fp,
            "steps_total": len(self.steps),
            "steps_done": done,
            "steps_dnf": dnf,
            "complete": complete,
            "steps": steps_doc,
            "metrics": metrics,
            "context": lift_context(steps_doc),
            "env": env,
            "elapsed_s": round(time.monotonic() - self._t0, 3),
            "measured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime()),
        }


# -- metric lifting ----------------------------------------------------------

# step-result keys hoisted to campaign-level metrics when numeric; the
# ledger reads ONLY these (plus *_per_sec rates) so a step result's
# internal timings can't masquerade as headline numbers
_LIFT_KEYS = (
    "idle_fraction_k1", "idle_fraction_k2", "idle_fraction_k4",
    "warmboot_first_sign_s", "warmboot_cache_misses",
    "warmboot_cache_hits",
)
_LIFT_CONTEXT = (
    "gg18_ot_checks_s", "gg18_ot_checks_on_s", "gg18_ot_checks_off_s",
    "gg18_ot_mta_device_s", "gg18_ot_mta_host_s", "device_idle_fraction",
)


def lift_metrics(steps_doc: Dict[str, dict]) -> Dict[str, float]:
    """Hoist each step's headline numbers into the campaign record so
    the claims engine evaluates ONE artifact per round."""
    out: Dict[str, float] = {}
    for _sid, res in sorted(steps_doc.items()):
        if not isinstance(res, dict) or res.get("dnf"):
            continue
        for k, v in res.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if k.endswith(("_per_sec", "_per_s")) or k in _LIFT_KEYS:
                out[k] = float(v)
        sweep = res.get("b_sweep")
        if isinstance(sweep, dict):
            for bsz, entry in sweep.items():
                if isinstance(entry, (int, float)) \
                        and not isinstance(entry, bool):
                    out[f"b_sweep_{bsz}_sigs_per_sec"] = float(entry)
    return out


def lift_context(steps_doc: Dict[str, dict]) -> Dict[str, object]:
    """Context numbers (timings, phase tables) the claims engine reads
    via ``ctx:``/derived metrics — kept separate from rate metrics."""
    out: Dict[str, object] = {}
    for _sid, res in sorted(steps_doc.items()):
        if not isinstance(res, dict) or res.get("dnf"):
            continue
        for k in _LIFT_CONTEXT:
            v = res.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = float(v)
        for k in ("phase_s", "gg18_ot_mta_phase_s"):
            if isinstance(res.get(k), dict) and res[k] \
                    and "no_spans" not in res[k]:
                out[k] = res[k]
        comp = res.get("compile")
        if isinstance(comp, dict):
            if isinstance(comp.get("unpredicted"), (int, float)):
                out["compile_unpredicted"] = float(comp["unpredicted"])
            if isinstance(comp.get("compiles"), (int, float)):
                out["compile_count"] = float(comp["compiles"])
    return out
