"""The bench trajectory ledger: committed perf artifacts, normalized.

Five BENCH rounds, a soak, and five multichip dryruns sit in the repo
as disconnected JSON files with four different shapes (driver-wrapped
``{"n", "rc", "parsed"}`` rounds, raw on-chip records, soak reports,
dryrun stubs). This module ingests every committed ``BENCH_*`` /
``SOAK_*`` / ``MULTICHIP_*`` artifact into one normalized record
stream — ``PERF_history.jsonl`` — keyed by an env-fingerprint group so
CPU-degraded runs are structurally segregated from chip trends (the
r05 stale-fallback confusion can no longer average into a trend line).

Normalization is DETERMINISTIC from the artifact bytes: no wall clock,
no host lookups — the committed history file is a pure function of the
committed artifacts, so drift is a gate (`tests/test_perfcheck_gate`)
exactly like HOST_TRANSFER_BUDGET.json.
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional

from .envfp import fingerprint_key

HISTORY_FILE = "PERF_history.jsonl"
ARTIFACT_GLOBS = (
    "BENCH_r*.json", "BENCH_TPU_*.json", "SOAK_*.json", "MULTICHIP_r*.json",
)
# scratch outputs that may sit untracked in a working tree
_EXCLUDE = {"SOAK_local.json"}

_ROUND_RE = re.compile(r"_r(\d+)\.json$")

# bench-record numeric fields that are metrics (rates) vs context
_RATE_SUFFIXES = ("_per_sec", "_per_s")
_CONTEXT_KEYS = (
    "batch", "runs", "setup_s", "compile_s", "profiled_run_s",
    "ed25519_batch", "dkg_batch", "reshare_batch", "gg18_ot_mta_batch",
    "gg18_ot_mta_host_s", "gg18_ot_mta_device_s",
    "gg18_ot_mta_overlap_ratio", "gg18_ot_mta_chunks",
    # bench_ot_host.py --device: host-vs-device hash-suite crossover
    "m_ots", "threads", "cores",
    "ot_host_stage_s", "ot_device_stage_s", "ot_device_stage_speedup",
    "ot_host_prg_s", "ot_device_prg_s",
    "ot_host_transpose_s", "ot_device_transpose_s",
    "ot_host_pads_s", "ot_device_pads_s",
)


def discover_artifacts(root: str) -> List[str]:
    out = []
    for pat in ARTIFACT_GLOBS:
        for p in glob.glob(os.path.join(root, pat)):
            if os.path.basename(p) not in _EXCLUDE:
                out.append(p)
    return sorted(set(out))


def _round_of(name: str) -> Optional[int]:
    m = _ROUND_RE.search(name)
    return int(m.group(1)) if m else None


def _base_record(source: str, kind: str) -> dict:
    return {
        "source": source,
        "kind": kind,
        "round": _round_of(source),
        "platform": "unknown",
        "degraded": True,
        "fingerprint": None,
        "metrics": {},
        "context": {},
        "measured_at": None,
        "notes": [],
    }


def _normalize_bench_parsed(rec: dict, parsed: dict) -> None:
    platform = str(parsed.get("platform") or "unknown")
    rec["platform"] = platform
    rec["measured_at"] = parsed.get("measured_at")
    value = parsed.get("value")
    if parsed.get("watchdog_timeout"):
        rec["notes"].append("watchdog fallback record — not a measurement")
    metric = parsed.get("metric")
    if metric is not None and isinstance(value, (int, float)):
        rec["metrics"][metric] = float(value)
    for k, v in parsed.items():
        if k == "value" or not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if k.endswith(_RATE_SUFFIXES):
            rec["metrics"][k] = float(v)
        elif k in _CONTEXT_KEYS:
            rec["context"][k] = v
    if isinstance(parsed.get("mta"), str):
        rec["context"]["mta"] = parsed["mta"]
    sweep = parsed.get("b_sweep")
    if isinstance(sweep, dict):
        ctx_sweep = {}
        for bsz, entry in sorted(sweep.items()):
            if isinstance(entry, (int, float)) and not isinstance(entry, bool):
                ctx_sweep[bsz] = float(entry)
                rec["metrics"][f"b_sweep_{bsz}_sigs_per_sec"] = float(entry)
            elif isinstance(entry, dict) and entry.get("dnf"):
                # the structured DNF shape bench.py records:
                # {"dnf": true, "reason": "..."} — degraded context, never
                # a metric
                ctx_sweep[bsz] = {"dnf": True}
                rec["notes"].append(
                    f"b_sweep B={bsz} DNF: "
                    f"{entry.get('reason') or 'no reason recorded'}"
                )
            else:
                # anything else (legacy bare strings) is flagged verbatim
                # rather than sniffed for substrings
                ctx_sweep[bsz] = {"dnf": True}
                rec["notes"].append(
                    f"b_sweep B={bsz} unstructured entry "
                    f"(pre-structured-DNF artifact): {entry!r}"
                )
        rec["context"]["b_sweep"] = ctx_sweep
    if isinstance(parsed.get("phase_s"), dict) and parsed["phase_s"]:
        if "no_spans" in parsed["phase_s"]:
            rec["notes"].append("no spans recorded (watchdog/DNF run)")
        else:
            rec["context"]["phase_s"] = parsed["phase_s"]
    env = parsed.get("env") if isinstance(parsed.get("env"), dict) else None
    if env:
        rec["env"] = env
    rec["fingerprint"] = fingerprint_key(env, platform_hint=platform)
    # degraded = anything that must never blend into a chip trend:
    # off-chip platforms, watchdog zero-records, stale-fallback carriers
    rec["degraded"] = (
        platform != "tpu"
        or not isinstance(value, (int, float))
        or float(value or 0.0) <= 0.0
        or bool(parsed.get("watchdog_timeout"))
    )
    if "last_tpu_measurement" in parsed:
        rec["notes"].append(
            "carries cached last_tpu_measurement (degraded-run rider; the "
            "on-chip record is ingested from its own artifact)"
        )


def _normalize_bench(source: str, doc: dict) -> dict:
    rec = _base_record(source, "bench")
    if "parsed" in doc or "rc" in doc:  # driver-wrapped round artifact
        rec["round"] = doc.get("n", rec["round"])
        rec["context"]["rc"] = doc.get("rc")
        parsed = doc.get("parsed")
        if parsed is None:
            rec["notes"].append(
                f"DNF: rc={doc.get('rc')} with no parseable metric line"
            )
            rec["fingerprint"] = fingerprint_key(None)
            return rec
        _normalize_bench_parsed(rec, parsed)
        return rec
    _normalize_bench_parsed(rec, doc)  # raw on-chip record
    return rec


def _normalize_soak(source: str, doc: dict) -> dict:
    rec = _base_record(source, "soak")
    thr = doc.get("throughput") or {}
    for k in ("sigs_per_s", "sigs_per_s_under_slo", "slo_hit_rate"):
        if isinstance(thr.get(k), (int, float)):
            rec["metrics"][k] = float(thr[k])
    if isinstance(thr.get("duration_s"), (int, float)):
        rec["context"]["duration_s"] = float(thr["duration_s"])
    out = doc.get("outcomes") or {}
    for k in ("submitted", "succeeded", "shed", "failed", "retries"):
        if isinstance(out.get(k), (int, float)):
            rec["context"][k] = out[k]
    lat = doc.get("latency_ms") or {}
    for lane, summ in sorted(lat.items()):
        if isinstance(summ, dict):
            for q in ("p50", "p99"):
                if isinstance(summ.get(q), (int, float)):
                    rec["metrics"][f"latency_{lane}_{q}_ms"] = float(summ[q])
    rec["context"]["accounting_ok"] = bool(doc.get("accounting_ok"))
    env = doc.get("env") if isinstance(doc.get("env"), dict) else None
    if env:
        rec["env"] = env
        rec["platform"] = str(env.get("platform") or "unknown")
    rec["fingerprint"] = fingerprint_key(env, platform_hint=rec["platform"])
    rec["degraded"] = rec["platform"] != "tpu"
    if rec["degraded"]:
        rec["notes"].append(
            "host-platform soak (compile-dominated latencies) — not a chip "
            "serving number"
        )
    return rec


def _normalize_multichip(source: str, doc: dict) -> dict:
    rec = _base_record(source, "multichip")
    ok = bool(doc.get("ok"))
    rec["metrics"]["dryrun_ok"] = 1.0 if ok else 0.0
    rec["context"]["n_devices"] = doc.get("n_devices")
    rec["context"]["rc"] = doc.get("rc")
    rec["context"]["skipped"] = bool(doc.get("skipped"))
    rec["platform"] = "tpu" if ok else "unknown"
    rec["degraded"] = not ok
    if not ok:
        rec["notes"].append("dryrun failed or had no devices")
    rec["fingerprint"] = fingerprint_key(None, platform_hint=rec["platform"])
    return rec


def normalize(path: str) -> dict:
    """One committed artifact → one normalized history record. Raises
    on unreadable JSON — an artifact the ledger cannot parse is a gate
    failure, not a silent skip."""
    name = os.path.basename(path)
    with open(path) as f:
        doc = json.load(f)
    if name.startswith("SOAK_"):
        return _normalize_soak(name, doc)
    if name.startswith("MULTICHIP_"):
        return _normalize_multichip(name, doc)
    return _normalize_bench(name, doc)


def build_history(root: str) -> List[dict]:
    """Every committed artifact, normalized and deterministically
    ordered (kind, round, source)."""
    records = [normalize(p) for p in discover_artifacts(root)]
    records.sort(key=lambda r: (r["kind"], r["round"] or 0, r["source"]))
    return records


def write_history(records: List[dict], path: str) -> None:
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")


def load_history(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def group_by_fingerprint(records: List[dict]) -> Dict[str, List[dict]]:
    groups: Dict[str, List[dict]] = {}
    for rec in records:
        groups.setdefault(rec["fingerprint"] or "unknown/unstamped",
                          []).append(rec)
    return groups
