"""The bench trajectory ledger: committed perf artifacts, normalized.

Five BENCH rounds, a soak, and five multichip dryruns sit in the repo
as disconnected JSON files with four different shapes (driver-wrapped
``{"n", "rc", "parsed"}`` rounds, raw on-chip records, soak reports,
dryrun stubs). This module ingests every committed ``BENCH_*`` /
``SOAK_*`` / ``MULTICHIP_*`` artifact into one normalized record
stream — ``PERF_history.jsonl`` — keyed by an env-fingerprint group so
CPU-degraded runs are structurally segregated from chip trends (the
r05 stale-fallback confusion can no longer average into a trend line).

Normalization is DETERMINISTIC from the artifact bytes: no wall clock,
no host lookups — the committed history file is a pure function of the
committed artifacts, so drift is a gate (`tests/test_perfcheck_gate`)
exactly like HOST_TRANSFER_BUDGET.json.
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional

from .envfp import fingerprint_key

HISTORY_FILE = "PERF_history.jsonl"
ARTIFACT_GLOBS = (
    "BENCH_r*.json", "BENCH_TPU_*.json", "SOAK_*.json", "MULTICHIP_r*.json",
    "BENCH_pipeline_*.json", "CAMPAIGN_*.json",
)
# scratch outputs that may sit untracked in a working tree; the campaign
# STATE checkpoint is runner bookkeeping, never a measurement artifact
_EXCLUDE = {"SOAK_local.json", "CAMPAIGN_state.json"}

_ROUND_RE = re.compile(r"_r(\d+)\.json$")

# bench-record numeric fields that are metrics (rates) vs context
_RATE_SUFFIXES = ("_per_sec", "_per_s")
_CONTEXT_KEYS = (
    "batch", "runs", "setup_s", "compile_s", "profiled_run_s",
    "ed25519_batch", "dkg_batch", "reshare_batch", "gg18_ot_mta_batch",
    "gg18_ot_mta_host_s", "gg18_ot_mta_device_s",
    "gg18_ot_mta_overlap_ratio", "gg18_ot_mta_chunks",
    # checks-on/off A/B (active-security overhead contract, PR 16) and
    # the span-derived idle meter — claim inputs, never rate metrics
    "gg18_ot_checks_on_s", "gg18_ot_checks_off_s", "gg18_ot_checks_s",
    "device_idle_fraction", "gg18_ot_mta_device_idle_fraction",
    "elapsed_s", "stale_s",
    # bench_ot_host.py --device: host-vs-device hash-suite crossover
    "m_ots", "threads", "cores",
    "ot_host_stage_s", "ot_device_stage_s", "ot_device_stage_speedup",
    "ot_host_prg_s", "ot_device_prg_s",
    "ot_host_transpose_s", "ot_device_transpose_s",
    "ot_host_pads_s", "ot_device_pads_s",
)


def discover_artifacts(root: str) -> List[str]:
    out = []
    for pat in ARTIFACT_GLOBS:
        for p in glob.glob(os.path.join(root, pat)):
            if os.path.basename(p) not in _EXCLUDE:
                out.append(p)
    return sorted(set(out))


def _round_of(name: str) -> Optional[int]:
    m = _ROUND_RE.search(name)
    return int(m.group(1)) if m else None


def _base_record(source: str, kind: str) -> dict:
    return {
        "source": source,
        "kind": kind,
        "round": _round_of(source),
        "platform": "unknown",
        "degraded": True,
        "fingerprint": None,
        "metrics": {},
        "context": {},
        "measured_at": None,
        "notes": [],
    }


def _normalize_bench_parsed(rec: dict, parsed: dict) -> None:
    platform = str(parsed.get("platform") or "unknown")
    rec["platform"] = platform
    rec["measured_at"] = parsed.get("measured_at")
    value = parsed.get("value")
    if parsed.get("watchdog_timeout"):
        note = "watchdog fallback record — not a measurement"
        if isinstance(parsed.get("elapsed_s"), (int, float)):
            note += f" (fired after {parsed['elapsed_s']:.1f}s)"
        rec["notes"].append(note)
    metric = parsed.get("metric")
    if metric is not None and isinstance(value, (int, float)):
        rec["metrics"][metric] = float(value)
    for k, v in parsed.items():
        if k == "value" or not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if k.endswith(_RATE_SUFFIXES):
            rec["metrics"][k] = float(v)
        elif k in _CONTEXT_KEYS:
            rec["context"][k] = v
    if isinstance(parsed.get("mta"), str):
        rec["context"]["mta"] = parsed["mta"]
    sweep = parsed.get("b_sweep")
    if isinstance(sweep, dict):
        ctx_sweep = {}
        for bsz, entry in sorted(sweep.items()):
            if isinstance(entry, (int, float)) and not isinstance(entry, bool):
                ctx_sweep[bsz] = float(entry)
                rec["metrics"][f"b_sweep_{bsz}_sigs_per_sec"] = float(entry)
            elif isinstance(entry, dict) and entry.get("dnf"):
                # the structured DNF shape bench.py records:
                # {"dnf": true, "reason": "..."} — degraded context, never
                # a metric. Newer entries also stamp elapsed_s + env, so
                # the note attributes the DNF to a host and a timing
                ctx_sweep[bsz] = {"dnf": True}
                note = (
                    f"b_sweep B={bsz} DNF: "
                    f"{entry.get('reason') or 'no reason recorded'}"
                )
                if isinstance(entry.get("elapsed_s"), (int, float)):
                    note += f" after {entry['elapsed_s']:.1f}s"
                dnf_env = entry.get("env")
                if isinstance(dnf_env, dict):
                    note += (
                        f" on {fingerprint_key(dnf_env)}"
                    )
                rec["notes"].append(note)
            else:
                # anything else (legacy bare strings) is flagged verbatim
                # rather than sniffed for substrings
                ctx_sweep[bsz] = {"dnf": True}
                rec["notes"].append(
                    f"b_sweep B={bsz} unstructured entry "
                    f"(pre-structured-DNF artifact): {entry!r}"
                )
        rec["context"]["b_sweep"] = ctx_sweep
    if isinstance(parsed.get("phase_s"), dict) and parsed["phase_s"]:
        if "no_spans" in parsed["phase_s"]:
            rec["notes"].append("no spans recorded (watchdog/DNF run)")
        else:
            rec["context"]["phase_s"] = parsed["phase_s"]
    # the OT-variant pass records its own phase table; the claims
    # engine's r2_mta_ot share derives from this one when present
    if isinstance(parsed.get("gg18_ot_mta_phase_s"), dict) \
            and parsed["gg18_ot_mta_phase_s"] \
            and "no_spans" not in parsed["gg18_ot_mta_phase_s"]:
        rec["context"]["gg18_ot_mta_phase_s"] = parsed["gg18_ot_mta_phase_s"]
    comp = parsed.get("compile")
    if isinstance(comp, dict):
        if isinstance(comp.get("unpredicted"), (int, float)):
            rec["context"]["compile_unpredicted"] = float(comp["unpredicted"])
        if isinstance(comp.get("compiles"), (int, float)):
            rec["context"]["compile_count"] = float(comp["compiles"])
    env = parsed.get("env") if isinstance(parsed.get("env"), dict) else None
    if env:
        rec["env"] = env
    rec["fingerprint"] = fingerprint_key(env, platform_hint=platform)
    # degraded = anything that must never blend into a chip trend:
    # off-chip platforms, watchdog zero-records, stale-fallback carriers
    rec["degraded"] = (
        platform != "tpu"
        or not isinstance(value, (int, float))
        or float(value or 0.0) <= 0.0
        or bool(parsed.get("watchdog_timeout"))
    )
    if "last_tpu_measurement" in parsed:
        rec["notes"].append(
            "carries cached last_tpu_measurement (degraded-run rider; the "
            "on-chip record is ingested from its own artifact)"
        )
        rider = parsed["last_tpu_measurement"]
        if isinstance(rider, dict):
            # surfaced for the claims engine: a claim satisfied ONLY by
            # this rider's numbers reads `stale`, never `claimed`
            rider_metrics = {}
            rm = rider.get("metric")
            if rm is not None and isinstance(
                    rider.get("value"), (int, float)):
                rider_metrics[rm] = float(rider["value"])
            for k, v in rider.items():
                if k.endswith(_RATE_SUFFIXES) and isinstance(
                        v, (int, float)) and not isinstance(v, bool):
                    rider_metrics[k] = float(v)
            stale_s = rider.get("stale_s")
            if stale_s is None and isinstance(
                    rider.get("age_hours"), (int, float)):
                stale_s = round(float(rider["age_hours"]) * 3600.0, 1)
            rec["context"]["embedded_tpu_rider"] = {
                "stale_s": stale_s,
                "metrics": rider_metrics,
            }


def _normalize_bench(source: str, doc: dict) -> dict:
    rec = _base_record(source, "bench")
    if "parsed" in doc or "rc" in doc:  # driver-wrapped round artifact
        rec["round"] = doc.get("n", rec["round"])
        rec["context"]["rc"] = doc.get("rc")
        parsed = doc.get("parsed")
        if parsed is None:
            rec["notes"].append(
                f"DNF: rc={doc.get('rc')} with no parseable metric line"
            )
            rec["fingerprint"] = fingerprint_key(None)
            return rec
        _normalize_bench_parsed(rec, parsed)
        return rec
    _normalize_bench_parsed(rec, doc)  # raw on-chip record
    return rec


def _normalize_soak(source: str, doc: dict) -> dict:
    rec = _base_record(source, "soak")
    thr = doc.get("throughput") or {}
    for k in ("sigs_per_s", "sigs_per_s_under_slo", "slo_hit_rate"):
        if isinstance(thr.get(k), (int, float)):
            rec["metrics"][k] = float(thr[k])
    if isinstance(thr.get("duration_s"), (int, float)):
        rec["context"]["duration_s"] = float(thr["duration_s"])
    out = doc.get("outcomes") or {}
    for k in ("submitted", "succeeded", "shed", "failed", "retries"):
        if isinstance(out.get(k), (int, float)):
            rec["context"][k] = out[k]
    lat = doc.get("latency_ms") or {}
    for lane, summ in sorted(lat.items()):
        if isinstance(summ, dict):
            for q in ("p50", "p99"):
                if isinstance(summ.get(q), (int, float)):
                    rec["metrics"][f"latency_{lane}_{q}_ms"] = float(summ[q])
    rec["context"]["accounting_ok"] = bool(doc.get("accounting_ok"))
    env = doc.get("env") if isinstance(doc.get("env"), dict) else None
    if env:
        rec["env"] = env
        rec["platform"] = str(env.get("platform") or "unknown")
    rec["fingerprint"] = fingerprint_key(env, platform_hint=rec["platform"])
    rec["degraded"] = rec["platform"] != "tpu"
    if rec["degraded"]:
        rec["notes"].append(
            "host-platform soak (compile-dominated latencies) — not a chip "
            "serving number"
        )
    return rec


def _normalize_multichip(source: str, doc: dict) -> dict:
    rec = _base_record(source, "multichip")
    ok = bool(doc.get("ok"))
    rec["metrics"]["dryrun_ok"] = 1.0 if ok else 0.0
    rec["context"]["n_devices"] = doc.get("n_devices")
    rec["context"]["rc"] = doc.get("rc")
    rec["context"]["skipped"] = bool(doc.get("skipped"))
    rec["platform"] = "tpu" if ok else "unknown"
    rec["degraded"] = not ok
    if not ok:
        rec["notes"].append("dryrun failed or had no devices")
    rec["fingerprint"] = fingerprint_key(None, platform_hint=rec["platform"])
    return rec


def _normalize_pipeline(source: str, doc: dict) -> dict:
    """scripts/bench_pipeline_cpu.py A/B artifact: K-sweep idle
    fractions are the metrics; bit-identity and the collapse ratio are
    context."""
    rec = _base_record(source, "pipeline")
    for k, v in doc.items():
        if k.startswith("idle_fraction_k") and isinstance(v, (int, float)) \
                and not isinstance(v, bool):
            rec["metrics"][k] = float(v)
    for k in ("batch", "idle_collapse_ratio"):
        if isinstance(doc.get(k), (int, float)) \
                and not isinstance(doc.get(k), bool):
            rec["context"][k] = doc[k]
    rec["context"]["signatures_bit_identical"] = bool(
        doc.get("signatures_bit_identical"))
    rec["measured_at"] = doc.get("measured_at")
    env = doc.get("env") if isinstance(doc.get("env"), dict) else None
    if env:
        rec["env"] = env
        rec["platform"] = str(env.get("platform") or "unknown")
    rec["fingerprint"] = fingerprint_key(env, platform_hint=rec["platform"])
    rec["degraded"] = (
        rec["platform"] != "tpu"
        or not doc.get("signatures_bit_identical")
    )
    if rec["platform"] != "tpu":
        rec["notes"].append(
            "host-platform pipeline A/B (scheduling proof only) — the "
            "chip idle collapse is a claims-ledger item"
        )
    return rec


def _normalize_campaign(source: str, doc: dict) -> dict:
    """perf/campaign.py report: metrics/context were already lifted by
    the runner; DNF steps become notes so the history shows exactly
    which part of a round died."""
    rec = _base_record(source, "campaign")
    for k, v in (doc.get("metrics") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            rec["metrics"][k] = float(v)
    ctx = doc.get("context")
    if isinstance(ctx, dict):
        rec["context"].update(ctx)
    rec["context"]["rehearse"] = bool(doc.get("rehearse"))
    rec["measured_at"] = doc.get("measured_at")
    for sid, res in sorted((doc.get("steps") or {}).items()):
        if isinstance(res, dict) and res.get("dnf"):
            note = f"step {sid} DNF: {res.get('reason') or 'no reason'}"
            if isinstance(res.get("elapsed_s"), (int, float)):
                note += f" after {res['elapsed_s']:.1f}s"
            rec["notes"].append(note)
    env = doc.get("env") if isinstance(doc.get("env"), dict) else None
    if env:
        rec["env"] = env
        rec["platform"] = str(env.get("platform") or "unknown")
    rec["fingerprint"] = fingerprint_key(env, platform_hint=rec["platform"])
    # a rehearsal is degraded BY DESIGN (it proves the harness, not the
    # numbers); a live campaign is degraded off-chip or when incomplete
    rec["degraded"] = (
        rec["platform"] != "tpu"
        or bool(doc.get("rehearse"))
        or not doc.get("complete")
    )
    if doc.get("rehearse"):
        rec["notes"].append(
            "CPU rehearsal campaign — harness proof, numbers are not "
            "chip evidence"
        )
    return rec


def normalize(path: str) -> dict:
    """One committed artifact → one normalized history record. Raises
    on unreadable JSON — an artifact the ledger cannot parse is a gate
    failure, not a silent skip."""
    name = os.path.basename(path)
    with open(path) as f:
        doc = json.load(f)
    if name.startswith("SOAK_"):
        return _normalize_soak(name, doc)
    if name.startswith("MULTICHIP_"):
        return _normalize_multichip(name, doc)
    if name.startswith("CAMPAIGN_"):
        return _normalize_campaign(name, doc)
    if name.startswith("BENCH_pipeline_"):
        return _normalize_pipeline(name, doc)
    return _normalize_bench(name, doc)


def build_history(root: str) -> List[dict]:
    """Every committed artifact, normalized and deterministically
    ordered (kind, round, source)."""
    records = [normalize(p) for p in discover_artifacts(root)]
    records.sort(key=lambda r: (r["kind"], r["round"] or 0, r["source"]))
    return records


def write_history(records: List[dict], path: str) -> None:
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")


def load_history(path: str) -> List[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def group_by_fingerprint(records: List[dict]) -> Dict[str, List[dict]]:
    groups: Dict[str, List[dict]] = {}
    for rec in records:
        groups.setdefault(rec["fingerprint"] or "unknown/unstamped",
                          []).append(rec)
    return groups
