"""mpcclaims: the claims ledger — every owed headline number as code.

ROADMAP item 1 owes one consolidated on-chip proof round, and every
later item's claim rests on that round existing. Until this module, the
owed numbers lived as prose ("expect r2_mta_ot well under 45%", "target
>= 10k sigs/s") scattered across ROADMAP/PERFORMANCE paragraphs — a
shape nothing can gate on, which is exactly how BENCH_r05 ended with a
CPU-degraded record in the round's official slot.

Here every owed number is a structured **claim**::

    {"id", "title", "metric", "predicate", "artifact_kind",
     "envfp_class", "roadmap"}   # static registry (this file)
    + {"status": "owed"|"claimed"|"stale", "evidence"}  # verdict engine

and the verdict engine evaluates the registry against the normalized
artifact corpus (``perf/ledger.build_history``). Two structural rules
make the r05 failure mode impossible:

- ``envfp_class: "chip"`` claims are only satisfiable by records that
  are non-degraded AND ``platform == "tpu"`` — a CPU fallback record,
  a watchdog zero-record, or a DNF can never flip a chip claim to
  ``claimed`` no matter what value it carries.
- a claim whose predicate holds ONLY on an embedded
  ``last_tpu_measurement`` rider (the stale cached record a degraded
  run carries along, stamped ``stale_s`` by bench.py) lands as
  ``stale``, never ``claimed`` — the evidence names the rider and its
  age so the reader knows the number predates the code under test.

``CLAIMS.json`` (the evaluated registry) and ``CLAIMS.md`` (the
human-readable verdict table) are committed and drift-gated: both are
pure functions of (this registry, the committed artifacts), regenerated
by ``scripts/claimscheck.py --regen`` and byte-checked by
``scripts/check_all.py`` / ``make claimscheck``.

Deliberately stdlib-only and jax-free: the gate runs everywhere the
static-analysis gates run, and the daemon health surface polls
``gauge_summary()`` at human cadence.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

CLAIMS_JSON = "CLAIMS.json"
CLAIMS_MD = "CLAIMS.md"

# -- metric addressing -------------------------------------------------------
#
# A claim's "metric" is one of:
#   <name>          -> record["metrics"][name]          (a rate/number)
#   ctx:<key>       -> record["context"][key]           (numeric context)
#   derived:<name>  -> computed from the record by _DERIVED[name]
#
# The vocabulary below is the drift gate's "0 unknown metrics" check:
# a claim referencing a metric outside it (and outside the corpus) is a
# typo that would sit "owed" forever without anyone noticing.

_PRIMARY_PHASES = (
    "r1_commit_encrypt_rangeproof",
    "r2_mta_ot",
    "r2_mta_respond",
    "r3_verify_decrypt",
    "r4_R_reconstruct_pok",
    "r5_phase5_combine_verify",
)


def _derived_r2_mta_ot_phase_share(record: dict) -> Optional[float]:
    """r2_mta_ot's share of the five primary GG18 round phases, from
    the OT-variant phase table when present (a paillier-flagship run
    records the OT pass under gg18_ot_mta_phase_s), else phase_s."""
    ctx = record.get("context") or {}
    table = ctx.get("gg18_ot_mta_phase_s") or ctx.get("phase_s") or {}
    if not isinstance(table, dict) or "r2_mta_ot" not in table:
        return None
    total = sum(
        float(table[k]) for k in _PRIMARY_PHASES
        if isinstance(table.get(k), (int, float))
    )
    if total <= 0:
        return None
    return float(table["r2_mta_ot"]) / total


_DERIVED = {
    "r2_mta_ot_phase_share": _derived_r2_mta_ot_phase_share,
}

KNOWN_METRICS = frozenset({
    # bench.py flagship + secondary emission
    "secp256k1_2of3_gg18_sigs_per_sec",
    "gg18_ot_mta_sigs_per_sec",
    "ed25519_2of3_sigs_per_sec",
    "ed25519_2of3_threshold_sigs_per_sec",
    "secp256k1_dkg_wallets_per_sec",
    "reshare_2of3_to_3of5_wallets_per_sec",
    "b_sweep_1024_sigs_per_sec",
    "b_sweep_4096_sigs_per_sec",
    "b_sweep_8192_sigs_per_sec",
    "b_sweep_16384_sigs_per_sec",
    # pipeline A/B artifacts (scripts/bench_pipeline_cpu.py)
    "idle_fraction_k1",
    "idle_fraction_k2",
    "idle_fraction_k4",
    # campaign reports (perf/campaign.py)
    "campaign_complete",
    "campaign_steps_done",
    "campaign_steps_total",
    "campaign_steps_dnf",
    "warmboot_first_sign_s",
    "warmboot_cache_misses",
    "warmboot_cache_hits",
    "ot_host_extension_stage_speedup",
    "ot_device_stage_speedup",
})

KNOWN_CONTEXT = frozenset({
    "gg18_ot_checks_s",
    "gg18_ot_checks_on_s",
    "gg18_ot_checks_off_s",
    "gg18_ot_mta_device_s",
    "device_idle_fraction",
    "compile_unpredicted",
    "compile_count",
})


def record_value(record: dict, metric: str) -> Optional[float]:
    """Resolve a claim metric against one normalized history record;
    None when the record does not carry it."""
    if metric.startswith("derived:"):
        fn = _DERIVED.get(metric[len("derived:"):])
        return fn(record) if fn else None
    if metric.startswith("ctx:"):
        v = (record.get("context") or {}).get(metric[len("ctx:"):])
    else:
        v = (record.get("metrics") or {}).get(metric)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


# -- predicate DSL -----------------------------------------------------------

_OPS = {
    "gt": lambda v, t: v > t,
    "ge": lambda v, t: v >= t,
    "lt": lambda v, t: v < t,
    "le": lambda v, t: v <= t,
    "eq": lambda v, t: v == t,
}


def eval_predicate(predicate: dict, record: dict,
                   value: Optional[float]) -> bool:
    """Machine-evaluate one predicate against a resolved metric value.
    ``exists`` passes on any resolved value; ``lt_metric``/``gt_metric``
    compare against a second metric of the SAME record (the K=2-beats-
    K=1 shape). Unresolvable values never satisfy anything."""
    if value is None:
        return False
    op = predicate.get("op")
    if op == "exists":
        return True
    if op in ("lt_metric", "gt_metric"):
        other = record_value(record, str(predicate.get("metric")))
        if other is None:
            return False
        return value < other if op == "lt_metric" else value > other
    fn = _OPS.get(op)
    if fn is None:
        raise ValueError(f"unknown predicate op {op!r}")
    return fn(value, float(predicate["value"]))


def render_predicate(predicate: dict) -> str:
    op = predicate.get("op")
    if op == "exists":
        return "recorded"
    if op in ("lt_metric", "gt_metric"):
        sym = "<" if op == "lt_metric" else ">"
        return f"{sym} {predicate.get('metric')}"
    sym = {"gt": ">", "ge": ">=", "lt": "<", "le": "<=", "eq": "="}[op]
    return f"{sym} {predicate['value']}"


# -- the registry ------------------------------------------------------------
#
# One entry per headline number the ROADMAP owes. "requires" are extra
# per-record numeric gates (same metric grammar) that qualify WHICH
# records may testify — e.g. the phase-share claim only counts runs
# whose trace actually carried device=True OT spans.

REGISTRY: List[dict] = [
    {
        "id": "flagship-ot-checks-on",
        "title": "OT-MtA flagship, active checks ON, beats the 72.1 headline",
        "metric": "gg18_ot_mta_sigs_per_sec",
        "predicate": {"op": "gt", "value": 72.1},
        "requires": [{"metric": "ctx:gg18_ot_checks_on_s",
                      "op": "gt", "value": 0.0}],
        "artifact_kind": ["bench", "campaign"],
        "envfp_class": "chip",
        "roadmap": "item 1+2 — the new headline; checks on by default "
                   "since PR 16, never yet run on a chip",
    },
    {
        "id": "r2-mta-ot-phase-share",
        "title": "r2_mta_ot phase share < 45% with device OT spans",
        "metric": "derived:r2_mta_ot_phase_share",
        "predicate": {"op": "lt", "value": 0.45},
        "requires": [{"metric": "ctx:gg18_ot_mta_device_s",
                      "op": "gt", "value": 0.0}],
        "artifact_kind": ["bench", "campaign"],
        "envfp_class": "chip",
        "roadmap": "item 1 — device OT kernels (PR 10) shrink the host "
                   "wall; pre-device artifact sits at 45.4%",
    },
    {
        "id": "ot-checks-delta",
        "title": "checks-on/off delta (gg18_ot_checks_s) measured on chip",
        "metric": "ctx:gg18_ot_checks_s",
        "predicate": {"op": "exists"},
        "artifact_kind": ["bench", "campaign"],
        "envfp_class": "chip",
        "roadmap": "item 2 — the overhead contract of the PR 16 active-"
                   "security checks (bench.py already records it)",
    },
    {
        "id": "ed25519-10k",
        "title": "ed25519 with device SHA-512 at >= 10k sigs/s",
        "metric": "ed25519_2of3_sigs_per_sec",
        "predicate": {"op": "ge", "value": 10000.0},
        "artifact_kind": ["bench", "campaign"],
        "envfp_class": "chip",
        "roadmap": "item 1 — north-star scheme target; last on-chip "
                   "number (3,125) predates the device hash suite",
    },
    {
        "id": "b-sweep-16384",
        "title": "b_sweep completes the 16384 bucket on chip",
        "metric": "b_sweep_16384_sigs_per_sec",
        "predicate": {"op": "gt", "value": 0.0},
        "artifact_kind": ["bench", "campaign"],
        "envfp_class": "chip",
        "roadmap": "item 1+4 — the ISSUE 17 bucket; B=8192 DNF'd "
                   "pre-device-OT",
    },
    {
        "id": "pipeline-idle-collapse",
        "title": "counter-phase pipeline: K=2 idle fraction below K=1 "
                 "at equal B, on chip",
        "metric": "idle_fraction_k2",
        "predicate": {"op": "lt_metric", "metric": "idle_fraction_k1"},
        "artifact_kind": ["pipeline", "campaign"],
        "envfp_class": "chip",
        "roadmap": "item 4 — the zero-idle meter (ISSUE 17), CPU A/B "
                   "committed, chip collapse owed",
    },
    {
        "id": "warm-cold-boot-60s",
        "title": "cold boot against a prewarmed cache: first signature "
                 "< 60 s, zero cache misses",
        "metric": "warmboot_first_sign_s",
        "predicate": {"op": "lt", "value": 60.0},
        "requires": [{"metric": "warmboot_cache_misses",
                      "op": "eq", "value": 0.0}],
        "artifact_kind": ["campaign"],
        "envfp_class": "chip",
        "roadmap": "item 1 — the mpcwarm (PR 12) proof vs the 802-1,401 s "
                   "compile wall",
    },
    {
        "id": "predicted-true-ledger",
        "title": "every compile in the round was statically predicted",
        "metric": "ctx:compile_unpredicted",
        "predicate": {"op": "eq", "value": 0.0},
        "requires": [{"metric": "ctx:compile_count",
                      "op": "gt", "value": 0.0}],
        "artifact_kind": ["bench", "campaign"],
        "envfp_class": "chip",
        "roadmap": "item 1 — `predicted: true` across the board "
                   "(mpcshape surface, PR 11)",
    },
    # -- rehearsal class: the harness itself, provable on any host ----------
    {
        "id": "campaign-rehearsal-complete",
        "title": "the full campaign step DAG runs end-to-end on CPU",
        "metric": "campaign_complete",
        "predicate": {"op": "eq", "value": 1.0},
        "artifact_kind": ["campaign"],
        "envfp_class": "rehearsal",
        "roadmap": "item 1 — scripts/tpu_round.py --rehearse: same DAG, "
                   "same state machine, same verdict path as the live "
                   "window",
    },
    {
        "id": "pipeline-idle-collapse-rehearsal",
        "title": "pipeline K=2 idle fraction below K=1 (CPU A/B proof)",
        "metric": "idle_fraction_k2",
        "predicate": {"op": "lt_metric", "metric": "idle_fraction_k1"},
        "artifact_kind": ["pipeline", "campaign"],
        "envfp_class": "rehearsal",
        "roadmap": "item 4 — BENCH_pipeline_cpu.json (ISSUE 17)",
    },
]

# the ROADMAP item-1 owed matrix: every headline metric here must be
# covered by at least one registry claim, or the drift gate fails —
# "silently untracked" is the state this file exists to abolish
ROADMAP_HEADLINES: Dict[str, str] = {
    "gg18_ot_mta_sigs_per_sec": "flagship OT sigs/s (replaces 72.1)",
    "derived:r2_mta_ot_phase_share": "r2_mta_ot share < 45%, device spans",
    "ctx:gg18_ot_checks_s": "checks-on/off delta",
    "ed25519_2of3_sigs_per_sec": "ed25519 >= 10k sigs/s",
    "b_sweep_16384_sigs_per_sec": "b_sweep through 16384",
    "idle_fraction_k2": "pipeline idle K=2 < K=1 at equal B",
    "warmboot_first_sign_s": "warm cold-boot first signature < 60 s",
    "ctx:compile_unpredicted": "`predicted: true` across the ledger",
}


# -- the verdict engine ------------------------------------------------------


def _meets_requires(claim: dict, record: dict) -> bool:
    for req in claim.get("requires", ()):  # all must hold on the record
        v = record_value(record, req["metric"])
        if v is None or not _OPS[req["op"]](v, float(req["value"])):
            return False
    return True


def _eligible(claim: dict, record: dict) -> bool:
    if record.get("kind") not in claim["artifact_kind"]:
        return False
    if claim["envfp_class"] == "chip":
        # the structural r05 fix: degraded/CPU records can testify only
        # for rehearsal claims, no matter what numbers they carry
        return (not record.get("degraded")
                and record.get("platform") == "tpu")
    return True


def _rider_of(record: dict) -> Optional[dict]:
    rider = (record.get("context") or {}).get("embedded_tpu_rider")
    return rider if isinstance(rider, dict) else None


def _evidence(record: dict, value: float) -> dict:
    return {
        "source": record.get("source"),
        "fingerprint": record.get("fingerprint"),
        "value": round(value, 6),
        "measured_at": record.get("measured_at"),
    }


def evaluate(records: Sequence[dict]) -> List[dict]:
    """Verdict pass: one evaluated claim per registry entry, in registry
    order — a pure function of (REGISTRY, records), no clock, no host
    facts, so the committed CLAIMS.json/CLAIMS.md are drift-gateable."""
    out = []
    for claim in REGISTRY:
        satisfied = None
        for rec in records:
            if not _eligible(claim, rec) or not _meets_requires(claim, rec):
                continue
            v = record_value(rec, claim["metric"])
            if eval_predicate(claim["predicate"], rec, v):
                satisfied = _evidence(rec, v)  # last (newest) wins
        status, evidence = "owed", None
        if satisfied is not None:
            status, evidence = "claimed", satisfied
        elif claim["envfp_class"] == "chip":
            # stale check: does the predicate hold only on an embedded
            # last_tpu_measurement rider some degraded run carried?
            for rec in records:
                rider = _rider_of(rec)
                if rider is None:
                    continue
                shim = {"metrics": rider.get("metrics") or {},
                        "context": {}}
                v = record_value(shim, claim["metric"])
                if not claim.get("requires") and eval_predicate(
                        claim["predicate"], shim, v):
                    status = "stale"
                    evidence = {
                        "source": rec.get("source"),
                        "fingerprint": rec.get("fingerprint"),
                        "value": round(v, 6),
                        "stale_s": rider.get("stale_s"),
                        "note": "embedded last_tpu_measurement rider — "
                                "predates the code under test",
                    }
        out.append({
            "id": claim["id"],
            "title": claim["title"],
            "metric": claim["metric"],
            "predicate": claim["predicate"],
            "artifact_kind": list(claim["artifact_kind"]),
            "envfp_class": claim["envfp_class"],
            "requires": list(claim.get("requires", [])),
            "roadmap": claim["roadmap"],
            "status": status,
            "evidence": evidence,
        })
    return out


def summary(evaluated: Sequence[dict]) -> Dict[str, int]:
    counts = {"owed": 0, "claimed": 0, "stale": 0}
    for c in evaluated:
        counts[c["status"]] = counts.get(c["status"], 0) + 1
    return counts


# -- renderers (both committed, both drift-gated) ----------------------------


def render_json(evaluated: Sequence[dict]) -> str:
    doc = {
        "_comment": (
            "Evaluated claims ledger — generated by scripts/claimscheck.py "
            "--regen from mpcium_tpu/perf/claims.REGISTRY x the committed "
            "perf artifacts. Do not edit by hand; CI byte-gates this file."
        ),
        "summary": summary(evaluated),
        "claims": list(evaluated),
    }
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"


def render_md(evaluated: Sequence[dict]) -> str:
    s = summary(evaluated)
    lines = [
        "# Claims ledger",
        "",
        "Every headline number the ROADMAP owes, as a machine-evaluated",
        "claim. Generated by `scripts/claimscheck.py --regen` from",
        "`mpcium_tpu/perf/claims.py` × the committed perf artifacts — do",
        "not edit by hand; `make claimscheck` byte-gates this file.",
        "",
        f"**{s['claimed']} claimed · {s['owed']} owed · {s['stale']} "
        f"stale.** `owed` = no eligible artifact satisfies the predicate",
        "yet (the TPU campaign — `scripts/tpu_round.py` — is the single",
        "entry point that converts these). `chip` claims accept only",
        "non-degraded on-chip records; a claim satisfied only by an",
        "embedded stale `last_tpu_measurement` rider reads `stale`,",
        "never `claimed`.",
        "",
        "| claim | class | predicate | status | evidence |",
        "|---|---|---|---|---|",
    ]
    for c in evaluated:
        pred = f"`{c['metric']}` {render_predicate(c['predicate'])}"
        for req in c["requires"]:
            pred += (f"; `{req['metric']}` "
                     f"{render_predicate({k: req[k] for k in ('op', 'value')})}")
        ev = ""
        if c["evidence"]:
            e = c["evidence"]
            ev = f"`{e['source']}` → {e['value']}"
            if e.get("stale_s") is not None:
                ev += f" (stale {e['stale_s']:.0f}s rider)"
        status = {"claimed": "**claimed**", "owed": "owed",
                  "stale": "STALE"}[c["status"]]
        lines.append(
            f"| {c['id']} — {c['title']} | {c['envfp_class']} | {pred} "
            f"| {status} | {ev} |"
        )
    lines += [
        "",
        "Provenance (ROADMAP pointers):",
        "",
    ]
    for c in evaluated:
        lines.append(f"- **{c['id']}**: {c['roadmap']}")
    lines.append("")
    return "\n".join(lines)


# -- the drift gate ----------------------------------------------------------


def registry_problems(records: Sequence[dict]) -> List[str]:
    """Registry hygiene: 0 unknown metrics (typo'd claims would sit owed
    forever) and 0 silently-untracked ROADMAP headline numbers."""
    problems = []
    corpus = set()
    for rec in records:
        corpus.update((rec.get("metrics") or {}).keys())
    seen_ids = set()
    claimed_metrics = set()
    for claim in REGISTRY:
        if claim["id"] in seen_ids:
            problems.append(f"duplicate claim id {claim['id']!r}")
        seen_ids.add(claim["id"])
        refs = [claim["metric"]]
        refs += [r["metric"] for r in claim.get("requires", ())]
        if claim["predicate"].get("op") in ("lt_metric", "gt_metric"):
            refs.append(claim["predicate"]["metric"])
        claimed_metrics.update(refs)
        for m in refs:
            if m.startswith("derived:"):
                known = m[len("derived:"):] in _DERIVED
            elif m.startswith("ctx:"):
                known = m[len("ctx:"):] in KNOWN_CONTEXT
            else:
                known = m in KNOWN_METRICS or m in corpus
            if not known:
                problems.append(
                    f"claim {claim['id']!r}: unknown metric {m!r} — not in "
                    f"the claims vocabulary nor the artifact corpus"
                )
    for metric, label in sorted(ROADMAP_HEADLINES.items()):
        if metric not in claimed_metrics:
            problems.append(
                f"ROADMAP headline {label!r} ({metric}) has no claim "
                f"tracking it — silently-untracked measurement debt"
            )
    return problems


def check_problems(root: str, records: Optional[Sequence[dict]] = None
                   ) -> List[str]:
    """The full claimscheck: registry hygiene + byte drift of the two
    committed renders. Empty list = green."""
    if records is None:
        from . import ledger

        records = ledger.build_history(root)
    problems = registry_problems(records)
    evaluated = evaluate(records)
    for basename, text in ((CLAIMS_JSON, render_json(evaluated)),
                           (CLAIMS_MD, render_md(evaluated))):
        path = os.path.join(root, basename)
        try:
            with open(path) as f:
                committed = f.read()
        except OSError:
            problems.append(
                f"{basename} missing — run scripts/claimscheck.py --regen"
            )
            continue
        if committed != text:
            problems.append(
                f"{basename} does not match the artifact corpus — "
                f"regenerate with scripts/claimscheck.py --regen and "
                f"review the diff"
            )
    return problems


# -- daemon health surface ---------------------------------------------------

_gauge_lock = threading.Lock()
_gauge_cache: dict = {"at": 0.0, "root": None, "counts": None}


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def gauge_summary(root: Optional[str] = None,
                  max_age_s: float = 60.0) -> Dict[str, int]:
    """owed/claimed/stale counts for the daemon health beat, cached at
    human cadence (the corpus is a dozen small JSON files; re-reading it
    every 10 s health tick is pointless). Never raises — an unreadable
    corpus reads as all-zero measurement debt plus an ``error`` flag."""
    root = root or _repo_root()
    now = time.monotonic()
    with _gauge_lock:
        if (_gauge_cache["counts"] is not None
                and _gauge_cache["root"] == root
                and now - _gauge_cache["at"] < max_age_s):
            return dict(_gauge_cache["counts"])
    try:
        from . import ledger

        counts = summary(evaluate(ledger.build_history(root)))
    except Exception:  # noqa: BLE001 — health must never die on claims
        counts = {"owed": 0, "claimed": 0, "stale": 0, "error": 1}
    with _gauge_lock:
        _gauge_cache.update({"at": now, "root": root, "counts": counts})
    return dict(counts)


def export_gauges(metrics, root: Optional[str] = None) -> Dict[str, int]:
    """Mirror the claim counts into a MetricsRegistry so the ``.prom``
    health sidecar shows measurement debt next to compile-watch state."""
    counts = gauge_summary(root)
    for key in ("owed", "claimed", "stale"):
        metrics.gauge(f"claims.{key}").set(float(counts.get(key, 0)))
    return counts


def reset_gauge_cache() -> None:
    """Test hook."""
    with _gauge_lock:
        _gauge_cache.update({"at": 0.0, "root": None, "counts": None})
