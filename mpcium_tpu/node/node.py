"""MPC node core: session factories + share persistence.

The reference's `mpc.Node` (pkg/mpc/node.go): holds identity/transport/
stores, generates ECDSA pre-params once at startup (node.go:69 — here
loadable from a safe-prime pool file so restarts are instant), and exposes
six factories (ECDSA/EdDSA × keygen/signing/resharing). Share persistence
uses ``ecdsa:<walletID>`` / ``eddsa:<walletID>`` store keys
(session.go:40-43); wallet metadata goes to the keyinfo store.
"""
from __future__ import annotations

import json
from typing import Callable, Optional, Sequence

from .. import wire
from ..core.paillier import PreParams, gen_preparams
from ..identity.identity import IdentityStore
from ..protocol.base import KeygenShare, ProtocolError
from ..protocol.ecdsa.keygen import ECDSAKeygenParty
from ..protocol.ecdsa.signing import ECDSASigningParty
from ..protocol.eddsa.keygen import EDDSAKeygenParty
from ..protocol.eddsa.signing import EDDSASigningParty
from ..protocol.resharing import ResharingParty
from ..registry.registry import PeerRegistry
from ..store.keyinfo import KeyInfo, KeyinfoStore
from ..store.kvstore import KVStore
from ..store.session_wal import SessionWALStore, SessionWALWriter, WALReplay
from ..transport.api import Transport
from ..utils import log
from .session import Session

ERR_NOT_ENOUGH_PARTICIPANTS = "not enough participants"


class NotEnoughParticipants(Exception):
    """Signing with a partial cluster — retryable (reference
    ErrNotEnoughParticipants, session.go:22, event_consumer.go:276-280)."""


def share_key(key_type: str, wallet_id: str) -> str:
    kt = {"secp256k1": "ecdsa", "ed25519": "eddsa"}.get(key_type, key_type)
    return f"{kt}:{wallet_id}"


class Node:
    def __init__(
        self,
        node_id: str,
        peer_ids: Sequence[str],
        transport: Transport,
        identity: IdentityStore,
        kvstore: KVStore,
        keyinfo: KeyinfoStore,
        registry: PeerRegistry,
        preparams: Optional[PreParams] = None,
        safe_prime_pool: Optional[str] = None,
        min_paillier_bits: int = 2046,
        hello_timeout_s: Optional[float] = 20.0,
        session_wal: Optional[SessionWALStore] = None,
    ):
        self.node_id = node_id
        self.peer_ids = sorted(set(peer_ids) | {node_id})
        self.transport = transport
        self.identity = identity
        self.kvstore = kvstore
        self.keyinfo = keyinfo
        self.registry = registry
        self.min_paillier_bits = min_paillier_bits
        # hello-barrier deadline for every session this node creates;
        # chaos drills shrink it so partition failures surface inside the
        # drill budget instead of the default 20 s (session.py:63)
        self.hello_timeout_s = hello_timeout_s
        # crash-recovery WAL namespace (None ⇒ feature off: sessions run
        # exactly as before, no journal files are ever created)
        self.session_wal = session_wal
        # ECDSA pre-params once at startup (reference node.go:69); the pool
        # file makes this seconds instead of minutes
        if preparams is None:
            log.info("generating ECDSA pre-params", node=node_id)
            preparams = gen_preparams(pool_path=safe_prime_pool)
            log.info("pre-params ready", node=node_id)
        self.preparams = preparams
        self.registry.watch()

    # -- persistence --------------------------------------------------------

    def save_share(self, share: KeygenShare, wallet_id: str) -> None:
        self.kvstore.put(
            share_key(share.key_type, wallet_id),
            json.dumps(share.to_json()).encode(),
        )
        self.keyinfo.save(
            share.key_type,
            wallet_id,
            KeyInfo(
                participant_peer_ids=share.participants,
                threshold=share.threshold,
                is_reshared=bool(share.aux.get("is_reshared", False)),
                public_key=share.public_key.hex(),
                vss_commitments=[c.hex() for c in share.vss_commitments],
                epoch=share.epoch,
            ),
        )

    def load_share(self, key_type: str, wallet_id: str) -> KeygenShare:
        raw = self.kvstore.get(share_key(key_type, wallet_id))
        if raw is None:
            raise ProtocolError(f"no {key_type} share for wallet {wallet_id!r}")
        return KeygenShare.from_json(json.loads(raw))

    # -- crash-recovery WAL -------------------------------------------------

    def _wal_create(self, session_id: str, meta: dict) -> Optional[SessionWALWriter]:
        """New journal for a fresh session (``meta`` holds everything
        ``resume_session`` needs to rebuild the party after a crash).
        WAL trouble never blocks live signing — it only disables recovery."""
        if self.session_wal is None:
            return None
        try:
            return self.session_wal.create(session_id, meta)
        except Exception as e:  # noqa: BLE001
            log.warn("session WAL create failed", session=session_id,
                     error=repr(e))
            return None

    # -- quorum selection ---------------------------------------------------

    def _ready_quorum(self, participants: Sequence[str], need: int) -> list:
        ready = set(self.registry.ready_peers())
        quorum = sorted(set(participants) & ready)
        if len(quorum) < need:
            raise NotEnoughParticipants(
                f"{len(quorum)}/{need} ready among {sorted(participants)}"
            )
        return quorum

    # -- keygen -------------------------------------------------------------

    def create_keygen_session(
        self,
        key_type: str,
        wallet_id: str,
        threshold: int,
        on_done: Optional[Callable] = None,
        on_error: Optional[Callable] = None,
    ) -> Session:
        # keygen requires the full configured cluster (reference node.go:95)
        if self.registry.ready_count() < len(self.peer_ids):
            raise NotEnoughParticipants(
                f"{self.registry.ready_count()}/{len(self.peer_ids)} ready"
            )
        participants = list(self.peer_ids)
        session_id = f"keygen:{wire._kt(key_type)}:{wallet_id}"
        if key_type == wire.KEY_TYPE_SECP256K1:
            party = ECDSAKeygenParty(
                session_id, self.node_id, participants, threshold,
                preparams=self.preparams,
                min_paillier_bits=self.min_paillier_bits,
            )
        else:
            party = EDDSAKeygenParty(
                session_id, self.node_id, participants, threshold
            )

        def persist_and_done(share: KeygenShare):
            self.save_share(share, wallet_id)
            if on_done:
                on_done(share)

        return Session(
            session_id=session_id,
            party=party,
            node_id=self.node_id,
            participants=participants,
            transport=self.transport,
            identity=self.identity,
            broadcast_topic=wire.keygen_broadcast_topic(key_type, wallet_id),
            direct_topic_fn=lambda n: wire.keygen_direct_topic(key_type, n, wallet_id),
            on_done=persist_and_done,
            on_error=on_error,
            hello_timeout_s=self.hello_timeout_s,
            wal=self._wal_create(session_id, {
                "kind": "keygen",
                "key_type": key_type,
                "wallet_id": wallet_id,
                "threshold": threshold,
                "participants": participants,
            }),
        )

    # -- signing ------------------------------------------------------------

    def create_signing_session(
        self,
        key_type: str,
        wallet_id: str,
        tx_id: str,
        tx: bytes,
        on_done: Optional[Callable] = None,
        on_error: Optional[Callable] = None,
        network_internal_code: str = "",
    ) -> Optional[Session]:
        """Returns None when this node is not in the selected quorum."""
        info = self.keyinfo.get(key_type, wallet_id)
        if info is None:
            # unknown OR keygen still persisting on this node — retryable;
            # truly unknown wallets exhaust redelivery and surface as a
            # dead-letter timeout (reference redelivery philosophy,
            # event_consumer.go:276-280)
            raise NotEnoughParticipants(
                f"no {key_type} metadata for wallet {wallet_id!r} (yet)"
            )
        quorum = self._ready_quorum(info.participant_peer_ids, info.threshold + 1)
        if self.node_id not in quorum:
            return None
        try:
            share = self.load_share(key_type, wallet_id)
        except ProtocolError:
            raise NotEnoughParticipants(
                f"no {key_type} share for wallet {wallet_id!r} (yet)"
            )
        # reshare-epoch fence: a signing request racing a committee rotation
        # must not build a quorum mixing old- and new-polynomial shares
        # (reference gates on IsReshared, node.go:149-159). A keyinfo/share
        # epoch mismatch means this node is mid-rotation — retryable. The
        # epoch is also baked into the session id and topics below, so nodes
        # on different epochs can never exchange rounds even transiently.
        if share.epoch != info.epoch:
            # interpolate the epoch numbers only, never the share object
            # (its repr would ride the traceback into logs) — MPL102
            epoch_have = share.epoch
            raise NotEnoughParticipants(
                f"reshare in progress for {wallet_id!r}: share epoch "
                f"{epoch_have} != keyinfo epoch {info.epoch}"
            )
        epoch_tag = f"{tx_id}~e{share.epoch}" if share.epoch else tx_id
        session_id = f"sign:{wire._kt(key_type)}:{wallet_id}:{epoch_tag}"
        if key_type == wire.KEY_TYPE_SECP256K1:
            digest = int.from_bytes(tx, "big")
            party = ECDSASigningParty(
                session_id, self.node_id, quorum, share, digest
            )
        else:
            party = EDDSASigningParty(
                session_id, self.node_id, quorum, share, tx
            )
        return Session(
            session_id=session_id,
            party=party,
            node_id=self.node_id,
            participants=quorum,
            transport=self.transport,
            identity=self.identity,
            broadcast_topic=wire.sign_broadcast_topic(
                key_type, wallet_id, epoch_tag
            ),
            direct_topic_fn=lambda n: wire.sign_direct_topic(
                key_type, n, epoch_tag
            ),
            on_done=on_done,
            on_error=on_error,
            hello_timeout_s=self.hello_timeout_s,
            wal=self._wal_create(session_id, {
                "kind": "sign",
                "key_type": key_type,
                "wallet_id": wallet_id,
                "tx_id": tx_id,
                "tx": tx.hex(),
                "epoch_tag": epoch_tag,
                "participants": quorum,
                "network_internal_code": network_internal_code,
            }),
        )

    # -- resharing ----------------------------------------------------------

    def create_resharing_session(
        self,
        key_type: str,
        wallet_id: str,
        new_threshold: int,
        on_done: Optional[Callable] = None,
        on_error: Optional[Callable] = None,
    ) -> Session:
        """Every ready node participates: old-quorum members re-deal, the
        new committee (= all ready nodes) receives. One party object plays
        both roles where they overlap (reference runs two sessions,
        §3.4 — the single dual-role party is the cleaner equivalent)."""
        info = self.keyinfo.get(key_type, wallet_id)
        if info is None:
            raise ProtocolError(f"unknown wallet {wallet_id!r} ({key_type})")
        old_quorum = self._ready_quorum(
            info.participant_peer_ids, info.threshold + 1
        )[: info.threshold + 1]
        new_committee = self.registry.ready_peers()
        if len(new_committee) < new_threshold + 1:
            raise NotEnoughParticipants(
                f"{len(new_committee)} ready < new threshold {new_threshold}+1"
            )
        is_old = self.node_id in old_quorum
        old_share = (
            self.load_share(key_type, wallet_id) if is_old else None
        )
        if old_share is not None and old_share.epoch != info.epoch:
            epoch_have = old_share.epoch
            raise NotEnoughParticipants(
                f"reshare in progress for {wallet_id!r}: share epoch "
                f"{epoch_have} != keyinfo epoch {info.epoch}"
            )
        session_id = f"resharing:{wire._kt(key_type)}:{wallet_id}:e{info.epoch}"
        party = ResharingParty(
            session_id,
            self.node_id,
            key_type,
            old_quorum,
            new_committee,
            new_threshold,
            old_share=old_share,
            old_public_key=bytes.fromhex(info.public_key) if info.public_key else None,
            old_vss_commitments=[bytes.fromhex(c) for c in info.vss_commitments]
            or None,
            preparams=self.preparams if key_type == wire.KEY_TYPE_SECP256K1 else None,
            min_paillier_bits=self.min_paillier_bits,
            old_epoch=info.epoch,
        )

        return Session(
            session_id=session_id,
            party=party,
            node_id=self.node_id,
            participants=sorted(set(old_quorum) | set(new_committee)),
            transport=self.transport,
            identity=self.identity,
            broadcast_topic=wire.resharing_broadcast_topic(key_type, wallet_id),
            direct_topic_fn=lambda n: wire.resharing_direct_topic(key_type, n, wallet_id),
            on_done=self._reshare_persist_cb(
                party, key_type, wallet_id, info, on_done
            ),
            on_error=on_error,
            hello_timeout_s=self.hello_timeout_s,
            wal=self._wal_create(session_id, {
                "kind": "reshare",
                "key_type": key_type,
                "wallet_id": wallet_id,
                "new_threshold": new_threshold,
                "old_quorum": old_quorum,
                "new_committee": new_committee,
                "old_epoch": info.epoch,
            }),
        )

    def _reshare_persist_cb(self, party, key_type, wallet_id, info, on_done):
        """Resharing completion: persist/supersede shares, then chain to the
        caller's callback. Shared by the factory and the crash-resume path."""

        def persist_and_done(share):
            if share is not None:  # new-committee member
                self.save_share(share, wallet_id)
            elif party.is_old:
                # old-only member (excluded from the new committee): its
                # share is superseded — delete it and move keyinfo to the
                # new topology so later signing attempts here neither use a
                # stale polynomial nor list this node as a participant
                # (reference IsReshared gating, node.go:149-159)
                self.kvstore.delete(share_key(key_type, wallet_id))
                self.keyinfo.save(
                    key_type,
                    wallet_id,
                    KeyInfo(
                        participant_peer_ids=list(party.new_committee),
                        threshold=party.new_threshold,
                        is_reshared=True,
                        public_key=info.public_key,
                        vss_commitments=[c.hex() for c in party.new_agg or []],
                        epoch=party.new_epoch,
                    ),
                )
            if on_done:
                on_done(share)

        return persist_and_done

    # -- crash resume -------------------------------------------------------

    def resume_session(
        self,
        rep: WALReplay,
        on_done: Optional[Callable] = None,
        on_error: Optional[Callable] = None,
    ) -> Session:
        """Rebuild an in-flight session from its WAL replay: reconstruct
        the party from the journaled factory arguments, restore the last
        checkpoint, and hand the sent history + post-checkpoint envelopes
        to the Session for wire replay. The participant set comes from the
        journal, NOT from a fresh registry quorum — the peers of the
        original run are the only valid counterparties."""
        if self.session_wal is None:
            raise ProtocolError("session WAL is not enabled")
        meta = rep.meta
        kind = meta.get("kind")
        key_type = meta["key_type"]
        wallet_id = meta["wallet_id"]
        sid = rep.session_id
        if kind == "keygen":
            participants = list(meta["participants"])
            if key_type == wire.KEY_TYPE_SECP256K1:
                party = ECDSAKeygenParty(
                    sid, self.node_id, participants, meta["threshold"],
                    preparams=self.preparams,
                    min_paillier_bits=self.min_paillier_bits,
                )
            else:
                party = EDDSAKeygenParty(
                    sid, self.node_id, participants, meta["threshold"]
                )

            def done_cb(share, _done=on_done):
                self.save_share(share, wallet_id)
                if _done:
                    _done(share)

            broadcast = wire.keygen_broadcast_topic(key_type, wallet_id)
            direct = lambda n: wire.keygen_direct_topic(key_type, n, wallet_id)  # noqa: E731
        elif kind == "sign":
            quorum = list(meta["participants"])
            share = self.load_share(key_type, wallet_id)
            tx = bytes.fromhex(meta["tx"])
            if key_type == wire.KEY_TYPE_SECP256K1:
                party = ECDSASigningParty(
                    sid, self.node_id, quorum, share,
                    int.from_bytes(tx, "big"),
                )
            else:
                party = EDDSASigningParty(sid, self.node_id, quorum, share, tx)
            epoch_tag = meta["epoch_tag"]
            done_cb = on_done
            broadcast = wire.sign_broadcast_topic(key_type, wallet_id, epoch_tag)
            direct = lambda n: wire.sign_direct_topic(key_type, n, epoch_tag)  # noqa: E731
        elif kind == "reshare":
            info = self.keyinfo.get(key_type, wallet_id)
            if info is None:
                raise ProtocolError(
                    f"cannot resume reshare: no keyinfo for {wallet_id!r}"
                )
            old_quorum = list(meta["old_quorum"])
            new_committee = list(meta["new_committee"])
            is_old = self.node_id in set(old_quorum)
            party = ResharingParty(
                sid,
                self.node_id,
                key_type,
                old_quorum,
                new_committee,
                meta["new_threshold"],
                old_share=self.load_share(key_type, wallet_id) if is_old else None,
                old_public_key=bytes.fromhex(info.public_key)
                if info.public_key else None,
                old_vss_commitments=[bytes.fromhex(c) for c in info.vss_commitments]
                or None,
                preparams=self.preparams
                if key_type == wire.KEY_TYPE_SECP256K1 else None,
                min_paillier_bits=self.min_paillier_bits,
                old_epoch=meta["old_epoch"],
            )
            done_cb = self._reshare_persist_cb(
                party, key_type, wallet_id, info, on_done
            )
            broadcast = wire.resharing_broadcast_topic(key_type, wallet_id)
            direct = lambda n: wire.resharing_direct_topic(key_type, n, wallet_id)  # noqa: E731
        else:
            raise ProtocolError(f"unknown WAL session kind {kind!r}")
        if rep.snapshot is not None:
            party.restore(rep.snapshot)
        # else: no checkpoint survived (crash/torn tail before the first
        # one) — nothing was ever routed, so the party safely starts fresh
        # inside the resume replay (resume_fresh below)
        return Session(
            session_id=sid,
            party=party,
            node_id=self.node_id,
            participants=sorted(party.party_ids),
            transport=self.transport,
            identity=self.identity,
            broadcast_topic=broadcast,
            direct_topic_fn=direct,
            on_done=done_cb,
            on_error=on_error,
            hello_timeout_s=self.hello_timeout_s,
            wal=self.session_wal.reopen(rep),
            resumed=True,
            resume_fresh=rep.snapshot is None,
            resume_sent=rep.sent,
            resume_envelopes=rep.envelopes,
        )
