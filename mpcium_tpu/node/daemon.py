"""Node daemon: the `mpcium start -n node0` equivalent (cmd/mpcium/main.go).

Wires every subsystem by hand like the reference (main.go:86-200): config →
logging → control-plane KV → encrypted share store → keyinfo → identity →
TCP bus transport → registry → node (pre-params) → event consumer +
timeout consumer → ready → signing consumer, then blocks until
SIGINT/SIGTERM.
"""
from __future__ import annotations

import getpass
import json
import signal
import threading
import time
from pathlib import Path

from ..config import check_required, init_config
from ..consumers.event_consumer import EventConsumer
from ..consumers.signing_consumer import SigningConsumer, TimeoutConsumer
from ..identity.identity import IdentityStore
from ..registry.registry import PeerRegistry
from ..store.keyinfo import KeyinfoStore
from ..store.kvstore import EncryptedFileKV, FileKV
from ..trace import arm as trace_arm
from ..transport.tcp import tcp_transport
from ..utils import log
from .node import Node


def publish_health(consumer, control_kv, name: str) -> dict:
    """One health beat: publish the consumer's operational snapshot as
    JSON under ``health/<name>`` and the same registry as Prometheus text
    exposition under ``health/<name>.prom`` — so ``kv get health/node0``
    stays the whole monitoring story and a scrape sidecar can serve
    ``.prom`` verbatim. Returns the JSON snapshot (tests assert on it)."""
    snap = consumer.health()
    snap["ts"] = time.time()
    control_kv.put(
        f"health/{name}",
        json.dumps(snap, sort_keys=True).encode(),
    )
    control_kv.put(
        f"health/{name}.prom",
        consumer.metrics.to_prometheus(labels={"node": name}).encode(),
    )
    return snap


def health_loop(consumer, control_kv, name: str, stop: threading.Event,
                interval_s: float = 10.0) -> None:
    """Periodic health publisher (daemon thread body). A failed publish
    is logged and the beat continues — monitoring must never kill the
    node it monitors."""
    while not stop.wait(interval_s):
        try:
            publish_health(consumer, control_kv, name)
        except Exception as e:  # noqa: BLE001 — never kill the beat
            log.warn("health publish failed", node=name, error=repr(e))


def load_peers(cfg, kv=None) -> dict:
    """peers.json {name: uuid} (reference generate-peers.go), else the
    control-plane ``mpc_peers/`` prefix (reference LoadPeersFromConsul,
    main.go:302-311) — from ``kv`` when given (broker control plane),
    else the FileKV directory."""
    p = Path(cfg.peers_file)
    if p.exists():
        return json.loads(p.read_text())
    kv = kv if kv is not None else FileKV(cfg.control_kv_dir)
    peers = {}
    for key in kv.keys("mpc_peers/"):
        peers[key[len("mpc_peers/"):]] = (kv.get(key) or b"").decode()
    if not peers:
        raise SystemExit(
            f"no peers: neither {cfg.peers_file} nor mpc_peers/ in the "
            f"{cfg.control_plane!r} control plane (run mpcium-tpu-cli "
            f"generate-peers + register-peers first)"
        )
    return peers


def run_node(
    name: str,
    config_path: str = "config.yaml",
    decrypt_private_key: bool = False,
    debug: bool = False,
    block: bool = True,
    fault_plan=None,  # faults.FaultPlan | path to a plan JSON | None
):
    cfg = init_config(config_path)
    log.init(
        production=cfg.environment == "production",
        level="DEBUG" if debug else "INFO",
    )
    check_required(cfg, ["badger_password", "event_initiator_pubkey"])
    # arm the flight recorder for this node: bounded ring buffer, incident
    # dumps (shed / timeout / drill failure) land under the db dir
    trace_arm(node_ids=[name],
              dump_dir=str(Path(cfg.db_dir) / name / "trace_incidents"))
    # compile ledger: this node is alive but cold until boot completes —
    # health publishes state=warming so a restart paying the compile
    # wall is distinguishable from a dead node. The ledger file lands
    # beside the node's stores.
    from ..perf import compile_watch

    compile_watch.mark_warming()
    compile_watch.set_ledger_dir(str(Path(cfg.db_dir) / name))
    passphrase = cfg.passphrase or None
    if decrypt_private_key and passphrase is None:
        passphrase = getpass.getpass(f"passphrase for {name} identity key: ")

    # transport first: with the broker control plane the SAME connection
    # serves registry/keyinfo/peers (reference topology: NATS + Consul are
    # two services; here the broker is the single network rendezvous)
    from ..transport.tcp import parse_addrs

    transport = tcp_transport(
        cfg.broker_host, cfg.broker_port,
        auth_token=cfg.broker_token or None,
        encrypt=cfg.broker_encrypt,
        standbys=parse_addrs(cfg.broker_standbys),
    )
    # chaos seam (ISSUE 3): an explicit plan argument or the
    # chaos_fault_plan config knob (path to a plan JSON) wraps this
    # daemon's transport in a FaultyTransport. Absent both — the normal
    # case — nothing is constructed and the bare transport flows on.
    fault_plan = fault_plan or (cfg.chaos_fault_plan or None)
    if fault_plan is not None:
        from ..faults.plan import FaultPlan
        from ..faults.transport import FaultyTransport

        if isinstance(fault_plan, (str, Path)):
            fault_plan = FaultPlan.from_json(Path(fault_plan).read_text())
        transport = FaultyTransport(transport, name, fault_plan)
        # mpclint: disable=MPL101,MPF701 — fault-plan seed is the chaos replay handle and must be logged; not key material
        log.warn("CHAOS: fault plan installed", node=name,
                 seed=fault_plan.seed, rules=fault_plan.describe())
    if cfg.control_plane == "broker":
        from ..store.broker_kv import BrokerKV

        control_kv = BrokerKV(transport.client)
    elif cfg.control_plane == "file":
        control_kv = FileKV(cfg.control_kv_dir)
    else:
        raise SystemExit(
            f"control_plane={cfg.control_plane!r}: expected 'file' or "
            f"'broker'"
        )

    peers = load_peers(cfg, control_kv)
    if name not in peers:
        raise SystemExit(f"node {name!r} not in peer set {sorted(peers)}")

    share_store = EncryptedFileKV(Path(cfg.db_dir) / name, cfg.badger_password)
    # crash-recovery WAL (default off): journals live sessions under the
    # share store's AEAD so a SIGKILL'd node resumes mid-round after restart
    session_wal = None
    if cfg.session_wal:
        from ..store.session_wal import SessionWALStore

        session_wal = SessionWALStore(share_store)
    keyinfo = KeyinfoStore(control_kv)
    identity = IdentityStore(
        cfg.identity_dir,
        name,
        peers,
        initiator_pubkey=bytes.fromhex(cfg.event_initiator_pubkey),
        passphrase=passphrase,
    )
    registry = PeerRegistry(name, list(peers), control_kv)
    node = Node(
        node_id=name,
        peer_ids=list(peers),
        transport=transport,
        identity=identity,
        kvstore=share_store,
        keyinfo=keyinfo,
        registry=registry,
        safe_prime_pool=cfg.safe_prime_pool or None,
        session_wal=session_wal,
    )
    # multi-device hosts shard the session axis of batched dispatches
    # over every local chip (engine/sharded.py; no-op on one device)
    try:
        import jax as _jax

        from ..engine.sharded import arm_session_axis

        mesh = arm_session_axis()
        if mesh is not None:
            log.info("session axis sharded over local devices",
                     devices=len(_jax.devices()))
    except Exception as e:  # noqa: BLE001 — never block startup on this
        log.warn("session-axis sharding unavailable", error=repr(e))

    consumer = EventConsumer(
        node, transport,
        batch_signing=cfg.batch_signing,
        batch_window_s=cfg.batch_window_s,
    )
    consumer.run()
    TimeoutConsumer(transport).run()
    registry.ready()
    # boot-time crash recovery: replay incomplete WAL sessions AFTER the
    # consumer subscribed (resumed peers' answers must not race our subs)
    # and after ready() so peers treat us as live again
    if session_wal is not None:
        try:
            consumer.resume_incomplete()
        except Exception as e:  # noqa: BLE001 — recovery must never block boot
            log.warn("WAL resume scan failed", node=name, error=repr(e))
    signing = SigningConsumer(transport)
    signing.run()
    # health surface: periodically publish the consumer's operational
    # snapshot (live sessions, dedup claims, scheduler lane depths, shed
    # counters, latency percentiles) to the control plane under
    # ``health/<name>`` — the same KV operators already watch for peer
    # liveness, so `kv get health/node0` is the whole monitoring story
    health_stop = threading.Event()
    threading.Thread(
        target=health_loop, args=(consumer, control_kv, name, health_stop),
        name=f"health-{name}", daemon=True,
    ).start()
    # every subsystem is wired and subscribed. With warm_enabled the
    # warm-start pass now pre-compiles the serving set (knobs × buckets
    # read from COMPILE_SURFACE.json) while health still publishes
    # state=warming — the node advertises ready only once the manifest
    # is covered or warm_budget_s expires. Cold boot (warm_enabled
    # false) flips straight to ready and live traffic pays the wall.
    if cfg.warm_enabled:
        from ..warm.prewarm import prewarm_for_daemon

        prewarm_for_daemon(cfg, name)
    compile_watch.mark_ready()
    log.info("node running", node=name, broker=f"{cfg.broker_host}:{cfg.broker_port}")

    if not block:
        return node, consumer, signing, registry

    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    stop.wait()
    log.info("shutting down", node=name)
    health_stop.set()
    signing.close()
    consumer.close()
    registry.resign()
    transport.client.close()
    return 0


def run_broker(
    host: str = "127.0.0.1",
    port: int = 4333,
    block: bool = True,
    journal: str = "",
    token: str = "",
    encrypt: bool = False,
    follow: str = "",
):
    """The `nats-server` analogue: `mpcium-tpu broker`. CLI flags win;
    otherwise config.yaml's broker_journal/broker_token apply. ``follow``
    ("host:port") starts this broker as a hot standby mirroring that
    primary's queue state until the primary dies."""
    from ..config import init_config
    from ..transport.tcp import BrokerServer, parse_addrs

    cfg = init_config()
    broker = BrokerServer(
        host=host, port=port,
        journal_path=journal or cfg.broker_journal or None,
        auth_token=token or cfg.broker_token or None,
        encrypt=encrypt or cfg.broker_encrypt,
        follow=parse_addrs(follow)[0] if follow else None,
    )
    log.init()
    log.info("broker listening", host=broker.host, port=broker.port)
    if not block:
        return broker
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    stop.wait()
    broker.close()
    return 0
