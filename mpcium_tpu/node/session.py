"""Transport-bound protocol session.

Binds a transport-free protocol party (protocol/*) to the messaging fabric:
outbound round messages are wrapped in signed envelopes and routed broadcast
vs unicast (reference session.go:97-134); inbound envelopes are verified
(Ed25519) before reaching the party (session.go:164-205); party state is
mutex-guarded (the reference's update mutex, session.go:79).

The reference's 1-second sleep barrier (event_consumer.go:173,325,484 — a
TODO'd hack) is replaced by a real readiness handshake: each participant
broadcasts a signed ``hello`` for the session and buffers protocol traffic
until every quorum member has said hello; receiving a hello from a peer we
haven't seen triggers a re-broadcast of our own, so late subscribers
converge without polling.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

from ..identity.identity import IdentityStore
from ..protocol.base import PartyBase, ProtocolError, RoundMsg
from ..store.session_wal import SessionWALWriter
from ..transport.api import Transport, TransportError
from ..utils import log, tracing
from ..utils.annotations import locked_by
from ..wire import Envelope

HELLO_ROUND = "__hello__"
# broadcast by a crash-resumed participant: peers re-route their sent
# history (broadcasts + unicasts addressed to the requester) so rounds the
# dead process missed are redelivered — duplicates are protocol-legal
# (identical-payload dedup in PartyBase._store)
RESUME_ROUND = "__resume__"


def _msg_to_json(m: RoundMsg) -> dict:
    return {
        "session_id": m.session_id,
        "round": m.round,
        "from_id": m.from_id,
        "payload": m.payload,
        "to": m.to,
    }


def _msg_from_json(d: dict) -> RoundMsg:
    return RoundMsg(
        d["session_id"], d["round"], d["from_id"], d["payload"], d.get("to")
    )


class SessionError(Exception):
    def __init__(self, message: str, culprit: Optional[str] = None):
        super().__init__(message)
        self.culprit = culprit


class RetryableSessionError(SessionError):
    """Transient failure (e.g. quorum peers never said hello inside the
    barrier deadline): the triggering event should be redelivered, not
    surfaced as a terminal error — the reference's un-acked-redelivery
    philosophy (event_consumer.go:276-280)."""


# the PR 4 `_started`-published-before-`start()` race is exactly the shape
# this declaration turns into a lint error (MPL301)
@locked_by(
    "_lock",
    "_started",
    "_start_claimed",
    "_failed",
    "_hellos",
    "_buffer",
    "_sent_raw",
    "_finished",
)
class Session:
    """One protocol run bound to topics.

    ``broadcast_topic``: fan-out topic for this session; ``direct_topic_fn``:
    node_id → unicast topic (reference TopicComposer, session.go:45-48).
    """

    def __init__(
        self,
        session_id: str,
        party: PartyBase,
        node_id: str,
        participants: Sequence[str],
        transport: Transport,
        identity: IdentityStore,
        broadcast_topic: str,
        direct_topic_fn: Callable[[str], str],
        on_done: Optional[Callable[[object], None]] = None,
        on_error: Optional[Callable[[Exception], None]] = None,
        hello_timeout_s: Optional[float] = 20.0,
        send_patience_s: float = 0.0,
        wal: Optional[SessionWALWriter] = None,
        resumed: bool = False,
        resume_fresh: bool = False,
        resume_sent: Optional[Sequence[dict]] = None,
        resume_envelopes: Optional[Sequence[bytes]] = None,
    ):
        self.session_id = session_id
        self.party = party
        self.node_id = node_id
        self.participants = sorted(participants)
        self.transport = transport
        self.identity = identity
        self.broadcast_topic = broadcast_topic
        self.direct_topic_fn = direct_topic_fn
        self.on_done = on_done
        self.on_error = on_error
        self._lock = threading.RLock()
        self._subs: List = []
        # a resumed session skips the hello barrier: its peers started long
        # ago and will never re-hello; protocol traffic flows immediately
        self._started = resumed
        # one-shot claim that the quorum completed and start() is underway;
        # _started flips only once start() has RUN (see _start_party)
        self._start_claimed = resumed
        self._failed = False
        self._hellos = {node_id}
        self._buffer: List[RoundMsg] = []
        # crash-recovery WAL (None ⇒ feature off: no journaling, no extra
        # state, transcript byte-identical to a WAL-less build)
        self._wal = wal
        self._resumed = resumed
        self._resume_fresh = resume_fresh
        self._resume_sent = list(resume_sent or [])
        self._resume_envelopes = list(resume_envelopes or [])
        self._replaying = False
        # full outbound history (routing metadata + signed wire bytes),
        # kept so a peer's __resume__ request can be answered verbatim
        self._sent_raw: List[tuple] = []
        self.created_at = time.monotonic()
        self.last_activity = self.created_at
        # mpctrace: every node derives the SAME trace id from the public
        # session id, so merged cross-node views group without any
        # coordination; wire context only refines parent/child edges
        self._trace_id = tracing.trace_id_for(session_id)
        self._trace_t0 = tracing.now_ns()
        self._done_evt = threading.Event()
        # one-shot claim for _finish, distinct from _done_evt: close() sets
        # the event for waiters, which must not make a racing _finish skip
        # its completion work (on_done + WAL drop)
        self._finished = False
        self.hello_timeout_s = hello_timeout_s
        # extra unicast retry budget on TOP of the transport's own
        # (3 s × 3 attempts, reference point2point.go:26-45). Batched
        # DKG/signing sessions set this generously: a peer can be busy for
        # minutes inside one round (XLA compiles, DLN verification) and an
        # unacked send then means "receiver busy", not "receiver gone".
        self.send_patience_s = send_patience_s
        self._hello_timer: Optional[threading.Timer] = None
        # unicasts go through a dedicated sender thread: an acked send can
        # block for the whole patience budget, and doing that INSIDE a
        # transport handler thread deadlocks the delivery pools (every
        # worker waiting on a peer whose workers are likewise stuck)
        import queue as _queue

        self._out_q: "_queue.Queue" = _queue.Queue()
        self._sender: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def listen(self) -> None:
        """Subscribe broadcast + own direct topic, then announce readiness
        (replaces ListenToIncomingMessageAsync + sleep barrier)."""
        self._subs.append(
            self.transport.pubsub.subscribe(self.broadcast_topic, self._on_raw)
        )
        self._subs.append(
            self.transport.direct.listen(
                self.direct_topic_fn(self.node_id), self._on_raw
            )
        )
        self._sender = threading.Thread(
            target=self._send_loop,
            name=f"send-{self.session_id[:24]}",
            daemon=True,
        )
        self._sender.start()
        self._send_hello()
        if self._resumed:
            self._replay_resume()
            return
        # barrier deadline: a never-arriving quorum peer must fail the
        # session RETRYABLY within the signing window, not sit buffered
        # until the 30-minute GC (reference window: 30 s, sign_consumer.go:
        # 16-20; the deadline here is per-session and shorter)
        if self.hello_timeout_s is not None:
            self._hello_timer = threading.Timer(
                self.hello_timeout_s, self._hello_deadline
            )
            self._hello_timer.daemon = True
            self._hello_timer.start()

    def _hello_deadline(self) -> None:
        with self._lock:
            if self._start_claimed or self._failed:
                return
            # claim the failure INSIDE the same hold that checks the claim:
            # a final hello racing the deadline must not both start and
            # fail the session
            self._failed = True
            missing = sorted(set(self.participants) - self._hellos)
        self._fail(
            RetryableSessionError(
                f"hello barrier timed out after {self.hello_timeout_s}s; "
                f"missing: {missing}"
            ),
            _claimed=True,
        )

    def close(self) -> None:
        if self._hello_timer is not None:
            self._hello_timer.cancel()
        for s in self._subs:
            try:
                s.unsubscribe()
            except Exception:  # noqa: BLE001
                pass
        self._subs.clear()
        # sentinel: the sender drains already-queued unicasts (peers may
        # still need them) and exits
        self._out_q.put(None)
        # release the WAL file handle but KEEP the file: a close that isn't
        # a completion (shutdown, GC reap) leaves the session resumable
        if self._wal is not None:
            self._wal.close()
        # an external close of an unfinished session must not leave wait()
        # callers blocking until their own timeout: signal them with a
        # RETRYABLE failure (shutdown is not the protocol's fault, and the
        # triggering event may legitimately be redelivered elsewhere)
        with self._lock:
            if self._done_evt.is_set():
                return
            if self._failed or self.party.done:
                self._done_evt.set()
                return
            self._failed = True
        self._done_evt.set()
        if self.on_error:
            try:
                self.on_error(RetryableSessionError("session closed"))
            except Exception as e:  # noqa: BLE001
                log.error("on_error callback failed", error=repr(e))

    def wait(self, timeout_s: float) -> bool:
        return self._done_evt.wait(timeout_s)

    @property
    def done(self) -> bool:
        return self.party.done

    @property
    def result(self):
        return self.party.result

    # -- outbound -----------------------------------------------------------

    def _send_hello(self) -> None:
        env = Envelope(
            session_id=self.session_id,
            round=HELLO_ROUND,
            from_id=self.node_id,
            payload={},
        )
        self.identity.sign_envelope(env)
        self.transport.pubsub.publish(self.broadcast_topic, env.encode())

    @staticmethod
    def send_decline(
        transport: Transport,
        identity: IdentityStore,
        node_id: str,
        session_id: str,
        broadcast_topic: str,
        reason: str = "",
    ) -> None:
        """Signed 'not joining' announcement for a session this node will
        never create (e.g. a batch it cannot serve yet). Peers waiting at
        the hello barrier fail RETRYABLY at once instead of burning their
        hello deadline — essential once deadlines are generous enough to
        ride out long compiles (send_patience_s)."""
        env = Envelope(
            session_id=session_id,
            round=HELLO_ROUND,
            from_id=node_id,
            payload={"bye": True, "reason": reason},
        )
        identity.sign_envelope(env)
        transport.pubsub.publish(broadcast_topic, env.encode())

    def _route(self, msgs: Sequence[RoundMsg]) -> None:
        # outbound trace context: the ids of the round span this batch of
        # messages came out of (None — and absent from the wire — when
        # tracing is off, keeping envelope bytes identical to pre-trace)
        ctx = tracing.wire_context()
        for m in msgs:
            env = Envelope(
                session_id=m.session_id,
                round=m.round,
                from_id=m.from_id,
                payload=m.payload,
                to=m.to,
                is_broadcast=m.is_broadcast,
                trace=ctx,
            )
            self.identity.sign_envelope(env)
            raw = env.encode()
            with self._lock:
                self._sent_raw.append((m.to, raw))
            if m.is_broadcast:
                self.transport.pubsub.publish(self.broadcast_topic, raw)
            else:
                # acked unicast, via the sender thread (see __init__ note)
                self._out_q.put((m.to, raw))

    # -- crash recovery -----------------------------------------------------

    def _replay_resume(self) -> None:
        """Rebuild the wire state of a crash-resumed session.

        1. Re-route the full sent history from the WAL. Checkpoints are
           written BEFORE their messages are routed, so any suffix of the
           history may never have left the dead process; peers that did see
           a message drop the duplicate.
        2. Broadcast ``__resume__`` so peers re-route THEIR history — the
           rounds they sent into the dead window are redelivered.
        3. Re-deliver envelopes journaled after the last checkpoint (their
           effect on party state was lost with the process).
        """
        try:
            log.info("resuming session from WAL", session=self.session_id,
                     node=self.node_id, sent=len(self._resume_sent),
                     pending=len(self._resume_envelopes))
            if self._resume_fresh:
                # crash predated the first checkpoint: nothing was routed,
                # so run start() now (it checkpoints before routing)
                with self._lock:
                    out = self.party.start()
                    if self._wal is not None:
                        self._checkpoint(out)
                self._route(out)
            self._route([_msg_from_json(d) for d in self._resume_sent])
            env = Envelope(
                session_id=self.session_id,
                round=RESUME_ROUND,
                from_id=self.node_id,
                payload={},
            )
            self.identity.sign_envelope(env)
            self.transport.pubsub.publish(self.broadcast_topic, env.encode())
            pending, self._resume_envelopes = self._resume_envelopes, []
            self._replaying = True
            try:
                for raw in pending:
                    self._on_raw(raw)
            finally:
                self._replaying = False
            # the checkpoint may already hold a finished party (crash landed
            # between the final checkpoint and the result callback)
            if self.party.done and not self._failed:
                self._finish()
        except Exception as e:  # noqa: BLE001
            self._fail(e)

    def _resend_history(self, requester: str) -> None:
        """Answer a peer's ``__resume__``: re-publish every broadcast and
        re-send the unicasts addressed to the requester, verbatim."""
        with self._lock:
            history = list(self._sent_raw)
        if not history:
            return
        log.info("re-sending history for resumed peer",
                 session=self.session_id, peer=requester, n=len(history))
        for to, raw in history:
            if to is None:
                self.transport.pubsub.publish(self.broadcast_topic, raw)
            elif to == requester:
                self._out_q.put((to, raw))

    def _checkpoint(self, out: Sequence[RoundMsg]) -> None:  # mpclint: holds=_lock
        """Journal party state + this step's outputs. Called under the
        session lock, BEFORE the outputs are routed: a resumed party must
        re-send the exact payloads peers may already hold, never re-derive
        fresh randomness for them (peers would flag equivocation)."""
        try:
            self._wal.checkpoint(
                self.party.snapshot(), [_msg_to_json(m) for m in out]
            )
        except Exception as e:  # noqa: BLE001
            # a stale WAL is worse than none: resuming from it would
            # re-derive randomness for payloads peers already hold
            # (equivocation). Disable recovery for this session, keep going.
            log.warn("session WAL checkpoint failed — disabling recovery",
                     session=self.session_id, error=repr(e))
            try:
                self._wal.drop()
            except Exception:  # noqa: BLE001
                pass
            self._wal = None

    def _send_loop(self) -> None:
        while True:
            item = self._out_q.get()
            if item is None:
                return
            to, raw = item
            # acked unicast (reference session.go:126, point2point.go:
            # 26-45). With patience, the WHOLE budget rides one transport
            # call: one delivery, waited on — never re-delivered to a busy
            # receiver (duplicate floods starve shared delivery pools)
            try:
                if self.send_patience_s > 0:
                    self.transport.direct.send(
                        self.direct_topic_fn(to), raw,
                        timeout_s=self.send_patience_s,
                    )
                else:
                    self.transport.direct.send(self.direct_topic_fn(to), raw)
            except TransportError as e:
                if not self._failed and not self.party.done:
                    self._fail(e)
                return

    # -- inbound ------------------------------------------------------------

    def _on_raw(self, raw: bytes) -> None:
        try:
            env = Envelope.decode(raw)
        except Exception as e:  # noqa: BLE001
            log.warn("undecodable envelope dropped", session=self.session_id,
                     error=repr(e))
            return
        if env.session_id != self.session_id:
            return
        if env.from_id == self.node_id:
            return  # own broadcast echo
        if env.from_id not in self.participants:
            log.warn("message from non-participant dropped",
                     session=self.session_id, sender=env.from_id)
            return
        if not self.identity.verify_envelope(env):
            log.warn("BAD SIGNATURE on envelope — dropped",
                     session=self.session_id, sender=env.from_id)
            return
        if env.round == HELLO_ROUND:
            if env.payload.get("bye"):
                with self._lock:
                    if self._start_claimed or self._failed:
                        return
                    self._failed = True
                if self._hello_timer is not None:
                    self._hello_timer.cancel()
                self.close()
                if self.on_error:
                    self.on_error(RetryableSessionError(
                        f"peer {env.from_id} declined session "
                        f"{self.session_id!r}: "
                        f"{env.payload.get('reason', '')}"
                    ))
                return
            self._on_hello(env.from_id)
            return
        if env.round == RESUME_ROUND:
            # a peer came back from the dead: count it present and replay
            # our history so the rounds it missed reach it again
            self._on_hello(env.from_id)
            self._resend_history(env.from_id)
            return
        # journal the verified envelope BEFORE delivery: if we die inside
        # receive(), replay re-delivers it (re-deliveries during resume are
        # already on disk — don't journal them twice)
        if self._wal is not None and not self._replaying:
            try:
                self._wal.envelope(raw)
            except Exception as e:  # noqa: BLE001
                log.warn("session WAL append failed", session=self.session_id,
                         error=repr(e))
        msg = RoundMsg(
            session_id=env.session_id,
            round=env.round,
            from_id=env.from_id,
            payload=env.payload,
            to=env.to,
        )
        parent = env.trace.get("s") if env.trace else None
        with self._lock:
            self.last_activity = time.monotonic()
            if not self._started:
                self._buffer.append(msg)
                return
        self._deliver(msg, parent=parent)

    def _on_hello(self, from_id: str) -> None:
        start_now = False
        with self._lock:
            if from_id not in self._hellos:
                self._hellos.add(from_id)
                # answer late joiners so they converge too
                self._send_hello()
            if (
                not self._start_claimed
                and not self._failed
                and self._hellos >= set(self.participants)
            ):
                self._start_claimed = True
                start_now = True
        if start_now:
            if self._hello_timer is not None:
                self._hello_timer.cancel()
            self._start_party()

    def _start_party(self) -> None:
        try:
            # start() can burn SECONDS of CPU (ECDSA keygen: DLN proofs over
            # big moduli) — run it OUTSIDE the lock so inbound deliveries
            # buffer-and-ack instantly instead of pinning a transport worker
            # until the sender's ack budget runs out. Only this thread
            # touches the party until _started flips: every inbound message
            # buffers while _started is False, so receive() cannot run
            # before start() has, and start() runs exactly once
            # (_start_claimed is a one-shot)
            with tracing.span(
                "round:start", trace_id=self._trace_id,
                node=self.node_id, tid=self.session_id,
            ):
                out = self.party.start()
                with self._lock:
                    self._started = True
                    buffered, self._buffer = self._buffer, []
                    if self._wal is not None:
                        # commit the start-time randomness (nonce
                        # commitments, Shamir coefficients) before
                        # anything leaves the node
                        self._checkpoint(out)
                self._route(out)
            for m in buffered:
                self._deliver(m)
        except Exception as e:  # noqa: BLE001
            self._fail(e)

    def _deliver(self, msg: RoundMsg, parent: Optional[str] = None) -> None:
        try:
            with tracing.span(
                f"round:{msg.round}", trace_id=self._trace_id,
                parent_id=parent, node=self.node_id, tid=self.session_id,
                sender=msg.from_id,
            ):
                with self._lock:
                    if self._failed or self.party.done:
                        return
                    out = self.party.receive(msg)
                    finished = self.party.done
                    if self._wal is not None and (out or finished):
                        self._checkpoint(out)
                self._route(out)
            if finished:
                self._finish()
        except ProtocolError as e:
            self._fail(e)
        except Exception as e:  # noqa: BLE001
            self._fail(e)

    def _finish(self) -> None:
        with self._lock:
            if self._finished:
                return
            self._finished = True
        tracing.emit(
            "session", self._trace_t0, tracing.now_ns(),
            node=self.node_id, tid=self.session_id,
            trace_id=self._trace_id, outcome="ok", resumed=self._resumed,
        )
        log.info("session complete", session=self.session_id, node=self.node_id)
        if self.on_done:
            try:
                self.on_done(self.party.result)
            except Exception as e:  # noqa: BLE001
                log.error("on_done callback failed", session=self.session_id,
                          error=repr(e))
                self._done_evt.set()
                return  # keep the WAL: completion isn't durable yet
        # drop the WAL only after on_done persisted its result — a crash
        # before this line resumes into a done party and re-runs on_done
        # (idempotent: share puts and result enqueues are keyed). A racing
        # close() may have released the writer handle already: appends
        # no-op on a closed writer and drop() unlinks by path, so the file
        # still goes away.
        if self._wal is not None:
            try:
                self._wal.done()
                self._wal.drop()
            except Exception:  # noqa: BLE001
                pass
        self._done_evt.set()

    def _fail(self, e: Exception, _claimed: bool = False) -> None:
        if not _claimed:
            with self._lock:
                if self._failed:
                    return
                self._failed = True
        culprit = getattr(e, "culprit", None)
        tracing.emit(
            "session", self._trace_t0, tracing.now_ns(),
            node=self.node_id, tid=self.session_id,
            trace_id=self._trace_id, outcome="fail", error=type(e).__name__,
        )
        tracing.incident(
            "session-fail", node=self.node_id, tid=self.session_id,
            error=type(e).__name__, retryable=isinstance(e, RetryableSessionError),
        )
        log.error("session failed", session=self.session_id, node=self.node_id,
                  error=str(e), culprit=culprit or "")
        # a failed session must not resurrect at the next boot; only a hard
        # crash (which never reaches _fail) leaves the WAL behind
        if self._wal is not None:
            try:
                self._wal.drop()
            except Exception:  # noqa: BLE001
                pass
        self._done_evt.set()
        if self.on_error:
            try:
                self.on_error(e)
            except Exception as cb_e:  # noqa: BLE001
                log.error("on_error callback failed", error=repr(cb_e))

    @property
    def failed(self) -> bool:
        return self._failed
