"""Transport-bound protocol session.

Binds a transport-free protocol party (protocol/*) to the messaging fabric:
outbound round messages are wrapped in signed envelopes and routed broadcast
vs unicast (reference session.go:97-134); inbound envelopes are verified
(Ed25519) before reaching the party (session.go:164-205); party state is
mutex-guarded (the reference's update mutex, session.go:79).

The reference's 1-second sleep barrier (event_consumer.go:173,325,484 — a
TODO'd hack) is replaced by a real readiness handshake: each participant
broadcasts a signed ``hello`` for the session and buffers protocol traffic
until every quorum member has said hello; receiving a hello from a peer we
haven't seen triggers a re-broadcast of our own, so late subscribers
converge without polling.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..identity.identity import IdentityStore
from ..protocol.base import PartyBase, ProtocolError, RoundMsg
from ..transport.api import Transport, TransportError
from ..utils import log
from ..wire import Envelope

HELLO_ROUND = "__hello__"


class SessionError(Exception):
    def __init__(self, message: str, culprit: Optional[str] = None):
        super().__init__(message)
        self.culprit = culprit


class RetryableSessionError(SessionError):
    """Transient failure (e.g. quorum peers never said hello inside the
    barrier deadline): the triggering event should be redelivered, not
    surfaced as a terminal error — the reference's un-acked-redelivery
    philosophy (event_consumer.go:276-280)."""


class Session:
    """One protocol run bound to topics.

    ``broadcast_topic``: fan-out topic for this session; ``direct_topic_fn``:
    node_id → unicast topic (reference TopicComposer, session.go:45-48).
    """

    def __init__(
        self,
        session_id: str,
        party: PartyBase,
        node_id: str,
        participants: Sequence[str],
        transport: Transport,
        identity: IdentityStore,
        broadcast_topic: str,
        direct_topic_fn: Callable[[str], str],
        on_done: Optional[Callable[[object], None]] = None,
        on_error: Optional[Callable[[Exception], None]] = None,
        hello_timeout_s: Optional[float] = 20.0,
        send_patience_s: float = 0.0,
    ):
        self.session_id = session_id
        self.party = party
        self.node_id = node_id
        self.participants = sorted(participants)
        self.transport = transport
        self.identity = identity
        self.broadcast_topic = broadcast_topic
        self.direct_topic_fn = direct_topic_fn
        self.on_done = on_done
        self.on_error = on_error
        self._lock = threading.RLock()
        self._subs: List = []
        self._started = False
        self._failed = False
        self._hellos = {node_id}
        self._buffer: List[RoundMsg] = []
        self.created_at = time.monotonic()
        self.last_activity = self.created_at
        self._done_evt = threading.Event()
        self.hello_timeout_s = hello_timeout_s
        # extra unicast retry budget on TOP of the transport's own
        # (3 s × 3 attempts, reference point2point.go:26-45). Batched
        # DKG/signing sessions set this generously: a peer can be busy for
        # minutes inside one round (XLA compiles, DLN verification) and an
        # unacked send then means "receiver busy", not "receiver gone".
        self.send_patience_s = send_patience_s
        self._hello_timer: Optional[threading.Timer] = None
        # unicasts go through a dedicated sender thread: an acked send can
        # block for the whole patience budget, and doing that INSIDE a
        # transport handler thread deadlocks the delivery pools (every
        # worker waiting on a peer whose workers are likewise stuck)
        import queue as _queue

        self._out_q: "_queue.Queue" = _queue.Queue()
        self._sender: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def listen(self) -> None:
        """Subscribe broadcast + own direct topic, then announce readiness
        (replaces ListenToIncomingMessageAsync + sleep barrier)."""
        self._subs.append(
            self.transport.pubsub.subscribe(self.broadcast_topic, self._on_raw)
        )
        self._subs.append(
            self.transport.direct.listen(
                self.direct_topic_fn(self.node_id), self._on_raw
            )
        )
        self._sender = threading.Thread(
            target=self._send_loop,
            name=f"send-{self.session_id[:24]}",
            daemon=True,
        )
        self._sender.start()
        self._send_hello()
        # barrier deadline: a never-arriving quorum peer must fail the
        # session RETRYABLY within the signing window, not sit buffered
        # until the 30-minute GC (reference window: 30 s, sign_consumer.go:
        # 16-20; the deadline here is per-session and shorter)
        if self.hello_timeout_s is not None:
            self._hello_timer = threading.Timer(
                self.hello_timeout_s, self._hello_deadline
            )
            self._hello_timer.daemon = True
            self._hello_timer.start()

    def _hello_deadline(self) -> None:
        with self._lock:
            if self._started or self._failed:
                return
            # claim the failure INSIDE the same hold that checks _started:
            # a final hello racing the deadline must not both start and
            # fail the session
            self._failed = True
            missing = sorted(set(self.participants) - self._hellos)
        self._fail(
            RetryableSessionError(
                f"hello barrier timed out after {self.hello_timeout_s}s; "
                f"missing: {missing}"
            ),
            _claimed=True,
        )

    def close(self) -> None:
        if self._hello_timer is not None:
            self._hello_timer.cancel()
        for s in self._subs:
            try:
                s.unsubscribe()
            except Exception:  # noqa: BLE001
                pass
        self._subs.clear()
        # sentinel: the sender drains already-queued unicasts (peers may
        # still need them) and exits
        self._out_q.put(None)

    def wait(self, timeout_s: float) -> bool:
        return self._done_evt.wait(timeout_s)

    @property
    def done(self) -> bool:
        return self.party.done

    @property
    def result(self):
        return self.party.result

    # -- outbound -----------------------------------------------------------

    def _send_hello(self) -> None:
        env = Envelope(
            session_id=self.session_id,
            round=HELLO_ROUND,
            from_id=self.node_id,
            payload={},
        )
        self.identity.sign_envelope(env)
        self.transport.pubsub.publish(self.broadcast_topic, env.encode())

    @staticmethod
    def send_decline(
        transport: Transport,
        identity: IdentityStore,
        node_id: str,
        session_id: str,
        broadcast_topic: str,
        reason: str = "",
    ) -> None:
        """Signed 'not joining' announcement for a session this node will
        never create (e.g. a batch it cannot serve yet). Peers waiting at
        the hello barrier fail RETRYABLY at once instead of burning their
        hello deadline — essential once deadlines are generous enough to
        ride out long compiles (send_patience_s)."""
        env = Envelope(
            session_id=session_id,
            round=HELLO_ROUND,
            from_id=node_id,
            payload={"bye": True, "reason": reason},
        )
        identity.sign_envelope(env)
        transport.pubsub.publish(broadcast_topic, env.encode())

    def _route(self, msgs: Sequence[RoundMsg]) -> None:
        for m in msgs:
            env = Envelope(
                session_id=m.session_id,
                round=m.round,
                from_id=m.from_id,
                payload=m.payload,
                to=m.to,
                is_broadcast=m.is_broadcast,
            )
            self.identity.sign_envelope(env)
            raw = env.encode()
            if m.is_broadcast:
                self.transport.pubsub.publish(self.broadcast_topic, raw)
            else:
                # acked unicast, via the sender thread (see __init__ note)
                self._out_q.put((m.to, raw))

    def _send_loop(self) -> None:
        while True:
            item = self._out_q.get()
            if item is None:
                return
            to, raw = item
            # acked unicast (reference session.go:126, point2point.go:
            # 26-45). With patience, the WHOLE budget rides one transport
            # call: one delivery, waited on — never re-delivered to a busy
            # receiver (duplicate floods starve shared delivery pools)
            try:
                if self.send_patience_s > 0:
                    self.transport.direct.send(
                        self.direct_topic_fn(to), raw,
                        timeout_s=self.send_patience_s,
                    )
                else:
                    self.transport.direct.send(self.direct_topic_fn(to), raw)
            except TransportError as e:
                if not self._failed and not self.party.done:
                    self._fail(e)
                return

    # -- inbound ------------------------------------------------------------

    def _on_raw(self, raw: bytes) -> None:
        try:
            env = Envelope.decode(raw)
        except Exception as e:  # noqa: BLE001
            log.warn("undecodable envelope dropped", session=self.session_id,
                     error=repr(e))
            return
        if env.session_id != self.session_id:
            return
        if env.from_id == self.node_id:
            return  # own broadcast echo
        if env.from_id not in self.participants:
            log.warn("message from non-participant dropped",
                     session=self.session_id, sender=env.from_id)
            return
        if not self.identity.verify_envelope(env):
            log.warn("BAD SIGNATURE on envelope — dropped",
                     session=self.session_id, sender=env.from_id)
            return
        if env.round == HELLO_ROUND:
            if env.payload.get("bye"):
                with self._lock:
                    if self._started or self._failed:
                        return
                    self._failed = True
                if self._hello_timer is not None:
                    self._hello_timer.cancel()
                self.close()
                if self.on_error:
                    self.on_error(RetryableSessionError(
                        f"peer {env.from_id} declined session "
                        f"{self.session_id!r}: "
                        f"{env.payload.get('reason', '')}"
                    ))
                return
            self._on_hello(env.from_id)
            return
        msg = RoundMsg(
            session_id=env.session_id,
            round=env.round,
            from_id=env.from_id,
            payload=env.payload,
            to=env.to,
        )
        with self._lock:
            self.last_activity = time.monotonic()
            if not self._started:
                self._buffer.append(msg)
                return
        self._deliver(msg)

    def _on_hello(self, from_id: str) -> None:
        start_now = False
        with self._lock:
            if from_id not in self._hellos:
                self._hellos.add(from_id)
                # answer late joiners so they converge too
                self._send_hello()
            if (
                not self._started
                and not self._failed
                and self._hellos >= set(self.participants)
            ):
                self._started = True
                start_now = True
        if start_now:
            if self._hello_timer is not None:
                self._hello_timer.cancel()
            self._start_party()

    def _start_party(self) -> None:
        try:
            with self._lock:
                out = self.party.start()
                buffered, self._buffer = self._buffer, []
            self._route(out)
            for m in buffered:
                self._deliver(m)
        except Exception as e:  # noqa: BLE001
            self._fail(e)

    def _deliver(self, msg: RoundMsg) -> None:
        try:
            with self._lock:
                if self._failed or self.party.done:
                    return
                out = self.party.receive(msg)
                finished = self.party.done
            self._route(out)
            if finished:
                self._finish()
        except ProtocolError as e:
            self._fail(e)
        except Exception as e:  # noqa: BLE001
            self._fail(e)

    def _finish(self) -> None:
        if self._done_evt.is_set():
            return
        self._done_evt.set()
        log.info("session complete", session=self.session_id, node=self.node_id)
        if self.on_done:
            try:
                self.on_done(self.party.result)
            except Exception as e:  # noqa: BLE001
                log.error("on_done callback failed", session=self.session_id,
                          error=repr(e))

    def _fail(self, e: Exception, _claimed: bool = False) -> None:
        if not _claimed:
            with self._lock:
                if self._failed:
                    return
                self._failed = True
        culprit = getattr(e, "culprit", None)
        log.error("session failed", session=self.session_id, node=self.node_id,
                  error=str(e), culprit=culprit or "")
        self._done_evt.set()
        if self.on_error:
            try:
                self.on_error(e)
            except Exception as cb_e:  # noqa: BLE001
                log.error("on_error callback failed", error=repr(cb_e))

    @property
    def failed(self) -> bool:
        return self._failed
