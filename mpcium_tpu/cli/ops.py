"""`mpcium-tpu-cli` — ops tooling.

Reference analogue: cmd/mpcium-cli (generate-peers, register-peers,
generate-identity, generate-initiator). Subcommands are registered lazily so
the entry point works even while later layers are still landing.
"""
from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpcium-tpu-cli", description="mpcium-tpu ops tooling"
    )
    sub = p.add_subparsers(dest="command")

    gp = sub.add_parser("generate-peers", help="generate peers.json")
    gp.add_argument("-n", "--number", type=int, required=True)
    gp.add_argument("-o", "--output", default="peers.json")

    rp = sub.add_parser(
        "register-peers", help="register peers.json into the registry"
    )
    rp.add_argument("-p", "--peers", default="peers.json")
    rp.add_argument("--registry-dir", default="registry")
    rp.add_argument(
        "--broker", default="",
        help="host:port — register into the broker control plane instead "
        "of a FileKV directory (multi-host deployments; see "
        "control_plane: broker)",
    )
    rp.add_argument("--broker-token", default="",
                    help="broker auth token (with --broker)")
    rp.add_argument("--broker-encrypt", action="store_true",
                    help="AEAD channel to the broker (with --broker)")

    gi = sub.add_parser("generate-identity", help="generate a node identity")
    gi.add_argument("--node", required=True)
    gi.add_argument("--encrypt", action="store_true")
    gi.add_argument("--identity-dir", default="identity")
    gi.add_argument("-p", "--peers", default="peers.json")

    gin = sub.add_parser(
        "generate-initiator", help="generate the event-initiator identity"
    )
    gin.add_argument("--encrypt", action="store_true")
    gin.add_argument("-o", "--output-dir", default=".")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command is None:
        build_parser().print_help()
        return 1
    from mpcium_tpu.cli import commands

    return commands.dispatch(args)


if __name__ == "__main__":
    sys.exit(main())
