"""Implementations of the ops CLI subcommands.

Reference behaviors mirrored (see SURVEY.md §2.1 #2):
- generate-peers  → peers.json {name: uuid}           (generate-peers.go:18-64)
- register-peers  → registry dir / peers db           (register-peers.go:16-70)
- generate-identity → <node>_identity.json + key file (generate-identity.go)
- generate-initiator → initiator keypair + metadata   (generate-initiator.go)
"""
from __future__ import annotations

import getpass
import json
import os
import platform
import uuid
from datetime import datetime, timezone


def dispatch(args) -> int:
    return {
        "generate-peers": _generate_peers,
        "register-peers": _register_peers,
        "generate-identity": _generate_identity,
        "generate-initiator": _generate_initiator,
    }[args.command](args)


def _generate_peers(args) -> int:
    peers = {f"node{i}": str(uuid.uuid4()) for i in range(args.number)}
    with open(args.output, "w") as f:
        json.dump(peers, f, indent=2)
    print(f"wrote {args.output} with {args.number} peers")
    return 0


def _register_peers(args) -> int:
    with open(args.peers) as f:
        peers = json.load(f)
    if getattr(args, "broker", ""):
        from mpcium_tpu.store.broker_kv import BrokerKV
        from mpcium_tpu.transport.tcp import TcpClient, parse_addrs

        host, port = parse_addrs(args.broker)[0]
        cli = TcpClient(
            host, port,
            auth_token=args.broker_token or None,
            encrypt=args.broker_encrypt,
            reconnect=False,
        )
        try:
            kv = BrokerKV(cli)
            for name, node_id in peers.items():
                kv.put(f"mpc_peers/{name}", node_id.encode())
        finally:
            cli.close()
        print(f"registered {len(peers)} peers into broker {args.broker}")
        return 0
    from mpcium_tpu.store.kvstore import FileKV

    kv = FileKV(args.registry_dir)
    for name, node_id in peers.items():
        kv.put(f"mpc_peers/{name}", node_id.encode())
    print(f"registered {len(peers)} peers into {args.registry_dir}")
    return 0


def _require_password() -> str:
    """Reference password policy: ≥12 chars incl. a special char
    (generate-identity.go:53-63)."""
    pw = getpass.getpass("passphrase: ")
    if len(pw) < 12 or not any(not c.isalnum() for c in pw):
        raise SystemExit(
            "passphrase must be ≥12 chars and contain a special character"
        )
    if getpass.getpass("confirm passphrase: ") != pw:
        raise SystemExit("passphrases do not match")
    return pw


def _generate_identity(args) -> int:
    from mpcium_tpu.identity.identity import generate_identity

    with open(args.peers) as f:
        peers = json.load(f)
    if args.node not in peers:
        raise SystemExit(f"node {args.node!r} not present in {args.peers}")
    password = _require_password() if args.encrypt else None
    ident = generate_identity(args.node, args.identity_dir, passphrase=password)
    print(f"wrote {args.identity_dir}/{args.node}_identity.json")
    print(
        f"wrote {args.identity_dir}/{args.node}_private.key"
        + (".enc" if password else "")
    )
    print(f"public key: {ident.public_key.hex()}")
    return 0


def _generate_initiator(args) -> int:
    from pathlib import Path

    from mpcium_tpu.identity.identity import InitiatorKey

    password = _require_password() if args.encrypt else None
    key = InitiatorKey.generate()
    out = Path(args.output_dir)
    out.mkdir(parents=True, exist_ok=True)
    key.save(out / "event_initiator.key", passphrase=password)
    meta = {
        "public_key": key.public_bytes.hex(),
        "creator": os.environ.get("USER", "unknown"),
        "host": platform.node(),
        "os": f"{platform.system()} {platform.release()}",
        "created_at": datetime.now(timezone.utc).isoformat(),
    }
    (out / "event_initiator.json").write_text(json.dumps(meta, indent=2))
    print(f"wrote {out}/event_initiator.key" + (".enc" if password else ""))
    print(f"wrote {out}/event_initiator.json")
    print(f"initiator public key: {meta['public_key']}")
    print("set event_initiator_pubkey to this value in every node's config.yaml")
    return 0
