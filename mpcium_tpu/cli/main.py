"""`mpcium-tpu` — the node daemon entry point.

Reference analogue: cmd/mpcium/main.go (`mpcium start -n node0`). The full
daemon wiring lands with the node/consumers layers; this module always
provides a working console entry.
"""
from __future__ import annotations

import argparse
import os
import sys

from mpcium_tpu import __version__


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpcium-tpu",
        description="TPU-native MPC/TSS wallet node daemon",
    )
    sub = p.add_subparsers(dest="command")
    start = sub.add_parser("start", help="run an MPC node")
    start.add_argument("-n", "--name", required=True, help="node name")
    start.add_argument("--config", default="config.yaml", help="config file")
    start.add_argument(
        "--decrypt-private-key",
        action="store_true",
        help="prompt for passphrase to decrypt the node identity key",
    )
    start.add_argument("--debug", action="store_true")
    broker = sub.add_parser(
        "broker", help="run the message broker (the nats-server analogue)"
    )
    broker.add_argument("--host", default="127.0.0.1")
    broker.add_argument("--port", type=int, default=4333)
    broker.add_argument(
        "--journal", default="",
        help="queue journal path (durable work queues; '' = in-memory)",
    )
    broker.add_argument(
        "--token", default=os.environ.get("MPCIUM_BROKER_TOKEN", ""),
        help="shared auth token, plaintext or sha256:<hex> "
        "(or MPCIUM_BROKER_TOKEN)",
    )
    broker.add_argument(
        "--follow", default="",
        help="run as a hot standby mirroring the primary at host:port "
        "(takes over when the primary dies; clients list both endpoints "
        "in broker_standbys)",
    )
    broker.add_argument(
        "--encrypt", action="store_true",
        default=os.environ.get("MPCIUM_BROKER_ENCRYPT", "").lower()
        not in ("", "0", "false", "no"),
        help="AEAD-encrypt every connection (X25519 + token-bound "
        "ChaCha20-Poly1305; or MPCIUM_BROKER_ENCRYPT=1)",
    )
    sub.add_parser("version", help="print version")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "version":
        print(__version__)
        return 0
    if args.command == "start":
        from mpcium_tpu.node.daemon import run_node

        return run_node(
            name=args.name,
            config_path=args.config,
            decrypt_private_key=args.decrypt_private_key,
            debug=args.debug,
        )
    if args.command == "broker":
        from mpcium_tpu.node.daemon import run_broker

        return run_broker(host=args.host, port=args.port,
                          journal=args.journal, token=args.token,
                          encrypt=args.encrypt, follow=args.follow)
    build_parser().print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
