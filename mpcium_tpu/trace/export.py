"""Chrome-trace-event export: merge per-node span buffers into one JSON
document loadable in Perfetto / chrome://tracing.

Mapping: pid = node (one process row per node), tid = the span's track
(session id or scheduler lane). Both get human names via ``M`` metadata
events so Perfetto shows ``node0`` / ``lane:interactive`` instead of
bare integers. Timestamps are microseconds relative to the earliest
span in the document (monotonic clocks share a timebase in-process, so
cross-node alignment is exact for LocalCluster traces).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

TRACE_FORMAT = "chrome-trace-events"


def chrome_trace(
    per_node: Dict[str, Tuple[List[dict], int]],
    meta: Optional[dict] = None,
    extra_events: Optional[List[dict]] = None,
) -> dict:
    """Build the Chrome trace document from ``{node: (spans, dropped)}``
    (the shape ``recorder.snapshot_all`` returns). ``extra_events`` are
    pre-built Chrome events appended verbatim — the perf ledger's
    counter track (``perf.report.counter_track``) rides in here so the
    bench trajectory lands in the same Perfetto document."""
    events: List[dict] = []
    pid_of: Dict[str, int] = {}
    tid_of: Dict[Tuple[str, str], int] = {}
    all_spans: List[Tuple[str, dict]] = [
        (node, s) for node, (spans, _d) in sorted(per_node.items())
        for s in spans
    ]
    t_base = min((s["t0_ns"] for _n, s in all_spans), default=0)

    for node, (_spans, _dropped) in sorted(per_node.items()):
        pid_of[node] = len(pid_of) + 1
        events.append({
            "ph": "M", "name": "process_name", "pid": pid_of[node], "tid": 0,
            "args": {"name": node},
        })

    for node, s in all_spans:
        pid = pid_of[node]
        track = str(s.get("tid") or "main")
        key = (node, track)
        if key not in tid_of:
            tid_of[key] = len([k for k in tid_of if k[0] == node]) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tid_of[key], "args": {"name": track},
            })
        tid = tid_of[key]
        ts_us = (s["t0_ns"] - t_base) / 1e3
        args = dict(s.get("attrs") or {})
        args["trace_id"] = s["trace_id"]
        args["span_id"] = s["span_id"]
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        if s.get("kind") == "i":
            events.append({
                "ph": "i", "name": s["name"], "pid": pid, "tid": tid,
                "ts": ts_us, "s": "t", "args": args,
            })
        else:
            events.append({
                "ph": "X", "name": s["name"], "pid": pid, "tid": tid,
                "ts": ts_us, "dur": max(0.0, (s["t1_ns"] - s["t0_ns"]) / 1e3),
                "args": args,
            })

    if extra_events:
        events.extend(extra_events)

    other = {
        "format": TRACE_FORMAT,
        "dropped_spans": {
            node: d for node, (_s, d) in sorted(per_node.items())
        },
    }
    if meta:
        other.update(meta)
    return {"traceEvents": events, "displayTimeUnit": "ms", "otherData": other}
