"""mpctrace: flight recorder + Perfetto export over utils.tracing.

``arm()`` turns tracing on with the per-node flight recorders as the
sink — the always-on mode every cluster/daemon runs in. The engine
flagship path never arms, so the bench number rides the no-op gate.
See OBSERVABILITY.md for the span model and how-to.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..utils import tracing
from . import recorder
from .export import chrome_trace
from .schema import TraceSchemaError, validate_chrome

__all__ = [
    "arm", "disarm", "armed", "snapshot_chrome",
    "chrome_trace", "validate_chrome", "TraceSchemaError", "recorder",
]


def arm(
    node_ids: Optional[List[str]] = None,
    capacity: Optional[int] = None,
    dump_dir: Optional[str] = None,
) -> None:
    """Enable tracing with flight recorders as the sink. Resets the
    buffers of ``node_ids`` (so reused node names start clean) and
    optionally configures the incident dump directory."""
    if node_ids is not None or capacity is not None:
        recorder.reset(node_ids, capacity=capacity)
    recorder.set_dump_dir(dump_dir)
    tracing.enable(sink=recorder.record)
    tracing.set_incident_hook(recorder.dump_incident)


def disarm() -> None:
    tracing.disable()
    recorder.set_dump_dir(None)


def armed() -> bool:
    return tracing.enabled()


def snapshot_chrome(
    node_ids: Optional[List[str]] = None,
    clear: bool = False,
    meta: Optional[Dict[str, object]] = None,
) -> dict:
    """Merge per-node flight recorders into one Chrome-trace document
    (pid=node, tid=session/lane) — the payload LocalCluster, drills and
    soak reports embed."""
    return chrome_trace(recorder.snapshot_all(node_ids, clear=clear), meta=meta)
