"""Chrome trace-event schema validation (zero-dependency).

What Perfetto/chrome://tracing actually require of the JSON object
format, written down as a checker so the committed sample trace and
every drill/soak-embedded trace can be validated in CI (`make
trace-check`) without a jsonschema dependency.
"""
from __future__ import annotations

from typing import List

_PHASES_DUR = {"X"}
_PHASES_INSTANT = {"i", "I"}
_PHASES_META = {"M"}
_KNOWN = _PHASES_DUR | _PHASES_INSTANT | _PHASES_META | {
    "B", "E", "C", "b", "e", "n", "s", "t", "f",
}


class TraceSchemaError(ValueError):
    pass


def validate_chrome(doc: object) -> int:
    """Validate a Chrome trace-event document; returns the number of
    events, raises TraceSchemaError with every problem found."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        raise TraceSchemaError(f"top level must be an object, got {type(doc).__name__}")
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise TraceSchemaError("traceEvents must be a list")
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _KNOWN:
            errors.append(f"{where}: unknown ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errors.append(f"{where}: missing/empty name")
        if not isinstance(ev.get("pid"), (int, str)):
            errors.append(f"{where}: missing pid")
        if ph in _PHASES_META:
            if not isinstance(ev.get("args"), dict):
                errors.append(f"{where}: metadata event needs args object")
            continue
        if not isinstance(ev.get("tid"), (int, str)):
            errors.append(f"{where}: missing tid")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: ts must be a non-negative number")
        if ph in _PHASES_DUR:
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: complete event needs non-negative dur")
        if "args" in ev and not isinstance(ev["args"], dict):
            errors.append(f"{where}: args must be an object")
    if errors:
        raise TraceSchemaError(
            f"{len(errors)} schema violation(s): " + "; ".join(errors[:10])
        )
    return len(events)
