"""Per-node flight recorders: bounded ring buffers of finished spans.

Always on once armed, never unbounded: each node keeps the most recent
``capacity`` spans (default 4096 ≈ a few minutes of soak traffic) and an
exact dropped-span counter, so a post-mortem knows both what happened
recently and how much history scrolled off. Recorders are keyed by node
id in a module-level registry because a LocalCluster runs all nodes in
one process; ``mpcium_tpu.trace.arm()`` installs ``record`` as the
tracing sink and routes each span to its node's buffer.

Incident dumps: when configured with ``set_dump_dir``, an incident
(shed/timeout/drill failure) writes the merged Chrome-trace JSON to
``trace_incident_<kind>_<n>.json`` — capped at ``_DUMP_LIMIT`` files per
process so a shed storm cannot fill a disk.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

DEFAULT_CAPACITY = 4096
_DUMP_LIMIT = 8


class FlightRecorder:
    """Bounded ring buffer of span dicts with an exact dropped count."""

    def __init__(self, node_id: str, capacity: int = DEFAULT_CAPACITY) -> None:
        self.node_id = node_id
        self.capacity = capacity
        self._lock = threading.Lock()
        self._spans: Deque[dict] = deque(maxlen=capacity)
        self.dropped = 0

    def record(self, span: dict) -> None:
        with self._lock:
            if len(self._spans) == self.capacity:
                self.dropped += 1
            self._spans.append(span)

    def snapshot(self, clear: bool = False) -> Tuple[List[dict], int]:
        with self._lock:
            spans = list(self._spans)
            dropped = self.dropped
            if clear:
                self._spans.clear()
                self.dropped = 0
        return spans, dropped


_lock = threading.Lock()
_recorders: Dict[str, FlightRecorder] = {}
_capacity = DEFAULT_CAPACITY
_dump_dir: Optional[str] = None
_dump_count = 0


def recorder_for(node_id: str) -> FlightRecorder:
    with _lock:
        rec = _recorders.get(node_id)
        if rec is None:
            rec = FlightRecorder(node_id, _capacity)
            _recorders[node_id] = rec
        return rec


def record(span: dict) -> None:
    """The tracing sink: route a finished span to its node's buffer."""
    recorder_for(span.get("node") or "local").record(span)


def reset(node_ids: Optional[List[str]] = None, capacity: Optional[int] = None) -> None:
    """Drop buffers (all, or just the named nodes). A new LocalCluster
    resets its node ids so traces never bleed between test clusters that
    reuse node names."""
    global _capacity
    with _lock:
        if capacity is not None:
            _capacity = capacity
        if node_ids is None:
            _recorders.clear()
        else:
            for nid in node_ids:
                _recorders.pop(nid, None)


def snapshot_all(
    node_ids: Optional[List[str]] = None, clear: bool = False
) -> Dict[str, Tuple[List[dict], int]]:
    """Per-node (spans, dropped) for the requested nodes (default all)."""
    with _lock:
        items = [
            (nid, rec) for nid, rec in sorted(_recorders.items())
            if node_ids is None or nid in node_ids
        ]
    return {nid: rec.snapshot(clear=clear) for nid, rec in items}


def set_dump_dir(path: Optional[str]) -> None:
    global _dump_dir, _dump_count
    _dump_dir = path
    _dump_count = 0


def dump_incident(kind: str, node: str, attrs: dict) -> None:
    """Incident hook target: write the merged buffers to the configured
    dump dir (bounded count). Never raises — a failed dump must not
    take the serving path down with it."""
    global _dump_count
    if _dump_dir is None:
        return
    with _lock:
        if _dump_count >= _DUMP_LIMIT:
            return
        _dump_count += 1
        n = _dump_count
    from .export import chrome_trace

    try:
        snap = snapshot_all()
        doc = chrome_trace(
            snap,
            meta={"incident": kind, "node": node, "attrs": attrs},
        )
        os.makedirs(_dump_dir, exist_ok=True)
        fn = os.path.join(_dump_dir, f"trace_incident_{kind}_{n}.json")
        with open(fn, "w") as fh:
            json.dump(doc, fh)
        from ..utils import log

        # the summary line an operator greps before opening the JSON:
        # how much history the dump holds and how much scrolled off
        log.info(
            "trace incident dumped", kind=kind, node=node, file=fn,
            spans=sum(len(s) for s, _d in snap.values()),
            ring_dropped={nid: d for nid, (_s, d) in sorted(snap.items())
                          if d},
        )
    except OSError:
        from ..utils import log

        log.warn("trace incident dump failed", kind=kind, dir=_dump_dir)
