"""mpctrace core: a zero-dependency span model for cross-node tracing.

Span identity is ``trace_id`` / ``span_id`` / ``parent_id``; clocks are
``time.monotonic_ns`` so spans from every node of an in-process cluster
share one timebase and survive wall-clock steps. Attributes are public
metadata ONLY: attribute names are screened against the mpclint secret
taxonomy at record time and refused (value replaced, never logged)
unless the name was explicitly declassified via ``declassify_attr`` —
the runtime twin of the ``# mpcflow: declassified`` registry.

The module-level ``_ENABLED`` flag is the no-op fast path: with tracing
disabled (the default — the flagship bench number is measured this way)
``span()`` returns a shared inert singleton, ``emit()`` returns before
building anything, and engine phase timers skip their device syncs, so
transcripts are bit-identical and overhead is a single attribute load.

Sinks receive finished spans as plain dicts (see ``_span_dict``); the
flight recorder in ``mpcium_tpu.trace`` installs itself as the sink via
``enable(sink=...)``. This module deliberately imports nothing from the
rest of the project so every layer (wire, engines, scheduler, logging)
can depend on it without cycles.

Determinism note (MPL2xx): ids come from a process-local counter and a
keyed hash of public names — no ambient entropy, no wall clock — so a
traced protocol run makes exactly the same decisions as an untraced one.
"""
from __future__ import annotations

import hashlib
import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

now_ns = time.monotonic_ns

# -- the no-op fast path gate -------------------------------------------------
_ENABLED = False
_sink: Optional[Callable[[dict], None]] = None
_incident_hook: Optional[Callable[[str, str, dict], None]] = None

_ids = itertools.count(1)
_state = threading.local()  # .stack: List[Span] of open spans in this thread

# attribute names that hit the secret taxonomy but were reviewed as
# public metadata; name -> reason (the declassify registry, runtime half)
_DECLASSIFIED_ATTRS: Dict[str, str] = {}

_ATTR_SCALARS = (str, int, float, bool, type(None))


def enabled() -> bool:
    return _ENABLED


def enable(sink: Optional[Callable[[dict], None]] = None) -> None:
    """Turn tracing on. ``sink`` is called with each finished span dict;
    without one, spans only feed context propagation (log correlation,
    wire context) and are otherwise discarded."""
    global _ENABLED, _sink
    _sink = sink
    _ENABLED = True


def disable() -> None:
    global _ENABLED, _sink, _incident_hook
    _ENABLED = False
    _sink = None
    _incident_hook = None


def set_incident_hook(hook: Optional[Callable[[str, str, dict], None]]) -> None:
    """Install the incident callback: ``hook(kind, node, attrs)``. The
    flight recorder uses it to dump buffers on shed/timeout/failure."""
    global _incident_hook
    _incident_hook = hook


def declassify_attr(name: str, reason: str) -> None:
    """Register a taxonomy-hitting attribute name as reviewed-public.
    The reason is mandatory and kept for the audit surface."""
    if not reason or not reason.strip():
        raise ValueError(f"declassify_attr({name!r}) requires a reason")
    _DECLASSIFIED_ATTRS[name] = reason


def declassified_attrs() -> Dict[str, str]:
    return dict(_DECLASSIFIED_ATTRS)


def _is_secret_attr(name: str) -> bool:
    # lazy import: taxonomy is stdlib-only but lives in the analysis
    # package; importing it here at module load would couple every
    # tracing user to the analyzer package's import time
    from ..analysis.taxonomy import is_secret_name

    return is_secret_name(name)


def clean_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    """Attribute hygiene: secret-taxonomy names are refused (value
    replaced with a marker, the value itself never retained) unless
    declassified; non-scalar values are reduced to their type name so
    no object repr can smuggle key material into a trace."""
    out: Dict[str, Any] = {}
    for k, v in attrs.items():
        if k not in _DECLASSIFIED_ATTRS and _is_secret_attr(k):
            out[k] = "<refused:secret-name>"
            continue
        if isinstance(v, _ATTR_SCALARS):
            out[k] = v
        else:
            out[k] = f"<obj:{type(v).__name__}>"
    return out


def trace_id_for(name: str) -> str:
    """Deterministic trace id from a public name (session id, drill
    name): every node derives the same id for the same session without
    coordination, so merged views group correctly even for spans that
    never rode a wire envelope."""
    return hashlib.sha256(b"mpctrace|" + name.encode()).hexdigest()[:16]


def _next_span_id() -> str:
    return f"{next(_ids):016x}"


def _stack() -> List["Span"]:
    st = getattr(_state, "stack", None)
    if st is None:
        st = []
        _state.stack = st
    return st


class Span:
    """An open span. Finish with ``end()`` or use ``span()`` as a
    context manager. Not thread-safe; a span belongs to one thread."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "node", "tid", "t0_ns", "t1_ns", "kind", "attrs", "_pushed",
    )

    def __init__(
        self,
        name: str,
        *,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        node: str = "local",
        tid: str = "main",
        kind: str = "X",
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        st = _stack()
        top = st[-1] if st else None
        self.name = name
        self.trace_id = trace_id or (top.trace_id if top else trace_id_for(name))
        self.parent_id = parent_id if parent_id is not None else (
            top.span_id if top else None
        )
        self.span_id = _next_span_id()
        # "local"/"main" are the unset sentinels: inherit from the
        # enclosing span so nested spans land on the right track
        self.node = top.node if (node == "local" and top is not None) else node
        self.tid = top.tid if (tid == "main" and top is not None) else tid
        self.t0_ns = now_ns()
        self.t1_ns = 0
        self.kind = kind
        self.attrs = clean_attrs(attrs) if attrs else {}
        self._pushed = False

    def set(self, **attrs: Any) -> None:
        self.attrs.update(clean_attrs(attrs))

    def end(self) -> None:
        self.t1_ns = now_ns()
        sink = _sink
        if sink is not None:
            sink(_span_dict(self))

    def __enter__(self) -> "Span":
        _stack().append(self)
        self._pushed = True
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._pushed:
            st = _stack()
            if st and st[-1] is self:
                st.pop()
            elif self in st:  # defensive: unbalanced exit
                st.remove(self)
            self._pushed = False
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self.end()


class _NoopSpan:
    """Shared inert span for the disabled fast path."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        return None

    def set(self, **attrs: Any) -> None:
        return None

    def end(self) -> None:
        return None


NOOP_SPAN = _NoopSpan()


_SPAN_KW = ("trace_id", "parent_id", "node", "tid", "kind", "attrs")


def span(name: str, **kw: Any):
    """Open a span (context manager). Known keywords (``trace_id``,
    ``parent_id``, ``node``, ``tid``, ``kind``, ``attrs``) configure the
    span; anything else becomes an attribute. No-op singleton when
    disabled — the fast path is this one flag check."""
    if not _ENABLED:
        return NOOP_SPAN
    cfg = {k: kw.pop(k) for k in _SPAN_KW if k in kw}
    if kw:
        cfg["attrs"] = {**kw, **(cfg.get("attrs") or {})}
    return Span(name, **cfg)


def _span_dict(s: Span) -> dict:
    return {
        "name": s.name,
        "trace_id": s.trace_id,
        "span_id": s.span_id,
        "parent_id": s.parent_id,
        "node": s.node,
        "tid": s.tid,
        "t0_ns": s.t0_ns,
        "t1_ns": s.t1_ns,
        "kind": s.kind,
        "attrs": s.attrs,
    }


def emit(
    name: str,
    t0_ns: int,
    t1_ns: int,
    *,
    node: str = "local",
    tid: str = "main",
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    kind: str = "X",
    **attrs: Any,
) -> None:
    """Record an already-finished interval as a span (retroactive form:
    the scheduler turns queue-entry lifetimes into spans at dispatch or
    shed time without holding live span objects in its entries)."""
    if not _ENABLED:
        return
    sink = _sink
    if sink is None:
        return
    sink({
        "name": name,
        "trace_id": trace_id or trace_id_for(name),
        "span_id": _next_span_id(),
        "parent_id": parent_id,
        "node": node,
        "tid": tid,
        "t0_ns": int(t0_ns),
        "t1_ns": int(t1_ns),
        "kind": kind,
        "attrs": clean_attrs(attrs) if attrs else {},
    })


def instant(name: str, *, node: str = "local", tid: str = "main",
            trace_id: Optional[str] = None, **attrs: Any) -> None:
    """Zero-duration marker event."""
    if not _ENABLED:
        return
    t = now_ns()
    emit(name, t, t, node=node, tid=tid, trace_id=trace_id, kind="i", **attrs)


def incident(kind: str, *, node: str = "local", tid: str = "main",
             **attrs: Any) -> None:
    """Mark an operational incident (shed, timeout, drill failure).
    Emits an instant span and fires the flight-recorder dump hook."""
    if not _ENABLED:
        return
    instant(f"incident:{kind}", node=node, tid=tid, **attrs)
    hook = _incident_hook
    if hook is not None:
        hook(kind, node, clean_attrs(attrs) if attrs else {})


def current_ids() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the innermost open span in this thread,
    or None. Used by utils.log for log/trace correlation."""
    if not _ENABLED:
        return None
    st = getattr(_state, "stack", None)
    if not st:
        return None
    top = st[-1]
    return (top.trace_id, top.span_id)


def wire_context() -> Optional[Dict[str, str]]:
    """Trace context in wire form ({"t": trace_id, "s": span_id}) for
    the optional envelope field, or None when no span is open."""
    ids = current_ids()
    if ids is None:
        return None
    return {"t": ids[0], "s": ids[1]}


class PhaseTimer:
    """Engine-side phase instrumentation: device-phase spans with a sync
    at each phase boundary, ONLY when tracing is on (or a legacy
    ``phase_times`` dict was requested). ``sync`` is supplied by the
    engine (``jax.block_until_ready``) so this module stays jax-free.

    ``mark(name, *tensors)`` closes the interval since the previous mark
    as a span named ``phase:<name>``; with tracing disabled and no
    ``phase_times`` dict, ``mark`` is one attribute load and a return —
    no sync, no allocation — which is what keeps untraced transcripts
    bit-identical.
    """

    __slots__ = ("on", "phases", "_sync", "node", "tid", "trace_id",
                 "parent_id", "last_ns", "_last_span_id")

    def __init__(
        self,
        engine: str,
        sync: Callable[..., Any],
        *,
        phase_times: Optional[Dict[str, float]] = None,
        node: str = "local",
        tid: Optional[str] = None,
    ) -> None:
        self.on = _ENABLED or phase_times is not None
        self.phases = phase_times
        self._sync = sync
        self.node = node
        self.tid = tid or engine
        self.trace_id = trace_id_for(engine) if self.on else None
        ids = current_ids()
        self.parent_id = ids[1] if ids else None
        if ids:
            self.trace_id = ids[0]
        self.last_ns = now_ns() if self.on else 0
        self._last_span_id: Optional[str] = None

    def mark(self, name: str, *tensors: Any, **attrs: Any) -> None:
        if not self.on:
            return
        if tensors:
            self._sync(tensors)
        t = now_ns()
        if self.phases is not None:
            self.phases[name] = (t - self.last_ns) / 1e9
            # derived sub-phase scalars (the OT host/device split) keep
            # their legacy flat keys so old consumers read the same dict
            for k, v in attrs.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self.phases[f"{name}_{k}"] = v
        emit(
            f"phase:{name}", self.last_ns, t,
            node=self.node, tid=self.tid,
            trace_id=self.trace_id, parent_id=self.parent_id,
            **attrs,
        )
        self.last_ns = t


def phase_share(spans: List[dict]) -> Dict[str, float]:
    """Fold phase spans back into the bench-table shape: span
    ``phase:<name>`` -> ``{name: seconds}`` and pipeline host stages
    ``host:<name>`` -> ``{host_<name>: seconds}``, with numeric span
    attrs flattened as ``<name>_<attr>`` (the OT host/device split).
    This is how bench.py reproduces its phase-share fields from the
    trace instead of the old private dict; without the ``host:`` fold a
    cohorted run's wire stages would silently vanish from the table.

    A run that produced no phase spans (watchdog fallback, engine died
    before its first mark) returns the explicit ``{"no_spans": 0.0}``
    marker instead of an empty dict, so downstream merges keep their
    keys and a reader can tell "nothing measured" from "lost"."""
    out: Dict[str, float] = {}
    for s in spans:
        if s["name"].startswith("phase:"):
            name = s["name"][len("phase:"):]
        elif s["name"].startswith("host:"):
            name = "host_" + s["name"][len("host:"):]
        else:
            continue
        out[name] = out.get(name, 0.0) + (s["t1_ns"] - s["t0_ns"]) / 1e9
        for k, v in s.get("attrs", {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"{name}_{k}"] = v
    if not out:
        return {"no_spans": 0.0}
    return out


def device_idle_fraction(spans: List[dict]) -> float:
    """Fraction of the traced window in which the device had NO
    ``phase:*`` span open — the idle metric ROADMAP item 4's zero-idle
    pipeline is judged by.

    The window spans from the first to the last edge over BOTH device
    (``phase:*``) and pipeline host-stage (``host:*``) spans, so host
    wire time at the edges counts against the device. Overlapping
    device spans (counter-phase cohorts) are unioned, not summed —
    overlap is exactly the effect being measured. Returns 0.0 when no
    device spans exist (nothing measured ⇒ nothing claimable)."""
    dev: List[tuple] = []
    lo = hi = None
    for s in spans:
        name = s.get("name", "")
        if not (name.startswith("phase:") or name.startswith("host:")):
            continue
        t0, t1 = s["t0_ns"], s["t1_ns"]
        lo = t0 if lo is None else min(lo, t0)
        hi = t1 if hi is None else max(hi, t1)
        if name.startswith("phase:"):
            dev.append((t0, t1))
    if not dev or hi is None or hi <= lo:
        return 0.0
    dev.sort()
    busy = 0
    cur0, cur1 = dev[0]
    for t0, t1 in dev[1:]:
        if t0 > cur1:
            busy += cur1 - cur0
            cur0, cur1 = t0, t1
        else:
            cur1 = max(cur1, t1)
    busy += cur1 - cur0
    return max(0.0, 1.0 - busy / (hi - lo))
