"""Runtime-side mpclint/mpcflow annotations (zero-cost at runtime).

``@locked_by(lock, *fields)`` declares which instance attributes a class
guards under which lock. mpclint's lock-discipline rule (MPL301) reads
the decorator *statically* and flags any write to a declared field that
is not inside ``with self.<lock>:`` (``__init__`` is exempt — objects
under construction are unpublished). At runtime the decorator only
records the declaration on the class, so annotated and unannotated
builds behave identically.

A method whose whole body runs under the lock (a helper only called from
locked contexts) is marked on its ``def`` line::

    def _checkpoint(self, out):  # mpclint: holds=_lock
        ...

See STATIC_ANALYSIS.md for the full registry.
"""
from __future__ import annotations

from typing import Callable, Dict, Generic, Tuple, TypeVar

T = TypeVar("T", bound=type)
_V = TypeVar("_V")


class Secret(Generic[_V]):
    """Type-annotation marker: the annotated value IS secret material,
    whatever its spelling. mpcflow (analysis/flow/taint.py) reads it
    statically — a parameter or return annotated ``Secret[...]`` seeds
    the MPF7xx taint lattice at every call boundary::

        def load_share(self, ...) -> "Secret[KeygenShare]": ...
        def seal(self, plaintext: "Secret[bytes]") -> bytes: ...

    At runtime it is inert: ``Secret[bytes]`` is just ``bytes`` to every
    type checker via the alias below, and nothing is instantiated. Use
    string-form annotations (as above) so importing modules stay free of
    typing machinery at import time.
    """

    def __class_getitem__(cls, item):
        return item

# thread-name prefixes the tests' conftest leak-checker treats as
# process-lifetime singletons; MPL502 accepts threads named under them
# as "registered" (tests/conftest.py no_leaked_nondaemon_threads)
REGISTERED_THREAD_PREFIXES: Tuple[str, ...] = ("ot-host",)


def locked_by(lock: str, *fields: str) -> Callable[[T], T]:
    """Class decorator: ``fields`` may only be written while holding
    ``self.<lock>``. Stackable for classes with several locks."""

    def wrap(cls: T) -> T:
        reg: Dict[str, Tuple[str, ...]] = dict(
            getattr(cls, "__mpclint_locked_by__", {})
        )
        reg[lock] = tuple(dict.fromkeys(reg.get(lock, ()) + fields))
        cls.__mpclint_locked_by__ = reg  # type: ignore[attr-defined]
        return cls

    return wrap
