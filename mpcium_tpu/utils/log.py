"""Structured logging (the zerolog analogue, reference pkg/logger).

Key/value logging with dev (human console) and production (JSON lines)
modes; errors carry stack info. Wraps stdlib logging so host applications
can re-route handlers.
"""
from __future__ import annotations

import json
import logging
import os
import re
import sys
import time
import traceback
from typing import Any

from . import tracing

_logger = logging.getLogger("mpcium_tpu")
_production = False


def init(production: bool | None = None, level: str = "INFO") -> None:
    """Configure global logging. Dev → console k=v lines; production →
    JSON lines on stderr (reference logger.go:12-27)."""
    global _production
    if production is None:
        production = os.environ.get("MPCIUM_ENV") == "production"
    _production = production
    _logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    _logger.handlers.clear()
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter("%(message)s"))
    _logger.addHandler(h)
    _logger.propagate = False


def _emit(level: int, msg: str, kv: dict) -> None:
    if not _logger.handlers:
        init()
    # log/trace correlation: when a span is open on this thread, every
    # record carries its ids so a log line can be found in the trace
    ids = tracing.current_ids()
    if ids is not None:
        kv.setdefault("trace_id", ids[0])
        kv.setdefault("span_id", ids[1])
    if _production:
        record = {
            "level": logging.getLevelName(level).lower(),
            "time": time.time(),
            "message": msg,
            **{k: _safe(v) for k, v in kv.items()},
        }
        _logger.log(level, json.dumps(record, sort_keys=True))
    else:
        pairs = " ".join(f"{k}={_safe(v)}" for k, v in kv.items())
        _logger.log(
            level, f"{logging.getLevelName(level):<5} {msg}" + (f" | {pairs}" if pairs else "")
        )


def _is_secret_name(name: str) -> bool:
    # lazy import: taxonomy is stdlib-only, but keep log importable
    # without dragging the analysis package in at interpreter start
    from ..analysis.taxonomy import is_secret_name

    # the taxonomy tokenizer splits snake_case; type names are CamelCase
    # (NonceShare), so de-camel before asking
    snake = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", name)
    return is_secret_name(name) or is_secret_name(snake)


def _safe(v: Any):
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, (str, int, float, bool, type(None))):
        return v
    # refuse to repr() objects that look like key material: a type or
    # attribute name hitting the secret taxonomy means the default repr
    # could serialize secrets into a log line (MPL101's runtime twin)
    tname = type(v).__name__
    attr_names = list(getattr(v, "__dict__", ()) or ())
    attr_names += [a for a in getattr(type(v), "__slots__", ()) or ()]
    if _is_secret_name(tname) or any(_is_secret_name(a) for a in attr_names):
        return f"<redacted:{tname}>"
    return repr(v)


def debug(msg: str, **kv) -> None:
    _emit(logging.DEBUG, msg, kv)


def info(msg: str, **kv) -> None:
    _emit(logging.INFO, msg, kv)


def warn(msg: str, **kv) -> None:
    _emit(logging.WARNING, msg, kv)


def error(msg: str, **kv) -> None:
    """Adds caller stack context (reference logger.go:108)."""
    kv.setdefault("stack", "".join(traceback.format_stack(limit=6)[:-1])[-400:])
    _emit(logging.ERROR, msg, kv)


def fatal(msg: str, **kv) -> None:
    _emit(logging.CRITICAL, msg, kv)
    raise SystemExit(1)
