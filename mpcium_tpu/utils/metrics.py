"""Lightweight in-process metrics: counters, gauges, histograms.

The serving front (batch scheduler, event consumer, soak harness) needs
honest numbers — queue depth per lane, batch fill ratio, dispatch age,
shed counts, end-to-end latency percentiles — without dragging in a
metrics dependency. This module is deliberately tiny: thread-safe
get-or-create by name, cheap O(1) updates on the hot path, and a
``snapshot()`` dict suitable for JSON health surfaces and soak reports.

Histograms keep exact count/sum/min/max plus a bounded reservoir of
recent observations (default 8192) for percentile estimates; at soak
scale that is a sliding-window percentile, which is what an SLO monitor
wants anyway.
"""
from __future__ import annotations

import math
import threading
from collections import deque
from typing import Deque, Dict, Optional, Tuple


class Counter:
    """Monotonic counter. ``inc`` only; resets never."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Instantaneous value. ``set``/``inc``/``dec``."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Exact count/sum/min/max + bounded reservoir for percentiles.

    The reservoir is a deque of the most recent ``reservoir`` samples —
    a sliding window, not uniform sampling. For SLO latency monitoring
    the recent window is the interesting one.
    """

    def __init__(self, name: str, reservoir: int = 8192) -> None:
        self.name = name
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._samples: Deque[float] = deque(maxlen=reservoir)

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            self._samples.append(v)

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile over the reservoir window; q in [0, 100]."""
        if not 0 <= q <= 100:
            raise ValueError(f"percentile out of range: {q}")
        with self._lock:
            if not self._samples:
                return None
            ordered = sorted(self._samples)
        rank = max(1, math.ceil(q / 100.0 * len(ordered)))
        return ordered[rank - 1]

    @property
    def mean(self) -> Optional[float]:
        with self._lock:
            return self.sum / self.count if self.count else None

    @staticmethod
    def _rank(ordered, q: float) -> Optional[float]:
        if not ordered:
            return None
        rank = max(1, math.ceil(q / 100.0 * len(ordered)))
        return ordered[rank - 1]

    def summary(self) -> Dict[str, Optional[float]]:
        # one lock acquisition, one reservoir copy, ONE sort for all
        # three quantiles (percentile() re-sorts per call — fine for a
        # spot read, wasteful for every snapshot/health publish)
        with self._lock:
            count, total = self.count, self.sum
            lo, hi = self.min, self.max
            ordered = sorted(self._samples)
        return {
            "count": count,
            "sum": total,
            "min": lo,
            "max": hi,
            "mean": (total / count) if count else None,
            "p50": self._rank(ordered, 50),
            "p90": self._rank(ordered, 90),
            "p99": self._rank(ordered, 99),
        }


class MetricsRegistry:
    """Thread-safe get-or-create registry.

    Names are flat dotted strings (``scheduler.shed_total``); a name is
    bound to one metric type for its lifetime — asking for the same name
    as a different type raises, because that is always a bug.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, name: str, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str, reservoir: int = 8192) -> Histogram:
        return self._get_or_create(name, Histogram, reservoir=reservoir)

    def get(self, name: str) -> Optional[object]:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """JSON-ready dict grouped by type: ``counters``/``gauges`` →
        name → float, ``histograms`` → name → summary dict."""
        with self._lock:
            items: Tuple[Tuple[str, object], ...] = tuple(self._metrics.items())
        out: Dict[str, Dict[str, object]] = {
            "counters": {}, "gauges": {}, "histograms": {},
        }
        for name, m in sorted(items):
            if isinstance(m, Histogram):
                out["histograms"][name] = m.summary()
            elif isinstance(m, Counter):
                out["counters"][name] = m.value
            else:
                out["gauges"][name] = m.value  # type: ignore[union-attr]
        return out

    def to_prometheus(self, labels: Optional[Dict[str, str]] = None) -> str:
        """Prometheus text exposition (format 0.0.4): counters and
        gauges as-is, histograms as summaries with ``quantile`` labels
        plus ``_count``/``_sum``. Dots in names become underscores;
        ``labels`` (e.g. ``{"node": "node0"}``) are applied to every
        sample so per-node texts can be concatenated."""
        base = dict(labels or {})
        with self._lock:
            items: Tuple[Tuple[str, object], ...] = tuple(self._metrics.items())

        def fmt(name: str, value: float, extra: Optional[Dict[str, str]] = None) -> str:
            lbl = {**base, **(extra or {})}
            body = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(lbl.items()))
            return f"{name}{{{body}}} {value}" if body else f"{name} {value}"

        lines = []
        for name, m in sorted(items):
            pname = _prom_name(name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pname} counter")
                lines.append(fmt(pname, m.value))
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pname} gauge")
                lines.append(fmt(pname, m.value))
            elif isinstance(m, Histogram):
                s = m.summary()
                lines.append(f"# TYPE {pname} summary")
                for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
                    if s[key] is not None:
                        lines.append(fmt(pname, s[key], {"quantile": q}))
                lines.append(fmt(f"{pname}_count", s["count"] or 0))
                lines.append(fmt(f"{pname}_sum", s["sum"] or 0.0))
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    return out if not out[:1].isdigit() else f"_{out}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
