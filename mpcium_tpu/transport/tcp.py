"""TCP message bus: the multi-process NATS equivalent.

One :class:`BrokerServer` (the `nats-server` analogue from the reference's
docker-compose) + per-process :class:`TcpTransport` clients implementing
the same four delivery semantics as the loopback fabric:

- pub/sub fan-out (with trailing-``*`` patterns)
- acked unicast: the broker routes to one listener and relays the ack;
  the sender retries on timeout (reference point2point.go budgets)
- durable queues: broker-held state — pending buffering, Nats-Msg-Id
  idempotency, per-message delivery counts, redelivery on nak/disconnect,
  dead-letter broadcast after max_deliver
- dead-letter events fan out to every connected client that registered

Durability: ``journal_path`` gives the broker an append-only JSONL journal
of queue state (enqueue / done records). A restarted broker replays it and
redelivers every enqueued-but-unacked message — the reference's file-backed
JetStream WorkQueue retention (message_queue.go:56-63). Pub/sub and direct
traffic stay ephemeral, as in NATS core.

Auth: ``auth_token`` requires every client's first frame to be
``{"op": "auth", "token": ...}`` — the reference's NATS user/password
credentials (main.go:346-359, config.prod.yaml.template). The broker
stores and compares only the SHA-256 of the token (constant-time), so
config files can hold ``sha256:<hex>`` instead of the secret (the digest
is still a full credential for this broker — see SECURITY.md).

Encryption: ``encrypt=True`` wraps every connection in the AEAD channel
of :mod:`.secure` (X25519 ephemerals + token-bound HKDF +
ChaCha20-Poly1305 with per-direction counter nonces) — the equivalent of
the reference's production TLS-to-NATS posture, with mutual
authentication riding the shared token instead of certificates.

High availability: the reference clusters NATS (and JetStream replicates
streams); here a second broker started with ``follow=(host, port)`` runs
as a **hot standby** — it attaches to the primary over the same
authenticated/encrypted channel, snapshots every not-yet-done queue
message, then mirrors the live enqueue/done stream into its own journal.
Clients list both endpoints (``TcpClient(addrs=[primary, standby] )`` /
config ``broker_standbys``): when the primary dies they transparently
reconnect down the list, re-authenticate, and replay their
subscriptions, and the standby serves the mirrored backlog. Semantics
across a failover are NATS-like: durable queues are at-least-once
(consumers are idempotent; the dedup window does not replicate for
snapshot entries), pub/sub and direct traffic are ephemeral (app-level
acks/retries cover the gap). Split-brain is bounded by the address-list
ordering — clients prefer the primary while it is reachable — and there
is no automatic fail-back: re-arming HA after an outage means restarting
the dead broker as the new standby (runbook in INSTALLATION.md).

Framing: newline-delimited JSON, payloads hex-encoded. This is a dev/ops
fabric for single-digit node counts (the reference's deployment shape);
protocol payload sizes are small (keygen/signing round messages).
"""
from __future__ import annotations


import itertools
import json
import os
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

try:
    from cryptography.exceptions import InvalidTag as _InvalidTag
except ImportError:  # bare env: softcrypto's AEAD raises its own InvalidTag
    from ..core.softcrypto import InvalidTag as _InvalidTag

from .api import (
    DeadLetterHandler,
    DirectMessaging,
    Handler,
    MessageQueue,
    Permanent,
    PubSub,
    QueueConfig,
    QueueHandler,
    Subscription,
    Transport,
    TransportError,
)
from .loopback import topic_matches
from ..utils import log


def _send_frame(sock: socket.socket, obj: dict, cipher=None) -> None:
    data = json.dumps(obj, separators=(",", ":")).encode()
    if cipher is not None:
        data = cipher.encrypt(data).hex().encode()
    sock.sendall(data + b"\n")


def _recv_line_blocking(sock: socket.socket, timeout_s: float = 10.0) -> bytes:
    """Read one newline-terminated line (handshake only — before the
    read loop starts)."""
    sock.settimeout(timeout_s)
    buf = b""
    try:
        while b"\n" not in buf:
            chunk = sock.recv(4096)
            if not chunk:
                raise TransportError("connection closed during handshake")
            buf += chunk
    finally:
        sock.settimeout(None)
    line, _rest = buf.split(b"\n", 1)
    # handshake is strictly one line each way before any other traffic, so
    # _rest is empty by protocol
    return line


class _Conn:
    """Broker-side client connection."""

    def __init__(self, sock: socket.socket, broker: "BrokerServer", cid: int):
        self.sock = sock
        self.broker = broker
        self.cid = cid
        self.subs: Dict[int, Tuple[str, str]] = {}  # sid -> (kind, pattern)
        self.is_replica = False  # a standby broker following this one
        self.wants_dead_letters = False
        self.lock = threading.Lock()
        self.alive = True
        self.authed = False
        self.cipher = None  # set by the broker's handshake when encrypting

    def send(self, obj: dict) -> bool:
        try:
            with self.lock:
                _send_frame(self.sock, obj, self.cipher)
            return True
        except OSError:
            self.alive = False
            return False


class BrokerServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        queue_config: QueueConfig = QueueConfig(),
        journal_path: Optional[str] = None,
        auth_token: Optional[str] = None,
        journal_fsync: bool = True,
        encrypt: bool = False,
        follow: Optional[Tuple[str, int]] = None,
        queue_ttl_s: float = 1800.0,
    ):
        from .secure import hash_token

        self.queue_config = queue_config
        # stored hashed (sha256:<hex>): comparisons are digest-vs-digest,
        # and configs may carry the digest instead of the secret
        self.auth_token = None if auth_token is None else hash_token(auth_token)
        self.encrypt = encrypt
        if encrypt and auth_token is None:
            raise ValueError(
                "encrypt=True requires an auth token (the AEAD channel's "
                "mutual authentication is token-bound)"
            )
        # fsync acked enqueues (host-crash durability); opt out for tests /
        # throwaway brokers where the per-enqueue fsync cost matters
        self._journal_fsync = journal_fsync
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()
        self._conns: Dict[int, _Conn] = {}
        self._lock = threading.RLock()
        self._cid = itertools.count(1)
        self._did = itertools.count(1)
        self._rr = itertools.count()
        # bounded dedup window (JetStream duplicate-window semantics)
        self._dedup_window_s = 120.0
        self._seen_ids: Dict[Tuple[str, str], float] = {}
        self._pending_q: deque = deque()  # (topic, data, deliveries, mid)
        self._pending_mids: Set[int] = set()  # mirror of _pending_q mids
        # Work-queue TTL: per-tx/per-wallet RESULT topics mean a result
        # published after its (sole) requester timed out and unsubscribed
        # has no consumer, is never nak'd, and would otherwise pend — in
        # memory, the journal, and every standby — forever. Expired
        # messages take the dead-letter path. mid -> first-enqueue WALL
        # time (wall, not monotonic: the stamp is journaled and
        # replicated, so the age survives restarts and standby
        # promotion); redeliveries keep the original stamp. A sweep
        # thread expires the backlog even on a quiet broker with no new
        # subscriptions to trigger a dispatch.
        self.queue_ttl_s = queue_ttl_s
        self._enq_ts: Dict[int, float] = {}
        # Control-plane KV served over the wire (the Consul analogue —
        # reference pkg/infra/consul.go serves registry/keyinfo/peers over
        # HTTP(S)+ACL; here the broker IS the network rendezvous, so the
        # same socket carries the control plane). Durable keys are
        # journaled (fsync'd) and replicated to standbys; transient keys
        # (registry liveness heartbeats at 1 Hz) are neither — after a
        # failover the nodes' heartbeat loops repopulate them within a
        # poll period. Values are hex strings (JSON-frame safe).
        self._kv: Dict[str, str] = {}
        self._kv_transient: Set[str] = set()
        self._inflight: Dict[int, Tuple[str, str, int, int, int]] = {}
        # did -> (topic, data, deliveries, cid, mid)
        self._mid_next = 1  # next mid (plain int: replication bumps it)
        self._journal = None
        self._jlock = threading.Lock()
        if journal_path is not None:
            self._replay_journal(journal_path)
            self._journal = open(journal_path, "a", buffering=1)
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="broker-accept", daemon=True
        )
        self._accept_thread.start()
        if queue_ttl_s > 0:
            threading.Thread(
                target=self._ttl_sweep_loop, name="broker-ttl-sweep",
                daemon=True,
            ).start()
        # -- standby mode: follow a primary's queue state until it dies ----
        # (see the "High availability" section of the module docstring)
        self._follow = follow
        self._follower_cli: Optional["TcpClient"] = None
        self._rep_synced = threading.Event()
        if follow is not None:
            threading.Thread(
                target=self._follow_loop, name="broker-follow", daemon=True
            ).start()

    # -- durability ---------------------------------------------------------

    def _replay_journal(self, path: str) -> None:
        """Rebuild pending queue state from the append-only journal, then
        compact it (pending survivors only). Enqueued-but-not-done messages
        are redelivered once a consumer subscribes — the reference's
        file-backed WorkQueue retention (message_queue.go:56-63)."""
        pending: Dict[int, Tuple[str, str, str, float]] = {}
        max_mid = 0
        if os.path.exists(path):
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail write on crash
                    if rec.get("j") == "enq":
                        pending[rec["mid"]] = (
                            rec["topic"], rec["data"], rec.get("key", ""),
                            # wall-clock enqueue stamp: the TTL age
                            # survives restarts (pre-stamp journals age
                            # from replay time)
                            float(rec.get("ts", time.time())),
                        )
                        max_mid = max(max_mid, rec["mid"])
                    elif rec.get("j") == "done":
                        pending.pop(rec["mid"], None)
                    elif rec.get("j") == "kvp":
                        self._kv[rec["k"]] = rec["v"]
                    elif rec.get("j") == "kvd":
                        self._kv.pop(rec["k"], None)
        self._mid_next = max_mid + 1
        tmp = path + ".tmp"
        now = time.monotonic()
        with open(tmp, "w") as fh:
            for mid, (topic, data, key, ts) in sorted(pending.items()):
                fh.write(json.dumps(
                    {"j": "enq", "mid": mid, "topic": topic, "data": data,
                     "key": key, "ts": ts}, separators=(",", ":")) + "\n")
                self._pending_q.append((topic, data, 0, mid))
                self._pending_mids.add(mid)
                self._enq_ts[mid] = ts
                if key:
                    self._seen_ids[(topic.rsplit(".", 1)[0], key)] = now
            for k in sorted(self._kv):
                fh.write(json.dumps(
                    {"j": "kvp", "k": k, "v": self._kv[k]},
                    separators=(",", ":")) + "\n")
        os.replace(tmp, path)

    def _journal_write(self, rec: dict, durable: bool = False) -> None:
        # dedicated journal lock: fsync latency must not serialize the
        # broker's global dispatch lock (pub/sub and direct traffic need no
        # durability and should never stall behind a disk flush)
        with self._jlock:
            # re-check under the lock: close() nulls self._journal while a
            # racing write could otherwise hit a closed file
            j = self._journal
            if j is None:
                return
            j.write(json.dumps(rec, separators=(",", ":")) + "\n")
            if durable and self._journal_fsync:
                j.flush()
                os.fsync(j.fileno())

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        if self._follower_cli is not None:
            self._follower_cli.close()
        try:
            self._srv.close()
        except OSError:
            pass
        # wake the accept thread: its blocked accept() holds a reference
        # to the listening socket, which otherwise stays in LISTEN and
        # squats the port against a broker restart
        try:
            socket.create_connection((self.host, self.port),
                                     timeout=1).close()
        except OSError:
            pass
        with self._lock:
            for c in self._conns.values():
                try:
                    # shutdown FIRST: close() alone neither wakes the read
                    # thread blocked in recv (whose in-flight syscall keeps
                    # the kernel socket alive, squatting the port against a
                    # restart) nor sends FIN to the peer
                    c.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    c.sock.close()
                except OSError:
                    pass
        with self._jlock:
            if self._journal is not None:
                self._journal.close()
                self._journal = None

    # -- accept/read --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            if self._closed:
                try:
                    sock.close()
                finally:
                    return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock, self, next(self._cid))
            with self._lock:
                self._conns[conn.cid] = conn
            threading.Thread(
                target=self._read_loop, args=(conn,),
                name=f"broker-read-{conn.cid}", daemon=True,
            ).start()

    def _handshake(self, conn: _Conn) -> None:
        """Server side of the AEAD channel establishment (secure.py)."""
        from .secure import derive_cipher, fresh_keypair

        hello = json.loads(_recv_line_blocking(conn.sock))
        if hello.get("op") != "ehello":
            raise TransportError("client did not start AEAD handshake")
        client_pub = bytes.fromhex(hello["epub"])
        priv, server_pub = fresh_keypair()
        _send_frame(conn.sock, {"op": "ehello", "epub": server_pub.hex()})
        conn.cipher = derive_cipher(
            priv, client_pub, client_pub, server_pub,
            self.auth_token, is_server=True,
        )

    def _read_loop(self, conn: _Conn) -> None:
        if self.encrypt:
            try:
                self._handshake(conn)
            except Exception as e:  # noqa: BLE001
                log.warn("broker: AEAD handshake failed", error=repr(e))
                self._drop(conn)
                return
        buf = b""
        try:
            while not self._closed:
                chunk = conn.sock.recv(65536)
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if line:
                        if conn.cipher is not None:
                            line = conn.cipher.decrypt(
                                bytes.fromhex(line.decode())
                            )
                        self._handle(conn, json.loads(line))
        except (OSError, ValueError, _InvalidTag):
            pass
        finally:
            self._drop(conn)

    def _drop(self, conn: _Conn) -> None:
        conn.alive = False
        with self._lock:
            self._conns.pop(conn.cid, None)
            # redeliver this client's unacked queue messages
            orphaned = [
                (did, v) for did, v in self._inflight.items() if v[3] == conn.cid
            ]
            for did, (topic, data, deliveries, _cid, mid) in orphaned:
                del self._inflight[did]
                self._queue_dispatch(topic, data, deliveries, mid)

    # -- frame handling ------------------------------------------------------

    def _handle(self, conn: _Conn, f: dict) -> None:
        op = f.get("op")
        if self.auth_token is not None and not conn.authed:
            # first frame must authenticate (reference NATS credentials,
            # main.go:346-359); hashed constant-time compare, drop on failure
            from .secure import token_matches

            if op == "auth" and token_matches(
                str(f.get("token", "")), self.auth_token
            ):
                conn.authed = True
                conn.send({"op": "auth_ok"})
            else:
                log.warn("broker: unauthenticated client rejected")
                try:
                    conn.send({"op": "auth_err"})
                    conn.sock.close()
                except OSError:
                    pass
            return
        if op == "auth":
            conn.send({"op": "auth_ok"})  # auth disabled: accept anything
            return
        if op == "sub":
            with self._lock:
                conn.subs[f["sid"]] = (f["kind"], f["pattern"])
            if f["kind"] == "queue":
                self._flush_pending()
        elif op == "unsub":
            with self._lock:
                conn.subs.pop(f["sid"], None)
        elif op == "dead_sub":
            conn.wants_dead_letters = True
        elif op == "pub":
            self._fanout(f["topic"], f["data"], f.get("reply"))
        elif op == "direct":
            self._direct(conn, f)
        elif op == "ack":  # receiver acked a direct message
            self._relay_ack(f)
        elif op == "enqueue":
            key = f.get("key", "")
            if key:
                with self._lock:
                    now = time.monotonic()
                    self._seen_ids = {
                        k: t
                        for k, t in self._seen_ids.items()
                        if now - t < self._dedup_window_s
                    }
                    dk = (f["topic"].rsplit(".", 1)[0], key)
                    if dk in self._seen_ids:
                        return
                    self._seen_ids[dk] = now
            with self._lock:
                mid = self._mid_next
                self._mid_next += 1
                ts = time.time()
                self._enq_ts[mid] = ts
            # enqueues are acknowledged to publishers — fsync (when enabled)
            # so an accepted request survives a host crash, not just a
            # process crash ("done" records may be lost: redelivery of a
            # completed message is the safe direction for a work queue)
            self._journal_write(
                {"j": "enq", "mid": mid, "topic": f["topic"],
                 "data": f["data"], "key": key, "ts": ts},
                durable=True,
            )
            self._queue_dispatch(
                f["topic"], f["data"], 0, mid,
                rep_rec={"j": "enq", "mid": mid, "topic": f["topic"],
                         "data": f["data"], "key": key, "ts": ts},
            )
        elif op == "kvput":
            k, v = f["k"], f["v"]
            transient = bool(f.get("t"))
            # journal + replicate INSIDE the lock: KV mutations of the
            # same key are order-sensitive (unlike queue done records) —
            # a put and a delete racing outside the lock could reach the
            # journal/standbys in the opposite order they were applied,
            # resurrecting a revoked key after failover. Durable KV ops
            # are rare (peers/keyinfo writes); heartbeats are transient
            # and skip this path, so the fsync-under-lock cost is
            # negligible.
            with self._lock:
                self._kv[k] = v
                if transient:
                    self._kv_transient.add(k)
                else:
                    self._kv_transient.discard(k)
                    self._journal_write({"j": "kvp", "k": k, "v": v},
                                        durable=True)
                    self._replicate({"j": "kvp", "k": k, "v": v})
            conn.send({"op": "kvr", "rid": f["rid"], "ok": True})
        elif op == "kvget":
            with self._lock:
                v = self._kv.get(f["k"])
            conn.send({"op": "kvr", "rid": f["rid"], "v": v})
        elif op == "kvdel":
            k = f["k"]
            with self._lock:
                was_transient = k in self._kv_transient
                self._kv.pop(k, None)
                self._kv_transient.discard(k)
                if not was_transient:
                    # durable: a lost delete would resurrect a
                    # deliberately removed control-plane key (e.g. a
                    # revoked peer) — the unsafe direction
                    self._journal_write({"j": "kvd", "k": k}, durable=True)
                    self._replicate({"j": "kvd", "k": k})
            conn.send({"op": "kvr", "rid": f["rid"], "ok": True})
        elif op == "kvkeys":
            p = f.get("p", "")
            with self._lock:
                ks = sorted(k for k in self._kv if k.startswith(p))
            conn.send({"op": "kvr", "rid": f["rid"], "keys": ks})
        elif op == "kvscan":
            # one-round-trip prefix scan: the registry polls liveness at
            # 1 Hz per node; per-key gets would be O(N) RTTs per poll
            p = f.get("p", "")
            with self._lock:
                items = {
                    k: v for k, v in self._kv.items() if k.startswith(p)
                }
            conn.send({"op": "kvr", "rid": f["rid"], "items": items})
        elif op == "qack":
            with self._lock:
                v = self._inflight.pop(f["did"], None)
                if v:
                    self._enq_ts.pop(v[4], None)
            if v:
                self._journal_write({"j": "done", "mid": v[4]})
                self._replicate({"j": "done", "mid": v[4]})
        elif op == "qnak":
            with self._lock:
                v = self._inflight.pop(f["did"], None)
            if v:
                topic, data, deliveries, _cid, mid = v
                if f.get("permanent"):
                    with self._lock:
                        self._enq_ts.pop(mid, None)
                    self._journal_write({"j": "done", "mid": mid})
                    self._replicate({"j": "done", "mid": mid})
                    return
                if deliveries >= self.queue_config.max_deliver:
                    with self._lock:
                        self._enq_ts.pop(mid, None)
                    self._journal_write({"j": "done", "mid": mid})
                    self._replicate({"j": "done", "mid": mid})
                    self._dead_letter(topic, data, deliveries)
                else:
                    self._queue_dispatch(topic, data, deliveries, mid)
        elif op == "replica":
            # a standby broker wants the queue state: snapshot every
            # not-yet-done message (pending + inflight: inflight would be
            # redelivered after a failover anyway — at-least-once). The
            # snapshot is SENT while holding the broker lock: a concurrent
            # qack's live "done" record must not overtake the snapshot
            # "enq" for the same mid (the standby would keep a completed
            # message pending forever). Snapshot size is bounded by the
            # undone backlog; stalling dispatch for its transmission is
            # the price of a consistent cut.
            with self._lock:
                now = time.time()
                snapshot = [
                    {"j": "enq", "mid": mid, "topic": t, "data": d,
                     "ts": self._enq_ts.get(mid, now)}
                    for (t, d, _dl, mid) in self._pending_q
                ] + [
                    {"j": "enq", "mid": v[4], "topic": v[0], "data": v[1],
                     "ts": self._enq_ts.get(v[4], now)}
                    for v in self._inflight.values()
                ]
                kv_snapshot = [
                    {"j": "kvp", "k": k, "v": v}
                    for k, v in sorted(self._kv.items())
                    if k not in self._kv_transient
                ]
                for rec in sorted(snapshot, key=lambda r: r["mid"]):
                    conn.send({"op": "rep", **rec})
                for rec in kv_snapshot:
                    conn.send({"op": "rep", **rec})
                conn.send({"op": "rep", "j": "synced"})
                conn.is_replica = True

    # -- replication (standby brokers) ---------------------------------------

    def _replicate(self, rec: dict) -> None:
        """Stream a queue-journal record to every attached standby."""
        with self._lock:
            reps = [c for c in self._conns.values() if c.is_replica]
        for c in reps:
            c.send({"op": "rep", **rec})

    def _follow_loop(self) -> None:
        """Standby side: attach to the primary, mirror its queue state into
        our own journal/pending set, and keep mirroring. A lost primary
        connection is NOT assumed to be primary death (a transient blip
        must not silently disarm replication): the loop re-attaches and
        re-snapshots forever — the snapshot/stream dedup in
        _apply_replica_record makes re-follows idempotent, and "done"s
        missed during an outage at worst leave already-completed messages
        pending here (redelivery of completed work is the safe direction;
        consumers are idempotent). While the primary is actually down this
        broker simply keeps serving — clients reach it via their address
        lists — so "promotion" needs no state transition at all."""
        host, port = self._follow
        token = self.auth_token  # hashed form authenticates (secure.py)
        attached = False
        while not self._closed:
            try:
                cli = TcpClient(
                    host, port, workers=2, auth_token=token,
                    encrypt=self.encrypt, reconnect=False,
                )
            except (OSError, TransportError):
                if attached:
                    attached = False
                    log.warn(
                        "broker standby: primary unreachable — serving "
                        "active, will re-follow when it returns",
                        primary=f"{host}:{port}",
                    )
                time.sleep(1.0)
                continue
            self._follower_cli = cli
            cli._rep_handler = self._apply_replica_record
            try:
                cli._send({"op": "replica"})
            except TransportError:
                cli.close()
                continue
            attached = True
            log.info("broker standby: following primary",
                     primary=f"{host}:{port}")
            cli._reader.join()  # blocks until the primary connection dies
            cli.close()
            self._follower_cli = None

    def _apply_replica_record(self, rec: dict) -> None:
        j = rec.get("j")
        if j == "synced":
            self._rep_synced.set()
            return
        # Chain replication: forward every applied record to replicas
        # attached to THIS standby (primary <- s1 <- s2 ...), so a second
        # standby stays current after the first one is promoted. The
        # forward happens INSIDE the same critical section that applies
        # the record (the RLock re-enters for _replicate) — forwarding
        # outside it would let a downstream replica cut its snapshot
        # between the forward and the apply and miss the record from
        # both paths.
        if j == "enq":
            mid = rec["mid"]
            topic, data, key = rec["topic"], rec["data"], rec.get("key", "")
            ts = float(rec.get("ts", time.time()))
            with self._lock:
                # local mid counter must stay ahead of replicated ids so
                # post-promotion enqueues never collide
                self._mid_next = max(self._mid_next, mid + 1)
                if mid in self._pending_mids:
                    return  # snapshot/stream or re-follow overlap
                if key:
                    self._seen_ids[(topic.rsplit(".", 1)[0], key)] = (
                        time.monotonic()
                    )
                self._pending_q.append((topic, data, 0, mid))
                self._pending_mids.add(mid)
                self._enq_ts[mid] = ts
                self._replicate(rec)
            self._journal_write(
                {"j": "enq", "mid": mid, "topic": topic, "data": data,
                 "key": key, "ts": ts},
                durable=True,
            )
        elif j == "done":
            with self._lock:
                self._enq_ts.pop(rec["mid"], None)
                if rec["mid"] in self._pending_mids:
                    self._pending_mids.discard(rec["mid"])
                    self._pending_q = deque(
                        e for e in self._pending_q if e[3] != rec["mid"]
                    )
                self._replicate(rec)
            self._journal_write({"j": "done", "mid": rec["mid"]})
        elif j == "kvp":
            with self._lock:
                self._kv[rec["k"]] = rec["v"]
                self._replicate(rec)
            self._journal_write({"j": "kvp", "k": rec["k"], "v": rec["v"]},
                                durable=True)
        elif j == "kvd":
            with self._lock:
                self._kv.pop(rec["k"], None)
                self._replicate(rec)
            # durable like kvput: resurrecting a deliberately deleted
            # control-plane key (a removed peer) is the unsafe direction
            self._journal_write({"j": "kvd", "k": rec["k"]}, durable=True)

    # -- pub/sub -------------------------------------------------------------

    def _fanout(self, topic: str, data_hex: str, reply: Optional[str]) -> None:
        with self._lock:
            targets = [
                (c, sid)
                for c in self._conns.values()
                for sid, (kind, pat) in c.subs.items()
                if kind == "pubsub" and topic_matches(pat, topic)
            ]
        for c, sid in targets:
            c.send({"op": "msg", "sid": sid, "topic": topic, "data": data_hex,
                    "reply": reply})

    # -- direct --------------------------------------------------------------

    def _direct(self, sender: _Conn, f: dict) -> None:
        with self._lock:
            targets = [
                (c, sid)
                for c in self._conns.values()
                for sid, (kind, pat) in c.subs.items()
                if kind == "direct" and topic_matches(pat, f["topic"])
            ]
        if not targets:
            sender.send({"op": "dack", "rid": f["rid"], "ok": False})
            return
        c, sid = targets[0]
        ok = c.send(
            {"op": "dmsg", "sid": sid, "data": f["data"], "rid": f["rid"],
             "from_cid": sender.cid}
        )
        if not ok:
            sender.send({"op": "dack", "rid": f["rid"], "ok": False})

    def _relay_ack(self, f: dict) -> None:
        target_cid = f.get("to_cid")
        with self._lock:
            conn = self._conns.get(target_cid)
        if conn:
            conn.send({"op": "dack", "rid": f["rid"], "ok": bool(f.get("ok", True))})

    # -- queues --------------------------------------------------------------

    def _queue_dispatch(
        self, topic: str, data_hex: str, deliveries: int, mid: int,
        rep_rec: Optional[dict] = None,
    ) -> None:
        """Route one queue message. ``rep_rec`` (fresh enqueues only) is
        the replication record; the replica list is read inside the SAME
        critical section that enters the message into pending/inflight, so
        a standby's snapshot cut can never fall between them (a message
        missing from both snapshot and stream would be silently lost on
        failover despite the publisher's fsynced ack)."""
        while True:
            reps: list = []
            with self._lock:
                # TTL check first (see _enq_ts comment in __init__): an
                # expired message must neither enter pending/inflight nor be
                # streamed to standbys as live — it takes the dead-letter
                # path below. The replica list read and the pending/inflight
                # entry stay inside this ONE critical section so a standby's
                # snapshot cut can never fall between them.
                ts = self._enq_ts.setdefault(mid, time.time())
                expired = (
                    self.queue_ttl_s > 0
                    and time.time() - ts > self.queue_ttl_s
                )
                if expired:
                    self._enq_ts.pop(mid, None)
                else:
                    if rep_rec is not None:
                        reps = [c for c in self._conns.values() if c.is_replica]
                    targets = [
                        (c, sid)
                        for c in self._conns.values()
                        if c.alive
                        for sid, (kind, pat) in c.subs.items()
                        if kind == "queue" and topic_matches(pat, topic)
                    ]
                    if not targets:
                        self._pending_q.append(
                            (topic, data_hex, deliveries, mid))
                        self._pending_mids.add(mid)
                        c = None
                    else:
                        c, sid = targets[next(self._rr) % len(targets)]
                        did = next(self._did)
                        self._inflight[did] = (
                            topic, data_hex, deliveries + 1, c.cid, mid
                        )
            if expired:
                log.warn("queue message expired (no consumer within TTL)",
                         topic=topic, mid=mid, ttl_s=self.queue_ttl_s)
                self._journal_write({"j": "done", "mid": mid})
                self._replicate({"j": "done", "mid": mid})
                self._dead_letter(topic, data_hex, deliveries)
                return
            for r in reps:
                r.send({"op": "rep", **rep_rec})
            if c is None:
                return
            if c.send(
                {"op": "qmsg", "sid": sid, "did": did,
                 "data": data_hex, "topic": topic}
            ):
                return
            # Dead target: send() marked the conn not-alive, so the next
            # pass excludes it — the retry is bounded by the number of
            # live-at-selection conns. (This used to recurse, which blew
            # the stack during broker-failover churn when a batch of
            # messages all re-routed off the same dying connection.)
            with self._lock:
                self._inflight.pop(did, None)
            rep_rec = None

    def _flush_pending(self) -> None:
        with self._lock:
            pending, self._pending_q = list(self._pending_q), deque()
            self._pending_mids.clear()
        for topic, data_hex, deliveries, mid in pending:
            self._queue_dispatch(topic, data_hex, deliveries, mid)

    def _ttl_sweep_loop(self) -> None:
        """Expire the pending backlog even on a quiet broker: without a
        sweep, TTL would only be evaluated when a new subscription
        triggers a dispatch attempt, so an orphaned result on an idle
        broker would still pend forever."""
        interval = max(1.0, min(self.queue_ttl_s / 4, 60.0))
        while not self._closed:
            time.sleep(interval)
            if self._closed:
                return
            now = time.time()
            expired = []
            with self._lock:
                keep: deque = deque()
                for e in self._pending_q:
                    ts = self._enq_ts.setdefault(e[3], now)
                    if now - ts > self.queue_ttl_s:
                        expired.append(e)
                        self._pending_mids.discard(e[3])
                        self._enq_ts.pop(e[3], None)
                    else:
                        keep.append(e)
                self._pending_q = keep
            for topic, data_hex, deliveries, mid in expired:
                log.warn(
                    "queue message expired (no consumer within TTL)",
                    topic=topic, mid=mid, ttl_s=self.queue_ttl_s,
                )
                self._journal_write({"j": "done", "mid": mid})
                self._replicate({"j": "done", "mid": mid})
                self._dead_letter(topic, data_hex, deliveries)

    def _dead_letter(self, topic: str, data_hex: str, deliveries: int) -> None:
        with self._lock:
            targets = [c for c in self._conns.values() if c.wants_dead_letters]
        for c in targets:
            c.send({"op": "dead", "topic": topic, "data": data_hex,
                    "deliveries": deliveries})


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class _ClientSub(Subscription):
    def __init__(self, client: "TcpClient", sid: int):
        self.client = client
        self.sid = sid

    def unsubscribe(self) -> None:
        self.client._unsubscribe(self.sid)


class TcpClient:
    """One broker connection per process; thread-pool handler execution.

    High availability: ``addrs`` lists broker endpoints in preference
    order (primary first, standbys after — the NATS client's server-list
    semantics). The initial connect walks the list until one accepts; a
    lost connection triggers transparent failover in the reader thread —
    reconnect (cycling the list with backoff up to
    ``reconnect_deadline_s``), re-authenticate, re-establish the AEAD
    channel with fresh ephemerals, and replay every live subscription.
    In-flight direct sends fail fast on disconnect so their app-level
    retry budgets (point2point semantics) spend the wait productively.
    """

    def __init__(
        self,
        host: str,
        port: int,
        workers: int = 16,
        auth_token: Optional[str] = None,
        encrypt: bool = False,
        addrs: Optional[List[Tuple[str, int]]] = None,
        reconnect: bool = True,
        reconnect_deadline_s: float = 60.0,
    ):
        from concurrent.futures import ThreadPoolExecutor

        if encrypt and auth_token is None:
            raise ValueError("encrypt=True requires auth_token")
        self._addrs: List[Tuple[str, int]] = list(addrs or []) or [(host, port)]
        self._auth_token = auth_token
        self._encrypt = encrypt
        self._reconnect = reconnect
        self._reconnect_deadline_s = reconnect_deadline_s
        self._wlock = threading.Lock()
        self._sid = itertools.count(1)
        self._rid = itertools.count(1)
        # sid -> (kind, pattern, handler); pattern kept for failover replay
        self._handlers: Dict[int, Tuple[str, str, object]] = {}
        self._dack_events: Dict[int, Tuple[threading.Event, List[bool]]] = {}
        # rid -> (event, response box) for synchronous KV requests
        self._kv_events: Dict[int, Tuple[threading.Event, List[dict]]] = {}
        self._dead_handlers: List[DeadLetterHandler] = []
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="tcpbus")
        # queue handlers may block (signing bridge reply wait): own pool so
        # they cannot starve pub/sub + direct delivery
        self._qpool = ThreadPoolExecutor(max_workers=workers,
                                         thread_name_prefix="tcpbus-q")
        self._closed = False
        self._connected = threading.Event()
        # replication hook: a standby BrokerServer following a primary sets
        # this to receive "rep" frames (see BrokerServer._follow_loop)
        self._rep_handler = None
        self.sock, self._cipher = self._establish_any(
            time.monotonic() + 10, initial=True
        )
        self._connected.set()
        self._reader = threading.Thread(
            target=self._read_loop, name="tcpbus-read", daemon=True
        )
        self._reader.start()

    # -- connection establishment -------------------------------------------

    def _establish(self, addr: Tuple[str, int]):
        """Open one broker connection: TCP + optional AEAD handshake +
        auth, all synchronously (no reader thread involved — this runs
        both at construction and from the reader during failover)."""
        sock = socket.create_connection(addr, timeout=10)
        if sock.getsockname() == sock.getpeername():
            # TCP simultaneous-open on loopback: hammering a dead broker's
            # (ephemeral) port can self-connect, which both looks like a
            # broker and SQUATS the port so the real one can't rebind
            sock.close()
            raise TransportError(f"self-connection to {addr}")
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        cipher = None
        try:
            if self._encrypt:
                from .secure import derive_cipher, fresh_keypair, hash_token

                priv, epub = fresh_keypair()
                _send_frame(sock, {"op": "ehello", "epub": epub.hex()})
                hello = json.loads(_recv_line_blocking(sock))
                if hello.get("op") != "ehello":
                    raise TransportError(
                        "broker did not complete AEAD handshake"
                    )
                server_pub = bytes.fromhex(hello["epub"])
                cipher = derive_cipher(
                    priv, server_pub, epub, server_pub,
                    hash_token(self._auth_token), is_server=False,
                )
            if self._auth_token is not None:
                _send_frame(sock, {"op": "auth", "token": self._auth_token},
                            cipher)
                line = _recv_line_blocking(sock)
                if cipher is not None:
                    line = cipher.decrypt(bytes.fromhex(line.decode()))
                if json.loads(line).get("op") != "auth_ok":
                    raise TransportError("broker rejected credentials")
        except BaseException:
            sock.close()
            raise
        return sock, cipher

    def _establish_any(self, deadline: float, initial: bool = False):
        """Walk the address list (with backoff) until a broker accepts.

        The sleep uses AWS-style decorrelated jitter (sleep ~ U(base,
        3·prev), capped): when a broker dies, EVERY client of the bus
        enters this loop at the same instant, and a deterministic
        doubling schedule would hammer the reborn broker in synchronized
        waves — each wave a burst of simultaneous accepts, handshakes
        and auth round-trips. Randomizing per-client spreads the herd.
        """
        import random

        base, cap = 0.1, 2.0
        backoff = base
        last: Exception = TransportError("no broker address configured")
        while True:
            for addr in self._addrs:
                if self._closed:
                    raise TransportError("client closed")
                try:
                    return self._establish(addr)
                except (OSError, TransportError, ValueError,
                        _InvalidTag) as e:
                    last = e
            if time.monotonic() >= deadline or (initial and not
                                                self._reconnect):
                raise TransportError(
                    f"no broker reachable among {self._addrs}: {last!r}"
                )
            time.sleep(backoff)
            backoff = min(cap, random.uniform(base, backoff * 3))

    def close(self) -> None:
        self._closed = True
        self._connected.set()  # release senders parked on the event
        try:
            self.sock.shutdown(socket.SHUT_RDWR)  # wake the reader's recv
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._qpool.shutdown(wait=False, cancel_futures=True)

    def _send(self, obj: dict) -> None:
        # two attempts: a send can lose the connection-lost race with the
        # reader (event still set, socket just died) — park through the
        # failover once and retry before surfacing an error
        for attempt in (0, 1):
            if self._closed:
                raise TransportError("client closed")
            # park briefly through a failover window instead of erroring
            if not self._connected.wait(timeout=10) or self._closed:
                raise TransportError("broker unreachable")
            with self._wlock:
                try:
                    _send_frame(self.sock, obj, self._cipher)
                    return
                except OSError as e:
                    err = e
            if attempt == 0:
                time.sleep(0.05)  # let the reader notice and clear the event
        raise TransportError(f"broker connection lost: {err!r}")

    # -- subscription registry ----------------------------------------------

    def _subscribe(self, kind: str, pattern: str, handler) -> _ClientSub:
        sid = next(self._sid)
        self._handlers[sid] = (kind, pattern, handler)
        self._send({"op": "sub", "kind": kind, "pattern": pattern, "sid": sid})
        return _ClientSub(self, sid)

    def _unsubscribe(self, sid: int) -> None:
        self._handlers.pop(sid, None)
        try:
            self._send({"op": "unsub", "sid": sid})
        except TransportError:
            pass

    # -- reader --------------------------------------------------------------

    def _read_loop(self) -> None:
        while not self._closed:
            buf = b""
            try:
                while not self._closed:
                    chunk = self.sock.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if line:
                            if self._cipher is not None:
                                line = self._cipher.decrypt(
                                    bytes.fromhex(line.decode())
                                )
                            self._dispatch(json.loads(line))
            except (OSError, ValueError, _InvalidTag):
                pass  # a tampered/desynced AEAD stream is a dead connection
            if self._closed or not self._reconnect:
                return
            self._connected.clear()  # before touching the socket: senders
            # must park on the event, not race into a closing fd
            # close the dead socket NOW: an abandoned half-open fd leaves
            # the broker side in FIN_WAIT_2, which (unlike TIME_WAIT)
            # blocks a restarted broker from rebinding its port
            try:
                self.sock.close()
            except OSError:
                pass
            # the reader is the only failover driver: it must survive any
            # surprise (e.g. a racing subscribe during replay) or the
            # client is bricked with the broker healthy
            while not self._closed and not self._connected.is_set():
                try:
                    self._failover()
                except Exception as e:  # noqa: BLE001
                    log.error("tcp bus: failover error; retrying",
                              error=repr(e))
                    time.sleep(0.5)

    def _failover(self) -> None:
        """Reconnect (possibly to a standby) and replay subscriptions."""
        self._connected.clear()
        # outstanding direct sends cannot be acked on a dead connection:
        # fail them now so their retry budgets cover the reconnect window
        for evt, result in list(self._dack_events.values()):
            result.append(False)
            evt.set()
        # likewise outstanding KV requests (kv_request retries once after
        # the reconnect)
        for evt, box in list(self._kv_events.values()):
            box.append({"err": "connection lost"})
            evt.set()
        log.warn("tcp bus: broker connection lost; failing over",
                 addrs=str(self._addrs))
        # retry FOREVER (the NATS client model): a broker outage longer
        # than the deadline must degrade to parked/erroring sends, never
        # permanently brick the process — the deadline only paces how
        # often the outage is logged
        while True:
            try:
                sock, cipher = self._establish_any(
                    time.monotonic() + self._reconnect_deadline_s
                )
                break
            except TransportError as e:
                if self._closed:
                    return
                log.error("tcp bus: no broker reachable; still retrying",
                          error=repr(e))
        with self._wlock:
            self.sock, self._cipher = sock, cipher
        # replay the live registry on the new broker. list() snapshots the
        # dict in one C call — a concurrent subscribe/unsubscribe must not
        # blow up the iteration (late additions park in _send on
        # _connected and register themselves after the event sets)
        try:
            for sid, (kind, pattern, _h) in sorted(list(self._handlers.items())):
                with self._wlock:
                    _send_frame(self.sock,
                                {"op": "sub", "kind": kind,
                                 "pattern": pattern, "sid": sid},
                                self._cipher)
            if self._dead_handlers:
                with self._wlock:
                    _send_frame(self.sock, {"op": "dead_sub"}, self._cipher)
        except OSError:
            return  # next read-loop pass will fail over again
        self._connected.set()
        log.info("tcp bus: reconnected", subs=len(self._handlers))

    def _dispatch(self, f: dict) -> None:
        op = f.get("op")
        if op in ("auth_ok", "auth_err"):
            return  # auth is synchronous in _establish; stray frames ignored
        if op == "rep":
            if self._rep_handler is not None:
                self._rep_handler(f)
            return
        if op == "msg":
            ent = self._handlers.get(f["sid"])
            if ent:
                handler = ent[2]
                data = bytes.fromhex(f["data"])
                reply = f.get("reply")
                if reply:
                    data = json.dumps(
                        {"reply": reply, "data": data.hex()}
                    ).encode()
                self._pool.submit(self._safe, handler, data)
        elif op == "dmsg":
            ent = self._handlers.get(f["sid"])

            def run():
                ok = True
                if ent:
                    try:
                        ent[2](bytes.fromhex(f["data"]))
                    except Exception:  # noqa: BLE001
                        ok = False
                try:
                    self._send({"op": "ack", "rid": f["rid"],
                                "to_cid": f["from_cid"], "ok": ok})
                except TransportError:
                    pass

            self._pool.submit(run)
        elif op == "dack":
            ent = self._dack_events.get(f["rid"])
            if ent:
                ent[1].append(bool(f.get("ok")))
                ent[0].set()
        elif op == "kvr":
            ent = self._kv_events.get(f["rid"])
            if ent:
                ent[1].append(f)
                ent[0].set()
        elif op == "qmsg":
            ent = self._handlers.get(f["sid"])

            def runq():
                if ent is None:
                    self._send({"op": "qnak", "did": f["did"]})
                    return
                try:
                    ent[2](bytes.fromhex(f["data"]))
                    self._send({"op": "qack", "did": f["did"]})
                except Permanent:
                    self._send({"op": "qnak", "did": f["did"], "permanent": True})
                except Exception:  # noqa: BLE001
                    self._send({"op": "qnak", "did": f["did"]})

            self._qpool.submit(runq)
        elif op == "dead":
            for h in list(self._dead_handlers):
                self._pool.submit(
                    self._safe_dead, h, f["topic"], bytes.fromhex(f["data"]),
                    f["deliveries"],
                )

    @staticmethod
    def _safe(handler, data) -> None:
        try:
            handler(data)
        except Exception as e:  # noqa: BLE001
            log.error("tcp bus handler error", error=repr(e))

    @staticmethod
    def _safe_dead(handler, topic, data, deliveries) -> None:
        try:
            handler(topic, data, deliveries)
        except Exception as e:  # noqa: BLE001
            log.error("dead-letter handler error", error=repr(e))

    # -- ops ------------------------------------------------------------------

    def publish(self, topic: str, data: bytes, reply: Optional[str] = None) -> None:
        self._send({"op": "pub", "topic": topic, "data": data.hex(),
                    "reply": reply})

    def direct_send(self, topic: str, data: bytes, timeout_s: float = 3.0,
                    attempts: int = 3, retry_delay_s: float = 0.05) -> None:
        """Acked unicast with a TIME budget of ``timeout_s * attempts``
        total. An instant dack-failure (no subscriber registered at the
        broker — the normal state mid-failover while peers re-replay
        their subscriptions at different speeds) must not burn a whole
        attempt: the budget is a deadline, retried on a short delay, the
        same patience contract the loopback fabric implements."""
        deadline = time.monotonic() + timeout_s * max(attempts, 1)
        while True:
            rid = next(self._rid)
            evt: Tuple[threading.Event, List[bool]] = (threading.Event(), [])
            self._dack_events[rid] = evt
            try:
                self._send({"op": "direct", "topic": topic, "data": data.hex(),
                            "rid": rid})
                remaining = deadline - time.monotonic()
                if (evt[0].wait(min(max(remaining, 0.05), timeout_s))
                        and evt[1] and evt[1][0]):
                    return
            except TransportError:
                pass  # reconnect in progress: retry within the budget
            finally:
                self._dack_events.pop(rid, None)
            if time.monotonic() + retry_delay_s >= deadline:
                raise TransportError(f"direct send to {topic!r} not acked")
            time.sleep(retry_delay_s)

    def enqueue(self, topic: str, data: bytes, idempotency_key: str = "") -> None:
        self._send({"op": "enqueue", "topic": topic, "data": data.hex(),
                    "key": idempotency_key})

    def kv_request(self, frame: dict, timeout_s: float = 10.0) -> dict:
        """Synchronous control-plane KV round-trip (kvput/kvget/kvdel/
        kvkeys → kvr). One transparent retry after a broker failover —
        KV ops are idempotent, and the standby carries the replicated
        durable keys."""
        last: Exception = TransportError("kv request not attempted")
        for _ in range(2):
            rid = next(self._rid)
            evt, box = threading.Event(), []
            self._kv_events[rid] = (evt, box)
            try:
                self._send({**frame, "rid": rid})
                if not evt.wait(timeout_s):
                    raise TransportError(
                        f"KV request timed out: {frame.get('op')}"
                    )
                if box and "err" not in box[0]:
                    return box[0]
                last = TransportError(
                    f"KV request failed: {box[0].get('err') if box else '?'}"
                )
            except TransportError as e:
                last = e
            finally:
                self._kv_events.pop(rid, None)
            # wait out the failover window before the single retry
            self._connected.wait(timeout=timeout_s)
        raise last

    def add_dead_letter_handler(self, handler: DeadLetterHandler) -> None:
        if not self._dead_handlers:
            self._send({"op": "dead_sub"})
        self._dead_handlers.append(handler)


def parse_addrs(spec: str) -> List[Tuple[str, int]]:
    """``"host:port[,host:port...]"`` → address list (config
    broker_standbys / --follow)."""
    out: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        if not port.isdigit():
            raise ValueError(
                f"broker address {part!r} must be host:port "
                f"(broker_standbys / --follow)"
            )
        out.append((host or "127.0.0.1", int(port)))
    return out


def tcp_transport(
    host: str,
    port: int,
    auth_token: Optional[str] = None,
    encrypt: bool = False,
    standbys: Optional[List[Tuple[str, int]]] = None,
) -> Transport:
    """Connect to a broker → a :class:`Transport` bundle. ``standbys``
    appends failover endpoints after the primary (client walks the list)."""
    client = TcpClient(
        host, port, auth_token=auth_token, encrypt=encrypt,
        addrs=[(host, port)] + list(standbys or []),
    )

    class _PS(PubSub):
        def publish(self, topic, data):
            client.publish(topic, data)

        def publish_with_reply(self, topic, reply_topic, data):
            client.publish(topic, data, reply=reply_topic)

        def subscribe(self, topic, handler: Handler):
            return client._subscribe("pubsub", topic, handler)

    class _DM(DirectMessaging):
        def send(self, topic, data, timeout_s=None):
            if timeout_s is None:
                client.direct_send(topic, data)
            else:
                client.direct_send(
                    topic, data, timeout_s=timeout_s, attempts=1
                )

        def listen(self, topic, handler: Handler):
            return client._subscribe("direct", topic, handler)

    class _MQ(MessageQueue):
        def enqueue(self, topic, data, idempotency_key=""):
            client.enqueue(topic, data, idempotency_key)

        def dequeue(self, topic_filter, handler: QueueHandler):
            return client._subscribe("queue", topic_filter, handler)

    t = Transport(
        pubsub=_PS(),
        direct=_DM(),
        queues=_MQ(),
        set_dead_letter_handler=client.add_dead_letter_handler,
    )
    t.client = client  # keep a handle for close()
    return t
