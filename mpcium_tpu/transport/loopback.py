"""In-process loopback fabric: n parties in one process.

The test/bench seam the reference never built (SURVEY.md §4: "in-memory
loopback transport implementing the pub/sub + direct interfaces, n parties
in one process"). One :class:`LoopbackFabric` is shared by all in-process
nodes; each node gets a :class:`Transport` view of it.

Delivery model: handlers run on a worker-thread pool (the reference spawns
a goroutine per inbound direct message — session.go:278 — precisely so a
handler can perform blocking acked sends without deadlocking the fabric).
Handlers must therefore guard their own state (the protocol layer holds a
per-session lock, like the reference's party mutex, session.go:79).
Topic wildcards: a trailing ``*`` segment matches any suffix (NATS-ish,
enough for the reference's ``mpc.<consumer>.*`` filters).

Durable queue semantics: at-least-once, bounded redelivery with
``max_deliver`` then dead-letter callback (the JetStream
max-deliveries-advisory analogue, timeout_consumer.go:14), idempotent
enqueue via Nats-Msg-Id-style keys (message_queue.go:100-110).
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import defaultdict, deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Tuple

from .api import (
    DeadLetterHandler,
    DirectMessaging,
    Handler,
    MessageQueue,
    Permanent,
    PubSub,
    QueueConfig,
    Subscription,
    Transport,
    TransportError,
)


def topic_matches(pattern: str, topic: str) -> bool:
    if pattern == topic:
        return True
    if pattern.endswith("*"):
        return topic.startswith(pattern[:-1])
    return False


@dataclass
class _Sub(Subscription):
    fabric: "LoopbackFabric"
    kind: str
    pattern: str
    handler: Callable
    active: bool = True

    def unsubscribe(self) -> None:
        self.active = False
        with self.fabric._lock:
            subs = self.fabric._subs[self.kind].get(self.pattern, [])
            if self in subs:
                subs.remove(self)


class LoopbackFabric:
    """The shared in-process bus."""

    def __init__(
        self, queue_config: QueueConfig = QueueConfig(), workers: int = 16
    ):
        from concurrent.futures import ThreadPoolExecutor

        self._lock = threading.RLock()
        self._subs: Dict[str, Dict[str, List[_Sub]]] = {
            "pubsub": defaultdict(list),
            "direct": defaultdict(list),
            "queue": defaultdict(list),
        }
        self._queue_config = queue_config
        # idempotency keys live for a bounded window (JetStream's duplicate
        # window semantics): repeats within it are deduped, later legitimate
        # re-submissions (e.g. a second reshare of the same wallet) pass,
        # and the set cannot grow without bound
        self._dedup_window_s = 120.0
        self._seen_msg_ids: Dict[Tuple[str, str], float] = {}
        self._dead_letter: List[DeadLetterHandler] = []
        self._pending_queue_msgs: deque = deque()  # undelivered (no consumer yet)
        self._seq = itertools.count()
        self._closed = False
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="loopback"
        )
        # queue handlers may block for long periods (e.g. the signing
        # bridge's reply wait) — they get their own pool so they cannot
        # starve protocol pub/sub + direct delivery
        self._qpool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="loopback-q"
        )
        self._inflight = 0
        self._idle = threading.Condition(self._lock)

    # -- lifecycle ----------------------------------------------------------

    def close(self, join_timeout_s: float = 10.0) -> None:
        self._closed = True
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._qpool.shutdown(wait=False, cancel_futures=True)
        # close() is a teardown barrier: the workers must actually be gone
        # when it returns (the soak smoke asserts zero leaked threads), but
        # a handler wedged on a dead peer must not hang close() forever,
        # and a handler that itself triggers close() must not join its own
        # thread — hence the bounded, self-excluding join.
        me = threading.current_thread()
        deadline = time.monotonic() + join_timeout_s
        for pool in (self._pool, self._qpool):
            for t in list(getattr(pool, "_threads", ())):
                if t is me:
                    continue
                t.join(max(0.0, deadline - time.monotonic()))

    def drain(self, timeout_s: float = 60.0) -> None:
        """Block until no handler is in flight (tests)."""
        deadline = time.monotonic() + timeout_s
        with self._idle:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TransportError("loopback fabric did not drain")
                self._idle.wait(remaining)

    # -- dispatch -----------------------------------------------------------

    def _post(self, fn: Callable[[], None], blocking: bool = False) -> None:
        if self._closed:
            raise TransportError("fabric closed")
        with self._lock:
            self._inflight += 1

        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — handler errors are logged
                from ..utils.log import error

                error("loopback handler error", error=repr(e))
            finally:
                with self._idle:
                    self._inflight -= 1
                    if self._inflight == 0:
                        self._idle.notify_all()

        (self._qpool if blocking else self._pool).submit(run)

    # -- pub/sub ------------------------------------------------------------

    def publish(self, topic: str, data: bytes) -> None:
        with self._lock:
            targets = [
                s
                for pat, subs in self._subs["pubsub"].items()
                if topic_matches(pat, topic)
                for s in subs
                if s.active
            ]
        for s in targets:
            self._post(lambda s=s: s.active and s.handler(data))

    def subscribe(self, pattern: str, handler: Handler, kind: str = "pubsub") -> _Sub:
        sub = _Sub(self, kind, pattern, handler)
        with self._lock:
            self._subs[kind][pattern].append(sub)
        if kind == "queue":
            self._flush_pending()
        return sub

    # -- direct (acked unicast) ---------------------------------------------

    def direct_send(self, topic: str, data: bytes, timeout_s: float = 3.0,
                    attempts: int = 3, retry_delay_s: float = 0.05) -> None:
        """Acked unicast. Each attempt posts ONE delivery and waits its
        full per-attempt budget for the ack — a slow (busy) receiver is
        waited on, never re-delivered, so a loaded system cannot amplify
        one message into a queue-flooding stream of duplicates. Re-posts
        happen only when the delivery ERRORED or no subscriber existed."""
        deadline = time.monotonic() + timeout_s * attempts
        deliveries = 0
        while True:
            done = threading.Event()
            err: List[BaseException] = []
            with self._lock:
                targets = [
                    s
                    for pat, subs in self._subs["direct"].items()
                    if topic_matches(pat, topic)
                    for s in subs
                    if s.active
                ]
            if targets:
                def run(s=targets[0]):
                    try:
                        s.handler(data)
                    except BaseException as e:  # noqa: BLE001
                        err.append(e)
                    finally:
                        done.set()

                deliveries += 1
                self._post(run)
                # wait for THIS delivery until the overall deadline
                if done.wait(max(0.0, deadline - time.monotonic())) and not err:
                    return  # acked
                if not done.is_set():
                    # still undelivered at the deadline: give the in-flight
                    # handler no duplicate sibling — just report
                    raise TransportError(
                        f"direct send to {topic!r} not acked after "
                        f"{deliveries} deliveries"
                    )
                if err and deliveries >= max(attempts, 3):
                    # handler keeps ERRORING: bounded re-delivery, never a
                    # deadline-long 50 ms re-post storm
                    raise TransportError(
                        f"direct send to {topic!r} not acked after "
                        f"{deliveries} deliveries"
                    )
            if time.monotonic() >= deadline:
                raise TransportError(
                    f"direct send to {topic!r} not acked after "
                    f"{deliveries} deliveries"
                )
            time.sleep(retry_delay_s)

    # -- durable queues -----------------------------------------------------

    def enqueue(self, topic: str, data: bytes, idempotency_key: str = "") -> None:
        if idempotency_key:
            with self._lock:
                now = time.monotonic()
                key = (topic.rsplit(".", 1)[0], idempotency_key)
                self._seen_msg_ids = {
                    k: t
                    for k, t in self._seen_msg_ids.items()
                    if now - t < self._dedup_window_s
                }
                if key in self._seen_msg_ids:
                    return  # deduped (Nats-Msg-Id semantics)
                self._seen_msg_ids[key] = now
        self._deliver_queue_msg(topic, data, deliveries=0)

    def _deliver_queue_msg(self, topic: str, data: bytes, deliveries: int) -> None:
        with self._lock:
            targets = [
                s
                for pat, subs in self._subs["queue"].items()
                if topic_matches(pat, topic)
                for s in subs
                if s.active
            ]
        if not targets:
            with self._lock:
                self._pending_queue_msgs.append((topic, data, deliveries))
            return
        target = targets[next(self._seq) % len(targets)]  # work-queue balance

        def run():
            n = deliveries + 1
            try:
                target.handler(data)
            except Permanent:
                return  # terminated, no redelivery
            except Exception:  # noqa: BLE001 — nak ⇒ redelivery
                if n >= self._queue_config.max_deliver:
                    self._fire_dead_letter(topic, data, n)
                else:
                    self._deliver_queue_msg(topic, data, n)

        self._post(run, blocking=True)

    def _flush_pending(self) -> None:
        with self._lock:
            pending, self._pending_queue_msgs = (
                list(self._pending_queue_msgs),
                deque(),
            )
        for topic, data, deliveries in pending:
            self._deliver_queue_msg(topic, data, deliveries)

    def _fire_dead_letter(self, topic: str, data: bytes, deliveries: int) -> None:
        with self._lock:
            handlers = list(self._dead_letter)
        for h in handlers:
            self._post(lambda h=h: h(topic, data, deliveries))

    def add_dead_letter_handler(self, handler: DeadLetterHandler) -> None:
        with self._lock:
            self._dead_letter.append(handler)

    # -- node-facing views --------------------------------------------------

    def transport(self) -> Transport:
        fabric = self

        class _PS(PubSub):
            def publish(self, topic, data):
                fabric.publish(topic, data)

            def publish_with_reply(self, topic, reply_topic, data):
                import json

                wrapped = json.dumps(
                    {"reply": reply_topic, "data": data.hex()}
                ).encode()
                fabric.publish(topic, wrapped)

            def subscribe(self, topic, handler):
                return fabric.subscribe(topic, handler, kind="pubsub")

        class _DM(DirectMessaging):
            def send(self, topic, data, timeout_s=None):
                if timeout_s is None:
                    fabric.direct_send(topic, data)
                else:
                    fabric.direct_send(
                        topic, data, timeout_s=timeout_s, attempts=1
                    )

            def listen(self, topic, handler):
                return fabric.subscribe(topic, handler, kind="direct")

        class _MQ(MessageQueue):
            def enqueue(self, topic, data, idempotency_key=""):
                fabric.enqueue(topic, data, idempotency_key)

            def dequeue(self, topic_filter, handler):
                return fabric.subscribe(topic_filter, handler, kind="queue")

        return Transport(
            pubsub=_PS(),
            direct=_DM(),
            queues=_MQ(),
            set_dead_letter_handler=fabric.add_dead_letter_handler,
        )
