"""Transport interfaces — the four delivery semantics of the reference's
NATS fabric (SURVEY.md §5.8):

1. ephemeral pub/sub         (protocol broadcasts, command fan-out)
2. acked unicast with retry  (protocol round unicasts; point2point.go)
3. durable idempotent queues (signing ingestion + results; message_queue.go)
4. dead-letter signaling     (max-deliveries → timeout events)

Implementations: :mod:`.loopback` (in-process test/bench fabric — the seam
the reference never built, SURVEY.md §4) and :mod:`.tcp` (multi-process).
All handlers receive raw ``bytes``.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Callable, Optional

Handler = Callable[[bytes], None]
# queue handler returns: None/ACK_OK → ack; raising → retry (nak);
# raising Permanent → terminate (no redelivery)
QueueHandler = Callable[[bytes], Optional[str]]


class Permanent(Exception):
    """Queue handler verdict: do not redeliver (reference ErrPermament,
    message_queue.go:16)."""


class Subscription(abc.ABC):
    @abc.abstractmethod
    def unsubscribe(self) -> None: ...


class PubSub(abc.ABC):
    """Reference messaging.PubSub (pubsub.go:18-72)."""

    @abc.abstractmethod
    def publish(self, topic: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def publish_with_reply(self, topic: str, reply_topic: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def subscribe(self, topic: str, handler: Handler) -> Subscription: ...


class DirectMessaging(abc.ABC):
    """Reference messaging.DirectMessaging (point2point.go:11-14): acked
    request/reply unicast with bounded retry."""

    @abc.abstractmethod
    def send(self, topic: str, data: bytes, timeout_s: Optional[float] = None) -> None:
        """Blocks until the receiver acks; raises TransportError after the
        retry budget (reference default: 3 s timeout × 3 attempts, 50 ms
        delay). ``timeout_s`` overrides the TOTAL budget with a single
        long-wait delivery — the caller's statement that a slow receiver
        is busy, not gone (batched rounds can compute for minutes), and
        must not be re-delivered to."""

    @abc.abstractmethod
    def listen(self, topic: str, handler: Handler) -> Subscription: ...


@dataclass
class QueueConfig:
    """Durable queue behavior knobs (reference message_queue.go:80-89 +
    pubsub.go:225-234)."""

    max_deliver: int = 3
    ack_wait_s: float = 30.0


class MessageQueue(abc.ABC):
    """Reference messaging.MessageQueue (message_queue.go:17-21): durable
    work queue with idempotent publish and bounded redelivery."""

    @abc.abstractmethod
    def enqueue(self, topic: str, data: bytes, idempotency_key: str = "") -> None: ...

    @abc.abstractmethod
    def dequeue(self, topic_filter: str, handler: QueueHandler) -> Subscription:
        """Deliver matching messages; handler raising ⇒ redelivery up to
        max_deliver, then dead-letter."""


DeadLetterHandler = Callable[[str, bytes, int], None]  # (topic, data, deliveries)


class TransportError(Exception):
    pass


@dataclass
class Transport:
    """Bundle handed to the node: the full fabric."""

    pubsub: PubSub
    direct: DirectMessaging
    queues: MessageQueue
    set_dead_letter_handler: Callable[[DeadLetterHandler], None] = field(
        default=lambda h: None
    )
