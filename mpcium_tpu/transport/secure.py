"""AEAD channel for the TCP bus: X25519 + PSK-bound HKDF + ChaCha20-Poly1305.

The reference's production posture is TLS to NATS with credentials
(main.go:346-359, config.prod.yaml.template). The equivalent here is an
encrypted, token-authenticated channel with no certificate infrastructure:

1. Both ends exchange fresh ephemeral X25519 public keys (one plaintext
   line each way).
2. Directional keys derive via HKDF-SHA256 from the ECDH shared secret,
   salted with both ephemerals, with SHA-256(auth token) mixed into the
   info string. An active man-in-the-middle can relay the ECDH but —
   without the token — cannot derive either key, so it can neither read
   nor forge: confidentiality AND mutual authentication rest on the
   shared token plus fresh ephemerals (forward secrecy per connection).
3. Every subsequent newline frame is ChaCha20-Poly1305 with a per-
   direction counter nonce (replay/reorder within a connection fails
   authentication), hex-encoded to stay line-framed.

Message *integrity at the application layer* additionally never depends
on the channel: protocol envelopes are Ed25519-signed end-to-end
(SECURITY.md "Transport").
"""
from __future__ import annotations

import hashlib
from typing import Tuple

try:
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.hashes import SHA256
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
except ImportError:  # bare env: RFC-vector-validated pure-python fallback
    from ..core.softcrypto import (
        HKDF,
        SHA256,
        ChaCha20Poly1305,
        X25519PrivateKey,
        X25519PublicKey,
    )


class ChannelCipher:
    """One direction pair of AEAD states with counter nonces."""

    def __init__(self, send_key: bytes, recv_key: bytes):
        self._send = ChaCha20Poly1305(send_key)
        self._recv = ChaCha20Poly1305(recv_key)
        self._send_ctr = 0
        self._recv_ctr = 0

    def encrypt(self, plaintext: bytes) -> bytes:
        nonce = self._send_ctr.to_bytes(12, "little")
        self._send_ctr += 1
        return self._send.encrypt(nonce, plaintext, None)

    def decrypt(self, ciphertext: bytes) -> bytes:
        nonce = self._recv_ctr.to_bytes(12, "little")
        self._recv_ctr += 1
        return self._recv.decrypt(nonce, ciphertext, None)  # raises on tamper


def fresh_keypair() -> Tuple[X25519PrivateKey, bytes]:
    priv = X25519PrivateKey.generate()
    return priv, priv.public_key().public_bytes_raw()


def derive_cipher(
    priv: X25519PrivateKey,
    peer_pub: bytes,
    client_pub: bytes,
    server_pub: bytes,
    token: str,
    is_server: bool,
) -> ChannelCipher:
    ss = priv.exchange(X25519PublicKey.from_public_bytes(peer_pub))
    salt = client_pub + server_pub
    token_h = hashlib.sha256(token.encode()).digest()

    def _hk(label: bytes) -> bytes:
        return HKDF(
            algorithm=SHA256(), length=32, salt=salt,
            info=b"mpcium-tpu/bus/" + label + token_h,
        ).derive(ss)

    k_c2s, k_s2c = _hk(b"c2s"), _hk(b"s2c")
    if is_server:
        return ChannelCipher(send_key=k_s2c, recv_key=k_c2s)
    return ChannelCipher(send_key=k_c2s, recv_key=k_s2c)


def hash_token(token: str) -> str:
    """Canonical stored form of a broker token: sha256:<hex>. Accepts an
    already-hashed value unchanged.

    The digest form is itself a FULL broker credential (it authenticates
    and keys the AEAD channel — deliberately so, which is how a standby
    broker configured with only the digest can follow its primary).
    Holding the digest in config instead of the raw token protects only
    one thing: a raw token reused across systems is not exposed to
    whoever reads this config. Treat ``sha256:<hex>`` values with the
    same care as the secret (SECURITY.md "Broker channel")."""
    if token.startswith("sha256:"):
        return token
    return "sha256:" + hashlib.sha256(token.encode()).hexdigest()


def token_matches(presented: str, stored: str) -> bool:
    import hmac as _hmac

    return _hmac.compare_digest(hash_token(presented), hash_token(stored))
