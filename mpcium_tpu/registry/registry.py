"""Peer registry / liveness (the Consul `ready/` analogue, pkg/mpc/registry.go).

`ready(node)` writes ``ready/<nodeID>``; a watcher polls the listing at the
reference's 1 Hz (registry.go:16), maintains the ready map/count, logs
connect/disconnect transitions, and flips cluster-ready when everyone is
present (registry.go:68-89). `resign()` removes the key on shutdown
(registry.go:198-207)."""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set

from ..store.kvstore import KVStore
from ..utils import log

READY_PREFIX = "ready/"
DEFAULT_POLL_S = 1.0  # reference registry.go:16
# First-sight tolerance: a key we have never observed change counts as
# live only while its self-reported wall stamp is within this bound of
# our clock (covers realistic cross-host skew; a SIGKILLed peer's old
# corpse key is rejected immediately, a fresh one goes dead after one
# staleness window because its value never changes). Ongoing liveness is
# purely change-based and never compares clocks.
COARSE_SKEW_S = 300.0


class PeerRegistry:
    """Reference mpc.PeerRegistry (registry.go:19-27)."""

    def __init__(
        self,
        node_id: str,
        peer_ids: List[str],
        kv: KVStore,
        poll_interval_s: float = DEFAULT_POLL_S,
    ):
        self.node_id = node_id
        self.peer_ids = sorted(set(peer_ids) | {node_id})
        self.kv = kv
        self.poll_interval_s = poll_interval_s
        self._ready_map: Set[str] = set()
        self._cluster_ready = False
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # local desired-state: heartbeats follow THIS flag, not the KV's
        # current contents — liveness keys are transient on the broker
        # control plane, so after a broker failover the key is absent on
        # the standby and a KV-presence check would silently stop
        # re-registering forever
        self._registered = False
        # pid -> (last heartbeat value, LOCAL monotonic time it changed,
        # confirmed): liveness is judged by whether a peer's heartbeat
        # value keeps CHANGING, on this observer's clock — remote wall
        # clocks are never compared against ours (cross-host skew > the
        # 5 s budget would mark healthy peers dead forever), and a key
        # merely EXISTING proves nothing (a SIGKILLed peer's stale key
        # persists; "confirmed" flips only once a change is observed)
        self._hb_seen: Dict[str, tuple] = {}

    # -- lifecycle ----------------------------------------------------------

    def ready(self) -> None:
        """Announce readiness (registry.go:93-107). The value carries a
        heartbeat timestamp; the watch loop refreshes it each tick and
        watchers treat stale entries as dead — so a SIGKILLed node that
        never ran resign() falls out of quorum instead of poisoning every
        future session (Consul achieves this with session TTLs)."""
        self._registered = True
        self._heartbeat()
        self._poll_once()

    def _heartbeat(self) -> None:
        # liveness entries are transient on KV backends that distinguish
        # (BrokerKV: no journal/replication churn at 1 Hz x N nodes)
        put = getattr(self.kv, "put_transient", self.kv.put)
        put(READY_PREFIX + self.node_id, str(time.time()).encode())

    def resign(self) -> None:
        """De-register on shutdown (registry.go:198-207)."""
        self._registered = False
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2 * self.poll_interval_s + 1)
        self.kv.delete(READY_PREFIX + self.node_id)

    def watch(self) -> None:
        """Start the background poll loop (registry.go:109-146)."""
        if self._thread:
            return
        self._thread = threading.Thread(
            target=self._watch_loop, name=f"registry-{self.node_id}", daemon=True
        )
        self._thread.start()

    # -- queries (registry.go:157-196) --------------------------------------

    def ready_count(self) -> int:
        with self._lock:
            return len(self._ready_map)

    def ready_peers(self) -> List[str]:
        with self._lock:
            return sorted(self._ready_map)

    def is_peer_ready(self, peer_id: str) -> bool:
        with self._lock:
            return peer_id in self._ready_map

    def all_ready(self) -> bool:
        with self._lock:
            return self._cluster_ready

    def wait_all_ready(self, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self._poll_once()
            if self.all_ready():
                return True
            time.sleep(min(self.poll_interval_s, 0.05))
        return False

    # -- internals ----------------------------------------------------------

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            # a KV error (broker failover window on the network control
            # plane) must not kill the watch thread: a dead loop would
            # silently stop heartbeating forever and every peer would
            # mark this node dead until a process restart
            try:
                if self._registered:
                    self._heartbeat()  # refresh own TTL while registered
                self._poll_once()
            except Exception as e:  # noqa: BLE001
                log.warn("registry poll failed; retrying",
                         node=self.node_id, error=repr(e))

    def _stale_after_s(self) -> float:
        # a peer missing 5 heartbeat periods (min 3 s) is dead
        return max(5 * self.poll_interval_s, 3.0)

    @staticmethod
    def _coarse_fresh(raw: bytes) -> bool:
        try:
            ts = float(raw)
        except (TypeError, ValueError):
            return False  # legacy "true" values: must be seen to change
        return abs(time.time() - ts) <= COARSE_SKEW_S

    def _poll_once(self) -> None:
        stale_after = self._stale_after_s()
        local_now = time.monotonic()
        now = set()
        seen_pids = set()
        # one network round-trip when the KV supports prefix scans
        # (BrokerKV); keys()+get() per peer otherwise (FileKV/MemoryKV)
        scan = getattr(self.kv, "scan", None)
        if scan is not None:
            entries = scan(READY_PREFIX).items()
        else:
            entries = [
                (k, self.kv.get(k)) for k in self.kv.keys(READY_PREFIX)
            ]
        for k, raw in entries:
            pid = k[len(READY_PREFIX):]
            if pid not in self.peer_ids or raw is None:
                continue
            seen_pids.add(pid)
            if pid == self.node_id:
                # our own registration needs no cross-checking
                if self._registered:
                    now.add(pid)
                continue
            prev = self._hb_seen.get(pid)
            if prev is None:
                # first sight: benefit of the doubt only within the
                # coarse skew bound (see COARSE_SKEW_S); confirmation —
                # and all ongoing liveness — comes from observing the
                # value CHANGE on our own clock
                self._hb_seen[pid] = (raw, local_now, False)
                if self._coarse_fresh(raw):
                    now.add(pid)
            elif prev[0] != raw:
                self._hb_seen[pid] = (raw, local_now, True)
                now.add(pid)
            elif local_now - prev[1] <= stale_after and (
                prev[2] or self._coarse_fresh(raw)
            ):
                now.add(pid)
        # explicit resign (key deleted) forgets the peer immediately
        for pid in list(self._hb_seen):
            if pid not in seen_pids:
                del self._hb_seen[pid]
        with self._lock:
            joined = now - self._ready_map
            left = self._ready_map - now
            self._ready_map = now
            was_ready = self._cluster_ready
            self._cluster_ready = now == set(self.peer_ids)
        for p in sorted(joined):
            log.info("peer ready", peer=p, node=self.node_id)
        for p in sorted(left):
            log.warn("peer disconnected!", peer=p, node=self.node_id)  # registry.go:135
        if self._cluster_ready and not was_ready:
            log.info("ALL PEERS ARE READY", node=self.node_id)  # registry.go:86
