// Batched SHA-256 / SHA-512 over fixed-width rows.
//
// The TPU engines' host hash points (commitment hashes, Fiat-Shamir
// challenges, RFC 8032 challenges) hash B independent fixed-width rows per
// round. Python's per-row hashlib loop costs ~1-2 us of interpreter
// overhead per row; this C++ path does the whole batch in one call
// (threaded across rows). Implementations follow FIPS 180-4 directly.
//
// Build: g++ -O3 -shared -fPIC -o libbatchhash.so batch_hash.cpp -lpthread
// (driven automatically by mpcium_tpu.native on first import).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- SHA-256

inline uint32_t rotr32(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

void sha256_blocks(uint32_t h[8], const uint8_t* data, size_t n_blocks) {
  for (size_t b = 0; b < n_blocks; ++b) {
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = (uint32_t(data[b * 64 + 4 * i]) << 24) |
             (uint32_t(data[b * 64 + 4 * i + 1]) << 16) |
             (uint32_t(data[b * 64 + 4 * i + 2]) << 8) |
             uint32_t(data[b * 64 + 4 * i + 3]);
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], bb = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K256[i] + w[i];
      uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
      uint32_t mj = (a & bb) ^ (a & c) ^ (bb & c);
      uint32_t t2 = S0 + mj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = bb; bb = a; a = t1 + t2;
    }
    h[0] += a; h[1] += bb; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }
}

void sha256_one(const uint8_t* msg, size_t len, uint8_t out[32]) {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  size_t full = len / 64;
  sha256_blocks(h, msg, full);
  uint8_t tail[128] = {0};
  size_t rem = len - full * 64;
  std::memcpy(tail, msg + full * 64, rem);
  tail[rem] = 0x80;
  size_t tail_blocks = (rem + 9 <= 64) ? 1 : 2;
  uint64_t bits = uint64_t(len) * 8;
  for (int i = 0; i < 8; ++i)
    tail[tail_blocks * 64 - 1 - i] = uint8_t(bits >> (8 * i));
  sha256_blocks(h, tail, tail_blocks);
  for (int i = 0; i < 8; ++i) {
    out[4 * i] = uint8_t(h[i] >> 24);
    out[4 * i + 1] = uint8_t(h[i] >> 16);
    out[4 * i + 2] = uint8_t(h[i] >> 8);
    out[4 * i + 3] = uint8_t(h[i]);
  }
}

// ---------------------------------------------------------------- SHA-512

inline uint64_t rotr64(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

const uint64_t K512[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

void sha512_blocks(uint64_t h[8], const uint8_t* data, size_t n_blocks) {
  for (size_t b = 0; b < n_blocks; ++b) {
    uint64_t w[80];
    for (int i = 0; i < 16; ++i) {
      uint64_t v = 0;
      for (int j = 0; j < 8; ++j) v = (v << 8) | data[b * 128 + 8 * i + j];
      w[i] = v;
    }
    for (int i = 16; i < 80; ++i) {
      uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
      uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = h[0], bb = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 80; ++i) {
      uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
      uint64_t ch = (e & f) ^ (~e & g);
      uint64_t t1 = hh + S1 + ch + K512[i] + w[i];
      uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
      uint64_t mj = (a & bb) ^ (a & c) ^ (bb & c);
      uint64_t t2 = S0 + mj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = bb; bb = a; a = t1 + t2;
    }
    h[0] += a; h[1] += bb; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }
}

void sha512_one(const uint8_t* msg, size_t len, uint8_t out[64]) {
  uint64_t h[8] = {0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
                   0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
                   0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
                   0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
  size_t full = len / 128;
  sha512_blocks(h, msg, full);
  uint8_t tail[256] = {0};
  size_t rem = len - full * 128;
  std::memcpy(tail, msg + full * 128, rem);
  tail[rem] = 0x80;
  size_t tail_blocks = (rem + 17 <= 128) ? 1 : 2;
  uint64_t bits = uint64_t(len) * 8;  // < 2^64; high 64 bits stay zero
  for (int i = 0; i < 8; ++i)
    tail[tail_blocks * 128 - 1 - i] = uint8_t(bits >> (8 * i));
  sha512_blocks(h, tail, tail_blocks);
  for (int i = 0; i < 8; ++i)
    for (int j = 0; j < 8; ++j)
      out[8 * i + j] = uint8_t(h[i] >> (56 - 8 * j));
}

// Thread count: MPCIUM_NATIVE_THREADS pins it (1 = deterministic
// single-thread mode, checked per call so tests can flip it);
// otherwise hardware_concurrency. Every parallelized loop writes
// disjoint output ranges, so results are bit-identical at any count.
unsigned resolve_threads() {
  const char* env = std::getenv("MPCIUM_NATIVE_THREADS");
  if (env && *env) {
    long v = std::strtol(env, nullptr, 10);
    if (v >= 1) return unsigned(v);
  }
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 4 : n;
}

template <typename F>
void parallel_rows(size_t rows, F fn) {
  unsigned n_threads = resolve_threads();
  if (n_threads == 1 || rows < 256) {
    // single-thread pin, or below the point where spawn costs more
    // than it saves
    for (size_t i = 0; i < rows; ++i) fn(i);
    return;
  }
  std::vector<std::thread> ts;
  size_t per = (rows + n_threads - 1) / n_threads;
  for (unsigned t = 0; t < n_threads; ++t) {
    size_t lo = t * per, hi = std::min(rows, lo + per);
    if (lo >= hi) break;
    ts.emplace_back([=]() {
      for (size_t i = lo; i < hi; ++i) fn(i);
    });
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// rows: B rows of row_len bytes, prefixed per-call with `prefix`
// (prefix_len bytes, shared across rows). out: B × 32 (or 64) bytes.
void batch_sha256(const uint8_t* prefix, size_t prefix_len,
                  const uint8_t* rows, size_t row_len, size_t n_rows,
                  uint8_t* out) {
  parallel_rows(n_rows, [=](size_t i) {
    std::vector<uint8_t> buf(prefix_len + row_len);
    std::memcpy(buf.data(), prefix, prefix_len);
    std::memcpy(buf.data() + prefix_len, rows + i * row_len, row_len);
    sha256_one(buf.data(), buf.size(), out + i * 32);
  });
}

void batch_sha512(const uint8_t* prefix, size_t prefix_len,
                  const uint8_t* rows, size_t row_len, size_t n_rows,
                  uint8_t* out) {
  parallel_rows(n_rows, [=](size_t i) {
    std::vector<uint8_t> buf(prefix_len + row_len);
    std::memcpy(buf.data(), prefix, prefix_len);
    std::memcpy(buf.data() + prefix_len, rows + i * row_len, row_len);
    sha512_one(buf.data(), buf.size(), out + i * 64);
  });
}

// Packed bit-matrix transpose (the OT-MtA host hot path). `packed` is
// the (kappa, m/8) extension matrix with numpy little-bitorder packing:
// bit j of row r is (packed[r][j>>3]>>(j&7))&1. Row j of `out` is the
// kappa column bits re-packed LE into kappa/8 bytes -- the per-OT
// "t row" whose prefixed hash makes the pad. The python equivalent
// materializes the unpacked (kappa, m) byte matrix plus a
// cache-hostile strided transpose copy (~130 MB per leg at m = 2^20);
// this walks the packed matrix directly and writes m*kappa/8 bytes
// once. Row hashing (with per-payload-set prefixes) rides
// batch_sha256, so a multi-set extension pays the transpose exactly
// once however many pad domains it derives.
// Fused PRG expansion (the OT-MtA host hot path next to the
// transpose). Each 32-byte seed row j expands to n_blocks SHA-256
// blocks: out[j][b] = sha256(prefix || seed_j || le16(j) ||
// le32(blk_off + b)). Identical stream to mta_ot._prg's numpy
// fallback, which materializes the full (n_seeds * n_blocks, 38)
// message matrix before hashing; this builds each 38-byte message in
// a thread-local stack buffer. blk_off lets a chunked pipeline expand
// a block sub-range that concatenates bit-exactly with its
// neighbours.
void prg_expand(const uint8_t* prefix, size_t prefix_len,
                const uint8_t* seeds, size_t n_seeds, size_t n_blocks,
                size_t blk_off, uint8_t* out) {
  parallel_rows(n_seeds * n_blocks, [=](size_t i) {
    const size_t j = i / n_blocks;
    const uint32_t blk = uint32_t(blk_off + i % n_blocks);
    std::vector<uint8_t> buf(prefix_len + 38);
    std::memcpy(buf.data(), prefix, prefix_len);
    std::memcpy(buf.data() + prefix_len, seeds + j * 32, 32);
    buf[prefix_len + 32] = uint8_t(j);
    buf[prefix_len + 33] = uint8_t(j >> 8);
    for (int k = 0; k < 4; ++k)
      buf[prefix_len + 34 + k] = uint8_t(blk >> (8 * k));
    sha256_one(buf.data(), buf.size(), out + i * 32);
  });
}

// In-place dst ^= src over n bytes, threaded in 64 KiB stripes. The
// OT-MtA masking legs (y0/y1 ^= pad, t0^t1, pad ^= payload) otherwise
// materialize a fresh ~M x 32 numpy temporary per xor.
void xor_rows(uint8_t* dst, const uint8_t* src, size_t n) {
  const size_t stripe = size_t(1) << 16;
  const size_t n_stripes = (n + stripe - 1) / stripe;
  parallel_rows(n_stripes, [=](size_t i) {
    const size_t lo = i * stripe;
    const size_t hi = lo + stripe < n ? lo + stripe : n;
    for (size_t k = lo; k < hi; ++k) dst[k] ^= src[k];
  });
}

// dst[r] ^= row for every one of n_rows rows (the U ^= r_packed
// broadcast leg).
void xor_bcast_row(uint8_t* dst, const uint8_t* row, size_t n_rows,
                   size_t row_len) {
  parallel_rows(n_rows, [=](size_t r) {
    uint8_t* d = dst + r * row_len;
    for (size_t k = 0; k < row_len; ++k) d[k] ^= row[k];
  });
}

void ot_transpose(const uint8_t* packed, size_t kappa, size_t m,
                  uint8_t* out) {
  const size_t kb = kappa / 8;
  const size_t mb = (m + 7) / 8;
  parallel_rows(m, [=](size_t j) {
    uint8_t* trow = out + j * kb;
    const size_t jb = j >> 3;
    const int js = int(j & 7);
    for (size_t t = 0; t < kb; ++t) {
      uint8_t byte = 0;
      const uint8_t* col = packed + (8 * t) * mb + jb;
      for (int s = 0; s < 8; ++s)
        byte |= uint8_t((col[size_t(s) * mb] >> js) & 1) << s;
      trow[t] = byte;
    }
  });
}

}  // extern "C"
