"""Native (C++) runtime components, loaded via ctypes.

`batch_hash`: row-batched SHA-256/512 for the engines' host hash points
(commitments, Fiat–Shamir challenges) — one call per batch instead of one
Python hashlib call per session — plus the OT-MtA host hot path:
`ot_transpose` (packed bit-matrix transpose), `prg_expand` (fused
seed → SHA-256 block expansion) and `xor_rows` (in-place masking).
Every loop threads across rows; MPCIUM_NATIVE_THREADS pins the count
(1 = deterministic single-thread mode; outputs are bit-identical at
any count — rows write disjoint ranges). Compiled with g++ on first
import and cached next to the source; falls back to hashlib/numpy
transparently if no toolchain is available.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "batch_hash.cpp"
_LIB = _HERE / "libbatchhash.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[ctypes.CDLL]:
    try:
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            subprocess.run(
                [
                    "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                    "-o", str(_LIB) + ".tmp", str(_SRC), "-lpthread",
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(str(_LIB) + ".tmp", _LIB)
        lib = ctypes.CDLL(str(_LIB))
        for fn in (lib.batch_sha256, lib.batch_sha512):
            fn.restype = None
            fn.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
                ctypes.c_void_p,
            ]
        # newer entry points bind best-effort: a stale .so missing one
        # must not disable the whole module (batch_sha256 carried
        # rounds of production use before ot_transpose existed)
        if hasattr(lib, "ot_transpose"):
            lib.ot_transpose.restype = None
            lib.ot_transpose.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
                ctypes.c_void_p,
            ]
        if hasattr(lib, "prg_expand"):
            lib.prg_expand.restype = None
            lib.prg_expand.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
                ctypes.c_size_t, ctypes.c_void_p,
            ]
        if hasattr(lib, "xor_rows"):
            lib.xor_rows.restype = None
            lib.xor_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ]
        if hasattr(lib, "xor_bcast_row"):
            lib.xor_bcast_row.restype = None
            lib.xor_bcast_row.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_size_t,
            ]
        return lib
    except Exception:  # noqa: BLE001 — no toolchain / build failure
        return None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if not _tried:
            _lib = _build()
            _tried = True
        return _lib


def available() -> bool:
    return _get_lib() is not None


def batch_sha256(prefix: bytes, rows: np.ndarray) -> np.ndarray:
    """SHA-256(prefix ‖ row) for every row of a (B, W) uint8 array → (B, 32)."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    B, W = rows.shape
    lib = _get_lib()
    out = np.empty((B, 32), dtype=np.uint8)
    if lib is not None:
        lib.batch_sha256(
            prefix, len(prefix),
            rows.ctypes.data_as(ctypes.c_void_p), W, B,
            out.ctypes.data_as(ctypes.c_void_p),
        )
        return out
    for i in range(B):
        out[i] = np.frombuffer(
            hashlib.sha256(prefix + rows[i].tobytes()).digest(), dtype=np.uint8
        )
    return out


def ot_transpose(packed: np.ndarray):
    """Packed bit-matrix transpose (see batch_hash.cpp). ``packed``:
    (kappa, m/8) uint8, numpy little-bitorder packing along the last
    axis → (m, kappa/8) re-packed column rows. None when the native
    library (or this entry point) is unavailable — caller falls back to
    the numpy unpack/T/pack path."""
    lib = _get_lib()
    if lib is None or not hasattr(lib, "ot_transpose"):
        return None
    packed = np.ascontiguousarray(packed, dtype=np.uint8)
    kappa = packed.shape[0]  # matrix rows == trow bits
    # kappa // 8 below would silently DROP the trailing bits of every
    # column for a non-multiple-of-8 kappa (safe today at KAPPA=128,
    # silent corruption for any future parameter change)
    assert kappa % 8 == 0, f"ot_transpose: kappa={kappa} not a multiple of 8"
    m = packed.shape[1] * 8
    out = np.empty((m, kappa // 8), dtype=np.uint8)
    lib.ot_transpose(
        packed.ctypes.data_as(ctypes.c_void_p), kappa, m,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out


def prg_expand(
    prefix: bytes, seeds: np.ndarray, n_blocks: int, blk_off: int = 0
):
    """Fused PRG expansion (see batch_hash.cpp): each 32-byte seed row
    j expands to ``n_blocks`` SHA-256 blocks
    sha256(prefix ‖ seed_j ‖ le16(j) ‖ le32(blk_off + b)) →
    (n_seeds, n_blocks*32). None when the native library (or this
    entry point) is unavailable — caller falls back to the numpy
    row-assembly path (bit-identical stream)."""
    lib = _get_lib()
    if lib is None or not hasattr(lib, "prg_expand"):
        return None
    seeds = np.ascontiguousarray(seeds, dtype=np.uint8)
    n_seeds = seeds.shape[0]
    assert seeds.shape[1] == 32 and n_seeds < (1 << 16)
    out = np.empty((n_seeds, n_blocks * 32), dtype=np.uint8)
    lib.prg_expand(
        prefix, len(prefix),
        seeds.ctypes.data_as(ctypes.c_void_p), n_seeds, n_blocks, blk_off,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out


def xor_rows(dst: np.ndarray, src: np.ndarray) -> np.ndarray:
    """In-place ``dst ^= src`` and return ``dst``. ``src`` is either the
    same size as ``dst`` or a single row broadcast across dst's leading
    axes. Rides the threaded native xor when built (thread count via
    MPCIUM_NATIVE_THREADS); numpy in-place otherwise — either way no
    fresh result array is materialized."""
    lib = _get_lib()
    src = np.ascontiguousarray(src, dtype=np.uint8)
    if (
        lib is None
        or not hasattr(lib, "xor_rows")
        or dst.dtype != np.uint8
        or not dst.flags.c_contiguous
        or not dst.flags.writeable
    ):
        np.bitwise_xor(dst, src, out=dst)
        return dst
    if src.size == dst.size:
        lib.xor_rows(
            dst.ctypes.data_as(ctypes.c_void_p),
            src.ctypes.data_as(ctypes.c_void_p), dst.size,
        )
    elif dst.size % src.size == 0 and hasattr(lib, "xor_bcast_row"):
        lib.xor_bcast_row(
            dst.ctypes.data_as(ctypes.c_void_p),
            src.ctypes.data_as(ctypes.c_void_p),
            dst.size // src.size, src.size,
        )
    else:
        np.bitwise_xor(dst, src, out=dst)
    return dst


def batch_sha512(prefix: bytes, rows: np.ndarray) -> np.ndarray:
    """SHA-512(prefix ‖ row) per row → (B, 64)."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    B, W = rows.shape
    lib = _get_lib()
    out = np.empty((B, 64), dtype=np.uint8)
    if lib is not None:
        lib.batch_sha512(
            prefix, len(prefix),
            rows.ctypes.data_as(ctypes.c_void_p), W, B,
            out.ctypes.data_as(ctypes.c_void_p),
        )
        return out
    for i in range(B):
        out[i] = np.frombuffer(
            hashlib.sha512(prefix + rows[i].tobytes()).digest(), dtype=np.uint8
        )
    return out
