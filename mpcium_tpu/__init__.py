"""mpcium_tpu — TPU-native threshold-signature (MPC/TSS) wallet framework.

A brand-new JAX/XLA/Pallas-first implementation of the capabilities of the
`mpcium` reference (Go, /root/reference): t-of-n distributed key generation,
GG18 ECDSA (secp256k1) and EdDSA (Ed25519) threshold signing, and committee
resharing, driven by an authenticated event plane with durable queues, peer
registry, encrypted share storage, a client SDK and ops CLI.

Unlike the reference — which runs one tss-lib session per wallet on CPU
(reference: pkg/mpc/session.go) — the cryptographic core here is batched:
multi-word modular arithmetic and curve ops are JAX kernels `vmap`ed over a
leading *session* axis, so thousands of concurrent wallets' round computations
run as one fixed-shape TPU dispatch (see SURVEY.md §2.2, §7).

Layer map (mirrors SURVEY.md §7.2 build order):
  core/       bignum limb arithmetic, prime fields, secp256k1 + ed25519,
              Paillier, hashing (host-side control plane)
  ops/        TPU-optimised kernels (Pallas / MXU paths) for the hot math
  protocol/   transport-free round state machines: eddsa + ecdsa
              keygen / signing / resharing
  engine/     the batch scheduler: pad/bucket sessions into fixed-shape
              dispatches, vmap/shard_map over the session axis
  transport/  pub/sub, acked unicast, durable idempotent queues, dead-letter
  registry/   peer liveness registry
  store/      encrypted share store + wallet keyinfo metadata
  identity/   Ed25519 node/initiator identities, envelope signing,
              passphrase-encrypted keys at rest
  node/       session factories (the reference's pkg/mpc/node.go analogue)
  consumers/  event consumers (keygen / signing / resharing / timeout)
  client/     MPCClient SDK
  cli/        ops tooling (peers / identity / initiator bootstrap)
  utils/      config, logging, serialization
"""

__version__ = "0.1.0"
