"""TPU-optimised modular-arithmetic kernels (MXU formulations).

``modmul`` carries the Barrett context whose constant multiplies ride the
MXU as Toeplitz matmuls and whose carries use a logarithmic lookahead —
the execution engine under the batched GG18 signing path (the tss-lib
Paillier/MtA arithmetic of SURVEY.md §2.3, batched over sessions).
"""
from .modmul import MXUBarrett, carry, mul_const, mul_pair, profile

__all__ = ["MXUBarrett", "carry", "mul_const", "mul_pair", "profile"]
