"""Device hash suite: one Merkle–Damgård engine, four hot-path kernels.

``ops/sha256.py`` proved that FIPS 180-4 compression lowers well to
vmapped uint32 lanes (32-bit message schedule + 64 rounds under
``lax.scan``). This module generalizes that proof into the shared
engine behind every hashing hot path the budget tracks (ROADMAP item 2;
HOST_TRANSFER_BUDGET.json):

* **SHA-256** — the existing kernel, factored here; ``ops.sha256``
  delegates so its public API is unchanged.
* **SHA-512** — 64-bit lanes as ``(hi, lo)`` uint32 limb pairs with
  explicit carry, because JAX defaults to 32-bit ints and the TPU has
  no native 64-bit integer path; 80 rounds, 128-byte blocks. Wired into
  ``engine/eddsa_batch.py::challenge_hashes`` (the Ed25519 3.1k/s
  plateau was the host SHA-512 round-trip) and usable per-session by
  ``protocol/eddsa/signing.py``.
* **PRG expansion** (``prg_expand_device``) — the IKNP seed→keystream
  expansion ``sha256(prefix ‖ seed ‖ le16(j) ‖ le32(blk))``,
  byte-identical to ``native.prg_expand`` / ``mta_ot._prg``, batched
  over (seed, block) on device.
* **Packed bit-transpose** (``ot_transpose_device``) — the (κ, M/8) ↔
  (M, κ/8) little-bitorder transpose that cost a ~130 MB strided host
  copy per extension leg in the numpy fallback.
* **Pad hash** (``pad_hash_core``) — the per-OT correlation hash
  ``H(prefix ‖ row ‖ le32(index))`` of ``mta_ot._derive_pads_multi``.

Everything here is a pure trace function plus a thin jitted wrapper, so
``mta_ot``'s device extension path can fuse PRG + transpose + pads +
masking into ONE dispatch per chunk. Domain prefixes are TRACED uint8
arrays, never static arguments: the OT tags embed a per-invocation
counter, and a static prefix would recompile every extension (the
executable is shape-keyed only — one compile per (prefix length,
batch shape) bucket).

Transcript discipline: these kernels change WHERE bytes are computed,
never the bytes. tests/test_hash_suite.py pins them against
hashlib/native/NumPy on FIPS vectors and ragged shapes, and
tests/test_mta_ot_pipeline.py + test_mta_ot_device.py prove the OT
transcripts bit-identical to the host path (OT_WIRE_VERSION stays 2).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# ---------------------------------------------------------------------------
# constants (FIPS 180-4): derived from prime roots with integer
# arithmetic — no float precision, no 80-entry transcription risk
# ---------------------------------------------------------------------------


def _primes(n: int):
    out, c = [], 2
    while len(out) < n:
        if all(c % p for p in out):
            out.append(c)
        c += 1
    return out


def _icbrt(n: int) -> int:
    x = 1 << -(-n.bit_length() // 3)
    while True:
        y = (2 * x + n // (x * x)) // 3
        if y >= x:
            return x
        x = y


def _isqrt(n: int) -> int:
    import math

    return math.isqrt(n)


_P80 = _primes(80)

# SHA-256: frac(cbrt(p)) · 2^32 and frac(sqrt(p)) · 2^32
_K256 = np.array(
    [_icbrt(p << 96) & 0xFFFFFFFF for p in _P80[:64]], dtype=np.uint32
)
_H256 = np.array(
    [_isqrt(p << 64) & 0xFFFFFFFF for p in _P80[:8]], dtype=np.uint32
)

# SHA-512: frac(cbrt(p)) · 2^64 and frac(sqrt(p)) · 2^64, as (hi, lo)
# uint32 pairs (JAX default dtypes are 32-bit; TPUs have no int64 lanes)
_K512_INT = [_icbrt(p << 192) & 0xFFFFFFFFFFFFFFFF for p in _P80]
_H512_INT = [_isqrt(p << 128) & 0xFFFFFFFFFFFFFFFF for p in _P80[:8]]
_K512_HI = np.array([k >> 32 for k in _K512_INT], dtype=np.uint32)
_K512_LO = np.array([k & 0xFFFFFFFF for k in _K512_INT], dtype=np.uint32)
_H512_HI = np.array([h >> 32 for h in _H512_INT], dtype=np.uint32)
_H512_LO = np.array([h & 0xFFFFFFFF for h in _H512_INT], dtype=np.uint32)

assert _K256[0] == 0x428A2F98 and _H256[0] == 0x6A09E667
assert _K512_INT[0] == 0x428A2F98D728AE22
assert _H512_INT[0] == 0x6A09E667F3BCC908


# ---------------------------------------------------------------------------
# SHA-256 core (factored from ops/sha256.py)
# ---------------------------------------------------------------------------


def _rotr32(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> n) | (x << (32 - n))


def sha256_compress(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """state (..., 8) uint32, block (..., 16) uint32 → new state."""

    def sched(carry_w, _):
        w = carry_w  # (..., 16) rolling window
        s0 = _rotr32(w[..., 1], 7) ^ _rotr32(w[..., 1], 18) ^ (w[..., 1] >> 3)
        s1 = (
            _rotr32(w[..., 14], 17)
            ^ _rotr32(w[..., 14], 19)
            ^ (w[..., 14] >> 10)
        )
        nxt = w[..., 0] + s0 + w[..., 9] + s1
        return jnp.concatenate([w[..., 1:], nxt[..., None]], axis=-1), w[..., 0]

    # words 0..63: first 16 from the block, rest from the rolling schedule
    _, w_all = lax.scan(sched, block, None, length=64)
    # w_all: (64, ...) — word t of the schedule

    def round_step(st, wk):
        w_t, k_t = wk
        a, b, c, d, e, f, g, h = [st[..., i] for i in range(8)]
        S1 = _rotr32(e, 6) ^ _rotr32(e, 11) ^ _rotr32(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + k_t + w_t
        S0 = _rotr32(a, 2) ^ _rotr32(a, 13) ^ _rotr32(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        return jnp.stack(
            [t1 + t2, a, b, c, d + t1, e, f, g], axis=-1
        ), None

    out, _ = lax.scan(round_step, state, (w_all, jnp.asarray(_K256)))
    return state + out


def bytes_to_words32(b: jnp.ndarray) -> jnp.ndarray:
    """(..., 4k) uint8 big-endian → (..., k) uint32."""
    k = b.shape[-1] // 4
    w = b.reshape(b.shape[:-1] + (k, 4)).astype(jnp.uint32)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) | w[..., 3]


def words32_to_bytes(w: jnp.ndarray) -> jnp.ndarray:
    out = jnp.stack(
        [(w >> 24) & 0xFF, (w >> 16) & 0xFF, (w >> 8) & 0xFF, w & 0xFF],
        axis=-1,
    ).astype(jnp.uint8)
    return out.reshape(w.shape[:-1] + (w.shape[-1] * 4,))


def _md_pad(data: jnp.ndarray, msg_len: int, block: int, len_bytes: int):
    """Merkle–Damgård strengthening: 0x80, zeros, big-endian bit length
    in the trailing ``len_bytes`` — shared by both widths."""
    pad_total = (-(msg_len + 1 + len_bytes)) % block + 1 + len_bytes
    batch = data.shape[:-1]
    pad = jnp.zeros(batch + (pad_total,), jnp.uint8)
    pad = pad.at[..., 0].set(0x80)
    bitlen = msg_len * 8
    lenb = jnp.asarray(
        [(bitlen >> (8 * i)) & 0xFF for i in range(7, -1, -1)], jnp.uint8
    )
    pad = pad.at[..., -8:].set(jnp.broadcast_to(lenb, batch + (8,)))
    return jnp.concatenate([data, pad], axis=-1)


def sha256_core(data: jnp.ndarray, msg_len: int) -> jnp.ndarray:
    """Pure trace function: (..., msg_len) uint8 → (..., 32) digests.
    Callers embedding this in a larger jitted kernel use it directly;
    standalone callers go through :func:`sha256`."""
    full = _md_pad(data, msg_len, 64, 8)
    words = bytes_to_words32(full)  # (..., 16·n_blocks)
    n_blocks = words.shape[-1] // 16
    state = jnp.broadcast_to(jnp.asarray(_H256), data.shape[:-1] + (8,))
    for i in range(n_blocks):
        state = sha256_compress(state, words[..., 16 * i : 16 * (i + 1)])
    return words32_to_bytes(state)


@functools.partial(jax.jit, static_argnames=("msg_len",))
def sha256_fixed(data: jnp.ndarray, msg_len: int) -> jnp.ndarray:
    """data (..., msg_len) uint8 → (..., 32) uint8 digests."""
    return sha256_core(data, msg_len)


def sha256(data: jnp.ndarray) -> jnp.ndarray:
    """Batched SHA-256 over the last axis: (..., L) uint8 → (..., 32)."""
    return sha256_fixed(data, data.shape[-1])


# ---------------------------------------------------------------------------
# SHA-512 core: 64-bit words as (hi, lo) uint32 pairs
# ---------------------------------------------------------------------------
#
# Every 64-bit quantity is a pair of same-shaped uint32 arrays. Addition
# carries explicitly (uint32 wraps, carry = lo_sum < lo_a); rotates and
# shifts branch STATICALLY on the amount, so each lowers to two shifts
# and an or — no 64-bit emulation library, just the five ops SHA-512
# needs.


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _rotr64(h, l, n: int):  # noqa: E741 — l is the low word
    if n == 0:
        return h, l
    if n == 32:
        return l, h
    if n > 32:
        return _rotr64(l, h, n - 32)
    return (
        (h >> n) | (l << (32 - n)),
        (l >> n) | (h << (32 - n)),
    )


def _shr64(h, l, n: int):  # noqa: E741
    if n == 0:
        return h, l
    if n >= 32:
        return jnp.zeros_like(h), h >> (n - 32) if n > 32 else h
    return h >> n, (l >> n) | (h << (32 - n))


def _xor3(a, b, c):
    return (a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1])


def sha512_compress(state_h, state_l, block_h, block_l):
    """state (..., 8)×2 uint32, block (..., 16)×2 uint32 → new state."""

    def sched(carry, _):
        wh, wl = carry  # (..., 16) rolling windows
        s0 = _xor3(
            _rotr64(wh[..., 1], wl[..., 1], 1),
            _rotr64(wh[..., 1], wl[..., 1], 8),
            _shr64(wh[..., 1], wl[..., 1], 7),
        )
        s1 = _xor3(
            _rotr64(wh[..., 14], wl[..., 14], 19),
            _rotr64(wh[..., 14], wl[..., 14], 61),
            _shr64(wh[..., 14], wl[..., 14], 6),
        )
        nh, nl = _add64(
            *_add64(*_add64(wh[..., 0], wl[..., 0], *s0),
                    wh[..., 9], wl[..., 9]),
            *s1,
        )
        return (
            jnp.concatenate([wh[..., 1:], nh[..., None]], axis=-1),
            jnp.concatenate([wl[..., 1:], nl[..., None]], axis=-1),
        ), (wh[..., 0], wl[..., 0])

    _, (w_all_h, w_all_l) = lax.scan(
        sched, (block_h, block_l), None, length=80
    )

    def round_step(st, wk):
        sh, sl = st
        w_h, w_l, k_h, k_l = wk
        ah, bh, ch_, dh, eh, fh, gh, hh = [sh[..., i] for i in range(8)]
        al, bl, cl, dl, el, fl, gl, hl = [sl[..., i] for i in range(8)]
        S1 = _xor3(
            _rotr64(eh, el, 14), _rotr64(eh, el, 18), _rotr64(eh, el, 41)
        )
        chh = (eh & fh) ^ (~eh & gh)
        chl = (el & fl) ^ (~el & gl)
        t1 = _add64(
            *_add64(*_add64(*_add64(hh, hl, *S1), chh, chl), k_h, k_l),
            w_h, w_l,
        )
        S0 = _xor3(
            _rotr64(ah, al, 28), _rotr64(ah, al, 34), _rotr64(ah, al, 39)
        )
        majh = (ah & bh) ^ (ah & ch_) ^ (bh & ch_)
        majl = (al & bl) ^ (al & cl) ^ (bl & cl)
        t2 = _add64(*S0, majh, majl)
        nah, nal = _add64(*t1, *t2)
        neh, nel = _add64(dh, dl, *t1)
        return (
            jnp.stack([nah, ah, bh, ch_, neh, eh, fh, gh], axis=-1),
            jnp.stack([nal, al, bl, cl, nel, el, fl, gl], axis=-1),
        ), None

    (out_h, out_l), _ = lax.scan(
        round_step,
        (state_h, state_l),
        (w_all_h, w_all_l, jnp.asarray(_K512_HI), jnp.asarray(_K512_LO)),
    )
    return _add64(state_h, state_l, out_h, out_l)


def sha512_core(data: jnp.ndarray, msg_len: int) -> jnp.ndarray:
    """Pure trace function: (..., msg_len) uint8 → (..., 64) digests.
    128-byte blocks; the 16-byte length field's high quadword is zero
    (messages here are far below 2^64 bits)."""
    full = _md_pad(data, msg_len, 128, 16)
    words = bytes_to_words32(full)  # (..., 32·n_blocks) — BE uint32 halves
    n_blocks = words.shape[-1] // 32
    batch = data.shape[:-1]
    sh = jnp.broadcast_to(jnp.asarray(_H512_HI), batch + (8,))
    sl = jnp.broadcast_to(jnp.asarray(_H512_LO), batch + (8,))
    for i in range(n_blocks):
        blk = words[..., 32 * i : 32 * (i + 1)]
        sh, sl = sha512_compress(sh, sl, blk[..., 0::2], blk[..., 1::2])
    # interleave (hi, lo) back into 16 BE uint32 words → 64 bytes
    out = jnp.stack([sh, sl], axis=-1).reshape(batch + (16,))
    return words32_to_bytes(out)


@functools.partial(jax.jit, static_argnames=("msg_len",))
def sha512_fixed(data: jnp.ndarray, msg_len: int) -> jnp.ndarray:
    return sha512_core(data, msg_len)


def sha512(data: jnp.ndarray) -> jnp.ndarray:
    """Batched SHA-512 over the last axis: (..., L) uint8 → (..., 64)."""
    return sha512_fixed(data, data.shape[-1])


def sha512_bytes(data: bytes) -> bytes:
    """Single-message device SHA-512 → 64 digest bytes. The per-session
    protocol path (protocol/eddsa/signing.py) can route its RFC 8032
    challenge through the batched kernel with this; the batch engines
    use :func:`sha512` directly and never leave the device."""
    arr = jnp.asarray(np.frombuffer(data, np.uint8))
    return bytes(np.asarray(sha512(arr)))  # mpcflow: host-ok — single-digest egress for the host protocol caller


# ---------------------------------------------------------------------------
# OT hot-path kernels (PRG expansion, packed transpose, pad hash)
# ---------------------------------------------------------------------------


def le16_bytes(x: jnp.ndarray) -> jnp.ndarray:
    """uint32 (...,) → (..., 2) little-endian uint8."""
    return jnp.stack([x & 0xFF, (x >> 8) & 0xFF], axis=-1).astype(jnp.uint8)


def le32_bytes(x: jnp.ndarray) -> jnp.ndarray:
    """uint32 (...,) → (..., 4) little-endian uint8."""
    return jnp.stack(
        [(x >> (8 * i)) & 0xFF for i in range(4)], axis=-1
    ).astype(jnp.uint8)


def prg_expand_core(
    seeds: jnp.ndarray, prefix: jnp.ndarray, nblk: int, blk_off
) -> jnp.ndarray:
    """Trace function: (n, 32) uint8 seeds → (n, nblk·32) keystream,
    block (j, b) = sha256(prefix ‖ seed_j ‖ le16(j) ‖ le32(blk_off+b)) —
    the exact message layout of ``native.prg_expand`` / ``mta_ot._prg``.
    ``prefix`` is a traced (P,) uint8 array (OT tags embed a counter);
    ``blk_off`` is a traced scalar (chunked callers slide it)."""
    n = seeds.shape[0]
    P = prefix.shape[0]
    j_le = le16_bytes(jnp.arange(n, dtype=jnp.uint32))  # (n, 2)
    blk = jnp.asarray(blk_off, jnp.uint32) + jnp.arange(nblk, dtype=jnp.uint32)
    blk_le = le32_bytes(blk)  # (nblk, 4)
    msg = jnp.concatenate(
        [
            jnp.broadcast_to(prefix, (n, nblk, P)),
            jnp.broadcast_to(seeds[:, None, :], (n, nblk, 32)),
            jnp.broadcast_to(j_le[:, None, :], (n, nblk, 2)),
            jnp.broadcast_to(blk_le[None, :, :], (n, nblk, 4)),
        ],
        axis=-1,
    )
    return sha256_core(msg, P + 38).reshape(n, nblk * 32)


@functools.partial(jax.jit, static_argnames=("nblk",))
def _prg_expand_jit(seeds, prefix, blk_off, nblk):
    return prg_expand_core(seeds, prefix, nblk, blk_off)


def prg_expand_device(
    prefix: bytes, seeds, nblk: int, blk_off: int = 0
) -> jnp.ndarray:
    """Standalone entry matching ``native.prg_expand``'s signature:
    (n_seeds, 32) uint8 → (n_seeds, nblk·32) device keystream."""
    pre = jnp.asarray(np.frombuffer(prefix, np.uint8))
    return _prg_expand_jit(
        jnp.asarray(seeds), pre, jnp.uint32(blk_off), nblk
    )


def pack_bits_core(bits: jnp.ndarray) -> jnp.ndarray:
    """(..., 8k) 0/1 → (..., k) packed little-bitorder uint8 (device
    twin of np.packbits(..., bitorder="little"))."""
    k = bits.shape[-1] // 8
    w = jnp.left_shift(
        jnp.uint32(1), jnp.arange(8, dtype=jnp.uint32)
    )
    grouped = bits.reshape(bits.shape[:-1] + (k, 8)).astype(jnp.uint32)
    return (grouped * w).sum(axis=-1).astype(jnp.uint8)


def unpack_bits_core(packed: jnp.ndarray) -> jnp.ndarray:
    """(..., k) uint8 → (..., 8k) 0/1 uint8, little bitorder."""
    bits = (
        packed[..., None] >> jnp.arange(8, dtype=jnp.uint8)[None, :]
    ) & 1
    return bits.reshape(packed.shape[:-1] + (packed.shape[-1] * 8,))


def ot_transpose_core(packed: jnp.ndarray) -> jnp.ndarray:
    """Trace function: (R, C) packed little-bitorder bytes → the packed
    transpose (C·8, R/8) — unpack, transpose, repack, all fused by XLA
    (no ~130 MB strided host copy; R must be a multiple of 8)."""
    R, C = packed.shape
    bits = unpack_bits_core(packed)  # (R, 8C)
    return pack_bits_core(bits.T)  # (8C, R) → (8C, R/8)


ot_transpose_device = jax.jit(ot_transpose_core)


def pad_hash_core(
    prefix: jnp.ndarray, rows: jnp.ndarray, idx_le: jnp.ndarray
) -> jnp.ndarray:
    """Trace function: per-OT correlation pads
    H(prefix ‖ row_j ‖ le32(index_j)) → (M, 32); the device twin of
    ``mta_ot._derive_pads_multi``'s per-prefix hash."""
    M = rows.shape[0]
    msg = jnp.concatenate(
        [jnp.broadcast_to(prefix, (M, prefix.shape[0])), rows, idx_le],
        axis=-1,
    )
    return sha256_core(msg, msg.shape[-1])


@jax.jit
def pad_hash_device(prefix, rows, m_off):
    idx = le32_bytes(
        jnp.asarray(m_off, jnp.uint32)
        + jnp.arange(rows.shape[0], dtype=jnp.uint32)
    )
    return pad_hash_core(prefix, rows, idx)
