"""Batched Paillier on the MXU kernels (the GG18 signing hot path).

Three measured-cost optimizations over core.paillier.PaillierBatch (which
drives the generic 11-bit einsum path with full-width exponents):

1. **Short-randomizer encryption.** Enc(m; r) = (1+mN)·r^N mod N² costs a
   2048-bit exponentiation. Fix a random unit y at key load and precompute
   h = y^N mod N²; then for a short uniform u (2·security = 256 bits),
   r = y^u and r^N = h^u — both 256-bit FIXED-BASE exponentiations
   (comb tables, one mulmod per 4-bit window ⇒ 64 + 64 mulmods instead of
   ~3000). Statistically the randomizer ranges over a 2^256-size subgroup
   of the units: ciphertext indistinguishability follows from DCR + the
   standard short-exponent assumption; the MtA/range-proof algebra is
   unchanged because the proofs only ever use the VALUE r = y^u mod N.
2. **CRT decryption.** Dec(c) works mod p² and q² (2048-bit contexts, half
   the limb width of N²) with 1024-bit constant exponents p-1, q-1, then a
   CRT combine mod q — ~3× cheaper than c^λ mod N².
3. **All multiplies ride ops.modmul** (MXU Toeplitz const-muls, lookahead
   carries).

Reference correspondence: tss-lib's paillier.{EncryptAndReturnRandomness,
Decrypt} under the GG18 rounds (SURVEY.md §2.3); the per-session Go path
becomes one fused dispatch over the session batch.

Security note (SECURITY.md "Cryptographic assumptions of the batched
engine"): the short-randomizer optimization adds a short-exponent/
subgroup-sampling assumption on top of DCR. ``MPCIUM_PAILLIER_RAND_BITS``
widens the exponent (e.g. 2176 ≥ |N|+128 for statistical uniformity over
⟨y⟩); the per-session protocol path keeps reference-equivalent uniform
randomizers.
"""
from __future__ import annotations

import os
import secrets
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core import bignum as bn
from ..core.paillier import PaillierPrivateKey, PaillierPublicKey
from . import modmul as mm

# short-randomizer exponent width (2 x 128-bit security); widen via env to
# trade speed for a weaker sampling assumption (SECURITY.md)
RAND_BITS = int(os.environ.get("MPCIUM_PAILLIER_RAND_BITS", "256"))


class PaillierMXU:
    """Batched Paillier for one public key over a session axis."""

    def __init__(self, pk: PaillierPublicKey, y: Optional[int] = None,
                 rng=secrets):
        self.pk = pk
        self.ctx_N = mm.MXUBarrett(pk.N)
        self.ctx_N2 = mm.MXUBarrett(pk.N2)
        self.prof_n = self.ctx_N.prof
        self.prof_n2 = self.ctx_N2.prof
        # short-randomizer base: y uniform unit mod N (gcd≠1 ⇒ factoring N)
        self.y = y if y is not None else (rng.randbelow(pk.N - 2) + 2)
        self.h = pow(self.y, pk.N, pk.N2)
        self._N_T = mm._const_matrices(pk.N, self.prof_n.n_limbs)

    # -- host <-> device ----------------------------------------------------

    def to_limbs_N(self, xs) -> np.ndarray:
        return bn.batch_to_limbs(xs, self.prof_n)

    def to_limbs_N2(self, xs) -> np.ndarray:
        return bn.batch_to_limbs(xs, self.prof_n2)

    def from_limbs_N(self, arr) -> list:
        return bn.batch_from_limbs(np.asarray(arr), self.prof_n)

    def from_limbs_N2(self, arr) -> list:
        return bn.batch_from_limbs(np.asarray(arr), self.prof_n2)

    # -- kernels ------------------------------------------------------------

    def enc_deterministic(self, m_limbs: jnp.ndarray) -> jnp.ndarray:
        """(1 + m·N) mod N² for m < N (the g^m leg; exact, no reduction
        needed since (1+mN) < N²)."""
        mN = mm.carry(mm.mul_const(m_limbs, self._N_T))
        out = bn.take_limbs(mN, 0, self.prof_n2.n_limbs).at[..., 0].add(1)
        return mm.carry(out)

    def encrypt(
        self, m_limbs: jnp.ndarray, u_bits: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """c = (1+mN)·h^u mod N², r = y^u mod N.

        ``u_bits`` (..., RAND_BITS) int32 CSPRNG bits. Returns (c, r); r is
        the effective Paillier randomizer (c == (1+mN)·r^N), which the MtA
        range proofs consume.
        """
        hu = self.ctx_N2.powmod_fixed_base(self.h, u_bits)
        c = self.ctx_N2.mulmod(self.enc_deterministic(m_limbs), hu)
        r = self.ctx_N.powmod_fixed_base(self.y % self.pk.N, u_bits)
        return c, r

    def add(self, c1: jnp.ndarray, c2: jnp.ndarray) -> jnp.ndarray:
        return self.ctx_N2.mulmod(c1, c2)

    def scalar_mul(self, c: jnp.ndarray, k_bits: jnp.ndarray) -> jnp.ndarray:
        return self.ctx_N2.powmod(c, k_bits)


class PaillierMXUPrivate(PaillierMXU):
    """Adds CRT decryption (private-key holder side)."""

    def __init__(self, sk: PaillierPrivateKey, y: Optional[int] = None,
                 rng=secrets):
        super().__init__(sk.public, y=y, rng=rng)
        self.sk = sk
        p, q = sk.p, sk.q
        self.ctx_p2 = mm.MXUBarrett(p * p)
        self.ctx_q2 = mm.MXUBarrett(q * q)
        self.ctx_p = mm.MXUBarrett(p)
        self.ctx_q = mm.MXUBarrett(q)
        # L_p(x) = (x-1)/p as multiplication by p^-1 mod R^k (x-1 is an
        # exact multiple of p, so the low limbs of the product are exact)
        kp = self.ctx_p2.prof.n_limbs
        kq = self.ctx_q2.prof.n_limbs
        Rp = 1 << (mm.LIMB_BITS * kp)
        Rq = 1 << (mm.LIMB_BITS * kq)
        self._pinv_T = mm._const_matrices(pow(p, -1, Rp), kp)
        self._qinv_T = mm._const_matrices(pow(q, -1, Rq), kq)
        # h_p = L_p((1+N)^(p-1) mod p²)^-1 mod p, and mod-q twin
        def _L(x: int, r: int) -> int:
            return (x - 1) // r

        self.h_p = pow(_L(pow(1 + sk.N, p - 1, p * p), p), -1, p)
        self.h_q = pow(_L(pow(1 + sk.N, q - 1, q * q), q), -1, q)
        # CRT combine: m = m_p + p·((m_q - m_p)·p^-1 mod q)
        self.p_inv_mod_q = pow(p, -1, q)
        self._p_T_wide = mm._const_matrices(p, self.ctx_q.prof.n_limbs)

    def _half_decrypt(self, c, ctx2, ctx1, r: int, hr: int, inv_T) -> jnp.ndarray:
        """m_r = L_r(c^(r-1) mod r²)·h_r mod r → limbs in ctx1's profile."""
        u = ctx2.powmod_const_exp(ctx2.reduce(c), r - 1)
        # u - 1 via the complement trick (u-1 may have long borrow chains,
        # which the fast lookahead carry does not handle): u + (R^k - 1)
        # mod R^k == u - 1 for u ≥ 1.
        k = ctx2.prof.n_limbs
        u_minus = mm.carry(bn.pad_limbs(u + mm.MASK, 1))[..., :k]
        L = mm.carry(mm.mul_const(u_minus, inv_T))[..., :k]
        # exact division: L = (u-1)/r < r — fits the mod-r context
        return ctx1.mulmod_const(bn.take_limbs(L, 0, ctx1.prof.n_limbs), hr)

    def decrypt(self, c: jnp.ndarray) -> jnp.ndarray:
        """Batched CRT decrypt → plaintext limbs mod N (prof_n)."""
        sk = self.sk
        p, q = sk.p, sk.q
        m_p = self._half_decrypt(
            c, self.ctx_p2, self.ctx_p, p, self.h_p, self._pinv_T
        )
        m_q = self._half_decrypt(
            c, self.ctx_q2, self.ctx_q, q, self.h_q, self._qinv_T
        )
        # t = (m_q - m_p) · p^-1 mod q
        nq = self.ctx_q.prof.n_limbs
        mq_q = self.ctx_q.reduce(bn.take_limbs(m_q, 0, nq))
        mp_q = self.ctx_q.reduce(bn.take_limbs(m_p, 0, nq))
        t = self.ctx_q.mulmod_const(
            self.ctx_q.submod(mq_q, mp_q), self.p_inv_mod_q
        )
        # m = m_p + p·t  (< p·q = N; exact, no modular reduction needed)
        pt = mm.carry(mm.mul_const(t, self._p_T_wide))
        n = self.prof_n.n_limbs
        return mm.carry(
            bn.take_limbs(pt, 0, n) + bn.take_limbs(m_p, 0, n)
        )
