"""Fused Pallas TPU kernel for batched mulmod (the GG18 hot op).

The XLA band-GEMM path (`ops.modmul._k_mulmod`) materializes the Toeplitz
band (~78 MB bf16 at B=1024/4096-bit) and the block products (~93 MB f32)
in HBM between fusions — PERFORMANCE.md "kernel gaps" #1 puts the
resulting traffic floor at ~0.25-0.35 ms out of the measured 1.82 ms
mulmod. This kernel keeps the ENTIRE mulmod — pairwise product, carry
normalization, both Barrett constant legs, and the trailing conditional
subtractions — inside one `pallas_call`, so per batch-tile the only HBM
traffic is x, y in and the result out (~0.9 MB per 128 rows at 4096-bit
vs ~170 MB total today).

Design notes (why it looks nothing like a GPU bignum kernel):

* **The pairwise product cannot ride the MXU.** A batched x·y product
  needs a per-element operand matrix (the Toeplitz band of y_b differs
  for every b), and the systolic array only amortizes SHARED operands.
  Instead the product runs on the VPU as a shift-and-FMA convolution in
  f32 — exact, because 7-bit limbs give partial products ≤ 127² and any
  convolution column sums ≤ `occ` of them: occ·127² < 2²⁴ for moduli up
  to ~7280 bits (the same exactness budget `ops.modmul.mul_const` uses).
  Eight phase accumulators S_r (r = 0..7) turn 1-lane shifts into one
  8-lane shift per 8 FMA sweeps:
      conv = Σ_r shift_r(S_r),   S_r = Σ_q shift_{8q}(x) · y[8q+r]
* **The Barrett legs DO ride the MXU.** µ and m are shared across the
  batch, so `q1 @ T_µ` and `q3 @ T_m` are plain 2D bf16 matmuls with f32
  accumulation (bit-exact below 2²⁴), issued from inside the kernel on
  VMEM-resident constant tiles that persist across grid steps.
* **Carries are lane-axis passes.** Three shift-and-add roll passes bound
  limbs ≤ 135, then a Hillis–Steele doubling pass over the
  generate/propagate semiring replaces `lax.associative_scan` (which
  Mosaic does not lower). All shifts are static `jnp.concatenate` slices
  — no `pltpu.roll` — so the kernel also runs under `interpret=True` for
  CPU-exactness tests.

Same reduction algebra as `ops.modmul._reduce_impl` (HAC Alg. 14.42);
bit-for-bit equality against `core.bignum` host ints is property-tested
in tests/test_pallas_mulmod.py. Selected via MPCIUM_MULMOD=pallas (see
`ops.modmul.mulmod`). Reference correspondence: this executes the
tss-lib Paillier/MtA arithmetic the reference delegates to
(SURVEY.md §2.3); the leading axis is the concurrent-session batch.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LIMB_BITS = 7
MASK = (1 << LIMB_BITS) - 1


def _roundup(v: int, m: int) -> int:
    return -(-v // m) * m


def _shift_up(x: jnp.ndarray, k: int, fill: int = 0):
    """shift limbs toward HIGHER lane index by k (value · R^k), static k."""
    if k == 0:
        return x
    pad = jnp.full(x.shape[:-1] + (k,), fill, x.dtype)
    return jnp.concatenate([pad, x[..., :-k]], axis=-1)


def _carry_int(v: jnp.ndarray) -> jnp.ndarray:
    """Exact carry normalization along the lane axis — the in-kernel
    port of `ops.modmul.carry` (3 roll passes then carry-lookahead; input
    contract limb < 127·2²¹). The lookahead runs as a Hillis–Steele
    doubling over the (generate, propagate) semiring: identity shifts in
    g=0 / p=1."""
    for _ in range(3):
        v = (v & MASK) + _shift_up(v >> LIMB_BITS, 1)
    g = v >> LIMB_BITS  # 0/1 after the roll passes
    r = v & MASK
    p = (r == MASK).astype(jnp.int32)
    d = 1
    n = v.shape[-1]
    while d < n:
        gs = _shift_up(g, d, fill=0)
        ps = _shift_up(p, d, fill=1)
        g = g | (p & gs)
        p = p & ps
        d *= 2
    return (r + _shift_up(g, 1)) & MASK


def _mulmod_kernel(
    x_ref, y2_ref, tmu_ref, tm_ref, comp_ref, out_ref, *, occ: int,
    n_pad: int, frame: int, l1: int
):
    tb = x_ref.shape[0]
    f32 = jnp.float32

    # ---- stage 1: pairwise product as a VPU shift-FMA convolution -----
    xf = jnp.pad(
        x_ref[:].astype(f32), ((0, 0), (0, frame - n_pad))
    )  # (tb, frame)
    nq = y2_ref.shape[0]  # ceil(occ/8); y zero above occ

    # y arrives pre-arranged as (nq, tb, 8): Mosaic only allows dynamic
    # lane-dim offsets it can prove 128-aligned, so the q-loop indexes
    # the LEADING dim (dynamic ok) and the 8 per-phase scalars are
    # static lane slices broadcast along the frame.
    def q_body(q, st):
        xc = st[0]
        ss = list(st[1:])
        yq = y2_ref[q].astype(f32)  # (tb, 8)
        for r in range(8):
            ss[r] = ss[r] + xc * yq[:, r:r + 1]
        return (_shift_up(xc, 8),) + tuple(ss)

    zeros = jnp.zeros((tb, frame), f32)
    st = lax.fori_loop(
        0, nq, q_body, (xf,) + tuple(zeros for _ in range(8))
    )
    acc = st[1]
    for r in range(1, 8):
        acc = acc + _shift_up(st[1 + r], r)

    # f32 column sums ≤ occ·127² < 2²⁴ ⇒ exact; normalize in int32
    prod = _carry_int(acc.astype(jnp.int32))  # (tb, frame)

    # ---- stage 2: Barrett reduction (MXU constant legs) ----------------
    # q1 = prod >> (occ-1) limbs over the 2n-limb product window
    q1 = prod[:, occ - 1:occ - 1 + l1]  # (tb, l1)
    q2 = _carry_int(
        jnp.dot(
            q1.astype(jnp.bfloat16), tmu_ref[:],
            preferred_element_type=f32,
        ).astype(jnp.int32)
    )  # (tb, c1)
    q3 = q2[:, occ + 1:]  # (tb, l3)
    # only limbs [0, occ+1) of q3·m are consumed; carries propagate
    # upward, so the Toeplitz is pre-sliced to occ+2 columns
    q3m = _carry_int(
        jnp.dot(
            q3.astype(jnp.bfloat16), tm_ref[:],
            preferred_element_type=f32,
        ).astype(jnp.int32)
    )  # (tb, occ+2)

    # r = x - q3·m over occ+1 limbs via the elementwise radix complement
    # (keeps limbs non-negative for the carry; the spurious R^(occ+1)
    # lands exactly in limb occ+1 and is dropped by the slice)
    one0 = jnp.pad(
        jnp.ones((tb, 1), jnp.int32), ((0, 0), (0, occ + 1))
    )
    t = jnp.pad(
        prod[:, :occ + 1] + (MASK - q3m[:, :occ + 1]),
        ((0, 0), (0, 1)),
    ) + one0
    r1 = _carry_int(t)[:, :occ + 1]

    comp = comp_ref[:]  # (1, occ+2)

    def cond_sub(rr):
        u = _carry_int(jnp.pad(rr, ((0, 0), (0, 1))) + comp)
        ge = (u[:, occ + 1] >= 1)[:, None]
        return jnp.where(ge, u[:, :occ + 1], rr)

    r1 = cond_sub(cond_sub(r1))
    out_ref[:] = jnp.pad(r1[:, :occ], ((0, 0), (0, n_pad - occ)))


@functools.partial(
    jax.jit,
    static_argnames=("occ", "n", "tb", "interpret"),
)
def _mulmod_call(
    x, y, tmu_p, tm_p, comp_p, occ: int, n: int, tb: int, interpret: bool
):
    """Single fused mulmod dispatch. x, y: (B, n) normalized int32 limbs,
    B a multiple of tb. Constants pre-padded by `_consts_for`."""
    b = x.shape[0]
    n_pad = _roundup(n, 128)
    # conv frame: highest nonzero conv lane < 2·occ + 14 (phase shifts);
    # Barrett's q1 window needs lanes < 2n
    frame = _roundup(max(2 * n, 2 * occ + 16), 128)
    l1 = 2 * n - occ + 1
    xp = jnp.pad(x, ((0, 0), (0, n_pad - n)))
    # pre-arrange y as (nq, B, 8): y2[q, b, r] = y[b, 8q+r] (see kernel)
    nq = -(-occ // 8)
    ypad = max(0, 8 * nq - n)
    y2 = jnp.pad(y, ((0, 0), (0, ypad)))[:, :8 * nq]
    y2 = y2.reshape(b, nq, 8).transpose(1, 0, 2)
    kernel = functools.partial(
        _mulmod_kernel, occ=occ, n_pad=n_pad, frame=frame, l1=l1
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n_pad), jnp.int32),
        grid=(b // tb,),
        in_specs=[
            pl.BlockSpec((tb, n_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nq, tb, 8), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(tmu_p.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(tm_p.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(comp_p.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tb, n_pad), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xp, y2, tmu_p, tm_p, comp_p)
    return out[:, :n]


def _consts_for(T_mu, T_m, comp, occ: int, n: int):
    """Kernel-shaped views of the MXUBarrett operands: T_mu sliced to the
    q1 row count, T_m to the q3 rows × (occ+2) consumed columns, comp as
    a broadcastable row."""
    l1 = 2 * n - occ + 1
    tmu_p = T_mu[:l1]  # (l1, c1)
    c1 = tmu_p.shape[1]
    l3 = c1 - occ - 1
    tm_p = T_m[:l3, :occ + 2]
    comp_p = comp.reshape(1, occ + 2).astype(jnp.int32)
    return tmu_p, tm_p, comp_p


def _pick_tile(b: int) -> int:
    for tb in (64, 32, 16, 8):
        if b % tb == 0:
            return tb
    return 0  # pad to 8 below


def mulmod(a, b, T_mu, T_m, comp, occ: int, n: int, interpret: bool):
    """Fused a·b mod m. a, b: (..., n) normalized int32 limbs. Drop-in
    for `ops.modmul._k_mulmod` given the same context operands."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    lead = shape[:-1]
    a2 = jnp.broadcast_to(a, shape).reshape(-1, n)
    b2 = jnp.broadcast_to(b, shape).reshape(-1, n)
    B = a2.shape[0]
    tb = _pick_tile(B)
    if tb == 0:
        bp = _roundup(B, 8)
        a2 = jnp.pad(a2, ((0, bp - B), (0, 0)))
        b2 = jnp.pad(b2, ((0, bp - B), (0, 0)))
        tb = 8
    tmu_p, tm_p, comp_p = _consts_for(T_mu, T_m, comp, occ, n)
    out = _mulmod_call(a2, b2, tmu_p, tm_p, comp_p, occ, n, tb, interpret)
    return out[:B].reshape(lead + (n,))
