"""Batched SHA-256 on device (FIPS 180-4), fixed message length.

The batched GG18 fabric derives hash commitments and Fiat–Shamir
challenges from fixed-width byte serializations of round tensors. Hashing
them on host costs a device→host transfer plus a Python hashlib loop per
round (~25k calls per 4096-session batch — deadly on a single host core).
Here the whole batch hashes as one fused device dispatch: 32-bit message
schedule + compression expressed over (B,) uint32 lanes, messages padded to
a static block count at trace time.

Reference correspondence: replaces the per-session SHA-256 commitments the
reference gets from Go crypto/sha256 via tss-lib (commitment scheme used in
GG18 rounds; SURVEY.md §2.3).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> n) | (x << (32 - n))


def _compress(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """state (..., 8) uint32, block (..., 16) uint32 → new state."""

    def sched(carry_w, _):
        w = carry_w  # (..., 16) rolling window
        s0 = _rotr(w[..., 1], 7) ^ _rotr(w[..., 1], 18) ^ (w[..., 1] >> 3)
        s1 = _rotr(w[..., 14], 17) ^ _rotr(w[..., 14], 19) ^ (w[..., 14] >> 10)
        nxt = w[..., 0] + s0 + w[..., 9] + s1
        return jnp.concatenate([w[..., 1:], nxt[..., None]], axis=-1), w[..., 0]

    # words 0..63: first 16 from the block, rest from the rolling schedule
    _, w_all = lax.scan(sched, block, None, length=64)
    # w_all: (64, ...) — word t of the schedule

    def round_step(st, wk):
        w_t, k_t = wk
        a, b, c, d, e, f, g, h = [st[..., i] for i in range(8)]
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + k_t + w_t
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        return jnp.stack(
            [t1 + t2, a, b, c, d + t1, e, f, g], axis=-1
        ), None

    out, _ = lax.scan(round_step, state, (w_all, jnp.asarray(_K)))
    return state + out


def _bytes_to_words(b: jnp.ndarray) -> jnp.ndarray:
    """(..., 4k) uint8 big-endian → (..., k) uint32."""
    k = b.shape[-1] // 4
    w = b.reshape(b.shape[:-1] + (k, 4)).astype(jnp.uint32)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) | w[..., 3]


def _words_to_bytes(w: jnp.ndarray) -> jnp.ndarray:
    out = jnp.stack(
        [(w >> 24) & 0xFF, (w >> 16) & 0xFF, (w >> 8) & 0xFF, w & 0xFF],
        axis=-1,
    ).astype(jnp.uint8)
    return out.reshape(w.shape[:-1] + (w.shape[-1] * 4,))


@functools.partial(jax.jit, static_argnames=("msg_len",))
def _sha256_fixed(data: jnp.ndarray, msg_len: int) -> jnp.ndarray:
    """data (..., msg_len) uint8 → (..., 32) uint8 digests."""
    pad_total = (-(msg_len + 9)) % 64 + 9
    n_blocks = (msg_len + pad_total) // 64
    batch = data.shape[:-1]
    pad = jnp.zeros(batch + (pad_total,), jnp.uint8)
    pad = pad.at[..., 0].set(0x80)
    bitlen = msg_len * 8
    lenb = jnp.asarray(
        [(bitlen >> (8 * i)) & 0xFF for i in range(7, -1, -1)], jnp.uint8
    )
    pad = pad.at[..., -8:].set(jnp.broadcast_to(lenb, batch + (8,)))
    full = jnp.concatenate([data, pad], axis=-1)
    words = _bytes_to_words(full)  # (..., 16·n_blocks)
    state = jnp.broadcast_to(jnp.asarray(_H0), batch + (8,))
    for i in range(n_blocks):
        state = _compress(state, words[..., 16 * i : 16 * (i + 1)])
    return _words_to_bytes(state)


def sha256(data: jnp.ndarray) -> jnp.ndarray:
    """Batched SHA-256 over the last axis: (..., L) uint8 → (..., 32)."""
    return _sha256_fixed(data, data.shape[-1])
