"""Batched SHA-256 on device (FIPS 180-4), fixed message length.

The batched GG18 fabric derives hash commitments and Fiat–Shamir
challenges from fixed-width byte serializations of round tensors. Hashing
them on host costs a device→host transfer plus a Python hashlib loop per
round (~25k calls per 4096-session batch — deadly on a single host core).
Here the whole batch hashes as one fused device dispatch: 32-bit message
schedule + compression expressed over (B,) uint32 lanes, messages padded to
a static block count at trace time.

The Merkle–Damgård core now lives in :mod:`ops.hash_suite` (it also
powers the device SHA-512, the IKNP PRG expansion and the OT pad
hashing — ROADMAP item 2); this module keeps the original public
surface and delegates.

Reference correspondence: replaces the per-session SHA-256 commitments the
reference gets from Go crypto/sha256 via tss-lib (commitment scheme used in
GG18 rounds; SURVEY.md §2.3).
"""
from __future__ import annotations

import jax.numpy as jnp

from .hash_suite import (  # noqa: F401 — re-exported compatibility surface
    _H256 as _H0,
    _K256 as _K,
    _rotr32 as _rotr,
    bytes_to_words32 as _bytes_to_words,
    sha256_compress as _compress,
    sha256_fixed as _sha256_fixed,
    words32_to_bytes as _words_to_bytes,
)

__all__ = [
    "_H0", "_K", "_rotr", "_bytes_to_words", "_compress",
    "_sha256_fixed", "_words_to_bytes", "sha256",
]


def sha256(data: jnp.ndarray) -> jnp.ndarray:
    """Batched SHA-256 over the last axis: (..., L) uint8 → (..., 32)."""
    return _sha256_fixed(data, data.shape[-1])
