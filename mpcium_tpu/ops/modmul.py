"""MXU-formulated batched modular arithmetic (the hot path of GG18).

The generic engine in :mod:`core.bignum` expresses everything as int32
einsums and sequential carry scans — correct, but it leaves the MXU idle
and serializes on limb-length scans. This module re-formulates the same
operations around three measured-on-chip facts (TPU v5e, B=4096, 4096-bit
operands; measurements from the on-chip microbenches):

1. **Multiplication by a per-modulus constant is a Toeplitz matmul.**
   Barrett reduction multiplies by two constants (mu and m). With 7-bit
   limbs both operands are exact in bf16 and every f32 partial sum stays
   below 2^24, so ``x @ Toeplitz(c)`` runs on the MXU at full bf16 speed
   with bit-exact integer results (~0.04 ms vs 0.33 ms for the int32
   einsum product).
2. **Carry propagation does not need an O(n) scan.** Three shift-and-add
   roll passes bound every limb by 135, after which carries are 0/1 and a
   logarithmic carry-lookahead (``lax.associative_scan`` over the classic
   generate/propagate semiring) finishes exact normalization.
3. **Conditional subtraction needs no lexicographic compare.** Adding the
   radix-complement constant R^k - m and inspecting the top carry limb
   gives the borrow bit and the difference in one carry pass.

Pairwise (batched x batched) products keep the blocked-einsum form of
``bignum.mul_wide`` but in the 7-bit limb family, which measured 3.8x
faster than the 11-bit family (0.088 ms vs 0.333 ms at B=4096) — XLA maps
the small-block einsum far better at 32-aligned widths with small values.

Reference correspondence: this is the execution engine for the tss-lib
Paillier/MtA arithmetic (SURVEY.md §2.3; reference delegates to
bnb-chain/tss-lib — pkg/mpc/ecdsa_signing_session.go drives it one session
at a time). Here the leading axis is the concurrent-session batch.

Representation: little-endian int32 limb tensors, 7 bits per limb
(radix 128), shape (..., n_limbs) — normalized unless stated otherwise.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import bignum as bn
from ..utils import log


def _jit_method(fn=None, *, static_argnums=(0,)):
    """jit with `self` static (instances hash by identity; each context
    owns its jit cache). Keeps the per-modulus Toeplitz/comb constants out
    of call signatures — they embed as compile-time constants."""
    if fn is None:
        return lambda f: jax.jit(f, static_argnums=static_argnums)
    return jax.jit(fn, static_argnums=static_argnums)

LIMB_BITS = 7
RADIX = 1 << LIMB_BITS
MASK = RADIX - 1

# blocked pairwise product: 32-limb blocks (same shape bignum.mul_wide uses)
_BLOCK = 32


def profile(value_bits: int) -> bn.LimbProfile:
    """7-bit limb profile sized for ``value_bits``, block-aligned."""
    n = -(-value_bits // LIMB_BITS)
    n = -(-n // _BLOCK) * _BLOCK  # pad to block multiple: einsum + matmul tile
    return bn.LimbProfile(bits=LIMB_BITS, n_limbs=n)


# ---------------------------------------------------------------------------
# carries: roll passes + logarithmic carry-lookahead
# ---------------------------------------------------------------------------


def _roll_pass(x: jnp.ndarray) -> jnp.ndarray:
    """One shift-and-add carry pass (keeps the value, shrinks the limbs)."""
    hi = x >> LIMB_BITS
    lo = x & MASK
    return lo + jnp.pad(hi, [(0, 0)] * (x.ndim - 1) + [(1, 0)])[..., :-1]


def carry(x: jnp.ndarray) -> jnp.ndarray:
    """Exact normalization of non-negative redundant limbs (total value
    must fit the limb count; same contract as bignum.carry minus
    negative-limb support).

    Exactness bound: each roll pass maps a limb bound M to 127 + M/128,
    so after three passes limbs are ≤ 127 + M/2²¹ + ~1; the lookahead
    stage needs limbs ≤ 255 (carries 0/1), giving the input contract
    **limb < 127·2²¹ ≈ 2^27.99**. Callers on the narrow paths stay below
    2²⁴; the i8 wide fallback approaches the true bound and is guarded
    at its call site.
    """
    x = _roll_pass(_roll_pass(_roll_pass(x)))
    # now 0 <= limb <= 135: incoming carries are 0/1
    g = (x >> LIMB_BITS).astype(jnp.int32)  # generate: 0/1
    r = x & MASK
    p = (r == MASK).astype(jnp.int32)  # propagate

    def op(a, b):
        ga, pa = a
        gb, pb = b
        return gb | (pb & ga), pb & pa

    G, _ = lax.associative_scan(op, (g, p), axis=-1)
    cin = jnp.pad(G, [(0, 0)] * (x.ndim - 1) + [(1, 0)])[..., :-1]
    return (r + cin) & MASK


# ---------------------------------------------------------------------------
# products
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _toeplitz_np(c_limbs: Tuple[int, ...], n_in: int) -> np.ndarray:
    """(n_in, n_in + len(c) - 1) f32 band matrix T[i, i+j] = c[j]."""
    m = len(c_limbs)
    T = np.zeros((n_in, n_in + m - 1), dtype=np.float32)
    for j, cj in enumerate(c_limbs):
        if cj:
            T[np.arange(n_in), np.arange(n_in) + j] = float(cj)
    return T


def _const_matrices(
    value: int, n_in: int, min_limbs: int = 1
) -> jnp.ndarray:
    limbs = []
    v = value
    while v:
        limbs.append(v & MASK)
        v >>= LIMB_BITS
    while len(limbs) < min_limbs:
        limbs.append(0)  # width-pad so same-modulus constants share shapes
    return jnp.asarray(_toeplitz_np(tuple(limbs), n_in), jnp.bfloat16)


def ints_to_limbs(vals, prof: bn.LimbProfile) -> np.ndarray:
    """Bulk python-int → limb conversion via byte packing (numpy-speed;
    bn.to_limbs is a per-limb python loop — too slow for comb tables)."""
    nbytes = -(-prof.bits * prof.n_limbs // 8)
    raw = np.frombuffer(
        b"".join(int(v).to_bytes(nbytes, "little") for v in vals),
        dtype=np.uint8,
    ).reshape(len(vals), nbytes)
    bits = np.unpackbits(raw, axis=-1, bitorder="little")[
        :, : prof.bits * prof.n_limbs
    ]
    groups = bits.reshape(len(vals), prof.n_limbs, prof.bits)
    weights = (1 << np.arange(prof.bits)).astype(np.int64)
    return (groups * weights).sum(-1).astype(np.int32)


def mul_const(x: jnp.ndarray, T: jnp.ndarray) -> jnp.ndarray:
    """x (normalized limbs) times a constant via its Toeplitz matrix →
    UNNORMALIZED int32 columns (each < n_in·127² < 2^24; caller carries).

    Exact: 7-bit limbs are exact bf16 values, partial products ≤ 127²
    are exact, and f32 accumulation stays integral below 2^24 (requires
    n_in ≤ 1040 limbs ⇒ moduli up to ~7280 bits).
    """
    assert x.shape[-1] == T.shape[0] and x.shape[-1] <= 1040
    out = lax.dot_general(
        x.astype(jnp.bfloat16),
        T,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return out.astype(jnp.int32)


import os

# Pairwise-product strategy: "bf16" (default — blocked einsum with bf16
# multiplicands and f32 accumulation, exact for 7-bit limbs, rides the
# MXU's native bf16 path) or "i32" (the round-3 blocked int32 einsum,
# kept as an escape hatch / differential-test oracle via MPCIUM_MULPAIR).
MULPAIR_STRATEGY = os.environ.get("MPCIUM_MULPAIR", "bf16")

# lax.scan body unrolling for exponentiation windows: each step is ~5
# mulmods (4 squarings + 1 table multiply); unrolling amortizes the TPU
# while-loop per-step overhead (PERFORMANCE.md gap 3) at the price of a
# proportionally larger compile. Default stays 1: on this 1-core host
# compile time is the scarcer resource than scan-step overhead (it ate
# two bench windows already, PERFORMANCE.md); flip via MPCIUM_SCAN_UNROLL
# once the on-chip microbench (.scratch/chipcheck.py) proves the win.
SCAN_UNROLL = int(os.environ.get("MPCIUM_SCAN_UNROLL", "1"))

# Fixed-base comb window width (bits). Combs have no squarings, so the
# mulmod count scales 1/w while table size scales 2^w/w; 8 halves the
# wide-exponent ring-Pedersen legs vs 4. Per-element-base powmods keep
# 4-bit windows (squarings dominate there; wider windows barely help).
COMB_W = int(os.environ.get("MPCIUM_COMB_W", "8"))

# Dispatch audit: set to a dict to accumulate mulmod-equivalent counts
# per (op, modulus-bits); None disables (no overhead on the hot path).
AUDIT = None

# Cumulative device-resident comb/constant table bytes across ALL contexts
# in this process. COMB_W=8 costs ~16x the table memory of w=4 (~100 MB
# per (base, 2048-bit modulus) comb, ~200 MB per counterparty NTilde), so
# larger committees can pressure HBM with nothing attributing it; each
# build is logged and crossing the soft cap warns once per GB.
_FB_TABLE_BYTES = 0
_FB_TABLE_WARN_GB = float(os.environ.get("MPCIUM_FB_TABLE_WARN_GB", "4"))


def _track_fb_table(nbytes: int, what: str, mod_bits: int) -> None:
    global _FB_TABLE_BYTES
    prev_gb = _FB_TABLE_BYTES / (1 << 30)
    _FB_TABLE_BYTES += nbytes
    now_gb = _FB_TABLE_BYTES / (1 << 30)
    log.debug(
        "fixed-base table built", kind=what, mod_bits=mod_bits,
        table_mb=round(nbytes / (1 << 20), 1),
        cumulative_mb=round(_FB_TABLE_BYTES / (1 << 20), 1),
    )
    if _FB_TABLE_WARN_GB > 0 and (
        int(now_gb / _FB_TABLE_WARN_GB) > int(prev_gb / _FB_TABLE_WARN_GB)
    ):
        log.warn(
            "cumulative fixed-base table memory crossed soft cap — "
            "HBM pressure is likely attributable to comb tables; "
            "lower MPCIUM_COMB_W or raise MPCIUM_FB_TABLE_WARN_GB",
            cumulative_gb=round(now_gb, 2), soft_cap_gb=_FB_TABLE_WARN_GB,
        )

# Largest block count for which the bf16 overlap-add stays f32-exact:
# each 32-limb block-product column is ≤ 32·127² = 516,128 and the
# overlap-add at any output block sums ≤ min(bx, by) columns, so
# min(bx, by) ≤ 32 keeps every partial sum ≤ 16,516,096 < 2²⁴.
_BF16_MAX_BLOCKS = 32

# The i8 strategy's int32 overlap-add is exact at any width, but the
# final carry() bounds it: lo+hi limbs reach 2*min(bx,by)*32*127^2,
# which must stay below carry()'s 127*2^21 limit => min(bx,by) <= 258;
# 256 keeps a margin (operands up to ~57k bits).
_I8_MAX_BLOCKS = 256


@functools.lru_cache(maxsize=None)
def _band_index_mask(n_cols: int):
    """Gather indices + mask building the Toeplitz band of a 32-limb block:
    band[i, n] = block[n - i] for 0 <= n-i < _BLOCK else 0. Cached as
    NUMPY (device conversion happens per trace: jnp.asarray under a jit
    trace yields a tracer, and caching tracers across traces leaks)."""
    i = np.arange(_BLOCK)[:, None]
    nn = np.arange(n_cols)[None, :]
    d = nn - i
    ok = (d >= 0) & (d < _BLOCK)
    return (
        np.clip(d, 0, _BLOCK - 1).astype(np.int32),
        ok.astype(np.float32),
    )


def _mul_pair_band(
    x: jnp.ndarray, y: jnp.ndarray, op_dtype
) -> jnp.ndarray:
    """Band-matrix pairwise product on the MXU, shared by the bf16 and
    int8 strategies (``op_dtype`` picks the operand path).

    Stage 1 builds the Toeplitz band of each 32-limb block of y
    (band[v, i, n] = y_v[n-i]) and contracts the limb index on the MXU:
    prods[..., u, v, n] = Σ_i x_u[i]·y_v[n-i] — a clean batched GEMM
    instead of the 3-operand conv einsum (whose outer-product
    materialization was ~25× slower than equivalent-MAC matmuls on the
    chip). Accumulation: bf16 operands accumulate in f32 (exact — 7-bit
    limbs are exact bf16 values and block columns stay ≤ 32·127² < 2²⁴);
    int8 operands accumulate in int32 (exact at every width).

    Stage 2 (overlap-add) sums ≤ min(bx, by) block columns; while
    min(bx, by) ≤ 32 every partial sum stays < 2²⁴ and it runs as an
    f32×f32 matmul at Precision.HIGHEST, which is f32-faithful on the
    TPU MXU (DEFAULT precision demotes f32 dots to one bf16 pass and
    silently rounds — the round-4 on-chip correctness lesson). Past 32
    blocks the int8 path falls back to an exact int32 contraction
    (stage 1 stays exact for BOTH dtypes at any width — the K=32 band
    contraction's sums never exceed 32·127²; only the f32 overlap-add
    breaks — but giving bf16 the int32 fallback too would silently
    change its cost profile, so it rejects instead). The fallback's own
    ceiling is the final carry: lo+hi limbs reach 2·min(bx,by)·32·127²,
    which must stay under carry()'s 127·2²¹ bound ⇒ min(bx, by) ≤ 256
    (operands ≤ ~57k bits), guarded below.
    Requires NORMALIZED inputs (the i32 strategy tolerates mildly
    redundant limbs; this one does not).
    """
    n_x, n_y = x.shape[-1], y.shape[-1]
    bx, by = -(-n_x // _BLOCK), -(-n_y // _BLOCK)
    wide = min(bx, by) > _BF16_MAX_BLOCKS
    # hard errors, not asserts: these guard cryptographic correctness
    # and must survive `python -O`
    if wide and op_dtype == jnp.bfloat16:
        raise ValueError(
            f"bf16 pairwise product overlap-add would exceed 2^24 "
            f"exactness: min({bx}, {by}) blocks > {_BF16_MAX_BLOCKS} "
            f"(operands up to {_BF16_MAX_BLOCKS * _BLOCK * LIMB_BITS} "
            f"bits); use MPCIUM_MULPAIR=i8 or i32 for wider operands"
        )
    if min(bx, by) > _I8_MAX_BLOCKS:
        raise ValueError(
            f"i8 pairwise product would exceed the carry-normalization "
            f"bound (limbs ≥ 127·2^21): min({bx}, {by}) blocks > "
            f"{_I8_MAX_BLOCKS}; use MPCIUM_MULPAIR=i32 for wider operands"
        )
    acc_dtype = jnp.float32 if op_dtype == jnp.bfloat16 else jnp.int32
    xb = bn.take_limbs(x, 0, bx * _BLOCK).reshape(
        x.shape[:-1] + (bx, _BLOCK)
    ).astype(op_dtype)
    yb = bn.take_limbs(y, 0, by * _BLOCK).reshape(
        y.shape[:-1] + (by, _BLOCK)
    ).astype(op_dtype)
    idx, mask = _band_index_mask(2 * _BLOCK - 1)
    # band[..., v, i, n] = y_v[n - i] (0 outside the band)
    band = jnp.take(yb, jnp.asarray(idx), axis=-1) * jnp.asarray(
        mask, op_dtype
    )
    prods = jnp.einsum(
        "...ui,...vin->...uvn", xb, band,
        preferred_element_type=acc_dtype,
    )
    bt = bx + by - 1
    if wide:
        # exact int32 overlap-add (VPU; only reachable from the i8 path)
        prods = prods.astype(jnp.int32)
        blk = jnp.asarray(np.asarray(bn._conv_tensor(bx, by)), jnp.int32)
        lo = jnp.einsum("...uvn,uvt->...tn", prods[..., :_BLOCK], blk)
        hi = jnp.einsum("...uvn,uvt->...tn", prods[..., _BLOCK:], blk)
    else:
        prods = prods.astype(jnp.float32)
        blk = jnp.asarray(np.asarray(bn._conv_tensor(bx, by)), jnp.float32)
        lo = jnp.einsum(
            "...uvn,uvt->...tn", prods[..., :_BLOCK], blk,
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
        hi = jnp.einsum(
            "...uvn,uvt->...tn", prods[..., _BLOCK:], blk,
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
    hi = jnp.pad(hi, [(0, 0)] * (hi.ndim - 1) + [(0, 1)])
    lo_flat = jnp.pad(
        lo.reshape(lo.shape[:-2] + (bt * _BLOCK,)),
        [(0, 0)] * (lo.ndim - 2) + [(0, _BLOCK)],
    )
    hi_flat = jnp.pad(
        hi.reshape(hi.shape[:-2] + (bt * _BLOCK,)),
        [(0, 0)] * (hi.ndim - 2) + [(_BLOCK, 0)],
    )
    total = carry(lo_flat + hi_flat)
    return total[..., : n_x + n_y]


def _mul_pair_bf16(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return _mul_pair_band(x, y, jnp.bfloat16)


def _mul_pair_i8(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """int8 band strategy: half the band traffic of bf16, int32
    accumulation — exact up to 256-block operands (~57k bits; past the
    32-block f32 bound the overlap-add falls back to int32, and the
    carry-normalization bound caps the fallback — see _mul_pair_band).
    Whether XLA maps the batched K=32 contraction onto the int8 MXU path
    is measured on the real chip by .scratch/chipcheck.py."""
    return _mul_pair_band(x, y, jnp.int8)


def mul_pair(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Pairwise (batched × batched) product → normalized (n_x+n_y) limbs.
    Blocked einsum in the 7-bit family; strategy via MPCIUM_MULPAIR
    (bf16 | i8 | i32)."""
    if MULPAIR_STRATEGY == "bf16":
        return _mul_pair_bf16(x, y)
    if MULPAIR_STRATEGY == "i8":
        return _mul_pair_i8(x, y)
    prof = bn.LimbProfile(bits=LIMB_BITS, n_limbs=max(x.shape[-1], y.shape[-1]))
    return bn.mul_wide(x, y, prof)


# ---------------------------------------------------------------------------
# module-level kernels (operand-passing: per-modulus constants arrive as
# ARGUMENTS, so one compiled executable serves every modulus of a given
# width — across parties, keys, processes, and the persistent cache)
# ---------------------------------------------------------------------------


def _cond_sub_impl(x: jnp.ndarray, comp: jnp.ndarray, occ: int) -> jnp.ndarray:
    """x < 2m over occ+1 limbs -> x mod m (complement-add carry)."""
    c = jnp.broadcast_to(comp, x.shape[:-1] + (occ + 2,))
    u = carry(bn.pad_limbs(x, 1) + c)  # x - m + R^(occ+1)
    ge = u[..., occ + 1] >= 1  # borrow-free <=> x >= m
    return jnp.where(ge[..., None], u[..., : occ + 1], x)


def _reduce_impl(x, T_mu, T_m, comp, occ: int, n: int) -> jnp.ndarray:
    """Barrett reduce; x normalized <= 2n limbs, x < R^occ * m (any product
    of two reduced values qualifies) -> x mod m over n limbs."""
    if x.shape[-1] <= occ:
        x = bn.pad_limbs(x, occ + 2 - x.shape[-1])
    q1 = bn.take_limbs(x, occ - 1, x.shape[-1] - (occ - 1))
    q2 = carry(mul_const(q1, T_mu[: q1.shape[-1]]))
    q3 = bn.take_limbs(q2, occ + 1, q2.shape[-1] - (occ + 1))
    q3m = carry(mul_const(q3, T_m[: q3.shape[-1]]))
    # subtract via elementwise radix complement of q3m (keeps limbs
    # non-negative for the lookahead carry); true r in [0, 3m) so the
    # extra R^(occ+1) lands exactly in limb occ+1, dropped below
    t = bn.take_limbs(x, 0, occ + 1) + (MASK - bn.take_limbs(q3m, 0, occ + 1))
    t = bn.pad_limbs(t, 1).at[..., 0].add(1)
    r = carry(t)[..., : occ + 1]
    r = _cond_sub_impl(r, comp, occ)
    r = _cond_sub_impl(r, comp, occ)
    out = r[..., :occ]
    return bn.pad_limbs(out, n - occ) if occ < n else out


def _one_like(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.zeros(x.shape[:-1] + (n,), jnp.int32).at[..., 0].set(1)


@functools.partial(jax.jit, static_argnames=("occ", "n"))
def _k_reduce(x, T_mu, T_m, comp, occ: int, n: int):
    return _reduce_impl(x, T_mu, T_m, comp, occ, n)


# Pairwise-mulmod implementation: "band" = Toeplitz-band GEMM + XLA-fused
# Barrett (the round-4 default); "pallas" = the fully fused VMEM-resident
# kernel in ops.pallas_mulmod (conv + carries + Barrett legs in ONE
# pallas_call — no HBM round-trips between stages). Uniform across every
# powmod/mulmod kernel in a process; unset, the choice follows the
# backend — pallas on real TPU (measured on-chip: 6.4x at 2048-bit,
# 1.35x at 4096-bit, flagship 13.7 vs 8.9 sigs/s), band on CPU (where
# pallas would run interpreted, orders of magnitude slower).
MULMOD_IMPL = os.environ.get("MPCIUM_MULMOD", "")
if MULMOD_IMPL not in ("", "band", "pallas"):
    raise ValueError(
        f"MPCIUM_MULMOD={MULMOD_IMPL!r}: expected 'band' or 'pallas'"
    )


def _impl() -> str:
    """Resolve the implementation at first-trace time (the backend is
    not known at import time; jax.default_backend() initializes it)."""
    global MULMOD_IMPL
    if not MULMOD_IMPL:
        MULMOD_IMPL = (
            "pallas" if jax.default_backend() == "tpu" else "band"
        )
    return MULMOD_IMPL


def _mm(a, b, T_mu, T_m, comp, occ: int, n: int) -> jnp.ndarray:
    """a·b mod m — the one mul+reduce step every kernel below loops."""
    if _impl() == "pallas":
        from . import pallas_mulmod

        return pallas_mulmod.mulmod(
            a, b, T_mu, T_m, comp, occ, n,
            interpret=jax.default_backend() == "cpu",
        )
    return _reduce_impl(mul_pair(a, b), T_mu, T_m, comp, occ, n)


@functools.partial(jax.jit, static_argnames=("occ", "n"))
def _k_mulmod(a, b, T_mu, T_m, comp, occ: int, n: int):
    return _mm(a, b, T_mu, T_m, comp, occ, n)


@functools.partial(jax.jit, static_argnames=("occ", "n"))
def _k_mulmod_const(a, T_c, T_mu, T_m, comp, occ: int, n: int):
    return _reduce_impl(carry(mul_const(a, T_c)), T_mu, T_m, comp, occ, n)


@functools.partial(jax.jit, static_argnames=("occ", "n"))
def _k_addmod(a, b, comp, occ: int, n: int):
    s = carry(bn.pad_limbs(a + b, 1))  # < 2m
    r = _cond_sub_impl(bn.take_limbs(s, 0, occ + 1), comp, occ)
    out = r[..., :occ]
    return bn.pad_limbs(out, n - occ) if occ < n else out


@functools.partial(jax.jit, static_argnames=("occ", "n"))
def _k_submod(a, b, m1, comp, occ: int, n: int):
    # a - b + m via the elementwise complement of b (non-negative limbs)
    t = (
        bn.take_limbs(a, 0, occ + 1)
        + (MASK - bn.take_limbs(b, 0, occ + 1))
        + bn.pad_limbs(m1, 1)[..., : occ + 1]
    )
    t = bn.pad_limbs(t, 1).at[..., 0].add(1)
    r = carry(t)[..., : occ + 1]  # a - b + m in (0, 2m); drop R^(occ+1)
    r = _cond_sub_impl(r, comp, occ)
    out = r[..., :occ]
    return bn.pad_limbs(out, n - occ) if occ < n else out


@functools.partial(jax.jit, static_argnames=("occ", "n"))
def _k_powmod(x, ebits, T_mu, T_m, comp, occ: int, n: int):
    """x^e, per-element exponent bits (LSB-first), 4-bit windows."""
    n_bits = ebits.shape[-1]
    nw = -(-n_bits // 4)
    if nw * 4 != n_bits:
        ebits = jnp.pad(
            ebits, [(0, 0)] * (ebits.ndim - 1) + [(0, nw * 4 - n_bits)]
        )
    w = ebits.reshape(ebits.shape[:-1] + (nw, 4))
    digits = jnp.flip(
        (w * jnp.asarray([1, 2, 4, 8], jnp.int32)).sum(-1), axis=-1
    )
    rows = [_one_like(x, n), x]
    for _ in range(14):
        rows.append(_mm(rows[-1], x, T_mu, T_m, comp, occ, n))
    tbl = jnp.stack(rows, axis=-2)

    def step(acc, d):
        for _ in range(4):
            acc = _mm(acc, acc, T_mu, T_m, comp, occ, n)
        sel = jnp.take_along_axis(
            tbl, d[..., None, None].astype(jnp.int32), axis=-2
        )[..., 0, :]
        return _mm(acc, sel, T_mu, T_m, comp, occ, n), None

    acc, _ = lax.scan(step, _one_like(x, n), jnp.moveaxis(digits, -1, 0),
                      unroll=SCAN_UNROLL)
    return acc


@functools.partial(jax.jit, static_argnames=("occ", "n"))
def _k_powmod_digits(x, digits, T_mu, T_m, comp, occ: int, n: int):
    """x^e for a batch-shared exponent given as an MSD-first (nw,) digit
    array (value is a runtime operand: one compile per digit COUNT)."""
    rows = [_one_like(x, n), x]
    for _ in range(14):
        rows.append(_mm(rows[-1], x, T_mu, T_m, comp, occ, n))
    tbl = jnp.stack(rows, axis=-2)

    def step(acc, d):
        for _ in range(4):
            acc = _mm(acc, acc, T_mu, T_m, comp, occ, n)
        sel = tbl[..., d, :]
        return _mm(acc, sel, T_mu, T_m, comp, occ, n), None

    acc, _ = lax.scan(step, _one_like(x, n), digits, unroll=SCAN_UNROLL)
    return acc


@functools.partial(jax.jit, static_argnames=("occ", "n"))
def _k_powmod_fb(tbl, ebits, T_mu, T_m, comp, occ: int, n: int):
    """comb-table fixed-base: tbl (nw, 2^w, n) operand, one mulmod per
    w-bit window (no squarings — fixed-base combs scale 1/w with window
    width, unlike per-element-base exponentiation whose squarings
    dominate; the window width is derived from the table shape)."""
    n_bits = ebits.shape[-1]
    nw = tbl.shape[0]
    wbits = tbl.shape[1].bit_length() - 1  # 2^w rows per window
    if nw * wbits != n_bits:
        ebits = jnp.pad(
            ebits, [(0, 0)] * (ebits.ndim - 1) + [(0, nw * wbits - n_bits)]
        )
    w = ebits.reshape(ebits.shape[:-1] + (nw, wbits))
    digits = (w * jnp.asarray([1 << i for i in range(wbits)], jnp.int32)).sum(-1)

    def step(acc, sl):
        d, rows = sl
        sel = rows[d]
        return _mm(acc, sel, T_mu, T_m, comp, occ, n), None

    acc, _ = lax.scan(
        step, _one_like(ebits, n), (jnp.moveaxis(digits, -1, 0), tbl),
        unroll=SCAN_UNROLL,
    )
    return acc


# ---------------------------------------------------------------------------
# the modular context
# ---------------------------------------------------------------------------


class MXUBarrett:
    """Barrett context for a fixed modulus with MXU-formulated primitives.

    Same reduction algebra as bignum.BarrettCtx (HAC Alg. 14.42) - the mu
    and m products ride constant Toeplitz matmuls, carries use the
    lookahead path, and the two trailing conditional subtractions use the
    radix-complement trick. All per-modulus constants are passed to the
    module-level kernels as OPERANDS so compiled executables are shared
    across moduli of a width (critical on a 1-core host: one compile per
    shape, hit by every party/key/process via the persistent cache).

    The modulus need NOT occupy the top limb (profiles are block-padded);
    the Barrett shift windows derive from the modulus' true occupancy.
    """

    def __init__(self, modulus: int, n_limbs: Optional[int] = None):
        self.modulus = modulus
        mb = modulus.bit_length()
        occ = -(-mb // LIMB_BITS)  # limbs the modulus actually occupies
        self.prof = (
            bn.LimbProfile(bits=LIMB_BITS, n_limbs=n_limbs)
            if n_limbs
            else profile(mb)
        )
        n = self.prof.n_limbs
        assert occ <= n
        self.occ = occ
        # Barrett: mu = floor(R^(2*occ) / m); q1 = x >> (occ-1) limbs;
        # q3 = (q1*mu) >> (occ+1) limbs; r = x - q3*m over occ+1 limbs.
        self.mu = (1 << (2 * occ * LIMB_BITS)) // modulus
        self._T_mu = _const_matrices(self.mu, 2 * n - (occ - 1))
        self._T_m = _const_matrices(modulus, 2 * n)
        comp = (1 << ((occ + 1) * LIMB_BITS)) - modulus
        self._comp = jnp.asarray(
            bn.to_limbs(comp, self.prof, n_limbs=occ + 2), jnp.int32
        )
        self._m1 = jnp.asarray(
            bn.to_limbs(modulus, self.prof, occ + 1), jnp.int32
        )
        self.m_limbs = bn.to_limbs(modulus, self.prof)
        self._fb_tables: Dict = {}

    # -- audit --------------------------------------------------------------

    def _audit(self, op: str, mulmods: float) -> None:
        """Record mulmod-equivalent dispatch counts into the module-level
        AUDIT dict (None = disabled, zero overhead). Key: (op, modulus
        bits). Used by .scratch/audit_counts.py to budget where the
        per-signature mulmods go without needing the chip."""
        if AUDIT is not None:
            k = (op, self.occ * LIMB_BITS)
            AUDIT[k] = AUDIT.get(k, 0.0) + mulmods

    # -- helpers ------------------------------------------------------------

    def const(self, value: int, batch_shape=()) -> jnp.ndarray:
        v = jnp.asarray(bn.to_limbs(value % self.modulus, self.prof))
        return jnp.broadcast_to(v, tuple(batch_shape) + (self.prof.n_limbs,))

    def one_like(self, x: jnp.ndarray) -> jnp.ndarray:
        return _one_like(x, self.prof.n_limbs)

    # -- core ---------------------------------------------------------------

    def reduce(self, x: jnp.ndarray) -> jnp.ndarray:
        self._audit("reduce", 0.5)
        return _k_reduce(
            x, self._T_mu, self._T_m, self._comp, self.occ, self.prof.n_limbs
        )

    def mulmod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        self._audit("mulmod", 1)
        return _k_mulmod(
            a, b, self._T_mu, self._T_m, self._comp, self.occ,
            self.prof.n_limbs,
        )

    def sqrmod(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.mulmod(a, a)

    def mulmod_const(self, a: jnp.ndarray, value: int) -> jnp.ndarray:
        """a times a python-int constant (cached width-padded Toeplitz)."""
        key = ("constT", value % self.modulus)
        T = self._fb_tables.get(key)
        if T is None:
            # pad the constant to occ limbs so every constant of this
            # modulus shares one kernel shape
            T = _const_matrices(
                value % self.modulus, self.prof.n_limbs, min_limbs=self.occ
            )
            self._fb_tables[key] = T
            _track_fb_table(
                sum(int(t.nbytes) for t in jax.tree.leaves(T)),
                "constT", self.modulus.bit_length(),
            )
        self._audit("mulmod_const", 0.5)
        return _k_mulmod_const(
            a, T, self._T_mu, self._T_m, self._comp, self.occ,
            self.prof.n_limbs,
        )

    def addmod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return _k_addmod(a, b, self._comp, self.occ, self.prof.n_limbs)

    def submod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return _k_submod(
            a, b, self._m1, self._comp, self.occ, self.prof.n_limbs
        )

    def negmod(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.submod(jnp.zeros_like(a), a)

    # -- exponentiation -----------------------------------------------------

    def powmod_const_exp(self, x: jnp.ndarray, exponent: int) -> jnp.ndarray:
        """x^e mod m for a batch-shared python-int exponent (digit array is
        a runtime operand: one compile per digit count, any value)."""
        if exponent == 0:
            return self.one_like(x)
        nw = -(-exponent.bit_length() // 4)
        self._audit(f"powmod_const_exp/e{4 * nw}", 5 * nw + 14)
        digits = jnp.asarray(
            [(exponent >> (4 * i)) & 15 for i in range(nw)][::-1], jnp.int32
        )
        return _k_powmod_digits(
            x, digits, self._T_mu, self._T_m, self._comp, self.occ,
            self.prof.n_limbs,
        )

    def powmod(self, x: jnp.ndarray, ebits: jnp.ndarray) -> jnp.ndarray:
        """x^e with per-element exponent bits (LSB-first), 4-bit windows."""
        self._audit(
            f"powmod/e{ebits.shape[-1]}",
            5 * (-(-ebits.shape[-1] // 4)) + 14,
        )
        return _k_powmod(
            x, ebits, self._T_mu, self._T_m, self._comp, self.occ,
            self.prof.n_limbs,
        )

    def powmod_fixed_base(self, base: int, ebits: jnp.ndarray) -> jnp.ndarray:
        """base^e mod m, python-int base, per-element exponent bits.
        Host-precomputed comb tables base^(2^(w·i) · d): ONE mulmod per
        w-bit window, no squarings (the ring-Pedersen commitment
        workhorse). Window width COMB_W (default 8): halving the mulmod
        count vs w=4 at the price of 2^w-row tables — ~100 MB per
        (base, 2048-bit modulus) for a 2400-bit exponent in the int32
        limb layout (300 windows x 256 rows x 320 limbs x 4 B),
        device-resident once per process; budget ~200 MB per
        counterparty NTilde (h1+h2) when sizing HBM."""
        n_bits = ebits.shape[-1]
        wbits = COMB_W
        nw = -(-n_bits // wbits)
        self._audit(f"powmod_fixed_base/e{n_bits}", nw)
        key = (base % self.modulus, nw, wbits)
        tbl = self._fb_tables.get(key)
        if tbl is None:
            # incremental build: b_i = base^(2^(w·i)) by squaring, row
            # entries by repeated multiply - O(nw·2^w) modmuls, not modexps
            m = self.modulus
            rows = 1 << wbits
            vals = []
            b_i = base % m
            for i in range(nw):
                acc = 1
                for w in range(rows):
                    vals.append(acc)
                    acc = acc * b_i % m
                b_i = pow(b_i, rows, m)
            tbl = jnp.asarray(
                ints_to_limbs(vals, self.prof).reshape(
                    nw, rows, self.prof.n_limbs
                )
            )
            self._fb_tables[key] = tbl
            _track_fb_table(
                int(tbl.nbytes), "comb", self.modulus.bit_length()
            )
        return _k_powmod_fb(
            tbl, ebits, self._T_mu, self._T_m, self._comp, self.occ,
            self.prof.n_limbs,
        )

    def invmod_prime(self, x: jnp.ndarray) -> jnp.ndarray:
        return self.powmod_const_exp(x, self.modulus - 2)

    # -- batch product reduction (for randomized batch verification) --------

    def prod_over_batch(self, x: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
        """Product of x_b mod m along ``axis`` by log-depth pairwise folds."""
        x = jnp.moveaxis(x, axis, 0)
        # (no _audit here: the fold's mulmod calls audit themselves)
        while x.shape[0] > 1:
            k = x.shape[0]
            if k % 2:
                x = jnp.concatenate([x, self.one_like(x[0])[None]], axis=0)
                k += 1
            x = self.mulmod(x[: k // 2], x[k // 2:])
        return x[0]
