"""Node/initiator identities and message authentication.

Reference behavior (pkg/identity/identity.go): every node holds an Ed25519
identity keypair; every cross-node protocol message is signed over canonical
bytes and verified against the sender's registered public key; initiator
commands are verified against the configured initiator public key; private
keys at rest are optionally passphrase-encrypted (age scrypt —
identity.go:160-177). Peer public keys are cross-validated at startup
(identity.go:81-125).

Implementation: OpenSSL Ed25519 via `cryptography` (host control-plane —
envelope auth is not protocol math), scrypt + ChaCha20-Poly1305 for at-rest
encryption (the age-equivalent authenticated passphrase scheme).
"""
from __future__ import annotations

import hashlib
import json
import os
import secrets
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Optional

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
except ImportError:  # bare env: RFC-vector-validated pure-python fallback
    from ..core.softcrypto import (
        ChaCha20Poly1305,
        Ed25519PrivateKey,
        Ed25519PublicKey,
        InvalidSignature,
        serialization,
    )

from ..wire import Envelope

ENC_SUFFIX = ".enc"  # the age-equivalent encrypted container suffix

# scrypt parameters (age defaults are N=2^18; interactive-friendly here)
_SCRYPT_N = 2**15
_SCRYPT_R = 8
_SCRYPT_P = 1


class IdentityError(Exception):
    pass


def _write_private_file(path, data: bytes) -> None:
    """Create/overwrite a key file with 0600 permissions — signing keys must
    not be world-readable on multi-user hosts."""
    p = Path(path)
    fd = os.open(str(p), os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        # mpclint: disable=MPF703 — this IS the at-rest identity key store: 0600 file, scrypt+AEAD-wrapped when a passphrase is set
        os.write(fd, data)
    finally:
        os.close(fd)


def _derive_key(passphrase: str, salt: bytes) -> bytes:
    return hashlib.scrypt(
        passphrase.encode(), salt=salt, n=_SCRYPT_N, r=_SCRYPT_R, p=_SCRYPT_P,
        maxmem=128 * 1024 * 1024, dklen=32,
    )


def encrypt_private_bytes(data: bytes, passphrase: str) -> bytes:
    """scrypt + ChaCha20-Poly1305 container: salt ‖ nonce ‖ ciphertext."""
    salt = secrets.token_bytes(16)
    nonce = secrets.token_bytes(12)
    ct = ChaCha20Poly1305(_derive_key(passphrase, salt)).encrypt(nonce, data, b"")
    return salt + nonce + ct


def decrypt_private_bytes(blob: bytes, passphrase: str) -> bytes:
    salt, nonce, ct = blob[:16], blob[16:28], blob[28:]
    try:
        return ChaCha20Poly1305(_derive_key(passphrase, salt)).decrypt(nonce, ct, b"")
    except Exception as e:  # noqa: BLE001 — wrong passphrase or corrupt
        raise IdentityError(f"cannot decrypt private key: {e}") from e


@dataclass
class NodeIdentity:
    node_id: str
    public_key: bytes  # 32-byte raw Ed25519

    def to_json(self) -> dict:
        return {"node_id": self.node_id, "public_key": self.public_key.hex()}

    @classmethod
    def from_json(cls, d: dict) -> "NodeIdentity":
        return cls(node_id=d["node_id"], public_key=bytes.fromhex(d["public_key"]))


def generate_identity(
    node_id: str,
    out_dir,
    passphrase: Optional[str] = None,
) -> NodeIdentity:
    """Create `<node>_identity.json` + `<node>_private.key[.enc]` (reference
    mpcium-cli generate-identity)."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    sk = Ed25519PrivateKey.generate()
    raw = sk.private_bytes(
        serialization.Encoding.Raw,
        serialization.PrivateFormat.Raw,
        serialization.NoEncryption(),
    )
    pub = sk.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    ident = NodeIdentity(node_id=node_id, public_key=pub)
    (out / f"{node_id}_identity.json").write_text(json.dumps(ident.to_json(), indent=1))
    key_path = out / f"{node_id}_private.key"
    if passphrase is not None:
        if len(passphrase) < 12 or not any(not c.isalnum() for c in passphrase):
            # reference password policy: ≥12 chars incl. special
            # (generate-identity.go:53-63)
            raise IdentityError(
                "passphrase must be ≥12 chars and contain a special character"
            )
        _write_private_file(
            str(key_path) + ENC_SUFFIX,
            encrypt_private_bytes(raw.hex().encode(), passphrase),
        )
    else:
        _write_private_file(key_path, raw.hex().encode())
    return ident


class IdentityStore:
    """Loads own private key + all peers' public keys; signs/verifies
    envelopes and initiator messages (reference identity.Store iface,
    identity.go:32-38)."""

    def __init__(
        self,
        identity_dir,
        node_id: str,
        peers: Dict[str, str],  # name -> peer uuid/nodeID (peers.json)
        initiator_pubkey: Optional[bytes] = None,
        passphrase: Optional[str] = None,
    ):
        d = Path(identity_dir)
        self.node_id = node_id
        self.initiator_pubkey = initiator_pubkey
        self._pub: Dict[str, Ed25519PublicKey] = {}
        # startup cross-validation (identity.go:81-125): every peer in the
        # topology must have an identity file and the IDs must match
        for name in sorted(peers):
            path = d / f"{name}_identity.json"
            if not path.exists():
                raise IdentityError(f"missing identity file for peer {name!r}")
            ident = NodeIdentity.from_json(json.loads(path.read_text()))
            if ident.node_id != name:
                raise IdentityError(
                    f"identity file {path} declares node_id {ident.node_id!r}, "
                    f"expected {name!r}"
                )
            self._pub[name] = Ed25519PublicKey.from_public_bytes(ident.public_key)
        if node_id not in self._pub:
            raise IdentityError(f"own identity {node_id!r} not in peer set")
        # own private key (hex file or encrypted container)
        key_path = d / f"{node_id}_private.key"
        enc_path = Path(str(key_path) + ENC_SUFFIX)
        if enc_path.exists():
            if passphrase is None:
                raise IdentityError("private key is encrypted; passphrase required")
            raw = bytes.fromhex(
                decrypt_private_bytes(enc_path.read_bytes(), passphrase).decode()
            )
        elif key_path.exists():
            raw = bytes.fromhex(key_path.read_text().strip())
        else:
            raise IdentityError(f"no private key for {node_id!r} in {d}")
        self._sk = Ed25519PrivateKey.from_private_bytes(raw)
        own_pub = self._sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        declared = self._pub[node_id].public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        if own_pub != declared:
            raise IdentityError("private key does not match published identity")

    # -- envelope auth ------------------------------------------------------

    def sign_envelope(self, env: Envelope) -> None:
        env.signature = self._sk.sign(env.marshal_for_signing())

    def verify_envelope(self, env: Envelope) -> bool:
        pub = self._pub.get(env.from_id)
        if pub is None or not env.signature:
            return False
        try:
            pub.verify(env.signature, env.marshal_for_signing())
            return True
        except InvalidSignature:
            return False

    # -- raw message auth (batch manifests etc.) ----------------------------

    def sign_raw(self, raw: bytes) -> bytes:
        return self._sk.sign(raw)

    def verify_peer(self, node_id: str, raw: bytes, signature: bytes) -> bool:
        pub = self._pub.get(node_id)
        if pub is None or not signature:
            return False
        try:
            pub.verify(signature, raw)
            return True
        except InvalidSignature:
            return False

    # -- initiator auth -----------------------------------------------------

    def verify_initiator(self, raw: bytes, signature: bytes) -> bool:
        if self.initiator_pubkey is None or not signature:
            return False
        try:
            Ed25519PublicKey.from_public_bytes(self.initiator_pubkey).verify(
                signature, raw
            )
            return True
        except InvalidSignature:
            return False

    def public_key(self, node_id: str) -> bytes:
        return self._pub[node_id].public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )


@dataclass
class InitiatorKey:
    """Client-side initiator signing key (reference event_initiator.key,
    client.go:64-146)."""

    _sk: Ed25519PrivateKey

    @classmethod
    def generate(cls) -> "InitiatorKey":
        return cls(_sk=Ed25519PrivateKey.generate())

    @classmethod
    def load(cls, path, passphrase: Optional[str] = None) -> "InitiatorKey":
        p = Path(path)
        enc = Path(str(p) + ENC_SUFFIX)
        if enc.exists():
            if passphrase is None:
                raise IdentityError("initiator key is encrypted; passphrase required")
            raw = bytes.fromhex(
                decrypt_private_bytes(enc.read_bytes(), passphrase).decode()
            )
        else:
            raw = bytes.fromhex(p.read_text().strip())
        return cls(_sk=Ed25519PrivateKey.from_private_bytes(raw))

    def save(self, path, passphrase: Optional[str] = None) -> None:
        raw = self._sk.private_bytes(
            serialization.Encoding.Raw,
            serialization.PrivateFormat.Raw,
            serialization.NoEncryption(),
        )
        if passphrase is not None:
            _write_private_file(
                str(path) + ENC_SUFFIX,
                encrypt_private_bytes(raw.hex().encode(), passphrase),
            )
        else:
            _write_private_file(path, raw.hex().encode())

    @property
    def public_bytes(self) -> bytes:
        return self._sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )

    def sign(self, raw: bytes) -> bytes:
        return self._sk.sign(raw)
