"""FaultyTransport: a fault-injecting decorator over any Transport.

Wraps the four delivery semantics of :class:`~..transport.api.Transport`
(loopback or TCP) and applies the active :class:`~.plan.FaultPlan` on
every publish/send/enqueue (outbound) and every handler delivery
(inbound). Construction is the only seam — a node built without a plan
never touches this module and runs byte-identically (the zero-overhead
contract tested by tests/test_faults_transport.py).

Semantics per channel:

- **pub/sub** — drop is a true loss (fire-and-forget fan-out), delay
  re-publishes after the jitter on a timer thread, reorder swaps a
  message with its successor;
- **acked unicast** — a drop consumes one of the sender's retry
  attempts then re-rolls (a lossy link under a retry protocol, not a
  forged ack: the caller either gets a real ack or a TransportError);
- **durable queue** — drop loses the enqueue, duplicate re-enqueues
  (drilling Nats-Msg-Id idempotency), delay defers it.

Tamper rules (active adversary, ISSUE 16) corrupt the payload on any
channel — outbound before delivery, inbound before the handler — via
:meth:`~.plan.FaultPlan.tamper_bytes` (PRF-chosen byte flip, truncate,
or replay substitution); the delivered bytes differ, the schedule log
records the judgement.

The :class:`CrashSwitch` gives SIGKILL semantics: once flipped, the node
emits nothing and hears nothing (its subscriptions stay registered, like
a dead process's socket buffers) until :meth:`CrashSwitch.restore`.
"""
from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

from ..transport.api import (
    DirectMessaging,
    Handler,
    MessageQueue,
    PubSub,
    QueueHandler,
    Subscription,
    Transport,
    TransportError,
)
from ..utils import log
from .plan import FaultPlan, MsgEvent, Rule

# pseudo-rule ids for non-probabilistic suppression, so reports show them
CRASH_RULE = "__crashed__"


class CrashSwitch:
    """Process-death toggle shared by a node's transport and the drill
    runner. ``on_crash`` hooks run once per flip (chaos.py registers the
    registry-heartbeat stopper there)."""

    def __init__(self, node_id: str = ""):
        self.node_id = node_id
        self._crashed = threading.Event()
        self._hooks: List[Callable[[], None]] = []
        self.crash_count = 0

    @property
    def crashed(self) -> bool:
        return self._crashed.is_set()

    def on_crash(self, hook: Callable[[], None]) -> None:
        self._hooks.append(hook)

    def crash(self) -> None:
        if self._crashed.is_set():
            return
        self._crashed.set()
        self.crash_count += 1
        log.warn("FAULT: node crashed", node=self.node_id)
        for h in list(self._hooks):
            try:
                h()
            except Exception as e:  # noqa: BLE001 — hooks must not cascade
                log.warn("crash hook failed", error=repr(e))

    def restore(self) -> None:
        log.info("FAULT: node restored", node=self.node_id)
        self._crashed.clear()


class FaultStats:
    """Counters + the deterministic schedule log, per transport; merged
    across a cluster into the drill report."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, Dict[str, int]] = defaultdict(
            lambda: defaultdict(int)
        )
        self.schedule: List[dict] = []
        self.retries_observed = 0

    def record(self, rule_id: str, action: str, ev: MsgEvent,
               key: bytes = b"", occ: int = 0, **extra) -> None:
        entry = {
            "rule": rule_id, "action": action, "channel": ev.channel,
            "direction": ev.direction, "topic": ev.topic,
            "node": ev.node_id, "key": key.hex(), "occ": occ,
        }
        entry.update(extra)
        with self._lock:
            self.counters[rule_id][action] += 1
            self.schedule.append(entry)

    def retry(self) -> None:
        with self._lock:
            self.retries_observed += 1

    def merge(self, other: "FaultStats") -> "FaultStats":
        with other._lock:
            sched, counters = list(other.schedule), dict(other.counters)
            retries = other.retries_observed
        with self._lock:
            self.schedule.extend(sched)
            for rid, acts in counters.items():
                for a, n in acts.items():
                    self.counters[rid][a] += n
            self.retries_observed += retries
        return self

    def canonical_schedule(self) -> List[tuple]:
        """Order-independent view for determinism assertions: the
        schedule as a sorted multiset (thread interleaving may permute
        append order between runs; the *set of judgements* may not
        differ)."""
        with self._lock:
            return sorted(
                (e["rule"], e["action"], e["channel"], e["direction"],
                 e["topic"], e["node"], e["key"], e["occ"])
                for e in self.schedule
            )

    def to_json(self) -> dict:
        with self._lock:
            return {
                "counters": {r: dict(a) for r, a in self.counters.items()},
                "retries_observed": self.retries_observed,
                "events": len(self.schedule),
            }


class _Timers:
    """Tracked daemon timers for delayed/reordered deliveries."""

    def __init__(self):
        self._lock = threading.Lock()
        self._live: List[threading.Timer] = []
        self._closed = False

    def after(self, delay_s: float, fn: Callable[[], None]) -> threading.Timer:
        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — late delivery races close
                log.warn("delayed fault delivery failed", error=repr(e))
            with self._lock:
                if t in self._live:
                    self._live.remove(t)

        t = threading.Timer(delay_s, run)
        t.daemon = True
        with self._lock:
            if self._closed:
                return t
            self._live.append(t)
        t.start()
        return t

    def close(self) -> None:
        with self._lock:
            self._closed = True
            live, self._live = self._live, []
        for t in live:
            t.cancel()


class _FaultSub(Subscription):
    def __init__(self, inner: Subscription):
        self._inner = inner

    def unsubscribe(self) -> None:
        self._inner.unsubscribe()


class FaultyTransport:
    """Transport decorator. Satisfies the :class:`Transport` bundle
    contract (``pubsub`` / ``direct`` / ``queues`` /
    ``set_dead_letter_handler``) and forwards any extra attributes of
    the wrapped bundle (e.g. the TCP bundle's ``client``)."""

    def __init__(self, inner: Transport, node_id: str, plan: FaultPlan,
                 stats: Optional[FaultStats] = None,
                 crash_switch: Optional[CrashSwitch] = None):
        self.inner = inner
        self.node_id = node_id
        self.plan = plan
        self.stats = stats or FaultStats()
        self.crash_switch = crash_switch or CrashSwitch(node_id)
        self._timers = _Timers()
        # reorder holding cells: rule_id -> (emit_fn, timer, ev)
        self._held: Dict[str, Tuple[Callable[[], None], threading.Timer, MsgEvent]] = {}
        self._held_lock = threading.Lock()
        self.pubsub = _FaultyPubSub(self)
        self.direct = _FaultyDirect(self)
        self.queues = _FaultyQueue(self)
        self.set_dead_letter_handler = inner.set_dead_letter_handler

    def __getattr__(self, name):
        # forward e.g. `.client` (TCP bundle) — only called for misses
        if name == "inner":  # guard: never recurse during construction
            raise AttributeError(name)
        return getattr(self.inner, name)

    def close(self) -> None:
        self._timers.close()

    # -- shared machinery ----------------------------------------------------

    def _suppressed(self, ev: MsgEvent) -> bool:
        """Crash/partition: the message never crosses this boundary."""
        if self.crash_switch.crashed:
            self.stats.record(CRASH_RULE, "drop", ev)
            return True
        iso = self.plan.isolated(self.node_id)
        if iso is not None:
            self.stats.record(iso.rule_id, "drop", ev)
            return True
        return False

    def _roll_drop(self, ev: MsgEvent) -> Optional[Rule]:
        for r in self.plan.matching(ev, ("drop",)):
            u, key, occ = self.plan.roll(r, ev)
            if u < r.p:
                self.stats.record(r.rule_id, "drop", ev, key, occ)
                return r
        return None

    def _sample_delay_s(self, ev: MsgEvent) -> float:
        total = 0.0
        for r in self.plan.matching(ev, ("delay",)):
            u, key, occ = self.plan.roll(r, ev)
            if u < r.p:
                d_ms = self.plan.delay_ms(r, key, occ)
                self.stats.record(r.rule_id, "delay", ev, key, occ,
                                  ms=round(d_ms, 3))
                total += d_ms / 1000.0
        return total

    def _roll_tamper(self, ev: MsgEvent) -> Optional[bytes]:
        """The corrupted payload when a tamper rule fires, else None.
        Rolled on the ORIGINAL bytes (the message key and occurrence
        stream never depend on what an earlier tamper rule did), applied
        cumulatively when several rules fire."""
        data = ev.data
        hit = False
        for r in self.plan.matching(ev, ("tamper",)):
            u, key, occ = self.plan.roll(r, ev)
            out = self.plan.tamper_bytes(r, key, occ, data,
                                         triggered=u < r.p)
            if out != data:
                self.stats.record(r.rule_id, "tamper", ev, key, occ,
                                  mode=r.mode, nbytes=len(out))
                data = out
                hit = True
        return data if hit else None

    def _roll_duplicate(self, ev: MsgEvent) -> bool:
        dup = False
        for r in self.plan.matching(ev, ("duplicate",)):
            u, key, occ = self.plan.roll(r, ev)
            if u < r.p:
                self.stats.record(r.rule_id, "duplicate", ev, key, occ)
                dup = True
        return dup

    def _maybe_crash_after(self, ev: MsgEvent) -> None:
        """crash_node trigger: the node just emitted ``ev``; if a crash
        rule matches (topic + round predicate), flip the switch — the
        message it rode out on was its last."""
        for r in self.plan.crash_rules(self.node_id):
            if not (r.topic in ("*",) or _topic_match(r.topic, ev.topic)):
                continue
            if r.at_round:
                if _envelope_round(ev.data) != r.at_round:
                    continue
            self.plan.mark_fired(r)
            self.stats.record(r.rule_id, "crash", ev)
            self.crash_switch.crash()
            return

    def _reorder(self, ev: MsgEvent, emit: Callable[[], None]) -> bool:
        """Returns True when the message was consumed by a reorder hold
        (it will be emitted later); False to emit normally."""
        for r in self.plan.matching(ev, ("reorder",)):
            rid = r.rule_id
            with self._held_lock:
                held = self._held.pop(rid, None)
            if held is not None:
                # successor arrived: emit it first, then the held one
                held_emit, timer, _held_ev = held
                timer.cancel()
                emit()
                held_emit()
                return True
            u, key, occ = self.plan.roll(r, ev)
            if u < r.p:
                self.stats.record(rid, "reorder", ev, key, occ)

                def flush(rid=rid):
                    with self._held_lock:
                        held2 = self._held.pop(rid, None)
                    if held2 is not None:
                        held2[0]()

                timer = self._timers.after(r.ms[0] / 1000.0, flush)
                with self._held_lock:
                    self._held[rid] = (emit, timer, ev)
                return True
        return False

    # -- inbound wrap --------------------------------------------------------

    def _wrap_handler(self, channel: str, topic: str, handler):
        def wrapped(data: bytes):
            ev = MsgEvent("in", channel, topic, data, self.node_id)
            if self._suppressed(ev):
                # a crashed/isolated node hears nothing; for the acked
                # channels the missing ack is exactly what a dead
                # process produces — the sender's retry budget decides
                if channel in ("direct", "queue"):
                    raise TransportError(
                        f"fault: {self.node_id} unreachable"
                    )
                return None
            if self._roll_drop(ev) is not None:
                if channel in ("direct", "queue"):
                    raise TransportError("fault: inbound delivery dropped")
                return None
            d = self._sample_delay_s(ev)
            if d > 0:
                time.sleep(d)
            t = self._roll_tamper(ev)
            return handler(data if t is None else t)

        return wrapped


def _topic_match(pattern: str, topic: str) -> bool:
    from .plan import glob_match

    return glob_match(pattern, topic)


def _envelope_round(data: bytes) -> str:
    """Best-effort round extraction from a wire Envelope (JSON)."""
    try:
        return str(json.loads(data).get("round", ""))
    except Exception:  # noqa: BLE001 — non-envelope payloads have no round
        return ""


class _FaultyPubSub(PubSub):
    def __init__(self, ft: FaultyTransport):
        self._ft = ft

    def publish(self, topic: str, data: bytes) -> None:
        ft = self._ft
        ev = MsgEvent("out", "pubsub", topic, data, ft.node_id)
        if ft.plan.empty and not ft.crash_switch.crashed:
            ft.inner.pubsub.publish(topic, data)
            return
        if ft._suppressed(ev):
            return
        if ft._roll_drop(ev) is not None:
            ft._maybe_crash_after(ev)
            return
        t = ft._roll_tamper(ev)
        payload = data if t is None else t

        def emit():
            ft.inner.pubsub.publish(topic, payload)
            if ft._roll_duplicate(ev):
                ft.inner.pubsub.publish(topic, payload)

        if ft._reorder(ev, emit):
            ft._maybe_crash_after(ev)
            return
        d = ft._sample_delay_s(ev)
        if d > 0:
            ft._timers.after(d, emit)
        else:
            emit()
        ft._maybe_crash_after(ev)

    def publish_with_reply(self, topic: str, reply_topic: str, data: bytes) -> None:
        # the wrapped fabric's reply envelope rides publish() semantics;
        # fault rules match on the OUTER topic
        ft = self._ft
        ev = MsgEvent("out", "pubsub", topic, data, ft.node_id)
        if not ft.plan.empty or ft.crash_switch.crashed:
            if ft._suppressed(ev) or ft._roll_drop(ev) is not None:
                return
            d = ft._sample_delay_s(ev)
            if d > 0:
                ft._timers.after(
                    d, lambda: ft.inner.pubsub.publish_with_reply(
                        topic, reply_topic, data)
                )
                return
        ft.inner.pubsub.publish_with_reply(topic, reply_topic, data)

    def subscribe(self, topic: str, handler: Handler) -> Subscription:
        ft = self._ft
        return _FaultSub(ft.inner.pubsub.subscribe(
            topic, ft._wrap_handler("pubsub", topic, handler)))


class _FaultyDirect(DirectMessaging):
    # a lossy link under the acked-retry protocol: each PRF'd loss
    # consumes one attempt and re-rolls with a bumped occurrence
    DROP_ATTEMPTS = 3
    RETRY_DELAY_S = 0.05

    def __init__(self, ft: FaultyTransport):
        self._ft = ft

    def send(self, topic: str, data: bytes,
             timeout_s: Optional[float] = None) -> None:
        ft = self._ft
        ev = MsgEvent("out", "direct", topic, data, ft.node_id)
        if ft.plan.empty and not ft.crash_switch.crashed:
            ft.inner.direct.send(topic, data, timeout_s=timeout_s)
            return
        if ft._suppressed(ev):
            raise TransportError(
                f"fault: {ft.node_id} is crashed/isolated; send to "
                f"{topic!r} suppressed"
            )
        d = ft._sample_delay_s(ev)
        if d > 0:
            time.sleep(d)
        t = ft._roll_tamper(ev)
        payload = data if t is None else t
        for attempt in range(self.DROP_ATTEMPTS):
            if ft._roll_drop(ev) is None:
                ft.inner.direct.send(topic, payload, timeout_s=timeout_s)
                if ft._roll_duplicate(ev):
                    try:
                        ft.inner.direct.send(topic, payload,
                                             timeout_s=timeout_s)
                    except TransportError:
                        pass  # duplicate delivery is best-effort
                ft._maybe_crash_after(ev)
                return
            ft.stats.retry()
            if attempt + 1 < self.DROP_ATTEMPTS:
                time.sleep(self.RETRY_DELAY_S)
        raise TransportError(
            f"fault: direct send to {topic!r} lost "
            f"{self.DROP_ATTEMPTS} consecutive deliveries"
        )

    def listen(self, topic: str, handler: Handler) -> Subscription:
        ft = self._ft
        return _FaultSub(ft.inner.direct.listen(
            topic, ft._wrap_handler("direct", topic, handler)))


class _FaultyQueue(MessageQueue):
    def __init__(self, ft: FaultyTransport):
        self._ft = ft

    def enqueue(self, topic: str, data: bytes, idempotency_key: str = "") -> None:
        ft = self._ft
        ev = MsgEvent("out", "queue", topic, data, ft.node_id)
        if ft.plan.empty and not ft.crash_switch.crashed:
            ft.inner.queues.enqueue(topic, data, idempotency_key)
            return
        if ft._suppressed(ev):
            raise TransportError(
                f"fault: {ft.node_id} is crashed/isolated; enqueue to "
                f"{topic!r} suppressed"
            )
        if ft._roll_drop(ev) is not None:
            return  # lost write — at-least-once producers re-send
        t = ft._roll_tamper(ev)
        payload = data if t is None else t

        def emit():
            ft.inner.queues.enqueue(topic, payload, idempotency_key)
            if ft._roll_duplicate(ev):
                # re-enqueue under the SAME idempotency key: the dedup
                # window must absorb it (and without a key, consumers
                # must tolerate the duplicate)
                ft.inner.queues.enqueue(topic, payload, idempotency_key)

        d = ft._sample_delay_s(ev)
        if d > 0:
            ft._timers.after(d, emit)
        else:
            emit()

    def dequeue(self, topic_filter: str, handler: QueueHandler) -> Subscription:
        ft = self._ft
        return _FaultSub(ft.inner.queues.dequeue(
            topic_filter, ft._wrap_handler("queue", topic_filter, handler)))
