"""Deterministic fault-injection & chaos drills (ISSUE 3).

The committee's value proposition is surviving partial failure; this
package attacks the failure surface on purpose, reproducibly:

- :mod:`.plan` — declarative, seed-deterministic fault plans (drop /
  delay / duplicate / reorder / crash / partition / tamper rules with
  match predicates and per-rule PRF streams);
- :mod:`.transport` — a :class:`~.transport.FaultyTransport` decorator
  over any :class:`~..transport.api.Transport` that applies the active
  plan on publish/deliver, plus the node crash switch;
- :mod:`.chaos` — the drill runner: stands up an in-process cluster,
  executes keygen → signing → reshare under a plan, and emits a
  structured, reproducible drill report (scripts/chaos_drill.py).
"""
from .plan import (  # noqa: F401
    FaultPlan,
    Rule,
    crash_node,
    delay,
    drop,
    duplicate,
    named_plan,
    partition,
    reorder,
    tamper,
)
from .transport import CrashSwitch, FaultStats, FaultyTransport  # noqa: F401
