"""Chaos drill runner: reproducible failure drills over a live cluster.

Each drill stands up an in-process :class:`~..cluster.LocalCluster`
(loopback or TCP+standby), runs real protocol work — EdDSA keygen →
signing → resharing through the full client/queue/consumer path — under
a seed-deterministic :class:`~.plan.FaultPlan`, and emits a structured
:class:`DrillReport`. ``scripts/chaos_drill.py`` is the CLI; the fast
deterministic variants run in the test tier under the ``chaos`` marker.

Drill catalog (expected outcome in parentheses):

- ``node-crash`` (recovered) — node2 SIGKILLs the instant it joins its
  first signing session; the tx fails LOUDLY, the committee detects the
  death via heartbeat staleness and signs with t+1 survivors, the node
  restarts, rejoins and signs again — then the wallet reshares cleanly.
- ``drop-jitter`` (success) — 10 % loss on every acked protocol unicast
  plus 50–200 ms jitter on all protocol traffic; the retry budgets
  absorb it and keygen → signing → reshare all complete.
- ``broker-failover`` (success) — TCP transport, hot-standby broker;
  the primary dies mid-run and clients transparently fail over.
- ``partition`` (loud-failure-then-recovery) — two of three nodes are
  isolated (over threshold: no quorum can form anywhere); signing fails
  loudly and retryably — a bounded timeout ERROR event, no hang, no
  silent corruption — and succeeds after the partition heals.
- ``kill-resume`` (resumed) — with the session WAL on, node2 SIGKILLs
  mid-round-2 of a signing session; the survivors stall (the quorum
  includes the corpse), the node respawns over its on-disk state, WAL
  replay re-claims the session and the SAME run completes with the
  bit-identical signature; the report carries ``resume_latency_s``.
- ``cheater`` (caught-and-quarantined) — an active adversary corrupts
  one PRF-chosen OT-MtA wire field in one batch lane mid-signing
  (ISSUE 16); the KOS / Gilboa / consistency checks catch the
  deviation and blame exactly the cheating party, the batch scheduler
  quarantines that one session behind a retryable culprit-named ABORT
  event and re-packs the survivors onto bucket-snapped sub-batches,
  while live EdDSA traffic keeps signing on a real cluster; the report
  carries ``culprit`` and ``survivors``.

Reproducing a failed drill: the report carries ``seed`` and the full
plan JSON; ``scripts/chaos_drill.py --plan <name> --seed <seed>`` reruns
the identical fault schedule (see plan.py's determinism contract).
"""
from __future__ import annotations

import hashlib
import shutil
import tempfile
import threading
import time
import types
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .. import wire
from ..cluster import LocalCluster, load_test_preparams
from ..trace import snapshot_chrome
from ..utils import log, tracing
from .plan import FaultPlan, named_plan
from .transport import FaultStats

DEFAULT_SEED = 7


@dataclass
class DrillReport:
    name: str
    seed: int
    expected: str
    outcome: str
    ok: bool
    duration_s: float
    plan: dict = field(default_factory=dict)
    faults: dict = field(default_factory=dict)
    notes: List[str] = field(default_factory=list)
    error: str = ""
    # kill-resume: wall time from respawn to the resumed session's result
    resume_latency_s: float = 0.0
    # kill-resume: warm-cache stats from the pre-respawn warm pass
    # ({warmed, hits, budget_s} — mpcium_tpu.warm.prewarm.warm_for_drill)
    warm: dict = field(default_factory=dict)
    # cheater: the blamed deviation ({session, lane, party, check, field})
    culprit: dict = field(default_factory=dict)
    # cheater: cohort completion stats after the quarantine
    # ({submitted, quarantined, completed, pending, chunks})
    survivors: dict = field(default_factory=dict)
    # merged cross-node Chrome-trace-event JSON (flight-recorder snapshot;
    # load in Perfetto / chrome://tracing)
    trace: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "expected": self.expected,
            "outcome": self.outcome,
            "ok": self.ok,
            "duration_s": round(self.duration_s, 3),
            "plan": self.plan,
            "faults": self.faults,
            "notes": self.notes,
            "error": self.error,
            "resume_latency_s": round(self.resume_latency_s, 3),
            "warm": self.warm,
            "culprit": self.culprit,
            "survivors": self.survivors,
            "trace": self.trace,
        }


def _wait(cond: Callable[[], bool], timeout_s: float,
          poll_s: float = 0.05) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return False


# -- cluster plumbing --------------------------------------------------------


def _mk_cluster(fault_plans: Optional[Dict[str, FaultPlan]] = None,
                transport: str = "loopback",
                broker_standby: bool = False,
                hello_timeout_s: float = 4.0,
                reply_timeout_s: float = 6.0,
                session_timeout_s: float = 12.0,
                gc_interval_s: float = 1.0,
                session_wal: bool = False) -> Tuple[LocalCluster, str]:
    """A 3-node t=1 drill cluster with tightened failure deadlines, so
    loud failures surface inside the drill budget instead of the
    production 30-minute GC."""
    root = tempfile.mkdtemp(prefix="mpcium-chaos-")
    cluster = LocalCluster(
        n_nodes=3,
        threshold=1,
        root_dir=root,
        preparams=load_test_preparams(bits=1024),
        transport=transport,
        broker_standby=broker_standby,
        fault_plans=fault_plans,
        hello_timeout_s=hello_timeout_s,
        reply_timeout_s=reply_timeout_s,
        session_timeout_s=session_timeout_s,
        gc_interval_s=gc_interval_s,
        session_wal=session_wal,
    )
    return cluster, root


def _close(cluster: LocalCluster, root: str) -> None:
    try:
        cluster.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _merged_stats(cluster: LocalCluster) -> FaultStats:
    merged = FaultStats()
    retired = getattr(cluster, "_retired_fault_transports", [])
    for ft in list(cluster.fault_transports.values()) + list(retired):
        merged.merge(ft.stats)
    return merged


def _eddsa_keygen(cluster: LocalCluster, wallet_id: str,
                  timeout_s: float = 60.0, attempts: int = 3) -> int:
    """EdDSA-only distributed keygen via direct sessions on every node
    (wallet creation through the client forces the heavyweight GG18
    curve too; drills exercise the failure machinery, not Paillier).
    Returns the number of attempts used."""
    from ..config import get_config

    threshold = get_config().mpc_threshold
    last_err: Optional[str] = None
    for attempt in range(1, attempts + 1):
        sessions = [
            node.create_keygen_session(
                wire.KEY_TYPE_ED25519, wallet_id, threshold
            )
            for node in cluster.nodes.values()
        ]
        for s in sessions:
            s.listen()
        ok = True
        for s in sessions:
            if not s.wait(timeout_s) or s.failed:
                ok = False
        for s in sessions:
            s.close()
        if ok:
            return attempt
        last_err = "; ".join(
            s.session_id for s in sessions if s.failed
        ) or "timeout"
        log.warn("drill keygen attempt failed; retrying",
                 wallet=wallet_id, attempt=attempt, detail=last_err)
    raise RuntimeError(
        f"eddsa keygen for {wallet_id!r} failed after {attempts} "
        f"attempts: {last_err}"
    )


def _sign(cluster: LocalCluster, wallet_id: str, tx_id: str,
          timeout_s: float = 60.0) -> wire.SigningResultEvent:
    return cluster.sign_sync(
        wire.SignTxMessage(
            key_type=wire.KEY_TYPE_ED25519,
            wallet_id=wallet_id,
            network_internal_code="chaos",
            tx_id=tx_id,
            tx=b"chaos:" + tx_id.encode(),
        ),
        timeout_s=timeout_s,
    )


def _sign_retrying(cluster: LocalCluster, wallet_id: str, tx_base: str,
                   notes: List[str], attempts: int = 3,
                   timeout_s: float = 60.0) -> wire.SigningResultEvent:
    """Client-level retry: terminal errors and timeouts re-submit under a
    FRESH tx id (result queues are idempotent per tx id — a retry that
    reused the id of a failed tx would have its success deduped against
    the old error event)."""
    last: Optional[wire.SigningResultEvent] = None
    for attempt in range(1, attempts + 1):
        tx_id = tx_base if attempt == 1 else f"{tx_base}~retry{attempt - 1}"
        try:
            ev = _sign(cluster, wallet_id, tx_id, timeout_s=timeout_s)
        except TimeoutError as e:
            notes.append(f"{tx_id}: client-side timeout ({e})")
            continue
        except Exception as e:  # noqa: BLE001 — e.g. enqueue during failover
            notes.append(f"{tx_id}: submit failed retryably ({e!r})")
            time.sleep(0.5)
            continue
        if ev.result_type == wire.RESULT_SUCCESS:
            if attempt > 1:
                notes.append(f"{tx_base}: succeeded on attempt {attempt}")
            return ev
        last = ev
        notes.append(f"{tx_id}: ERROR ({ev.error_reason!r}); retrying")
    raise RuntimeError(
        f"signing {tx_base!r} failed after {attempts} attempts: "
        f"{last.error_reason if last else 'no result'}"
    )


def _reshare(cluster: LocalCluster, wallet_id: str,
             timeout_s: float = 60.0) -> wire.ResharingSuccessEvent:
    return cluster.reshare_sync(
        wallet_id, new_threshold=1, key_type=wire.KEY_TYPE_ED25519,
        timeout_s=timeout_s,
    )


# -- node lifecycle (SIGKILL semantics) --------------------------------------


def _stop_heartbeat(node) -> None:
    """The process is dead: heartbeats stop, the ready key is NOT
    resigned — peers must detect the death via heartbeat staleness (the
    registry's change-based liveness), exactly like a real SIGKILL."""
    reg = node.registry
    reg._registered = False
    reg._stop.set()


def kill_node(cluster: LocalCluster, node_id: str) -> None:
    """Crash a node mid-protocol: its transport goes silent both ways
    and its registry heartbeat stops."""
    ft = cluster.fault_transports.get(node_id)
    if ft is None:
        raise KeyError(
            f"{node_id!r} has no FaultyTransport — install a fault plan "
            f"for it (LocalCluster fault_plans)"
        )
    _stop_heartbeat(cluster.nodes[node_id])
    ft.crash_switch.crash()


def restart_node(cluster: LocalCluster, node_id: str) -> None:
    """Bring a crashed node back: transport restored, registry re-arms
    its heartbeat and watch loop, readiness re-announced."""
    node = cluster.nodes[node_id]
    ft = cluster.fault_transports[node_id]
    ft.crash_switch.restore()
    reg = node.registry
    if reg._thread is not None:
        reg._thread.join(timeout=2.0)
        reg._thread = None
    reg._stop = threading.Event()
    reg.watch()
    reg.ready()


# -- the drills --------------------------------------------------------------


def _drill_node_crash(seed: int, scale: float) -> Tuple[str, bool, List[str], dict, dict]:
    plan = named_plan("node-crash", seed)
    notes: List[str] = []
    cluster, root = _mk_cluster({"node2": plan})
    try:
        # the crash rule fires inside the transport; SIGKILL semantics
        # need the heartbeat stopped at the same instant
        ft = cluster.fault_transports["node2"]
        ft.crash_switch.on_crash(
            lambda n=cluster.nodes["node2"]: _stop_heartbeat(n)
        )
        _eddsa_keygen(cluster, "w-crash")
        notes.append("keygen complete on all 3 nodes")

        # tx-c0 triggers the crash: node2 dies the moment it announces
        # itself in the signing session. The attempt must fail LOUDLY
        # (bounded ERROR event), never hang.
        try:
            ev0 = _sign(cluster, "w-crash", "tx-c0", timeout_s=60.0)
            loud = ev0.result_type == wire.RESULT_ERROR
            notes.append(
                f"tx-c0 under crash: {ev0.result_type} "
                f"({ev0.error_reason!r})"
            )
        except TimeoutError:
            loud = False
            notes.append("tx-c0 HUNG — no loud failure within budget")
        if not ft.crash_switch.crashed:
            notes.append("crash rule never fired")
            return "crash-not-triggered", False, notes, plan.to_json(), {}

        # survivors must notice the death (heartbeat staleness) ...
        survivors = ("node0", "node1")
        noticed = _wait(
            lambda: all(
                not cluster.nodes[n].registry.is_peer_ready("node2")
                for n in survivors
            ),
            timeout_s=15.0,
        )
        notes.append(f"death detected by survivors: {noticed}")
        # ... and sign with t+1 = 2 of 3
        ev1 = _sign_retrying(cluster, "w-crash", "tx-c1", notes)
        notes.append("signed with one node down")

        # restart: the node rejoins and the full committee signs again,
        # then the wallet reshares cleanly on the recovered cluster
        restart_node(cluster, "node2")
        rejoined = _wait(
            lambda: cluster.nodes["node0"].registry.is_peer_ready("node2"),
            timeout_s=15.0,
        )
        notes.append(f"node2 rejoined after restart: {rejoined}")
        ev2 = _sign_retrying(cluster, "w-crash", "tx-c2", notes)
        _reshare(cluster, "w-crash")
        ev3 = _sign_retrying(cluster, "w-crash", "tx-c3", notes)
        notes.append("post-restart sign + reshare + sign complete")

        ok = (loud and noticed and rejoined
              and ev1.result_type == wire.RESULT_SUCCESS
              and ev2.result_type == wire.RESULT_SUCCESS
              and ev3.result_type == wire.RESULT_SUCCESS)
        return ("recovered" if ok else "degraded", ok, notes,
                plan.to_json(), _merged_stats(cluster).to_json())
    finally:
        _close(cluster, root)


def _drill_drop_jitter(seed: int, scale: float) -> Tuple[str, bool, List[str], dict, dict]:
    plan = named_plan("drop-jitter", seed, scale=scale)
    notes: List[str] = []
    cluster, root = _mk_cluster({"*": plan})
    try:
        attempts = _eddsa_keygen(cluster, "w-dj")
        notes.append(f"keygen complete (attempt {attempts})")
        for i in range(3):
            ev = _sign_retrying(cluster, "w-dj", f"tx-dj{i}", notes)
            assert ev.result_type == wire.RESULT_SUCCESS
        notes.append("3 signatures under 10% unicast loss + jitter")
        _reshare(cluster, "w-dj")
        ev = _sign_retrying(cluster, "w-dj", "tx-dj-post-rs", notes)
        notes.append("reshare + post-reshare signature complete")
        stats = _merged_stats(cluster)
        faults = stats.to_json()
        notes.append(
            f"faults injected: {faults['counters']}; "
            f"unicast losses absorbed by retries: {stats.retries_observed}"
        )
        ok = ev.result_type == wire.RESULT_SUCCESS
        return ("success" if ok else "failed", ok, notes,
                plan.to_json(), faults)
    finally:
        _close(cluster, root)


def _drill_broker_failover(seed: int, scale: float) -> Tuple[str, bool, List[str], dict, dict]:
    plan = named_plan("broker-failover", seed)
    notes: List[str] = []
    cluster, root = _mk_cluster(
        {}, transport="tcp", broker_standby=True, reply_timeout_s=8.0,
    )
    try:
        _eddsa_keygen(cluster, "w-bf")
        ev = _sign(cluster, "w-bf", "tx-bf0", timeout_s=60.0)
        assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
        notes.append("keygen + baseline signature over primary broker")

        cluster.broker.close()
        notes.append("primary broker killed mid-run")
        # every client walks its address list to the standby and replays
        # subscriptions; the first post-failover submits can land in a
        # dead socket buffer, so the client-level retry does the rest
        ev = _sign_retrying(cluster, "w-bf", "tx-bf1", notes,
                            attempts=4, timeout_s=30.0)
        notes.append("signature completed via standby broker")
        ok = ev.result_type == wire.RESULT_SUCCESS
        return ("success" if ok else "failed", ok, notes,
                plan.to_json(), _merged_stats(cluster).to_json())
    finally:
        _close(cluster, root)


def _drill_partition(seed: int, scale: float) -> Tuple[str, bool, List[str], dict, dict]:
    plan = named_plan("partition", seed)
    notes: List[str] = []
    cluster, root = _mk_cluster(
        {"*": plan}, hello_timeout_s=3.0, reply_timeout_s=4.0,
        session_timeout_s=8.0,
    )
    try:
        _eddsa_keygen(cluster, "w-p")
        ev = _sign(cluster, "w-p", "tx-p0", timeout_s=60.0)
        assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
        notes.append("keygen + baseline signature pre-partition")

        plan.activate()  # partition node1+node2: over threshold, no quorum
        t0 = time.monotonic()
        try:
            ev1 = _sign(cluster, "w-p", "tx-p1", timeout_s=90.0)
            loud = ev1.result_type == wire.RESULT_ERROR
            notes.append(
                f"tx-p1 under partition: {ev1.result_type} after "
                f"{time.monotonic() - t0:.1f}s "
                f"(timeout={getattr(ev1, 'is_timeout', False)}, "
                f"reason={ev1.error_reason!r})"
            )
        except TimeoutError:
            loud = False
            notes.append("tx-p1 HUNG under partition — drill failed")

        plan.heal()
        notes.append("partition healed")
        ev2 = _sign_retrying(cluster, "w-p", "tx-p2", notes)
        ok = loud and ev2.result_type == wire.RESULT_SUCCESS
        notes.append("post-heal signature complete")
        return ("loud-failure-then-recovery" if ok else "degraded", ok,
                notes, plan.to_json(), _merged_stats(cluster).to_json())
    finally:
        _close(cluster, root)


def _drill_kill_resume(seed: int, scale: float):
    """SIGKILL mid-round-2, restart, SAME session completes.

    node2's fault plan crashes it the instant its round-2 decommitment
    broadcast leaves (the WAL already holds the round-2 checkpoint —
    checkpoint-before-route). Survivors stall: the signing quorum includes
    the corpse, so no 2-of-3 fallback exists for THIS session. The drill
    then respawns node2 over its surviving on-disk state; boot-time WAL
    replay must re-claim the session, answer the ``__resume__`` handshake
    and finish with the bit-identical signature on every node.
    """
    from ..core import hostmath as hm
    from ..warm.prewarm import warm_for_drill
    from .plan import crash_node

    # warm the drill's signing bucket BEFORE any session is live (a warm
    # pass mid-drill would stall the survivors past their round
    # timeouts) so resume_latency_s measures recovery, not the compile
    # wall — the warm stats ride the report beside it
    warm_stats = warm_for_drill()
    plan = FaultPlan(
        seed, [crash_node("node2", at_round="eddsa/sign/2", topic="sign:*")]
    )
    notes: List[str] = []
    cluster, root = _mk_cluster({"node2": plan}, session_wal=True)
    try:
        ft = cluster.fault_transports["node2"]
        ft.crash_switch.on_crash(
            lambda n=cluster.nodes["node2"]: _stop_heartbeat(n)
        )
        _eddsa_keygen(cluster, "w-kr")
        notes.append("keygen complete on all 3 nodes")
        pub = bytes.fromhex(
            cluster.nodes["node0"].keyinfo
            .get(wire.KEY_TYPE_ED25519, "w-kr").public_key
        )

        box: dict = {}

        def signer():
            try:
                box["ev"] = _sign(cluster, "w-kr", "tx-kr0", timeout_s=90.0)
            except Exception as e:  # noqa: BLE001 — surfaced via the box
                box["err"] = e
            box["t_done"] = time.monotonic()

        th = threading.Thread(target=signer, daemon=True)
        th.start()

        if not _wait(lambda: ft.crash_switch.crashed, timeout_s=30.0):
            notes.append("crash rule never fired")
            return "crash-not-triggered", False, notes, plan.to_json(), {}
        notes.append("node2 SIGKILLed on its round-2 broadcast")

        # hold the survivors' stalled Session objects so their in-memory
        # results can be compared bit-for-bit after recovery
        dedup = "w-kr-tx-kr0"
        held: Dict[str, object] = {}
        for nid in ("node0", "node1"):
            ec = cluster.node_consumers[nid]
            with ec._lock:
                ss = list(ec._sessions.get(dedup) or [])
            if ss:
                held[nid] = ss[0]
        stalled = len(held) == 2 and all(not s.done for s in held.values())
        notes.append(f"survivor sessions stalled mid-round: {stalled}")

        time.sleep(0.5)  # everything node2 says next must be WAL replay
        t_respawn = time.monotonic()
        new_ec = cluster.respawn_node("node2")
        with new_ec._lock:
            ss = list(new_ec._sessions.get(dedup) or [])
        if ss:
            held["node2"] = ss[0]
        notes.append(f"node2 respawned; WAL session re-claimed: {bool(ss)}")

        th.join(90.0)
        faults = _merged_stats(cluster).to_json()
        if "ev" not in box:
            notes.append(
                f"signing never completed after respawn "
                f"({box.get('err')!r})"
            )
            return "hung", False, notes, plan.to_json(), faults
        ev = box["ev"]
        resume_latency = box["t_done"] - t_respawn
        notes.append(
            f"tx-kr0: {ev.result_type} {resume_latency:.2f}s after respawn"
        )
        sig_ok = (
            ev.result_type == wire.RESULT_SUCCESS
            and hm.ed25519_verify(
                pub, b"chaos:tx-kr0", bytes.fromhex(ev.signature)
            )
        )
        notes.append(f"signature verifies under the wallet key: {sig_ok}")
        # the client event comes from whichever node finished FIRST (the
        # per-tx result queue dedups the rest) — give the other parties a
        # beat to cross their own finish line before comparing bytes
        _wait(lambda: all(s.done for s in held.values()), timeout_s=10.0)
        results = {
            nid: s.party.result.hex()
            for nid, s in held.items()
            if s.party.result is not None
        }
        identical = (
            len(results) == 3
            and len(set(results.values())) == 1
            and ev.signature in results.values()
        )
        notes.append(
            f"bit-identical signature on {sorted(results)}: {identical}"
        )
        # the result event fires from on_done, which runs BEFORE the WAL
        # drop in Session._finish — poll instead of instant-checking
        wal_drained = _wait(
            lambda: not cluster.nodes["node2"].session_wal.incomplete(),
            timeout_s=5.0,
        )
        notes.append(f"node2 WAL drained after completion: {wal_drained}")
        ok = stalled and sig_ok and identical and wal_drained
        return ("resumed" if ok else "degraded", ok, notes, plan.to_json(),
                faults,
                {"resume_latency_s": resume_latency, "warm": warm_stats})
    finally:
        _close(cluster, root)


class _DetRng:
    """Deterministic CSPRNG stand-in for the cheater drill's synthetic
    OT legs: a hash-counter stream, so the same seed draws identical
    bytes in identical call order (mirrors the tier-1 OT pipeline
    fixtures — the drill must be byte-reproducible from its seed)."""

    def __init__(self, seed: int):
        self.seed = seed
        self.ctr = 0

    def token_bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += hashlib.sha256(
                b"chaos-rng|%d|%d" % (self.seed, self.ctr)
            ).digest()
            self.ctr += 1
        return bytes(out[:n])

    def randbelow(self, n: int) -> int:
        return int.from_bytes(self.token_bytes(40), "big") % n


def _synth_ot_leg(seed: int):
    """OTMtALeg with synthetic base-OT material satisfying the base-OT
    postcondition (keysD[j] = k^{Δ_j}_j), skipping the curve ladders.
    The tag is 8 bytes like the tier-1 pipeline fixtures' so the drill's
    check kernels land in the SAME compile family (prefix lengths are
    part of the jit key) instead of paying a second compile wall."""
    import numpy as np

    from ..protocol.ecdsa import mta_ot

    rng = _DetRng(seed)
    leg = mta_ot.OTMtALeg.__new__(mta_ot.OTMtALeg)
    leg.tag = b"drill-|%d" % (seed % 10)
    leg.rng = _DetRng(seed + 1000)
    leg.ctr = 0
    leg.k0 = np.frombuffer(
        rng.token_bytes(mta_ot.KAPPA * 32), np.uint8
    ).reshape(-1, 32).copy()
    leg.k1 = np.frombuffer(
        rng.token_bytes(mta_ot.KAPPA * 32), np.uint8
    ).reshape(-1, 32).copy()
    leg.delta = np.frombuffer(rng.token_bytes(mta_ot.KAPPA), np.uint8) & 1
    leg.keysD = np.where(leg.delta[:, None].astype(bool), leg.k1, leg.k0)
    leg.delta_packed = mta_ot._pack(leg.delta)
    leg._delta_rows = np.nonzero(leg.delta)[0]
    return leg


def _drill_cheater(seed: int, scale: float):
    """Active deviation caught, blamed and absorbed under live traffic.

    Everything the cheater 'chooses' — which batch lane, which OT-MtA
    wire field (hence which check must catch it and which party is to
    blame), which byte, which xor mask — is a PRF draw from the named
    ``cheater`` plan, so the identical deviation replays from (seed,
    plan) alone. The corruption is injected protocol-level
    (``OTMtALeg.set_tamper``: the OT rounds never cross the transport
    in the in-process engine); the scheduler half drives the REAL
    quarantine machinery (``_absorb_cohort_abort``: retryable
    culprit-named ABORT event, claim handoff, bucket-snapped re-pack)
    with a recording engine stub — the real GG18+OT engine raising
    CohortAbort is covered by the slow tier (test_mta_ot.py). A live
    3-node cluster keeps signing EdDSA traffic throughout."""
    import numpy as np
    import jax.numpy as jnp

    from ..consumers.batch_scheduler import BatchSigningScheduler
    from ..core import bignum as bn
    from ..core.bignum import P256
    from ..engine.abort import CohortAbort
    from ..protocol.ecdsa import mta_ot
    from ..transport.loopback import LoopbackFabric

    plan = named_plan("cheater", seed)
    rule = plan.rules[0]
    notes: List[str] = []
    B = 4  # tier-1 OT batch shape (shared compile family)
    Q = mta_ot.Q

    # the corruption surfaces an active cheater controls, and the check
    # that MUST catch each (with the party its failure blames)
    surfaces = (
        ("U", None, "alice", mta_ot.CHECK_KOS),
        ("kos_tbar", None, "alice", mta_ot.CHECK_KOS),
        ("y1", 0, "bob", mta_ot.CHECK_GILBOA),
        ("D", 1, "bob", mta_ot.CHECK_GILBOA),
        ("B_pt", 0, "bob", mta_ot.CHECK_GILBOA),
        ("Beta_pt", 1, "bob", mta_ot.CHECK_CONSISTENCY),
    )
    lane = int(plan._u(rule, b"cheat", 0, lane="lane") * B)
    field_, set_idx, role, check = surfaces[
        int(plan._u(rule, b"cheat", 0, lane="field") * len(surfaces))
    ]
    spec = {
        "field": field_, "lane": lane,
        "byte": int(plan._u(rule, b"cheat", 0, lane="byte") * 4096),
        "xor": 1 + int(plan._u(rule, b"cheat", 0, lane="xor") * 255),
    }
    if set_idx is not None:
        spec["set"] = set_idx
    notes.append(
        f"PRF-derived deviation: field={field_} lane={lane} "
        f"byte={spec['byte']} xor={spec['xor']:#x} "
        f"(must blame {role} via {check!r})"
    )

    cluster, root = _mk_cluster()
    try:
        _eddsa_keygen(cluster, "w-ch")
        ev0 = _sign(cluster, "w-ch", "tx-ch0", timeout_s=60.0)
        assert ev0.result_type == wire.RESULT_SUCCESS, ev0.error_reason
        notes.append("keygen + baseline signature (live traffic up)")

        # live traffic rides concurrently with the cheat-and-catch
        live: dict = {}

        def _live_signer():
            try:
                live["ev"] = _sign_retrying(
                    cluster, "w-ch", "tx-ch-live", notes
                )
            except Exception as e:  # noqa: BLE001 — surfaced via the box
                live["err"] = e

        live_th = threading.Thread(target=_live_signer, daemon=True)
        live_th.start()

        # -- the deviation, and the checks catching it --------------------
        def _limbs(vals):
            return jnp.asarray(bn.batch_to_limbs(vals, P256))

        r = _DetRng(seed + 31)
        # nonzero Bob-side scalars: b ≡ 0 encodes the identity garbage
        # (the 2^-256 caveat SECURITY.md documents) and would mis-frame
        # the drill's blame assertion
        a = [r.randbelow(Q - 1) + 1 for _ in range(B)]
        g = [r.randbelow(Q - 1) + 1 for _ in range(B)]
        w = [r.randbelow(Q - 1) + 1 for _ in range(B)]

        leg = _synth_ot_leg(seed)
        leg.set_tamper(spec)
        leg.run_multi(_limbs(a), (_limbs(g), _limbs(w)))
        blames = leg.check_blame()
        caught = blames is not None and blames[lane] == (role, check)
        misblamed = [
            i for i, bl in enumerate(blames or [])
            if i != lane and bl is not None
        ]
        notes.append(f"blame vector: {blames}")
        if not caught or misblamed:
            notes.append(
                f"deviation NOT attributed cleanly (caught={caught}, "
                f"misblamed lanes={misblamed})"
            )
            return ("undetected", False, notes, plan.to_json(),
                    _merged_stats(cluster).to_json())

        # -- the quarantine: real scheduler machinery ---------------------
        survivors_expected = B - 1
        completed: List[Tuple[str, List[str]]] = []
        all_done = threading.Event()

        class _RecordingScheduler(BatchSigningScheduler):
            def _run_batch(self, batch_id, reqs, *mid, **kw):
                completed.append((batch_id, [m.tx_id for m, _r in reqs]))
                if sum(len(t) for _b, t in completed) >= survivors_expected:
                    all_done.set()

        fab = LoopbackFabric()
        t = fab.transport()
        events: List[wire.SigningResultEvent] = []
        ev_lock = threading.Lock()

        def _on_result(data: bytes) -> None:
            import json as _json

            with ev_lock:
                events.append(
                    wire.SigningResultEvent.from_json(_json.loads(data))
                )

        sub = t.queues.dequeue(f"{wire.TOPIC_SIGNING_RESULT}.*", _on_result)
        sched = _RecordingScheduler(
            types.SimpleNamespace(node_id="drill0", peer_ids=["drill0"]),
            transport=t,
        )
        reqs = [
            (wire.SignTxMessage(
                key_type="ecdsa", wallet_id=f"w-co{i}",
                network_internal_code="chaos", tx_id=f"tx-co{i}",
                tx=b"cohort:%d" % i,
            ), "")
            for i in range(B)
        ]
        abort = CohortAbort([(lane, role, check)], engine="gg18.sign")
        sched._absorb_cohort_abort("bdrill", reqs, frozenset(),
                                   abort.culprits)
        absorbed = all_done.wait(15.0)
        fab.drain(timeout_s=15.0)
        sub.unsubscribe()

        quarantined = [
            e for e in events if e.tx_id == reqs[lane][0].tx_id
        ]
        abort_named = (
            len(quarantined) == 1
            and quarantined[0].result_type == wire.RESULT_ERROR
            and quarantined[0].retryable
            and role in quarantined[0].error_reason
            and check in quarantined[0].error_reason
        )
        survivor_txs = sorted(
            tx for _b, txs in completed for tx in txs
        )
        expect_txs = sorted(
            m.tx_id for i, (m, _r) in enumerate(reqs) if i != lane
        )
        chunks = [len(txs) for _b, txs in completed]
        pow2 = all(n & (n - 1) == 0 for n in chunks)
        notes.append(
            f"quarantine: {len(quarantined)} retryable ABORT event(s) "
            f"naming ({role}, {check!r}); survivors re-packed into "
            f"pow-2 chunks {chunks}"
        )
        invariant = (
            absorbed and survivor_txs == expect_txs
            and len(survivor_txs) + len(quarantined) == B
        )
        notes.append(
            f"cohort invariant: submitted={B} = completed="
            f"{len(survivor_txs)} + quarantined={len(quarantined)}, "
            f"pending={B - len(survivor_txs) - len(quarantined)}"
        )

        # -- survivors complete: honest re-run at the same batch shape ----
        leg.set_tamper(None)
        out2 = leg.run_multi(_limbs(a), (_limbs(g), _limbs(w)))
        blames2 = leg.check_blame()
        clean = blames2 is not None and all(bl is None for bl in blames2)
        shares_ok = True
        for (al, be), b_ints in zip(out2, (g, w)):
            ai = bn.batch_from_limbs(np.asarray(al), P256)
            bi = bn.batch_from_limbs(np.asarray(be), P256)
            shares_ok &= all(
                (ai[i] + bi[i]) % Q == a[i] * b_ints[i] % Q
                for i in range(B)
            )
        notes.append(
            f"honest re-run: checks clean={clean}, MtA shares "
            f"valid={shares_ok}"
        )

        live_th.join(90.0)
        live_ok = (
            "ev" in live
            and live["ev"].result_type == wire.RESULT_SUCCESS
        )
        notes.append(f"live traffic kept signing throughout: {live_ok}")

        ok = (caught and not misblamed and abort_named and invariant
              and clean and shares_ok and live_ok)
        culprit = {
            "session": reqs[lane][0].tx_id, "lane": lane,
            "party": role, "check": check, "field": field_,
        }
        survivors = {
            "submitted": B, "quarantined": len(quarantined),
            "completed": len(survivor_txs),
            "pending": B - len(survivor_txs) - len(quarantined),
            "chunks": chunks if pow2 else chunks + ["NOT-POW2"],
        }
        return ("caught-and-quarantined" if ok else "leaked", ok, notes,
                plan.to_json(), _merged_stats(cluster).to_json(),
                {"culprit": culprit, "survivors": survivors})
    finally:
        _close(cluster, root)


DRILLS: Dict[str, Tuple[Callable, str]] = {
    "node-crash": (_drill_node_crash, "recovered"),
    "drop-jitter": (_drill_drop_jitter, "success"),
    "broker-failover": (_drill_broker_failover, "success"),
    "partition": (_drill_partition, "loud-failure-then-recovery"),
    "kill-resume": (_drill_kill_resume, "resumed"),
    "cheater": (_drill_cheater, "caught-and-quarantined"),
}


def run_drill(name: str, seed: int = DEFAULT_SEED,
              scale: float = 1.0) -> DrillReport:
    """Run one named drill; never raises — failures land in the report."""
    if name not in DRILLS:
        raise KeyError(f"unknown drill {name!r}; have {sorted(DRILLS)}")
    fn, expected = DRILLS[name]
    t0 = time.monotonic()
    extra: dict = {}
    try:
        res = fn(seed, scale)
        outcome, ok, notes, plan_json, faults = res[:5]
        if len(res) > 5:  # optional per-drill metrics (resume_latency_s)
            extra = res[5]
        err = ""
    except Exception as e:  # noqa: BLE001 — report, don't crash the runner
        outcome, ok, notes, plan_json, faults = "error", False, [], {}, {}
        err = repr(e)
    # flight-recorder buffers survive cluster close — merge every node's
    # ring into one Perfetto-loadable document for the report; a failed
    # drill also drops an incident dump (dir set by the drill's cluster,
    # so it only survives when the operator keeps the root)
    if not ok:
        tracing.incident("drill-failure", node="local", drill=name,
                         outcome=outcome)
    trace_doc = snapshot_chrome(
        clear=True, meta={"drill": name, "seed": seed, "outcome": outcome},
    )
    return DrillReport(
        name=name, seed=seed, expected=expected, outcome=outcome, ok=ok,
        duration_s=time.monotonic() - t0, plan=plan_json, faults=faults,
        notes=notes, error=err, trace=trace_doc, **extra,
    )


def run_all(seed: int = DEFAULT_SEED, scale: float = 1.0) -> List[DrillReport]:
    return [run_drill(name, seed=seed, scale=scale) for name in DRILLS]
