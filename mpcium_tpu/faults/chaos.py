"""Chaos drill runner: reproducible failure drills over a live cluster.

Each drill stands up an in-process :class:`~..cluster.LocalCluster`
(loopback or TCP+standby), runs real protocol work — EdDSA keygen →
signing → resharing through the full client/queue/consumer path — under
a seed-deterministic :class:`~.plan.FaultPlan`, and emits a structured
:class:`DrillReport`. ``scripts/chaos_drill.py`` is the CLI; the fast
deterministic variants run in the test tier under the ``chaos`` marker.

Drill catalog (expected outcome in parentheses):

- ``node-crash`` (recovered) — node2 SIGKILLs the instant it joins its
  first signing session; the tx fails LOUDLY, the committee detects the
  death via heartbeat staleness and signs with t+1 survivors, the node
  restarts, rejoins and signs again — then the wallet reshares cleanly.
- ``drop-jitter`` (success) — 10 % loss on every acked protocol unicast
  plus 50–200 ms jitter on all protocol traffic; the retry budgets
  absorb it and keygen → signing → reshare all complete.
- ``broker-failover`` (success) — TCP transport, hot-standby broker;
  the primary dies mid-run and clients transparently fail over.
- ``partition`` (loud-failure-then-recovery) — two of three nodes are
  isolated (over threshold: no quorum can form anywhere); signing fails
  loudly and retryably — a bounded timeout ERROR event, no hang, no
  silent corruption — and succeeds after the partition heals.
- ``kill-resume`` (resumed) — with the session WAL on, node2 SIGKILLs
  mid-round-2 of a signing session; the survivors stall (the quorum
  includes the corpse), the node respawns over its on-disk state, WAL
  replay re-claims the session and the SAME run completes with the
  bit-identical signature; the report carries ``resume_latency_s``.

Reproducing a failed drill: the report carries ``seed`` and the full
plan JSON; ``scripts/chaos_drill.py --plan <name> --seed <seed>`` reruns
the identical fault schedule (see plan.py's determinism contract).
"""
from __future__ import annotations

import shutil
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .. import wire
from ..cluster import LocalCluster, load_test_preparams
from ..trace import snapshot_chrome
from ..utils import log, tracing
from .plan import FaultPlan, named_plan
from .transport import FaultStats

DEFAULT_SEED = 7


@dataclass
class DrillReport:
    name: str
    seed: int
    expected: str
    outcome: str
    ok: bool
    duration_s: float
    plan: dict = field(default_factory=dict)
    faults: dict = field(default_factory=dict)
    notes: List[str] = field(default_factory=list)
    error: str = ""
    # kill-resume: wall time from respawn to the resumed session's result
    resume_latency_s: float = 0.0
    # kill-resume: warm-cache stats from the pre-respawn warm pass
    # ({warmed, hits, budget_s} — mpcium_tpu.warm.prewarm.warm_for_drill)
    warm: dict = field(default_factory=dict)
    # merged cross-node Chrome-trace-event JSON (flight-recorder snapshot;
    # load in Perfetto / chrome://tracing)
    trace: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "expected": self.expected,
            "outcome": self.outcome,
            "ok": self.ok,
            "duration_s": round(self.duration_s, 3),
            "plan": self.plan,
            "faults": self.faults,
            "notes": self.notes,
            "error": self.error,
            "resume_latency_s": round(self.resume_latency_s, 3),
            "warm": self.warm,
            "trace": self.trace,
        }


def _wait(cond: Callable[[], bool], timeout_s: float,
          poll_s: float = 0.05) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return False


# -- cluster plumbing --------------------------------------------------------


def _mk_cluster(fault_plans: Optional[Dict[str, FaultPlan]] = None,
                transport: str = "loopback",
                broker_standby: bool = False,
                hello_timeout_s: float = 4.0,
                reply_timeout_s: float = 6.0,
                session_timeout_s: float = 12.0,
                gc_interval_s: float = 1.0,
                session_wal: bool = False) -> Tuple[LocalCluster, str]:
    """A 3-node t=1 drill cluster with tightened failure deadlines, so
    loud failures surface inside the drill budget instead of the
    production 30-minute GC."""
    root = tempfile.mkdtemp(prefix="mpcium-chaos-")
    cluster = LocalCluster(
        n_nodes=3,
        threshold=1,
        root_dir=root,
        preparams=load_test_preparams(bits=1024),
        transport=transport,
        broker_standby=broker_standby,
        fault_plans=fault_plans,
        hello_timeout_s=hello_timeout_s,
        reply_timeout_s=reply_timeout_s,
        session_timeout_s=session_timeout_s,
        gc_interval_s=gc_interval_s,
        session_wal=session_wal,
    )
    return cluster, root


def _close(cluster: LocalCluster, root: str) -> None:
    try:
        cluster.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _merged_stats(cluster: LocalCluster) -> FaultStats:
    merged = FaultStats()
    retired = getattr(cluster, "_retired_fault_transports", [])
    for ft in list(cluster.fault_transports.values()) + list(retired):
        merged.merge(ft.stats)
    return merged


def _eddsa_keygen(cluster: LocalCluster, wallet_id: str,
                  timeout_s: float = 60.0, attempts: int = 3) -> int:
    """EdDSA-only distributed keygen via direct sessions on every node
    (wallet creation through the client forces the heavyweight GG18
    curve too; drills exercise the failure machinery, not Paillier).
    Returns the number of attempts used."""
    from ..config import get_config

    threshold = get_config().mpc_threshold
    last_err: Optional[str] = None
    for attempt in range(1, attempts + 1):
        sessions = [
            node.create_keygen_session(
                wire.KEY_TYPE_ED25519, wallet_id, threshold
            )
            for node in cluster.nodes.values()
        ]
        for s in sessions:
            s.listen()
        ok = True
        for s in sessions:
            if not s.wait(timeout_s) or s.failed:
                ok = False
        for s in sessions:
            s.close()
        if ok:
            return attempt
        last_err = "; ".join(
            s.session_id for s in sessions if s.failed
        ) or "timeout"
        log.warn("drill keygen attempt failed; retrying",
                 wallet=wallet_id, attempt=attempt, detail=last_err)
    raise RuntimeError(
        f"eddsa keygen for {wallet_id!r} failed after {attempts} "
        f"attempts: {last_err}"
    )


def _sign(cluster: LocalCluster, wallet_id: str, tx_id: str,
          timeout_s: float = 60.0) -> wire.SigningResultEvent:
    return cluster.sign_sync(
        wire.SignTxMessage(
            key_type=wire.KEY_TYPE_ED25519,
            wallet_id=wallet_id,
            network_internal_code="chaos",
            tx_id=tx_id,
            tx=b"chaos:" + tx_id.encode(),
        ),
        timeout_s=timeout_s,
    )


def _sign_retrying(cluster: LocalCluster, wallet_id: str, tx_base: str,
                   notes: List[str], attempts: int = 3,
                   timeout_s: float = 60.0) -> wire.SigningResultEvent:
    """Client-level retry: terminal errors and timeouts re-submit under a
    FRESH tx id (result queues are idempotent per tx id — a retry that
    reused the id of a failed tx would have its success deduped against
    the old error event)."""
    last: Optional[wire.SigningResultEvent] = None
    for attempt in range(1, attempts + 1):
        tx_id = tx_base if attempt == 1 else f"{tx_base}~retry{attempt - 1}"
        try:
            ev = _sign(cluster, wallet_id, tx_id, timeout_s=timeout_s)
        except TimeoutError as e:
            notes.append(f"{tx_id}: client-side timeout ({e})")
            continue
        except Exception as e:  # noqa: BLE001 — e.g. enqueue during failover
            notes.append(f"{tx_id}: submit failed retryably ({e!r})")
            time.sleep(0.5)
            continue
        if ev.result_type == wire.RESULT_SUCCESS:
            if attempt > 1:
                notes.append(f"{tx_base}: succeeded on attempt {attempt}")
            return ev
        last = ev
        notes.append(f"{tx_id}: ERROR ({ev.error_reason!r}); retrying")
    raise RuntimeError(
        f"signing {tx_base!r} failed after {attempts} attempts: "
        f"{last.error_reason if last else 'no result'}"
    )


def _reshare(cluster: LocalCluster, wallet_id: str,
             timeout_s: float = 60.0) -> wire.ResharingSuccessEvent:
    return cluster.reshare_sync(
        wallet_id, new_threshold=1, key_type=wire.KEY_TYPE_ED25519,
        timeout_s=timeout_s,
    )


# -- node lifecycle (SIGKILL semantics) --------------------------------------


def _stop_heartbeat(node) -> None:
    """The process is dead: heartbeats stop, the ready key is NOT
    resigned — peers must detect the death via heartbeat staleness (the
    registry's change-based liveness), exactly like a real SIGKILL."""
    reg = node.registry
    reg._registered = False
    reg._stop.set()


def kill_node(cluster: LocalCluster, node_id: str) -> None:
    """Crash a node mid-protocol: its transport goes silent both ways
    and its registry heartbeat stops."""
    ft = cluster.fault_transports.get(node_id)
    if ft is None:
        raise KeyError(
            f"{node_id!r} has no FaultyTransport — install a fault plan "
            f"for it (LocalCluster fault_plans)"
        )
    _stop_heartbeat(cluster.nodes[node_id])
    ft.crash_switch.crash()


def restart_node(cluster: LocalCluster, node_id: str) -> None:
    """Bring a crashed node back: transport restored, registry re-arms
    its heartbeat and watch loop, readiness re-announced."""
    node = cluster.nodes[node_id]
    ft = cluster.fault_transports[node_id]
    ft.crash_switch.restore()
    reg = node.registry
    if reg._thread is not None:
        reg._thread.join(timeout=2.0)
        reg._thread = None
    reg._stop = threading.Event()
    reg.watch()
    reg.ready()


# -- the drills --------------------------------------------------------------


def _drill_node_crash(seed: int, scale: float) -> Tuple[str, bool, List[str], dict, dict]:
    plan = named_plan("node-crash", seed)
    notes: List[str] = []
    cluster, root = _mk_cluster({"node2": plan})
    try:
        # the crash rule fires inside the transport; SIGKILL semantics
        # need the heartbeat stopped at the same instant
        ft = cluster.fault_transports["node2"]
        ft.crash_switch.on_crash(
            lambda n=cluster.nodes["node2"]: _stop_heartbeat(n)
        )
        _eddsa_keygen(cluster, "w-crash")
        notes.append("keygen complete on all 3 nodes")

        # tx-c0 triggers the crash: node2 dies the moment it announces
        # itself in the signing session. The attempt must fail LOUDLY
        # (bounded ERROR event), never hang.
        try:
            ev0 = _sign(cluster, "w-crash", "tx-c0", timeout_s=60.0)
            loud = ev0.result_type == wire.RESULT_ERROR
            notes.append(
                f"tx-c0 under crash: {ev0.result_type} "
                f"({ev0.error_reason!r})"
            )
        except TimeoutError:
            loud = False
            notes.append("tx-c0 HUNG — no loud failure within budget")
        if not ft.crash_switch.crashed:
            notes.append("crash rule never fired")
            return "crash-not-triggered", False, notes, plan.to_json(), {}

        # survivors must notice the death (heartbeat staleness) ...
        survivors = ("node0", "node1")
        noticed = _wait(
            lambda: all(
                not cluster.nodes[n].registry.is_peer_ready("node2")
                for n in survivors
            ),
            timeout_s=15.0,
        )
        notes.append(f"death detected by survivors: {noticed}")
        # ... and sign with t+1 = 2 of 3
        ev1 = _sign_retrying(cluster, "w-crash", "tx-c1", notes)
        notes.append("signed with one node down")

        # restart: the node rejoins and the full committee signs again,
        # then the wallet reshares cleanly on the recovered cluster
        restart_node(cluster, "node2")
        rejoined = _wait(
            lambda: cluster.nodes["node0"].registry.is_peer_ready("node2"),
            timeout_s=15.0,
        )
        notes.append(f"node2 rejoined after restart: {rejoined}")
        ev2 = _sign_retrying(cluster, "w-crash", "tx-c2", notes)
        _reshare(cluster, "w-crash")
        ev3 = _sign_retrying(cluster, "w-crash", "tx-c3", notes)
        notes.append("post-restart sign + reshare + sign complete")

        ok = (loud and noticed and rejoined
              and ev1.result_type == wire.RESULT_SUCCESS
              and ev2.result_type == wire.RESULT_SUCCESS
              and ev3.result_type == wire.RESULT_SUCCESS)
        return ("recovered" if ok else "degraded", ok, notes,
                plan.to_json(), _merged_stats(cluster).to_json())
    finally:
        _close(cluster, root)


def _drill_drop_jitter(seed: int, scale: float) -> Tuple[str, bool, List[str], dict, dict]:
    plan = named_plan("drop-jitter", seed, scale=scale)
    notes: List[str] = []
    cluster, root = _mk_cluster({"*": plan})
    try:
        attempts = _eddsa_keygen(cluster, "w-dj")
        notes.append(f"keygen complete (attempt {attempts})")
        for i in range(3):
            ev = _sign_retrying(cluster, "w-dj", f"tx-dj{i}", notes)
            assert ev.result_type == wire.RESULT_SUCCESS
        notes.append("3 signatures under 10% unicast loss + jitter")
        _reshare(cluster, "w-dj")
        ev = _sign_retrying(cluster, "w-dj", "tx-dj-post-rs", notes)
        notes.append("reshare + post-reshare signature complete")
        stats = _merged_stats(cluster)
        faults = stats.to_json()
        notes.append(
            f"faults injected: {faults['counters']}; "
            f"unicast losses absorbed by retries: {stats.retries_observed}"
        )
        ok = ev.result_type == wire.RESULT_SUCCESS
        return ("success" if ok else "failed", ok, notes,
                plan.to_json(), faults)
    finally:
        _close(cluster, root)


def _drill_broker_failover(seed: int, scale: float) -> Tuple[str, bool, List[str], dict, dict]:
    plan = named_plan("broker-failover", seed)
    notes: List[str] = []
    cluster, root = _mk_cluster(
        {}, transport="tcp", broker_standby=True, reply_timeout_s=8.0,
    )
    try:
        _eddsa_keygen(cluster, "w-bf")
        ev = _sign(cluster, "w-bf", "tx-bf0", timeout_s=60.0)
        assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
        notes.append("keygen + baseline signature over primary broker")

        cluster.broker.close()
        notes.append("primary broker killed mid-run")
        # every client walks its address list to the standby and replays
        # subscriptions; the first post-failover submits can land in a
        # dead socket buffer, so the client-level retry does the rest
        ev = _sign_retrying(cluster, "w-bf", "tx-bf1", notes,
                            attempts=4, timeout_s=30.0)
        notes.append("signature completed via standby broker")
        ok = ev.result_type == wire.RESULT_SUCCESS
        return ("success" if ok else "failed", ok, notes,
                plan.to_json(), _merged_stats(cluster).to_json())
    finally:
        _close(cluster, root)


def _drill_partition(seed: int, scale: float) -> Tuple[str, bool, List[str], dict, dict]:
    plan = named_plan("partition", seed)
    notes: List[str] = []
    cluster, root = _mk_cluster(
        {"*": plan}, hello_timeout_s=3.0, reply_timeout_s=4.0,
        session_timeout_s=8.0,
    )
    try:
        _eddsa_keygen(cluster, "w-p")
        ev = _sign(cluster, "w-p", "tx-p0", timeout_s=60.0)
        assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
        notes.append("keygen + baseline signature pre-partition")

        plan.activate()  # partition node1+node2: over threshold, no quorum
        t0 = time.monotonic()
        try:
            ev1 = _sign(cluster, "w-p", "tx-p1", timeout_s=90.0)
            loud = ev1.result_type == wire.RESULT_ERROR
            notes.append(
                f"tx-p1 under partition: {ev1.result_type} after "
                f"{time.monotonic() - t0:.1f}s "
                f"(timeout={getattr(ev1, 'is_timeout', False)}, "
                f"reason={ev1.error_reason!r})"
            )
        except TimeoutError:
            loud = False
            notes.append("tx-p1 HUNG under partition — drill failed")

        plan.heal()
        notes.append("partition healed")
        ev2 = _sign_retrying(cluster, "w-p", "tx-p2", notes)
        ok = loud and ev2.result_type == wire.RESULT_SUCCESS
        notes.append("post-heal signature complete")
        return ("loud-failure-then-recovery" if ok else "degraded", ok,
                notes, plan.to_json(), _merged_stats(cluster).to_json())
    finally:
        _close(cluster, root)


def _drill_kill_resume(seed: int, scale: float):
    """SIGKILL mid-round-2, restart, SAME session completes.

    node2's fault plan crashes it the instant its round-2 decommitment
    broadcast leaves (the WAL already holds the round-2 checkpoint —
    checkpoint-before-route). Survivors stall: the signing quorum includes
    the corpse, so no 2-of-3 fallback exists for THIS session. The drill
    then respawns node2 over its surviving on-disk state; boot-time WAL
    replay must re-claim the session, answer the ``__resume__`` handshake
    and finish with the bit-identical signature on every node.
    """
    from ..core import hostmath as hm
    from ..warm.prewarm import warm_for_drill
    from .plan import crash_node

    # warm the drill's signing bucket BEFORE any session is live (a warm
    # pass mid-drill would stall the survivors past their round
    # timeouts) so resume_latency_s measures recovery, not the compile
    # wall — the warm stats ride the report beside it
    warm_stats = warm_for_drill()
    plan = FaultPlan(
        seed, [crash_node("node2", at_round="eddsa/sign/2", topic="sign:*")]
    )
    notes: List[str] = []
    cluster, root = _mk_cluster({"node2": plan}, session_wal=True)
    try:
        ft = cluster.fault_transports["node2"]
        ft.crash_switch.on_crash(
            lambda n=cluster.nodes["node2"]: _stop_heartbeat(n)
        )
        _eddsa_keygen(cluster, "w-kr")
        notes.append("keygen complete on all 3 nodes")
        pub = bytes.fromhex(
            cluster.nodes["node0"].keyinfo
            .get(wire.KEY_TYPE_ED25519, "w-kr").public_key
        )

        box: dict = {}

        def signer():
            try:
                box["ev"] = _sign(cluster, "w-kr", "tx-kr0", timeout_s=90.0)
            except Exception as e:  # noqa: BLE001 — surfaced via the box
                box["err"] = e
            box["t_done"] = time.monotonic()

        th = threading.Thread(target=signer, daemon=True)
        th.start()

        if not _wait(lambda: ft.crash_switch.crashed, timeout_s=30.0):
            notes.append("crash rule never fired")
            return "crash-not-triggered", False, notes, plan.to_json(), {}
        notes.append("node2 SIGKILLed on its round-2 broadcast")

        # hold the survivors' stalled Session objects so their in-memory
        # results can be compared bit-for-bit after recovery
        dedup = "w-kr-tx-kr0"
        held: Dict[str, object] = {}
        for nid in ("node0", "node1"):
            ec = cluster.node_consumers[nid]
            with ec._lock:
                ss = list(ec._sessions.get(dedup) or [])
            if ss:
                held[nid] = ss[0]
        stalled = len(held) == 2 and all(not s.done for s in held.values())
        notes.append(f"survivor sessions stalled mid-round: {stalled}")

        time.sleep(0.5)  # everything node2 says next must be WAL replay
        t_respawn = time.monotonic()
        new_ec = cluster.respawn_node("node2")
        with new_ec._lock:
            ss = list(new_ec._sessions.get(dedup) or [])
        if ss:
            held["node2"] = ss[0]
        notes.append(f"node2 respawned; WAL session re-claimed: {bool(ss)}")

        th.join(90.0)
        faults = _merged_stats(cluster).to_json()
        if "ev" not in box:
            notes.append(
                f"signing never completed after respawn "
                f"({box.get('err')!r})"
            )
            return "hung", False, notes, plan.to_json(), faults
        ev = box["ev"]
        resume_latency = box["t_done"] - t_respawn
        notes.append(
            f"tx-kr0: {ev.result_type} {resume_latency:.2f}s after respawn"
        )
        sig_ok = (
            ev.result_type == wire.RESULT_SUCCESS
            and hm.ed25519_verify(
                pub, b"chaos:tx-kr0", bytes.fromhex(ev.signature)
            )
        )
        notes.append(f"signature verifies under the wallet key: {sig_ok}")
        # the client event comes from whichever node finished FIRST (the
        # per-tx result queue dedups the rest) — give the other parties a
        # beat to cross their own finish line before comparing bytes
        _wait(lambda: all(s.done for s in held.values()), timeout_s=10.0)
        results = {
            nid: s.party.result.hex()
            for nid, s in held.items()
            if s.party.result is not None
        }
        identical = (
            len(results) == 3
            and len(set(results.values())) == 1
            and ev.signature in results.values()
        )
        notes.append(
            f"bit-identical signature on {sorted(results)}: {identical}"
        )
        # the result event fires from on_done, which runs BEFORE the WAL
        # drop in Session._finish — poll instead of instant-checking
        wal_drained = _wait(
            lambda: not cluster.nodes["node2"].session_wal.incomplete(),
            timeout_s=5.0,
        )
        notes.append(f"node2 WAL drained after completion: {wal_drained}")
        ok = stalled and sig_ok and identical and wal_drained
        return ("resumed" if ok else "degraded", ok, notes, plan.to_json(),
                faults,
                {"resume_latency_s": resume_latency, "warm": warm_stats})
    finally:
        _close(cluster, root)


DRILLS: Dict[str, Tuple[Callable, str]] = {
    "node-crash": (_drill_node_crash, "recovered"),
    "drop-jitter": (_drill_drop_jitter, "success"),
    "broker-failover": (_drill_broker_failover, "success"),
    "partition": (_drill_partition, "loud-failure-then-recovery"),
    "kill-resume": (_drill_kill_resume, "resumed"),
}


def run_drill(name: str, seed: int = DEFAULT_SEED,
              scale: float = 1.0) -> DrillReport:
    """Run one named drill; never raises — failures land in the report."""
    if name not in DRILLS:
        raise KeyError(f"unknown drill {name!r}; have {sorted(DRILLS)}")
    fn, expected = DRILLS[name]
    t0 = time.monotonic()
    extra: dict = {}
    try:
        res = fn(seed, scale)
        outcome, ok, notes, plan_json, faults = res[:5]
        if len(res) > 5:  # optional per-drill metrics (resume_latency_s)
            extra = res[5]
        err = ""
    except Exception as e:  # noqa: BLE001 — report, don't crash the runner
        outcome, ok, notes, plan_json, faults = "error", False, [], {}, {}
        err = repr(e)
    # flight-recorder buffers survive cluster close — merge every node's
    # ring into one Perfetto-loadable document for the report; a failed
    # drill also drops an incident dump (dir set by the drill's cluster,
    # so it only survives when the operator keeps the root)
    if not ok:
        tracing.incident("drill-failure", node="local", drill=name,
                         outcome=outcome)
    trace_doc = snapshot_chrome(
        clear=True, meta={"drill": name, "seed": seed, "outcome": outcome},
    )
    return DrillReport(
        name=name, seed=seed, expected=expected, outcome=outcome, ok=ok,
        duration_s=time.monotonic() - t0, plan=plan_json, faults=faults,
        notes=notes, error=err, trace=trace_doc, **extra,
    )


def run_all(seed: int = DEFAULT_SEED, scale: float = 1.0) -> List[DrillReport]:
    return [run_drill(name, seed=seed, scale=scale) for name in DRILLS]
