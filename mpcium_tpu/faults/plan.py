"""Declarative, seed-deterministic fault plans.

A plan is an ordered list of rules — ``drop(p=0.1, topic="sign:*")``,
``delay(ms=(50, 200))``, ``duplicate()``, ``reorder()``,
``crash_node("node2", at_round="r1")``, ``partition(["node1"], 5.0)``,
``tamper(p=1.0, topic="bsign:*", mode="flip")`` — each with match
predicates over topic / observing node / channel / direction.

Determinism contract: every probabilistic decision is a pure function
``PRF(seed, rule_id, message_key, occurrence)`` where ``message_key``
hashes (topic, payload) and ``occurrence`` counts how many times THIS
rule has judged THIS message key. Two consequences:

1. the same ``(seed, plan)`` over the same traffic yields the identical
   fault schedule regardless of thread interleaving — concurrent
   messages cannot steal each other's PRNG draws the way a shared
   ``random.Random`` stream would let them;
2. a retransmission of the same bytes (an acked-unicast retry) re-rolls
   with a bumped occurrence instead of being deterministically
   black-holed forever — loss is i.i.d. per delivery attempt, like a
   real lossy link.

Time-windowed rules (``partition``) and trigger rules (``crash_node``)
are deterministic by construction (wall-time window from
:meth:`FaultPlan.activate`; round-trigger from message content).

Plans serialize to/from JSON so a failed drill reproduces from its
report: ``FaultPlan.from_json(report["plan"])``.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
from dataclasses import asdict, dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


def glob_match(pattern: str, value: str) -> bool:
    """Trailing-``*`` glob, the transport layer's own topic idiom
    (transport/loopback.py:topic_matches), extended with '*' matching
    everything."""
    if pattern == "*" or pattern == value:
        return True
    if pattern.endswith("*"):
        return value.startswith(pattern[:-1])
    return False


@dataclass(frozen=True)
class MsgEvent:
    """One message observed at a node's transport boundary."""

    direction: str  # "out" | "in"
    channel: str  # "pubsub" | "direct" | "queue"
    topic: str
    data: bytes
    node_id: str  # the node whose transport observed the message


@dataclass
class Rule:
    """One fault rule. ``kind`` ∈ {drop, delay, duplicate, reorder,
    crash_node, partition, tamper}; the constructor helpers below are
    the intended spelling."""

    kind: str
    p: float = 1.0
    topic: str = "*"
    node: str = "*"  # observing node (sender for "out", receiver for "in")
    channel: str = "*"  # pubsub | direct | queue | *
    direction: str = "out"  # out | in | *
    ms: Tuple[float, float] = (0.0, 0.0)  # delay bounds
    nodes: Tuple[str, ...] = ()  # partition: isolated nodes
    at_round: str = ""  # crash_node: fire when this round leaves the node
    start_s: float = 0.0  # partition: window start (from activate())
    duration_s: Optional[float] = None  # partition: None = until heal()
    mode: str = ""  # tamper: flip | truncate | replay ("" pre-tamper plans)
    rule_id: str = ""  # stable per-plan id (assigned by FaultPlan)

    def matches(self, ev: MsgEvent) -> bool:
        return (
            self.direction in ("*", ev.direction)
            and self.channel in ("*", ev.channel)
            and glob_match(self.topic, ev.topic)
            and glob_match(self.node, ev.node_id)
        )

    def to_json(self) -> dict:
        d = asdict(self)
        d["ms"] = list(self.ms)
        d["nodes"] = list(self.nodes)
        return d

    @classmethod
    def from_json(cls, d: dict) -> "Rule":
        d = dict(d)
        d["ms"] = tuple(d.get("ms", (0.0, 0.0)))
        d["nodes"] = tuple(d.get("nodes", ()))
        return cls(**d)


# -- rule constructors (the plan DSL) ---------------------------------------


def drop(p: float = 0.1, topic: str = "*", node: str = "*",
         channel: str = "*", direction: str = "out") -> Rule:
    """Lose matching messages with probability ``p`` per delivery
    attempt. On the acked-unicast channel a loss consumes one retry from
    the sender's budget (like a real lossy link under a retry protocol);
    on pub/sub and queue-enqueue it is a true loss."""
    return Rule(kind="drop", p=p, topic=topic, node=node, channel=channel,
                direction=direction)


def delay(ms: Tuple[float, float] = (50.0, 200.0), p: float = 1.0,
          topic: str = "*", node: str = "*", channel: str = "*",
          direction: str = "out") -> Rule:
    """Hold matching messages for a PRF-sampled jitter in ``ms`` before
    handing them on."""
    return Rule(kind="delay", p=p, ms=(float(ms[0]), float(ms[1])),
                topic=topic, node=node, channel=channel, direction=direction)


def duplicate(p: float = 1.0, topic: str = "*", node: str = "*",
              channel: str = "*", direction: str = "out") -> Rule:
    """Deliver matching messages twice (at-least-once semantics drill:
    queue consumers must be idempotent, dedup windows must hold)."""
    return Rule(kind="duplicate", p=p, topic=topic, node=node,
                channel=channel, direction=direction)


def reorder(p: float = 1.0, topic: str = "*", node: str = "*",
            channel: str = "*", direction: str = "out",
            window_ms: float = 100.0) -> Rule:
    """Hold a matching message back until the NEXT matching message has
    been sent (pairwise swap), flushing after ``window_ms`` if no
    successor shows up."""
    return Rule(kind="reorder", p=p, topic=topic, node=node, channel=channel,
                direction=direction, ms=(window_ms, window_ms))


def crash_node(node: str, at_round: str = "", topic: str = "*") -> Rule:
    """Kill ``node`` the moment it emits a message for ``at_round``
    (empty: its next outbound message). The transport flips its crash
    switch and fires the registered on-crash hook (chaos.py uses it to
    stop the registry heartbeat too — SIGKILL semantics)."""
    return Rule(kind="crash_node", node=node, at_round=at_round, topic=topic,
                direction="out")


TAMPER_MODES = ("flip", "truncate", "replay")


def tamper(p: float = 1.0, topic: str = "*", node: str = "*",
           channel: str = "*", direction: str = "out",
           mode: str = "flip") -> Rule:
    """Corrupt matching payloads in transit — the active-adversary
    drill (ISSUE 16: the protocol's KOS/Gilboa/consistency checks must
    catch, blame and survive this). Modes, all under the same
    PRF(seed, rule, msg, occurrence) contract:

    - ``flip``     XOR one PRF-chosen byte with a PRF-chosen nonzero
                   value (bit-level corruption a checksum-free wire
                   would pass through);
    - ``truncate`` cut the payload to a PRF-chosen strict prefix
                   (mid-message connection loss / short write);
    - ``replay``   substitute the rule's previously captured matching
                   payload (stale-message injection; the first match is
                   captured and passed through unmodified — under
                   concurrent senders "previous" follows arrival order,
                   so transcript-determinism drills run serial traffic).
    """
    if mode not in TAMPER_MODES:
        raise ValueError(f"tamper mode {mode!r}: expected one of "
                         f"{TAMPER_MODES}")
    return Rule(kind="tamper", p=p, topic=topic, node=node, channel=channel,
                direction=direction, mode=mode)


def partition(nodes: Sequence[str], duration_s: Optional[float] = None,
              start_s: float = 0.0) -> Rule:
    """Isolate ``nodes`` from everyone (drop all their traffic, both
    directions) during ``[start_s, start_s + duration_s)`` measured from
    :meth:`FaultPlan.activate`. ``duration_s=None`` holds until
    :meth:`FaultPlan.heal`."""
    return Rule(kind="partition", nodes=tuple(nodes), start_s=start_s,
                duration_s=duration_s, direction="*")


# -- the plan ----------------------------------------------------------------


def _msg_key(topic: str, data: bytes) -> bytes:
    return hashlib.sha256(topic.encode() + b"\x00" + data).digest()[:16]


class FaultPlan:
    """Seed + rules + the runtime occurrence state backing the PRF."""

    def __init__(self, seed: int, rules: Iterable[Rule] = ()):
        self.seed = int(seed)
        self.rules: List[Rule] = []
        for i, r in enumerate(rules):
            if not r.rule_id:
                r.rule_id = f"{r.kind}#{i}"
            self.rules.append(r)
        self._lock = threading.Lock()
        self._occ: Dict[Tuple[str, bytes], int] = {}
        self._epoch: Optional[float] = None
        self._healed = False
        self._fired: set = set()  # crash rules are one-shot events
        self._replay: Dict[str, bytes] = {}  # tamper(replay) capture cells

    # -- lifecycle ----------------------------------------------------------

    def activate(self, now: Optional[float] = None) -> "FaultPlan":
        """Anchor time-windowed rules (partition windows). Until this is
        called they are dormant — the drill runner arms them once the
        cluster is set up, so ``start_s`` is relative to the drill, not
        to transport construction. Probabilistic rules need no arming."""
        with self._lock:
            if self._epoch is None:
                self._epoch = time.monotonic() if now is None else now
        return self

    def heal(self) -> None:
        """End every partition immediately (drill 'partition heals')."""
        self._healed = True

    @property
    def empty(self) -> bool:
        return not self.rules

    # -- deterministic PRF --------------------------------------------------

    def _u(self, rule: Rule, key: bytes, occ: int, lane: str = "") -> float:
        h = hashlib.sha256(
            b"%d|%s|%d|%s|" % (self.seed, rule.rule_id.encode(), occ,
                               lane.encode()) + key
        ).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64

    def roll(self, rule: Rule, ev: MsgEvent) -> Tuple[float, bytes, int]:
        """One judgement of ``ev`` by ``rule``: returns (uniform draw,
        message key, occurrence). Bumps the occurrence counter so a
        retransmission re-rolls independently."""
        key = _msg_key(ev.topic, ev.data)
        with self._lock:
            occ = self._occ.get((rule.rule_id, key), 0)
            self._occ[(rule.rule_id, key)] = occ + 1
        return self._u(rule, key, occ), key, occ

    def delay_ms(self, rule: Rule, key: bytes, occ: int) -> float:
        lo, hi = rule.ms
        return lo + self._u(rule, key, occ, lane="delay") * (hi - lo)

    def tamper_bytes(self, rule: Rule, key: bytes, occ: int, data: bytes,
                     triggered: bool = True) -> bytes:
        """The corrupted payload for one tamper judgement. Pure in
        (seed, rule, key, occ, data) for flip/truncate; replay reads and
        refreshes the rule's capture cell (every MATCH captures, so the
        substituted bytes are the previously observed matching payload).
        With ``triggered=False`` only the replay capture side effect
        runs and ``data`` passes through unchanged."""
        if rule.mode == "replay":
            with self._lock:
                prev = self._replay.get(rule.rule_id)
                self._replay[rule.rule_id] = data
            return prev if (triggered and prev is not None) else data
        if not triggered or not data:
            return data
        if rule.mode == "truncate":
            cut = int(self._u(rule, key, occ, lane="cut") * len(data))
            return data[:min(cut, len(data) - 1)]
        # flip (the default): one byte, nonzero mask — always corrupts
        idx = int(self._u(rule, key, occ, lane="idx") * len(data)) % len(data)
        mask = 1 + int(self._u(rule, key, occ, lane="val") * 255)
        return data[:idx] + bytes([data[idx] ^ mask]) + data[idx + 1:]

    # -- queries the transport asks ----------------------------------------

    def matching(self, ev: MsgEvent, kinds: Tuple[str, ...]) -> List[Rule]:
        return [r for r in self.rules
                if r.kind in kinds and r.matches(ev)]

    def isolated(self, node_id: str, now: Optional[float] = None) -> Optional[Rule]:
        """The partition rule currently isolating ``node_id``, if any."""
        if self._healed:
            return None
        with self._lock:
            epoch = self._epoch
        if epoch is None:
            return None  # windows dormant until activate()
        for r in self.rules:
            if r.kind != "partition" or node_id not in r.nodes:
                continue
            t = (time.monotonic() if now is None else now) - epoch
            if t < r.start_s:
                continue
            if r.duration_s is not None and t >= r.start_s + r.duration_s:
                continue
            return r
        return None

    def crash_rules(self, node_id: str) -> List[Rule]:
        """Unfired crash rules for ``node_id`` — each is a one-shot
        event (a restarted node must not deterministically re-die on its
        next message; mark_fired() retires the rule)."""
        with self._lock:
            return [r for r in self.rules
                    if r.kind == "crash_node"
                    and r.rule_id not in self._fired
                    and glob_match(r.node, node_id)]

    def mark_fired(self, rule: Rule) -> None:
        with self._lock:
            self._fired.add(rule.rule_id)

    # -- (de)serialization --------------------------------------------------

    def to_json(self) -> dict:
        return {"seed": self.seed, "rules": [r.to_json() for r in self.rules]}

    @classmethod
    def from_json(cls, d) -> "FaultPlan":
        if isinstance(d, (str, bytes)):
            d = json.loads(d)
        return cls(d["seed"], [Rule.from_json(r) for r in d.get("rules", [])])

    def describe(self) -> str:
        return "; ".join(
            f"{r.rule_id}(p={r.p},topic={r.topic},node={r.node})"
            for r in self.rules
        ) or "(empty)"


# -- named plans (the drill catalog's building blocks) -----------------------

# protocol traffic topic globs (wire.py topic composers)
PROTOCOL_TOPICS = ("keygen:*", "sign:*", "resharing:*")
# batched-session traffic (batch_scheduler.py session ids): batched signing,
# batched DKG, batched resharing — NOT covered by PROTOCOL_TOPICS, which
# predate the batch scheduler. The load-soak plan targets these.
BATCH_TOPICS = ("bsign:*", "bdkg:*", "brs:*")


def _protocol_rules(seed: int, p_drop: float, jitter: Tuple[float, float]):
    rules: List[Rule] = []
    for t in PROTOCOL_TOPICS:
        # losses hit the acked-unicast channel where a retry budget
        # exists; jitter hits every protocol message
        rules.append(drop(p=p_drop, topic=t, channel="direct"))
        rules.append(delay(ms=jitter, topic=t))
    return rules


def named_plan(name: str, seed: int,
               scale: float = 1.0) -> FaultPlan:
    """The drill catalog's plans. ``scale`` shrinks time constants for
    fast deterministic test-tier runs (delays and windows multiply by
    it); probabilities and structure never change with scale."""
    if name == "drop-jitter":
        return FaultPlan(seed, _protocol_rules(
            seed, p_drop=0.1, jitter=(50.0 * scale, 200.0 * scale)))
    if name == "node-crash":
        # node2 dies right after announcing itself in the first signing
        # round it participates in; the committee must finish without it
        return FaultPlan(seed, [crash_node("node2", topic="sign:*")])
    if name == "broker-failover":
        # no message-level rules: the fault is the primary broker dying
        # mid-run (the drill kills it); the plan records the intent
        return FaultPlan(seed, [])
    if name == "partition":
        # isolate two of three nodes — over threshold, no quorum can form
        return FaultPlan(seed, [partition(("node1", "node2"))])
    if name == "duplicate-reorder":
        rules: List[Rule] = []
        for t in PROTOCOL_TOPICS:
            rules.append(duplicate(p=0.2, topic=t, channel="queue"))
            rules.append(reorder(p=0.3, topic=t, channel="pubsub",
                                 window_ms=50.0 * scale))
        return FaultPlan(seed, rules)
    if name == "cheater":
        # one active deviation inside the OT-MtA rounds of a batched
        # signing cohort (ISSUE 16). The OT wire rounds never cross the
        # transport in the in-process engine, so the drill injects the
        # corruption protocol-level (mta_ot.OTMtALeg.set_tamper) with
        # lane/field/byte all PRF-derived from THIS plan's seed — the
        # rule records the intent and keys the derivation.
        return FaultPlan(seed, [tamper(p=1.0, topic="bsign:*", mode="flip")])
    if name == "batch-chaos":
        # the load-soak plan: jitter on every batched-session round plus
        # acked-unicast losses (the sender's retry budget absorbs them —
        # latency degrades, correctness must not), and jitter on the
        # manifest fan-out so window/fallback timing is exercised. Result
        # topics are left clean: the soak's accounting needs every
        # submitted request to produce SOME terminal event.
        rules = []
        for t in BATCH_TOPICS:
            rules.append(drop(p=0.05, topic=t, channel="direct"))
            rules.append(delay(ms=(5.0 * scale, 60.0 * scale), topic=t))
        rules.append(delay(ms=(5.0 * scale, 40.0 * scale),
                           topic="mpc:batch_manifest"))
        return FaultPlan(seed, rules)
    raise KeyError(f"unknown named plan {name!r}")
