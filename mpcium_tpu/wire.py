"""Wire types: signed envelopes and initiator commands.

JSON schemas mirror the reference's `pkg/types` (tss.go:13-24,
initiator_msg.go) so that results/events are byte-compatible where the
survey pins them (§7.1 item 4). Canonical signing bytes follow the
reference's MarshalForSigning contract (types/tss.go:149-163): a sorted-key
JSON object of the protocol-relevant fields — signatures must not cover
themselves.
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, Optional

KEY_TYPE_SECP256K1 = "secp256k1"
KEY_TYPE_ED25519 = "ed25519"

# deadline lanes (SLO-aware continuous batching). ``priority`` selects the
# dispatch lane; ``deadline_ms`` is the client's end-to-end latency budget
# (0 ⇒ take the server-side config default). Both are omitted from signing
# bytes and JSON when default so legacy messages stay byte-identical.
PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BULK = "bulk"
PRIORITIES = (PRIORITY_INTERACTIVE, PRIORITY_BULK)


def canonical_json(obj: Any) -> bytes:
    """Deterministic JSON: sorted keys, no whitespace, UTF-8."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


# ---------------------------------------------------------------------------
# protocol round envelope (the TssMessage analogue)
# ---------------------------------------------------------------------------


@dataclass
class Envelope:
    """Signed protocol-round message (reference types.TssMessage).

    ``session_id`` doubles as the wallet/tx scope; ``payload`` carries the
    protocol round content (JSON-safe; batched rounds use base64 byte
    tensors). ``to`` empty ⇒ broadcast.
    """

    session_id: str
    round: str
    from_id: str
    payload: Dict[str, Any]
    to: Optional[str] = None
    is_broadcast: bool = True
    signature: bytes = b""
    # wire schema version. 0 is the v0 shape and is omitted from JSON (and
    # never covered by signing bytes), so legacy signed envelopes stay
    # byte-identical; bump only with a parser that handles both.
    v: int = 0
    # mpctrace context ({"t": trace_id, "s": span_id}): observability
    # metadata, same omit-while-default contract as ``v`` — absent from
    # JSON when None and NEVER covered by signing bytes, so legacy peers
    # ignore it and traced envelopes verify against untraced signatures.
    # Unauthenticated by design; must never feed a protocol decision.
    trace: Optional[Dict[str, str]] = None

    def marshal_for_signing(self) -> bytes:
        return canonical_json(
            {
                "session_id": self.session_id,
                "round": self.round,
                "from": self.from_id,
                "to": self.to or "",
                "is_broadcast": self.is_broadcast,
                "payload": self.payload,
            }
        )

    def to_json(self) -> Dict[str, Any]:
        out = {
            "session_id": self.session_id,
            "round": self.round,
            "from": self.from_id,
            "to": self.to,
            "is_broadcast": self.is_broadcast,
            "payload": self.payload,
            "signature": self.signature.hex(),
        }
        if self.v:
            out["v"] = self.v
        if self.trace:
            out["trace"] = self.trace
        return out

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "Envelope":
        return cls(
            session_id=d["session_id"],
            round=d["round"],
            from_id=d["from"],
            payload=d["payload"],
            to=d.get("to"),
            is_broadcast=d.get("is_broadcast", True),
            signature=bytes.fromhex(d.get("signature", "")),
            v=int(d.get("v", 0)),
            trace=d.get("trace"),
        )

    def encode(self) -> bytes:
        return canonical_json(self.to_json())

    @classmethod
    def decode(cls, raw: bytes) -> "Envelope":
        return cls.from_json(json.loads(raw))


# ---------------------------------------------------------------------------
# initiator commands (client → nodes)
# ---------------------------------------------------------------------------


@dataclass
class GenerateKeyMessage:
    """reference types.GenerateKeyMessage: raw = wallet id bytes."""

    wallet_id: str
    signature: bytes = b""
    v: int = 0

    def raw(self) -> bytes:
        return self.wallet_id.encode()

    def to_json(self) -> Dict[str, Any]:
        out = {"wallet_id": self.wallet_id, "signature": self.signature.hex()}
        if self.v:
            out["v"] = self.v
        return out

    @classmethod
    def from_json(cls, d) -> "GenerateKeyMessage":
        return cls(
            wallet_id=d["wallet_id"],
            signature=bytes.fromhex(d.get("signature", "")),
            v=int(d.get("v", 0)),
        )


@dataclass
class SignTxMessage:
    """reference types.SignTxMessage (initiator_msg.go:27-34): raw = JSON
    minus signature (sorted keys)."""

    key_type: str
    wallet_id: str
    network_internal_code: str
    tx_id: str
    tx: bytes

    signature: bytes = b""
    # SLO hints: 0/bulk are the wire defaults and are omitted from signing
    # bytes + JSON, so legacy signed messages keep their exact byte shape.
    deadline_ms: int = 0
    priority: str = PRIORITY_BULK
    # schema version, same omit-while-0 contract as the SLO fields
    v: int = 0

    def _slo_fields(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.deadline_ms:
            out["deadline_ms"] = self.deadline_ms
        if self.priority != PRIORITY_BULK:
            out["priority"] = self.priority
        return out

    def raw(self) -> bytes:
        body = {
            "key_type": self.key_type,
            "wallet_id": self.wallet_id,
            "network_internal_code": self.network_internal_code,
            "tx_id": self.tx_id,
            "tx": self.tx.hex(),
        }
        body.update(self._slo_fields())
        return canonical_json(body)

    def to_json(self) -> Dict[str, Any]:
        out = {
            "key_type": self.key_type,
            "wallet_id": self.wallet_id,
            "network_internal_code": self.network_internal_code,
            "tx_id": self.tx_id,
            "tx": self.tx.hex(),
            "signature": self.signature.hex(),
        }
        out.update(self._slo_fields())
        if self.v:
            out["v"] = self.v
        return out

    @classmethod
    def from_json(cls, d) -> "SignTxMessage":
        return cls(
            key_type=d["key_type"],
            wallet_id=d["wallet_id"],
            network_internal_code=d["network_internal_code"],
            tx_id=d["tx_id"],
            tx=bytes.fromhex(d["tx"]),
            signature=bytes.fromhex(d.get("signature", "")),
            deadline_ms=int(d.get("deadline_ms", 0)),
            priority=d.get("priority", PRIORITY_BULK),
            v=int(d.get("v", 0)),
        )


@dataclass
class ResharingMessage:
    """reference types.ResharingMessage (initiator_msg.go:36-59)."""

    wallet_id: str
    new_threshold: int
    key_type: str
    signature: bytes = b""
    deadline_ms: int = 0
    priority: str = PRIORITY_BULK
    v: int = 0

    def _slo_fields(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.deadline_ms:
            out["deadline_ms"] = self.deadline_ms
        if self.priority != PRIORITY_BULK:
            out["priority"] = self.priority
        return out

    def raw(self) -> bytes:
        body = {
            "wallet_id": self.wallet_id,
            "new_threshold": self.new_threshold,
            "key_type": self.key_type,
        }
        body.update(self._slo_fields())
        return canonical_json(body)

    def to_json(self) -> Dict[str, Any]:
        out = {
            "wallet_id": self.wallet_id,
            "new_threshold": self.new_threshold,
            "key_type": self.key_type,
            "signature": self.signature.hex(),
        }
        out.update(self._slo_fields())
        if self.v:
            out["v"] = self.v
        return out

    @classmethod
    def from_json(cls, d) -> "ResharingMessage":
        return cls(
            wallet_id=d["wallet_id"],
            new_threshold=int(d["new_threshold"]),
            key_type=d["key_type"],
            signature=bytes.fromhex(d.get("signature", "")),
            deadline_ms=int(d.get("deadline_ms", 0)),
            priority=d.get("priority", PRIORITY_BULK),
            v=int(d.get("v", 0)),
        )


# ---------------------------------------------------------------------------
# result events (nodes → client), byte-compatible with event/sign.go:21-34
# ---------------------------------------------------------------------------

RESULT_SUCCESS = "success"
RESULT_ERROR = "error"


@dataclass
class KeygenSuccessEvent:
    """reference mpc.KeygenSuccessEvent: one wallet, both curve pubkeys.

    The success shape is byte-compatible with the reference; failures add
    result_type/error_reason (the reference publishes NOTHING on keygen
    failure and clients wait forever — a wart not worth reproducing)."""

    wallet_id: str
    ecdsa_pub_key: str  # hex (SEC1 compressed; reference emits raw X||Y)
    eddsa_pub_key: str  # hex (compressed Edwards)
    result_type: str = RESULT_SUCCESS
    error_reason: str = ""
    retryable: bool = False
    v: int = 0

    def to_json(self) -> Dict[str, Any]:
        out = {
            "wallet_id": self.wallet_id,
            "ecdsa_pub_key": self.ecdsa_pub_key,
            "eddsa_pub_key": self.eddsa_pub_key,
        }
        if self.result_type != RESULT_SUCCESS:
            out["result_type"] = self.result_type
            out["error_reason"] = self.error_reason
            if self.retryable:
                out["retryable"] = True
        if self.v:
            out["v"] = self.v
        return out

    @classmethod
    def from_json(cls, d) -> "KeygenSuccessEvent":
        return cls(
            wallet_id=d["wallet_id"],
            ecdsa_pub_key=d.get("ecdsa_pub_key", ""),
            eddsa_pub_key=d.get("eddsa_pub_key", ""),
            result_type=d.get("result_type", RESULT_SUCCESS),
            error_reason=d.get("error_reason", ""),
            retryable=bool(d.get("retryable", False)),
            v=int(d.get("v", 0)),
        )


@dataclass
class SigningResultEvent:
    """reference event.SigningResultEvent (event/sign.go:21-34)."""

    result_type: str  # success | error
    wallet_id: str
    tx_id: str
    network_internal_code: str = ""
    error_reason: str = ""
    is_timeout: bool = False
    r: str = ""  # hex, ECDSA
    s: str = ""  # hex, ECDSA
    signature_recovery: str = ""  # hex byte, ECDSA
    signature: str = ""  # hex, EdDSA (64-byte R||s)
    # honest shedding: True ⇒ the request was refused before protocol work
    # (backpressure, deadline expiry) and a verbatim retry is safe. Omitted
    # from JSON when False so the reference-pinned success shape is unchanged.
    retryable: bool = False
    v: int = 0

    def to_json(self) -> Dict[str, Any]:
        out = {
            "result_type": self.result_type,
            "error_reason": self.error_reason,
            "is_timeout": self.is_timeout,
            "network_internal_code": self.network_internal_code,
            "wallet_id": self.wallet_id,
            "tx_id": self.tx_id,
            "r": self.r,
            "s": self.s,
            "signature_recovery": self.signature_recovery,
            "signature": self.signature,
        }
        if self.retryable:
            out["retryable"] = True
        if self.v:
            out["v"] = self.v
        return out

    @classmethod
    def from_json(cls, d) -> "SigningResultEvent":
        return cls(
            result_type=d["result_type"],
            wallet_id=d["wallet_id"],
            tx_id=d["tx_id"],
            network_internal_code=d.get("network_internal_code", ""),
            error_reason=d.get("error_reason", ""),
            is_timeout=bool(d.get("is_timeout", False)),
            r=d.get("r", ""),
            s=d.get("s", ""),
            signature_recovery=d.get("signature_recovery", ""),
            signature=d.get("signature", ""),
            retryable=bool(d.get("retryable", False)),
            v=int(d.get("v", 0)),
        )


@dataclass
class ResharingSuccessEvent:
    """reference mpc.ResharingSuccessEvent (ecdsa_resharing_session.go:40-44),
    plus an error shape (result_type/error_reason) for terminal failures."""

    wallet_id: str
    new_threshold: int
    key_type: str
    pub_key: str  # hex
    result_type: str = RESULT_SUCCESS
    error_reason: str = ""
    retryable: bool = False
    v: int = 0

    def to_json(self) -> Dict[str, Any]:
        out = {
            "wallet_id": self.wallet_id,
            "new_threshold": self.new_threshold,
            "key_type": self.key_type,
            "pub_key": self.pub_key,
        }
        if self.result_type != RESULT_SUCCESS:
            out["result_type"] = self.result_type
            out["error_reason"] = self.error_reason
            if self.retryable:
                out["retryable"] = True
        if self.v:
            out["v"] = self.v
        return out

    @classmethod
    def from_json(cls, d) -> "ResharingSuccessEvent":
        return cls(
            wallet_id=d["wallet_id"],
            new_threshold=int(d["new_threshold"]),
            key_type=d["key_type"],
            pub_key=d.get("pub_key", ""),
            result_type=d.get("result_type", RESULT_SUCCESS),
            error_reason=d.get("error_reason", ""),
            retryable=bool(d.get("retryable", False)),
            v=int(d.get("v", 0)),
        )


# ---------------------------------------------------------------------------
# topics (reference event_consumer.go:24-27, event/sign.go:3-11,
# pkg/mpc/session.go:40-43)
# ---------------------------------------------------------------------------

TOPIC_GENERATE = "mpc:generate"
TOPIC_SIGN = "mpc:sign"
TOPIC_RESHARE = "mpc:reshare"
TOPIC_SIGNING_REQUEST = "mpc.signing_request.event"
TOPIC_KEYGEN_RESULT = "mpc.mpc_keygen_success"
TOPIC_SIGNING_RESULT = "mpc.signing_result.complete"
TOPIC_RESHARING_RESULT = "mpc.mpc_resharing_success"
# batched-signing manifest fan-out (TPU batch scheduler; no reference
# analogue - the reference runs one goroutine per session)
TOPIC_BATCH_MANIFEST = "mpc:batch_manifest"


def keygen_broadcast_topic(key_type: str, wallet_id: str) -> str:
    return f"keygen:broadcast:{_kt(key_type)}:{wallet_id}"


def keygen_direct_topic(key_type: str, node_id: str, wallet_id: str) -> str:
    return f"keygen:direct:{_kt(key_type)}:{node_id}:{wallet_id}"


def sign_broadcast_topic(key_type: str, wallet_id: str, tx_id: str) -> str:
    return f"sign:{_kt(key_type)}:broadcast:{wallet_id}:{tx_id}"


def sign_direct_topic(key_type: str, node_id: str, tx_id: str) -> str:
    return f"sign:{_kt(key_type)}:direct:{node_id}:{tx_id}"


def resharing_broadcast_topic(key_type: str, wallet_id: str) -> str:
    return f"resharing:broadcast:{_kt(key_type)}:{wallet_id}"


def resharing_direct_topic(key_type: str, node_id: str, wallet_id: str) -> str:
    return f"resharing:direct:{_kt(key_type)}:{node_id}:{wallet_id}"


def _kt(key_type: str) -> str:
    """Reference uses 'ecdsa'/'eddsa' in topic segments."""
    return {"secp256k1": "ecdsa", "ed25519": "eddsa"}.get(key_type, key_type)
