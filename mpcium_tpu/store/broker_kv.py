"""Network-reachable control-plane KV served by the message broker.

The reference's control plane (registry liveness, keyinfo, peers) is
Consul over HTTP(S)+ACL, reachable from separate machines
(/root/reference/pkg/infra/consul.go:19-47, cmd/mpcium/main.go:302-311).
The FileKV equivalent only spans hosts via a shared volume — unusable
for MPC's actual deployment model of mutually-distrusting operators on
separate machines. Here the broker — already the cluster's network
rendezvous, with token auth, an AEAD channel, journal durability and
hot-standby replication — serves the same KV surface over its socket
(transport/tcp.py kvput/kvget/kvdel/kvkeys ops).

Durable keys (keyinfo, peers) are fsync-journaled on the broker and
replicated to standbys; liveness heartbeats use :meth:`put_transient`
(neither journaled nor replicated — after a failover each node's 1 Hz
heartbeat loop repopulates them within a poll period).

Select with ``control_plane: broker`` in config.yaml; nodes then share
ONLY broker addresses — no common filesystem.
"""
from __future__ import annotations

from typing import List, Optional

from .kvstore import KVStore


class BrokerKV(KVStore):
    def __init__(self, client, timeout_s: float = 10.0):
        self._cli = client  # transport.tcp.TcpClient
        self._timeout_s = timeout_s

    def put(self, key: str, value: bytes) -> None:
        self._cli.kv_request(
            {"op": "kvput", "k": key, "v": value.hex()}, self._timeout_s
        )

    def put_transient(self, key: str, value: bytes) -> None:
        """Best-effort, non-durable put (liveness heartbeats): not
        journaled, not replicated to standbys."""
        self._cli.kv_request(
            {"op": "kvput", "k": key, "v": value.hex(), "t": 1},
            self._timeout_s,
        )

    def get(self, key: str) -> Optional[bytes]:
        r = self._cli.kv_request(
            {"op": "kvget", "k": key}, self._timeout_s
        )
        v = r.get("v")
        return None if v is None else bytes.fromhex(v)

    def delete(self, key: str) -> None:
        self._cli.kv_request(
            {"op": "kvdel", "k": key}, self._timeout_s
        )

    def keys(self, prefix: str = "") -> List[str]:
        r = self._cli.kv_request(
            {"op": "kvkeys", "p": prefix}, self._timeout_s
        )
        return list(r.get("keys") or [])

    def scan(self, prefix: str = "") -> dict:
        """Prefix scan in ONE round-trip: {key: value-bytes}. The
        registry's 1 Hz liveness poll uses this instead of keys() +
        per-key get() (O(N) network RTTs per poll otherwise)."""
        r = self._cli.kv_request(
            {"op": "kvscan", "p": prefix}, self._timeout_s
        )
        return {
            k: bytes.fromhex(v) for k, v in (r.get("items") or {}).items()
        }
