"""Wallet metadata store (the Consul keyinfo analogue, pkg/keyinfo).

`KeyInfo{participant_peer_ids, threshold, is_reshared}` at
``threshold_keyinfo/<ecdsa|eddsa>:<walletID>`` (keyinfo.go:11-15,67-68),
extended with the public key + aggregated VSS commitments so that NEW
resharing committee members can verify the redeal binding without holding
an old share (protocol/resharing.py needs old_vss_commitments)."""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

from .kvstore import KVStore

PREFIX = "threshold_keyinfo/"


@dataclass
class KeyInfo:
    participant_peer_ids: List[str]
    threshold: int
    is_reshared: bool = False
    public_key: str = ""  # hex compressed
    vss_commitments: List[str] = field(default_factory=list)  # hex
    # resharing generation (see protocol.base.KeygenShare.epoch): signing is
    # fenced on keyinfo.epoch == share.epoch
    epoch: int = 0

    def to_json(self) -> dict:
        return {
            "participant_peer_ids": self.participant_peer_ids,
            "threshold": self.threshold,
            "is_reshared": self.is_reshared,
            "public_key": self.public_key,
            "vss_commitments": self.vss_commitments,
            "epoch": self.epoch,
        }

    @classmethod
    def from_json(cls, d: dict) -> "KeyInfo":
        return cls(
            participant_peer_ids=list(d["participant_peer_ids"]),
            threshold=int(d["threshold"]),
            is_reshared=bool(d.get("is_reshared", False)),
            public_key=d.get("public_key", ""),
            vss_commitments=list(d.get("vss_commitments", [])),
            epoch=int(d.get("epoch", 0)),
        )


class KeyinfoStore:
    """Reference keyinfo.Store (Get/Save, keyinfo.go:25-28)."""

    def __init__(self, kv: KVStore):
        self.kv = kv

    @staticmethod
    def _key(key_type: str, wallet_id: str) -> str:
        kt = {"secp256k1": "ecdsa", "ed25519": "eddsa"}.get(key_type, key_type)
        return f"{PREFIX}{kt}:{wallet_id}"

    def save(self, key_type: str, wallet_id: str, info: KeyInfo) -> None:
        self.kv.put(self._key(key_type, wallet_id), json.dumps(info.to_json()).encode())

    def get(self, key_type: str, wallet_id: str) -> Optional[KeyInfo]:
        raw = self.kv.get(self._key(key_type, wallet_id))
        return KeyInfo.from_json(json.loads(raw)) if raw else None
