"""Key/value stores: encrypted share store + plain control-plane KV.

Reference equivalents:
- encrypted Badger for key shares (pkg/kvstore/badger.go — encryption key
  MANDATORY, badger.go:21-24): here an AEAD-encrypted file-backed store
  (ChaCha20-Poly1305 per value, scrypt-derived master key, atomic writes).
- Consul KV for control plane (pkg/infra/consul.go `ConsulKV` iface:
  Put/Get/Delete/List): here :class:`MemoryKV` (in-process cluster fabric)
  and :class:`FileKV` (multi-process on shared disk).
"""
from __future__ import annotations

import abc
import hashlib
import json
import os
import secrets
import threading
from pathlib import Path
from typing import Dict, List, Optional

try:
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
except ImportError:  # bare env: RFC-vector-validated pure-python fallback
    from ..core.softcrypto import ChaCha20Poly1305


class KVStore(abc.ABC):
    """Reference kvstore.KVStore (kvstore.go:4-16) + Keys iterator."""

    @abc.abstractmethod
    def put(self, key: str, value: bytes) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    def keys(self, prefix: str = "") -> List[str]: ...

    def close(self) -> None:
        pass


class EncryptedFileKV(KVStore):
    """Encrypted share store. The encryption key is mandatory (reference
    badger.go:21-24 errors out without one). One file per key under
    ``root``; values sealed with ChaCha20-Poly1305; key names are hashed to
    filenames so the directory listing leaks no wallet ids."""

    def __init__(self, root, password: str):
        if not password:
            raise ValueError("encryption password is required")  # badger.go:23
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        salt_path = self.root / ".salt"
        if salt_path.exists():
            salt = salt_path.read_bytes()
        else:
            salt = secrets.token_bytes(16)
            salt_path.write_bytes(salt)
        self._key = hashlib.scrypt(
            password.encode(), salt=salt, n=2**14, r=8, p=1,
            maxmem=64 * 1024 * 1024, dklen=32,
        )
        self._aead = ChaCha20Poly1305(self._key)
        self._lock = threading.RLock()
        # encrypted name index (filename-hash -> key), itself sealed
        self._index_path = self.root / ".index"
        self._index: Dict[str, str] = {}
        if self._index_path.exists():
            try:
                self._index = json.loads(
                    self._open(self._index_path.read_bytes(), b"index")
                )
            except Exception as e:  # noqa: BLE001 — fail fast at open
                raise ValueError(
                    "wrong encryption password or corrupted store"
                ) from e

    def _fname(self, key: str) -> Path:
        return self.root / self.hashed_name(key)

    # public sealing surface: the session WAL (store/session_wal.py) seals
    # its entries with this store's AEAD + key-derived filenames so WAL
    # files leak exactly as little as the share files next to them
    def hashed_name(self, key: str) -> str:
        return hashlib.sha256(self._key + key.encode()).hexdigest()[:48]

    def seal(self, data: bytes, ad: bytes) -> bytes:
        return self._seal(data, ad)

    def unseal(self, blob: bytes, ad: bytes) -> bytes:
        return self._open(blob, ad)

    def _seal(self, data: bytes, ad: bytes) -> bytes:
        nonce = secrets.token_bytes(12)
        return nonce + self._aead.encrypt(nonce, data, ad)

    def _open(self, blob: bytes, ad: bytes) -> bytes:
        return self._aead.decrypt(blob[:12], blob[12:], ad)

    def _save_index(self) -> None:
        tmp = str(self._index_path) + ".tmp"
        Path(tmp).write_bytes(
            self._seal(json.dumps(self._index).encode(), b"index")
        )
        os.replace(tmp, self._index_path)

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            p = self._fname(key)
            tmp = str(p) + ".tmp"
            Path(tmp).write_bytes(self._seal(value, key.encode()))
            os.replace(tmp, p)
            if self._index.get(p.name) != key:
                self._index[p.name] = key
                self._save_index()

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            p = self._fname(key)
            if not p.exists():
                return None
            return self._open(p.read_bytes(), key.encode())

    def delete(self, key: str) -> None:
        with self._lock:
            p = self._fname(key)
            if p.exists():
                p.unlink()
            if p.name in self._index:
                del self._index[p.name]
                self._save_index()

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(k for k in self._index.values() if k.startswith(prefix))


class MemoryKV(KVStore):
    """In-process control-plane KV (the Consul analogue for loopback
    clusters); shared by reference `ConsulKV` consumers (registry, keyinfo,
    peers)."""

    def __init__(self):
        self._d: Dict[str, bytes] = {}
        self._lock = threading.RLock()

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._d[key] = value

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._d.get(key)

    def delete(self, key: str) -> None:
        with self._lock:
            self._d.pop(key, None)

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(k for k in self._d if k.startswith(prefix))


class FileKV(KVStore):
    """Shared-disk control-plane KV for multi-process deployments (each key
    is a file; names are percent-encoded). Suitable for a docker-compose
    style dev stack on one host; production control planes plug in their
    own KVStore (etcd/Consul adapters)."""

    def __init__(self, root):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.RLock()

    @staticmethod
    def _enc(key: str) -> str:
        import urllib.parse

        return urllib.parse.quote(key, safe="")

    @staticmethod
    def _dec(name: str) -> str:
        import urllib.parse

        return urllib.parse.unquote(name)

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            p = self.root / self._enc(key)
            tmp = str(p) + ".tmp"
            Path(tmp).write_bytes(value)
            os.replace(tmp, p)

    def get(self, key: str) -> Optional[bytes]:
        p = self.root / self._enc(key)
        try:
            return p.read_bytes()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        with self._lock:
            p = self.root / self._enc(key)
            if p.exists():
                p.unlink()

    def keys(self, prefix: str = "") -> List[str]:
        return sorted(
            self._dec(p.name)
            for p in self.root.iterdir()
            if not p.name.endswith(".tmp") and self._dec(p.name).startswith(prefix)
        )
