"""Encrypted per-session write-ahead log — crash-recoverable sessions.

Every in-flight MPC session journals (a) each *verified* inbound envelope
and (b) a checkpoint of party state taken immediately before any outbound
round traffic is handed to the transport. After a SIGKILL the daemon
replays the WAL: the party is rebuilt from the last checkpoint, envelopes
that arrived after it are re-delivered, and the already-sent history is
re-routed so peers that missed nothing simply drop duplicates.

Disk format (append-only, one file per session under ``<store>/wal/``)::

    [4-byte BE length][sealed record] ...

Each record is canonical JSON sealed with the *share store's* AEAD
(ChaCha20-Poly1305, scrypt-derived key — see
:class:`~mpcium_tpu.store.kvstore.EncryptedFileKV`), so WAL files leak
exactly as little as the key-share files beside them. The associated data
binds every record to its session id and sequence number
(``wal:<session_id>:<seq>``), which makes records non-spliceable across
files and non-reorderable within one. Record 0 is the ``meta`` record,
sealed under a fixed AD (``wal:meta``) because it is what *tells* us the
session id at replay time; its payload carries the id that all later
records are bound to.

Record types::

    {"t": "meta", "session_id": ..., "meta": {...}}   # session factory args
    {"t": "env",  "raw": <hex>}                       # verified inbound envelope
    {"t": "ckpt", "snap": {...}, "sent": [...]}       # party state + step outputs
    {"t": "done"}                                     # session completed

Durability: each append is flushed and ``fsync``'d before the caller
proceeds (checkpoints are written *before* the corresponding messages are
routed — a crashed party must never re-derive fresh randomness for
payloads peers already saw). A torn or corrupted tail — short frame,
absurd length, failed AEAD open — is tolerated: replay stops at the last
intact record and :meth:`SessionWALStore.reopen` truncates the garbage, so
recovery falls back to the previous checkpoint instead of crashing.
"""
from __future__ import annotations

import json
import os
import struct
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

from .kvstore import EncryptedFileKV

_LEN = struct.Struct(">I")
_META_AD = b"wal:meta"
# sanity bound on a single sealed record; anything larger is a torn/garbage
# length prefix, not a real record (checkpoints are a few hundred KB at most)
_MAX_RECORD = 64 * 1024 * 1024


def _ad(session_id: str, seq: int) -> bytes:
    return f"wal:{session_id}:{seq}".encode()


@dataclass
class WALReplay:
    """Result of replaying one WAL file up to its last intact record."""

    path: Path
    session_id: str = ""
    meta: Dict[str, Any] = field(default_factory=dict)
    snapshot: Optional[Dict[str, Any]] = None
    #: full sent history (concatenation of every checkpoint's step outputs)
    sent: List[Dict[str, Any]] = field(default_factory=list)
    #: raw verified envelopes received *after* the last checkpoint
    envelopes: List[bytes] = field(default_factory=list)
    done: bool = False
    records: int = 0
    valid_bytes: int = 0
    torn: bool = False


class SessionWALWriter:
    """Append handle for one session's WAL. Thread-safe; every append is
    fsync'd before returning (unless the store was built with
    ``fsync=False``, which only tests use)."""

    def __init__(
        self,
        store: EncryptedFileKV,
        path: Path,
        session_id: str,
        seq: int = 0,
        fsync: bool = True,
    ):
        self._store = store
        self.path = path
        self.session_id = session_id
        self._seq = seq
        self._fsync = fsync
        self._lock = threading.Lock()
        self._f = open(path, "ab")

    def _append(self, rec: Dict[str, Any]) -> None:
        data = json.dumps(rec, separators=(",", ":"), sort_keys=True).encode()
        with self._lock:
            if self._f is None:
                return  # closed/dropped: session outlived its WAL, ignore
            ad = _META_AD if self._seq == 0 else _ad(self.session_id, self._seq)
            sealed = self._store.seal(data, ad)
            self._f.write(_LEN.pack(len(sealed)) + sealed)
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
            self._seq += 1

    def meta(self, meta: Dict[str, Any]) -> None:
        """Record 0: everything the node needs to rebuild the session
        object (protocol kind, participants, message bytes, ...)."""
        self._append({"t": "meta", "session_id": self.session_id, "meta": meta})

    def envelope(self, raw: bytes) -> None:
        """A verified inbound envelope, journaled before delivery."""
        self._append({"t": "env", "raw": raw.hex()})

    def checkpoint(self, snap: Dict[str, Any], sent: List[Dict[str, Any]]) -> None:
        """Party state plus the outputs of this step — written *before* the
        outputs are routed, so replay reuses the exact payloads peers saw."""
        self._append({"t": "ckpt", "snap": snap, "sent": sent})

    def done(self) -> None:
        self._append({"t": "done"})

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def drop(self) -> None:
        """Close and delete — the session completed (or terminally failed)."""
        self.close()
        try:
            self.path.unlink()
        except FileNotFoundError:
            pass


class SessionWALStore:
    """Per-node WAL namespace under the encrypted share store's root.

    Filenames are key-derived hashes (like the share files), so a directory
    listing leaks neither wallet ids nor session counts' meanings.
    """

    def __init__(self, store: EncryptedFileKV, fsync: bool = True):
        self.store = store
        self.dir = store.root / "wal"
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fsync = fsync

    def _path(self, session_id: str) -> Path:
        return self.dir / (self.store.hashed_name("wal:" + session_id) + ".wal")

    # -- writing ------------------------------------------------------------

    def create(self, session_id: str, meta: Dict[str, Any]) -> SessionWALWriter:
        """Fresh WAL for a new session (any stale file for the same id —
        e.g. an earlier failed run — is discarded first)."""
        path = self._path(session_id)
        if path.exists():
            path.unlink()
        w = SessionWALWriter(self.store, path, session_id, fsync=self.fsync)
        w.meta(meta)
        return w

    def reopen(self, replay: WALReplay) -> SessionWALWriter:
        """Continue appending after the last intact record of a replayed
        file; a torn tail is truncated away here."""
        if replay.torn or replay.path.stat().st_size != replay.valid_bytes:
            with open(replay.path, "r+b") as f:
                f.truncate(replay.valid_bytes)
        return SessionWALWriter(
            self.store,
            replay.path,
            replay.session_id,
            seq=replay.records,
            fsync=self.fsync,
        )

    def drop(self, session_id: str) -> None:
        try:
            self._path(session_id).unlink()
        except FileNotFoundError:
            pass

    # -- replay -------------------------------------------------------------

    def replay(self, path: Path) -> Optional[WALReplay]:
        """Replay one file up to the last intact record. Returns ``None``
        when not even the meta record survives (nothing to resume)."""
        try:
            blob = path.read_bytes()
        except OSError:
            return None
        rep = WALReplay(path=path)
        off = 0
        while True:
            if off + _LEN.size > len(blob):
                rep.torn = rep.torn or off != len(blob)
                break
            (ln,) = _LEN.unpack_from(blob, off)
            if ln == 0 or ln > _MAX_RECORD or off + _LEN.size + ln > len(blob):
                rep.torn = True
                break
            sealed = blob[off + _LEN.size : off + _LEN.size + ln]
            ad = _META_AD if rep.records == 0 else _ad(rep.session_id, rep.records)
            try:
                rec = json.loads(self.store.unseal(sealed, ad))
                if rep.records == 0:
                    if rec.get("t") != "meta":
                        raise ValueError("first record is not meta")
                    rep.session_id = rec["session_id"]
                    rep.meta = rec.get("meta", {})
                elif rec["t"] == "env":
                    rep.envelopes.append(bytes.fromhex(rec["raw"]))
                elif rec["t"] == "ckpt":
                    rep.snapshot = rec["snap"]
                    rep.sent.extend(rec.get("sent", []))
                    # pre-checkpoint envelopes live inside the snapshot's
                    # inbox already; only post-checkpoint ones need redelivery
                    rep.envelopes.clear()
                elif rec["t"] == "done":
                    rep.done = True
            except Exception:  # noqa: BLE001 — torn/corrupt tail, stop here
                rep.torn = True
                break
            rep.records += 1
            off += _LEN.size + ln
            rep.valid_bytes = off
        if rep.records == 0:
            return None
        return rep

    def incomplete(self) -> List[WALReplay]:
        """All sessions with a readable meta record and no ``done`` marker —
        the resume set scanned at daemon boot."""
        out: List[WALReplay] = []
        for p in sorted(self.dir.glob("*.wal")):
            rep = self.replay(p)
            if rep is not None and not rep.done:
                out.append(rep)
        return out
