"""The secret taxonomy — which identifiers mpclint treats as secrets.

Two sources:

1. **Name conventions** (this module): an identifier is secret when any
   of its snake_case tokens is a secret token (``share``, ``seed``,
   ``pad``, ``nonce``, ``sk``, ``secret``, ``passphrase``, ``password``,
   ``otk``, ``priv``) or it ends in ``_key``/``_keys`` — unless a
   *public* token exempts it (``pub_key``, ``public_key``, ``wallet_id``,
   ``hashed_name`` are data, not secrets).
2. **Annotations** (per file): ``# mpclint: secret`` on a definition line
   declares the assigned name(s) secret regardless of spelling::

       blob = derive()  # mpclint: secret

The secret-hygiene rules (MPL1xx) consult :func:`is_secret_name` with
the file's annotation set merged in. SECURITY.md's secret-handling
section lists what these names actually protect: Shamir key shares, WAL
AEAD keys, OT pads and choice bits, signing nonces, identity private
keys, broker tokens.
"""
from __future__ import annotations

import re
from typing import Iterable, Set

# tokens that make an identifier secret on their own
SECRET_TOKENS: Set[str] = {
    "sk",
    "share",
    "shares",
    "subshare",
    "subshares",
    "seed",
    "seeds",
    "pad",
    "pads",
    "nonce",
    "nonces",
    "secret",
    "secrets",
    "passphrase",
    "password",
    "otk",
    "priv",
    "privkey",
    "token",
}
# identifiers ending in _key / _keys are AEAD/derived keys ⇒ secret
_KEY_SUFFIX_RE = re.compile(r".*_keys?$")
# tokens that mark an identifier as public/non-secret even when a secret
# token also matches ("pub_key", "public_key_share", "wallet_share_count")
PUBLIC_TOKENS: Set[str] = {
    "pub",
    "public",
    "pubkey",
    "wallet",
    "tx",
    "topic",
    "session",
    "batch",
    "id",
    "ids",
    "name",
    "names",
    "count",
    "hashed",
    "len",
    "path",
    "verify",
}
# exact names that look secret by token but are known-module/known-public
_EXEMPT_EXACT: Set[str] = {
    "secrets",  # the stdlib entropy module, not a value
    "_secrets",
    "token_bytes",  # secrets.token_bytes attribute chains
    "token_hex",
    "token_matches",
    "hash_token",
}

_TOKEN_SPLIT_RE = re.compile(r"[^a-zA-Z0-9]+")


def tokens(name: str) -> Set[str]:
    """snake_case/camelCase-insensitive token set of an identifier."""
    name = name.strip("_")
    # split snake_case, then lower (camelCase is rare in this codebase)
    return {t.lower() for t in _TOKEN_SPLIT_RE.split(name) if t}


def is_secret_name(name: str, extra: Iterable[str] = ()) -> bool:
    """True when ``name`` denotes secret material under the taxonomy or
    the per-file ``# mpclint: secret`` annotation set ``extra``."""
    if not name:
        return False
    if name in extra:
        return True
    if name in _EXEMPT_EXACT:
        return False
    toks = tokens(name)
    if toks & PUBLIC_TOKENS:
        return False
    if toks & SECRET_TOKENS:
        return True
    if _KEY_SUFFIX_RE.fullmatch(name) or name in ("key32",):
        return True
    return False


# identifiers whose == / != comparison must be constant-time: MAC tags,
# digests, signatures over secrets, tokens (MPL103)
COMPARE_SENSITIVE_TOKENS: Set[str] = {
    "tag",
    "mac",
    "hmac",
    "digest",
    "token",
}


def is_compare_sensitive(name: str, extra: Iterable[str] = ()) -> bool:
    if is_secret_name(name, extra):
        return True
    return bool(tokens(name) & COMPARE_SENSITIVE_TOKENS)
