"""mpclint core: findings, parsed files, suppressions, the rule runner.

The model is deliberately small:

- a :class:`Finding` is one violation with a *stable fingerprint*
  (rule + path + enclosing symbol + a rule-chosen detail key — line
  numbers are display-only, so baselines survive unrelated edits);
- a :class:`Rule` visits one :class:`ParsedFile` at a time and may keep
  cross-file state until :meth:`Rule.finalize` (the lock-graph rule
  needs the whole package before it can look for cycles);
- suppression is per-line (``# mpclint: disable=MPL101 — reason``) or
  per-file (``# mpclint: disable-file=MPL101`` in the header), parsed
  from raw source so rules never have to think about it.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

_DISABLE_RE = re.compile(r"#\s*mpclint:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s*[—-]|$)")
_DISABLE_FILE_RE = re.compile(
    r"#\s*mpclint:\s*disable-file=([A-Za-z0-9_,\s]+?)(?:\s*[—-]|$)"
)
_SECRET_ANNOT_RE = re.compile(r"#\s*mpclint:\s*secret\b")
_HOLDS_RE = re.compile(r"#\s*mpclint:\s*holds=([A-Za-z0-9_]+)")
# mpcflow (analysis/flow/) annotations, indexed here so both tools share
# one parse of every file:
#   x = drain()       # mpcflow: host-ok — wire egress: payload leaves device
#   pub = digest(sk)  # mpcflow: declassified — commitment, not the secret
_HOST_OK_RE = re.compile(r"#\s*mpcflow:\s*host-ok(?:\s*[—-]\s*(.*))?$")
_DECLASSIFY_RE = re.compile(r"#\s*mpcflow:\s*declassified\b")
# mpcshape (analysis/shape/) annotation, indexed here for the same
# shared-parse reason:
#   self.B = len(shares)  # mpcshape: unbounded-ok — manifests are pow-2
_SHAPE_OK_RE = re.compile(r"#\s*mpcshape:\s*unbounded-ok(?:\s*[—-]\s*(.*))?$")


@dataclass(frozen=True)
class Finding:
    """One violation. ``key`` is the rule-chosen stable detail (usually
    the offending identifier), so the fingerprint survives line drift."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    symbol: str  # dotted enclosing scope, "" at module level
    key: str
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}:{self.key}"

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.rule}{sym} {self.message}"


class ParsedFile:
    """One source file: AST + per-line suppression/annotation indexes."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        # line -> set of rule ids disabled on that line ("*" = all)
        self.disabled: Dict[int, Set[str]] = {}
        self.disabled_file: Set[str] = set()
        # lines carrying a `# mpclint: secret` annotation
        self.secret_lines: Set[int] = set()
        # lines whose `def` carries `# mpclint: holds=<lock>`
        self.holds: Dict[int, str] = {}
        # mpcflow: line -> reason for an intentional host transfer, and
        # lines whose assignments declassify secret taint
        self.host_ok: Dict[int, str] = {}
        self.declassified: Set[int] = set()
        # mpcshape: line -> reason a shape dim is allowed to stay unbounded
        self.shape_ok: Dict[int, str] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _DISABLE_RE.search(text)
            if m:
                self.disabled[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
            m = _DISABLE_FILE_RE.search(text)
            if m and i <= 15:
                self.disabled_file |= {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
            if _SECRET_ANNOT_RE.search(text):
                self.secret_lines.add(i)
            m = _HOLDS_RE.search(text)
            if m:
                self.holds[i] = m.group(1)
            m = _HOST_OK_RE.search(text)
            if m:
                self.host_ok[i] = (m.group(1) or "").strip()
            m = _SHAPE_OK_RE.search(text)
            if m:
                self.shape_ok[i] = (m.group(1) or "").strip()
            if _DECLASSIFY_RE.search(text):
                self.declassified.add(i)
        # extra secret names declared via `# mpclint: secret` annotations:
        # every assignment/arg defined on an annotated line
        self.extra_secrets: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) and (
                node.lineno in self.secret_lines
            ):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.extra_secrets.add(n.id)
                        elif isinstance(n, ast.Attribute):
                            self.extra_secrets.add(n.attr)
            elif isinstance(node, ast.arg) and node.lineno in self.secret_lines:
                self.extra_secrets.add(node.arg)
        # node -> dotted enclosing symbol
        self._symbols: Dict[ast.AST, str] = {}
        self._index_symbols(self.tree, [])

    def _index_symbols(self, node: ast.AST, stack: List[str]) -> None:
        name = getattr(node, "name", None)
        scoped = isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        )
        if scoped:
            stack = stack + [name]
        for child in ast.iter_child_nodes(node):
            self._symbols[child] = ".".join(stack)
            self._index_symbols(child, stack)

    def symbol_of(self, node: ast.AST) -> str:
        return self._symbols.get(node, "")

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.disabled_file or "*" in self.disabled_file:
            return True
        # the flagged line, or a continuation: also honor the line above
        # (comment-on-its-own-line style for long statements)
        for ln in (line, line - 1):
            tags = self.disabled.get(ln)
            if tags and (rule in tags or "*" in tags or "all" in tags):
                return True
        return False


class LintContext:
    """Shared state across files: the file set plus per-rule scratch."""

    def __init__(self, files: Sequence[ParsedFile]):
        self.files = list(files)
        self.by_rel: Dict[str, ParsedFile] = {f.rel: f for f in files}
        self.scratch: Dict[str, object] = {}


class Rule:
    """Base rule. Subclasses set ``id``/``summary`` and implement
    :meth:`check`; rules needing the whole package implement
    :meth:`finalize` too (called once, after every file)."""

    id: str = "MPL000"
    summary: str = ""

    def applies(self, rel: str) -> bool:
        return True

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        return iter(())

    def finalize(self, ctx: LintContext) -> Iterator[Finding]:
        return iter(())


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    files_scanned: int = 0
    parse_errors: List[str] = field(default_factory=list)


def iter_py_files(paths: Sequence[Path], root: Path) -> Iterator[Tuple[Path, str]]:
    seen: Set[Path] = set()
    for p in paths:
        p = p.resolve()
        candidates: Iterable[Path]
        if p.is_dir():
            candidates = sorted(p.rglob("*.py"))
        else:
            candidates = [p]
        for c in candidates:
            if c in seen or c.suffix != ".py":
                continue
            seen.add(c)
            try:
                rel = c.resolve().relative_to(root.resolve()).as_posix()
            except ValueError:
                rel = c.name
            yield c, rel


def parse_project(
    paths: Sequence[Path],
    root: Optional[Path] = None,
) -> Tuple[List[ParsedFile], List[str]]:
    """Parse every ``.py`` under ``paths`` once → (files, parse_errors).
    This is the shared AST cache: scripts/check_all.py parses here and
    hands the same ParsedFile list to mpclint AND mpcflow."""
    root = root or Path.cwd()
    files: List[ParsedFile] = []
    errors: List[str] = []
    for path, rel in iter_py_files(paths, root):
        try:
            files.append(ParsedFile(path, rel, path.read_text()))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{rel}: {e}")
    return files, errors


def lint_parsed(
    files: Sequence[ParsedFile],
    rules: Sequence[Rule],
    parse_errors: Sequence[str] = (),
) -> LintResult:
    """Run ``rules`` over already-parsed files (see parse_project).
    Suppressed findings are filtered here, centrally."""
    result = LintResult()
    result.parse_errors = list(parse_errors)
    result.files_scanned = len(files)
    ctx = LintContext(files)
    for pf in files:
        for rule in rules:
            if not rule.applies(pf.rel):
                continue
            for f in rule.check(pf, ctx):
                if not pf.is_suppressed(f.rule, f.line):
                    result.findings.append(f)
    for rule in rules:
        for f in rule.finalize(ctx):
            pf = ctx.by_rel.get(f.path)
            if pf is None or not pf.is_suppressed(f.rule, f.line):
                result.findings.append(f)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    return result


def lint_paths(
    paths: Sequence[Path],
    rules: Sequence[Rule],
    root: Optional[Path] = None,
) -> LintResult:
    """Parse + lint in one call (the single-tool entry point)."""
    files, errors = parse_project(paths, root=root)
    return lint_parsed(files, rules, parse_errors=errors)


def run_lint(
    paths: Optional[Sequence[Path]] = None,
    root: Optional[Path] = None,
) -> LintResult:
    """Full-rule-set sweep — the entry point the test gate and CLI share.
    Defaults to the ``mpcium_tpu`` package next to this file's repo root."""
    from .rules import all_rules

    root = root or Path(__file__).resolve().parents[2]
    paths = list(paths) if paths else [root / "mpcium_tpu"]
    return lint_paths(paths, all_rules(), root=root)


# -- shared AST helpers (used by several rule modules) -----------------------


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def self_attr(node: ast.AST) -> Optional[str]:
    """'x' for ``self.x``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None
