"""Fail-closed finding baseline.

Grandfathered findings live in a checked-in JSON file; each entry is a
fingerprint plus a one-line justification. The contract is **fail
closed both ways**:

- a finding NOT in the baseline fails the gate (new debt is refused);
- a baseline entry whose finding no longer fires ALSO fails the gate
  (the entry is stale — delete it), so the baseline only ever shrinks.

Fingerprints are line-number-free (see :class:`~.core.Finding`), so
unrelated edits to a file don't churn the baseline.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding

DEFAULT_BASELINE = ".mpclint-baseline.json"


class BaselineError(Exception):
    pass


@dataclass
class Baseline:
    path: Path
    entries: Dict[str, str] = field(default_factory=dict)  # fp -> justification

    def split(
        self,
        findings: Sequence[Finding],
        scope: Optional[Tuple[str, ...]] = None,
    ):
        """Partition a sweep against this baseline.

        Returns ``(new, grandfathered, stale)`` where ``new`` are
        findings with no baseline entry, ``grandfathered`` are matched
        findings, and ``stale`` are baseline fingerprints that matched
        nothing (each one must be deleted from the file).

        The baseline is shared between mpclint (MPL) and mpcflow (MPF);
        a runner that only executed one analyzer passes ``scope`` (rule
        prefixes it actually ran) so the other family's entries aren't
        reported stale. The combined gate (scripts/check_all.py) passes
        no scope and enforces staleness over everything."""
        fps = {f.fingerprint for f in findings}
        new = [f for f in findings if f.fingerprint not in self.entries]
        grandfathered = [f for f in findings if f.fingerprint in self.entries]
        stale = sorted(
            fp
            for fp in self.entries
            if fp not in fps and (scope is None or fp.startswith(scope))
        )
        return new, grandfathered, stale

    def save(self) -> None:
        payload = {
            "version": 1,
            "entries": [
                {"fingerprint": fp, "justification": just}
                for fp, just in sorted(self.entries.items())
            ],
        }
        self.path.write_text(json.dumps(payload, indent=1) + "\n")


def load_baseline(path: Path) -> Baseline:
    """Load (or start empty when the file doesn't exist yet). Malformed
    files raise — a silently-ignored baseline would un-gate the repo."""
    if not path.exists():
        return Baseline(path=path)
    try:
        d = json.loads(path.read_text())
        entries: Dict[str, str] = {}
        for e in d["entries"]:
            fp, just = e["fingerprint"], e["justification"].strip()
            if not just:
                raise BaselineError(
                    f"baseline entry {fp!r} has no justification"
                )
            if fp in entries:
                raise BaselineError(f"duplicate baseline entry {fp!r}")
            entries[fp] = just
    except BaselineError:
        raise
    except Exception as e:
        raise BaselineError(f"cannot parse baseline {path}: {e!r}") from e
    return Baseline(path=path, entries=entries)


def write_baseline(path: Path, findings: List[Finding], justification: str) -> Baseline:
    """--write-baseline support: grandfather the current sweep wholesale
    (every entry gets the same placeholder justification, meant to be
    hand-edited before commit)."""
    b = Baseline(path=path)
    for f in findings:
        b.entries.setdefault(f.fingerprint, justification or f.message)
    b.save()
    return b
